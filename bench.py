"""Headline benchmark: batched BLS signature-set verification throughput.

Reproduces the reference's hot workload (blst verifyMultipleSignatures via
the worker pool — beacon-node/test/perf/bls/bls.test.ts shapes, BASELINE.md
north star: >=50k signature-set verifications/sec, zero queue backlog) on
the device batch kernels.

The headline is the GROUPED kernel at the gossip shape (64 unique signing
roots per batch — committees share roots; BASELINE config #2): the batch
equation regrouped by bilinearity, per-root pubkey MSMs, ψ-split
randomness (parallel/verifier.grouped_verify_kernel). The honest
worst-case row (every root unique — range-sync-of-distinct-blocks shape)
runs the per-set kernel and is reported alongside, as are the end-to-end
wire→verdict rate and the incremental state-hashing numbers.

Harness (round-6 rewrite on `lodestar_tpu.observability.bench_emit`): every
phase runs under its own deadline (LODESTAR_TPU_BENCH_PHASE_DEADLINE
seconds, graceful skip on expiry) and the run ALWAYS ends in one JSON line
on stdout — {"metric", "value", "unit", "vs_baseline", "phases",
"stage_seconds", "planner", "partial"} — even when a phase dies or the
driver's global timeout SIGTERMs the process mid-phase (the BENCH_r05
`rc: 124, parsed: null` failure mode). The full document also goes to
bench_details.json; progress lines go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_SETS_PER_SEC = 50_000.0  # BASELINE.json north_star target
UNIQUE_ROOTS = 64  # committee gossip shares signing roots (config #2 shape)
GROUPED_LANES = 256  # sets per root-row: 64×256 = 16384 sets/dispatch
WORST_CASE_BATCH = 4096
REPS = 3


def _example_grouped(rows: int, lanes: int):
    """Valid grouped arrays (shared builder — __graft_entry__)."""
    from __graft_entry__ import _example_grouped as build

    return build(rows, lanes)


def _bench_grouped(jax, lanes: int = GROUPED_LANES, utilization: bool = False):
    """Device steady-state of the grouped kernel at the gossip shape.

    With `utilization`, returns (rate, busy_fraction): busy_fraction =
    async-pipelined per-call time / block-per-call time. Async submits
    overlap dispatch with device execution, block-per-call pays the full
    host round trip each call — the ratio is the fraction of steady-state
    wall time the chip spends executing vs waiting on host/dispatch
    (1.0 = dispatch fully hidden; the VERDICT r4 utilization row)."""
    from lodestar_tpu.observability.compile_ledger import ledger
    from lodestar_tpu.parallel.verifier import grouped_verify_kernel

    g, a_bits, b_bits = _example_grouped(UNIQUE_ROOTS, lanes)
    args = [
        jax.device_put(a)
        for a in (
            g.pk_x, g.pk_y, g.msg_x, g.msg_y, g.sig_x, g.sig_y,
            a_bits, b_bits, g.valid,
        )
    ]
    jax.block_until_ready(args)
    fn = ledger().wrap(jax.jit(grouped_verify_kernel), "bench_grouped")
    ok = bool(fn(*args))  # compile + correctness gate
    assert ok, "grouped bench batch failed verification"
    t0 = time.perf_counter()
    for _ in range(REPS):
        r = fn(*args)
    r.block_until_ready()
    dt = (time.perf_counter() - t0) / REPS
    rate = UNIQUE_ROOTS * lanes / dt
    if not utilization:
        return rate
    t0 = time.perf_counter()
    for _ in range(REPS):
        fn(*args).block_until_ready()  # full host round trip per call
    dt_blocked = (time.perf_counter() - t0) / REPS
    return rate, min(1.0, dt / dt_blocked)


def _bench_worst_case(jax) -> dict:
    """The adversarial row (VERDICT r4 #2):

    - `worst_case_unique`: an attacker floods unique AttestationData
      (roots never group) but signs with boundedly many keys — the
      planner routes the PK-GROUPED kernel (bilinearity on the pubkey
      axis: e(pk, Σ r_i·H_i); parallel/verifier
      pk_grouped_verify_kernel). 128 keys × 32 unique roots each.

    The distinct-pk-and-msg floor row moved to the parity-gated
    `floor_fused_pairing` phase (ISSUE 14; renamed by ISSUE 18)."""
    from __graft_entry__ import _example_pk_grouped
    from lodestar_tpu.observability.compile_ledger import ledger
    from lodestar_tpu.parallel.verifier import pk_grouped_verify_kernel

    g, a_bits, b_bits = _example_pk_grouped(128, 32, unique_msgs=8)
    args = [
        jax.device_put(x)
        for x in (g.pk_x, g.pk_y, g.msg_x, g.msg_y, g.sig_x, g.sig_y,
                  a_bits, b_bits, g.valid)
    ]
    jax.block_until_ready(args)
    fn = ledger().wrap(jax.jit(pk_grouped_verify_kernel), "bench_pk_grouped")
    ok = bool(fn(*args))
    assert ok, "pk-grouped bench batch failed verification"
    t0 = time.perf_counter()
    for _ in range(REPS):
        r = fn(*args)
    r.block_until_ready()
    dt = (time.perf_counter() - t0) / REPS
    return {
        "device_sets_per_sec_worst_case_unique": round(WORST_CASE_BATCH / dt, 2),
        "worst_case_unique_via": "pk_grouped_128x32",
    }


def _bench_floor_fused_pairing(jax) -> dict:
    """The unconditional floor, parity-gated old-vs-new (ISSUE 14,
    renamed from `floor_batched_fe` by ISSUE 18 — the floor row key is
    unchanged, so bench_compare's base-name match carries the trend).

    Shape: distinct pubkeys AND roots simultaneously (range-sync of
    distinct proposers' blocks — not an adversary-scalable shape);
    nothing groups, so the per-set kernel's rate is the floor.

    Rows:
    - `device_sets_per_sec_floor_distinct_pk_and_msg` — the REQUIRED
      floor key (binding moved here from `worst_case`), measured on the
      production per-set kernel, whose verdict tail now runs the
      shared-inversion batched final exp.
    - `device_sets_per_sec_verdicts_batched_fe` / `_legacy_fe` — the
      per-set VERDICT kernel (N per-lane final exps before ISSUE 14)
      both ways on the same device arrays. The two verdict vectors must
      be bit-identical and all-true or the phase dies: a batched-FE
      kernel that is fast but wrong must never report a floor number.
    - `device_sets_per_sec_fused_pairing` — ISSUE 18: the whole pairing
      (Miller loop + batched final exp) fused per VMEM tile, measured
      only where LODESTAR_TPU_PALLAS_PAIRING resolves on (TPU deploys);
      its verdicts must match the XLA route lane-for-lane or the phase
      dies. On CPU the knob resolves off and the row is skipped — the
      interpret-mode bit-parity twin lives in tests/test_pallas_tower.py
      (slow tier).
    """
    from __graft_entry__ import _example_arrays
    from lodestar_tpu.observability.compile_ledger import ledger
    from lodestar_tpu.ops import pallas_tower
    from lodestar_tpu.parallel.verifier import (
        batch_verify_kernel,
        individual_verify_kernel,
        individual_verify_kernel_legacy_fe,
        pairing_pallas_kernel,
    )

    args = [jax.device_put(a) for a in _example_arrays(WORST_CASE_BATCH)]
    jax.block_until_ready(args)
    # verdict kernels take no r_bits (index 6): (pk, msg, sig, valid)
    v_args = args[:6] + [args[7]]

    def steady(fn, call_args):
        t0 = time.perf_counter()
        for _ in range(REPS):
            r = fn(*call_args)
        r.block_until_ready()
        return (time.perf_counter() - t0) / REPS

    new_fn = ledger().wrap(
        jax.jit(individual_verify_kernel), "bench_verdicts_batched_fe"
    )
    old_fn = ledger().wrap(
        jax.jit(individual_verify_kernel_legacy_fe), "bench_verdicts_legacy_fe"
    )
    new_v = np.asarray(new_fn(*v_args))
    old_v = np.asarray(old_fn(*v_args))
    # the parity gate: same verdicts, and the known-valid batch passes
    assert (new_v == old_v).all() and new_v.all(), (
        "floor_fused_pairing parity gate failed: batched-FE verdicts "
        "diverge from per-lane FE"
    )
    rows = {
        "device_sets_per_sec_verdicts_batched_fe": round(
            WORST_CASE_BATCH / steady(new_fn, v_args), 2
        ),
        "device_sets_per_sec_verdicts_legacy_fe": round(
            WORST_CASE_BATCH / steady(old_fn, v_args), 2
        ),
        "parity_batched_vs_legacy_fe": True,
    }

    if pallas_tower.pairing_enabled():
        # explicit XLA-route twin: with the knob on, the production
        # kernel (new_fn) itself dispatches the fused path, so the gate
        # needs the unfused miller_loop + batched-FE composition spelled
        # out — not the knob-sensitive seam
        from lodestar_tpu.ops import fp12 as _fp12
        from lodestar_tpu.ops.pairing import (
            final_exponentiation_batch as _feb,
        )
        from lodestar_tpu.parallel.verifier import _individual_pairing_terms

        def _xla_route(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, valid):
            prod = _individual_pairing_terms(
                pk_x, pk_y, msg_x, msg_y, sig_x, sig_y
            )
            return _fp12.is_one(_feb(prod)) & valid

        fused_fn = ledger().wrap(
            jax.jit(pairing_pallas_kernel), "bench_fused_pairing"
        )
        fused_v = np.asarray(fused_fn(*v_args))
        xla_v = np.asarray(jax.jit(_xla_route)(*v_args))
        assert (fused_v == xla_v).all() and fused_v.all(), (
            "floor_fused_pairing parity gate failed: fused-pairing "
            "verdicts diverge from the XLA route"
        )
        rows["device_sets_per_sec_fused_pairing"] = round(
            WORST_CASE_BATCH / steady(fused_fn, v_args), 2
        )
        rows["parity_fused_vs_xla"] = True
    else:
        rows["fused_pairing_skipped"] = (
            "LODESTAR_TPU_PALLAS_PAIRING resolved off (non-TPU backend); "
            "interpret-mode bit-parity covered by tests/test_pallas_tower.py"
        )

    fn = ledger().wrap(jax.jit(batch_verify_kernel), "bench_batch")
    ok = bool(fn(*args))
    assert ok, "per-set bench batch failed verification"
    rows["device_sets_per_sec_floor_distinct_pk_and_msg"] = round(
        WORST_CASE_BATCH / steady(fn, args), 2
    )
    return rows


def _bench_e2e() -> dict | None:
    """Wire-bytes → verified/s through TpuBlsVerifier (marshal included).

    Sets are pre-generated OUTSIDE the timed region (network receive is
    not the thing under test); pubkeys come from a trusted cache exactly
    like the reference's pubkey cache (worker.ts deserializes without
    re-validating). Messages share UNIQUE_ROOTS signing roots per batch —
    the real gossip shape — so the verifier routes the grouped kernel.

    Round 6: `e2e_wire_to_verdict_sets_per_sec` is the NO-FLAGS DEFAULT
    configuration — which now means device-side signature decompression
    (flipped default, VERDICT r5 #4). The host-marshal path keeps its own
    key (`e2e_host_marshal_sets_per_sec`, the rounds-1..5-comparable
    trend line), so tools/bench_compare.py never silently compares
    different configurations.

    PIPELINED: batches go through `verify_signature_sets_submit`, so the
    host marshals batch k+1 while the device verifies batch k (the
    double-buffering of VERDICT r3 #4). A marshal-only rate is reported
    alongside: on this 1-core box the host is the e2e ceiling — the
    device needs ceil(marshal_ms/device_ms) cores to saturate.
    """
    from lodestar_tpu import native
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier, _rand_pairs

    if not native.HAVE_NATIVE_BLS:
        return None

    batch = UNIQUE_ROOTS * GROUPED_LANES  # reuse the headline kernel compile
    n_keys = 64
    sks = [bls.interop_secret_key(i) for i in range(n_keys)]
    pks = [sk.to_public_key() for sk in sks]
    roots = [bytes([r]) * 32 for r in range(UNIQUE_ROOTS)]
    sig_cache: dict[tuple[int, int], bytes] = {}
    sets = []
    for i in range(batch):
        k = i % n_keys
        m = (i * 7) % UNIQUE_ROOTS
        sig = sig_cache.get((k, m))
        if sig is None:
            sig = sig_cache[(k, m)] = sks[k].sign(roots[m]).to_bytes()
        sets.append(
            bls.SignatureSet(pubkey=pks[k], message=roots[m], signature=sig)
        )

    def timed_e2e(verifier):
        ok = verifier.verify_signature_sets(sets)  # compile + warm caches
        assert ok, "e2e batch failed verification"
        verifier._h2c_cache.clear()  # first timed rep pays the unique hashes
        verifier._pk_cache.clear()  # …and the cold pubkey decompressions
        t0 = time.perf_counter()
        pending = None
        for _ in range(REPS):
            nxt = verifier.verify_signature_sets_submit(sets)
            if pending is not None:
                assert pending()
            pending = nxt
        assert pending()
        return (time.perf_counter() - t0) / REPS

    # the NO-FLAGS default configuration: device decompress is default-on
    # since round 6, so this IS the wire-to-verdict path a stock node runs
    verifier = TpuBlsVerifier(
        buckets=(batch,), grouped_configs=((UNIQUE_ROOTS, GROUPED_LANES),)
    )
    dt = timed_e2e(verifier)

    # host-marshal variant: signatures decode + subgroup-check in the C
    # tier (the rounds-1..5 default) — kept as its own comparable row
    rows = {}
    try:
        host_verifier = TpuBlsVerifier(
            buckets=(batch,),
            grouped_configs=((UNIQUE_ROOTS, GROUPED_LANES),),
            device_decompress=False,
        )
        dt_host = timed_e2e(host_verifier)
        rows["e2e_host_marshal_sets_per_sec"] = round(batch / dt_host, 2)
    except Exception as e:
        print(f"host-marshal e2e failed: {e}", file=sys.stderr)
        dt_host = None

    plan = verifier._plan_groups(sets)
    verifier._h2c_cache.clear()
    verifier._pk_cache.clear()
    t0 = time.perf_counter()
    g = verifier._marshal_grouped(sets, plan)
    _rand_pairs(g.valid.shape)
    marshal_cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    g = verifier._marshal_grouped(sets, plan)
    _rand_pairs(g.valid.shape)
    marshal_warm_s = time.perf_counter() - t0
    best = min(d for d in (dt, dt_host) if d is not None)
    return {
        "e2e_wire_to_verdict_sets_per_sec": round(batch / dt, 2),
        "e2e_best_sets_per_sec": round(batch / best, 2),
        "e2e_device_decompress_sets_per_sec": round(batch / dt, 2),
        **rows,
        "marshal_sets_per_sec_warm_1core": round(batch / marshal_warm_s, 2),
        "marshal_sets_per_sec_cold_1core": round(batch / marshal_cold_s, 2),
    }


def _bench_attestation_epoch_warm() -> dict | None:
    """Epoch-cold vs epoch-warm attestation-lane HOST marshal (ISSUE 18).

    The steady-state attestation shape: distinct attesters (distinct
    pubkeys), a few shared signing roots per slot. What the epoch table
    + H(msg) dedup change is the HOST side of the lane — pubkey limbs
    and H(m) — so that is what this phase times, per rep:

    - cold: `_pk_cache`/`_h2c_cache` cleared and no epoch table entry —
      every set pays a C-tier G1 decompression and every unique root a
      hash_to_g2 (the post-restart / post-rotation first dispatch).
    - warm: table populated at "epoch transition" + roots pre-warmed via
      `warm_h2c` (the dispatcher's dedup seam); `_pk_cache` is still
      cleared per rep, so the warm rate measures the TABLE serving the
      marshal, not the bounded FIFO.

    Parity gate: the limb arrays the kernels would receive must be
    bit-identical cold vs warm — a table row may not differ from a fresh
    decompression in any bit. Acceptance: warm ≥ 2x cold.
    """
    from lodestar_tpu import native
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    if not native.HAVE_NATIVE_BLS:
        return None

    n_sets, n_roots = 256, 8
    sks = [bls.interop_secret_key(i) for i in range(n_sets)]
    pks = [sk.to_public_key() for sk in sks]
    roots = [bytes([0x18, r]) + b"\x00" * 30 for r in range(n_roots)]
    sets = [
        bls.SignatureSet(
            pubkey=pks[i],
            message=roots[i % n_roots],
            signature=sks[i].sign(roots[i % n_roots]).to_bytes(),
        )
        for i in range(n_sets)
    ]
    pk_bytes = [p.to_bytes() for p in pks]

    def marshal_once(v):
        """One attestation-lane host marshal: pubkey limbs + H(m)."""
        rows = v._pk_rows(sets)
        assert rows is not None
        for r in roots:
            assert v._hash_root(r) is not None
        return rows

    v = TpuBlsVerifier(buckets=(4,))
    t_cold = 0.0
    for _ in range(REPS):
        v._pk_cache.clear()
        with v._h2c_lock:
            v._h2c_cache.clear()
        if v._epoch_table is not None:
            v._epoch_table._entries.clear()
        t0 = time.perf_counter()
        cold_rows = marshal_once(v)
        t_cold += time.perf_counter() - t0
    cold_rate = n_sets / (t_cold / REPS)

    # epoch transition: populate the table + dedup pre-warm the roots
    v.epoch_table_populate(0, pk_bytes)
    v.warm_h2c(roots)
    t_warm = 0.0
    for _ in range(REPS):
        v._pk_cache.clear()  # the table, not the FIFO, must serve
        t0 = time.perf_counter()
        warm_rows = marshal_once(v)
        t_warm += time.perf_counter() - t0
    warm_rate = n_sets / (t_warm / REPS)

    assert np.array_equal(cold_rows[0], warm_rows[0]) and np.array_equal(
        cold_rows[1], warm_rows[1]
    ), ("attestation_epoch_warm parity gate failed: table rows diverge "
        "from fresh decompression")

    return {
        "attestation_epoch_warm_sets_per_sec": round(warm_rate, 2),
        "attestation_epoch_cold_sets_per_sec": round(cold_rate, 2),
        "attestation_epoch_warm_speedup": round(warm_rate / cold_rate, 2),
        "parity_epoch_warm_vs_cold": True,
        "attestation_epoch_warm_via": (
            f"pk_rows+h2c marshal, {n_sets} sets x {n_roots} roots"
        ),
        "epoch_table": (
            v.epoch_table_snapshot() if v._epoch_table is not None else None
        ),
    }


def _bench_adversarial_mix(jax) -> float | None:
    """50% unique-root sets injected into the gossip shape (VERDICT r3
    #1). Round 5: roots don't group across the mix, but the whole batch
    groups on the DUAL axis — the 64 signer keys — so the planner runs
    ONE pk-grouped dispatch and the attacker's unique AttestationData
    costs nothing extra (earlier rounds peeled shared roots onto the
    grouped kernel and paid the per-set kernel for the singleton half —
    the trend line changes meaning here). Device-rate row (marshal
    outside the timed region)."""
    from lodestar_tpu.parallel.verifier import (
        TpuBlsVerifier,
        _rand_pairs,
    )
    from lodestar_tpu import native
    from lodestar_tpu.bls import api as bls

    if not native.HAVE_NATIVE_BLS:
        return None

    half = WORST_CASE_BATCH // 2
    n_keys = 64
    sks = [bls.interop_secret_key(i) for i in range(n_keys)]
    pks = [sk.to_public_key() for sk in sks]
    shared_roots = [bytes([r]) + b"\x01" * 31 for r in range(UNIQUE_ROOTS)]
    sig_cache: dict[tuple[int, int], bytes] = {}
    sets = []
    for i in range(half):  # honest committee traffic
        k, m = i % n_keys, (i * 7) % UNIQUE_ROOTS
        sig = sig_cache.get((k, m))
        if sig is None:
            sig = sig_cache[(k, m)] = sks[k].sign(shared_roots[m]).to_bytes()
        sets.append(
            bls.SignatureSet(
                pubkey=pks[k], message=shared_roots[m], signature=sig
            )
        )
    for i in range(half):  # attacker-minted unique AttestationData
        k = i % n_keys
        msg = i.to_bytes(4, "big") + b"\xAD" * 28
        sets.append(
            bls.SignatureSet(
                pubkey=pks[k], message=msg, signature=sks[k].sign(msg).to_bytes()
            )
        )

    # device_decompress=False: this phase times the LIMB pk-grouped kernel
    # (marshal sits outside the timed region), so the submit gate must
    # compile that same kernel, not the raw variant the runtime default
    # would route (one compile, not two — compile containment)
    verifier = TpuBlsVerifier(
        buckets=(half,), grouped_configs=((UNIQUE_ROOTS, half // UNIQUE_ROOTS),),
        device_decompress=False,
    )
    resolver = verifier.verify_signature_sets_submit(sets)  # compile + gate
    assert resolver(), "adversarial-mix batch failed verification"

    # device-rate: marshal once, dispatch repeatedly. Roots don't group
    # (half are attacker-minted uniques), but the WHOLE batch groups on
    # the dual axis — 64 signer keys — so the planner runs ONE
    # pk-grouped dispatch (round-5 dual-axis defense): the attacker's
    # unique AttestationData costs nothing extra at all.
    pk_plan = verifier._plan_pk_groups(sets)
    assert pk_plan is not None, "mix batch must pk-group (64 keys)"
    gp = verifier._marshal_pk_grouped(sets, pk_plan)
    a2, b2 = _rand_pairs(gp.valid.shape)
    t0 = time.perf_counter()
    for _ in range(REPS):
        ok = bool(verifier.kernels.verify_pk_grouped(gp, a2, b2))
    dt = (time.perf_counter() - t0) / REPS
    assert ok
    return WORST_CASE_BATCH / dt


def _bench_bisect(pipeline) -> dict | None:
    """Bisection-verdict rows (round-6 tentpole acceptance): the
    all-valid per-set verdict path must cost ONE final exponentiation
    (bisection counter = 0 rounds), a k-invalid adversarial mix must
    isolate offenders in O(log N) rounds, and every verdict must match
    the CPU oracle bit-for-bit."""
    from lodestar_tpu import native
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.chain.bls_verifier import CpuBlsVerifier
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    if not native.HAVE_NATIVE_BLS:
        return None

    n = 128  # the production per-set bucket (warmup ladder shape)
    sks = [bls.interop_secret_key(i) for i in range(n)]
    sets = []
    for i in range(n):
        msg = i.to_bytes(4, "big") + b"\xB1" * 28  # all-distinct roots
        sets.append(
            bls.SignatureSet(
                pubkey=sks[i].to_public_key(),
                message=msg,
                signature=sks[i].sign(msg).to_bytes(),
            )
        )
    verifier = TpuBlsVerifier(buckets=(n,), observer=pipeline)
    oracle = CpuBlsVerifier()

    def snap():
        return pipeline.bisect_snapshot()

    base = snap()
    out = verifier.verify_signature_sets_individual(sets)  # compile + gate
    assert out == [True] * n, "all-valid bisect batch failed"
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = verifier.verify_signature_sets_individual(sets)
    dt = (time.perf_counter() - t0) / REPS
    after_valid = snap()
    rows = {
        "bisect_all_valid_sets_per_sec": round(n / dt, 2),
        "bisect_rounds_all_valid": after_valid["rounds"] - base["rounds"],
    }

    # k-invalid adversarial mix: 3 tampered sets scattered in the batch
    wrong = bls.interop_secret_key(999)
    bad = (7, 64, 127)
    for i in bad:
        sets[i] = bls.SignatureSet(
            pubkey=sets[i].pubkey,
            message=sets[i].message,
            signature=wrong.sign(sets[i].message).to_bytes(),
        )
    pre = snap()
    t0 = time.perf_counter()
    out = verifier.verify_signature_sets_individual(sets)
    dt_bad = time.perf_counter() - t0
    post = snap()
    expect = [i not in bad for i in range(n)]
    oracle_out = oracle.verify_signature_sets_individual(sets)
    rows.update({
        "bisect_k_invalid_sets_per_sec": round(n / dt_bad, 2),
        "bisect_rounds_k_invalid": post["rounds"] - pre["rounds"],
        "bisect_probes_k_invalid": post["probes"] - pre["probes"],
        "bisect_verdicts_match_oracle": int(
            out == expect and out == oracle_out
        ),
    })
    return rows


def _bench_sharded_grouped(jax, pipeline) -> dict | None:
    """Mesh-native serving (round-7 tentpole): the grouped kernel through
    the PRODUCTION mesh dispatcher (`parallel/mesh.BlsMeshDispatcher`) on
    whatever mesh this host offers — real chips on a multi-chip slice, 8
    virtual CPU devices otherwise (main() forces the host-platform count,
    so the shape matches the driver's `dryrun_multichip(8)` warm cache:
    8·n root-rows × 64 lanes).

    Two gates before the timed reps: the sharded verdict must equal the
    single-device kernel's on the SAME arrays — once valid, once with a
    tampered signature limb — i.e. meshing changes throughput, never
    verdicts. The dispatcher ticks the lodestar_bls_mesh_* families, so
    the emitted `mesh` section carries the per-chip dispatch counts."""
    from lodestar_tpu.observability.compile_ledger import ledger
    from lodestar_tpu.parallel.mesh import NOT_SHARDED, BlsMeshDispatcher
    from lodestar_tpu.parallel.sharded import mesh_divisor
    from lodestar_tpu.parallel.verifier import grouped_verify_kernel

    devices = jax.devices()
    n = mesh_divisor(len(devices))
    if n < 2:
        return None  # single chip, no virtual mesh — nothing to shard

    rows, lanes = 8 * n, 64
    g, a_bits, b_bits = _example_grouped(rows, lanes)
    dispatcher = BlsMeshDispatcher(devices[:n], observer=pipeline)
    unsharded_fn = ledger().wrap(jax.jit(grouped_verify_kernel), "bench_grouped")

    def unsharded() -> bool:
        return bool(
            unsharded_fn(
                g.pk_x, g.pk_y, g.msg_x, g.msg_y, g.sig_x, g.sig_y,
                a_bits, b_bits, g.valid,
            )
        )

    def sharded() -> bool:
        r = dispatcher.dispatch_grouped(g, a_bits, b_bits)
        assert r is not NOT_SHARDED, "mesh dispatcher refused the bench batch"
        return bool(r)

    ok = sharded()  # compile + parity gate (valid batch)
    assert ok == unsharded() and ok, "sharded verdict diverged on valid batch"
    g.sig_x[0, 0, 0, 0] ^= 1  # tampered: both tiers must reject identically
    assert sharded() == unsharded() == False, \
        "sharded verdict diverged on tampered batch"
    g.sig_x[0, 0, 0, 0] ^= 1

    t0 = time.perf_counter()
    for _ in range(REPS):
        r = dispatcher.dispatch_grouped(g, a_bits, b_bits)
    ok = bool(r)
    dt = (time.perf_counter() - t0) / REPS
    assert ok, "sharded bench batch failed verification"
    return {
        "sharded_grouped_sets_per_sec": round(rows * lanes / dt, 2),
        "mesh_devices": n,
        "mesh_platform": devices[0].platform,
        "sharded_verdicts_match_unsharded": 1,
    }


def _bench_fleet_dryrun(jax, pipeline) -> dict | None:
    """Two-level fleet serving dryrun (ISSUE 20): the SAME grouped batch
    through the flat single-host mesh AND an emulated 2-host (dcn, ici)
    two-level mesh over the identical device set. Parity gates first —
    valid and tampered verdict bytes must be identical between the two
    layouts (`fleet_parity_ok`, gated by tools/bench_compare.py) — then
    the retained-throughput fraction `fleet_overlap_fraction` =
    t_flat / t_two_level: the cost of routing the one Fp12 partial and
    the 64 combined plane sums per host across the DCN axis instead of
    keeping every collective on ICI. 1.0 = the two-level layout serves
    at flat-mesh speed (perfect overlap); the fleet-math section of
    BASELINE.md scales host count by this fraction."""
    import numpy as np

    from lodestar_tpu.parallel.fleet import FleetRouter
    from lodestar_tpu.parallel.mesh import NOT_SHARDED, BlsMeshDispatcher
    from lodestar_tpu.parallel.sharded import mesh_divisor

    devices = jax.devices()
    n = mesh_divisor(len(devices))
    if n < 4:
        return None  # an emulated 2-host fleet needs >=2 chips per host
    rows, lanes = 8 * n, 64
    g, a_bits, b_bits = _example_grouped(rows, lanes)
    flat = BlsMeshDispatcher(devices[:n], observer=pipeline)
    half = n // 2
    fleet = BlsMeshDispatcher(
        devices[:n],
        observer=pipeline,
        hosts=[list(range(half)), list(range(half, n))],
        router=FleetRouter(2, 0, observer=pipeline),
    )

    def run(d):
        r = d.dispatch_grouped(g, a_bits, b_bits)
        assert r is not NOT_SHARDED, "fleet dryrun batch refused"
        return r

    v_flat, v_fleet = run(flat), run(fleet)
    parity = (
        np.asarray(v_flat).tobytes() == np.asarray(v_fleet).tobytes()
        and bool(v_flat)
    )
    g.sig_x[0, 0, 0, 0] ^= 1  # tampered: both layouts must reject
    vb_flat, vb_fleet = run(flat), run(fleet)
    parity = (
        parity
        and np.asarray(vb_flat).tobytes() == np.asarray(vb_fleet).tobytes()
        and not bool(vb_flat)
    )
    g.sig_x[0, 0, 0, 0] ^= 1

    def time_reps(d) -> float:
        r = None
        t0 = time.perf_counter()
        for _ in range(REPS):
            r = run(d)
        bool(r)
        return (time.perf_counter() - t0) / REPS

    t_flat, t_fleet = time_reps(flat), time_reps(fleet)
    snap = fleet.fleet_snapshot() or {}
    return {
        "fleet_parity_ok": int(parity),
        "fleet_overlap_fraction": (
            round(t_flat / t_fleet, 4) if t_fleet > 0 else 0.0
        ),
        "fleet_sets_per_sec": round(rows * lanes / t_fleet, 2),
        "fleet_hosts": fleet.hosts_serving,
        "fleet_chips_per_host": half,
        "fleet_host_dispatches": snap.get("host_dispatches", {}),
    }


def _bench_e2e_mesh_raw(jax, pipeline, headline_rate) -> dict | None:
    """Wire-bytes → verdict through the MESH raw path (ISSUE 15 tentpole):
    the no-flags default facade with a mesh attached — host marshal is a
    pure byte scatter (signatures stay compressed wire bytes), each chip
    decompresses its own row slice on device via the sharded `*_raw`
    twins, then the usual grouped pairing check.

    Parity gate before the timed reps, same contract as
    `_bench_sharded_grouped`: on ONE marshalled batch with ONE set of
    random coefficients, the sharded-raw verdict must equal the
    single-device raw kernel's — once valid, once with a tampered
    signature byte. Then the timed region is the production facade
    (`verify_signature_sets_submit`, pipelined), so the row is honestly
    wire→verdict: plan + scatter + mesh dispatch every rep.

    `e2e_mesh_raw_vs_device_headline` is the acceptance ratio: the mesh
    path must hold ≥0.7× the single-device headline on this host."""
    from lodestar_tpu import native
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.parallel.mesh import NOT_SHARDED, BlsMeshDispatcher
    from lodestar_tpu.parallel.sharded import mesh_divisor
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier, _rand_pairs

    if not native.HAVE_NATIVE_BLS:
        return None
    devices = jax.devices()
    n = mesh_divisor(len(devices))
    if n < 2:
        return None  # single chip — no mesh ingest path to measure

    rows_, lanes = UNIQUE_ROOTS, 64  # the 64x64 warmup-rung shape; 64 % n == 0
    batch = rows_ * lanes
    n_keys = 64
    sks = [bls.interop_secret_key(i) for i in range(n_keys)]
    pks = [sk.to_public_key() for sk in sks]
    roots = [bytes([r]) * 32 for r in range(rows_)]
    sig_cache: dict[tuple[int, int], bytes] = {}
    sets = []
    for i in range(batch):
        k, m = i % n_keys, (i * 7) % rows_
        sig = sig_cache.get((k, m))
        if sig is None:
            sig = sig_cache[(k, m)] = sks[k].sign(roots[m]).to_bytes()
        sets.append(
            bls.SignatureSet(pubkey=pks[k], message=roots[m], signature=sig)
        )

    dispatcher = BlsMeshDispatcher(devices[:n], observer=pipeline)
    verifier = TpuBlsVerifier(
        buckets=(batch,), grouped_configs=((rows_, lanes),), mesh=dispatcher
    )
    if not verifier._device_decompress:
        return None  # DEVICE_DECOMPRESS=0 host: no raw ingest to bench

    plan = verifier._plan_groups(sets)
    assert plan is not None, "e2e mesh batch must group (64 shared roots)"
    marshalled = verifier._marshal_grouped(sets, plan, raw=True)
    assert marshalled is not None, "native tier refused the raw marshal"
    g, sig_raw = marshalled
    a_bits, b_bits = _rand_pairs(g.valid.shape)
    r = dispatcher.dispatch_grouped_raw(g, sig_raw, a_bits, b_bits)
    assert r is not NOT_SHARDED, "mesh dispatcher refused the e2e raw batch"
    ok = bool(r)
    assert ok == bool(
        verifier.kernels.verify_grouped_raw(g, sig_raw, a_bits, b_bits)
    ) and ok, "sharded-raw verdict diverged on valid batch"
    sig_raw[0, 0, 10] ^= 1  # tampered wire byte: identical rejection
    assert bool(
        dispatcher.dispatch_grouped_raw(g, sig_raw, a_bits, b_bits)
    ) == bool(
        verifier.kernels.verify_grouped_raw(g, sig_raw, a_bits, b_bits)
    ) == False, "sharded-raw verdict diverged on tampered batch"
    sig_raw[0, 0, 10] ^= 1

    ok = verifier.verify_signature_sets(sets)  # compile + correctness gate
    assert ok, "e2e mesh batch failed verification"
    verifier._h2c_cache.clear()  # first timed rep pays the unique hashes
    verifier._pk_cache.clear()
    t0 = time.perf_counter()
    pending = None
    for _ in range(REPS):
        nxt = verifier.verify_signature_sets_submit(sets)
        if pending is not None:
            assert pending()
        pending = nxt
    assert pending()
    dt = (time.perf_counter() - t0) / REPS
    rate = batch / dt
    out = {
        "e2e_mesh_raw_sets_per_sec": round(rate, 2),
        "e2e_mesh_raw_devices": n,
        "e2e_mesh_raw_verdicts_match_unsharded": 1,
    }
    if headline_rate:
        out["e2e_mesh_raw_vs_device_headline"] = round(rate / headline_rate, 4)
    return out


def _bench_flood(pipeline) -> dict:
    """Gossip-flood drill through the lane dispatcher (ISSUE 15): 16
    attester threads hammer 1-set requests with tiny lane caps while a
    proposer thread submits a 2-set block every 25 ms. The dispatcher is
    backed by a FIXED-SERVICE-TIME mock (no crypto) so the numbers
    isolate the SCHEDULING policy: the block lane must hold its latency
    (p50/p99 rows) and shed NOTHING while attestations shed freely."""
    import threading

    from lodestar_tpu.chain.bls_verifier import BlsShedError, MockBlsVerifier
    from lodestar_tpu.chain.dispatcher import BlsLaneDispatcher

    service_s = 0.004

    class _FixedService(MockBlsVerifier):
        def verify_signature_sets(self, sets) -> bool:
            time.sleep(service_s)
            return super().verify_signature_sets(sets)

    d = BlsLaneDispatcher(
        _FixedService(), max_sigs=64, max_wait_ms=4, pipeline=pipeline,
        workers=2, max_coalesce=256, pending_cap=8,
        lane_caps={"attestation": 4, "aggregate": 4, "sync_committee": 4},
        waiter_timeout_s=30.0,
    )
    stop_at = time.perf_counter() + 2.0
    counts = {"att_ok": 0, "att_shed": 0}
    lock = threading.Lock()
    block_lat: list[float] = []

    def attester():
        while time.perf_counter() < stop_at:
            try:
                d.verify_signature_sets(["att"], lane="attestation")
                with lock:
                    counts["att_ok"] += 1
            except BlsShedError:
                with lock:
                    counts["att_shed"] += 1

    def proposer():
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            ok = d.verify_signature_sets(["blk", "blk"], lane="block")
            block_lat.append(time.perf_counter() - t0)
            assert ok, "block verify failed under flood"
            time.sleep(0.025)

    threads = [threading.Thread(target=attester, daemon=True) for _ in range(16)]
    threads.append(threading.Thread(target=proposer, daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    d.close()

    snap = pipeline.lanes_snapshot()
    lat = np.asarray(block_lat)
    rows = {
        "flood_block_verify_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "flood_block_verify_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "flood_block_requests": len(block_lat),
        "flood_block_sheds": snap["sheds"].get("block", 0),
        "flood_attestation_verified": counts["att_ok"],
        "flood_attestation_sheds": counts["att_shed"],
        "flood_overlap_fraction": snap["overlap_fraction"],
        "flood_service_time_ms": service_s * 1e3,
    }
    # the acceptance shape: blocks NEVER shed, attestations DID (the
    # caps are sized so an un-prioritized dispatcher could not pass)
    assert rows["flood_block_sheds"] == 0, "a block was shed under flood"
    assert counts["att_shed"] > 0, "flood never saturated the lane caps"
    assert rows["flood_block_verify_p99_ms"] < 500.0, (
        "block lane failed to hold latency under flood"
    )
    return rows


def _bench_hasher() -> dict:
    """Incremental state hashing at mainnet registry scale (CPU tier)."""
    from lodestar_tpu.ssz.hashing import mix_in_length
    from lodestar_tpu.ssz.tree_cache import ChunkTree
    from lodestar_tpu.state_transition.hasher import _u64_chunks

    n = 1_000_000
    rng = np.random.default_rng(1)
    balances = rng.integers(
        31_000_000_000, 33_000_000_000, size=n, dtype=np.uint64
    )
    t = ChunkTree((1 << 40) // 4)
    t0 = time.perf_counter()
    t.update(_u64_chunks(balances))
    r0 = mix_in_length(t.root(), n)
    full_s = time.perf_counter() - t0
    balances[n // 2] += 1
    t0 = time.perf_counter()
    t.update(_u64_chunks(balances))
    r1 = mix_in_length(t.root(), n)
    one_ms = (time.perf_counter() - t0) * 1e3
    assert r1 != r0
    return {
        "hasher_1m_balances_full_s": round(full_s, 3),
        "hasher_1m_one_change_ms": round(one_ms, 2),
    }


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)

    from lodestar_tpu.observability import BenchEmitter
    from lodestar_tpu.observability.stages import default_pipeline
    from lodestar_tpu.utils.env import env_float

    # per-phase budget: SIGALRM raises inside the phase at the deadline,
    # which is recorded as `status: timeout` and skipped — later phases
    # still run, and the final JSON always prints (emitter atexit/SIGTERM)
    deadline = env_float("LODESTAR_TPU_BENCH_PHASE_DEADLINE")
    # the watchdog THREAD emits + exits even when the main thread is stuck
    # in a C call (XLA compile) that SIGALRM/SIGTERM cannot interrupt; set
    # it below the driver's global timeout
    global_deadline = env_float("LODESTAR_TPU_BENCH_GLOBAL_DEADLINE")
    pipeline = default_pipeline()
    em = BenchEmitter(
        "bls_signature_sets_verified_per_sec",
        "sets/s",
        baseline=BASELINE_SETS_PER_SEC,
        details_path=os.path.join(here, "bench_details.json"),
        global_deadline_s=global_deadline,
    )
    # emit-time sections: a mid-run kill still reports everything the
    # pipeline observed up to the signal
    em.add_section("stage_seconds", pipeline.stage_snapshot)
    em.add_section("planner", pipeline.planner_snapshot)
    em.add_section("bisect", pipeline.bisect_snapshot)
    # failure-policy / fault counters (round 7): a round that ran with
    # CPU fallbacks, an open breaker, or an armed fault plan carries
    # supervisor.degraded=true — tools/bench_compare.py skips it so a
    # degraded round can't masquerade as a device-perf regression
    em.add_section("supervisor", pipeline.supervisor_snapshot)
    # mesh serving counters (round 7): mesh size / evictions / per-chip
    # dispatch counts — the sharded_grouped phase drives these
    em.add_section("mesh", pipeline.mesh_snapshot)
    # lane dispatcher state (ISSUE 15): queue depths / sheds / coalescing
    # — the flood phase drives these; None until a dispatcher binds
    em.add_section("lanes", pipeline.lanes_snapshot)
    # fleet counters (ISSUE 20): host census / evictions / rebalances /
    # DCN collective seconds — the fleet_dryrun phase drives these
    em.add_section("fleet", pipeline.fleet_snapshot)
    # compile accounting + cold-start timeline: which kernels compiled
    # this run, cache hit/miss, cumulative compile seconds, and the
    # process-start→serving-ready phase marks
    from lodestar_tpu.observability.compile_ledger import ledger, timeline

    em.add_section("compile_ledger", lambda: ledger().snapshot())
    em.add_section("startup", lambda: timeline().snapshot())
    # SLO verdicts (round 16): every emission carries the burn state of
    # the committed objectives — bench_compare gates on a burning one by
    # NAME instead of a raw-number diff
    from lodestar_tpu.observability import device_ledger, slo

    slo.install(pipeline)
    em.add_section("slo", slo.snapshot_or_none)
    # device-time & memory ledger (round 16): busy/idle/overlap seconds
    # by lane x kernel x chip + memory watermarks; read at emit time, so
    # the watchdog's rc=124 document shows what the chips were doing
    em.add_section("device", device_ledger.ledger().snapshot)
    # per-run artifact, written inside emit() so even the watchdog's
    # os._exit(124) path leaves compile_ledger.json behind
    em.on_emit.append(
        lambda doc: ledger().write_artifact(
            os.path.join(here, "compile_ledger.json")
        )
    )
    em.extra["config"] = {
        "grouped_batch": UNIQUE_ROOTS * GROUPED_LANES,
        "unique_roots_per_batch": UNIQUE_ROOTS,
        "worst_case_batch": WORST_CASE_BATCH,
        "phase_deadline_s": deadline,
    }

    # the sharded-grouped phase needs a mesh: hosts where only the CPU
    # backend is live get 8 virtual devices (the driver's
    # dryrun_multichip(8) mesh, so its warm cache is shared). Must land
    # before the first jax import; accelerator enumeration is unaffected
    # (the flag only applies to the host platform).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    try:
        jax.devices()
    except RuntimeError:
        # TPU tunnel unavailable — rerun on CPU so the bench always
        # reports (execv replaces the image: no double emission)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)])

    # env-guarded persistent compile cache (LODESTAR_TPU_COMPILE_CACHE):
    # the compile-containment half of the BENCH_r05 rc=124 fix — a
    # warmup.py pass before the driver's run makes every phase hit
    # cached executables instead of dying in cold compiles
    from lodestar_tpu.utils.jax_env import enable_compile_cache, runtime_info

    enable_compile_cache(os.path.join(here, ".jax_cache"))
    timeline().mark("devices_ready")
    em.extra["runtime_info"] = runtime_info()

    grouped_rate = None

    def saw_rate(rate: float) -> None:
        nonlocal grouped_rate
        grouped_rate = max(grouped_rate or 0.0, rate)
        em.set_headline(grouped_rate)

    _log("bench: grouped phase...")
    with em.phase("grouped_64x256", deadline_s=deadline) as ph:
        rate = _bench_grouped(jax)
        ph.record("device_sets_per_sec", round(rate, 2))
        saw_rate(rate)
        _log(f"bench: grouped {rate:.1f} sets/s")
    # first production-shape phase done == this process could serve; the
    # mark is the bench's serving-ready SLO sample (cold vs warm cache)
    t_ready = timeline().mark_serving_ready()
    # cold-start rows (ISSUE 19): serving_ready_seconds is a GATED
    # bench_compare key (time direction: growth fails the round), and the
    # per-round AOT store outcomes say WHY it moved — a round that loaded
    # executables from disk vs one that compiled reads differently here
    with em.phase("cold_start") as ph:
        aot_counts = ledger().snapshot()["aot"]["counts"]
        ph.record("serving_ready_seconds", round(t_ready, 3))
        ph.record("aot_hits", aot_counts.get("hit", 0))
        ph.record("aot_misses", aot_counts.get("miss", 0))
        ph.record("aot_exports", aot_counts.get("export", 0))
        ph.record("aot_rejected", aot_counts.get("corrupt", 0)
                  + aot_counts.get("version_mismatch", 0))
    # wider lane buckets amortize the 2R+64-Miller fixed cost further;
    # the HEADLINE takes the best shape, but each shape's rate is
    # recorded under its own phase (no cross-shape mislabeling)
    with em.phase("grouped_64x512", deadline_s=deadline) as ph:
        rate, util = _bench_grouped(jax, 512, utilization=True)
        ph.record("device_sets_per_sec", round(rate, 2))
        ph.record("device_busy_fraction", round(util, 4))
        pipeline.device_busy.set(round(util, 4))
        saw_rate(rate)
        _log(f"bench: grouped 64x512 {rate:.1f} sets/s (busy {util:.3f})")
    with em.phase("grouped_64x1024", deadline_s=deadline) as ph:
        rate = _bench_grouped(jax, 1024)
        ph.record("device_sets_per_sec", round(rate, 2))
        saw_rate(rate)
        _log(f"bench: grouped 64x1024 {rate:.1f} sets/s")

    _log("bench: worst-case phase...")
    with em.phase("worst_case", deadline_s=deadline) as ph:
        ph.update(_bench_worst_case(jax))

    _log("bench: floor fused-pairing phase...")
    with em.phase("floor_fused_pairing", deadline_s=deadline) as ph:
        ph.update(_bench_floor_fused_pairing(jax))

    _log("bench: adversarial-mix phase...")
    with em.phase("adversarial_mix_50pct", deadline_s=deadline) as ph:
        mix_rate = _bench_adversarial_mix(jax)
        if mix_rate is not None:
            ph.record("device_sets_per_sec", round(mix_rate, 2))

    _log("bench: bisect-verdicts phase...")
    with em.phase("bisect_verdicts", deadline_s=deadline) as ph:
        bisect_rows = _bench_bisect(pipeline)
        if bisect_rows is not None:
            ph.update(bisect_rows)

    _log("bench: e2e phase...")
    with em.phase("e2e", deadline_s=deadline) as ph:
        e2e_rows = _bench_e2e() or {}
        ph.update(e2e_rows)
        if "e2e_best_sets_per_sec" in e2e_rows:
            # promoted top-level key (ADVICE round 5): best-of-variants
            # e2e rate, separate from the round-4-comparable headline
            em.extra["e2e_best_sets_per_sec"] = e2e_rows["e2e_best_sets_per_sec"]

    _log("bench: attestation epoch-warm phase...")
    with em.phase("attestation_epoch_warm", deadline_s=deadline) as ph:
        epoch_rows = _bench_attestation_epoch_warm()
        if epoch_rows is not None:
            ph.update(epoch_rows)
            _log(
                "bench: attestation epoch-warm "
                f"{epoch_rows['attestation_epoch_warm_sets_per_sec']:.1f} "
                f"sets/s ({epoch_rows['attestation_epoch_warm_speedup']:.1f}x "
                "over cold)"
            )

    _log("bench: sharded-grouped phase...")
    with em.phase("sharded_grouped", deadline_s=deadline) as ph:
        sharded_rows = _bench_sharded_grouped(jax, pipeline)
        if sharded_rows is not None:
            ph.update(sharded_rows)
            _log(
                "bench: sharded grouped "
                f"{sharded_rows['sharded_grouped_sets_per_sec']:.1f} sets/s "
                f"on {sharded_rows['mesh_devices']} device(s)"
            )

    _log("bench: fleet-dryrun phase...")
    with em.phase("fleet_dryrun", deadline_s=deadline) as ph:
        fleet_rows = _bench_fleet_dryrun(jax, pipeline)
        if fleet_rows is not None:
            ph.update(fleet_rows)
            _log(
                "bench: fleet dryrun parity_ok="
                f"{fleet_rows['fleet_parity_ok']} overlap="
                f"{fleet_rows['fleet_overlap_fraction']:.3f} "
                f"({fleet_rows['fleet_sets_per_sec']:.1f} sets/s on "
                f"{fleet_rows['fleet_hosts']} emulated host(s))"
            )

    _log("bench: e2e mesh-raw phase...")
    with em.phase("e2e_mesh_raw", deadline_s=deadline) as ph:
        mesh_e2e_rows = _bench_e2e_mesh_raw(jax, pipeline, grouped_rate)
        if mesh_e2e_rows is not None:
            ph.update(mesh_e2e_rows)
            _log(
                "bench: e2e mesh-raw "
                f"{mesh_e2e_rows['e2e_mesh_raw_sets_per_sec']:.1f} sets/s "
                f"on {mesh_e2e_rows['e2e_mesh_raw_devices']} device(s)"
            )

    _log("bench: flood phase...")
    with em.phase("flood", deadline_s=deadline) as ph:
        ph.update(_bench_flood(pipeline))

    _log("bench: stage-profile phase...")
    with em.phase("stage_profile", deadline_s=deadline) as ph:
        from lodestar_tpu.observability.stage_profile import profile_stages

        ph.update(profile_stages(pipeline, batch=256))

    with em.phase("hasher", deadline_s=deadline) as ph:
        ph.update(_bench_hasher())

    doc = em.emit()
    if doc is not None:
        _log(f"bench details: {json.dumps(doc)[:2000]}")


if __name__ == "__main__":
    main()
