"""Headline benchmark: batched BLS signature-set verification throughput.

Reproduces the reference's hot workload (blst verifyMultipleSignatures via
the worker pool — beacon-node/test/perf/bls/bls.test.ts shapes, BASELINE.md
north star: >=50k signature-set verifications/sec, zero queue backlog) on
the device batch kernel: one XLA dispatch verifies the whole batch.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Methodology: device-only steady-state throughput of the all-or-nothing
batch kernel at the largest device bucket (1024 sets; the reference chunks at
MAX_SIGNATURE_SETS_PER_JOB). Host marshalling (hash-to-curve, decode) is
pipelined off the hot path in the service tier and excluded here, matching
how the reference benchmarks bls.verifyMultipleSignatures alone.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_SETS_PER_SEC = 50_000.0  # BASELINE.json north_star target
BATCH = 4096
REPS = 3  # ~5 s/rep on v5e: keep the driver's round-end bench bounded


def main() -> None:
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import jax

    try:
        jax.devices()
    except RuntimeError:
        # TPU tunnel unavailable — rerun on CPU so the bench always reports
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)])

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )

    from __graft_entry__ import _example_arrays
    from lodestar_tpu.parallel.verifier import batch_verify_kernel

    # device-resident inputs: the metric is steady-state device throughput
    # (the service tier streams batches and overlaps transfer with compute;
    # timing the tunnel's host→device copy per rep would measure the tunnel)
    args = [jax.device_put(a) for a in _example_arrays(BATCH)]
    jax.block_until_ready(args)
    fn = jax.jit(batch_verify_kernel)

    # compile + correctness gate
    ok = bool(fn(*args))
    assert ok, "bench batch failed verification"

    t0 = time.perf_counter()
    for _ in range(REPS):
        r = fn(*args)
    r.block_until_ready()
    dt = (time.perf_counter() - t0) / REPS

    sets_per_sec = BATCH / dt
    print(
        json.dumps(
            {
                "metric": "bls_signature_sets_verified_per_sec",
                "value": round(sets_per_sec, 2),
                "unit": "sets/s",
                "vs_baseline": round(sets_per_sec / BASELINE_SETS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
