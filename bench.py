"""Headline benchmark: batched BLS signature-set verification throughput.

Reproduces the reference's hot workload (blst verifyMultipleSignatures via
the worker pool — beacon-node/test/perf/bls/bls.test.ts shapes, BASELINE.md
north star: >=50k signature-set verifications/sec, zero queue backlog) on
the device batch kernel: one XLA dispatch verifies the whole batch.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — the
device-kernel steady-state number (comparable across rounds). The honest
END-TO-END pipeline number (wire bytes → native C marshal w/ h2c cache →
device dispatch → verdict; VERDICT round-1 weakness #3) is measured too
and written to bench_details.json next to this file, plus echoed on
stderr so the driver log carries it.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_SETS_PER_SEC = 50_000.0  # BASELINE.json north_star target
BATCH = 4096
REPS = 3  # ~5 s/rep on v5e: keep the driver's round-end bench bounded
UNIQUE_ROOTS = 64  # committee gossip shares signing roots (config #2 shape)


def _bench_device(jax) -> float:
    """Device-resident steady-state kernel throughput (sets/s)."""
    from __graft_entry__ import _example_arrays
    from lodestar_tpu.parallel.verifier import batch_verify_kernel

    args = [jax.device_put(a) for a in _example_arrays(BATCH)]
    jax.block_until_ready(args)
    fn = jax.jit(batch_verify_kernel)

    ok = bool(fn(*args))  # compile + correctness gate
    assert ok, "bench batch failed verification"

    t0 = time.perf_counter()
    for _ in range(REPS):
        r = fn(*args)
    r.block_until_ready()
    dt = (time.perf_counter() - t0) / REPS
    return BATCH / dt


def _bench_e2e() -> float | None:
    """Wire-bytes → verified/s through TpuBlsVerifier (marshal included).

    Sets are pre-generated OUTSIDE the timed region (network receive is
    not the thing under test); pubkeys come from a trusted cache exactly
    like the reference's pubkey cache (worker.ts deserializes without
    re-validating). Messages share UNIQUE_ROOTS signing roots per batch —
    the real gossip shape (a whole committee signs the same data).
    """
    from lodestar_tpu import native
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    if not native.HAVE_NATIVE_BLS:
        return None

    n_keys = 64
    sks = [bls.interop_secret_key(i) for i in range(n_keys)]
    pks = [sk.to_public_key() for sk in sks]
    roots = [bytes([r]) * 32 for r in range(UNIQUE_ROOTS)]
    sig_cache: dict[tuple[int, int], bytes] = {}
    sets = []
    for i in range(BATCH):
        k = i % n_keys
        m = (i * 7) % UNIQUE_ROOTS
        sig = sig_cache.get((k, m))
        if sig is None:
            sig = sig_cache[(k, m)] = sks[k].sign(roots[m]).to_bytes()
        sets.append(
            bls.SignatureSet(pubkey=pks[k], message=roots[m], signature=sig)
        )

    verifier = TpuBlsVerifier(buckets=(BATCH,))
    ok = verifier.verify_signature_sets(sets)  # compile + gate + warm h2c
    assert ok, "e2e batch failed verification"
    verifier._h2c_cache.clear()  # first timed rep pays the unique hashes

    t0 = time.perf_counter()
    for _ in range(REPS):
        ok = verifier.verify_signature_sets(sets)
    dt = (time.perf_counter() - t0) / REPS
    assert ok
    return BATCH / dt


def main() -> None:
    import os

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    import jax

    try:
        jax.devices()
    except RuntimeError:
        # TPU tunnel unavailable — rerun on CPU so the bench always reports
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)])

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )

    device_rate = _bench_device(jax)
    try:
        e2e_rate = _bench_e2e()
    except Exception as e:  # the headline metric must still report
        print(f"e2e bench failed: {e}", file=sys.stderr)
        e2e_rate = None

    details = {
        "device_sets_per_sec": round(device_rate, 2),
        "e2e_wire_to_verdict_sets_per_sec": (
            round(e2e_rate, 2) if e2e_rate else None
        ),
        "batch": BATCH,
        "unique_roots_per_batch": UNIQUE_ROOTS,
    }
    with open(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_details.json"),
        "w",
    ) as f:
        json.dump(details, f, indent=2)
    print(f"bench details: {details}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "bls_signature_sets_verified_per_sec",
                "value": round(device_rate, 2),
                "unit": "sets/s",
                "vs_baseline": round(device_rate / BASELINE_SETS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
