"""Per-slot status logging (reference: `node/notifier.ts` runNodeNotifier —
the one-line "Synced - slot: X - head: Y - finalized: Z - peers: N" heartbeat).
"""

from __future__ import annotations

import time

from ..utils.logger import get_logger


class NodeNotifier:
    def __init__(self, node, interval_slots: int = 1):
        self.node = node
        self.interval_slots = interval_slots
        self.log = get_logger("notifier")
        self._last_head = b""
        self._last_t = time.monotonic()

    def on_slot(self, clock_slot: int) -> None:
        if clock_slot % self.interval_slots:
            return
        chain = self.node.chain
        head = chain.head_state
        now = time.monotonic()
        dt = now - self._last_t
        self._last_t = now
        head_moved = chain.head_root != self._last_head
        self._last_head = chain.head_root
        n_peers = len(getattr(self.node, "peers", ()) or ())
        self.log.info(
            "%s - slot: %d - head: %d %s - exec: %s - finalized: %d - peers: %d (%.1fs)",
            "Synced" if head_moved else "Searching head",
            clock_slot,
            head.state.slot,
            chain.head_root.hex()[:8],
            (
                bytes(head.state.latest_execution_payload_header.block_hash).hex()[:8]
                if head.is_execution
                else "-"
            ),
            chain.finalized_checkpoint[0],
            n_peers,
            dt,
        )
