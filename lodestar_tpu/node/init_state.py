"""Anchor-state decision tree.

Reference: `cli/src/cmds/beacon/initBeaconState.ts` — in priority order:
1. checkpoint sync: fetch a finalized state from a trusted Beacon API and
   anchor from it (weak-subjectivity check applies);
2. db resume: the persisted latest state;
3. genesis: build from deposits (dev: interop genesis).

States persist with their fork name so resume decodes with the right
container across fork boundaries.
"""

from __future__ import annotations

import time

from ..params import ForkName
from ..utils.logger import get_logger

log = get_logger("init-state")


class StateInitError(Exception):
    pass


def _wall_clock_epoch(config, state) -> int:
    spe = config.preset.SLOTS_PER_EPOCH
    return max(
        0, int(time.time() - state.genesis_time) // (config.SECONDS_PER_SLOT * spe)
    )


def init_beacon_state(
    config,
    types_all,
    db,
    checkpoint_state_bytes: bytes | None = None,
    checkpoint_fork: str = ForkName.phase0,
    genesis_state=None,
    current_epoch: int | None = None,
):
    """Returns (state, origin) where origin ∈ {"checkpoint", "db", "genesis"}.

    `types_all`: the full per-fork namespace (get_types(preset)).
    `checkpoint_state_bytes`: SSZ-serialized finalized BeaconState from a
    trusted source (the CLI fetches it + its fork via getStateV2 —
    reference fetchWeakSubjectivityState). `current_epoch`: clock epoch for
    the weak-subjectivity check; None derives it from the wall clock.
    """
    ns = types_all.by_fork if hasattr(types_all, "by_fork") else None
    if checkpoint_state_bytes is not None:
        container = (
            ns[checkpoint_fork].BeaconState if ns else types_all.BeaconState
        )
        state = container.deserialize(checkpoint_state_bytes)
        epoch = current_epoch if current_epoch is not None else _wall_clock_epoch(config, state)
        from ..state_transition import CachedBeaconState
        from ..state_transition.weak_subjectivity import (
            compute_weak_subjectivity_period,
        )

        cached = CachedBeaconState(config, state.copy(), config.preset)
        ws_period = compute_weak_subjectivity_period(cached)
        if epoch > cached.current_epoch + ws_period:
            raise StateInitError(
                f"checkpoint state (epoch {cached.current_epoch}) is outside "
                f"the weak-subjectivity period ({ws_period} epochs) at clock "
                f"epoch {epoch}"
            )
        log.info(
            "anchor from checkpoint state: fork %s slot %d root %s",
            checkpoint_fork,
            state.slot,
            state.hash_tree_root().hex()[:12],
        )
        return state, "checkpoint"

    resumed = load_persisted_state(types_all, db)
    if resumed is not None:
        log.info("resuming from db: slot %d", resumed.slot)
        return resumed, "db"

    if genesis_state is not None:
        log.info("starting from genesis: time %d", genesis_state.genesis_time)
        return genesis_state, "genesis"

    raise StateInitError(
        "no anchor state: provide a checkpoint state, a populated datadir, "
        "or genesis parameters"
    )


# -- persistence (reference chain.persistToDisk/loadFromDisk) ----------------

# raw controller keys outside the Bucket range (0xfe prefix) so the state
# round-trips fork-agnostically
_STATE_KEY = bytes([0xFE]) + b"latest_state"
_FORK_KEY = bytes([0xFE]) + b"latest_state_fork"


def persist_state(db, state, fork: str | None = None) -> None:
    """Write the latest state snapshot (+ its fork name) for db-resume."""
    if fork is None:
        fork = _fork_of_state(state)
    controller = db.db
    controller.put(_STATE_KEY, type(state).ssz_type.serialize(state))
    controller.put(_FORK_KEY, str(fork).encode())


def load_persisted_state(types_all, db):
    controller = db.db
    raw = controller.get(_STATE_KEY)
    if raw is None:
        return None
    fork = (controller.get(_FORK_KEY) or b"phase0").decode()
    ns = types_all.by_fork if hasattr(types_all, "by_fork") else None
    container = ns[fork].BeaconState if ns else types_all.BeaconState
    return container.deserialize(raw)


def _fork_of_state(state) -> str:
    if hasattr(state, "next_withdrawal_index"):
        return ForkName.capella
    if hasattr(state, "latest_execution_payload_header"):
        return ForkName.bellatrix
    if hasattr(state, "previous_epoch_participation"):
        return ForkName.altair
    return ForkName.phase0
