"""BeaconNode composition root.

Reference: `beacon-node/src/node/nodejs.ts:127-270` — wiring order
db.start → metrics → chain → network → sync → api server → metrics server;
`close()` persists the chain state back to the db (nodejs.ts:275-290).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..api import BeaconApiServer
from ..api.impl import BeaconApiImpl
from ..chain import BeaconChain, CpuBlsVerifier
from ..db import BeaconDb
from ..db.controller import FileDb, MemoryDb
from ..metrics import MetricsServer, create_beacon_metrics
from ..utils.logger import get_logger
from .init_state import persist_state
from .notifier import NodeNotifier


@dataclass
class NodeOptions:
    """Reference: IBeaconNodeOptions (`node/options.ts`) — the flag tree the
    CLI maps 1:1 onto."""

    datadir: str | None = None  # None → in-memory db
    db_controller: object | None = None  # pre-opened controller wins over datadir
    rest: bool = True
    rest_port: int = 0
    rest_bearer_token: str | None = None  # require Authorization: Bearer …
    rest_cors_origin: str | None = None  # Access-Control-Allow-Origin value
    metrics: bool = False
    metrics_port: int = 0
    tpu_verifier: bool = False
    execution_engine: object | None = None
    eth1_provider: object | None = None  # IEth1Provider (mock or HTTP)
    notifier_interval_slots: int = 1


class BeaconNode:
    """Owns every service; `BeaconNode.init(...)` is the only constructor
    path (reference pattern)."""

    def __init__(self):
        raise TypeError("use BeaconNode.init()")

    @classmethod
    def init(cls, config, types, anchor_state, opts: NodeOptions | None = None):
        self = object.__new__(cls)
        opts = opts or NodeOptions()
        self.opts = opts
        self.config = config
        self.types = types
        self.log = get_logger("node")
        # cold-start timeline: marks are seconds since PROCESS start, so
        # interpreter+import time counts toward the serving-ready SLO
        from ..observability.compile_ledger import timeline

        timeline().mark("node_init")

        # 1. db
        if opts.db_controller is not None:
            controller = opts.db_controller
        else:
            controller = FileDb(opts.datadir) if opts.datadir else MemoryDb()
        self.db = BeaconDb(types, controller)

        # 2. metrics + per-validator monitor (reference validatorMonitor
        # wired at node init; register indices via monitor_validators())
        self.metrics = create_beacon_metrics()
        from ..state_transition import stf as _stf

        _stf.set_metrics(self.metrics)
        from ..metrics.validator_monitor import ValidatorMonitor

        self.validator_monitor = ValidatorMonitor(self.metrics.registry)
        from ..metrics.gc_stats import install_gc_metrics

        install_gc_metrics(self.metrics.registry)

        # 2b. lifecycle tracing: the process-wide tracer backs the metrics
        # server's /debug/traces; completed traces tick the prometheus
        # counter. Re-init (tests, in-process restart) REPLACES the node
        # hook so a dead registry stops receiving counts.
        from ..observability import spans as _spans

        self.tracer = _spans.tracer

        def _count_trace(doc, _m=self.metrics):
            kind = (doc.get("attrs") or {}).get("kind") or doc["name"]
            _m.lifecycle_traces_total.inc(kind=kind)

        _count_trace._node_wired = True
        self.tracer.on_finish[:] = [
            cb for cb in self.tracer.on_finish
            if not getattr(cb, "_node_wired", False)
        ] + [_count_trace]

        # 3. chain (verifier choice mirrors reference blsVerifyAllMainThread);
        # the device tier sits behind the cross-thread batching facade so
        # concurrent gossip-queue validations merge into device batches
        if opts.tpu_verifier:
            # persistent XLA compile cache BEFORE the first kernel trace:
            # a node restart must hit `tools/warmup.py`'s cached
            # executables, not recompile the dispatch ladder cold
            # (LODESTAR_TPU_COMPILE_CACHE overrides/disables)
            from ..utils.jax_env import enable_compile_cache

            enable_compile_cache()
            from ..chain.bls_verifier import DeviceBlsVerifier
            from ..chain.dispatcher import BlsLaneDispatcher
            from ..chain.supervisor import SupervisedBlsVerifier

            # pipeline telemetry rides the node registry: stage timers +
            # planner counters from the device tier, flush/queue gauges
            # from the batching facade — all on /metrics by default.
            # The supervisor between them owns the failure policy:
            # per-dispatch deadlines, one retry, CPU-oracle fallback and
            # the circuit breaker (docs/robustness.md) — a device outage
            # degrades throughput instead of rejecting valid blocks
            self.bls_supervisor = SupervisedBlsVerifier(
                DeviceBlsVerifier(observer=self.metrics.pipeline),
                CpuBlsVerifier(),
                observer=self.metrics.pipeline,
            )
            # continuous-batching front-end with priority lanes (block >
            # sync-committee > aggregate > attestation): coalesces
            # concurrent gossip verifies, double-buffers host prep
            # against device compute, and sheds attestations first under
            # flood (never blocks) — chain/dispatcher.py
            verifier = BlsLaneDispatcher(
                self.bls_supervisor, prom=self.metrics,
            )
            # crash-safe warm boot (ISSUE 19): load every persisted AOT
            # executable for this build fingerprint BEFORE declaring the
            # verifier ready — a restart against a populated store serves
            # its dispatch ladder without entering XLA at all; a missing/
            # corrupt store degrades to the normal JIT path (counted, not
            # fatal)
            from ..observability.compile_ledger import ledger as _ledger

            aot = _ledger().preload_aot()
            if aot["loaded"]:
                self.log.info(
                    "aot store: %d executable(s) loaded in %.1fs "
                    "(restart without XLA in the loop)",
                    len(aot["loaded"]), aot["seconds"],
                )
            timeline().mark("verifier_ready")
        else:
            self.bls_supervisor = None
            verifier = CpuBlsVerifier()
        # fleet ingest routing (ISSUE 20): when LODESTAR_TPU_FLEET is
        # active this host validates only its subnet slice of attestation
        # gossip, and a supervisor host-eviction rebalances the slice map
        # onto the survivors (parallel/fleet.py; wired into the gossip
        # handlers at attach_network)
        from ..parallel.fleet import FleetRouter, FleetTopology

        fleet_topo = FleetTopology.from_env()
        self.fleet_router = None
        if fleet_topo.active:
            self.fleet_router = FleetRouter(
                fleet_topo.hosts, fleet_topo.rank,
                observer=self.metrics.pipeline,
            )
            if self.bls_supervisor is not None:
                try:
                    self.bls_supervisor.fleet_attach_router(
                        self.fleet_router
                    )
                except Exception:  # noqa: BLE001 — wiring must not kill init
                    self.log.debug(
                        "fleet router mesh attach failed", exc_info=True
                    )
            self.log.info(
                "fleet ingest: rank %d/%d owns %d attestation subnet(s)",
                fleet_topo.rank, fleet_topo.hosts,
                len(self.fleet_router.slice_for()),
            )
        self.chain = BeaconChain(
            config,
            types,
            anchor_state,
            verifier=verifier,
            db=self.db,
            execution_engine=opts.execution_engine,
        )
        self.chain.metrics = self.metrics
        if hasattr(self.db.db, "metrics"):
            self.db.db.metrics = self.metrics
        self.chain.validator_monitor = self.validator_monitor

        # 3b. eth1 deposit follower (live JSON-RPC or mock; None = none)
        self.eth1_tracker = None
        if opts.eth1_provider is not None:
            from ..eth1 import Eth1DepositTracker

            self.eth1_tracker = Eth1DepositTracker(
                config, types, opts.eth1_provider
            )
            self.chain.eth1_tracker = self.eth1_tracker

        # 4. network + sync are attached by the caller once a transport
        # exists (dev mode runs networkless, like reference dev w/o peers)
        self.peers = []
        self.sync = None
        self.network = None

        # 5. servers
        self.api_server = None
        self.metrics_server = None
        if opts.rest:
            impl = BeaconApiImpl(config, types, self.chain)
            self.api_server = BeaconApiServer(
                impl, port=opts.rest_port, metrics=self.metrics,
                bearer_token=opts.rest_bearer_token,
                cors_origin=opts.rest_cors_origin,
            )
            self.api_server.start()
            self.log.info("REST API on :%d", self.api_server.port)
        # SLO engine over the node's live pipeline: /debug/slo, the
        # lodestar_slo_* families and supervisor pokes all read it
        from ..observability import device_ledger, slo

        slo.install(self.metrics.pipeline)
        if opts.metrics:
            self.metrics_server = MetricsServer(
                self.metrics.registry, port=opts.metrics_port,
                tracer=self.tracer,
                breaker=(
                    self.bls_supervisor.breaker_snapshot
                    if self.bls_supervisor is not None
                    else None
                ),
                mesh=(
                    self.bls_supervisor.mesh_snapshot
                    if self.bls_supervisor is not None
                    else None
                ),
                fleet=self._fleet_debug_snapshot,
                lanes=self.metrics.pipeline.lanes_snapshot,
                slo=slo.snapshot_or_none,
                device=device_ledger.ledger().snapshot,
                epoch_table=(
                    self.bls_supervisor.epoch_table_snapshot
                    if self.bls_supervisor is not None
                    else None
                ),
            )
            self.metrics_server.start()
            self.log.info("metrics on :%d", self.metrics_server.port)

        self.notifier = NodeNotifier(self, opts.notifier_interval_slots)

        # runtime identity on /metrics (lodestar_tpu_build_info) + the
        # serving-ready SLO mark: init returning IS this node's ready
        # point. Device enumeration only when the device tier is on — a
        # CPU-only node must not pay backend init just to label a gauge.
        from ..utils.jax_env import runtime_info

        self.metrics.pipeline.set_build_info(
            runtime_info(enumerate_devices=opts.tpu_verifier)
        )
        ready_s = timeline().mark_serving_ready()
        self.log.info("serving-ready %.2fs after process start", ready_s)
        return self

    def attach_network(self, network) -> None:
        """Bind a started Network: REST node-identity/peers routes and the
        sync layer see it (reference nodejs.ts wiring order §3.1). A
        fleet node also binds its subnet router into the gossip handlers
        so foreign-slice attestations are dropped pre-validation."""
        self.network = network
        if self.api_server is not None:
            self.api_server.impl.network = network
        handlers = getattr(network, "gossip_handlers", None)
        if self.fleet_router is not None and handlers is not None:
            from ..utils.env import env_bool

            if env_bool("LODESTAR_TPU_FLEET_INGEST"):
                handlers.fleet_router = self.fleet_router

    def _fleet_debug_snapshot(self):
        """Zero-arg provider for `/debug/fleet`: the two-level mesh census
        (with the router's slice state) when the device tier serves a
        fleet, else the bare router view, else None (wired: false)."""
        snap = None
        if self.bls_supervisor is not None:
            try:
                snap = self.bls_supervisor.fleet_snapshot()
            except Exception:  # noqa: BLE001 — debug surface must not raise
                snap = None
        if snap is None and self.fleet_router is not None:
            snap = {"router": self.fleet_router.snapshot()}
        if snap is not None:
            snap["counters"] = self.metrics.pipeline.fleet_snapshot()
        return snap

    # -- slot driving --------------------------------------------------------

    def monitor_validators(self, indices) -> None:
        """Register validator indices for per-duty tracking (reference
        --monitoredValidators flag → validatorMonitor)."""
        for i in indices:
            self.validator_monitor.register_validator(int(i))

    def on_clock_slot(self, slot: int) -> None:
        """Per-slot housekeeping: clock, fork-choice time, prepared state,
        metrics, status line."""
        self.chain.clock.set_slot(slot)
        self.chain.fork_choice.update_time(slot)
        self.chain.prepare_next_slot.on_slot(slot)
        self._follow_eth1_async()
        m = self.metrics
        m.head_slot.set(self.chain.head_state.state.slot)
        m.clock_slot.set(slot)
        m.clock_epoch.set(slot // self.config.preset.SLOTS_PER_EPOCH)
        m.head_distance.set(max(0, slot - self.chain.head_state.state.slot))
        try:
            m.active_validators.set(
                len(
                    self.chain.head_state.flat.active_indices(
                        slot // self.config.preset.SLOTS_PER_EPOCH
                    )
                )
            )
        except Exception as e:
            # phase0 test states lack the flat active-index path
            self.log.debug("active-validator gauge update failed: %s", e)
        m.current_justified_epoch.set(self.chain.justified_checkpoint[0])
        m.finalized_epoch.set(self.chain.finalized_checkpoint[0])
        m.state_cache_size.set(len(self.chain.state_cache._cache))
        m.fork_choice_nodes.set(len(self.chain.fork_choice.proto.nodes))
        m.fork_choice_votes.set(len(self.chain.fork_choice._vote_next))
        m.proposer_boost_active.set(
            1 if self.chain.fork_choice.proposer_boost_root else 0
        )
        for kind, cache in (
            ("attesters", self.chain.seen_attesters),
            ("aggregators", self.chain.seen_aggregators),
            ("block_proposers", self.chain.seen_block_proposers),
            ("aggregated", self.chain.seen_aggregated),
            ("sync_committee", self.chain.seen_sync_committee),
        ):
            try:
                m.seen_cache_size.set(len(cache._seen), kind=kind)
            except (AttributeError, TypeError):
                pass
        # h2c cache size via the DeviceBlsVerifier seam (ThreadBuffered
        # facade delegates); CpuBlsVerifier has no cache — gauge stays 0
        sizer = getattr(self.chain.bls, "h2c_cache_size", None)
        if callable(sizer):
            m.h2c_cache_size.set(sizer())
        # 0 stalled / 1 syncing / 2 synced: synced = within one slot of
        # the clock; stalled = behind AND head unchanged for >3 slots
        head = self.chain.head_state.state.slot
        if slot - head <= 1:
            m.sync_status.set(2)
            self._head_progress = (head, slot)
        else:
            last_head, last_slot = getattr(self, "_head_progress", (head, slot))
            if head > last_head:
                self._head_progress = (head, slot)
                m.sync_status.set(1)
            elif slot - last_slot > 3:
                m.sync_status.set(0)
            else:
                m.sync_status.set(1)
        pool = self.chain.attestation_pool
        m.op_pool_size.set(
            sum(len(v) for v in pool._by_slot.values())
            if hasattr(pool, "_by_slot")
            else 0,
            kind="attestations",
        )
        m.op_pool_size.set(len(self.chain.op_pool.voluntary_exits), kind="exits")
        m.op_pool_size.set(
            len(self.chain.op_pool.attester_slashings), kind="attester_slashings"
        )
        spe = self.config.preset.SLOTS_PER_EPOCH
        if slot % spe == 0:
            # epoch transition: pre-populate the device-resident pubkey
            # table for the new epoch's active set (ISSUE 18) — off the
            # slot path, one population in flight at a time
            self._populate_epoch_table_async(slot // spe)
        if slot % spe == 0 and self.validator_monitor.monitored:
            epoch_now = slot // spe
            if epoch_now >= 2:
                self.validator_monitor.on_balances(
                    epoch_now - 2, self.chain.head_state.state.balances
                )
                self.validator_monitor.log_epoch(epoch_now - 2, self.log)
        stats = getattr(self.db.db, "stats", None)
        if callable(stats):
            st = stats()
            m.db_entries.set(st["entries"])
            m.db_live_bytes.set(st["live_bytes"])
            m.db_dead_bytes.set(st["dead_bytes"])
        self.notifier.on_slot(slot)

    def _populate_epoch_table_async(self, epoch: int) -> None:
        """Decompress the epoch's active-validator pubkeys into the
        device-resident `EpochPubkeyTable` on a background thread — the
        reference's EpochContext pubkey cache, device-tier (ISSUE 18).
        Committees are fixed per epoch, so after this the attestation
        lanes read pubkey limbs with a memcpy instead of a C-tier sqrt.
        At most one population in flight; verifiers without the seam
        (CPU tier, mock) are skipped."""
        populate = getattr(self.chain.bls, "epoch_table_populate", None)
        if not callable(populate) or getattr(self, "_epoch_table_filling", False):
            return
        try:
            flat = self.chain.head_state.flat
            indices = flat.active_indices(epoch)
            pubkeys = [flat.pubkeys[int(i)].to_bytes() for i in indices]
        except Exception as e:
            # phase0 test states lack the flat active-index path
            self.log.debug("epoch-table population skipped: %s", e)
            return
        self._epoch_table_filling = True

        def _run():
            try:
                rows = populate(epoch, pubkeys)
                self.log.info(
                    "epoch table populated: epoch %d, %d rows", epoch, rows
                )
            except Exception as e:
                self.log.warning("epoch-table population failed: %s", e)
            finally:
                self._epoch_table_filling = False

        import threading

        threading.Thread(
            target=_run, name="epoch-table-fill", daemon=True
        ).start()

    def _follow_eth1_async(self) -> None:
        """Kick the deposit-log follower on a background thread, at most
        one catch-up in flight (reference: periodic eth1 update loop —
        the initial historical sync can take minutes and must never block
        the slot path or a proposal)."""
        tracker = self.eth1_tracker
        if tracker is None or getattr(self, "_eth1_following", False):
            return
        self._eth1_following = True

        def _run():
            try:
                tracker.follow()
            except Exception as e:
                self.log.warning("eth1 follow failed: %s", e)
            finally:
                self._eth1_following = False

        import threading

        threading.Thread(target=_run, name="eth1follow", daemon=True).start()

    def run(self, slots: int, slot_time: float = 0.0, on_slot=None) -> None:
        """Drive `slots` wall-clock slots (dev/test; production would follow
        the genesis-anchored clock)."""
        start = self.chain.head_state.state.slot
        for slot in range(start + 1, start + slots + 1):
            if on_slot is not None:
                on_slot(slot)
            self.on_clock_slot(slot)
            if slot_time > 0:
                time.sleep(slot_time)

    # -- shutdown ------------------------------------------------------------

    def close(self) -> None:
        """Persist the head state then stop servers (reference
        BeaconNode.close → chain.persistToDisk)."""
        try:
            head = self.chain.head_state
            head.sync_flat()
            persist_state(self.db, head.state, head.fork)
        except Exception as e:  # persist is best-effort on shutdown
            self.log.error("state persist failed: %s", e)
        if self.api_server:
            self.api_server.close()
        if self.metrics_server:
            self.metrics_server.close()
        stopper = getattr(self.chain.bls, "stop_profiling", None)
        if callable(stopper):
            stopper()  # flush the XLA trace (LODESTAR_TPU_PROFILE)
        # lane dispatcher: stop workers, shed queued waiters promptly.
        # Looked up on the TYPE so the facade's __getattr__ delegation
        # can't alias this onto the supervisor's close()
        if hasattr(type(self.chain.bls), "close"):
            try:
                self.chain.bls.close()
            except Exception as e:
                self.log.error("lane dispatcher close failed: %s", e)
        if getattr(self, "bls_supervisor", None) is not None:
            self.bls_supervisor.close()  # stop canary + dispatch worker
        self.chain._verify_pool.shutdown(wait=False)
        self.db.close()
