"""BeaconNode: the composition root.

Reference: `beacon-node/src/node/nodejs.ts:127-270` — `BeaconNode.init()`
wires db → metrics → chain → network → sync → api → servers, and `close()`
persists caches; `node/notifier.ts` logs per-slot status lines.
"""

from .node import BeaconNode, NodeOptions  # noqa: F401
from .init_state import init_beacon_state  # noqa: F401
from .notifier import NodeNotifier  # noqa: F401
