"""lodestar_tpu — a TPU-native Ethereum consensus framework.

A from-scratch rebuild of the capabilities of ChainSafe Lodestar (reference:
/root/reference, v1.1.1) designed TPU-first: the consensus state transition and
fork choice are pure Python/numpy over flat arrays, SSZ merkleization is backed
by a batched hashing layer, and the hot path — BLS12-381 batch signature
verification (reference: packages/beacon-node/src/chain/bls/) — runs as
vmapped XLA kernels on TPU with a pure-Python bigint tier as fallback and
correctness oracle.

Layering (mirrors SURVEY.md §1, bottom-up):
  params -> utils -> ssz -> types -> config -> ops/bls/parallel ->
  state_transition / fork_choice -> db -> api -> chain/network/sync ->
  validator / light_client -> cli
"""

__version__ = "0.1.0"
