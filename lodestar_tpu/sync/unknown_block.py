"""Unknown-block sync: resolve unknown parent chains by root.

Reference: `sync/unknownBlock.ts:26` — when gossip delivers a block (or an
attestation references a root) whose ancestry is unknown, walk parents
backward via beacon_blocks_by_root until connecting to the known chain,
then import forward."""

from __future__ import annotations

from .peer import IPeer, PeerError

MAX_PARENT_CHAIN = 32


class UnknownBlockSyncError(Exception):
    pass


class UnknownBlockSync:
    def __init__(self, chain, types):
        self.chain = chain
        self.types = types
        self.peers: list[IPeer] = []

    def add_peer(self, peer: IPeer) -> None:
        self.peers.append(peer)

    def resolve(self, signed_block, verify_signatures: bool = True) -> bytes:
        """Import `signed_block`, fetching unknown ancestors first.
        Returns the imported block root."""
        pending = [signed_block]
        seen = {signed_block.message.hash_tree_root()}
        while True:
            parent_root = bytes(pending[-1].message.parent_root)
            if parent_root in self.chain.blocks or parent_root in self.chain.finalized_blocks:
                break
            if len(pending) >= MAX_PARENT_CHAIN:
                raise UnknownBlockSyncError("parent chain too long")
            fetched = self._fetch_by_root(parent_root)
            if fetched is None:
                raise UnknownBlockSyncError(
                    f"no peer has parent {parent_root.hex()}"
                )
            root = fetched.message.hash_tree_root()
            if root != parent_root or root in seen:
                raise UnknownBlockSyncError("peer returned wrong/duplicate block")
            seen.add(root)
            pending.append(fetched)
        for signed in reversed(pending):
            self.chain.process_block(signed, verify_signatures=verify_signatures)
        return signed_block.message.hash_tree_root()

    def _fetch_by_root(self, root: bytes):
        for peer in self.peers:
            try:
                got = peer.beacon_blocks_by_root([root])
            except PeerError:
                continue
            if got:
                return got[0]
        return None
