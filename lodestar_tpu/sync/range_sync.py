"""Range sync: download epoch batches from peers, import sequentially.

Reference: `sync/range/` — `SyncChain` (chain.ts:82) holds a window of
`SyncBatch`es in a state machine (AwaitingDownload → Downloading →
AwaitingProcessing → Processing → AwaitingValidation), downloads from many
peers concurrently with a peer balancer (`utils/peerBalancer.ts`), imports
in order, retries failed batches with rotated peers (`batch.ts`).

This implementation keeps the batch state machine and peer rotation; the
download loop is synchronous rounds (the asyncio overlap arrives with the
live transport)."""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from .peer import IPeer, PeerError

EPOCHS_PER_BATCH = 2
MAX_BATCH_RETRIES = 5


class BatchStatus(str, Enum):
    AWAITING_DOWNLOAD = "AwaitingDownload"
    DOWNLOADING = "Downloading"
    AWAITING_PROCESSING = "AwaitingProcessing"
    PROCESSING = "Processing"
    PROCESSED = "Processed"
    FAILED = "Failed"


@dataclass
class SyncBatch:
    start_slot: int
    count: int
    status: BatchStatus = BatchStatus.AWAITING_DOWNLOAD
    blocks: list = field(default_factory=list)
    failed_attempts: int = 0
    failed_peers: set[str] = field(default_factory=set)


class RangeSyncError(Exception):
    pass


class RangeSync:
    def __init__(
        self, chain, types, slots_per_epoch: int, verify_signatures: bool = True,
        metrics=None,
    ):
        self.chain = chain
        self.types = types
        self.spe = slots_per_epoch
        self.verify_signatures = verify_signatures
        self.peers: list[IPeer] = []
        self.metrics = metrics

    def _export_batch_states(self, batches) -> None:
        if self.metrics is None:
            return
        counts: dict[str, int] = {s.value: 0 for s in BatchStatus}
        for b in batches:
            counts[b.status.value] = counts.get(b.status.value, 0) + 1
        for state, n in counts.items():
            self.metrics.sync_batches_in_state.set(n, state=state)

    def add_peer(self, peer: IPeer) -> None:
        self.peers.append(peer)

    # -- peer balancer (reference utils/peerBalancer.ts) ---------------------

    def _pick_peer(self, batch: SyncBatch) -> IPeer:
        candidates = [p for p in self.peers if p.peer_id not in batch.failed_peers]
        if not candidates:
            candidates = self.peers
        if not candidates:
            raise RangeSyncError("no peers")
        # least-recently-failed first, stable rotation by attempt count
        return candidates[batch.failed_attempts % len(candidates)]

    # -- driving -------------------------------------------------------------

    def sync_to(self, target_slot: int) -> int:
        """Sync the canonical chain up to `target_slot`; returns head slot.

        Builds the batch window, downloads each batch (with retries and
        peer rotation), processes in order — one round-trip of the
        reference's state machine per batch."""
        head_slot = self.chain.head_state.state.slot
        batch_span = EPOCHS_PER_BATCH * self.spe
        batches: list[SyncBatch] = []
        start = head_slot + 1
        while start <= target_slot:
            count = min(batch_span, target_slot - start + 1)
            batches.append(SyncBatch(start_slot=start, count=count))
            start += count

        for batch in batches:
            self._export_batch_states(batches)
            self._download(batch)
            self._process(batch)
            self._export_batch_states(batches)
        return self.chain.head_state.state.slot

    def _download(self, batch: SyncBatch) -> None:
        while batch.failed_attempts <= MAX_BATCH_RETRIES:
            peer = self._pick_peer(batch)
            batch.status = BatchStatus.DOWNLOADING
            try:
                batch.blocks = peer.beacon_blocks_by_range(
                    batch.start_slot, batch.count
                )
                batch.status = BatchStatus.AWAITING_PROCESSING
                return
            except PeerError:
                batch.failed_attempts += 1
                batch.failed_peers.add(peer.peer_id)
                batch.status = BatchStatus.AWAITING_DOWNLOAD
        batch.status = BatchStatus.FAILED
        raise RangeSyncError(
            f"batch at slot {batch.start_slot} failed after retries"
        )

    def _process(self, batch: SyncBatch) -> None:
        import time as _time

        batch.status = BatchStatus.PROCESSING
        t0 = _time.monotonic()
        try:
            # segment import: the WHOLE batch's signature sets verify as
            # one batched dispatch (reference verifyBlocksSignatures —
            # ~8k sigs per mainnet segment in one worker batch)
            self.chain.process_block_segment(
                batch.blocks, verify_signatures=self.verify_signatures
            )
            batch.status = BatchStatus.PROCESSED
            if self.metrics is not None:
                self.metrics.sync_range_batches_total.inc(outcome="processed")
                self.metrics.sync_blocks_imported_total.inc(len(batch.blocks))
                self.metrics.sync_segment_seconds.observe(_time.monotonic() - t0)
        except Exception as e:
            # a bad segment sends the batch back for re-download from a
            # different peer (reference: batch retry on processing failure)
            batch.failed_attempts += 1
            batch.status = BatchStatus.FAILED
            if self.metrics is not None:
                self.metrics.sync_range_batches_total.inc(outcome="failed")
            raise RangeSyncError(f"processing failed: {e}") from e
