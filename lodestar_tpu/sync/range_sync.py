"""Range sync: download epoch batches from peers, import sequentially.

Reference: `sync/range/` — `SyncChain` (chain.ts:82) holds a window of
`SyncBatch`es in a state machine (AwaitingDownload → Downloading →
AwaitingProcessing → Processing → AwaitingValidation), downloads from many
peers concurrently with a peer balancer (`utils/peerBalancer.ts`), imports
in order, retries failed batches with rotated peers (`batch.ts`).

This implementation keeps the batch state machine and peer rotation, and
overlaps download with import (VERDICT r3 #7): a bounded window of
batches downloads concurrently on a thread pool (network I/O releases
the GIL; the reference keeps ~`batchBuffer` batches in flight the same
way, `sync/range/chain.ts:82`) while the import side consumes strictly
in order — so the TPU verifier is never idle waiting on the wire, and
the wire never waits on a long segment import."""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from .peer import IPeer, PeerError

EPOCHS_PER_BATCH = 2
MAX_BATCH_RETRIES = 5
DOWNLOAD_WINDOW = 4  # batches in flight ahead of the import cursor
# (reference: SyncChain keeps batchBuffer=5 epochs of batches downloading
# while processing sequentially — sync/range/chain.ts)


class BatchStatus(str, Enum):
    AWAITING_DOWNLOAD = "AwaitingDownload"
    DOWNLOADING = "Downloading"
    AWAITING_PROCESSING = "AwaitingProcessing"
    PROCESSING = "Processing"
    PROCESSED = "Processed"
    FAILED = "Failed"


@dataclass
class SyncBatch:
    start_slot: int
    count: int
    status: BatchStatus = BatchStatus.AWAITING_DOWNLOAD
    blocks: list = field(default_factory=list)
    failed_attempts: int = 0
    failed_peers: set[str] = field(default_factory=set)
    rr_offset: int = 0  # spreads concurrent first attempts over peers


class RangeSyncError(Exception):
    pass


class RangeSync:
    def __init__(
        self, chain, types, slots_per_epoch: int, verify_signatures: bool = True,
        metrics=None, download_window: int = DOWNLOAD_WINDOW,
        epochs_per_batch: int = EPOCHS_PER_BATCH,
    ):
        self.chain = chain
        self.types = types
        self.spe = slots_per_epoch
        self.verify_signatures = verify_signatures
        self.peers: list[IPeer] = []
        self.metrics = metrics
        self.download_window = max(1, download_window)
        self.epochs_per_batch = max(1, epochs_per_batch)

    def _export_batch_states(self, batches) -> None:
        if self.metrics is None:
            return
        counts: dict[str, int] = {s.value: 0 for s in BatchStatus}
        for b in batches:
            counts[b.status.value] = counts.get(b.status.value, 0) + 1
        for state, n in counts.items():
            self.metrics.sync_batches_in_state.set(n, state=state)

    def add_peer(self, peer: IPeer) -> None:
        self.peers.append(peer)

    # -- peer balancer (reference utils/peerBalancer.ts) ---------------------

    def _pick_peer(self, batch: SyncBatch) -> IPeer:
        candidates = [p for p in self.peers if p.peer_id not in batch.failed_peers]
        if not candidates:
            candidates = self.peers
        if not candidates:
            raise RangeSyncError("no peers")
        # rotate by attempt count (every retry lands on a DIFFERENT peer —
        # deterministic, so two peers always alternate) offset by the
        # batch's fixed index (concurrent window batches spread over the
        # peer set instead of piling on peers[0] — the reference's
        # peerBalancer assigns idle peers first)
        return candidates[(batch.failed_attempts + batch.rr_offset) % len(candidates)]

    # -- driving -------------------------------------------------------------

    def sync_to(self, target_slot: int) -> int:
        """Sync the canonical chain up to `target_slot`; returns head slot.

        Builds the batch window, downloads each batch (with retries and
        peer rotation), processes in order — one round-trip of the
        reference's state machine per batch."""
        head_slot = self.chain.head_state.state.slot
        batch_span = self.epochs_per_batch * self.spe
        batches: list[SyncBatch] = []
        start = head_slot + 1
        while start <= target_slot:
            count = min(batch_span, target_slot - start + 1)
            batches.append(
                SyncBatch(start_slot=start, count=count, rr_offset=len(batches))
            )
            start += count

        if not batches:
            return head_slot

        from concurrent.futures import ThreadPoolExecutor

        window = self.download_window
        with ThreadPoolExecutor(
            max_workers=window, thread_name_prefix="range-dl"
        ) as pool:
            futures: dict[int, object] = {}

            def top_up(cursor: int) -> None:
                hi = min(len(batches), cursor + window)
                for j in range(cursor, hi):
                    if j not in futures:
                        futures[j] = pool.submit(self._download, batches[j])

            for i, batch in enumerate(batches):
                top_up(i)
                self._export_batch_states(batches)
                futures.pop(i).result()  # raises if download exhausted retries
                top_up(i + 1)  # keep the window full while we import
                self._process(batch)
                self._export_batch_states(batches)
        return self.chain.head_state.state.slot

    def _download(self, batch: SyncBatch) -> None:
        while batch.failed_attempts <= MAX_BATCH_RETRIES:
            peer = self._pick_peer(batch)
            batch.status = BatchStatus.DOWNLOADING
            try:
                # concurrent window batches may land on the same peer;
                # IPeer implementations serialize requests internally
                batch.blocks = peer.beacon_blocks_by_range(
                    batch.start_slot, batch.count
                )
                batch.status = BatchStatus.AWAITING_PROCESSING
                return
            except PeerError:
                batch.failed_attempts += 1
                batch.failed_peers.add(peer.peer_id)
                batch.status = BatchStatus.AWAITING_DOWNLOAD
        batch.status = BatchStatus.FAILED
        raise RangeSyncError(
            f"batch at slot {batch.start_slot} failed after retries"
        )

    def _process(self, batch: SyncBatch) -> None:
        import time as _time

        batch.status = BatchStatus.PROCESSING
        t0 = _time.monotonic()
        try:
            # segment import: the WHOLE batch's signature sets verify as
            # one batched dispatch (reference verifyBlocksSignatures —
            # ~8k sigs per mainnet segment in one worker batch)
            self.chain.process_block_segment(
                batch.blocks, verify_signatures=self.verify_signatures
            )
            batch.status = BatchStatus.PROCESSED
            if self.metrics is not None:
                self.metrics.sync_range_batches_total.inc(outcome="processed")
                self.metrics.sync_blocks_imported_total.inc(len(batch.blocks))
                self.metrics.sync_segment_seconds.observe(_time.monotonic() - t0)
        except Exception as e:
            # a bad segment sends the batch back for re-download from a
            # different peer (reference: batch retry on processing failure)
            batch.failed_attempts += 1
            batch.status = BatchStatus.FAILED
            if self.metrics is not None:
                self.metrics.sync_range_batches_total.inc(outcome="failed")
            raise RangeSyncError(f"processing failed: {e}") from e
