"""Sync (SURVEY.md §2.2 `sync/`): range sync, unknown-block sync.

Reference: `sync/sync.ts` orchestrator — RangeSync (per-target SyncChains
of epoch batches with peer balancing, `range/`), UnknownBlockSync
(fetch-by-root for unknown parents, `unknownBlock.ts`), BackfillSync.
Peers are anything speaking the req/resp surface (`IPeer`), so tests wire
two in-process nodes through the real wire codec.
"""

from .range_sync import BatchStatus, RangeSync, SyncBatch  # noqa: F401
from .unknown_block import UnknownBlockSync  # noqa: F401
from .peer import IPeer, LocalPeer  # noqa: F401
