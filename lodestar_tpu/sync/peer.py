"""Peer abstraction for sync: the req/resp client surface.

`LocalPeer` wires two in-process nodes through the REAL wire codec
(encode_request → handler → decode_response_chunks), so sync tests
exercise the same bytes a network transport would carry (reference analog:
e2e tests with real libp2p between local nodes, SURVEY.md §4.4)."""

from __future__ import annotations

from typing import Protocol

from ..network.reqresp import (
    RespCode,
    decode_response_chunks,
)


class IPeer(Protocol):
    """A sync-usable remote peer.

    Implementations MUST tolerate concurrent request calls (serialize
    internally, as LocalPeer does): RangeSync's download window and
    BackfillSync may both issue requests to the same peer from
    different threads, and a transport multiplexing one stream per
    peer would otherwise interleave request frames."""

    peer_id: str

    def status(self): ...
    def beacon_blocks_by_range(self, start_slot: int, count: int) -> list: ...
    def beacon_blocks_by_root(self, roots: list[bytes]) -> list: ...


class PeerError(Exception):
    pass


class LocalPeer:
    """A peer backed by another node's ReqRespHandlers (same process).

    Requests serialize on an internal lock — the IPeer contract — so
    RangeSync's download window and BackfillSync can hit the same peer
    from different threads without interleaving."""

    def __init__(self, peer_id: str, handlers, types):
        import threading

        self.peer_id = peer_id
        self.handlers = handlers
        self.types = types
        self._lock = threading.Lock()

    def status(self):
        with self._lock:
            wire = self.handlers.on_status(None)
        chunks = decode_response_chunks(wire)
        self._check(chunks)
        return self.types.Status.deserialize(chunks[0][1])

    def beacon_blocks_by_range(self, start_slot: int, count: int) -> list:
        with self._lock:
            wire = self.handlers.on_beacon_blocks_by_range(start_slot, count)
        chunks = decode_response_chunks(wire)
        self._check(chunks)
        return [self.types.SignedBeaconBlock.deserialize(p) for _, p in chunks]

    def beacon_blocks_by_root(self, roots: list[bytes]) -> list:
        with self._lock:
            wire = self.handlers.on_beacon_blocks_by_root(roots)
        chunks = decode_response_chunks(wire)
        self._check(chunks)
        return [self.types.SignedBeaconBlock.deserialize(p) for _, p in chunks]

    @staticmethod
    def _check(chunks) -> None:
        for code, payload in chunks:
            if code != RespCode.SUCCESS:
                raise PeerError(f"{code.name}: {payload[:64]!r}")
