"""Backfill sync: repopulate history below a checkpoint anchor.

Reference: `sync/backfill/backfill.ts:106` + `verify.ts` — after
checkpoint (weak-subjectivity) sync, walk BACKWARD from the anchor to
genesis: batches are validated by hash-chain linkage (child.parent_root
== parent root) and proposer signatures verified in one batched dispatch
per segment (no state transition — the anchor state's registry provides
pubkeys since the registry is append-only).
"""

from __future__ import annotations

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import DOMAIN_BEACON_PROPOSER
from .peer import IPeer, PeerError

BACKFILL_BATCH_SLOTS = 64


class BackfillError(Exception):
    pass


class BackfillSync:
    def __init__(self, config, types, db, anchor_block, anchor_state, verifier):
        """`anchor_block`: trusted signed block (checkpoint); `anchor_state`
        its post state (pubkey registry); `verifier`: IBlsVerifier."""
        self.config = config
        self.types = types
        self.db = db
        self.verifier = verifier
        self.anchor = anchor_block
        self._pubkeys = [bytes(v.pubkey) for v in anchor_state.validators]
        self.peers: list[IPeer] = []
        self.oldest_root = anchor_block.message.hash_tree_root()
        self.oldest_slot = anchor_block.message.slot
        self._expected_parent = bytes(anchor_block.message.parent_root)

    def add_peer(self, peer: IPeer) -> None:
        self.peers.append(peer)

    # -- verification (reference backfill/verify.ts) -------------------------

    def _verify_segment(self, blocks: list) -> None:
        """Blocks ascending by slot, ending at the current backfill head:
        linkage + batched proposer signatures."""
        # hash-chain linkage up to the known oldest block
        expected = self._expected_parent
        for signed in reversed(blocks):
            root = signed.message.hash_tree_root()
            if root != expected:
                raise BackfillError(
                    f"linkage broken at slot {signed.message.slot}: "
                    f"{root.hex()[:12]} != {expected.hex()[:12]}"
                )
            expected = bytes(signed.message.parent_root)
        # batched proposer signature verification
        sets = []
        for signed in blocks:
            msg = signed.message
            if msg.proposer_index >= len(self._pubkeys):
                raise BackfillError("proposer index beyond anchor registry")
            domain = self.config.get_domain(DOMAIN_BEACON_PROPOSER, msg.slot)
            sets.append(
                bls.SignatureSet(
                    pubkey=bls.PublicKey.from_bytes(
                        self._pubkeys[msg.proposer_index], validate=False
                    ),
                    message=compute_signing_root(msg.hash_tree_root(), domain),
                    signature=bytes(signed.signature),
                )
            )
        if sets and not self.verifier.verify_signature_sets(sets):
            raise BackfillError("backfill segment signature verification failed")

    # -- driving -------------------------------------------------------------

    def sync_to_genesis(self) -> int:
        """Backfill until slot 0 is linked; returns number of archived
        blocks. Peers rotate on failure (reference: batch retries)."""
        archived = 0
        while self.oldest_slot > 0 and self._expected_parent != b"\x00" * 32:
            start = max(0, self.oldest_slot - BACKFILL_BATCH_SLOTS)
            count = self.oldest_slot - start
            blocks = self._download(start, count)
            if not blocks:
                raise BackfillError(f"no blocks available below {self.oldest_slot}")
            self._verify_segment(blocks)
            for signed in blocks:
                self.db.archive_block(signed)
                archived += 1
            self.oldest_slot = blocks[0].message.slot
            self.oldest_root = blocks[0].message.hash_tree_root()
            self._expected_parent = bytes(blocks[0].message.parent_root)
            if blocks[0].message.slot == 1 and self._expected_parent is not None:
                break  # genesis (slot-0 anchor) reached
        return archived

    def _download(self, start: int, count: int) -> list:
        last_err: Exception | None = None
        for peer in self.peers:
            try:
                blocks = peer.beacon_blocks_by_range(start, count)
                if blocks:
                    return blocks
            except PeerError as e:
                last_err = e
        if last_err is not None:
            raise BackfillError(str(last_err))
        return []
