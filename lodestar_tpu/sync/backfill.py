"""Backfill sync: repopulate history below a checkpoint anchor.

Reference: `sync/backfill/backfill.ts:106` + `verify.ts` — after
checkpoint (weak-subjectivity) sync, walk BACKWARD from the anchor to
genesis: batches are validated by hash-chain linkage (child.parent_root
== parent root) and proposer signatures verified in one batched dispatch
per segment (no state transition — the anchor state's registry provides
pubkeys since the registry is append-only).
"""

from __future__ import annotations

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import DOMAIN_BEACON_PROPOSER
from .peer import IPeer, PeerError

BACKFILL_BATCH_SLOTS = 64


class BackfillError(Exception):
    pass


class BackfillSync:
    def __init__(
        self, config, types, db, anchor_block, anchor_state, verifier,
        terminal_root: bytes | None = None, metrics=None,
    ):
        """`anchor_block`: trusted signed block (checkpoint); `anchor_state`
        its post state (pubkey registry); `verifier`: IBlsVerifier;
        `terminal_root`: the genesis block root — backfill is complete when
        the linkage reaches it (None: complete when the slot-1 window is
        exhausted)."""
        self.config = config
        self.types = types
        self.db = db
        self.verifier = verifier
        self.metrics = metrics
        self.anchor = anchor_block
        self.terminal_root = terminal_root
        self._pubkeys = [bytes(v.pubkey) for v in anchor_state.validators]
        self.peers: list[IPeer] = []
        self.oldest_slot = anchor_block.message.slot
        self._expected_parent = bytes(anchor_block.message.parent_root)

    def add_peer(self, peer: IPeer) -> None:
        self.peers.append(peer)

    # -- verification (reference backfill/verify.ts) -------------------------

    def _verify_segment(self, blocks: list) -> None:
        """Blocks ascending by slot, ending at the current backfill head:
        linkage + batched proposer signatures."""
        # hash-chain linkage up to the known oldest block
        expected = self._expected_parent
        for signed in reversed(blocks):
            root = signed.message.hash_tree_root()
            if root != expected:
                raise BackfillError(
                    f"linkage broken at slot {signed.message.slot}: "
                    f"{root.hex()[:12]} != {expected.hex()[:12]}"
                )
            expected = bytes(signed.message.parent_root)
        # batched proposer signature verification
        sets = []
        for signed in blocks:
            msg = signed.message
            if msg.proposer_index >= len(self._pubkeys):
                raise BackfillError("proposer index beyond anchor registry")
            domain = self.config.get_domain(DOMAIN_BEACON_PROPOSER, msg.slot)
            sets.append(
                bls.SignatureSet(
                    pubkey=bls.PublicKey.from_bytes(
                        self._pubkeys[msg.proposer_index], validate=False
                    ),
                    message=compute_signing_root(msg.hash_tree_root(), domain),
                    signature=bytes(signed.signature),
                )
            )
        if sets and not self.verifier.verify_signature_sets(sets):
            raise BackfillError("backfill segment signature verification failed")

    # -- driving -------------------------------------------------------------

    def sync_to_genesis(self) -> int:
        """Backfill until the linkage reaches the terminal (genesis) root,
        or the slot-1 window is exhausted; returns archived block count."""
        archived = 0
        while self.oldest_slot > 1 and self._expected_parent != self.terminal_root:
            start = max(1, self.oldest_slot - BACKFILL_BATCH_SLOTS)
            count = self.oldest_slot - start
            blocks = self._download_verified(start, count)
            m = getattr(self, "metrics", None)
            if m is not None:
                m.backfill_batches_total.inc(
                    outcome="verified" if blocks else "empty"
                )
                m.backfill_slot.set(self.oldest_slot)
            if not blocks:
                if start == 1:
                    break  # chain has no blocks below oldest_slot — done
                raise BackfillError(f"no blocks available below {self.oldest_slot}")
            for signed in blocks:
                self.db.archive_block(signed)
                archived += 1
            self.oldest_slot = blocks[0].message.slot
            self._expected_parent = bytes(blocks[0].message.parent_root)
        # an empty final window is only complete if the linkage actually
        # reached the terminal root — otherwise a peer served a lying empty
        # response over an unreachable hole
        if (
            self.terminal_root is not None
            and self._expected_parent != self.terminal_root
        ):
            raise BackfillError(
                f"backfill incomplete: linkage stopped at "
                f"{self._expected_parent.hex()[:12]}, terminal "
                f"{self.terminal_root.hex()[:12]} not reached"
            )
        return archived

    def _download_verified(self, start: int, count: int) -> list:
        """Download + verify one batch, rotating peers on EITHER transport
        failure or verification failure — one bad peer must not brick
        backfill while honest peers remain (reference: batch retries with
        peer rotation)."""
        transport_err: Exception | None = None
        verify_err: Exception | None = None
        served_empty = False
        for peer in self.peers:
            try:
                blocks = peer.beacon_blocks_by_range(start, count)
            except PeerError as e:
                transport_err = e
                continue
            if not blocks:
                served_empty = True
                continue
            try:
                self._verify_segment(blocks)
                return blocks
            except BackfillError as e:
                verify_err = e
        # a verification failure is stronger evidence than an empty reply:
        # some peer HAS blocks for this range, so don't accept emptiness
        if verify_err is not None:
            raise BackfillError(str(verify_err))
        if served_empty:
            return []  # every responsive peer confirms the range is empty
        if transport_err is not None:
            raise BackfillError(str(transport_err))
        return []
