"""Fork names and ordering.

Equivalent of /root/reference/packages/params/src/forkName.ts (`ForkName`,
`ForkSeq`): the ordered list of consensus forks this framework implements.
"""

from __future__ import annotations

from enum import IntEnum


class ForkSeq(IntEnum):
    """Fork sequence number — totally ordered, usable for `>=` gating."""

    phase0 = 0
    altair = 1
    bellatrix = 2
    capella = 3


class ForkName:
    phase0 = "phase0"
    altair = "altair"
    bellatrix = "bellatrix"
    capella = "capella"


FORK_ORDER: tuple[str, ...] = (
    ForkName.phase0,
    ForkName.altair,
    ForkName.bellatrix,
    ForkName.capella,
)

# Forks at/after which blocks carry an execution payload
EXECUTION_FORKS = frozenset({ForkName.bellatrix, ForkName.capella})
# Forks at/after which light-client (sync committee) data exists
LIGHT_CLIENT_FORKS = frozenset({ForkName.altair, ForkName.bellatrix, ForkName.capella})
# Forks with withdrawals
WITHDRAWAL_FORKS = frozenset({ForkName.capella})


def fork_seq(fork: str) -> ForkSeq:
    return ForkSeq[fork]


def highest_fork(forks: list[str]) -> str:
    return max(forks, key=lambda f: ForkSeq[f])
