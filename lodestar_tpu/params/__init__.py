"""Spec presets, constants, and fork names (layer L0).

Equivalent of the reference package `@lodestar/params`
(/root/reference/packages/params). The active preset defaults to ``mainnet``
and may be overridden by the ``LODESTAR_TPU_PRESET`` environment variable
(the reference uses ``LODESTAR_PRESET``: params/src/setPreset.ts) or by
calling :func:`set_active_preset` before any consensus objects are built.
"""

from __future__ import annotations

from ..utils.env import env_str
from .constants import *  # noqa: F401,F403
from .fork_name import EXECUTION_FORKS, FORK_ORDER, ForkName, ForkSeq, fork_seq  # noqa: F401
from .presets import MAINNET, MINIMAL, PRESETS, Preset  # noqa: F401

ACTIVE_PRESET: Preset = PRESETS.get(env_str("LODESTAR_TPU_PRESET"), MAINNET)


def set_active_preset(name_or_preset: str | Preset) -> Preset:
    """Override the process-default preset (call before building any state).

    Mirrors `setActivePreset` in the reference (params/src/setPreset.ts); unlike
    the reference we do not hard-fail on late calls because all consensus code
    receives its preset through the BeaconConfig object rather than via module
    globals — this only changes the *default*.
    """
    global ACTIVE_PRESET
    preset = PRESETS[name_or_preset] if isinstance(name_or_preset, str) else name_or_preset
    ACTIVE_PRESET = preset
    return preset
