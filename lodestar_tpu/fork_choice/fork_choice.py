"""ForkChoice: LMD-GHOST votes + FFG checkpoints over the proto-array.

Reference behavior: `fork-choice/src/forkChoice/forkChoice.ts` —
`onBlock` (:294), `onAttestation` (:505), `updateHead` (:184), queued
attestations for future epochs, equivocation (attester-slashing) handling,
checkpoint balances. Vote state here is three numpy arrays indexed by
validator (current root index, next root index, last-update epoch) so
`compute_deltas` is two bincounts over int arrays
(reference computeDeltas.ts walks a JS array per validator).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..params.constants import INTERVALS_PER_SLOT
from .proto_array import ProtoArray

NO_VOTE = -1


@dataclass
class ForkChoiceStore:
    """FFG bookkeeping (reference IForkChoiceStore, forkChoice/store.ts)."""

    current_slot: int
    justified_checkpoint: tuple[int, bytes]
    finalized_checkpoint: tuple[int, bytes]
    justified_balances: np.ndarray  # effective balances at justified state
    # better justified checkpoint held back by the bouncing-attack guard
    # (adopted at the next epoch boundary — forkChoice.ts onTick)
    best_justified: tuple[int, bytes] | None = None
    best_justified_balances: np.ndarray | None = None
    # best checkpoints any imported block WOULD reach if its epoch ended
    # now (forkChoice.ts fcStore.unrealizedJustified)
    unrealized_justified: tuple[int, bytes] | None = None
    unrealized_justified_balances: np.ndarray | None = None
    unrealized_finalized: tuple[int, bytes] | None = None


class ForkChoiceError(ValueError):
    pass


class ForkChoice:
    def __init__(
        self,
        store: ForkChoiceStore,
        proto_array: ProtoArray,
        slots_per_epoch: int,
        seconds_per_slot: int = 12,
        proposer_score_boost: int = 40,
        safe_slots_to_update_justified: int = 8,
        proposer_boost_enabled: bool = True,
        justified_balances_getter=None,
    ):
        self.store = store
        self.proto = proto_array
        # resolves (epoch, root) -> effective balances of THAT checkpoint's
        # state (reference justifiedBalancesGetter, forkChoice.ts:129);
        # without it adoption falls back to whatever balances the importing
        # block carried — close, but wrong across a large balance churn
        self.justified_balances_getter = justified_balances_getter
        self.slots_per_epoch = slots_per_epoch
        self.proto.slots_per_epoch = slots_per_epoch
        self.proto.current_slot = store.current_slot
        self.seconds_per_slot = seconds_per_slot
        self.proposer_score_boost = proposer_score_boost
        self.safe_slots_to_update_justified = safe_slots_to_update_justified
        self.proposer_boost_enabled = proposer_boost_enabled
        # timely-block boost (reference forkChoice.ts:93-95): root boosted
        # this slot, and the cached score at the current justified balances
        self.proposer_boost_root: bytes | None = None
        self._justified_proposer_boost_score: int | None = None
        n = len(store.justified_balances)
        # votes: per-validator (current message root idx, next message root
        # idx into proto.indices-space roots, target epoch of next message)
        self._vote_current = {}
        self._vote_next: dict[int, bytes] = {}
        self._vote_current_root: dict[int, bytes] = {}
        self._vote_next_epoch: dict[int, int] = {}
        self._equivocating: set[int] = set()
        self._queued_attestations: list[tuple[int, list[int], bytes, int]] = []
        self._balances_used = store.justified_balances.copy()
        self.head_root: bytes | None = None

    # -- time ----------------------------------------------------------------

    def update_time(self, current_slot: int) -> None:
        if current_slot - self.store.current_slot > 2 * self.slots_per_epoch:
            # far-future jump (node way behind wall clock): stepping every
            # slot would grind millions of iterations — land directly and
            # drain the queues/boost once
            self.store.current_slot = current_slot
            self.proposer_boost_root = None
            self._on_epoch_boundary()
            self._process_queued_attestations()
            return
        while self.store.current_slot < current_slot:
            self.store.current_slot += 1
            # a new slot always clears the previous slot's proposer boost
            # (reference onTick :1168-1171)
            self.proposer_boost_root = None
            if self.store.current_slot % self.slots_per_epoch == 0:
                self._on_epoch_boundary()
                self._process_queued_attestations()

    def _on_epoch_boundary(self) -> None:
        """Adopt held-back and unrealized checkpoints (reference onTick
        :1178-1201): best_justified first (bouncing-attack guard release),
        then any better unrealized justification/finalization."""
        s = self.store
        if (
            s.best_justified is not None
            and s.best_justified[0] > s.justified_checkpoint[0]
            and self._is_descendant_of_finalized(s.best_justified[1])
        ):
            s.justified_checkpoint = s.best_justified
            bal = self._resolve_justified_balances(
                s.best_justified, s.best_justified_balances
            )
            if bal is not None:
                s.justified_balances = bal
            self._justified_proposer_boost_score = None
        if s.unrealized_justified is not None and self._is_descendant_of_finalized(
            s.unrealized_justified[1]
        ):
            # same conflicting-fork guard as best_justified: the unrealized
            # max may come from a fork finalization has since orphaned
            self._update_checkpoints(
                s.unrealized_justified,
                s.unrealized_finalized,
                s.unrealized_justified_balances,
                state_slot=None,  # epoch boundary: adopt unconditionally
            )

    def _resolve_justified_balances(self, checkpoint, fallback):
        """Balances for the checkpoint's own state when the chain can
        provide them (checkpoint-state cache), else the caller's fallback."""
        if self.justified_balances_getter is not None:
            bal = self.justified_balances_getter(checkpoint)
            if bal is not None:
                return bal
        return fallback

    def _is_descendant_of_finalized(self, root: bytes) -> bool:
        fin_epoch, fin_root = self.store.finalized_checkpoint
        if fin_epoch == 0:
            return True
        fin_slot = fin_epoch * self.slots_per_epoch
        return self.proto.get_ancestor_at_slot(root, fin_slot) == fin_root

    def _current_epoch(self) -> int:
        return self.store.current_slot // self.slots_per_epoch

    # -- block import --------------------------------------------------------

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: bytes,
        state_root: bytes,
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes],
        justified_balances: np.ndarray | None = None,
        execution_status: str = "pre_merge",
        unrealized_justified_checkpoint: tuple[int, bytes] | None = None,
        unrealized_finalized_checkpoint: tuple[int, bytes] | None = None,
        block_delay_sec: float | None = None,
    ) -> None:
        """Register an imported block (caller has fully verified it —
        reference onBlock precondition).

        `block_delay_sec` (arrival time minus slot start) drives the
        proposer boost: a block for the CURRENT slot arriving before the
        attesting interval (1/3 slot) gets the boost (reference
        forkChoice.ts:362-369)."""
        if parent_root not in self.proto.indices and len(self.proto.nodes) > 0:
            raise ForkChoiceError("unknown parent")

        if (
            self.proposer_boost_enabled
            and block_delay_sec is not None
            # non-negative: a block broadcast AHEAD of its slot (clock
            # disparity, or current_slot forced forward by the import
            # path) must not collect a free boost
            and 0 <= block_delay_sec < self.seconds_per_slot / INTERVALS_PER_SLOT
            and self.store.current_slot == slot
        ):
            self.proposer_boost_root = root

        self._update_checkpoints(
            justified_checkpoint,
            finalized_checkpoint,
            justified_balances,
            state_slot=slot,
        )

        uj = unrealized_justified_checkpoint or justified_checkpoint
        uf = unrealized_finalized_checkpoint or finalized_checkpoint
        s = self.store
        if s.unrealized_justified is None or uj[0] > s.unrealized_justified[0]:
            s.unrealized_justified = uj
            s.unrealized_justified_balances = (
                justified_balances
                if justified_balances is not None
                else s.justified_balances
            )
        if s.unrealized_finalized is None or uf[0] > s.unrealized_finalized[0]:
            s.unrealized_finalized = uf
        # a block from a PAST epoch pulls its unrealized checkpoints up
        # right away (reference forkChoice.ts:445-453)
        if slot // self.slots_per_epoch < self._current_epoch():
            self._update_checkpoints(uj, uf, justified_balances, state_slot=slot)

        self.proto.on_block(
            slot,
            root,
            parent_root if len(self.proto.nodes) > 0 else None,
            state_root,
            justified_checkpoint[0],
            finalized_checkpoint[0],
            execution_status,
            unrealized_justified_epoch=uj[0],
            unrealized_finalized_epoch=uf[0],
        )

    def _update_checkpoints(
        self,
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes] | None,
        justified_balances: np.ndarray | None,
        state_slot: int | None,
    ) -> None:
        """Reference updateCheckpoints (forkChoice.ts:916+): a better
        justified checkpoint is adopted immediately only early in the
        epoch (bouncing-attack prevention, SAFE_SLOTS_TO_UPDATE_JUSTIFIED)
        — otherwise held in best_justified for the next epoch boundary.
        Finalization always advances, and forces the justified update."""
        s = self.store
        if justified_checkpoint[0] > s.justified_checkpoint[0]:
            if (
                s.best_justified is None
                or justified_checkpoint[0] > s.best_justified[0]
            ):
                s.best_justified = justified_checkpoint
                s.best_justified_balances = justified_balances
            in_safe_window = (
                state_slot is None
                or s.current_slot % self.slots_per_epoch
                < self.safe_slots_to_update_justified
            )
            if in_safe_window:
                s.justified_checkpoint = justified_checkpoint
                bal = self._resolve_justified_balances(
                    justified_checkpoint, justified_balances
                )
                if bal is not None:
                    s.justified_balances = bal
                self._justified_proposer_boost_score = None
        if (
            finalized_checkpoint is not None
            and finalized_checkpoint[0] > s.finalized_checkpoint[0]
        ):
            s.finalized_checkpoint = finalized_checkpoint
            if justified_checkpoint[0] > s.justified_checkpoint[0]:
                s.justified_checkpoint = justified_checkpoint
                bal = self._resolve_justified_balances(
                    justified_checkpoint, justified_balances
                )
                if bal is not None:
                    s.justified_balances = bal
                self._justified_proposer_boost_score = None

    # -- attestations --------------------------------------------------------

    def on_attestation(
        self,
        validator_indices: list[int],
        block_root: bytes,
        target_epoch: int,
    ) -> None:
        """Record LMD votes (caller validated the attestation). Future-epoch
        attestations queue until their epoch (reference queues by slot)."""
        if target_epoch > self._current_epoch():
            self._queued_attestations.append(
                (target_epoch, list(validator_indices), block_root, target_epoch)
            )
            return
        if block_root not in self.proto.indices:
            raise ForkChoiceError("attestation for unknown block")
        for v in validator_indices:
            if v in self._equivocating:
                continue
            prev_epoch = self._vote_next_epoch.get(v, -1)
            if target_epoch > prev_epoch:
                self._vote_next[v] = block_root
                self._vote_next_epoch[v] = target_epoch

    def on_attester_slashing(self, validator_indices: list[int]) -> None:
        """Equivocating validators stop counting (reference
        forkChoice.onAttesterSlashing)."""
        self._equivocating.update(validator_indices)

    def _process_queued_attestations(self) -> None:
        epoch = self._current_epoch()
        still: list = []
        for item in self._queued_attestations:
            if item[0] <= epoch:
                try:
                    self.on_attestation(item[1], item[2], item[3])
                except ForkChoiceError:
                    pass
            else:
                still.append(item)
        self._queued_attestations = still

    # -- head ----------------------------------------------------------------

    def _compute_deltas(self) -> np.ndarray:
        """Vectorized computeDeltas: subtract old-vote weight, add new-vote
        weight, per node — two bincounts over node indices."""
        n_nodes = len(self.proto.nodes)
        deltas = np.zeros(n_nodes, np.int64)
        old_bal = self._balances_used
        new_bal = self.store.justified_balances

        sub_idx, sub_w, add_idx, add_w = [], [], [], []
        for v, next_root in list(self._vote_next.items()):
            equiv = v in self._equivocating
            cur_root = self._vote_current_root.get(v)
            if cur_root is not None and cur_root in self.proto.indices:
                w = int(old_bal[v]) if v < len(old_bal) else 0
                sub_idx.append(self.proto.indices[cur_root])
                sub_w.append(w)
            if not equiv and next_root in self.proto.indices:
                w = int(new_bal[v]) if v < len(new_bal) else 0
                add_idx.append(self.proto.indices[next_root])
                add_w.append(w)
                self._vote_current_root[v] = next_root
            elif equiv:
                self._vote_current_root.pop(v, None)
                self._vote_next.pop(v, None)
        if sub_idx:
            deltas -= np.bincount(
                np.asarray(sub_idx), weights=np.asarray(sub_w), minlength=n_nodes
            ).astype(np.int64)
        if add_idx:
            deltas += np.bincount(
                np.asarray(add_idx), weights=np.asarray(add_w), minlength=n_nodes
            ).astype(np.int64)
        self._balances_used = new_bal.copy()
        return deltas

    def _compute_proposer_boost_score(self) -> int:
        """Boost = committee-weight-per-slot × PROPOSER_SCORE_BOOST%
        (reference computeProposerBoostScore, forkChoice.ts:1251-1263),
        cached until the justified balances change."""
        if self._justified_proposer_boost_score is None:
            bal = self.store.justified_balances
            total = int(bal[bal > 0].sum())
            committee_weight = total // self.slots_per_epoch
            self._justified_proposer_boost_score = (
                committee_weight * self.proposer_score_boost
            ) // 100
        return self._justified_proposer_boost_score

    def update_head(self) -> bytes:
        """Apply pending vote deltas, refresh scores, walk to head
        (reference updateHead :184)."""
        deltas = self._compute_deltas()
        boost = None
        if self.proposer_boost_enabled and self.proposer_boost_root is not None:
            boost = (self.proposer_boost_root, self._compute_proposer_boost_score())
        self.proto.apply_score_changes(
            deltas,
            self.store.justified_checkpoint[0],
            self.store.finalized_checkpoint[0],
            proposer_boost=boost,
            current_slot=self.store.current_slot,
        )
        self.head_root = self.proto.find_head(self.store.justified_checkpoint[1])
        return self.head_root

    def get_proposer_boost_root(self) -> bytes | None:
        return self.proposer_boost_root

    # -- queries -------------------------------------------------------------

    def get_ancestor(self, root: bytes, slot: int) -> bytes | None:
        return self.proto.get_ancestor_at_slot(root, slot)

    def has_block(self, root: bytes) -> bool:
        return root in self.proto.indices

    def prune(self) -> None:
        self.proto.maybe_prune(self.store.finalized_checkpoint[1])
