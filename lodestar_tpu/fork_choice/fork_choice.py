"""ForkChoice: LMD-GHOST votes + FFG checkpoints over the proto-array.

Reference behavior: `fork-choice/src/forkChoice/forkChoice.ts` —
`onBlock` (:294), `onAttestation` (:505), `updateHead` (:184), queued
attestations for future epochs, equivocation (attester-slashing) handling,
checkpoint balances. Vote state here is three numpy arrays indexed by
validator (current root index, next root index, last-update epoch) so
`compute_deltas` is two bincounts over int arrays
(reference computeDeltas.ts walks a JS array per validator).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .proto_array import ProtoArray, ProtoArrayError

NO_VOTE = -1


@dataclass
class ForkChoiceStore:
    """FFG bookkeeping (reference IForkChoiceStore, forkChoice/store.ts)."""

    current_slot: int
    justified_checkpoint: tuple[int, bytes]
    finalized_checkpoint: tuple[int, bytes]
    justified_balances: np.ndarray  # effective balances at justified state
    best_justified: tuple[int, bytes] | None = None
    unrealized_justified: tuple[int, bytes] | None = None


class ForkChoiceError(ValueError):
    pass


class ForkChoice:
    def __init__(
        self,
        store: ForkChoiceStore,
        proto_array: ProtoArray,
        slots_per_epoch: int,
    ):
        self.store = store
        self.proto = proto_array
        self.slots_per_epoch = slots_per_epoch
        n = len(store.justified_balances)
        # votes: per-validator (current message root idx, next message root
        # idx into proto.indices-space roots, target epoch of next message)
        self._vote_current = {}
        self._vote_next: dict[int, bytes] = {}
        self._vote_current_root: dict[int, bytes] = {}
        self._vote_next_epoch: dict[int, int] = {}
        self._equivocating: set[int] = set()
        self._queued_attestations: list[tuple[int, list[int], bytes, int]] = []
        self._balances_used = store.justified_balances.copy()
        self.head_root: bytes | None = None

    # -- time ----------------------------------------------------------------

    def update_time(self, current_slot: int) -> None:
        if current_slot - self.store.current_slot > 2 * self.slots_per_epoch:
            # far-future jump (node way behind wall clock): stepping every
            # slot would grind millions of iterations — land directly and
            # drain the attestation queue once
            self.store.current_slot = current_slot
            self._process_queued_attestations()
            return
        while self.store.current_slot < current_slot:
            self.store.current_slot += 1
            if self.store.current_slot % self.slots_per_epoch == 0:
                self._process_queued_attestations()

    def _current_epoch(self) -> int:
        return self.store.current_slot // self.slots_per_epoch

    # -- block import --------------------------------------------------------

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: bytes,
        state_root: bytes,
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes],
        justified_balances: np.ndarray | None = None,
        execution_status: str = "pre_merge",
    ) -> None:
        """Register an imported block (caller has fully verified it —
        reference onBlock precondition)."""
        if parent_root not in self.proto.indices and len(self.proto.nodes) > 0:
            raise ForkChoiceError("unknown parent")
        if justified_checkpoint[0] > self.store.justified_checkpoint[0]:
            self.store.justified_checkpoint = justified_checkpoint
            if justified_balances is not None:
                self.store.justified_balances = justified_balances
        if finalized_checkpoint[0] > self.store.finalized_checkpoint[0]:
            self.store.finalized_checkpoint = finalized_checkpoint
        self.proto.on_block(
            slot,
            root,
            parent_root if len(self.proto.nodes) > 0 else None,
            state_root,
            justified_checkpoint[0],
            finalized_checkpoint[0],
            execution_status,
        )

    # -- attestations --------------------------------------------------------

    def on_attestation(
        self,
        validator_indices: list[int],
        block_root: bytes,
        target_epoch: int,
    ) -> None:
        """Record LMD votes (caller validated the attestation). Future-epoch
        attestations queue until their epoch (reference queues by slot)."""
        if target_epoch > self._current_epoch():
            self._queued_attestations.append(
                (target_epoch, list(validator_indices), block_root, target_epoch)
            )
            return
        if block_root not in self.proto.indices:
            raise ForkChoiceError("attestation for unknown block")
        for v in validator_indices:
            if v in self._equivocating:
                continue
            prev_epoch = self._vote_next_epoch.get(v, -1)
            if target_epoch > prev_epoch:
                self._vote_next[v] = block_root
                self._vote_next_epoch[v] = target_epoch

    def on_attester_slashing(self, validator_indices: list[int]) -> None:
        """Equivocating validators stop counting (reference
        forkChoice.onAttesterSlashing)."""
        self._equivocating.update(validator_indices)

    def _process_queued_attestations(self) -> None:
        epoch = self._current_epoch()
        still: list = []
        for item in self._queued_attestations:
            if item[0] <= epoch:
                try:
                    self.on_attestation(item[1], item[2], item[3])
                except ForkChoiceError:
                    pass
            else:
                still.append(item)
        self._queued_attestations = still

    # -- head ----------------------------------------------------------------

    def _compute_deltas(self) -> np.ndarray:
        """Vectorized computeDeltas: subtract old-vote weight, add new-vote
        weight, per node — two bincounts over node indices."""
        n_nodes = len(self.proto.nodes)
        deltas = np.zeros(n_nodes, np.int64)
        old_bal = self._balances_used
        new_bal = self.store.justified_balances

        sub_idx, sub_w, add_idx, add_w = [], [], [], []
        for v, next_root in list(self._vote_next.items()):
            equiv = v in self._equivocating
            cur_root = self._vote_current_root.get(v)
            if cur_root is not None and cur_root in self.proto.indices:
                w = int(old_bal[v]) if v < len(old_bal) else 0
                sub_idx.append(self.proto.indices[cur_root])
                sub_w.append(w)
            if not equiv and next_root in self.proto.indices:
                w = int(new_bal[v]) if v < len(new_bal) else 0
                add_idx.append(self.proto.indices[next_root])
                add_w.append(w)
                self._vote_current_root[v] = next_root
            elif equiv:
                self._vote_current_root.pop(v, None)
                self._vote_next.pop(v, None)
        if sub_idx:
            deltas -= np.bincount(
                np.asarray(sub_idx), weights=np.asarray(sub_w), minlength=n_nodes
            ).astype(np.int64)
        if add_idx:
            deltas += np.bincount(
                np.asarray(add_idx), weights=np.asarray(add_w), minlength=n_nodes
            ).astype(np.int64)
        self._balances_used = new_bal.copy()
        return deltas

    def update_head(self) -> bytes:
        """Apply pending vote deltas, refresh scores, walk to head
        (reference updateHead :184)."""
        deltas = self._compute_deltas()
        self.proto.apply_score_changes(
            deltas,
            self.store.justified_checkpoint[0],
            self.store.finalized_checkpoint[0],
        )
        self.head_root = self.proto.find_head(self.store.justified_checkpoint[1])
        return self.head_root

    # -- queries -------------------------------------------------------------

    def get_ancestor(self, root: bytes, slot: int) -> bytes | None:
        return self.proto.get_ancestor_at_slot(root, slot)

    def has_block(self, root: bytes) -> bool:
        return root in self.proto.indices

    def prune(self) -> None:
        self.proto.maybe_prune(self.store.finalized_checkpoint[1])
