"""LMD-GHOST + Casper FFG fork choice (SURVEY.md §2 `fork-choice`).

Reference: `packages/fork-choice` — `ProtoArray` (protoArray.ts),
`computeDeltas` (computeDeltas.ts), `ForkChoice` (forkChoice.ts). Here the
vote/delta bookkeeping is flat numpy arrays (validator-indexed), so the
per-epoch delta computation is two `bincount`s instead of a JS loop.
"""

from .proto_array import ProtoArray, ProtoNode  # noqa: F401
from .fork_choice import ForkChoice, ForkChoiceStore  # noqa: F401
