"""Proto-array: the flat DAG behind LMD-GHOST head selection.

Reference behavior: `fork-choice/src/protoArray/protoArray.ts` —
append-only node list in insertion (topological) order; weights updated by
a single backward pass (`applyScoreChanges` :91), head found by walking
best-descendant links (`findHead` :455). Re-derived from the original
proto_array design; this implementation keeps weights/deltas in numpy
int64 arrays so score application is array math plus one sequential
parent-accumulation pass.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class ProtoNode:
    slot: int
    root: bytes
    parent: int | None          # index into nodes
    state_root: bytes
    justified_epoch: int
    finalized_epoch: int
    # what the checkpoints would be if the block's epoch ended at import
    # time (reference protoArray/interface.ts:71-74); used by the
    # viability filter for blocks from prior epochs
    unrealized_justified_epoch: int = 0
    unrealized_finalized_epoch: int = 0
    # execution status is tracked for bellatrix+ (optimistic sync);
    # "valid" for pre-merge blocks
    execution_status: str = "pre_merge"  # pre_merge | valid | syncing | invalid
    best_child: int | None = None
    best_descendant: int | None = None


class ProtoArrayError(ValueError):
    pass


class ProtoArray:
    def __init__(
        self,
        justified_epoch: int,
        finalized_epoch: int,
        slots_per_epoch: int = 32,
    ):
        self.nodes: list[ProtoNode] = []
        self.indices: dict[bytes, int] = {}
        self.weights = np.zeros(0, np.int64)
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.slots_per_epoch = slots_per_epoch
        self.current_slot = 0  # refreshed by apply_score_changes
        # boost applied in the previous score pass, to back out before
        # applying this pass's boost (reference previousProposerBoost)
        self.previous_proposer_boost: tuple[bytes, int] | None = None
        self.prune_threshold = 256

    # -- insertion -----------------------------------------------------------

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: bytes | None,
        state_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
        execution_status: str = "pre_merge",
        unrealized_justified_epoch: int | None = None,
        unrealized_finalized_epoch: int | None = None,
    ) -> None:
        if root in self.indices:
            return
        parent = self.indices.get(parent_root) if parent_root is not None else None
        node_idx = len(self.nodes)
        self.nodes.append(
            ProtoNode(
                slot=slot,
                root=root,
                parent=parent,
                state_root=state_root,
                justified_epoch=justified_epoch,
                finalized_epoch=finalized_epoch,
                unrealized_justified_epoch=(
                    unrealized_justified_epoch
                    if unrealized_justified_epoch is not None
                    else justified_epoch
                ),
                unrealized_finalized_epoch=(
                    unrealized_finalized_epoch
                    if unrealized_finalized_epoch is not None
                    else finalized_epoch
                ),
                execution_status=execution_status,
            )
        )
        self.indices[root] = node_idx
        self.weights = np.append(self.weights, np.int64(0))
        if parent is not None:
            self._maybe_update_best_child_and_descendant(parent, node_idx)

    # -- scoring -------------------------------------------------------------

    def apply_score_changes(
        self,
        deltas: np.ndarray,
        justified_epoch: int,
        finalized_epoch: int,
        proposer_boost: tuple[bytes, int] | None = None,
        current_slot: int | None = None,
    ) -> None:
        """deltas: (len(nodes),) int64 — per-node vote weight change.
        proposer_boost: (block_root, score) for this pass — the previous
        pass's boost is backed out automatically (reference
        protoArray.ts:145-148 currentBoost/previousBoost).

        TWO backward passes, as in the reference (protoArray.ts
        applyScoreChanges): first apply every weight and back-propagate
        child deltas to parents; only then refresh best-child/descendant
        links — sibling comparisons must see a fully coherent weight set,
        or a best child losing weight keeps its crown against an
        already-visited heavier sibling."""
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("delta/node length mismatch")
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        if current_slot is not None:
            self.current_slot = current_slot

        deltas = deltas.astype(np.int64).copy()
        # fold boosts into the deltas up front (one dict lookup each, not a
        # root comparison per node); the invalid-node override below still
        # discards them on an invalidated node
        if proposer_boost is not None:
            idx = self.indices.get(proposer_boost[0])
            if idx is not None:
                deltas[idx] += proposer_boost[1]
        if self.previous_proposer_boost is not None:
            idx = self.indices.get(self.previous_proposer_boost[0])
            if idx is not None:
                deltas[idx] -= self.previous_proposer_boost[1]
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.execution_status == "invalid":
                deltas[i] = -int(self.weights[i])
            self.weights[i] += deltas[i]
            if self.weights[i] < 0:
                raise ProtoArrayError(f"negative node weight at {i}")
            if node.parent is not None:
                deltas[node.parent] += deltas[i]
        for i in range(len(self.nodes) - 1, -1, -1):
            parent = self.nodes[i].parent
            if parent is not None:
                self._maybe_update_best_child_and_descendant(parent, i)
        self.previous_proposer_boost = proposer_boost

    # -- head selection ------------------------------------------------------

    def find_head(self, justified_root: bytes) -> bytes:
        idx = self.indices.get(justified_root)
        if idx is None:
            raise ProtoArrayError("justified root unknown to proto array")
        node = self.nodes[idx]
        best = node.best_descendant if node.best_descendant is not None else idx
        head = self.nodes[best]
        if not self._node_is_viable_for_head(head):
            raise ProtoArrayError("best descendant not viable for head")
        return head.root

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """filter_block_tree equivalent (reference protoArray.ts:733-763):
        blocks from a PREVIOUS epoch are judged on their unrealized
        checkpoints — a tip that would justify the store's checkpoint if
        its epoch ended now must stay viable, or every late-epoch fork
        tip gets filtered and head selection can dead-end."""
        if node.execution_status == "invalid":
            return False
        current_epoch = self.current_slot // self.slots_per_epoch
        from_prev_epoch = node.slot // self.slots_per_epoch < current_epoch
        j = node.unrealized_justified_epoch if from_prev_epoch else node.justified_epoch
        f = node.unrealized_finalized_epoch if from_prev_epoch else node.finalized_epoch
        return (j == self.justified_epoch or self.justified_epoch == 0) and (
            f == self.finalized_epoch or self.finalized_epoch == 0
        )

    def _node_leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(self.nodes[node.best_descendant])
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child_and_descendant(self, parent_idx: int, child_idx: int):
        child = self.nodes[child_idx]
        parent = self.nodes[parent_idx]
        child_leads = self._node_leads_to_viable_head(child)
        child_best = (
            child.best_descendant if child.best_descendant is not None else child_idx
        )

        if parent.best_child == child_idx:
            if not child_leads:
                self._change_to_none(parent_idx)
            else:
                parent.best_descendant = child_best
        elif child_leads:
            if parent.best_child is None:
                parent.best_child = child_idx
                parent.best_descendant = child_best
            else:
                current_best = self.nodes[parent.best_child]
                current_leads = self._node_leads_to_viable_head(current_best)
                cb_idx = (
                    current_best.best_descendant
                    if current_best.best_descendant is not None
                    else parent.best_child
                )
                if not current_leads:
                    parent.best_child = child_idx
                    parent.best_descendant = child_best
                else:
                    cw = self.weights[child_idx]
                    bw = self.weights[parent.best_child]
                    # tie-break on root bytes (deterministic, matches the
                    # ≥ semantics: later-inserted equal-weight wins via >=)
                    if cw > bw or (
                        cw == bw and child.root >= current_best.root
                    ):
                        parent.best_child = child_idx
                        parent.best_descendant = child_best

    def _change_to_none(self, parent_idx: int) -> None:
        self.nodes[parent_idx].best_child = None
        self.nodes[parent_idx].best_descendant = None

    # -- queries -------------------------------------------------------------

    def __contains__(self, root: bytes) -> bool:
        return root in self.indices

    def get_node(self, root: bytes) -> ProtoNode | None:
        idx = self.indices.get(root)
        return self.nodes[idx] if idx is not None else None

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        a = self.indices.get(ancestor_root)
        d = self.indices.get(descendant_root)
        if a is None or d is None:
            return False
        a_slot = self.nodes[a].slot
        idx: int | None = d
        while idx is not None and self.nodes[idx].slot >= a_slot:
            if idx == a:
                return True
            idx = self.nodes[idx].parent
        return False

    def get_ancestor_at_slot(self, root: bytes, slot: int) -> bytes | None:
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            if node.slot <= slot:
                return node.root
            idx = node.parent
        return None

    def iter_ancestors(self, root: bytes):
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            yield node
            idx = node.parent

    # -- pruning -------------------------------------------------------------

    def maybe_prune(self, finalized_root: bytes) -> list[ProtoNode]:
        """Drop everything before the finalized node (reference maybePrune:
        only when the prefix exceeds pruneThreshold, to amortize)."""
        fin_idx = self.indices.get(finalized_root)
        if fin_idx is None:
            raise ProtoArrayError("finalized root unknown")
        if fin_idx < self.prune_threshold:
            return []
        removed = self.nodes[:fin_idx]
        self.nodes = self.nodes[fin_idx:]
        self.weights = self.weights[fin_idx:].copy()
        for node in removed:
            del self.indices[node.root]
        for root in list(self.indices):
            self.indices[root] -= fin_idx
        for node in self.nodes:
            node.parent = (
                node.parent - fin_idx
                if node.parent is not None and node.parent >= fin_idx
                else None
            )
            node.best_child = (
                node.best_child - fin_idx
                if node.best_child is not None and node.best_child >= fin_idx
                else None
            )
            node.best_descendant = (
                node.best_descendant - fin_idx
                if node.best_descendant is not None and node.best_descendant >= fin_idx
                else None
            )
        return removed


    # -- optimistic sync (execution status transitions) ----------------------

    def set_execution_valid(self, root: bytes) -> None:
        """VALID from the EL: this block and every SYNCING ancestor payload
        is valid (reference protoArray validateLatestHash upward walk).
        Never resurrects an 'invalid' node — a contradictory EL signal is
        ignored rather than re-enabling an EL-rejected branch."""
        idx = self.indices.get(root)
        if idx is None:
            return
        node = self.nodes[idx]
        if node.execution_status == "invalid":
            return
        if node.execution_status == "syncing":
            node.execution_status = "valid"
        # ancestors: an EL-valid payload transitively validates every
        # optimistically imported (syncing) ancestor payload
        idx = node.parent
        while idx is not None and self.nodes[idx].execution_status == "syncing":
            self.nodes[idx].execution_status = "valid"
            idx = self.nodes[idx].parent

    def invalidate_payloads(self, head_root: bytes, latest_valid_root: bytes | None) -> list[bytes]:
        """INVALID from the EL with a latest-valid-hash: every block from
        `head_root` back to (exclusive) `latest_valid_root` is invalid,
        and every DESCENDANT of an invalidated block is too (reference
        protoArray invalidation walk for engine INVALID + LVH;
        round-1 VERDICT: 'no LVH invalidation path').

        Returns the invalidated roots. Weights are corrected on the next
        apply_score_changes pass (the invalid override zeroes them)."""
        start = self.indices.get(head_root)
        if start is None:
            return []
        if latest_valid_root is not None:
            # a stale/faulty EL can report an LVH that is NOT on the head's
            # ancestor path; walking until we "hit" it would invalidate the
            # whole optimistic chain back to the last validated block. Verify
            # ancestry first — off-path LVH degrades to the no-LVH behavior
            # (invalidate only the offending payload). (round-2 advisor)
            idx: int | None = start
            on_path = False
            while idx is not None:
                node = self.nodes[idx]
                if node.root == latest_valid_root:
                    on_path = True
                    break
                if node.execution_status in ("pre_merge", "valid"):
                    break
                idx = node.parent
            if not on_path:
                latest_valid_root = None
        bad: set[int] = set()
        idx = start
        while idx is not None:
            node = self.nodes[idx]
            if latest_valid_root is not None and node.root == latest_valid_root:
                break
            if node.execution_status in ("pre_merge", "valid"):
                # never cross an EL-validated (or pre-merge) block: an
                # LVH that is off this ancestor path, stale, or malicious
                # must not invalidate the whole chain (round-2 review)
                break
            bad.add(idx)
            node.execution_status = "invalid"
            if latest_valid_root is None:
                break  # no LVH: only the head payload is known-bad
            idx = node.parent
        # descendants of any invalidated node are unreachable-valid
        for i, node in enumerate(self.nodes):
            if node.parent in bad and i not in bad:
                bad.add(i)
                node.execution_status = "invalid"
        # drop best-child links that point into the invalid set
        for node in self.nodes:
            if node.best_child in bad:
                node.best_child = None
            if node.best_descendant in bad:
                node.best_descendant = None
        return [self.nodes[i].root for i in sorted(bad)]
