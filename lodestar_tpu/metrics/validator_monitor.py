"""ValidatorMonitor: per-validator duty tracking for operators.

Reference: `metrics/validatorMonitor.ts` (478 LoC) — registered validator
indices get per-epoch summaries (attestation included/missed, inclusion
distance, head/target correctness, blocks proposed) surfaced as metrics
and epoch-end log lines.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class EpochSummary:
    attestation_included: bool = False
    inclusion_distance: int = 0
    target_correct: bool = False
    head_correct: bool = False
    blocks_proposed: int = 0
    sync_signatures: int = 0


class ValidatorMonitor:
    def __init__(self, registry=None):
        self._monitored: set[int] = set()
        self._summaries: dict[tuple[int, int], EpochSummary] = {}
        self._metrics = None
        if registry is not None:
            self._metrics = {
                "included": registry.counter(
                    "validator_monitor_attestation_included_total",
                    "attestations included for monitored validators",
                    label_names=("index",),
                ),
                "missed": registry.counter(
                    "validator_monitor_attestation_missed_total",
                    "attestations missed for monitored validators",
                    label_names=("index",),
                ),
                "proposed": registry.counter(
                    "validator_monitor_blocks_proposed_total",
                    "blocks proposed by monitored validators",
                    label_names=("index",),
                ),
            }

    def register_validator(self, index: int) -> None:
        self._monitored.add(index)

    @property
    def monitored(self) -> set[int]:
        return set(self._monitored)

    def _summary(self, index: int, epoch: int) -> EpochSummary:
        return self._summaries.setdefault((index, epoch), EpochSummary())

    # -- event hooks (called by the import pipeline) -------------------------

    def on_attestation_included(
        self, epoch: int, indices, inclusion_distance: int,
        target_correct: bool, head_correct: bool,
    ) -> None:
        for idx in indices:
            if idx in self._monitored:
                s = self._summary(idx, epoch)
                # keep the BEST observation across re-inclusions (minimum
                # distance, OR-ed correctness) — a later aggregate must not
                # degrade the report
                if s.attestation_included:
                    s.inclusion_distance = min(s.inclusion_distance, inclusion_distance)
                else:
                    s.attestation_included = True
                    s.inclusion_distance = inclusion_distance
                    if self._metrics:
                        self._metrics["included"].inc(index=str(idx))
                s.target_correct = s.target_correct or target_correct
                s.head_correct = s.head_correct or head_correct

    def on_block_proposed(self, epoch: int, proposer_index: int) -> None:
        if proposer_index in self._monitored:
            self._summary(proposer_index, epoch).blocks_proposed += 1
            if self._metrics:
                self._metrics["proposed"].inc(index=str(proposer_index))

    # -- epoch rollup --------------------------------------------------------

    def summarize_epoch(self, epoch: int) -> dict[int, EpochSummary]:
        """Epoch-end rollup; validators with no inclusion are counted
        missed (reference: onceEpochTransition log + metrics)."""
        out = {}
        for idx in self._monitored:
            s = self._summaries.get((idx, epoch), EpochSummary())
            out[idx] = s
            if not s.attestation_included and self._metrics:
                self._metrics["missed"].inc(index=str(idx))
        # prune old epochs
        self._summaries = {
            k: v for k, v in self._summaries.items() if k[1] >= epoch - 1
        }
        return out
