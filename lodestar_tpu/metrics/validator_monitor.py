"""ValidatorMonitor: per-validator duty tracking for operators.

Reference: `metrics/validatorMonitor.ts` (478 LoC) — registered validator
indices get per-epoch summaries (attestation seen on gossip / included in
a block, inclusion distance, head/target correctness, blocks proposed,
aggregates, sync-committee signatures, balance deltas) surfaced as
metrics and epoch-end log lines.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class EpochSummary:
    # attestation lifecycle (reference: registerGossipAttestation /
    # registerAttestationInBlock)
    attestation_seen: bool = False          # observed on gossip
    attestation_seen_delay_sec: float = 0.0
    attestation_included: bool = False      # landed in a block
    inclusion_distance: int = 0
    target_correct: bool = False
    head_correct: bool = False
    # aggregation duties
    aggregates_published: int = 0
    attestation_in_aggregate: bool = False
    # proposals
    blocks_proposed: int = 0
    block_seen_delay_sec: float = 0.0
    # sync committee
    sync_signatures: int = 0
    sync_signatures_included: int = 0
    # rewards proxy
    balance_gwei: int = 0


class ValidatorMonitor:
    def __init__(self, registry=None):
        self._monitored: set[int] = set()
        self._summaries: dict[tuple[int, int], EpochSummary] = {}
        self._metrics = None
        if registry is not None:
            label = ("index",)
            self._metrics = {
                "seen": registry.counter(
                    "validator_monitor_attestation_seen_total",
                    "monitored validators' attestations observed on gossip",
                    label_names=label,
                ),
                "included": registry.counter(
                    "validator_monitor_attestation_included_total",
                    "attestations included for monitored validators",
                    label_names=label,
                ),
                "missed": registry.counter(
                    "validator_monitor_attestation_missed_total",
                    "attestations missed for monitored validators",
                    label_names=label,
                ),
                "distance": registry.histogram(
                    "validator_monitor_inclusion_distance",
                    "inclusion distance of monitored attestations",
                    buckets=(1, 2, 3, 4, 5, 8, 16, 32),
                ),
                "target_miss": registry.counter(
                    "validator_monitor_target_incorrect_total",
                    "included attestations with the wrong target",
                    label_names=label,
                ),
                "head_miss": registry.counter(
                    "validator_monitor_head_incorrect_total",
                    "included attestations with the wrong head",
                    label_names=label,
                ),
                "proposed": registry.counter(
                    "validator_monitor_blocks_proposed_total",
                    "blocks proposed by monitored validators",
                    label_names=label,
                ),
                "aggregates": registry.counter(
                    "validator_monitor_aggregates_published_total",
                    "aggregate-and-proofs from monitored aggregators",
                    label_names=label,
                ),
                "sync_sigs": registry.counter(
                    "validator_monitor_sync_signatures_total",
                    "sync-committee messages from monitored validators",
                    label_names=label,
                ),
                "sync_included": registry.counter(
                    "validator_monitor_sync_signatures_included_total",
                    "monitored sync signatures included in SyncAggregates",
                    label_names=label,
                ),
                "balance": registry.gauge(
                    "validator_monitor_balance_gwei",
                    "latest monitored validator balance",
                    label_names=label,
                ),
                # timeliness: delay from the duty's slot start to the
                # event reaching this node (reference validatorMonitor
                # *_delay_seconds families — the per-validator view of
                # the node-wide slot-milestone metrics)
                "att_delay": registry.histogram(
                    "validator_monitor_attestation_seen_delay_seconds",
                    "slot-start -> gossip-seen delay of monitored attestations",
                    buckets=(0.5, 1, 1.5, 2, 3, 4, 6, 8, 12),
                ),
                "block_delay": registry.histogram(
                    "validator_monitor_block_seen_delay_seconds",
                    "slot-start -> import delay of monitored proposals",
                    buckets=(0.5, 1, 1.5, 2, 3, 4, 6, 8, 12),
                ),
            }

    def register_validator(self, index: int) -> None:
        self._monitored.add(index)

    @property
    def monitored(self) -> set[int]:
        return set(self._monitored)

    def _summary(self, index: int, epoch: int) -> EpochSummary:
        return self._summaries.setdefault((index, epoch), EpochSummary())

    # -- event hooks (called by gossip validation / import pipeline) --------

    def on_gossip_attestation(
        self, epoch: int, index: int, delay_sec: float = 0.0
    ) -> None:
        """A monitored validator's unaggregated attestation arrived on
        gossip (reference registerGossipAttestation)."""
        if index in self._monitored:
            s = self._summary(index, epoch)
            if not s.attestation_seen:
                s.attestation_seen = True
                s.attestation_seen_delay_sec = delay_sec
                if self._metrics:
                    self._metrics["seen"].inc(index=str(index))
                    self._metrics["att_delay"].observe(delay_sec)

    def on_attestation_included(
        self, epoch: int, indices, inclusion_distance: int,
        target_correct: bool, head_correct: bool,
    ) -> None:
        for idx in indices:
            if idx in self._monitored:
                s = self._summary(idx, epoch)
                # keep the BEST observation across re-inclusions (minimum
                # distance, OR-ed correctness) — a later aggregate must not
                # degrade the report
                if s.attestation_included:
                    s.inclusion_distance = min(s.inclusion_distance, inclusion_distance)
                else:
                    s.attestation_included = True
                    s.inclusion_distance = inclusion_distance
                    if self._metrics:
                        self._metrics["included"].inc(index=str(idx))
                        self._metrics["distance"].observe(inclusion_distance)
                        if not target_correct:
                            self._metrics["target_miss"].inc(index=str(idx))
                        if not head_correct:
                            self._metrics["head_miss"].inc(index=str(idx))
                s.target_correct = s.target_correct or target_correct
                s.head_correct = s.head_correct or head_correct

    def on_attestation_in_aggregate(self, epoch: int, indices) -> None:
        for idx in indices:
            if idx in self._monitored:
                self._summary(idx, epoch).attestation_in_aggregate = True

    def on_aggregate_published(self, epoch: int, aggregator_index: int) -> None:
        if aggregator_index in self._monitored:
            self._summary(aggregator_index, epoch).aggregates_published += 1
            if self._metrics:
                self._metrics["aggregates"].inc(index=str(aggregator_index))

    def on_block_proposed(
        self, epoch: int, proposer_index: int, delay_sec: float = 0.0
    ) -> None:
        if proposer_index in self._monitored:
            s = self._summary(proposer_index, epoch)
            s.blocks_proposed += 1
            s.block_seen_delay_sec = delay_sec
            if self._metrics:
                self._metrics["proposed"].inc(index=str(proposer_index))
                self._metrics["block_delay"].observe(delay_sec)

    def on_sync_committee_message(self, epoch: int, index: int) -> None:
        if index in self._monitored:
            self._summary(index, epoch).sync_signatures += 1
            if self._metrics:
                self._metrics["sync_sigs"].inc(index=str(index))

    def on_sync_signature_included(self, epoch: int, indices) -> None:
        for idx in indices:
            if idx in self._monitored:
                self._summary(idx, epoch).sync_signatures_included += 1
                if self._metrics:
                    self._metrics["sync_included"].inc(index=str(idx))

    def on_balances(self, epoch: int, balances) -> None:
        """Record monitored balances at an epoch boundary (reference
        registerValidatorStatuses' balance tracking)."""
        for idx in self._monitored:
            if idx < len(balances):
                bal = int(balances[idx])
                self._summary(idx, epoch).balance_gwei = bal
                if self._metrics:
                    self._metrics["balance"].set(bal, index=str(idx))

    # -- epoch rollup --------------------------------------------------------

    def summarize_epoch(self, epoch: int) -> dict[int, EpochSummary]:
        """Epoch-end rollup; validators with no inclusion are counted
        missed (reference: onceEpochTransition log + metrics)."""
        out = {}
        for idx in self._monitored:
            s = self._summaries.get((idx, epoch), EpochSummary())
            out[idx] = s
            if not s.attestation_included and self._metrics:
                self._metrics["missed"].inc(index=str(idx))
        # prune old epochs
        self._summaries = {
            k: v for k, v in self._summaries.items() if k[1] >= epoch - 1
        }
        return out

    def log_epoch(self, epoch: int, log) -> None:
        """Operator-facing epoch-end line per monitored validator
        (reference logs 'validator monitor' summaries)."""
        for idx, s in sorted(self.summarize_epoch(epoch).items()):
            log.info(
                "monitor v%d e%d: att %s dist=%d target=%s head=%s "
                "props=%d aggs=%d sync=%d/%d bal=%d",
                idx, epoch,
                "included" if s.attestation_included
                else ("seen" if s.attestation_seen else "MISSED"),
                s.inclusion_distance,
                "ok" if s.target_correct else "x",
                "ok" if s.head_correct else "x",
                s.blocks_proposed, s.aggregates_published,
                s.sync_signatures_included, s.sync_signatures,
                s.balance_gwei,
            )
