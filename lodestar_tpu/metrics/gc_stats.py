"""GC pause metrics — the reference's `gc-stats`/`prometheus-gc-stats`
equivalent (SURVEY.md §2.3 native deps table; beacon-node package.json).

CPython exposes collection hooks via `gc.callbacks`; we time each
collection and export pause histograms + collected-object counters per
generation. `install_gc_metrics(registry)` is idempotent.
"""

from __future__ import annotations

import gc
import time


class GcMetrics:
    def __init__(self, registry):
        self.pause_seconds = registry.histogram(
            "python_gc_pause_seconds", "stop-the-world GC pause duration",
            label_names=("generation",),
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5),
        )
        self.collections_total = registry.counter(
            "python_gc_collections_total", "GC runs per generation",
            label_names=("generation",),
        )
        self.collected_total = registry.counter(
            "python_gc_collected_objects_total", "objects collected",
            label_names=("generation",),
        )
        self.uncollectable_total = registry.counter(
            "python_gc_uncollectable_total", "uncollectable objects found",
        )
        self._t0 = 0.0

    def _cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._t0 = time.perf_counter()
            return
        gen = str(info.get("generation", "?"))
        self.pause_seconds.observe(time.perf_counter() - self._t0, generation=gen)
        self.collections_total.inc(generation=gen)
        self.collected_total.inc(info.get("collected", 0), generation=gen)
        if info.get("uncollectable"):
            self.uncollectable_total.inc(info["uncollectable"])


_installed: GcMetrics | None = None


def install_gc_metrics(registry) -> GcMetrics:
    """Install (or rebind) the process-global GC callback.

    The gc hook is registered once; a new registry (e.g. an in-process
    node restart) REPLACES the metric family bundle so the live node's
    /metrics keeps receiving observations instead of a dead registry.
    """
    global _installed
    if _installed is None:
        _installed = GcMetrics(registry)
        gc.callbacks.append(_installed._cb)
    elif _installed.pause_seconds not in getattr(registry, "_metrics", []):
        fresh = GcMetrics(registry)
        fresh._t0 = _installed._t0
        # swap the bundle the registered callback dispatches into
        _installed.__dict__.update(fresh.__dict__)
    return _installed
