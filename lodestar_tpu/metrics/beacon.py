"""Beacon metric set: the interop-standard gauges plus the lodestar-
specific BLS-pool/block-processor metrics our services emit.

Reference: `metrics/metrics/beacon.ts` (official interop names) and
`metrics/metrics/lodestar.ts` (lodestar_* namespace; blsThreadPool.* at
:412 — mapped here to the device-verifier equivalents).
"""

from __future__ import annotations

from .registry import MetricsRegistry


def create_beacon_metrics(registry: MetricsRegistry | None = None):
    r = registry if registry is not None else MetricsRegistry()

    class M:
        pass

    m = M()
    m.registry = r
    # interop-standard (beacon.ts)
    m.head_slot = r.gauge("beacon_head_slot", "slot of the chain head")
    m.finalized_epoch = r.gauge("beacon_finalized_epoch", "latest finalized epoch")
    m.current_justified_epoch = r.gauge(
        "beacon_current_justified_epoch", "current justified epoch"
    )
    m.proposed_blocks_total = r.counter(
        "beacon_blocks_proposed_total", "blocks proposed by this node"
    )
    m.processed_blocks_total = r.counter(
        "beacon_processed_blocks_total", "blocks imported"
    )
    m.gossip_attestations_total = r.counter(
        "beacon_gossip_attestation_total", "gossip attestations by outcome",
        label_names=("outcome",),
    )
    # lodestar_* equivalents (lodestar.ts) — the device verifier pool
    m.bls_batches_total = r.counter(
        "lodestar_bls_verifier_batches_total", "batched verification dispatches"
    )
    m.bls_sets_total = r.counter(
        "lodestar_bls_verifier_sets_total", "signature sets verified"
    )
    m.bls_batch_fallbacks_total = r.counter(
        "lodestar_bls_verifier_batch_fallbacks_total",
        "batches that failed and fell back to per-set verdicts",
    )
    m.bls_verify_seconds = r.histogram(
        "lodestar_bls_verifier_seconds", "device batch verification latency"
    )
    m.block_import_seconds = r.histogram(
        "lodestar_block_processor_import_seconds", "block import pipeline latency"
    )
    m.state_cache_size = r.gauge("lodestar_state_cache_size", "hot states cached")
    m.fork_choice_nodes = r.gauge(
        "lodestar_fork_choice_nodes", "proto-array node count"
    )
    # network (gossipsub / reqresp / discovery — reference lodestar.ts
    # gossipsub.* / reqResp.* metric families)
    m.peers_connected = r.gauge("lodestar_peers_connected", "live transport connections")
    m.gossip_mesh_peers = r.gauge(
        "lodestar_gossip_mesh_peers", "mesh size per topic kind",
        label_names=("kind",),
    )
    m.gossip_rx_total = r.counter(
        "lodestar_gossip_messages_received_total", "gossip messages received by outcome",
        label_names=("outcome",),
    )
    m.gossip_tx_total = r.counter(
        "lodestar_gossip_messages_sent_total", "gossip messages published"
    )
    m.gossip_queue_length = r.gauge(
        "lodestar_gossip_validation_queue_length", "validation queue depth",
        label_names=("topic",),
    )
    m.gossip_queue_dropped_total = r.counter(
        "lodestar_gossip_validation_queue_dropped_total", "jobs dropped at full queues",
        label_names=("topic",),
    )
    m.reqresp_seconds = r.histogram(
        "lodestar_reqresp_request_seconds", "outbound req/resp latency",
        label_names=("protocol",),
    )
    m.discovery_table_size = r.gauge(
        "lodestar_discovery_table_size", "routing table entries"
    )

    # --- discv5 detail (reference lodestar_discv5_* dashboard families) --
    m.discv5_rx_total = r.counter(
        "lodestar_discv5_messages_received_total",
        "discovery packets handled by type",
        label_names=("type",),
    )
    m.discv5_tx_total = r.counter(
        "lodestar_discv5_messages_sent_total",
        "discovery packets sent by type",
        label_names=("type",),
    )
    m.discv5_endpoint_proofs = r.gauge(
        "lodestar_discv5_endpoint_proofs",
        "peers with a completed endpoint proof (anti-reflection)",
    )
    m.discv5_pending_challenges = r.gauge(
        "lodestar_discv5_pending_challenges",
        "FINDNODE challenges awaiting their PONG",
    )
    m.discv5_challenge_drops_total = r.counter(
        "lodestar_discv5_challenge_drops_total",
        "challenge pings refused by the token bucket / live-challenge cap",
    )
    m.discv5_lookups_total = r.counter(
        "lodestar_discv5_lookups_total", "recursive FINDNODE lookups started"
    )
    m.discv5_liveness_evictions_total = r.counter(
        "lodestar_discv5_liveness_evictions_total",
        "table entries evicted by failed liveness pings",
    )

    # --- BLS verifier pipeline (reference blsThreadPool.* lodestar.ts:412+;
    # the "zero backlog" dashboard rows — VERDICT round-1 #9) -------------
    m.bls_buffer_depth = r.gauge(
        "lodestar_bls_verifier_buffer_sigs", "signature sets waiting in the batch buffer"
    )
    m.bls_buffer_wait_seconds = r.histogram(
        "lodestar_bls_verifier_buffer_wait_seconds",
        "time a set waited in the buffer before dispatch",
    )
    m.bls_job_sets = r.histogram(
        "lodestar_bls_verifier_sets_per_job", "signature sets per device dispatch"
    )
    m.bls_marshal_seconds = r.histogram(
        "lodestar_bls_verifier_marshal_seconds", "host marshalling latency per batch"
    )
    m.bls_h2c_cache_hits_total = r.counter(
        "lodestar_bls_verifier_h2c_cache_hits_total", "hash-to-curve cache hits"
    )
    m.bls_h2c_cache_misses_total = r.counter(
        "lodestar_bls_verifier_h2c_cache_misses_total", "hash-to-curve cache misses"
    )
    m.bls_main_thread_sets_total = r.counter(
        "lodestar_bls_verifier_main_thread_sets_total",
        "sets verified synchronously (non-batchable path)",
    )

    # --- block processor stages (reference lodestar.ts blockProcessor.* +
    # verifyBlock stage timers) ------------------------------------------
    m.block_stf_seconds = r.histogram(
        "lodestar_block_processor_stf_seconds", "state transition latency"
    )
    m.block_sig_seconds = r.histogram(
        "lodestar_block_processor_signatures_seconds",
        "block signature batch latency",
    )
    m.block_payload_seconds = r.histogram(
        "lodestar_block_processor_payload_seconds",
        "execution payload verification latency",
    )
    m.block_import_errors_total = r.counter(
        "lodestar_block_processor_errors_total", "failed imports by reason",
        label_names=("reason",),
    )
    m.blocking_wait_timeouts_total = r.counter(
        "lodestar_chain_blocking_wait_timeouts_total",
        "serving-path future waits that hit LODESTAR_TPU_IMPORT_WAIT_TIMEOUT",
        label_names=("site",),
    )

    # --- regen / caches (reference regen.* stateCache.*) ----------------
    m.regen_replays_total = r.counter(
        "lodestar_regen_replays_total", "state replays (cache misses)"
    )
    m.regen_queue_pending = r.gauge(
        "lodestar_regen_queue_pending", "pending replay requests"
    )
    m.regen_rejections_total = r.counter(
        "lodestar_regen_rejections_total", "replays rejected at the 256 bound"
    )
    m.state_cache_hits_total = r.counter(
        "lodestar_state_cache_hits_total", "hot state cache hits"
    )
    m.state_cache_misses_total = r.counter(
        "lodestar_state_cache_misses_total", "hot state cache misses"
    )
    m.checkpoint_cache_size = r.gauge(
        "lodestar_checkpoint_state_cache_size", "checkpoint states cached"
    )

    # --- op pools (reference opPool.*) ----------------------------------
    m.op_pool_size = r.gauge(
        "lodestar_op_pool_size", "pool entry count by kind",
        label_names=("kind",),
    )

    # --- sync (reference sync.* backfill.*) -----------------------------
    m.sync_range_batches_total = r.counter(
        "lodestar_sync_range_batches_total", "range-sync batches by outcome",
        label_names=("outcome",),
    )
    m.sync_unknown_block_fetches_total = r.counter(
        "lodestar_sync_unknown_block_fetches_total", "unknown-block root fetches"
    )
    m.backfill_slot = r.gauge(
        "lodestar_backfill_earliest_slot", "earliest backfilled slot"
    )

    # --- db / storage engine (reference db.* + native kvstore stats) ----
    m.db_ops_total = r.counter(
        "lodestar_db_ops_total", "db operations by kind",
        label_names=("op",),
    )
    m.db_entries = r.gauge("lodestar_db_entries", "KV entries")
    m.db_live_bytes = r.gauge("lodestar_db_live_bytes", "live bytes on disk")
    m.db_dead_bytes = r.gauge(
        "lodestar_db_dead_bytes", "dead bytes awaiting compaction"
    )

    # --- eth1 (reference eth1.*) ----------------------------------------
    m.eth1_deposits_total = r.counter(
        "lodestar_eth1_deposit_logs_total", "deposit logs ingested"
    )
    m.eth1_synced_block = r.gauge(
        "lodestar_eth1_synced_block", "latest eth1 block ingested"
    )
    m.eth1_request_errors_total = r.counter(
        "lodestar_eth1_request_errors_total", "eth1 RPC failures"
    )

    # --- clock / validator interop extras (beacon.ts) -------------------
    m.clock_slot = r.gauge("beacon_clock_slot", "wall-clock slot")
    m.reorgs_total = r.counter("beacon_reorgs_total", "head reorg events")
    m.head_root_changes_total = r.counter(
        "beacon_head_changes_total", "head updates"
    )
    m.proposer_boost_active = r.gauge(
        "lodestar_fork_choice_proposer_boost_active",
        "1 while a proposer boost is applied",
    )
    m.fork_choice_votes = r.gauge(
        "lodestar_fork_choice_tracked_votes", "validators with live LMD votes"
    )

    # --- gossipsub detail (reference lodestar.ts gossipsub.* — per-topic
    # accept/reject/ignore, control traffic, mesh churn, score buckets) ---
    m.gossip_validation_total = r.counter(
        "lodestar_gossip_validation_total",
        "validation results per topic kind",
        label_names=("kind", "outcome"),
    )
    m.gossip_duplicates_total = r.counter(
        "lodestar_gossip_duplicate_messages_total",
        "messages already seen (dropped pre-validation)",
    )
    m.gossip_graft_rx_total = r.counter(
        "lodestar_gossip_graft_received_total", "GRAFT control messages received"
    )
    m.gossip_prune_rx_total = r.counter(
        "lodestar_gossip_prune_received_total", "PRUNE control messages received"
    )
    m.gossip_ihave_rx_total = r.counter(
        "lodestar_gossip_ihave_received_total", "IHAVE ids advertised to us"
    )
    m.gossip_iwant_rx_total = r.counter(
        "lodestar_gossip_iwant_received_total", "IWANT ids requested from us"
    )
    m.gossip_iwant_served_total = r.counter(
        "lodestar_gossip_iwant_served_total", "IWANT ids answered from mcache"
    )
    m.gossip_iwant_budget_drops_total = r.counter(
        "lodestar_gossip_iwant_budget_drops_total",
        "IWANT ids dropped by the per-peer budget/score gate",
    )
    m.gossip_peers_by_score = r.gauge(
        "lodestar_gossip_peers_by_score",
        "peer count per score band",
        label_names=("band",),
    )
    m.gossip_score_min = r.gauge(
        "lodestar_gossip_peer_score_min", "lowest peer score"
    )
    m.gossip_score_max = r.gauge(
        "lodestar_gossip_peer_score_max", "highest peer score"
    )
    m.gossip_mesh_churn_total = r.counter(
        "lodestar_gossip_mesh_churn_total",
        "mesh membership changes",
        label_names=("direction",),
    )
    m.gossip_validation_seconds = r.histogram(
        "lodestar_gossip_validation_seconds",
        "validator latency per topic kind",
        label_names=("kind",),
    )

    # --- reqresp detail (reference lodestar.ts reqResp.* — per-protocol
    # request/byte/error counters, rate limits) ---------------------------
    m.reqresp_incoming_requests_total = r.counter(
        "lodestar_reqresp_incoming_requests_total",
        "inbound requests per protocol",
        label_names=("protocol",),
    )
    m.reqresp_incoming_errors_total = r.counter(
        "lodestar_reqresp_incoming_errors_total",
        "inbound requests that errored per protocol",
        label_names=("protocol",),
    )
    m.reqresp_outgoing_requests_total = r.counter(
        "lodestar_reqresp_outgoing_requests_total",
        "outbound requests per protocol",
        label_names=("protocol",),
    )
    m.reqresp_outgoing_errors_total = r.counter(
        "lodestar_reqresp_outgoing_errors_total",
        "outbound requests that errored per protocol",
        label_names=("protocol",),
    )
    m.reqresp_bytes_sent_total = r.counter(
        "lodestar_reqresp_bytes_sent_total",
        "response bytes written per protocol",
        label_names=("protocol",),
    )
    m.reqresp_bytes_received_total = r.counter(
        "lodestar_reqresp_bytes_received_total",
        "response bytes read per protocol",
        label_names=("protocol",),
    )
    m.reqresp_rate_limited_total = r.counter(
        "lodestar_reqresp_rate_limited_total",
        "requests refused by rate limiters",
        label_names=("limiter",),
    )
    m.reqresp_response_chunks_total = r.counter(
        "lodestar_reqresp_response_chunks_total",
        "response chunks received per result code",
        label_names=("code",),
    )

    # --- sync detail (reference lodestar.ts sync.* — batch states,
    # processed-block rate, peer counts per sync kind) --------------------
    m.sync_batches_in_state = r.gauge(
        "lodestar_sync_batches_in_state",
        "range-sync batches per state",
        label_names=("state",),
    )
    m.sync_blocks_imported_total = r.counter(
        "lodestar_sync_blocks_imported_total", "blocks imported by range sync"
    )
    m.sync_segment_seconds = r.histogram(
        "lodestar_sync_segment_import_seconds", "segment import latency"
    )
    m.sync_peers = r.gauge(
        "lodestar_sync_peers", "peers usable per sync kind",
        label_names=("kind",),
    )
    m.sync_status = r.gauge(
        "lodestar_sync_status", "0 stalled / 1 syncing / 2 synced"
    )
    m.backfill_batches_total = r.counter(
        "lodestar_backfill_batches_total", "backfill batches by outcome",
        label_names=("outcome",),
    )

    # --- eth1 detail (reference lodestar.ts eth1.*) ----------------------
    m.eth1_follow_distance = r.gauge(
        "lodestar_eth1_follow_distance_blocks",
        "blocks between eth1 head and our synced block",
    )
    m.eth1_request_seconds = r.histogram(
        "lodestar_eth1_request_seconds", "eth1 JSON-RPC latency",
        label_names=("method",),
    )
    m.eth1_logs_batch_size = r.histogram(
        "lodestar_eth1_logs_batch_size", "deposit logs per getLogs window"
    )

    # --- execution engine (reference lodestar.ts executionEngine.*) ------
    m.engine_requests_total = r.counter(
        "lodestar_engine_http_requests_total",
        "engine API calls by method and outcome",
        label_names=("method", "outcome"),
    )
    m.engine_request_seconds = r.histogram(
        "lodestar_engine_http_seconds", "engine API latency",
        label_names=("method",),
    )
    m.engine_payload_status_total = r.counter(
        "lodestar_engine_payload_status_total",
        "newPayload verdicts",
        label_names=("status",),
    )

    # --- REST API server (reference lodestar.ts restApi.*) ---------------
    m.api_requests_total = r.counter(
        "lodestar_api_requests_total",
        "REST requests by namespace and status class",
        label_names=("namespace", "status"),
    )
    m.api_request_seconds = r.histogram(
        "lodestar_api_request_seconds", "REST handler latency",
        label_names=("namespace",),
    )
    m.api_sse_subscribers = r.gauge(
        "lodestar_api_sse_subscribers", "open event-stream connections"
    )

    # --- chain internals (epoch transitions, caches, archiver) -----------
    m.epoch_transition_seconds = r.histogram(
        "lodestar_stfn_epoch_transition_seconds", "epoch processing latency"
    )
    m.state_hash_seconds = r.histogram(
        "lodestar_stfn_hash_tree_root_seconds",
        "incremental state hashing latency",
    )
    m.state_hash_dirty_validators = r.histogram(
        "lodestar_stfn_hash_dirty_validators",
        "validator rows re-hashed per state root",
    )
    m.shuffling_cache_hits_total = r.counter(
        "lodestar_shuffling_cache_hits_total", "epoch shuffling cache hits"
    )
    m.shuffling_cache_misses_total = r.counter(
        "lodestar_shuffling_cache_misses_total", "epoch shuffling cache builds"
    )
    m.attestation_pool_inserts_total = r.counter(
        "lodestar_attestation_pool_inserts_total",
        "attestation pool insert outcomes",
        label_names=("outcome",),
    )
    m.archiver_states_total = r.counter(
        "lodestar_archiver_states_written_total", "states archived"
    )
    m.archiver_blocks_total = r.counter(
        "lodestar_archiver_blocks_migrated_total",
        "finalized blocks migrated to cold storage",
    )
    m.seen_cache_size = r.gauge(
        "lodestar_seen_cache_size", "entries per seen-cache kind",
        label_names=("kind",),
    )

    # --- validator client (reference lodestar.ts validator.*) ------------
    m.vc_duties_total = r.counter(
        "lodestar_vc_duties_total", "duties performed by kind and outcome",
        label_names=("kind", "outcome"),
    )
    m.vc_signer_seconds = r.histogram(
        "lodestar_vc_signer_seconds", "signing latency",
        label_names=("kind",),
    )

    # --- process health (reference nodejs.* equivalents) -----------------
    m.event_loop_lag_seconds = r.gauge(
        "lodestar_event_loop_lag_seconds", "asyncio scheduling lag"
    )
    m.process_rss_bytes = r.gauge(
        "lodestar_process_rss_bytes", "resident set size"
    )
    m.open_fds = r.gauge("lodestar_process_open_fds", "open file descriptors")
    m.clock_epoch = r.gauge("beacon_clock_epoch", "wall-clock epoch")
    m.active_validators = r.gauge(
        "beacon_current_active_validators", "active validator count"
    )
    m.head_distance = r.gauge(
        "lodestar_head_slot_distance",
        "slots between wall clock and head (sync lag)",
    )
    m.db_compactions_total = r.counter(
        "lodestar_db_compactions_total", "KV log compactions run"
    )
    m.h2c_cache_size = r.gauge(
        "lodestar_bls_verifier_h2c_cache_size", "hash-to-curve cache entries"
    )

    # --- slot-milestone lifecycle (observability.spans; reference: the
    # validator-monitor timeliness metrics + "delay from slot start"
    # dashboard rows). One histogram family labeled by milestone so a
    # slow slot decomposes into receive/validate/verify/import/head.
    m.slot_milestone_seconds = r.histogram(
        "lodestar_slot_milestone_delay_seconds",
        "delay from slot start to each block lifecycle milestone",
        label_names=("milestone",),
        buckets=(0.1, 0.25, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0),
    )
    m.slot_milestone_last = r.gauge(
        "lodestar_slot_milestone_last_delay_seconds",
        "latest observed per-milestone delay from slot start",
        label_names=("milestone",),
    )
    m.lifecycle_traces_total = r.counter(
        "lodestar_lifecycle_traces_total",
        "completed lifecycle traces by root span kind",
        label_names=("kind",),
    )

    # --- BLS pipeline telemetry (observability.stages) ------------------
    # stage timers, planner-decision counters, flush/queue gauges, device
    # busy fraction — registered on THIS registry so the families render
    # on /metrics; verifier wiring takes the bundle via `m.pipeline`
    # (node.py passes it to DeviceBlsVerifier/ThreadBufferedVerifier).
    from ..observability.stages import create_pipeline_metrics

    m.pipeline = create_pipeline_metrics(r)
    return m
