"""Beacon metric set: the interop-standard gauges plus the lodestar-
specific BLS-pool/block-processor metrics our services emit.

Reference: `metrics/metrics/beacon.ts` (official interop names) and
`metrics/metrics/lodestar.ts` (lodestar_* namespace; blsThreadPool.* at
:412 — mapped here to the device-verifier equivalents).
"""

from __future__ import annotations

from .registry import MetricsRegistry


def create_beacon_metrics(registry: MetricsRegistry | None = None):
    r = registry if registry is not None else MetricsRegistry()

    class M:
        pass

    m = M()
    m.registry = r
    # interop-standard (beacon.ts)
    m.head_slot = r.gauge("beacon_head_slot", "slot of the chain head")
    m.finalized_epoch = r.gauge("beacon_finalized_epoch", "latest finalized epoch")
    m.current_justified_epoch = r.gauge(
        "beacon_current_justified_epoch", "current justified epoch"
    )
    m.proposed_blocks_total = r.counter(
        "beacon_blocks_proposed_total", "blocks proposed by this node"
    )
    m.processed_blocks_total = r.counter(
        "beacon_processed_blocks_total", "blocks imported"
    )
    m.gossip_attestations_total = r.counter(
        "beacon_gossip_attestation_total", "gossip attestations by outcome",
        label_names=("outcome",),
    )
    # lodestar_* equivalents (lodestar.ts) — the device verifier pool
    m.bls_batches_total = r.counter(
        "lodestar_bls_verifier_batches_total", "batched verification dispatches"
    )
    m.bls_sets_total = r.counter(
        "lodestar_bls_verifier_sets_total", "signature sets verified"
    )
    m.bls_batch_fallbacks_total = r.counter(
        "lodestar_bls_verifier_batch_fallbacks_total",
        "batches that failed and fell back to per-set verdicts",
    )
    m.bls_verify_seconds = r.histogram(
        "lodestar_bls_verifier_seconds", "device batch verification latency"
    )
    m.block_import_seconds = r.histogram(
        "lodestar_block_processor_import_seconds", "block import pipeline latency"
    )
    m.state_cache_size = r.gauge("lodestar_state_cache_size", "hot states cached")
    m.fork_choice_nodes = r.gauge(
        "lodestar_fork_choice_nodes", "proto-array node count"
    )
    # network (gossipsub / reqresp / discovery — reference lodestar.ts
    # gossipsub.* / reqResp.* metric families)
    m.peers_connected = r.gauge("lodestar_peers_connected", "live transport connections")
    m.gossip_mesh_peers = r.gauge(
        "lodestar_gossip_mesh_peers", "mesh size per topic kind",
        label_names=("kind",),
    )
    m.gossip_rx_total = r.counter(
        "lodestar_gossip_messages_received_total", "gossip messages received by outcome",
        label_names=("outcome",),
    )
    m.gossip_tx_total = r.counter(
        "lodestar_gossip_messages_sent_total", "gossip messages published"
    )
    m.gossip_queue_length = r.gauge(
        "lodestar_gossip_validation_queue_length", "validation queue depth",
        label_names=("topic",),
    )
    m.gossip_queue_dropped_total = r.counter(
        "lodestar_gossip_validation_queue_dropped_total", "jobs dropped at full queues",
        label_names=("topic",),
    )
    m.reqresp_seconds = r.histogram(
        "lodestar_reqresp_request_seconds", "outbound req/resp latency",
        label_names=("protocol",),
    )
    m.discovery_table_size = r.gauge(
        "lodestar_discovery_table_size", "routing table entries"
    )
    return m
