"""Metrics registry + /metrics HTTP endpoint (SURVEY.md §2.2 `metrics/`).

Reference: prom-client registry with ~200 lodestar metrics
(`metrics/metrics/lodestar.ts`), interop beacon metrics, ValidatorMonitor,
HTTP server (`metrics/server/http.ts`). Here: a dependency-free registry
emitting the Prometheus text exposition format, the beacon/lodestar metric
sets used by the services built so far, and the same HTTP surface.
"""

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    GaugeFunc,
    Histogram,
    MetricsRegistry,
    Summary,
)
from .beacon import create_beacon_metrics  # noqa: F401
from .server import MetricsServer  # noqa: F401
