"""/metrics HTTP endpoint (reference: `metrics/server/http.ts`) plus the
profiler + lifecycle-trace control surface:

    GET /metrics          Prometheus text exposition
    POST /profiler/start  start an XLA profiler trace (?dir=<path>)
    POST /profiler/stop   stop it; returns the trace directory
    GET /debug/traces     recent lifecycle traces as JSON
                          (?slot=N &root=0x… &limit=K)
    GET /debug/breaker    device-supervisor circuit-breaker state +
                          failure-policy counters (chain/supervisor.py)
    GET /debug/mesh       serving-mesh census: healthy/serving/evicted
                          chips and compiled sharded verifiers
                          (parallel/mesh.py); unmeshed nodes report
                          wired: false
    GET /debug/fleet      fleet-serving census: two-level host layout,
                          per-host dispatches, evicted hosts and the
                          subnet router's slice/rebalance state
                          (parallel/mesh.py + parallel/fleet.py);
                          single-host nodes report wired: false
    GET /debug/lanes      priority-lane dispatcher state: per-lane queue
                          depth/caps, shed counts, coalesced batches and
                          the double-buffer overlap fraction
                          (chain/dispatcher.py); nodes without a lane
                          dispatcher report wired: false
    GET /debug/faults     fault-injection plan (testing/faults.py);
                          ?set=<spec> arms it, ?clear=1 disarms — the
                          live chaos-drill control surface
                          (docs/robustness.md)
    GET /debug/compiles   compile-ledger snapshot (every compile event:
                          kernel, shape key, duration, cache hit/miss,
                          cumulative seconds), the startup timeline
                          (serving-ready SLO marks), and the flight-
                          recorder ring (?limit=K recent events)
                          (observability/compile_ledger.py)
    GET /debug/slo        SLO engine state: every committed objective's
                          burn-rate windows, error-budget remaining and
                          ok/burning verdict (observability/slo.py);
                          nodes without an installed engine report
                          wired: false
    GET /debug/device     device-time & memory ledger: busy/idle/overlap
                          device-seconds by lane x kernel x chip plus
                          the sampled per-chip memory watermarks
                          (observability/device_ledger.py)
    GET /debug/epoch_table  epoch-resident pubkey table census: rows and
                          device residency per retained epoch, eviction
                          and device-put-failure counters
                          (parallel/epoch_table.py); nodes without the
                          table (CPU tier, knob off) report wired: false

(GET also accepted on the profiler routes — operator curl ergonomics.)
The profiler hooks default to `observability.trace`, the same process-
wide switch the device verifier uses, so the endpoint and
LODESTAR_TPU_PROFILE cannot double-start a trace. `/debug/traces` reads
the `observability.spans` ring buffer — the gossip-wire→head-update
span layer — newest first.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsServer:
    def __init__(
        self,
        registry,
        host: str = "127.0.0.1",
        port: int = 0,
        profiler_start=None,
        profiler_stop=None,
        tracer=None,
        breaker=None,
        mesh=None,
        fleet=None,
        lanes=None,
        slo=None,
        device=None,
        epoch_table=None,
    ):
        reg = registry
        if profiler_start is None or profiler_stop is None:
            from ..observability import trace

            profiler_start = profiler_start or trace.start_profiling
            profiler_stop = profiler_stop or trace.stop_profiling
        if tracer is None:
            from ..observability import spans

            tracer = spans.tracer

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send_json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _handle(self):
                parsed = urllib.parse.urlsplit(self.path)
                route = parsed.path.rstrip("/")
                if route == "/profiler/start":
                    q = urllib.parse.parse_qs(parsed.query)
                    trace_dir = (q.get("dir") or [None])[0]
                    started = profiler_start(trace_dir)
                    if started is None:
                        self._send_json(
                            409,
                            {"status": "error",
                             "reason": "trace already running or profiler unavailable"},
                        )
                    else:
                        self._send_json(200, {"status": "started", "dir": started})
                    return
                if route == "/profiler/stop":
                    stopped = profiler_stop()
                    if stopped is None:
                        self._send_json(
                            409, {"status": "error", "reason": "no trace running"}
                        )
                    else:
                        self._send_json(200, {"status": "stopped", "dir": stopped})
                    return
                if route == "/debug/traces":
                    q = urllib.parse.parse_qs(parsed.query)

                    def _one(key):
                        return (q.get(key) or [None])[0]

                    slot = _one("slot")
                    try:
                        slot = int(slot) if slot is not None else None
                        limit = min(int(_one("limit") or 64), 256)
                    except ValueError:
                        self._send_json(400, {"error": "bad slot/limit"})
                        return
                    docs = tracer.traces(
                        slot=slot, root=_one("root"), limit=limit
                    )
                    self._send_json(
                        200,
                        {
                            "count": len(docs),
                            "completed_total": tracer.completed_total,
                            "enabled": tracer.enabled,
                            "traces": docs,
                        },
                    )
                    return
                if route == "/debug/breaker":
                    # breaker = zero-arg callable returning the
                    # supervisor's breaker_snapshot(); unwired nodes
                    # (CPU-only verifier) report wired: false
                    if breaker is None:
                        self._send_json(200, {"wired": False})
                        return
                    try:
                        doc = {"wired": True, **breaker()}
                    except Exception as e:
                        self._send_json(500, {"error": str(e)})
                        return
                    self._send_json(200, doc)
                    return
                if route == "/debug/mesh":
                    # mesh = zero-arg callable returning the verifier's
                    # mesh_snapshot(); single-device or CPU-only nodes
                    # report wired: false (no mesh, kernels unsharded)
                    snap = None
                    if mesh is not None:
                        try:
                            snap = mesh()
                        except Exception as e:
                            self._send_json(500, {"error": str(e)})
                            return
                    if snap is None:
                        self._send_json(200, {"wired": False})
                        return
                    self._send_json(200, {"wired": True, **snap})
                    return
                if route == "/debug/fleet":
                    # fleet = zero-arg callable returning the dispatcher's
                    # fleet_snapshot(); single-host or unmeshed nodes
                    # report wired: false (no DCN axis, no subnet router)
                    snap = None
                    if fleet is not None:
                        try:
                            snap = fleet()
                        except Exception as e:
                            self._send_json(500, {"error": str(e)})
                            return
                    if snap is None:
                        self._send_json(200, {"wired": False})
                        return
                    self._send_json(200, {"wired": True, **snap})
                    return
                if route == "/debug/lanes":
                    # lanes = zero-arg callable returning the pipeline's
                    # lanes_snapshot(); None (no lane dispatcher bound)
                    # reports wired: false
                    snap = None
                    if lanes is not None:
                        try:
                            snap = lanes()
                        except Exception as e:
                            self._send_json(500, {"error": str(e)})
                            return
                    if snap is None:
                        self._send_json(200, {"wired": False})
                        return
                    self._send_json(200, {"wired": True, **snap})
                    return
                if route == "/debug/slo":
                    # slo = zero-arg callable returning the engine's
                    # snapshot(), None while no engine is installed
                    # (defaults to the process-wide singleton)
                    snap = None
                    provider = slo
                    if provider is None:
                        from ..observability import slo as slo_mod

                        provider = slo_mod.snapshot_or_none
                    try:
                        snap = provider()
                    except Exception as e:
                        self._send_json(500, {"error": str(e)})
                        return
                    if snap is None:
                        self._send_json(200, {"wired": False})
                        return
                    self._send_json(200, {"wired": True, **snap})
                    return
                if route == "/debug/device":
                    # device = zero-arg callable returning the device
                    # ledger's snapshot() (defaults to the process-wide
                    # singleton — always wired, attribution may be empty)
                    provider = device
                    if provider is None:
                        from ..observability import device_ledger

                        provider = device_ledger.ledger().snapshot
                    try:
                        snap = provider()
                    except Exception as e:
                        self._send_json(500, {"error": str(e)})
                        return
                    if snap is None:
                        self._send_json(200, {"wired": False})
                        return
                    self._send_json(200, {"wired": True, **snap})
                    return
                if route == "/debug/epoch_table":
                    # epoch_table = zero-arg callable returning the
                    # verifier's epoch_table_snapshot(); unwired nodes
                    # (CPU-only tier) report wired: false
                    snap = None
                    if epoch_table is not None:
                        try:
                            snap = epoch_table()
                        except Exception as e:
                            self._send_json(500, {"error": str(e)})
                            return
                    if snap is None or not snap.get("enabled", True):
                        self._send_json(200, {"wired": False})
                        return
                    self._send_json(200, {"wired": True, **snap})
                    return
                if route == "/debug/faults":
                    from ..testing import faults

                    q = urllib.parse.parse_qs(parsed.query)
                    try:
                        if "set" in q:
                            doc = faults.configure(q["set"][0])
                        elif "clear" in q:
                            # ?clear=1&reset_counters=1 also zeroes the
                            # injection counters (drill teardown); a bare
                            # clear keeps them so a degraded run stays
                            # self-labelled
                            reset = q.get("reset_counters", ["0"])[0]
                            faults.clear(
                                reset_counters=reset.lower()
                                not in ("", "0", "false")
                            )
                            doc = faults.snapshot()
                        else:
                            doc = faults.snapshot()
                    except ValueError as e:
                        self._send_json(400, {"error": str(e)})
                        return
                    self._send_json(200, doc)
                    return
                if route == "/debug/compiles":
                    from ..observability import compile_ledger, flight_recorder

                    q = urllib.parse.parse_qs(parsed.query)
                    try:
                        limit = min(int((q.get("limit") or [64])[0]), 256)
                    except ValueError:
                        self._send_json(400, {"error": "bad limit"})
                        return
                    led_snap = compile_ledger.ledger().snapshot()
                    self._send_json(
                        200,
                        {
                            "ledger": led_snap,
                            # surfaced top-level too: the AOT restart
                            # story (store dir, hit/corrupt counts,
                            # loaded executables) is its own section
                            "aot": led_snap.get("aot"),
                            "startup": compile_ledger.timeline().snapshot(),
                            "flight_recorder":
                                flight_recorder.recorder().dump(limit=limit),
                        },
                    )
                    return
                if route not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                payload = reg.expose().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._handle()

            def do_POST(self):
                self._handle()

        self._server = ThreadingHTTPServer((host, port), Handler)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
