"""/metrics HTTP endpoint (reference: `metrics/server/http.ts`)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsServer:
    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0):
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                payload = reg.expose().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._server = ThreadingHTTPServer((host, port), Handler)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
