"""Prometheus-style metric primitives (counter / gauge / histogram) and a
registry rendering the text exposition format — the prom-client role."""

from __future__ import annotations

import threading


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()

    def _key(self, labels: dict[str, str]) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != {sorted(self.label_names)}"
            )
        return tuple(labels[k] for k in self.label_names)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def collect(self):
        for key, v in sorted(self._values.items()):
            yield dict(zip(self.label_names, key)), v

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)


class GaugeFunc(_Metric):
    """Callback gauge: the value is pulled from a function at collection
    time (prom-client's `collect()` hook) instead of being pushed with
    `set()` — queue depths and cache sizes stay live without a polling
    loop. Unlabeled by design; `set_function` allows late binding once
    the observed object exists."""

    kind = "gauge"

    def __init__(self, name, help_, fn=None):
        super().__init__(name, help_, ())
        self._fn = fn

    def set_function(self, fn) -> None:
        with self._lock:
            self._fn = fn

    def value(self) -> float:
        with self._lock:
            fn = self._fn
        if fn is None:
            return 0.0
        try:
            return float(fn())
        except Exception:
            return 0.0

    def collect(self):
        yield {}, self.value()


class Summary(_Metric):
    """Prometheus summary (sum + count, no quantile streams — the same
    subset prom-client exports by default without `percentiles`)."""

    kind = "summary"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._sums: dict[tuple, float] = {}
        self._counts: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._counts[key] = self._counts.get(key, 0) + 1

    def sum(self, **labels) -> float:
        return self._sums.get(self._key(labels), 0.0)

    def count(self, **labels) -> int:
        return self._counts.get(self._key(labels), 0)

    def time(self, **labels):
        """Context manager observing elapsed seconds."""
        import time as _time

        summ = self

        class _Timer:
            def __enter__(self):
                self.t0 = _time.perf_counter()
                return self

            def __exit__(self, *exc):
                summ.observe(_time.perf_counter() - self.t0, **labels)
                return False

        return _Timer()


class Histogram(_Metric):
    kind = "histogram"
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10)

    def __init__(self, name, help_, label_names=(), buckets=None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets) if buckets else self.DEFAULT_BUCKETS
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, **labels):
        """Context manager observing elapsed seconds."""
        import time as _time

        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = _time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(_time.perf_counter() - self.t0, **labels)
                return False

        return _Timer()


class MetricsRegistry:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self._metrics: list[_Metric] = []

    def counter(self, name, help_="", label_names=()):
        m = Counter(self.prefix + name, help_, tuple(label_names))
        self._metrics.append(m)
        return m

    def gauge(self, name, help_="", label_names=()):
        m = Gauge(self.prefix + name, help_, tuple(label_names))
        self._metrics.append(m)
        return m

    def histogram(self, name, help_="", label_names=(), buckets=None):
        m = Histogram(self.prefix + name, help_, tuple(label_names), buckets)
        self._metrics.append(m)
        return m

    def summary(self, name, help_="", label_names=()):
        m = Summary(self.prefix + name, help_, tuple(label_names))
        self._metrics.append(m)
        return m

    def gauge_func(self, name, help_="", fn=None):
        m = GaugeFunc(self.prefix + name, help_, fn)
        self._metrics.append(m)
        return m

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines: list[str] = []
        for m in self._metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                for key, counts in sorted(m._counts.items()):
                    labels = dict(zip(m.label_names, key))
                    # counts are already cumulative (observe increments
                    # every bucket >= value)
                    for b, c in zip(m.buckets, counts):
                        lines.append(
                            f"{m.name}_bucket{_fmt_labels({**labels, 'le': repr(float(b))})} {c}"
                        )
                    total = m._totals[key]
                    lines.append(
                        f"{m.name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} {total}"
                    )
                    lines.append(f"{m.name}_sum{_fmt_labels(labels)} {m._sums[key]}")
                    lines.append(f"{m.name}_count{_fmt_labels(labels)} {total}")
            elif isinstance(m, Summary):
                for key, s in sorted(m._sums.items()):
                    labels = dict(zip(m.label_names, key))
                    lines.append(f"{m.name}_sum{_fmt_labels(labels)} {s}")
                    lines.append(
                        f"{m.name}_count{_fmt_labels(labels)} {m._counts[key]}"
                    )
            else:
                for labels, v in m.collect():
                    lines.append(f"{m.name}{_fmt_labels(labels)} {v}")
        return "\n".join(lines) + "\n"
