"""bellatrix (merge) SSZ container types.

Equivalent of /root/reference/packages/types/src/bellatrix/sszTypes.ts:
execution payloads enter the beacon block.
"""

from __future__ import annotations

from types import SimpleNamespace

from ..params.presets import Preset
from ..ssz import (
    BLSSignature,
    Bytes20,
    Bytes32,
    ByteListType,
    ByteVectorType,
    ListType,
    uint64,
    uint256,
)
from .phase0 import _container


def make_types(p: Preset, phase0: SimpleNamespace, altair: SimpleNamespace) -> SimpleNamespace:
    Root = Bytes32
    Transaction = ByteListType(p.MAX_BYTES_PER_TRANSACTION)

    _payload_prefix = [
        ("parent_hash", Bytes32),
        ("fee_recipient", Bytes20),
        ("state_root", Bytes32),
        ("receipts_root", Bytes32),
        ("logs_bloom", ByteVectorType(p.BYTES_PER_LOGS_BLOOM)),
        ("prev_randao", Bytes32),
        ("block_number", uint64),
        ("gas_limit", uint64),
        ("gas_used", uint64),
        ("timestamp", uint64),
        ("extra_data", ByteListType(p.MAX_EXTRA_DATA_BYTES)),
        ("base_fee_per_gas", uint256),
        ("block_hash", Bytes32),
    ]
    ExecutionPayload = _container(
        "ExecutionPayload",
        _payload_prefix + [("transactions", ListType(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD))],
    )
    ExecutionPayloadHeader = _container(
        "ExecutionPayloadHeader", _payload_prefix + [("transactions_root", Root)]
    )

    BeaconBlockBody = _container(
        "BeaconBlockBody",
        altair.BeaconBlockBody.fields + [("execution_payload", ExecutionPayload.ssz_type)],
    )
    BeaconBlock = _container(
        "BeaconBlock",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBody.ssz_type),
        ],
    )
    SignedBeaconBlock = _container(
        "SignedBeaconBlock",
        [("message", BeaconBlock.ssz_type), ("signature", BLSSignature)],
    )

    BeaconState = _container(
        "BeaconState",
        altair.BeaconState.fields
        + [("latest_execution_payload_header", ExecutionPayloadHeader.ssz_type)],
    )

    # blinded flow (MEV builder API): the payload header replaces the payload
    BlindedBeaconBlockBody = _container(
        "BlindedBeaconBlockBody",
        [
            ("execution_payload_header", ExecutionPayloadHeader.ssz_type)
            if n == "execution_payload"
            else (n, t)
            for n, t in BeaconBlockBody.fields
        ],
    )
    BlindedBeaconBlock = _container(
        "BlindedBeaconBlock",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BlindedBeaconBlockBody.ssz_type),
        ],
    )
    SignedBlindedBeaconBlock = _container(
        "SignedBlindedBeaconBlock",
        [("message", BlindedBeaconBlock.ssz_type), ("signature", BLSSignature)],
    )

    PowBlock = _container(
        "PowBlock",
        [
            ("block_hash", Bytes32),
            ("parent_hash", Bytes32),
            ("total_difficulty", uint256),
        ],
    )

    merged = {k: v for k, v in vars(altair).items() if isinstance(v, type)}
    merged.update({k: v for k, v in locals().items() if isinstance(v, type)})
    return SimpleNamespace(**merged)
