"""capella SSZ container types.

Equivalent of /root/reference/packages/types/src/capella/sszTypes.ts:
withdrawals + BLS-to-execution credential changes + historical summaries.
"""

from __future__ import annotations

from types import SimpleNamespace

from ..params.presets import Preset
from ..ssz import (
    BLSPubkey,
    BLSSignature,
    Bytes20,
    Bytes32,
    ListType,
    uint64,
)
from .phase0 import _container


def make_types(
    p: Preset, phase0: SimpleNamespace, altair: SimpleNamespace, bellatrix: SimpleNamespace
) -> SimpleNamespace:
    Root = Bytes32

    Withdrawal = _container(
        "Withdrawal",
        [
            ("index", uint64),
            ("validator_index", uint64),
            ("address", Bytes20),
            ("amount", uint64),
        ],
    )
    BLSToExecutionChange = _container(
        "BLSToExecutionChange",
        [
            ("validator_index", uint64),
            ("from_bls_pubkey", BLSPubkey),
            ("to_execution_address", Bytes20),
        ],
    )
    SignedBLSToExecutionChange = _container(
        "SignedBLSToExecutionChange",
        [("message", BLSToExecutionChange.ssz_type), ("signature", BLSSignature)],
    )
    HistoricalSummary = _container(
        "HistoricalSummary",
        [("block_summary_root", Root), ("state_summary_root", Root)],
    )

    # ExecutionPayload gains `withdrawals`
    ExecutionPayload = _container(
        "ExecutionPayload",
        bellatrix.ExecutionPayload.fields
        + [("withdrawals", ListType(Withdrawal.ssz_type, p.MAX_WITHDRAWALS_PER_PAYLOAD))],
    )
    ExecutionPayloadHeader = _container(
        "ExecutionPayloadHeader",
        bellatrix.ExecutionPayloadHeader.fields + [("withdrawals_root", Root)],
    )

    body_fields = [
        (name, ExecutionPayload.ssz_type if name == "execution_payload" else typ)
        for name, typ in bellatrix.BeaconBlockBody.fields
    ]
    BeaconBlockBody = _container(
        "BeaconBlockBody",
        body_fields
        + [
            (
                "bls_to_execution_changes",
                ListType(SignedBLSToExecutionChange.ssz_type, p.MAX_BLS_TO_EXECUTION_CHANGES),
            )
        ],
    )
    BeaconBlock = _container(
        "BeaconBlock",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBody.ssz_type),
        ],
    )
    SignedBeaconBlock = _container(
        "SignedBeaconBlock",
        [("message", BeaconBlock.ssz_type), ("signature", BLSSignature)],
    )

    # blinded body: the header sits exactly in the payload's field position
    BlindedBeaconBlockBody = _container(
        "BlindedBeaconBlockBody",
        [
            ("execution_payload_header", ExecutionPayloadHeader.ssz_type)
            if n == "execution_payload"
            else (n, t)
            for n, t in BeaconBlockBody.fields
        ],
    )
    BlindedBeaconBlock = _container(
        "BlindedBeaconBlock",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BlindedBeaconBlockBody.ssz_type),
        ],
    )
    SignedBlindedBeaconBlock = _container(
        "SignedBlindedBeaconBlock",
        [("message", BlindedBeaconBlock.ssz_type), ("signature", BLSSignature)],
    )

    state_fields = [
        (
            name,
            ExecutionPayloadHeader.ssz_type
            if name == "latest_execution_payload_header"
            else typ,
        )
        for name, typ in bellatrix.BeaconState.fields
    ]
    BeaconState = _container(
        "BeaconState",
        state_fields
        + [
            ("next_withdrawal_index", uint64),
            ("next_withdrawal_validator_index", uint64),
            (
                "historical_summaries",
                ListType(HistoricalSummary.ssz_type, p.HISTORICAL_ROOTS_LIMIT),
            ),
        ],
    )

    merged = {k: v for k, v in vars(bellatrix).items() if isinstance(v, type)}
    merged.update({k: v for k, v in locals().items() if isinstance(v, type)})
    return SimpleNamespace(**merged)
