"""phase0 SSZ container types.

Equivalent of /root/reference/packages/types/src/phase0/sszTypes.ts. Field
names and order follow the consensus spec exactly (merkle roots depend on
them). Types are built per-preset because list lengths/limits are preset
quantities.
"""

from __future__ import annotations

from types import SimpleNamespace

from ..params import ATTESTATION_SUBNET_COUNT, DEPOSIT_CONTRACT_TREE_DEPTH, JUSTIFICATION_BITS_LENGTH
from ..params.presets import Preset
from ..ssz import (
    BitListType,
    BitVectorType,
    BLSPubkey,
    BLSSignature,
    Bytes4,
    Bytes32,
    Container,
    ListType,
    VectorType,
    boolean,
    uint64,
)


def _container(name: str, fields: list) -> type[Container]:
    return type(name, (Container,), {"fields": fields})


def make_types(p: Preset) -> SimpleNamespace:
    Root = Bytes32

    Fork = _container(
        "Fork",
        [
            ("previous_version", Bytes4),
            ("current_version", Bytes4),
            ("epoch", uint64),
        ],
    )
    ForkData = _container(
        "ForkData",
        [("current_version", Bytes4), ("genesis_validators_root", Root)],
    )
    SigningData = _container(
        "SigningData", [("object_root", Root), ("domain", Bytes32)]
    )
    Checkpoint = _container("Checkpoint", [("epoch", uint64), ("root", Root)])
    Validator = _container(
        "Validator",
        [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", Bytes32),
            ("effective_balance", uint64),
            ("slashed", boolean),
            ("activation_eligibility_epoch", uint64),
            ("activation_epoch", uint64),
            ("exit_epoch", uint64),
            ("withdrawable_epoch", uint64),
        ],
    )
    AttestationData = _container(
        "AttestationData",
        [
            ("slot", uint64),
            ("index", uint64),
            ("beacon_block_root", Root),
            ("source", Checkpoint.ssz_type),
            ("target", Checkpoint.ssz_type),
        ],
    )
    CommitteeBits = BitListType(p.MAX_VALIDATORS_PER_COMMITTEE)
    IndexedAttestation = _container(
        "IndexedAttestation",
        [
            ("attesting_indices", ListType(uint64, p.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", AttestationData.ssz_type),
            ("signature", BLSSignature),
        ],
    )
    PendingAttestation = _container(
        "PendingAttestation",
        [
            ("aggregation_bits", CommitteeBits),
            ("data", AttestationData.ssz_type),
            ("inclusion_delay", uint64),
            ("proposer_index", uint64),
        ],
    )
    Attestation = _container(
        "Attestation",
        [
            ("aggregation_bits", CommitteeBits),
            ("data", AttestationData.ssz_type),
            ("signature", BLSSignature),
        ],
    )
    AggregateAndProof = _container(
        "AggregateAndProof",
        [
            ("aggregator_index", uint64),
            ("aggregate", Attestation.ssz_type),
            ("selection_proof", BLSSignature),
        ],
    )
    SignedAggregateAndProof = _container(
        "SignedAggregateAndProof",
        [("message", AggregateAndProof.ssz_type), ("signature", BLSSignature)],
    )
    Eth1Data = _container(
        "Eth1Data",
        [
            ("deposit_root", Root),
            ("deposit_count", uint64),
            ("block_hash", Bytes32),
        ],
    )
    HistoricalBatch = _container(
        "HistoricalBatch",
        [
            ("block_roots", VectorType(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", VectorType(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
        ],
    )
    DepositMessage = _container(
        "DepositMessage",
        [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", Bytes32),
            ("amount", uint64),
        ],
    )
    DepositData = _container(
        "DepositData",
        [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", Bytes32),
            ("amount", uint64),
            ("signature", BLSSignature),
        ],
    )
    Deposit = _container(
        "Deposit",
        [
            ("proof", VectorType(Bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1)),
            ("data", DepositData.ssz_type),
        ],
    )
    BeaconBlockHeader = _container(
        "BeaconBlockHeader",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Root),
            ("state_root", Root),
            ("body_root", Root),
        ],
    )
    SignedBeaconBlockHeader = _container(
        "SignedBeaconBlockHeader",
        [("message", BeaconBlockHeader.ssz_type), ("signature", BLSSignature)],
    )
    ProposerSlashing = _container(
        "ProposerSlashing",
        [
            ("signed_header_1", SignedBeaconBlockHeader.ssz_type),
            ("signed_header_2", SignedBeaconBlockHeader.ssz_type),
        ],
    )
    AttesterSlashing = _container(
        "AttesterSlashing",
        [
            ("attestation_1", IndexedAttestation.ssz_type),
            ("attestation_2", IndexedAttestation.ssz_type),
        ],
    )
    VoluntaryExit = _container(
        "VoluntaryExit", [("epoch", uint64), ("validator_index", uint64)]
    )
    SignedVoluntaryExit = _container(
        "SignedVoluntaryExit",
        [("message", VoluntaryExit.ssz_type), ("signature", BLSSignature)],
    )
    BeaconBlockBody = _container(
        "BeaconBlockBody",
        [
            ("randao_reveal", BLSSignature),
            ("eth1_data", Eth1Data.ssz_type),
            ("graffiti", Bytes32),
            ("proposer_slashings", ListType(ProposerSlashing.ssz_type, p.MAX_PROPOSER_SLASHINGS)),
            ("attester_slashings", ListType(AttesterSlashing.ssz_type, p.MAX_ATTESTER_SLASHINGS)),
            ("attestations", ListType(Attestation.ssz_type, p.MAX_ATTESTATIONS)),
            ("deposits", ListType(Deposit.ssz_type, p.MAX_DEPOSITS)),
            ("voluntary_exits", ListType(SignedVoluntaryExit.ssz_type, p.MAX_VOLUNTARY_EXITS)),
        ],
    )
    BeaconBlock = _container(
        "BeaconBlock",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBody.ssz_type),
        ],
    )
    SignedBeaconBlock = _container(
        "SignedBeaconBlock",
        [("message", BeaconBlock.ssz_type), ("signature", BLSSignature)],
    )
    BeaconState = _container(
        "BeaconState",
        [
            ("genesis_time", uint64),
            ("genesis_validators_root", Root),
            ("slot", uint64),
            ("fork", Fork.ssz_type),
            ("latest_block_header", BeaconBlockHeader.ssz_type),
            ("block_roots", VectorType(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", VectorType(Root, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("historical_roots", ListType(Root, p.HISTORICAL_ROOTS_LIMIT)),
            ("eth1_data", Eth1Data.ssz_type),
            (
                "eth1_data_votes",
                ListType(Eth1Data.ssz_type, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH),
            ),
            ("eth1_deposit_index", uint64),
            ("validators", ListType(Validator.ssz_type, p.VALIDATOR_REGISTRY_LIMIT)),
            ("balances", ListType(uint64, p.VALIDATOR_REGISTRY_LIMIT)),
            ("randao_mixes", VectorType(Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR)),
            ("slashings", VectorType(uint64, p.EPOCHS_PER_SLASHINGS_VECTOR)),
            (
                "previous_epoch_attestations",
                ListType(PendingAttestation.ssz_type, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH),
            ),
            (
                "current_epoch_attestations",
                ListType(PendingAttestation.ssz_type, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH),
            ),
            ("justification_bits", BitVectorType(JUSTIFICATION_BITS_LENGTH)),
            ("previous_justified_checkpoint", Checkpoint.ssz_type),
            ("current_justified_checkpoint", Checkpoint.ssz_type),
            ("finalized_checkpoint", Checkpoint.ssz_type),
        ],
    )

    # --- p2p wire types (reference: types/src/phase0/sszTypes.ts Status etc.)
    Status = _container(
        "Status",
        [
            ("fork_digest", Bytes4),
            ("finalized_root", Root),
            ("finalized_epoch", uint64),
            ("head_root", Root),
            ("head_slot", uint64),
        ],
    )
    Metadata = _container(
        "Metadata",
        [("seq_number", uint64), ("attnets", BitVectorType(ATTESTATION_SUBNET_COUNT))],
    )

    Eth1Block = _container(
        "Eth1Block",
        [("timestamp", uint64), ("deposit_root", Root), ("deposit_count", uint64)],
    )

    return SimpleNamespace(**{k: v for k, v in locals().items() if isinstance(v, type)})
