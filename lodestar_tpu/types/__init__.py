"""Per-fork SSZ types (layer L1) — equivalent of @lodestar/types.

``get_types(preset)`` builds (and caches) the full namespace of container
classes for every fork, e.g.::

    t = get_types(MAINNET)
    block = t.phase0.SignedBeaconBlock(...)
    t.capella.BeaconState.deserialize(data)

``ssz`` is the namespace for the process-default preset (reference exposes a
module-level ``ssz`` object: types/src/index.ts).
"""

from __future__ import annotations

from types import SimpleNamespace

from .. import params as _params
from ..params import ForkName
from ..params.presets import Preset
from . import altair as _altair
from . import bellatrix as _bellatrix
from . import capella as _capella
from . import phase0 as _phase0

_cache: dict[int, SimpleNamespace] = {}


def get_types(preset: Preset | None = None) -> SimpleNamespace:
    # Read the active preset at call time so set_active_preset() is honored.
    preset = preset or _params.ACTIVE_PRESET
    key = id(preset)
    cached = _cache.get(key)
    if cached is not None:
        return cached

    phase0 = _phase0.make_types(preset)
    altair = _altair.make_types(preset, phase0)
    bellatrix = _bellatrix.make_types(preset, phase0, altair)
    capella = _capella.make_types(preset, phase0, altair, bellatrix)
    namespace = SimpleNamespace(
        preset=preset,
        phase0=phase0,
        altair=altair,
        bellatrix=bellatrix,
        capella=capella,
        by_fork={
            ForkName.phase0: phase0,
            ForkName.altair: altair,
            ForkName.bellatrix: bellatrix,
            ForkName.capella: capella,
        },
    )
    _cache[key] = namespace
    return namespace


def __getattr__(name: str):
    # Lazy default-preset namespace (reference: `ssz` export of
    # @lodestar/types) — resolved on first access so late
    # set_active_preset() calls are honored and import stays cheap.
    if name == "ssz":
        return get_types()
    raise AttributeError(name)
