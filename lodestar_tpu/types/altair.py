"""altair SSZ container types.

Equivalent of /root/reference/packages/types/src/altair/sszTypes.ts:
sync committees, participation flags, light-client protocol containers.
"""

from __future__ import annotations

from types import SimpleNamespace

from ..params import (
    CURRENT_SYNC_COMMITTEE_DEPTH,
    FINALIZED_ROOT_DEPTH,
    NEXT_SYNC_COMMITTEE_DEPTH,
    SYNC_COMMITTEE_SUBNET_COUNT,
)
from ..params.presets import Preset
from ..ssz import (
    BitVectorType,
    BLSPubkey,
    BLSSignature,
    Bytes32,
    ListType,
    VectorType,
    uint8,
    uint64,
)
from .phase0 import _container


def make_types(p: Preset, phase0: SimpleNamespace) -> SimpleNamespace:
    Root = Bytes32

    SyncCommittee = _container(
        "SyncCommittee",
        [
            ("pubkeys", VectorType(BLSPubkey, p.SYNC_COMMITTEE_SIZE)),
            ("aggregate_pubkey", BLSPubkey),
        ],
    )
    SyncAggregate = _container(
        "SyncAggregate",
        [
            ("sync_committee_bits", BitVectorType(p.SYNC_COMMITTEE_SIZE)),
            ("sync_committee_signature", BLSSignature),
        ],
    )
    SyncCommitteeMessage = _container(
        "SyncCommitteeMessage",
        [
            ("slot", uint64),
            ("beacon_block_root", Root),
            ("validator_index", uint64),
            ("signature", BLSSignature),
        ],
    )
    SyncCommitteeContribution = _container(
        "SyncCommitteeContribution",
        [
            ("slot", uint64),
            ("beacon_block_root", Root),
            ("subcommittee_index", uint64),
            (
                "aggregation_bits",
                BitVectorType(p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT),
            ),
            ("signature", BLSSignature),
        ],
    )
    ContributionAndProof = _container(
        "ContributionAndProof",
        [
            ("aggregator_index", uint64),
            ("contribution", SyncCommitteeContribution.ssz_type),
            ("selection_proof", BLSSignature),
        ],
    )
    SignedContributionAndProof = _container(
        "SignedContributionAndProof",
        [("message", ContributionAndProof.ssz_type), ("signature", BLSSignature)],
    )
    SyncAggregatorSelectionData = _container(
        "SyncAggregatorSelectionData",
        [("slot", uint64), ("subcommittee_index", uint64)],
    )

    BeaconBlockBody = _container(
        "BeaconBlockBody",
        phase0.BeaconBlockBody.fields + [("sync_aggregate", SyncAggregate.ssz_type)],
    )
    BeaconBlock = _container(
        "BeaconBlock",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", BeaconBlockBody.ssz_type),
        ],
    )
    SignedBeaconBlock = _container(
        "SignedBeaconBlock",
        [("message", BeaconBlock.ssz_type), ("signature", BLSSignature)],
    )

    # BeaconState: phase0 with pending attestations replaced by participation
    # flags, plus inactivity scores and sync committees.
    state_fields = []
    for name, typ in phase0.BeaconState.fields:
        if name == "previous_epoch_attestations":
            state_fields.append(
                ("previous_epoch_participation", ListType(uint8, p.VALIDATOR_REGISTRY_LIMIT))
            )
        elif name == "current_epoch_attestations":
            state_fields.append(
                ("current_epoch_participation", ListType(uint8, p.VALIDATOR_REGISTRY_LIMIT))
            )
        else:
            state_fields.append((name, typ))
    state_fields += [
        ("inactivity_scores", ListType(uint64, p.VALIDATOR_REGISTRY_LIMIT)),
        ("current_sync_committee", SyncCommittee.ssz_type),
        ("next_sync_committee", SyncCommittee.ssz_type),
    ]
    BeaconState = _container("BeaconState", state_fields)

    # --- light-client protocol (altair sync protocol; reference:
    # types/src/altair/sszTypes.ts LightClient* containers)
    LightClientBootstrap = _container(
        "LightClientBootstrap",
        [
            ("header", phase0.BeaconBlockHeader.ssz_type),
            ("current_sync_committee", SyncCommittee.ssz_type),
            ("current_sync_committee_branch", VectorType(Root, CURRENT_SYNC_COMMITTEE_DEPTH)),
        ],
    )
    LightClientUpdate = _container(
        "LightClientUpdate",
        [
            ("attested_header", phase0.BeaconBlockHeader.ssz_type),
            ("next_sync_committee", SyncCommittee.ssz_type),
            ("next_sync_committee_branch", VectorType(Root, NEXT_SYNC_COMMITTEE_DEPTH)),
            ("finalized_header", phase0.BeaconBlockHeader.ssz_type),
            ("finality_branch", VectorType(Root, FINALIZED_ROOT_DEPTH)),
            ("sync_aggregate", SyncAggregate.ssz_type),
            ("signature_slot", uint64),
        ],
    )
    LightClientFinalityUpdate = _container(
        "LightClientFinalityUpdate",
        [
            ("attested_header", phase0.BeaconBlockHeader.ssz_type),
            ("finalized_header", phase0.BeaconBlockHeader.ssz_type),
            ("finality_branch", VectorType(Root, FINALIZED_ROOT_DEPTH)),
            ("sync_aggregate", SyncAggregate.ssz_type),
            ("signature_slot", uint64),
        ],
    )
    LightClientOptimisticUpdate = _container(
        "LightClientOptimisticUpdate",
        [
            ("attested_header", phase0.BeaconBlockHeader.ssz_type),
            ("sync_aggregate", SyncAggregate.ssz_type),
            ("signature_slot", uint64),
        ],
    )

    Metadata = _container(
        "Metadata",
        phase0.Metadata.fields + [("syncnets", BitVectorType(SYNC_COMMITTEE_SUBNET_COUNT))],
    )

    # inherit unchanged phase0 containers, then overlay the altair ones
    # (reference: ssz.altair re-exports phase0 types it doesn't redefine)
    merged = {k: v for k, v in vars(phase0).items() if isinstance(v, type)}
    merged.update({k: v for k, v in locals().items() if isinstance(v, type)})
    return SimpleNamespace(**merged)
