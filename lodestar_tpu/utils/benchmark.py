"""Micro-benchmark harness for perf suites.

Reference: `@dapplion/benchmark` + `.benchrc.yaml` — per-case timed runs
with warmup, ops/sec reporting, and a relative regression gate: results
persist to a JSON history file and a case fails when it regresses more
than `threshold`× against its recorded best (the reference gates at 3×
vs branch history since no absolute numbers are committed).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass


@dataclass
class BenchResult:
    name: str
    ops_per_sec: float
    seconds_per_op: float
    runs: int


class BenchRunner:
    def __init__(
        self,
        history_path: str | None = None,
        threshold: float = 3.0,
        min_runs: int = 5,
        max_seconds: float = 5.0,
    ):
        self.history_path = history_path
        self.threshold = threshold
        self.min_runs = min_runs
        self.max_seconds = max_seconds
        self.results: list[BenchResult] = []
        self._history = {}
        if history_path and os.path.exists(history_path):
            try:
                with open(history_path) as f:
                    self._history = json.load(f)
            except (OSError, json.JSONDecodeError):
                self._history = {}

    def run(self, name: str, fn, *args) -> BenchResult:
        fn(*args)  # warmup
        runs = 0
        t_start = time.perf_counter()
        while runs < self.min_runs or (
            time.perf_counter() - t_start < self.max_seconds
            and runs < 10_000
        ):
            fn(*args)
            runs += 1
            if time.perf_counter() - t_start >= self.max_seconds:
                break
        total = time.perf_counter() - t_start
        result = BenchResult(
            name=name,
            ops_per_sec=runs / total,
            seconds_per_op=total / runs,
            runs=runs,
        )
        self.results.append(result)
        return result

    def check_regressions(self) -> list[str]:
        """Names regressing > threshold× vs recorded best (empty = pass)."""
        failures = []
        for r in self.results:
            best = self._history.get(r.name)
            if best and r.seconds_per_op > best * self.threshold:
                failures.append(
                    f"{r.name}: {r.seconds_per_op:.6f}s/op vs best {best:.6f} "
                    f"(> {self.threshold}x)"
                )
        return failures

    def save_history(self) -> None:
        if not self.history_path:
            return
        for r in self.results:
            best = self._history.get(r.name)
            if best is None or r.seconds_per_op < best:
                self._history[r.name] = r.seconds_per_op
        with open(self.history_path, "w") as f:
            json.dump(self._history, f, indent=1, sort_keys=True)
