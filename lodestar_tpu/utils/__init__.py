"""Shared utilities (layer L0) — equivalent of @lodestar/utils."""

from .bytes import (  # noqa: F401
    bytes32_rjust,
    bytes_to_int,
    from_hex,
    int_to_bytes,
    to_hex,
    uint64_to_bytes,
    xor_bytes,
)
from .errors import ErrorAborted, LodestarError, TimeoutError_  # noqa: F401
from .logger import get_logger  # noqa: F401
from .promise import retry, sleep, with_timeout  # noqa: F401
from .queue import JobItemQueue, QueueError, QueueType  # noqa: F401
