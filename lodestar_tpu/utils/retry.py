"""Shared retry helper: jittered exponential backoff behind one policy
object.

Before round 7 every client carried its own ad-hoc loop (eth1 JSON-RPC
retried with bare exponential sleeps, the engine and signer clients did
not retry at all, and the device supervisor needed a third copy), so the
thundering-herd and max-delay fixes never landed in the same place
twice. `RetryPolicy` + `retry_call` is the single copy: the eth1
provider, the engine client, the external signer, `json_http_request`,
and `chain/supervisor.py` all route through it.

The jitter is symmetric (delay x (1 +/- jitter)) so N nodes restarting
against the same dead endpoint don't re-synchronize their retries — the
classic correlated-retry stampede (AWS architecture blog's "exponential
backoff and jitter").
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable


def _always(exc: BaseException) -> bool:
    return True


@dataclass
class RetryPolicy:
    """max_attempts TOTAL tries (1 = no retry); delays grow
    base_delay_s * 2^k, capped at max_delay_s, jittered +/- `jitter`
    fraction. `retryable(exc)` gates which failures are worth retrying
    (a 404 isn't; a connection reset is)."""

    max_attempts: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    jitter: float = 0.25
    retryable: Callable[[BaseException], bool] = _always
    sleep: Callable[[float], None] = time.sleep
    rand: Callable[[], float] = field(default=random.random)

    def delay_s(self, failure_index: int) -> float:
        """Jittered backoff delay after the (failure_index+1)-th failure."""
        base = min(self.max_delay_s, self.base_delay_s * (2.0 ** failure_index))
        if self.jitter <= 0:
            return base
        return max(0.0, base * (1.0 + self.jitter * (2.0 * self.rand() - 1.0)))


def transient_http(exc: BaseException) -> bool:
    """The transport-level failures every HTTP/JSON-RPC client should
    retry: socket errors and protocol breakage — never application-level
    error replies (those raised as custom error classes don't match)."""
    import http.client

    return isinstance(exc, (OSError, http.client.HTTPException))


def retry_call(fn, *, policy: RetryPolicy | None = None, on_error=None):
    """Call `fn()` under `policy`; re-raises the last exception once
    attempts are exhausted or the failure is not retryable.

    `on_error(exc, attempt, will_retry)` fires on EVERY failed attempt
    (attempt is 0-based) so callers can keep their error counters
    exactly as the old ad-hoc loops did."""
    policy = policy or RetryPolicy()
    attempts = max(1, int(policy.max_attempts))
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:
            will_retry = attempt + 1 < attempts and policy.retryable(e)
            if on_error is not None:
                on_error(e, attempt, will_retry)
            if not will_retry:
                raise
            policy.sleep(policy.delay_s(attempt))
    raise AssertionError("unreachable")  # pragma: no cover
