"""Typed errors.

Equivalent of /root/reference/packages/utils/src/errors.ts (`LodestarError`,
typed error metadata) — errors carry a structured ``type`` dict so callers can
branch on error codes rather than parse messages.
"""

from __future__ import annotations

from typing import Any, Mapping


class LodestarError(Exception):
    """Base error carrying a structured metadata object with a ``code`` key."""

    def __init__(self, error_type: Mapping[str, Any], message: str | None = None):
        self.type = dict(error_type)
        self.code: str = str(self.type.get("code", "ERR_UNKNOWN"))
        super().__init__(message or self._format())

    def _format(self) -> str:
        meta = ", ".join(f"{k}={v}" for k, v in self.type.items() if k != "code")
        return f"{self.code}({meta})" if meta else self.code

    def get_metadata(self) -> dict[str, Any]:
        return dict(self.type)


class ErrorAborted(LodestarError):
    """Raised when an operation is interrupted by an abort signal
    (reference: utils/src/errors.ts `ErrorAborted`)."""

    def __init__(self, message: str = "aborted"):
        super().__init__({"code": "ERR_ABORTED"}, message)


class TimeoutError_(LodestarError):
    def __init__(self, message: str = "timeout"):
        super().__init__({"code": "ERR_TIMEOUT"}, message)
