"""Module-scoped logger.

Equivalent role of /root/reference/packages/utils/src/logger/winston.ts:
child loggers scoped by module name with a uniform format. Built on stdlib
``logging`` instead of winston.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname)-5s [%(name)s]%(trace)s %(message)s"
_configured = False


class _TraceContextFilter(logging.Filter):
    """Inject the active lifecycle trace-id (observability.spans) into
    every record, so a slow-slot log line correlates with its
    `/debug/traces` entry. Outside any trace the field renders empty."""

    def filter(self, record: logging.LogRecord) -> bool:
        tid = None
        try:
            from ..observability.spans import current_trace_id

            tid = current_trace_id()
        except Exception:
            pass
        record.trace = f" [t:{tid[:8]}]" if tid else ""
        return True


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
    handler.addFilter(_TraceContextFilter())
    root = logging.getLogger("lodestar_tpu")
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    root.propagate = False
    _configured = True


def get_logger(module: str, level: int | None = None) -> logging.Logger:
    """Child logger for a module (reference's LogModule enum, e.g. 'chain',
    'network', 'sync' — beacon-node/src/node/nodejs.ts:60-71)."""
    _ensure_configured()
    logger = logging.getLogger(f"lodestar_tpu.{module}")
    if level is not None:
        logger.setLevel(level)
    return logger
