"""Module-scoped logger.

Equivalent role of /root/reference/packages/utils/src/logger/winston.ts:
child loggers scoped by module name with a uniform format. Built on stdlib
``logging`` instead of winston.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname)-5s [%(name)s]%(trace)s %(message)s"
_configured = False


class _TraceContextFilter(logging.Filter):
    """Inject the active lifecycle trace-id (observability.spans) into
    every record, so a slow-slot log line correlates with its
    `/debug/traces` entry. Outside any trace the field renders empty."""

    def filter(self, record: logging.LogRecord) -> bool:
        tid = None
        try:
            from ..observability.spans import current_trace_id

            tid = current_trace_id()
        except ImportError:
            pass  # circular import during startup; logging inside a log
            # filter would recurse, so stay silent and render no trace id
        record.trace = f" [t:{tid[:8]}]" if tid else ""
        return True


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
    handler.addFilter(_TraceContextFilter())
    root = logging.getLogger("lodestar_tpu")
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    root.propagate = False
    _configured = True


def get_logger(module: str, level: int | None = None) -> logging.Logger:
    """Child logger for a module (reference's LogModule enum, e.g. 'chain',
    'network', 'sync' — beacon-node/src/node/nodejs.ts:60-71)."""
    _ensure_configured()
    logger = logging.getLogger(f"lodestar_tpu.{module}")
    if level is not None:
        logger.setLevel(level)
    return logger


class RateLimitedLogger:
    """Per-key rate limiter over a logger: failure paths that can fire
    per-dispatch (device fallback, breaker rejections) must not turn a
    degraded hour into a gigabyte of identical lines. Suppressed calls
    are counted and the count is prepended to the next emitted line."""

    def __init__(self, logger: logging.Logger, interval_s: float = 30.0):
        import threading
        import time as _time

        self._logger = logger
        self._interval = interval_s
        self._last: dict[str, float] = {}
        self._suppressed: dict[str, int] = {}
        self._lock = threading.Lock()
        self._now = _time.monotonic

    def log(self, level: int, key: str, msg: str, *args) -> bool:
        """Emit at most once per `interval_s` per `key`; returns whether
        the line was emitted."""
        now = self._now()
        with self._lock:
            last = self._last.get(key, float("-inf"))
            if now - last < self._interval:
                self._suppressed[key] = self._suppressed.get(key, 0) + 1
                return False
            self._last[key] = now
            skipped, self._suppressed[key] = self._suppressed.get(key, 0), 0
        if skipped:
            msg = f"(+{skipped} suppressed) " + msg
        self._logger.log(level, msg, *args)
        return True

    def warning(self, key: str, msg: str, *args) -> bool:
        return self.log(logging.WARNING, key, msg, *args)

    def error(self, key: str, msg: str, *args) -> bool:
        return self.log(logging.ERROR, key, msg, *args)
