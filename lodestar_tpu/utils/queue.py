"""Bounded async job queue with concurrency control.

Equivalent of /root/reference/packages/beacon-node/src/util/queue/itemQueue.ts
(`JobItemQueue`): a FIFO/LIFO bounded queue that runs an async processor with
a concurrency limit, drops (errors) items beyond ``max_length``, and exposes
metrics hooks. Used by gossip validation queues, the block processor, and
regen — and here also as the batching front-end for TPU BLS dispatch.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Awaitable, Callable, Generic, TypeVar

from .errors import ErrorAborted, LodestarError

T = TypeVar("T")
R = TypeVar("R")


class QueueType(str, Enum):
    FIFO = "FIFO"
    LIFO = "LIFO"


class QueueError(LodestarError):
    pass


@dataclass
class QueueMetrics:
    length: int = 0
    dropped_jobs: int = 0
    job_time_total: float = 0.0
    job_wait_time_total: float = 0.0
    jobs_done: int = 0

    def observe_job(self, wait: float, duration: float) -> None:
        self.jobs_done += 1
        self.job_wait_time_total += wait
        self.job_time_total += duration


@dataclass
class _Item(Generic[T]):
    args: T
    added_at: float
    future: "asyncio.Future[Any]" = field(default=None)  # type: ignore[assignment]


class JobItemQueue(Generic[T, R]):
    """Run ``process(item)`` for pushed items with bounded queue + concurrency.

    Reference semantics (itemQueue.ts:11): if the queue is full the *oldest*
    pending item is dropped for LIFO, the new item is rejected for FIFO.
    """

    def __init__(
        self,
        process: Callable[[T], Awaitable[R]],
        max_length: int = 1024,
        max_concurrency: int = 1,
        queue_type: QueueType = QueueType.FIFO,
        yield_every_ms: float = 50.0,
        name: str = "queue",
    ):
        self._process = process
        self.max_length = max_length
        self.max_concurrency = max_concurrency
        self.queue_type = queue_type
        self.yield_every_ms = yield_every_ms
        self.name = name
        self.metrics = QueueMetrics()
        self._items: deque[_Item[T]] = deque()
        self._running = 0
        self._closed = False

    def __len__(self) -> int:
        return len(self._items)

    async def push(self, args: T) -> R:
        """Enqueue and await the processed result."""
        if self._closed:
            raise ErrorAborted(f"queue {self.name} closed")
        if len(self._items) >= self.max_length:
            self.metrics.dropped_jobs += 1
            if self.queue_type is QueueType.LIFO:
                # Drop the oldest pending job to make room (reference drops
                # from the tail end for LIFO queues).
                dropped = self._items.popleft()
                if not dropped.future.done():
                    dropped.future.set_exception(
                        QueueError({"code": "QUEUE_MAX_LENGTH", "queue": self.name})
                    )
            else:
                raise QueueError({"code": "QUEUE_MAX_LENGTH", "queue": self.name})

        item: _Item[T] = _Item(args=args, added_at=time.monotonic())
        item.future = asyncio.get_running_loop().create_future()
        self._items.append(item)
        self.metrics.length = len(self._items)
        self._maybe_spawn()
        return await item.future

    def _maybe_spawn(self) -> None:
        while self._running < self.max_concurrency and self._items:
            item = self._items.pop() if self.queue_type is QueueType.LIFO else self._items.popleft()
            self._running += 1
            asyncio.get_running_loop().create_task(self._run(item))

    async def _run(self, item: _Item[T]) -> None:
        start = time.monotonic()
        wait = start - item.added_at
        try:
            result = await self._process(item.args)
            if not item.future.done():
                item.future.set_result(result)
        except Exception as e:  # noqa: BLE001 — propagate to caller's future
            if not item.future.done():
                item.future.set_exception(e)
        finally:
            self.metrics.observe_job(wait, time.monotonic() - start)
            self._running -= 1
            self.metrics.length = len(self._items)
            self._maybe_spawn()

    def close(self) -> None:
        self._closed = True
        while self._items:
            item = self._items.popleft()
            if not item.future.done():
                item.future.set_exception(ErrorAborted(f"queue {self.name} closed"))
