"""Typed registry of every ``LODESTAR_TPU_*`` environment variable.

Before this module existed, ~30 knobs were read ad-hoc with `os.getenv`
scattered across the tree — three different truthiness conventions, no
single place to learn a knob exists, and nothing stopping a typo'd name
from silently reading the default forever. Every runtime read now goes
through the typed accessors below; the graftlint `env-registry` rule
(tools/lint) fails tier-1 on any raw ``os.getenv("LODESTAR_TPU_*")``
outside this file, and `tools/gen_config_docs.py` renders the registry
into `docs/configuration.md` (drift-checked in tier-1).

Conventions the registry enforces:

- **bool**: set values parse case-insensitively; ``0 / off / false / no``
  and the empty string are False, anything else is True. Unset returns
  the registered default. (This replaces the three historical idioms
  ``== "1"``, ``!= "0"`` and ``not in ("0", "off", "false")``.)
- **int / float**: unparseable or empty values fall back to the
  registered default rather than raising — a malformed knob must never
  take down a serving node (the pre-existing `_env_float` contract).
- **str**: the raw string when set (even empty), else the default.

Reading an UNREGISTERED name raises ``KeyError`` immediately: that is a
programming error, and the lint rule catches it statically as well.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = [
    "EnvVar", "REGISTRY", "env_str", "env_int", "env_float", "env_bool",
    "raw", "is_set",
]

_FALSE_VALUES = ("0", "off", "false", "no", "")


@dataclass(frozen=True)
class EnvVar:
    name: str
    type: str  # "str" | "int" | "float" | "bool"
    default: object
    doc: str  # one line; rendered into docs/configuration.md


REGISTRY: dict[str, EnvVar] = {}


def _register(name: str, type_: str, default, doc: str) -> None:
    REGISTRY[name] = EnvVar(name, type_, default, doc)


# --- kernel / math-backend selection (ops/) -------------------------------
_register("LODESTAR_TPU_LEGACY_FP", "bool", False,
          "Force the word-serial scan Fp multiplier (the CPU-backend "
          "default) instead of the dispatcher's pick.")
_register("LODESTAR_TPU_MXU_MUL", "bool", False,
          "Route Fp multiplication through the bf16 MXU matmul kernel.")
_register("LODESTAR_TPU_PALLAS_MUL", "bool", False,
          "Route Fp multiplication through the VMEM-resident Pallas "
          "kernel (ops/pallas_fp.py).")
_register("LODESTAR_TPU_PALLAS_MXU", "bool", False,
          "Route Fp multiplication through the Pallas MXU tile kernel "
          "(ops/pallas_mxu.py).")
_register("LODESTAR_TPU_PADCONV_FP", "bool", False,
          "Route Fp multiplication through the padded-convolution "
          "multiplier.")
_register("LODESTAR_TPU_PALLAS_MIN_LANES", "int", None,
          "Minimum batch lanes before the Pallas MXU kernel beats the "
          "default path; smaller batches use the fallback multiplier.")
_register("LODESTAR_TPU_PALLAS_MILLER", "str", "auto",
          "VMEM-resident Pallas Miller-loop tower kernel "
          "(ops/pallas_tower.py): auto (on when the backend lowers "
          "Pallas, i.e. TPU), 1/on (forced; interpreter mode off-TPU), "
          "0/off.")
_register("LODESTAR_TPU_PALLAS_PAIRING", "str", "auto",
          "VMEM-resident fused FULL-pairing Pallas kernel (Miller loop + "
          "batched final exponentiation in one tile, ops/pallas_tower.py): "
          "auto (on when the backend lowers Pallas, i.e. TPU), 1/on "
          "(forced; interpreter mode off-TPU), 0/off. Routes the per-set "
          "verdict kernel's whole pairing tail.")
_register("LODESTAR_TPU_FINAL_EXP_KS_CARRY", "bool", False,
          "Route the final-exp hard part's carries through the scan-free "
          "Kogge-Stone form (fp.ks_carry) inside the batched final-exp "
          "kernel only; default stays carry_scan — measured 3.5x compile "
          "and ~7.5x runtime WORSE on CPU (docs/architecture.md); the "
          "knob stays for TPU re-measurement.")
_register("LODESTAR_TPU_LAZY_FP2", "bool", True,
          "Lazy-reduction Fp2 multiplication (3 reductions -> 2); off "
          "restores the 3-full-multiply form.")
_register("LODESTAR_TPU_LAZY_FP2_MAX_ELEMS", "int", 1 << 24,
          "Element-count ceiling above which lazy Fp2 falls back to the "
          "narrow form (lazy doubles live intermediate width).")

# --- verifier / serving path ---------------------------------------------
_register("LODESTAR_TPU_DEVICE_DECOMPRESS", "bool", True,
          "On-device G2 signature decompression (default-on); off keeps "
          "the C-tier host marshal.")
_register("LODESTAR_TPU_PK_CACHE_MAX", "int", 1 << 21,
          "Bounded FIFO pubkey-decompression cache entries (~550 B "
          "each); below the active validator set it thrashes to 0% "
          "hits.")
_register("LODESTAR_TPU_EPOCH_TABLE", "bool", True,
          "Epoch-scoped device-resident pubkey table "
          "(parallel/epoch_table.py): decompressed G1 limbs for the "
          "active validator set, populated at epoch transition; off "
          "keeps the per-dispatch FIFO pubkey cache only.")
_register("LODESTAR_TPU_EPOCH_TABLE_EPOCHS", "int", 2,
          "Epoch entries the pubkey table retains (LRU rotation); the "
          "reference keeps current+next EpochContext the same way.")
_register("LODESTAR_TPU_EPOCH_TABLE_MAX_ROWS", "int", 1 << 21,
          "Row cap per epoch entry of the device pubkey table (~256 B "
          "of limb data each); populate calls beyond it are truncated "
          "and counted as evictions.")
_register("LODESTAR_TPU_H2C_DEDUP", "bool", True,
          "Hash-to-curve dedup across coalesced aggregates at the lane "
          "dispatcher: duplicate messages in one merged batch pay one "
          "hash_to_g2 (pre-warmed through the h2c cache); off restores "
          "per-request hashing.")
_register("LODESTAR_TPU_MARSHAL_THREADS", "int", None,
          "Host marshal thread-pool size override (default: cpu_count; "
          "0 disables the pool).")
_register("LODESTAR_TPU_MESH", "str", "auto",
          "Mesh serving policy: auto (multi-chip hardware only), force "
          "(any >1-device backend, incl. virtual CPU meshes), off.")
_register("LODESTAR_TPU_FLEET", "str", None,
          "Fleet (multi-host) serving: unset/off = single host; "
          "'host:port' names the jax.distributed coordinator (real "
          "multi-process fleet); 'emulate' splits the local devices "
          "into virtual hosts (CPU parity dryruns). Engages only when "
          "mesh serving itself is enabled (LODESTAR_TPU_MESH).")
_register("LODESTAR_TPU_FLEET_HOSTS", "int", 2,
          "Fleet host count: jax.distributed process count "
          "(distributed mode) or virtual-host count (emulate mode).")
_register("LODESTAR_TPU_FLEET_RANK", "int", 0,
          "This process's host rank in [0, FLEET_HOSTS); rank 0 owns "
          "the root tail of two-level dispatches.")
_register("LODESTAR_TPU_FLEET_INGEST", "bool", True,
          "When the fleet is active, drop gossip attestations whose "
          "subnet the FleetRouter assigns to another host (each host's "
          "lanes see only its slice); off validates everything locally.")
_register("LODESTAR_TPU_WAITER_TIMEOUT", "float", 300.0,
          "Seconds a buffered-verifier waiter blocks on the flush "
          "thread before escalating and failing the call.")
_register("LODESTAR_TPU_LANE_WORKERS", "int", 2,
          "Lane-dispatcher worker threads; 2 double-buffers (host "
          "marshal of batch N+1 overlaps device compute of batch N).")
_register("LODESTAR_TPU_LANE_MAX_COALESCE", "int", 512,
          "Max signature sets coalesced into one lane-dispatcher device "
          "batch (continuous batching merges in-flight requests up to "
          "this).")
_register("LODESTAR_TPU_LANE_PENDING_CAP", "int", 4096,
          "Global queued-set cap across all lanes; admission over it "
          "evicts lowest-priority queued work (never blocks) or sheds "
          "the incoming request.")
_register("LODESTAR_TPU_LANE_CAP_ATTESTATION", "int", 2048,
          "Queued-set cap for the attestation lane (shed first under "
          "flood); 0 disables the cap.")
_register("LODESTAR_TPU_LANE_CAP_AGGREGATE", "int", 1024,
          "Queued-set cap for the aggregate-and-proof lane; 0 disables "
          "the cap.")
_register("LODESTAR_TPU_LANE_CAP_SYNC_COMMITTEE", "int", 512,
          "Queued-set cap for the sync-committee lane; 0 disables the "
          "cap. The block lane is never capped or shed.")
_register("LODESTAR_TPU_IMPORT_WAIT_TIMEOUT", "float", 300.0,
          "Seconds the block-import path waits on a verification/"
          "payload future before escalating (counted in "
          "lodestar_chain_blocking_wait_timeouts_total).")
_register("LODESTAR_TPU_PRESET", "str", "mainnet",
          "Active consensus preset (mainnet | minimal).")

# --- supervisor / failure policy (chain/supervisor.py) --------------------
_register("LODESTAR_TPU_DEVICE_DEADLINE", "float", 120.0,
          "Per-dispatch device deadline in seconds; a blown deadline "
          "abandons the wedged worker and falls back.")
_register("LODESTAR_TPU_DEVICE_RETRIES", "float", 1.0,
          "Extra attempts for raised transient device errors (deadline "
          "blowouts are never retried).")
_register("LODESTAR_TPU_BREAKER_THRESHOLD", "float", 3.0,
          "Consecutive device failures that open the circuit breaker.")
_register("LODESTAR_TPU_BREAKER_COOLDOWN", "float", 30.0,
          "Seconds between canary probes while the breaker is open.")
_register("LODESTAR_TPU_AUDIT_NEGATIVE", "bool", True,
          "Re-check device-negative verdicts on the CPU oracle "
          "(corruption can fake a False but not the identity element).")

# --- observability --------------------------------------------------------
_register("LODESTAR_TPU_PROFILE", "str", None,
          "Directory for the XLA profiler trace; set = auto-start on "
          "first device dispatch.")
_register("LODESTAR_TPU_TRACE_LIFECYCLE", "bool", True,
          "Gossip-wire -> head-update lifecycle span tracing "
          "(observability/spans.py); off = shared-singleton zero-cost "
          "mode.")
_register("LODESTAR_TPU_PERSIST_INVALID", "str", None,
          "Directory to dump SSZ objects that failed import (debugging; "
          "unset = disabled).")
_register("LODESTAR_TPU_FLIGHT_RECORDER_SIZE", "int", 256,
          "Bounded event ring of the black-box flight recorder "
          "(observability/flight_recorder.py); dumped into bench "
          "documents and /debug/compiles.")
_register("LODESTAR_TPU_SLO_RULES", "str", None,
          "Path to the SLO objectives file (observability/slo.py); "
          "unset = the committed dashboards/slo_rules.json.")
_register("LODESTAR_TPU_SLO_POKE_S", "float", 1.0,
          "Min seconds between event-driven SLO re-evaluations "
          "(slo.poke() from the supervisor failure path); 0 = every "
          "poke evaluates.")
_register("LODESTAR_TPU_DEVICE_LEDGER_MEM_SAMPLE_S", "float", 10.0,
          "Min seconds between jax device-memory samples in the device "
          "ledger (observability/device_ledger.py); 0 = sampler off.")

# --- compile containment --------------------------------------------------
_register("LODESTAR_TPU_COMPILE_CACHE", "str", None,
          "Persistent XLA compile-cache dir; 0/off/none disables "
          "persistence; unset = repo-local .jax_cache.")
_register("LODESTAR_TPU_CACHE_LIMIT_GB", "float", 2.0,
          "Shared LRU byte bound across the persistent compile cache "
          "AND the AOT executable store "
          "(tools/prune_compile_cache.py).")
_register("LODESTAR_TPU_AOT_STORE", "str", None,
          "Directory of serialized AOT-compiled executables "
          "(ops/aot_store.py); 0/off/none disables the store entirely; "
          "unset = repo-local .aot_store.")
_register("LODESTAR_TPU_AOT_LOAD", "bool", True,
          "Load persisted AOT executables before compiling (restart "
          "without XLA in the loop); off forces normal JIT even with a "
          "populated store.")
_register("LODESTAR_TPU_AOT_EXPORT", "bool", False,
          "Producer mode: first-dispatch compiles go through "
          "lower().compile() and the executable is serialized into the "
          "AOT store (tools/warmup.py --aot-export sets this).")

# --- bench / tools / tests ------------------------------------------------
_register("LODESTAR_TPU_BENCH_PHASE_DEADLINE", "float", 600.0,
          "Per-phase SIGALRM deadline in bench.py; a blown phase is "
          "skipped, not fatal.")
_register("LODESTAR_TPU_BENCH_GLOBAL_DEADLINE", "float", 840.0,
          "Bench watchdog-thread deadline; fires a partial flush marked "
          "timed_out and exits 124.")
_register("LODESTAR_TPU_DRYRUN_PLATFORM", "str", "cpu",
          "Platform for __graft_entry__ dryrun entry points (axon = "
          "real devices).")
_register("LODESTAR_TPU_FAULTS", "str", None,
          "Fault-injection plan armed at import, e.g. "
          "'exception,latency:0.05' (testing/faults.py).")
_register("LODESTAR_TPU_TEST_PLATFORM", "str", "cpu",
          "JAX platform for the test suite (tests/conftest.py); axon = "
          "real hardware.")
_register("LODESTAR_TPU_PERF", "bool", False,
          "Enable the perf assertion suites (tests/test_perf_suites.py).")


def _var(name: str) -> EnvVar:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not a registered LODESTAR_TPU env var; declare it "
            "in lodestar_tpu/utils/env.py (the registry feeds "
            "docs/configuration.md and the env-registry lint rule)"
        ) from None


def is_set(name: str) -> bool:
    """True when the (registered) variable is present in the process env."""
    return os.environ.get(_var(name).name) is not None


def raw(name: str) -> str | None:
    """The raw string value, or None when unset. For the few knobs with
    site-specific sentinel parsing (e.g. LODESTAR_TPU_COMPILE_CACHE's
    0/off/none disable values) — prefer the typed accessors."""
    return os.environ.get(_var(name).name)


def env_str(name: str) -> str | None:
    var = _var(name)
    value = os.environ.get(name)
    return value if value is not None else var.default


def env_int(name: str) -> int | None:
    var = _var(name)
    value = os.environ.get(name)
    if value is None:
        return var.default
    try:
        return int(value)
    except ValueError:
        return var.default


def env_float(name: str) -> float | None:
    var = _var(name)
    value = os.environ.get(name)
    if value is None:
        return var.default
    try:
        return float(value)
    except ValueError:
        return var.default


def env_bool(name: str) -> bool:
    var = _var(name)
    value = os.environ.get(name)
    if value is None:
        return bool(var.default)
    return value.strip().lower() not in _FALSE_VALUES
