"""Byte helpers (hex, int encodings).

Equivalent of /root/reference/packages/utils/src/bytes.ts: little/big-endian
int <-> bytes conversions used throughout SSZ and the p2p layer. Consensus
integers are little-endian uint64.
"""

from __future__ import annotations


def to_hex(data: bytes) -> str:
    return "0x" + data.hex()


def from_hex(hex_str: str) -> bytes:
    return bytes.fromhex(hex_str[2:] if hex_str.startswith("0x") else hex_str)


def int_to_bytes(value: int, length: int, byteorder: str = "little") -> bytes:
    return int(value).to_bytes(length, byteorder)  # type: ignore[arg-type]


def bytes_to_int(data: bytes, byteorder: str = "little") -> int:
    return int.from_bytes(data, byteorder)  # type: ignore[arg-type]


def uint64_to_bytes(value: int) -> bytes:
    return int(value).to_bytes(8, "little")


def bytes32_rjust(data: bytes) -> bytes:
    """Right-pad to 32 bytes (SSZ chunk padding)."""
    if len(data) > 32:
        raise ValueError(f"data longer than 32 bytes: {len(data)}")
    return data + b"\x00" * (32 - len(data))


def xor_bytes(a: bytes, b: bytes) -> bytes:
    if len(a) != len(b):
        raise ValueError("xor length mismatch")
    return bytes(x ^ y for x, y in zip(a, b))
