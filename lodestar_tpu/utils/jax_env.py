"""JAX platform/mesh environment setup (shared by tests and driver entry).

Forcing a platform must happen BEFORE jax initializes its backends: the
ambient environment may point JAX_PLATFORMS at a single-chip TPU tunnel
that can neither provide n devices nor tolerate a second client claim.
These helpers own the process-global env (JAX_PLATFORMS, XLA_FLAGS, live
jax config) — callers that need the ambient platform afterwards must run
in a fresh process.
"""

from __future__ import annotations

import os
import re

__all__ = [
    "force_platform", "enable_compile_cache", "default_cache_dir",
    "runtime_info",
]


def runtime_info(enumerate_devices: bool = True) -> dict:
    """The process runtime identity for the `lodestar_tpu_build_info`
    gauge and the bench document's `runtime_info` block: jax/jaxlib
    version, backend, device kind/count, mesh divisor, compile-cache dir.

    `enumerate_devices=False` skips `jax.devices()` — backend
    initialization is a process-global side effect a CPU-only node
    (opts.tpu_verifier off) must not pay just to label a gauge. All
    values are strings (they ride Prometheus labels)."""
    info = {
        "jax": "none",
        "jaxlib": "none",
        "backend": "none",
        "device_kind": "none",
        "device_count": "0",
        "mesh_divisor": "0",
        "compile_cache": "unset",
    }
    try:
        import jax
    except ImportError:
        return info
    info["jax"] = getattr(jax, "__version__", "unknown")
    try:
        import jaxlib

        info["jaxlib"] = getattr(jaxlib, "__version__", "unknown")
    except ImportError:
        pass
    cache = getattr(jax.config, "jax_compilation_cache_dir", None)
    if cache:
        info["compile_cache"] = cache
    if not enumerate_devices:
        return info
    try:
        devices = jax.devices()
    except RuntimeError:
        return info  # backend init failed; the static identity still helps
    info["backend"] = devices[0].platform
    info["device_kind"] = getattr(
        devices[0], "device_kind", devices[0].platform
    )
    info["device_count"] = str(len(devices))
    # parallel.mesh is jax-free at import (unlike parallel.sharded)
    from ..parallel.mesh import mesh_divisor

    info["mesh_divisor"] = str(mesh_divisor(len(devices)))
    return info


def default_cache_dir() -> str:
    """The repo-local `.jax_cache` every tool/bench/test shares."""
    return os.path.abspath(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "..", "..", ".jax_cache")
    )


def enable_compile_cache(cache_dir: str | None = None) -> str | None:
    """Point jax at the persistent XLA compilation cache — the compile-
    containment knob (VERDICT r5 weak #1/#7: cold compiles killed the
    driver's bench run; the deep pairing kernels take 7-13 minutes each
    on the CPU backend).

    Env-guarded: LODESTAR_TPU_COMPILE_CACHE=<dir> overrides the location;
    =0/off/none disables persistence entirely (e.g. a read-only deploy
    image). Default location is the repo-local `.jax_cache` shared by
    node.py, bench.py, tools/warmup.py and the test suite, so one
    `tools/warmup.py` pass serves them all. Returns the active directory,
    or None when disabled. Safe to call before or after backend init
    (`jax_compilation_cache_dir` is a runtime config)."""
    from .env import raw

    env = raw("LODESTAR_TPU_COMPILE_CACHE")
    if env is not None and env.strip().lower() in ("0", "off", "none", ""):
        return None
    cache = env or cache_dir or default_cache_dir()
    import jax

    jax.config.update("jax_compilation_cache_dir", cache)
    return cache


def force_platform(platform: str, n_devices: int | None = None) -> None:
    """Force the JAX platform (and, for cpu, a virtual device count).

    Safe no-matter-what only before backend initialization; afterwards the
    env edits are no-ops, so we fail loudly if jax already has backends
    with the wrong shape rather than let callers mis-measure.
    """
    os.environ["JAX_PLATFORMS"] = platform
    if platform == "cpu" and n_devices is not None:
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        xla_flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in xla_flags:
            xla_flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, xla_flags
            )
        else:
            xla_flags = (xla_flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = xla_flags

    # A site hook may have imported jax already, latching the ambient
    # platform; updating the live config — not just the env var — makes
    # backends() initialize only the selected platform (still lazy here).
    import jax

    jax.config.update("jax_platforms", platform)

    if n_devices is not None:
        devices = jax.devices()  # initializes the backend now
        if len(devices) < n_devices:
            raise RuntimeError(
                f"{platform} backend has {len(devices)} devices, need "
                f"{n_devices}. If another platform was already initialized "
                "in this process, re-run in a fresh process."
            )
