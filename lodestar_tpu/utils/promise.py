"""sleep / retry / timeout helpers.

Equivalent of /root/reference/packages/utils/src/{sleep,retry,timeout}.ts.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, TypeVar

from .errors import ErrorAborted, TimeoutError_

T = TypeVar("T")


async def sleep(seconds: float, abort_event: asyncio.Event | None = None) -> None:
    """Sleep, waking early (with ErrorAborted) if the abort event fires."""
    if abort_event is None:
        await asyncio.sleep(seconds)
        return
    if abort_event.is_set():
        raise ErrorAborted()
    try:
        await asyncio.wait_for(abort_event.wait(), timeout=seconds)
        raise ErrorAborted()
    except asyncio.TimeoutError:
        return


async def with_timeout(aw: Awaitable[T], timeout: float) -> T:
    try:
        return await asyncio.wait_for(aw, timeout=timeout)
    except asyncio.TimeoutError as e:
        raise TimeoutError_() from e


async def retry(
    fn: Callable[[], Awaitable[T]],
    retries: int = 3,
    retry_delay: float = 0.0,
    should_retry: Callable[[Exception], bool] | None = None,
) -> T:
    last_error: Exception | None = None
    for attempt in range(retries):
        try:
            return await fn()
        except Exception as e:  # noqa: BLE001
            last_error = e
            if should_retry is not None and not should_retry(e):
                break
            if attempt < retries - 1 and retry_delay > 0:
                await asyncio.sleep(retry_delay)
    assert last_error is not None
    raise last_error
