"""Shared blocking JSON-over-HTTP request helper.

One transport helper for every REST-ish client in the tree (beacon API,
builder relay, external signer) so timeout/TLS/error-shape fixes land in
one place. The reference splits these across cross-fetch wrappers; here a
single function serves all blocking clients.
"""

from __future__ import annotations

import http.client
import json


def json_http_request(
    host: str,
    port: int,
    method: str,
    path: str,
    body=None,
    timeout: float = 10.0,
    error_cls: type[Exception] = RuntimeError,
    retries: int = 0,
    retry_policy=None,
):
    """Issue one request, decode the JSON reply, raise `error_cls` on >=400.

    `retries` > 0 (or an explicit `retry_policy`) re-issues the request
    through `utils.retry` on TRANSPORT failures only (socket/protocol
    errors) — never on an HTTP error status: the server answered, and
    re-sending a non-idempotent request it already processed is the
    caller's decision, not the transport helper's."""

    def _once():
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status >= 400:
                raise error_cls(f"{resp.status}: {raw[:200]!r}")
            return json.loads(raw) if raw else None
        finally:
            conn.close()

    if retries <= 0 and retry_policy is None:
        return _once()
    from .retry import RetryPolicy, retry_call, transient_http

    policy = retry_policy or RetryPolicy(
        max_attempts=1 + retries, base_delay_s=0.2, retryable=transient_http
    )
    return retry_call(_once, policy=policy)
