"""Shared blocking JSON-over-HTTP request helper.

One transport helper for every REST-ish client in the tree (beacon API,
builder relay, external signer) so timeout/TLS/error-shape fixes land in
one place. The reference splits these across cross-fetch wrappers; here a
single function serves all blocking clients.
"""

from __future__ import annotations

import http.client
import json


def json_http_request(
    host: str,
    port: int,
    method: str,
    path: str,
    body=None,
    timeout: float = 10.0,
    error_cls: type[Exception] = RuntimeError,
):
    """Issue one request, decode the JSON reply, raise `error_cls` on >=400."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        raw = resp.read()
        if resp.status >= 400:
            raise error_cls(f"{resp.status}: {raw[:200]!r}")
        return json.loads(raw) if raw else None
    finally:
        conn.close()
