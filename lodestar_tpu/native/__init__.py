"""Native tier loader: C codecs/hashes with pure-Python fallbacks.

Replaces the reference's native npm deps (SURVEY.md §2.3): as-sha256 →
`sha256`/`sha256_level`; xxhash-wasm → `xxh64`; snappyjs → snappy codec.
The extension builds lazily on first import (gcc via setuptools); when a
toolchain is unavailable the hashlib/pure-Python fallbacks keep every API
working (snappy falls back to a Python port of the same block format).

`HAVE_NATIVE` reports which tier is active; `install_ssz_backend()` swaps
the SSZ hasher to the batched native level function.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys

_HERE = os.path.dirname(__file__)
HAVE_NATIVE = False
_mod = None


def _try_import():
    global _mod, HAVE_NATIVE
    try:
        from . import _lodestar_native as m  # type: ignore[attr-defined]

        _mod, HAVE_NATIVE = m, True
        return True
    except ImportError:
        return False


_STAMP = os.path.join(_HERE, "_build_stamp.txt")


def _src_digest() -> str:
    """Content hash of every C source/header (order-independent of mtime)."""
    import glob

    h = hashlib.sha256()
    for path in sorted(
        glob.glob(os.path.join(_HERE, "src", "*.c"))
        + glob.glob(os.path.join(_HERE, "src", "*.h"))
    ):
        h.update(os.path.basename(path).encode())
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _build() -> bool:
    """Compile the extension in-place with cc (no pip required).

    Writes a content-hash stamp next to the .so on success; the stamp is
    committed with the .so so fresh checkouts are not misread as stale
    (file mtimes after `git clone` are meaningless).
    """
    import sysconfig

    src = [os.path.join(_HERE, "src", f) for f in (
        "module.c", "sha256.c", "xxhash64.c", "snappy_codec.c", "bls12.c",
        "kvstore.c"
    )]
    ext_suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_HERE, "_lodestar_native" + ext_suffix)
    include = sysconfig.get_paths()["include"]
    cmd = [
        os.environ.get("CC", "cc"), "-O3", "-funroll-loops", "-shared", "-fPIC",
        f"-I{include}", *src, "-o", out,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        ok = proc.returncode == 0 and os.path.exists(out)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if ok:
        try:
            with open(_STAMP, "w") as f:
                f.write(_src_digest() + "\n")
        except OSError:
            pass
    return ok


def _is_stale() -> bool:
    """True when the C sources differ from what the extension was built
    from (content hash vs the build stamp).

    Must be checked BEFORE the first import: CPython cannot reload a C
    extension in-process, so a stale .so must be rebuilt first.
    """
    import sysconfig

    ext_suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    ext = os.path.join(_HERE, "_lodestar_native" + ext_suffix)
    if not os.path.exists(ext):
        return True
    try:
        with open(_STAMP) as f:
            stamp = f.read().strip()
    except OSError:
        return True  # no stamp: unknown provenance, rebuild to be safe
    return stamp != _src_digest()


def _load() -> None:
    """Build (at most once) then import the extension.

    The stale check runs BEFORE the first import — CPython cannot reload
    a C extension in-process, so a stale .so must be rebuilt first. If a
    rebuild of stale sources fails but an old .so exists, we refuse to
    import it: silently running pre-edit native code in a consensus
    client is worse than falling back to the (correct, slow) pure-Python
    tier, and the warning tells the operator which one they got.
    """
    stale = _is_stale()
    built = _build() if stale else False
    if stale and not built:
        import warnings

        warnings.warn(
            "lodestar_tpu.native: C sources changed (or no extension was "
            "built) and recompilation failed; using pure-Python fallbacks",
            RuntimeWarning,
            stacklevel=2,
        )
        return  # do NOT import a stale .so
    if not _try_import() and not built:
        # up-to-date .so failed to load (e.g. built for another platform):
        # one rebuild attempt, then fall back silently to pure Python
        if _build():
            _try_import()


_load()

HAVE_NATIVE_BLS = HAVE_NATIVE and hasattr(_mod, "bls_marshal_sets")


# --- public API (native or fallback) ---------------------------------------

def sha256(data: bytes) -> bytes:
    if HAVE_NATIVE:
        return _mod.sha256(data)
    return hashlib.sha256(data).digest()


def sha256_level(data: bytes) -> bytes:
    """N×64 bytes → N×32 bytes (one merkle level in one call)."""
    if HAVE_NATIVE:
        return _mod.sha256_level(data)
    out = bytearray(len(data) // 2)
    for i in range(0, len(data), 64):
        out[i // 2 : i // 2 + 32] = hashlib.sha256(data[i : i + 64]).digest()
    return bytes(out)


def xxh64(data: bytes, seed: int = 0) -> int:
    if HAVE_NATIVE:
        return _mod.xxh64(data, seed)
    return _xxh64_py(data, seed)


def snappy_compress(data: bytes) -> bytes:
    if HAVE_NATIVE:
        return _mod.snappy_compress(data)
    return _snappy_compress_py(data)


def snappy_uncompress(data: bytes) -> bytes:
    if HAVE_NATIVE:
        return _mod.snappy_uncompress(data)
    return _snappy_uncompress_py(data)


def install_ssz_backend() -> None:
    """Route SSZ merkleization through the batched native level hasher."""
    from ..ssz import hashing

    hashing.set_hash_backend(sha256_level)


# --- native BLS12-381 marshalling tier (bls12.c) -----------------------------
#
# Device-limb outputs (int32, 32x12-bit Montgomery — ops/limbs.py layout).
# No Python fallbacks here: callers check HAVE_NATIVE_BLS and route through
# the big-int oracle otherwise (parallel/verifier._marshal).

def bls_g1_decompress(data: bytes, check_subgroup: bool = True):
    """48B compressed G1 → (rc, np (2,32) int32 x/y limbs).
    rc: 0 ok, 1 infinity, -1 malformed, -2 off-curve, -3 subgroup."""
    import numpy as np

    rc, buf = _mod.bls_g1_decompress(data, int(check_subgroup))
    return rc, np.frombuffer(buf, np.int32).reshape(2, 32)


def bls_g2_decompress(data: bytes, check_subgroup: bool = True):
    """96B compressed G2 → (rc, np (2,2,32) int32 x/y limbs)."""
    import numpy as np

    rc, buf = _mod.bls_g2_decompress(data, int(check_subgroup))
    return rc, np.frombuffer(buf, np.int32).reshape(2, 2, 32)


def bls_hash_to_g2(msg: bytes, dst: bytes):
    """RFC 9380 hash_to_curve → (rc, np (2,2,32) int32 x/y limbs)."""
    import numpy as np

    rc, buf = _mod.bls_hash_to_g2(msg, dst)
    return rc, np.frombuffer(buf, np.int32).reshape(2, 2, 32)


def bls_sign(sk_be: bytes, msg: bytes, dst: bytes):
    """[sk]·H(msg) → (rc, 96B compressed G2 signature)."""
    return _mod.bls_sign(sk_be, msg, dst)


def bls_verify_sets(pks: bytes, msgs: list[bytes], sigs: bytes, dst: bytes,
                    h_x=None, h_y=None):
    """Full CPU verification of n signature sets: decompress + subgroup
    checks + hash-to-curve + two pairings per set, in C with the GIL
    released (the production fallback tier — reference: blst C verify
    behind maybeBatch.ts). `h_x`/`h_y` ((n, 2, 32) int32 device limbs):
    precomputed H(m) from the signing-root cache, skipping per-set
    hashing. Returns a list[bool] of per-set verdicts."""
    import numpy as np

    lens = b"".join(len(m).to_bytes(8, "little") for m in msgs)
    if h_x is not None and h_y is not None:
        ok = _mod.bls_verify_sets(
            pks, b"".join(msgs), lens, sigs, dst,
            np.ascontiguousarray(h_x, np.int32).tobytes(),
            np.ascontiguousarray(h_y, np.int32).tobytes(),
        )
    else:
        ok = _mod.bls_verify_sets(pks, b"".join(msgs), lens, sigs, dst)
    return [bool(b) for b in ok]


def bls_g1_aggregate(pks: bytes, check_each: bool = True):
    """N×48B pubkeys → (rc, np (2,32) limbs of the affine sum).
    rc 1 = aggregate is infinity."""
    import numpy as np

    rc, buf = _mod.bls_g1_aggregate(pks, int(check_each))
    return rc, np.frombuffer(buf, np.int32).reshape(2, 32)


def bls_marshal_sets(pks: bytes, msgs: bytes, sigs: bytes, dst: bytes,
                     check_pk_subgroup: bool = False,
                     check_sig_subgroup: bool = True,
                     do_hash: bool = True, do_pk: bool = True):
    """Batch-marshal n signature sets straight into device arrays.

    pks n×48B, msgs n×32B signing roots, sigs n×96B →
    (pk_x (n,32), pk_y (n,32), msg_x (n,2,32), msg_y, sig_x, sig_y, ok (n,) bool)

    Pubkey subgroup checks default OFF: pubkeys reaching the verifier were
    KeyValidate'd at construction (PublicKey.from_bytes) — re-checking per
    batch is the hot-path waste the reference also avoids by trusting its
    pubkey cache (worker.ts deserializes affine without re-checking).
    Signature subgroup checks default ON (sigFromBytes validates).
    do_hash=False skips the per-set hash-to-curve (msg arrays stay zero)
    so callers can fill them from a cache — committee gossip shares
    signing roots, making per-set hashing mostly redundant.
    do_pk=False likewise skips pubkey decompression (pk arrays stay
    zero) for callers holding a pubkey-limb cache — attesters repeat
    across epochs, the reference's pubkey cache exists for this reason.
    """
    import numpy as np

    buf, ok = _mod.bls_marshal_sets(
        pks, msgs, sigs, dst, int(check_pk_subgroup), int(check_sig_subgroup),
        int(do_hash), int(do_pk),
    )
    n = len(ok)
    a = np.frombuffer(buf, np.int32)
    pk_x = a[: n * 32].reshape(n, 32)
    pk_y = a[n * 32 : n * 64].reshape(n, 32)
    msg_x = a[n * 64 : n * 128].reshape(n, 2, 32)
    msg_y = a[n * 128 : n * 192].reshape(n, 2, 32)
    sig_x = a[n * 192 : n * 256].reshape(n, 2, 32)
    sig_y = a[n * 256 : n * 320].reshape(n, 2, 32)
    return pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, np.frombuffer(ok, np.uint8).astype(bool)


# --- pure-Python fallbacks ---------------------------------------------------

_P1, _P2, _P3, _P4, _P5 = (
    0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
    0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5,
)
_M = (1 << 64) - 1


def _rotl(x, r):
    return ((x << r) | (x >> (64 - r))) & _M


def _round(acc, inp):
    return (_rotl((acc + inp * _P2) & _M, 31) * _P1) & _M


def _xxh64_py(data: bytes, seed: int) -> int:
    n = len(data)
    p = 0
    if n >= 32:
        v1, v2, v3, v4 = (
            (seed + _P1 + _P2) & _M, (seed + _P2) & _M, seed, (seed - _P1) & _M
        )
        while p + 32 <= n:
            v1 = _round(v1, int.from_bytes(data[p : p + 8], "little")); p += 8
            v2 = _round(v2, int.from_bytes(data[p : p + 8], "little")); p += 8
            v3 = _round(v3, int.from_bytes(data[p : p + 8], "little")); p += 8
            v4 = _round(v4, int.from_bytes(data[p : p + 8], "little")); p += 8
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _M
        for v in (v1, v2, v3, v4):
            h = ((h ^ _round(0, v)) * _P1 + _P4) & _M
    else:
        h = (seed + _P5) & _M
    h = (h + n) & _M
    while p + 8 <= n:
        h = ((_rotl(h ^ _round(0, int.from_bytes(data[p : p + 8], "little")), 27) * _P1) + _P4) & _M
        p += 8
    if p + 4 <= n:
        h = ((_rotl(h ^ (int.from_bytes(data[p : p + 4], "little") * _P1) & _M, 23) * _P2) + _P3) & _M
        p += 4
    while p < n:
        h = (_rotl(h ^ (data[p] * _P5) & _M, 11) * _P1) & _M
        p += 1
    h ^= h >> 33
    h = (h * _P2) & _M
    h ^= h >> 29
    h = (h * _P3) & _M
    h ^= h >> 32
    return h


def _uvarint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _snappy_compress_py(data: bytes) -> bytes:
    """Valid (all-literal) snappy block stream — correctness fallback."""
    out = bytearray(_uvarint(len(data)))
    i = 0
    while i < len(data):
        chunk = data[i : i + 65536]
        l = len(chunk) - 1
        if l < 60:
            out.append(l << 2)
        else:
            out.append(61 << 2)
            out += l.to_bytes(2, "little")
        out += chunk
        i += len(chunk)
    return bytes(out)


def _snappy_uncompress_py(data: bytes) -> bytes:
    # varint header
    shift = 0
    declared = 0
    i = 0
    while True:
        if i >= len(data):
            raise ValueError("bad snappy header")
        b = data[i]
        i += 1
        declared |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    out = bytearray()
    while i < len(data):
        tag = data[i]
        i += 1
        kind = tag & 3
        if kind == 0:
            l = tag >> 2
            if l >= 60:
                nb = l - 59
                l = int.from_bytes(data[i : i + nb], "little")
                i += nb
            l += 1
            out += data[i : i + l]
            i += l
        else:
            if kind == 1:
                length = 4 + ((tag >> 2) & 7)
                offset = ((tag >> 5) << 8) | data[i]
                i += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[i : i + 2], "little")
                i += 2
            else:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[i : i + 4], "little")
                i += 4
            if offset == 0 or offset > len(out):
                raise ValueError("corrupt snappy stream")
            for _ in range(length):
                out.append(out[-offset])
    if len(out) != declared:
        raise ValueError("snappy length mismatch")
    return bytes(out)
