/* Portable SHA-256 with a batched two-to-one "hash level" API.
 *
 * Native-tier replacement for the reference's WASM `@chainsafe/as-sha256`
 * (SSZ merkleization hot loop — SURVEY.md §2.3): hashLevel() digests N
 * 64-byte parent preimages in one call, amortizing FFI overhead across a
 * whole merkle level. Straightforward FIPS 180-4 implementation, no
 * dependencies.
 */

#include <stdint.h>
#include <string.h>

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

#define ROTR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_compress(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  uint32_t a, b, c, d, e, f, g, h;
  int i;
  for (i = 0; i < 16; i++) {
    w[i] = ((uint32_t)block[i * 4] << 24) | ((uint32_t)block[i * 4 + 1] << 16) |
           ((uint32_t)block[i * 4 + 2] << 8) | (uint32_t)block[i * 4 + 3];
  }
  for (i = 16; i < 64; i++) {
    uint32_t s0 = ROTR(w[i - 15], 7) ^ ROTR(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = ROTR(w[i - 2], 17) ^ ROTR(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  a = state[0]; b = state[1]; c = state[2]; d = state[3];
  e = state[4]; f = state[5]; g = state[6]; h = state[7];
  for (i = 0; i < 64; i++) {
    uint32_t S1 = ROTR(e, 6) ^ ROTR(e, 11) ^ ROTR(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = ROTR(a, 2) ^ ROTR(a, 13) ^ ROTR(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

static const uint32_t IV[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                               0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

void lodestar_sha256(const uint8_t *data, size_t len, uint8_t out[32]) {
  uint32_t state[8];
  uint8_t block[64];
  uint64_t bitlen = (uint64_t)len * 8;
  size_t i, rem;
  memcpy(state, IV, sizeof(IV));
  for (i = 0; i + 64 <= len; i += 64) sha256_compress(state, data + i);
  rem = len - i;
  memset(block, 0, 64);
  memcpy(block, data + i, rem);
  block[rem] = 0x80;
  if (rem >= 56) {
    sha256_compress(state, block);
    memset(block, 0, 64);
  }
  for (i = 0; i < 8; i++) block[56 + i] = (uint8_t)(bitlen >> (56 - 8 * i));
  sha256_compress(state, block);
  for (i = 0; i < 8; i++) {
    out[i * 4] = (uint8_t)(state[i] >> 24);
    out[i * 4 + 1] = (uint8_t)(state[i] >> 16);
    out[i * 4 + 2] = (uint8_t)(state[i] >> 8);
    out[i * 4 + 3] = (uint8_t)state[i];
  }
}

/* N 64-byte inputs -> N 32-byte digests (one merkle level).
 * 64-byte single-block preimages take the fast fixed-padding path. */
void lodestar_sha256_level(const uint8_t *in, size_t n, uint8_t *out) {
  static const uint8_t pad_block[64] = {
      0x80, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
      0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
      0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
      0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0x02, 0x00};
  size_t j;
  for (j = 0; j < n; j++) {
    uint32_t state[8];
    int i;
    memcpy(state, IV, sizeof(IV));
    sha256_compress(state, in + j * 64);
    sha256_compress(state, pad_block);
    for (i = 0; i < 8; i++) {
      uint8_t *o = out + j * 32;
      o[i * 4] = (uint8_t)(state[i] >> 24);
      o[i * 4 + 1] = (uint8_t)(state[i] >> 16);
      o[i * 4 + 2] = (uint8_t)(state[i] >> 8);
      o[i * 4 + 3] = (uint8_t)state[i];
    }
  }
}
