/* XXH64 — native gossip fast-msg-id hash.
 *
 * Replacement for the reference's `xxhash-wasm` (gossip de-dup msg-id,
 * SURVEY.md §2.3; `network/gossip/encoding.ts:12`). Implements the
 * standard XXH64 one-shot algorithm.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#define PRIME64_1 0x9E3779B185EBCA87ULL
#define PRIME64_2 0xC2B2AE3D27D4EB4FULL
#define PRIME64_3 0x165667B19E3779F9ULL
#define PRIME64_4 0x85EBCA77C2B2AE63ULL
#define PRIME64_5 0x27D4EB2F165667C5ULL

static uint64_t rotl64(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

static uint64_t read64(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v; /* little-endian hosts only (x86-64/arm64) */
}

static uint32_t read32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static uint64_t round64(uint64_t acc, uint64_t input) {
  acc += input * PRIME64_2;
  acc = rotl64(acc, 31);
  return acc * PRIME64_1;
}

static uint64_t merge_round(uint64_t acc, uint64_t val) {
  acc ^= round64(0, val);
  return acc * PRIME64_1 + PRIME64_4;
}

uint64_t lodestar_xxh64(const uint8_t *data, size_t len, uint64_t seed) {
  const uint8_t *p = data;
  const uint8_t *end = data + len;
  uint64_t h;

  if (len >= 32) {
    uint64_t v1 = seed + PRIME64_1 + PRIME64_2;
    uint64_t v2 = seed + PRIME64_2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - PRIME64_1;
    const uint8_t *limit = end - 32;
    do {
      v1 = round64(v1, read64(p)); p += 8;
      v2 = round64(v2, read64(p)); p += 8;
      v3 = round64(v3, read64(p)); p += 8;
      v4 = round64(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + PRIME64_5;
  }

  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= round64(0, read64(p));
    h = rotl64(h, 27) * PRIME64_1 + PRIME64_4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * PRIME64_1;
    h = rotl64(h, 23) * PRIME64_2 + PRIME64_3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * PRIME64_5;
    h = rotl64(h, 11) * PRIME64_1;
    p++;
  }

  h ^= h >> 33;
  h *= PRIME64_2;
  h ^= h >> 29;
  h *= PRIME64_3;
  h ^= h >> 32;
  return h;
}
