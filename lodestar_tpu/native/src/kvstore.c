/* Native persistent KV engine — the LevelDB-class storage tier.
 *
 * Replaces the reference's leveldown (C++ LevelDB behind
 * db/src/controller/level.ts — SURVEY.md §2.3) with a from-scratch
 * log-structured engine in the bitcask family:
 *
 *   - values live ON DISK in append-only CRC-framed segment files;
 *     only the key index (key bytes + 16B locator per entry) stays in
 *     memory, so a datadir can exceed process memory (round-1 FileDb
 *     loaded everything into a Python dict — VERDICT weakness #8).
 *   - writes append to the active segment (fsync on batch boundaries),
 *     segments rotate at SEG_LIMIT; replay tolerates torn tails.
 *   - deletes append tombstones; compaction rewrites live records into
 *     fresh segments when the dead ratio crosses a threshold.
 *   - range iteration sorts the in-memory keys (qsort) on demand — the
 *     archive sweep / prefix-scan access pattern of the beacon DB
 *     (Repository.keys_stream) is rare next to point reads.
 *
 * Single-writer, in-process. Thread safety is the binding's job (the
 * Python layer serializes through its own lock).
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#ifdef _WIN32
#error "POSIX only"
#endif
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#define KV_SEG_LIMIT (256u * 1024u * 1024u)
#define KV_MAX_SEGS 4096
#define KV_COMPACT_RATIO 2 /* dead > live * ratio -> compact */
#define KV_COMPACT_MIN (8u * 1024u * 1024u)

/* ---------------- crc32 (IEEE, table-driven) ---------------- */

static uint32_t kv_crc_table[256];
static int kv_crc_init_done = 0;

static void kv_crc_init(void) {
  if (kv_crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    kv_crc_table[i] = c;
  }
  kv_crc_init_done = 1;
}

static uint32_t kv_crc32(uint32_t crc, const uint8_t *buf, size_t len) {
  crc = ~crc;
  for (size_t i = 0; i < len; i++)
    crc = kv_crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

/* ---------------- index ---------------- */

typedef struct {
  uint64_t key_off;  /* into key arena; UINT64_MAX = empty slot */
  uint64_t val_off;  /* value offset within segment */
  uint32_t val_len;
  uint16_t key_len;
  uint16_t file_id;
} kv_slot;

typedef struct kv_store {
  char dir[3072];
  /* hash table, open addressing, power-of-two */
  kv_slot *slots;
  uint64_t cap;
  uint64_t count;
  /* key arena */
  uint8_t *arena;
  uint64_t arena_len, arena_cap;
  uint64_t arena_dead; /* bytes of arena held by overwritten keys */
  /* segments */
  int active_fd;
  uint16_t active_id;
  uint64_t active_size;
  uint64_t live_bytes, dead_bytes;
  /* one-slot read-fd cache for sealed segments (archive sweeps issue
   * thousands of gets against the same sealed file) */
  int read_fd;
  int read_fd_id;
  /* rotation threshold: KV_SEG_LIMIT, or LODESTAR_KV_SEG_LIMIT env
   * override (test hook — lets compaction tests span segments without
   * writing 256 MB) */
  uint64_t seg_limit;
  /* verify record CRCs on get: always during compaction's copy loop
   * (corruption must not propagate into the new generation) and under
   * LODESTAR_KV_PARANOID=1; off on the hot read path (open-time replay
   * already CRC-checks every record) */
  int verify_reads;
} kv_store;

static uint64_t kv_hash(const uint8_t *key, size_t len) {
  uint64_t h = 1469598103934665603ull; /* FNV-1a 64 */
  for (size_t i = 0; i < len; i++) {
    h ^= key[i];
    h *= 1099511628211ull;
  }
  return h;
}

static const uint8_t *kv_key_at(const kv_store *s, const kv_slot *e) {
  return s->arena + e->key_off;
}

static int kv_grow(kv_store *s);
void lodestar_kv_close(kv_store *s);

/* find slot for key; returns pointer to slot (occupied with the key, or
 * first empty). */
static kv_slot *kv_find(kv_store *s, const uint8_t *key, size_t klen) {
  uint64_t mask = s->cap - 1;
  uint64_t i = kv_hash(key, klen) & mask;
  for (;;) {
    kv_slot *e = &s->slots[i];
    if (e->key_off == UINT64_MAX) return e;
    if (e->key_len == klen && memcmp(kv_key_at(s, e), key, klen) == 0) return e;
    i = (i + 1) & mask;
  }
}

static int kv_arena_push(kv_store *s, const uint8_t *key, size_t klen,
                         uint64_t *off) {
  if (s->arena_len + klen > s->arena_cap) {
    uint64_t ncap = s->arena_cap ? s->arena_cap * 2 : 1 << 20;
    while (ncap < s->arena_len + klen) ncap *= 2;
    uint8_t *na = realloc(s->arena, ncap);
    if (!na) return -1;
    s->arena = na;
    s->arena_cap = ncap;
  }
  memcpy(s->arena + s->arena_len, key, klen);
  *off = s->arena_len;
  s->arena_len += klen;
  return 0;
}

static int kv_index_put(kv_store *s, const uint8_t *key, size_t klen,
                        uint16_t file_id, uint64_t val_off, uint32_t val_len) {
  if ((s->count + 1) * 10 >= s->cap * 7) {
    if (kv_grow(s) != 0) return -1;
  }
  kv_slot *e = kv_find(s, key, klen);
  if (e->key_off == UINT64_MAX) {
    if (kv_arena_push(s, key, klen, &e->key_off) != 0) return -1;
    e->key_len = (uint16_t)klen;
    s->count++;
  }
  e->file_id = file_id;
  e->val_off = val_off;
  e->val_len = val_len;
  return 0;
}

/* tombstone-free deletion: open addressing needs backward-shift or a
 * DELETED marker; use the marker (key_len == UINT16_MAX sentinel would
 * clash with real keys' lengths, so mark by val_len and keep the key for
 * probe continuity). */
#define KV_DELETED UINT32_MAX

static void kv_index_del(kv_store *s, const uint8_t *key, size_t klen) {
  kv_slot *e = kv_find(s, key, klen);
  if (e->key_off != UINT64_MAX && e->val_len != KV_DELETED) {
    e->val_len = KV_DELETED;
    s->arena_dead += e->key_len;
  }
}

static int kv_grow(kv_store *s) {
  uint64_t ncap = s->cap ? s->cap * 2 : 1024;
  kv_slot *ns = malloc(ncap * sizeof(kv_slot));
  if (!ns) return -1;
  for (uint64_t i = 0; i < ncap; i++) ns[i].key_off = UINT64_MAX;
  kv_slot *old = s->slots;
  uint64_t ocap = s->cap;
  s->slots = ns;
  s->cap = ncap;
  uint64_t live = 0;
  for (uint64_t i = 0; i < ocap; i++) {
    kv_slot *e = &old[i];
    if (e->key_off == UINT64_MAX || e->val_len == KV_DELETED) continue;
    kv_slot *n = kv_find(s, kv_key_at(s, e), e->key_len);
    *n = *e;
    live++;
  }
  s->count = live;
  free(old);
  return 0;
}

/* ---------------- segments ---------------- */

static void kv_seg_path(const kv_store *s, uint16_t id, char *out,
                        size_t outlen) {
  snprintf(out, outlen, "%s/seg-%05u.kv", s->dir, (unsigned)id);
}

/* record: [crc32 u32][op u8][klen u16][vlen u32][key][value] (LE) */
#define KV_HDR 11

static int kv_append_record(kv_store *s, uint8_t op, const uint8_t *key,
                            uint16_t klen, const uint8_t *val, uint32_t vlen,
                            uint64_t *val_off_out) {
  uint8_t hdr[KV_HDR];
  hdr[4] = op;
  memcpy(hdr + 5, &klen, 2);
  memcpy(hdr + 7, &vlen, 4);
  uint32_t crc = kv_crc32(0, hdr + 4, KV_HDR - 4);
  crc = kv_crc32(crc, key, klen);
  if (vlen) crc = kv_crc32(crc, val, vlen);
  memcpy(hdr, &crc, 4);
  uint64_t rec_off = s->active_size;
  if (write(s->active_fd, hdr, KV_HDR) != KV_HDR) return -1;
  if (write(s->active_fd, key, klen) != (ssize_t)klen) return -1;
  if (vlen && write(s->active_fd, val, vlen) != (ssize_t)vlen) return -1;
  if (val_off_out) *val_off_out = rec_off + KV_HDR + klen;
  s->active_size += KV_HDR + klen + vlen;
  return 0;
}

static int kv_open_active(kv_store *s, uint16_t id, int truncate) {
  char path[3200];
  kv_seg_path(s, id, path, sizeof(path));
  /* O_RDWR: gets are pread()s against the same fd when the key lives in
   * the active segment; O_APPEND keeps every write at the tail. */
  int fd = open(path, O_CREAT | O_RDWR | (truncate ? O_TRUNC : O_APPEND),
                0644);
  if (fd < 0) return -1;
  if (s->active_fd >= 0) close(s->active_fd);
  s->active_fd = fd;
  s->active_id = id;
  struct stat st;
  s->active_size = (fstat(fd, &st) == 0) ? (uint64_t)st.st_size : 0;
  return 0;
}

static int kv_maybe_rotate(kv_store *s) {
  if (s->active_size < (s->seg_limit ? s->seg_limit : KV_SEG_LIMIT)) return 0;
  if (s->active_id + 1 >= KV_MAX_SEGS) return 0; /* refuse to wrap */
  fsync(s->active_fd);
  return kv_open_active(s, (uint16_t)(s->active_id + 1), 0);
}

static int kv_replay_segment(kv_store *s, uint16_t id) {
  char path[3200];
  kv_seg_path(s, id, path, sizeof(path));
  FILE *f = fopen(path, "rb");
  if (!f) return 0; /* missing = fine */
  uint8_t hdr[KV_HDR];
  uint8_t *buf = NULL;
  size_t buf_cap = 0;
  uint64_t off = 0;
  for (;;) {
    if (fread(hdr, 1, KV_HDR, f) != KV_HDR) break;
    uint32_t crc, vlen;
    uint16_t klen;
    uint8_t op = hdr[4];
    memcpy(&crc, hdr, 4);
    memcpy(&klen, hdr + 5, 2);
    memcpy(&vlen, hdr + 7, 4);
    size_t need = (size_t)klen + vlen;
    if (need > (64u << 20)) break; /* corrupt length */
    if (need > buf_cap) {
      uint8_t *nb = realloc(buf, need ? need : 1);
      if (!nb) break;
      buf = nb;
      buf_cap = need;
    }
    if (fread(buf, 1, need, f) != need) break; /* torn tail */
    uint32_t want = kv_crc32(0, hdr + 4, KV_HDR - 4);
    want = kv_crc32(want, buf, klen);
    if (vlen) want = kv_crc32(want, buf + klen, vlen);
    if (want != crc) break; /* torn/corrupt: stop this segment */
    if (op == 0) {
      kv_slot *e = kv_find(s, buf, klen);
      if (e->key_off != UINT64_MAX && e->val_len != KV_DELETED) {
        uint64_t old = KV_HDR + e->key_len + e->val_len;
        s->dead_bytes += old;
        s->live_bytes -= old < s->live_bytes ? old : s->live_bytes;
      }
      kv_index_put(s, buf, klen, id, off + KV_HDR + klen, vlen);
      s->live_bytes += KV_HDR + klen + vlen;
    } else {
      kv_slot *e = kv_find(s, buf, klen);
      if (e->key_off != UINT64_MAX && e->val_len != KV_DELETED) {
        uint64_t old = KV_HDR + e->key_len + e->val_len;
        s->dead_bytes += old;
        s->live_bytes -= old < s->live_bytes ? old : s->live_bytes;
      }
      kv_index_del(s, buf, klen);
      s->dead_bytes += KV_HDR + klen;
    }
    off += KV_HDR + need;
  }
  free(buf);
  fclose(f);
  return 0;
}

/* ---------------- public API ---------------- */

kv_store *lodestar_kv_open(const char *dir) {
  kv_crc_init();
  if (mkdir(dir, 0755) != 0 && errno != EEXIST) return NULL;
  kv_store *s = calloc(1, sizeof(kv_store));
  if (!s) return NULL;
  snprintf(s->dir, sizeof(s->dir), "%s", dir);
  s->active_fd = -1;
  s->read_fd = -1;
  s->read_fd_id = -1;
  {
    const char *lim = getenv("LODESTAR_KV_SEG_LIMIT");
    s->seg_limit = lim ? strtoull(lim, NULL, 10) : 0;
    const char *par = getenv("LODESTAR_KV_PARANOID");
    s->verify_reads = par && par[0] && par[0] != '0';
  }
  if (kv_grow(s) != 0) {
    free(s);
    return NULL;
  }
  /* compaction crash recovery (see lodestar_kv_compact swap protocol) */
  {
    char marker[3200];
    snprintf(marker, sizeof(marker), "%s/compact.done", dir);
    FILE *mf = fopen(marker, "rb");
    int new_max = -1;
    if (mf) {
      if (fscanf(mf, "%d", &new_max) != 1) new_max = -1;
      fclose(mf);
    }
    DIR *rd = opendir(dir);
    if (rd) {
      struct dirent *ent;
      while ((ent = readdir(rd)) != NULL) {
        unsigned id;
        /* CAUTION: sscanf counts conversions even when trailing literal
         * text doesn't fully match ("seg-00000.kv" matches the pattern
         * below!) — require the exact ".kv.new" name shape explicitly */
        size_t L = strlen(ent->d_name);
        if (L == strlen("seg-00000.kv.new") &&
            sscanf(ent->d_name, "seg-%05u.kv", &id) == 1 &&
            strcmp(ent->d_name + L - 7, ".kv.new") == 0) {
          char from[3300], to[3200];
          snprintf(from, sizeof(from), "%s/%s", dir, ent->d_name);
          snprintf(to, sizeof(to), "%s/seg-%05u.kv", dir, id);
          if (new_max >= 0 && (int)id <= new_max) {
            rename(from, to); /* finish the interrupted promotion */
          } else {
            unlink(from); /* incomplete compaction: old gen is intact */
          }
        }
      }
      closedir(rd);
    }
    if (new_max >= 0) {
      /* drop old-generation finals beyond the new generation */
      DIR *rd2 = opendir(dir);
      if (rd2) {
        struct dirent *ent;
        while ((ent = readdir(rd2)) != NULL) {
          unsigned id;
          if (sscanf(ent->d_name, "seg-%05u.kv", &id) == 1 &&
              strlen(ent->d_name) == strlen("seg-00000.kv") &&
              (int)id > new_max) {
            char p[3300];
            snprintf(p, sizeof(p), "%s/%s", dir, ent->d_name);
            unlink(p);
          }
        }
        closedir(rd2);
      }
      unlink(marker);
    }
  }
  /* replay existing segments in id order */
  int max_id = -1;
  DIR *d = opendir(dir);
  if (d) {
    struct dirent *ent;
    while ((ent = readdir(d)) != NULL) {
      unsigned id;
      if (strlen(ent->d_name) == strlen("seg-00000.kv") &&
          sscanf(ent->d_name, "seg-%05u.kv", &id) == 1) {
        if ((int)id > max_id) max_id = (int)id;
      }
    }
    closedir(d);
  }
  for (int id = 0; id <= max_id; id++) kv_replay_segment(s, (uint16_t)id);
  if (kv_open_active(s, (uint16_t)(max_id < 0 ? 0 : max_id), 0) != 0) {
    free(s->slots);
    free(s->arena);
    free(s);
    return NULL;
  }
  return s;
}

int lodestar_kv_put(kv_store *s, const uint8_t *key, size_t klen,
                    const uint8_t *val, size_t vlen, int sync) {
  if (klen == 0 || klen > 60000 || vlen > (64u << 20) - 1) return -1;
  kv_slot *e = kv_find(s, key, klen);
  if (e->key_off != UINT64_MAX && e->val_len != KV_DELETED) {
    uint64_t old = KV_HDR + e->key_len + e->val_len;
    s->dead_bytes += old;
    s->live_bytes -= old < s->live_bytes ? old : s->live_bytes;
  }
  uint64_t voff;
  if (kv_append_record(s, 0, key, (uint16_t)klen, val, (uint32_t)vlen, &voff))
    return -1;
  if (kv_index_put(s, key, klen, s->active_id, voff, (uint32_t)vlen)) return -1;
  s->live_bytes += KV_HDR + klen + vlen;
  if (sync) fsync(s->active_fd);
  return kv_maybe_rotate(s);
}

int lodestar_kv_delete(kv_store *s, const uint8_t *key, size_t klen,
                       int sync) {
  kv_slot *e = kv_find(s, key, klen);
  if (e->key_off == UINT64_MAX || e->val_len == KV_DELETED) return 0;
  {
    uint64_t old = KV_HDR + e->key_len + e->val_len;
    s->dead_bytes += old + KV_HDR + klen;
    s->live_bytes -= old < s->live_bytes ? old : s->live_bytes;
  }
  if (kv_append_record(s, 1, key, (uint16_t)klen, NULL, 0, NULL)) return -1;
  kv_index_del(s, key, klen);
  if (sync) fsync(s->active_fd);
  return 0;
}

int lodestar_kv_sync(kv_store *s) { return fsync(s->active_fd); }

/* get: returns value length, or -1 if absent, -2 on IO error. Caller
 * provides a buffer via out/out_cap; if too small, returns length anyway
 * (caller retries with bigger buffer). */
int64_t lodestar_kv_get(kv_store *s, const uint8_t *key, size_t klen,
                        uint8_t *out, size_t out_cap) {
  kv_slot *e = kv_find(s, key, klen);
  if (e->key_off == UINT64_MAX || e->val_len == KV_DELETED) return -1;
  if (out_cap < e->val_len) return (int64_t)e->val_len;
  int fd;
  if (e->file_id == s->active_id) {
    fd = s->active_fd;
  } else if (s->read_fd >= 0 && s->read_fd_id == (int)e->file_id) {
    fd = s->read_fd; /* sealed-segment fd cache: archive sweeps reuse it */
  } else {
    char path[3200];
    kv_seg_path(s, e->file_id, path, sizeof(path));
    fd = open(path, O_RDONLY);
    if (fd < 0) return -2;
    if (s->read_fd >= 0) close(s->read_fd);
    s->read_fd = fd;
    s->read_fd_id = (int)e->file_id;
  }
  ssize_t got = pread(fd, out, e->val_len, (off_t)e->val_off);
  if (got != (ssize_t)e->val_len) return -2;
  /* verify the record CRC (header+key live just before the value): a
   * stale fd or corrupted segment must surface as -2, never as silently
   * wrong value bytes */
  if (s->verify_reads) {
    uint8_t hk[KV_HDR + 256];
    uint8_t *hkp = hk;
    size_t hklen = KV_HDR + e->key_len;
    if (hklen > sizeof(hk)) {
      hkp = malloc(hklen);
      if (!hkp) return -2;
    }
    off_t rec_off = (off_t)e->val_off - (off_t)hklen;
    int ok = rec_off >= 0 && pread(fd, hkp, hklen, rec_off) == (ssize_t)hklen;
    if (ok) {
      uint32_t crc_stored;
      memcpy(&crc_stored, hkp, 4);
      uint32_t want = kv_crc32(0, hkp + 4, KV_HDR - 4);
      want = kv_crc32(want, hkp + KV_HDR, e->key_len);
      if (e->val_len) want = kv_crc32(want, out, e->val_len);
      ok = want == crc_stored &&
           memcmp(hkp + KV_HDR, key, klen < e->key_len ? klen : e->key_len) == 0;
    }
    if (hkp != hk) free(hkp);
    if (!ok) return -2;
  }
  return (int64_t)e->val_len;
}

/* collect keys in [gte, lt), sorted. Returns count; fills offsets/lengths
 * into caller arrays up to max_out. Two-pass friendly: call with
 * max_out=0 to count. */
typedef struct {
  const uint8_t *key;
  uint16_t len;
} kv_keyref;

static int kv_keyref_cmp(const void *a, const void *b) {
  const kv_keyref *x = a, *y = b;
  size_t n = x->len < y->len ? x->len : y->len;
  int c = memcmp(x->key, y->key, n);
  if (c) return c;
  return (int)x->len - (int)y->len;
}

static int kv_in_range(const uint8_t *k, uint16_t klen, const uint8_t *gte,
                       size_t gl, const uint8_t *lt, size_t ll) {
  kv_keyref a = {k, klen};
  kv_keyref g = {gte, (uint16_t)gl};
  kv_keyref l = {lt, (uint16_t)ll};
  if (gl && kv_keyref_cmp(&a, &g) < 0) return 0;
  if (ll && kv_keyref_cmp(&a, &l) >= 0) return 0;
  return 1;
}

/* returns a malloc'd array of keyrefs (caller frees) sorted ascending */
kv_keyref *lodestar_kv_range(kv_store *s, const uint8_t *gte, size_t gl,
                             const uint8_t *lt, size_t ll, uint64_t *n_out) {
  uint64_t n = 0;
  for (uint64_t i = 0; i < s->cap; i++) {
    kv_slot *e = &s->slots[i];
    if (e->key_off == UINT64_MAX || e->val_len == KV_DELETED) continue;
    if (kv_in_range(kv_key_at(s, e), e->key_len, gte, gl, lt, ll)) n++;
  }
  kv_keyref *arr = malloc((n ? n : 1) * sizeof(kv_keyref));
  if (!arr) {
    *n_out = 0;
    return NULL;
  }
  uint64_t j = 0;
  for (uint64_t i = 0; i < s->cap; i++) {
    kv_slot *e = &s->slots[i];
    if (e->key_off == UINT64_MAX || e->val_len == KV_DELETED) continue;
    if (kv_in_range(kv_key_at(s, e), e->key_len, gte, gl, lt, ll)) {
      arr[j].key = kv_key_at(s, e);
      arr[j].len = e->key_len;
      j++;
    }
  }
  qsort(arr, n, sizeof(kv_keyref), kv_keyref_cmp);
  *n_out = n;
  return arr;
}

uint64_t lodestar_kv_count(kv_store *s) {
  uint64_t n = 0;
  for (uint64_t i = 0; i < s->cap; i++)
    if (s->slots[i].key_off != UINT64_MAX && s->slots[i].val_len != KV_DELETED)
      n++;
  return n;
}

void lodestar_kv_stats(kv_store *s, uint64_t out[4]) {
  out[0] = lodestar_kv_count(s);
  out[1] = s->live_bytes;
  out[2] = s->dead_bytes;
  out[3] = (uint64_t)s->active_id;
}

/* unlink every regular file in dir (best-effort; missing dir is fine). */
static void kv_purge_dir(const char *dir) {
  DIR *d = opendir(dir);
  if (!d) return;
  struct dirent *de;
  char p[3400];
  while ((de = readdir(d)) != NULL) {
    if (de->d_name[0] == '.') continue;
    snprintf(p, sizeof(p), "%s/%s", dir, de->d_name);
    unlink(p);
  }
  closedir(d);
}

/* compaction: rewrite live records into a fresh segment line. */
int lodestar_kv_compact(kv_store *s) {
  char tmpdir[3200];
  snprintf(tmpdir, sizeof(tmpdir), "%s/compact.tmp", s->dir);
  /* purge leftovers from any previously-failed compaction BEFORE opening:
   * stale segments in compact.tmp would be replayed by lodestar_kv_open
   * into the new generation and could resurrect keys deleted since the
   * failed run (round-3 review). */
  kv_purge_dir(tmpdir);
  kv_store *ns = lodestar_kv_open(tmpdir);
  if (!ns) return -1;
  uint8_t *vbuf = NULL;
  size_t vcap = 0;
  int rc = 0;
  int saved_verify = s->verify_reads;
  s->verify_reads = 1; /* never copy corrupt bytes into the new generation */
  for (uint64_t i = 0; i < s->cap && rc == 0; i++) {
    kv_slot *e = &s->slots[i];
    if (e->key_off == UINT64_MAX || e->val_len == KV_DELETED) continue;
    if (e->val_len > vcap) {
      uint8_t *nb = realloc(vbuf, e->val_len);
      if (!nb) {
        rc = -1;
        break;
      }
      vbuf = nb;
      vcap = e->val_len;
    }
    int64_t got = lodestar_kv_get(s, kv_key_at(s, e), e->key_len, vbuf, vcap);
    if (got < 0) {
      rc = -1;
      break;
    }
    rc = lodestar_kv_put(ns, kv_key_at(s, e), e->key_len, vbuf,
                         (size_t)got, 0);
  }
  s->verify_reads = saved_verify;
  free(vbuf);
  if (rc == 0) rc = lodestar_kv_sync(ns);
  if (rc != 0) {
    /* abandon: close AND purge the tmp segments so they cannot be
     * replayed into a later compaction's new generation */
    void lodestar_kv_close(kv_store *);
    lodestar_kv_close(ns);
    kv_purge_dir(tmpdir);
    rmdir(tmpdir);
    return -1;
  }
  /* crash-safe swap (round-2 review: unlink-all-then-rename loses the
   * whole db on a crash in the window). Protocol:
   *   1. rename new segments into the main dir as seg-NNNNN.kv.new
   *   2. write + fsync a compact.done marker carrying the new max id
   *   3. unlink old finals, promote .new -> final, remove the marker
   * Recovery in lodestar_kv_open: with a valid marker, finish step 3;
   * without one, discard any .new leftovers (old generation is intact —
   * compaction is logically a no-op, so either complete generation is
   * correct). */
  for (int id = 0; rc == 0 && id <= (int)ns->active_id; id++) {
    char from[3250], to[3300];
    kv_seg_path(ns, (uint16_t)id, from, sizeof(from));
    kv_seg_path(s, (uint16_t)id, to, sizeof(to) - 5);
    strcat(to, ".new");
    if (rename(from, to) != 0) rc = -1;
  }
  char marker[3200];
  snprintf(marker, sizeof(marker), "%s/compact.done", s->dir);
  if (rc == 0) {
    int mfd = open(marker, O_CREAT | O_WRONLY | O_TRUNC, 0644);
    if (mfd >= 0) {
      char buf[32];
      int n = snprintf(buf, sizeof(buf), "%d\n", (int)ns->active_id);
      if (write(mfd, buf, n) != n) rc = -1;
      fsync(mfd);
      close(mfd);
    } else {
      rc = -1;
    }
  }
  if (rc != 0) {
    /* stage-1/2 failure: the old generation is fully intact on disk and
     * nothing was promoted — do NOT adopt the new index (adopting here
     * would point every get at files that don't exist). Clean up the
     * .new leftovers and keep serving the old state. (round-3 review) */
    for (int id = 0; id <= (int)ns->active_id; id++) {
      char to[3200], from[3300];
      kv_seg_path(s, (uint16_t)id, to, sizeof(to));
      snprintf(from, sizeof(from), "%s.new", to);
      unlink(from);
    }
    unlink(marker);
    lodestar_kv_close(ns);
    kv_purge_dir(tmpdir); /* segments the rename loop never reached */
    rmdir(tmpdir);
    return -1;
  }
  for (int id = 0; id <= (int)s->active_id; id++) {
    char p[3200];
    kv_seg_path(s, (uint16_t)id, p, sizeof(p));
    unlink(p);
  }
  for (int id = 0; id <= (int)ns->active_id; id++) {
    char from[3300], to[3200];
    kv_seg_path(s, (uint16_t)id, to, sizeof(to));
    snprintf(from, sizeof(from), "%s.new", to);
    if (rename(from, to) != 0) rc = -1;
    /* a promote-stage rename failure is still adopted below: the old
     * finals are gone and the fsync'd marker lets open-time recovery
     * finish the promotion */
  }
  if (rc == 0) unlink(marker); /* keep the marker while recovery needs it */
  rmdir(tmpdir);
  /* adopt the new store's state in place */
  close(s->active_fd);
  if (ns->active_fd >= 0) close(ns->active_fd);
  free(s->slots);
  free(s->arena);
  s->slots = ns->slots;
  s->cap = ns->cap;
  s->count = ns->count;
  s->arena = ns->arena;
  s->arena_len = ns->arena_len;
  s->arena_cap = ns->arena_cap;
  s->arena_dead = 0;
  s->live_bytes = ns->live_bytes;
  s->dead_bytes = 0;
  s->active_fd = -1;
  /* the sealed-segment fd cache points at a pre-compaction file that was
   * just unlinked; a post-compaction get whose entry shares the cached
   * file_id would pread the dead file at new-generation offsets and
   * return wrong bytes — drop the cache with the old generation */
  if (s->read_fd >= 0) close(s->read_fd);
  s->read_fd = -1;
  s->read_fd_id = -1;
  uint16_t new_active = ns->active_id;
  free(ns);
  return kv_open_active(s, new_active, 0) || rc;
}

int lodestar_kv_should_compact(kv_store *s) {
  return s->dead_bytes > KV_COMPACT_MIN &&
         s->dead_bytes > s->live_bytes * KV_COMPACT_RATIO;
}

void lodestar_kv_close(kv_store *s) {
  if (!s) return;
  if (s->active_fd >= 0) {
    fsync(s->active_fd);
    close(s->active_fd);
  }
  if (s->read_fd >= 0) close(s->read_fd);
  free(s->slots);
  free(s->arena);
  free(s);
}
