/* Native BLS12-381 host tier: the marshalling fast path.
 *
 * Replaces the pure-Python big-int hot path between wire bytes and the
 * device verifier (reference analog: blst's in-C preprocessing used by
 * chain/bls/multithread/worker.ts:33-55 and main-thread aggregation
 * bls/utils.ts:5-16).  Scope:
 *
 *   - G1/G2 point decompression (ZCash flags) + on-curve + subgroup checks
 *   - SSWU hash-to-curve for G2 (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_)
 *   - G1 pubkey aggregation
 *   - batched signature-set marshalling straight into the device's
 *     32x12-bit Montgomery limb layout (ops/limbs.py)
 *
 * Field arithmetic: 6x64-bit limbs, Montgomery form (R = 2^384), CIOS
 * multiplication with __uint128_t.  All constants are generated from the
 * Python oracle (gen_bls12_consts.py) so the two tiers cannot disagree.
 * Scalar multiplications here are variable-time: every input is public
 * (signatures, pubkeys, message hashes) — no secrets are processed.
 */

#include <stdint.h>
#include <string.h>
#include <stddef.h>

#include "bls12_consts.h"

void lodestar_sha256(const uint8_t *data, size_t len, uint8_t out[32]);

typedef uint64_t fp[6];
typedef struct { fp c0, c1; } fp2;
typedef struct { fp X, Y, Z; } g1p;   /* jacobian; Z==0 -> infinity */
typedef struct { fp2 X, Y, Z; } g2p;

/* ---------------- fp ---------------- */

static void fp_copy(fp r, const fp a) { memcpy(r, a, sizeof(fp)); }
static void fp_zero(fp r) { memset(r, 0, sizeof(fp)); }
static int fp_is_zero(const fp a) {
  return (a[0] | a[1] | a[2] | a[3] | a[4] | a[5]) == 0;
}
static int fp_eq(const fp a, const fp b) { return memcmp(a, b, sizeof(fp)) == 0; }

/* a >= b (both < 2^384) */
static int fp_cmp_ge(const uint64_t *a, const uint64_t *b, int n) {
  for (int i = n - 1; i >= 0; i--) {
    if (a[i] > b[i]) return 1;
    if (a[i] < b[i]) return 0;
  }
  return 1;
}

static void fp_sub_raw(uint64_t *r, const uint64_t *a, const uint64_t *b, int n) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < n; i++) {
    unsigned __int128 d = (unsigned __int128)a[i] - b[i] - (uint64_t)borrow;
    r[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

static void fp_add(fp r, const fp a, const fp b) {
  unsigned __int128 c = 0;
  for (int i = 0; i < 6; i++) {
    c += (unsigned __int128)a[i] + b[i];
    r[i] = (uint64_t)c;
    c >>= 64;
  }
  if (c || fp_cmp_ge(r, BLS_P, 6)) {
    /* subtract p (carry c can only be 0 here since 2p < 2^384+p... handle both) */
    uint64_t t[6];
    fp_sub_raw(t, r, BLS_P, 6);
    /* if there was a carry out, the subtraction is unconditionally right */
    fp_copy(r, t);
  }
}

static void fp_sub(fp r, const fp a, const fp b) {
  if (fp_cmp_ge(a, b, 6)) {
    fp_sub_raw(r, a, b, 6);
  } else {
    uint64_t t[6];
    unsigned __int128 c = 0;
    for (int i = 0; i < 6; i++) {
      c += (unsigned __int128)a[i] + BLS_P[i];
      t[i] = (uint64_t)c;
      c >>= 64;
    }
    fp_sub_raw(r, t, b, 6);
  }
}

static void fp_neg(fp r, const fp a) {
  if (fp_is_zero(a)) { fp_zero(r); return; }
  fp_sub_raw(r, BLS_P, a, 6);
}

/* CIOS Montgomery multiplication: r = a*b*R^-1 mod p, result < p. */
static void fp_mul(fp r, const fp a, const fp b) {
  uint64_t t[8];
  memset(t, 0, sizeof(t));
  for (int i = 0; i < 6; i++) {
    unsigned __int128 c = 0;
    for (int j = 0; j < 6; j++) {
      c += (unsigned __int128)a[j] * b[i] + t[j];
      t[j] = (uint64_t)c;
      c >>= 64;
    }
    c += t[6];
    t[6] = (uint64_t)c;
    t[7] = (uint64_t)(c >> 64);

    uint64_t m = t[0] * BLS_N0;
    c = (unsigned __int128)m * BLS_P[0] + t[0];
    c >>= 64;
    for (int j = 1; j < 6; j++) {
      c += (unsigned __int128)m * BLS_P[j] + t[j];
      t[j - 1] = (uint64_t)c;
      c >>= 64;
    }
    c += t[6];
    t[5] = (uint64_t)c;
    t[6] = t[7] + (uint64_t)(c >> 64);
  }
  if (t[6] || fp_cmp_ge(t, BLS_P, 6)) fp_sub_raw(t, t, BLS_P, 6);
  memcpy(r, t, sizeof(fp));
}

static void fp_sqr(fp r, const fp a) { fp_mul(r, a, a); }

/* a^e for little-endian word exponent (variable time; public data only). */
static void fp_exp(fp r, const fp a, const uint64_t *e, int words) {
  fp acc;
  fp_copy(acc, BLS_ONE_M);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) fp_sqr(acc, acc);
      if ((e[w] >> b) & 1) {
        if (started) fp_mul(acc, acc, a);
        else { fp_copy(acc, a); started = 1; }
      }
    }
  }
  fp_copy(r, acc);
}

static void fp_inv(fp r, const fp a) { fp_exp(r, a, BLS_EXP_INV, 6); }

/* sqrt (p = 3 mod 4): cand = a^((p+1)/4); returns 0 if a is not a QR. */
static int fp_sqrt(fp r, const fp a) {
  fp cand, chk;
  fp_exp(cand, a, BLS_EXP_SQRT, 6);
  fp_sqr(chk, cand);
  if (!fp_eq(chk, a)) return 0;
  fp_copy(r, cand);
  return 1;
}

/* Montgomery -> canonical integer (little-endian words). */
static void fp_from_mont(uint64_t out[6], const fp a) {
  fp one = {1, 0, 0, 0, 0, 0};
  fp_mul((uint64_t *)out, a, one);
}

static void fp_to_mont(fp r, const uint64_t in[6]) { fp_mul(r, in, BLS_R2); }

static int fp_sgn0(const fp a) {
  uint64_t c[6];
  fp_from_mont(c, a);
  return (int)(c[0] & 1);
}

static int fp_lex_larger(const fp a) {
  uint64_t c[6];
  fp_from_mont(c, a);
  /* canonical > (p-1)/2 */
  for (int i = 5; i >= 0; i--) {
    if (c[i] > BLS_HALF_P[i]) return 1;
    if (c[i] < BLS_HALF_P[i]) return 0;
  }
  return 0; /* equal -> not larger */
}

/* 48 big-endian bytes -> canonical words; returns 0 if >= p. */
static int fp_from_be(uint64_t out[6], const uint8_t in[48]) {
  for (int i = 0; i < 6; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[(5 - i) * 8 + j];
    out[i] = w;
  }
  return !fp_cmp_ge(out, BLS_P, 6);
}

/* Montgomery fp -> 32x12-bit int32 device limbs (value = a*R mod p). */
static void fp_to_limbs12(int32_t out[32], const fp a) {
  /* the Montgomery residue itself is what the device stores */
  const uint64_t *w = a;
  for (int i = 0; i < 32; i++) {
    int bit = i * 12;
    int word = bit >> 6, off = bit & 63;
    uint64_t v = w[word] >> off;
    if (off > 52 && word < 5) v |= w[word + 1] << (64 - off);
    out[i] = (int32_t)(v & 0xFFF);
  }
}

/* ---------------- fp2 ---------------- */

static void fp2_copy(fp2 *r, const fp2 *a) { *r = *a; }
static void fp2_zero(fp2 *r) { fp_zero(r->c0); fp_zero(r->c1); }
static int fp2_is_zero(const fp2 *a) { return fp_is_zero(a->c0) && fp_is_zero(a->c1); }
static int fp2_eq(const fp2 *a, const fp2 *b) {
  return fp_eq(a->c0, b->c0) && fp_eq(a->c1, b->c1);
}
static void fp2_one(fp2 *r) { fp_copy(r->c0, BLS_ONE_M); fp_zero(r->c1); }

static void fp2_add(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_add(r->c0, a->c0, b->c0);
  fp_add(r->c1, a->c1, b->c1);
}
static void fp2_sub(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_sub(r->c0, a->c0, b->c0);
  fp_sub(r->c1, a->c1, b->c1);
}
static void fp2_neg(fp2 *r, const fp2 *a) {
  fp_neg(r->c0, a->c0);
  fp_neg(r->c1, a->c1);
}
static void fp2_conj(fp2 *r, const fp2 *a) {
  fp_copy(r->c0, a->c0);
  fp_neg(r->c1, a->c1);
}

static void fp2_mul(fp2 *r, const fp2 *a, const fp2 *b) {
  fp t0, t1, t2, t3, s0, s1;
  fp_mul(t0, a->c0, b->c0);
  fp_mul(t1, a->c1, b->c1);
  fp_add(t2, a->c0, a->c1);
  fp_add(t3, b->c0, b->c1);
  fp_mul(t2, t2, t3);          /* (a0+a1)(b0+b1) */
  fp_sub(s0, t0, t1);          /* c0 = a0b0 - a1b1 */
  fp_sub(t2, t2, t0);
  fp_sub(s1, t2, t1);          /* c1 = cross */
  fp_copy(r->c0, s0);
  fp_copy(r->c1, s1);
}

static void fp2_sqr(fp2 *r, const fp2 *a) {
  fp t0, t1, s0;
  fp_add(t0, a->c0, a->c1);
  fp_sub(t1, a->c0, a->c1);
  fp_mul(s0, t0, t1);          /* (a0+a1)(a0-a1) */
  fp_mul(t0, a->c0, a->c1);
  fp_copy(r->c0, s0);
  fp_add(r->c1, t0, t0);       /* 2 a0 a1 */
}

static void fp2_mul_fp(fp2 *r, const fp2 *a, const fp k) {
  fp_mul(r->c0, a->c0, k);
  fp_mul(r->c1, a->c1, k);
}

static void fp2_inv(fp2 *r, const fp2 *a) {
  fp n, n0, n1;
  fp_sqr(n0, a->c0);
  fp_sqr(n1, a->c1);
  fp_add(n, n0, n1);
  fp_inv(n, n);
  fp_mul(r->c0, a->c0, n);
  fp_mul(n, a->c1, n);
  fp_neg(r->c1, n);
}

static void fp2_exp(fp2 *r, const fp2 *a, const uint64_t *e, int words) {
  fp2 acc;
  fp2_one(&acc);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) fp2_sqr(&acc, &acc);
      if ((e[w] >> b) & 1) {
        if (started) fp2_mul(&acc, &acc, a);
        else { fp2_copy(&acc, a); started = 1; }
      }
    }
  }
  fp2_copy(r, &acc);
}

/* Fq2 sqrt: cand = a^((p^2+7)/16) corrected by {1, i, w, iw}. 0 = not a QR. */
static int fp2_sqrt(fp2 *r, const fp2 *a) {
  if (fp2_is_zero(a)) { fp2_zero(r); return 1; }
  fp2 cand, s, chk;
  fp2_exp(&cand, a, BLS_EXP_SQRT_FQ2, 12);
  for (int i = 0; i < 4; i++) {
    fp2 corr;
    fp_copy(corr.c0, BLS_SQRT_CORR[i][0]);
    fp_copy(corr.c1, BLS_SQRT_CORR[i][1]);
    fp2_mul(&s, &cand, &corr);
    fp2_sqr(&chk, &s);
    if (fp2_eq(&chk, a)) { fp2_copy(r, &s); return 1; }
  }
  return 0;
}

static int fp2_sgn0(const fp2 *a) {
  /* RFC 9380 sgn0, m=2 */
  uint64_t c0[6];
  fp_from_mont(c0, a->c0);
  int sign_0 = (int)(c0[0] & 1);
  int zero_0 = 1;
  for (int i = 0; i < 6; i++) zero_0 &= (c0[i] == 0);
  int sign_1 = fp_sgn0(a->c1);
  return sign_0 | (zero_0 & sign_1);
}

static int fp2_lex_larger(const fp2 *y) {
  /* ZCash convention: compare (c1, c0) lexicographically with (p-1)/2 */
  if (!fp_is_zero(y->c1)) return fp_lex_larger(y->c1);
  return fp_lex_larger(y->c0);
}

/* ---------------- G1 (jacobian) ---------------- */

static void g1_infinity(g1p *r) {
  fp_copy(r->X, BLS_ONE_M);
  fp_copy(r->Y, BLS_ONE_M);
  fp_zero(r->Z);
}
static int g1_is_infinity(const g1p *p) { return fp_is_zero(p->Z); }

static void g1_dbl(g1p *r, const g1p *p) {
  if (g1_is_infinity(p)) { *r = *p; return; }
  fp A, B, C, D, E, F, t;
  fp_sqr(A, p->X);
  fp_sqr(B, p->Y);
  fp_sqr(C, B);
  fp_add(t, p->X, B);
  fp_sqr(t, t);
  fp_sub(t, t, A);
  fp_sub(t, t, C);
  fp_add(D, t, t);            /* 2((X+B)^2 - A - C) */
  fp_add(E, A, A);
  fp_add(E, E, A);            /* 3A */
  fp_sqr(F, E);
  fp t2;
  fp_add(t2, D, D);
  fp_sub(F, F, t2);           /* X3 = F - 2D */
  fp Y3;
  fp_sub(Y3, D, F);
  fp_mul(Y3, E, Y3);
  fp C8;
  fp_add(C8, C, C);
  fp_add(C8, C8, C8);
  fp_add(C8, C8, C8);         /* 8C */
  fp_sub(Y3, Y3, C8);
  fp Z3;
  fp_mul(Z3, p->Y, p->Z);
  fp_add(Z3, Z3, Z3);
  fp_copy(r->X, F);
  fp_copy(r->Y, Y3);
  fp_copy(r->Z, Z3);
}

static void g1_add(g1p *r, const g1p *p, const g1p *q) {
  if (g1_is_infinity(p)) { *r = *q; return; }
  if (g1_is_infinity(q)) { *r = *p; return; }
  fp Z1Z1, Z2Z2, U1, U2, S1, S2, H, rr, t;
  fp_sqr(Z1Z1, p->Z);
  fp_sqr(Z2Z2, q->Z);
  fp_mul(U1, p->X, Z2Z2);
  fp_mul(U2, q->X, Z1Z1);
  fp_mul(t, q->Z, Z2Z2);
  fp_mul(S1, p->Y, t);
  fp_mul(t, p->Z, Z1Z1);
  fp_mul(S2, q->Y, t);
  fp_sub(H, U2, U1);
  fp_sub(rr, S2, S1);
  if (fp_is_zero(H)) {
    if (fp_is_zero(rr)) { g1_dbl(r, p); return; }
    g1_infinity(r);
    return;
  }
  fp I, J, r2, V, X3, Y3, Z3;
  fp_add(t, H, H);
  fp_sqr(I, t);               /* (2H)^2 */
  fp_mul(J, H, I);
  fp_add(r2, rr, rr);
  fp_mul(V, U1, I);
  fp_sqr(X3, r2);
  fp_sub(X3, X3, J);
  fp_sub(X3, X3, V);
  fp_sub(X3, X3, V);
  fp_sub(Y3, V, X3);
  fp_mul(Y3, r2, Y3);
  fp_mul(t, S1, J);
  fp_add(t, t, t);
  fp_sub(Y3, Y3, t);
  fp_add(Z3, p->Z, q->Z);
  fp_sqr(Z3, Z3);
  fp_sub(Z3, Z3, Z1Z1);
  fp_sub(Z3, Z3, Z2Z2);
  fp_mul(Z3, Z3, H);
  fp_copy(r->X, X3);
  fp_copy(r->Y, Y3);
  fp_copy(r->Z, Z3);
}

static void g1_scalar_mul(g1p *r, const g1p *p, const uint64_t *k, int words) {
  g1p acc;
  g1_infinity(&acc);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) g1_dbl(&acc, &acc);
      if ((k[w] >> b) & 1) {
        if (started) g1_add(&acc, &acc, p);
        else { acc = *p; started = 1; }
      }
    }
  }
  if (!started) g1_infinity(&acc);
  *r = acc;
}

/* GLV endomorphism φ(x,y) = (β·x, y), β = 2^((p-1)/3) (Montgomery form).
 * On G1, φ acts as multiplication by −x² (verified against the Python
 * oracle, including completeness on random non-subgroup curve points:
 * tests/test_native_bls.py).  Fast membership: φ(P) + [x²]P == O —
 * a 128-bit ladder instead of the 255-bit order ladder (~2×). */
static const uint64_t BLS_BETA_M[6] = {
    0x30f1361b798a64e8ULL, 0xf3b8ddab7ece5a2aULL, 0x16a8ca3ac61577f7ULL,
    0xc26a2ff874fd029bULL, 0x3636b76660701c6eULL, 0x051ba4ab241b6160ULL};
static const uint64_t BLS_X_SQ[2] = {0x0000000100000000ULL,
                                     0xac45a4010001a402ULL};

static int g1_in_subgroup(const g1p *p) {
  if (g1_is_infinity(p)) return 1;
  g1p phi = *p, t;
  fp_mul(phi.X, phi.X, BLS_BETA_M);
  g1_scalar_mul(&t, p, BLS_X_SQ, 2);
  g1_add(&t, &t, &phi);
  return g1_is_infinity(&t);
}

static void g1_to_affine(fp x, fp y, const g1p *p) {
  fp zi, zi2;
  fp_inv(zi, p->Z);
  fp_sqr(zi2, zi);
  fp_mul(x, p->X, zi2);
  fp_mul(zi2, zi2, zi);
  fp_mul(y, p->Y, zi2);
}

/* ---------------- G2 (jacobian over fp2) ---------------- */

static void g2_infinity(g2p *r) {
  fp2_one(&r->X);
  fp2_one(&r->Y);
  fp2_zero(&r->Z);
}
static int g2_is_infinity(const g2p *p) { return fp2_is_zero(&p->Z); }

static void g2_dbl(g2p *r, const g2p *p) {
  if (g2_is_infinity(p)) { *r = *p; return; }
  fp2 A, B, C, D, E, F, t, t2, Y3, Z3, C8;
  fp2_sqr(&A, &p->X);
  fp2_sqr(&B, &p->Y);
  fp2_sqr(&C, &B);
  fp2_add(&t, &p->X, &B);
  fp2_sqr(&t, &t);
  fp2_sub(&t, &t, &A);
  fp2_sub(&t, &t, &C);
  fp2_add(&D, &t, &t);
  fp2_add(&E, &A, &A);
  fp2_add(&E, &E, &A);
  fp2_sqr(&F, &E);
  fp2_add(&t2, &D, &D);
  fp2_sub(&F, &F, &t2);
  fp2_sub(&Y3, &D, &F);
  fp2_mul(&Y3, &E, &Y3);
  fp2_add(&C8, &C, &C);
  fp2_add(&C8, &C8, &C8);
  fp2_add(&C8, &C8, &C8);
  fp2_sub(&Y3, &Y3, &C8);
  fp2_mul(&Z3, &p->Y, &p->Z);
  fp2_add(&Z3, &Z3, &Z3);
  fp2_copy(&r->X, &F);
  fp2_copy(&r->Y, &Y3);
  fp2_copy(&r->Z, &Z3);
}

static void g2_add(g2p *r, const g2p *p, const g2p *q) {
  if (g2_is_infinity(p)) { *r = *q; return; }
  if (g2_is_infinity(q)) { *r = *p; return; }
  fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, H, rr, t, I, J, r2, V, X3, Y3, Z3;
  fp2_sqr(&Z1Z1, &p->Z);
  fp2_sqr(&Z2Z2, &q->Z);
  fp2_mul(&U1, &p->X, &Z2Z2);
  fp2_mul(&U2, &q->X, &Z1Z1);
  fp2_mul(&t, &q->Z, &Z2Z2);
  fp2_mul(&S1, &p->Y, &t);
  fp2_mul(&t, &p->Z, &Z1Z1);
  fp2_mul(&S2, &q->Y, &t);
  fp2_sub(&H, &U2, &U1);
  fp2_sub(&rr, &S2, &S1);
  if (fp2_is_zero(&H)) {
    if (fp2_is_zero(&rr)) { g2_dbl(r, p); return; }
    g2_infinity(r);
    return;
  }
  fp2_add(&t, &H, &H);
  fp2_sqr(&I, &t);
  fp2_mul(&J, &H, &I);
  fp2_add(&r2, &rr, &rr);
  fp2_mul(&V, &U1, &I);
  fp2_sqr(&X3, &r2);
  fp2_sub(&X3, &X3, &J);
  fp2_sub(&X3, &X3, &V);
  fp2_sub(&X3, &X3, &V);
  fp2_sub(&Y3, &V, &X3);
  fp2_mul(&Y3, &r2, &Y3);
  fp2_mul(&t, &S1, &J);
  fp2_add(&t, &t, &t);
  fp2_sub(&Y3, &Y3, &t);
  fp2_add(&Z3, &p->Z, &q->Z);
  fp2_sqr(&Z3, &Z3);
  fp2_sub(&Z3, &Z3, &Z1Z1);
  fp2_sub(&Z3, &Z3, &Z2Z2);
  fp2_mul(&Z3, &Z3, &H);
  fp2_copy(&r->X, &X3);
  fp2_copy(&r->Y, &Y3);
  fp2_copy(&r->Z, &Z3);
}

static void g2_neg(g2p *r, const g2p *p) {
  *r = *p;
  fp2_neg(&r->Y, &p->Y);
}

static void g2_scalar_mul(g2p *r, const g2p *p, const uint64_t *k, int words) {
  g2p acc;
  g2_infinity(&acc);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) g2_dbl(&acc, &acc);
      if ((k[w] >> b) & 1) {
        if (started) g2_add(&acc, &acc, p);
        else { acc = *p; started = 1; }
      }
    }
  }
  if (!started) g2_infinity(&acc);
  *r = acc;
}

static void g2_psi(g2p *r, const g2p *p);

/* Fast membership (Scott): P ∈ G2 ⟺ ψ(P) == [x]P; x is negative, so
 * check ψ(P) + [|x|]P == O — a 64-bit ladder instead of the 255-bit
 * order ladder (~4×).  Verified against the Python oracle including
 * completeness on random non-subgroup E'(Fp2) points
 * (tests/test_native_bls.py). */
static int g2_in_subgroup(const g2p *p) {
  if (g2_is_infinity(p)) return 1;
  g2p psi_p, t;
  g2_psi(&psi_p, p);
  g2_scalar_mul(&t, p, BLS_X_ABS, 1);
  g2_add(&t, &t, &psi_p);
  return g2_is_infinity(&t);
}

static void g2_to_affine(fp2 *x, fp2 *y, const g2p *p) {
  fp2 zi, zi2;
  fp2_inv(&zi, &p->Z);
  fp2_sqr(&zi2, &zi);
  fp2_mul(x, &p->X, &zi2);
  fp2_mul(&zi2, &zi2, &zi);
  fp2_mul(y, &p->Y, &zi2);
}

/* psi endomorphism on jacobian coords: conjugate everything, then scale
 * X by CX and Y by CY (valid because conj(X/Z^2) = conj(X)/conj(Z)^2). */
static void g2_psi(g2p *r, const g2p *p) {
  fp2 cx, cy;
  memcpy(&cx, BLS_PSI_CX, sizeof(fp2));
  memcpy(&cy, BLS_PSI_CY, sizeof(fp2));
  fp2 X, Y, Z;
  fp2_conj(&X, &p->X);
  fp2_conj(&Y, &p->Y);
  fp2_conj(&Z, &p->Z);
  fp2_mul(&r->X, &X, &cx);
  fp2_mul(&r->Y, &Y, &cy);
  fp2_copy(&r->Z, &Z);
}

/* Budroni-Pintore: h_eff.P = [S1]P + [S2]psi(P) + psi^2(2P), S2 < 0. */
static void g2_clear_cofactor(g2p *r, const g2p *p) {
  g2p t1, t2, t3, psi_p;
  g2_scalar_mul(&t1, p, BLS_BP_S1, 3);
  g2_psi(&psi_p, p);
  g2_scalar_mul(&t2, &psi_p, BLS_BP_S2_ABS, 2);
  g2_neg(&t2, &t2);
  g2_dbl(&t3, p);
  g2_psi(&t3, &t3);
  g2_psi(&t3, &t3);
  g2_add(&t1, &t1, &t2);
  g2_add(r, &t1, &t3);
}

/* ---------------- serialization ---------------- */

#define FLAG_C 0x80
#define FLAG_I 0x40
#define FLAG_S 0x20

/* Parse a 48B compressed G1 point into affine-Z=1 montgomery coords.
 * Returns 0 ok / 1 infinity / -1 malformed / -2 not on curve.  The ZCash
 * flag rules: C must be set; I implies all other payload bits zero.  No
 * subgroup check here — callers decide (single shared implementation for
 * decompress + aggregate, so validation policy lives in one place). */
static int g1_parse_compressed(const uint8_t in[48], g1p *out) {
  uint8_t flags = in[0];
  if (!(flags & FLAG_C)) return -1;
  if (flags & FLAG_I) {
    if (flags != (FLAG_C | FLAG_I)) return -1;
    for (int i = 1; i < 48; i++)
      if (in[i]) return -1;
    g1_infinity(out);
    return 1;
  }
  uint8_t buf[48];
  memcpy(buf, in, 48);
  buf[0] &= 0x1F;
  uint64_t xw[6];
  if (!fp_from_be(xw, buf)) return -1;
  fp x, y, y2, t;
  fp_to_mont(x, xw);
  fp_sqr(t, x);
  fp_mul(t, t, x);
  fp_add(y2, t, BLS_B1_M);
  if (!fp_sqrt(y, y2)) return -2;
  if (fp_lex_larger(y) != !!(flags & FLAG_S)) fp_neg(y, y);
  fp_copy(out->X, x);
  fp_copy(out->Y, y);
  fp_copy(out->Z, BLS_ONE_M);
  return 0;
}

/* returns 0 ok / 1 infinity / -1 malformed / -2 not on curve /
 * -3 not in subgroup.  out_x/out_y are 32 int32 device limbs. */
int lodestar_bls_g1_decompress(const uint8_t in[48], int32_t out_x[32],
                               int32_t out_y[32], int check_subgroup) {
  memset(out_x, 0, 32 * sizeof(int32_t));
  memset(out_y, 0, 32 * sizeof(int32_t));
  g1p p;
  int rc = g1_parse_compressed(in, &p);
  if (rc != 0) return rc;
  if (check_subgroup && !g1_in_subgroup(&p)) return -3;
  fp_to_limbs12(out_x, p.X);
  fp_to_limbs12(out_y, p.Y);
  return 0;
}

/* parse a compressed G2 point to affine Montgomery coordinates.
 * Returns 0 ok / 1 infinity / -1 malformed / -2 off-curve / -3 subgroup. */
static int g2_parse_compressed_aff(const uint8_t in[96], fp2 *x, fp2 *y,
                                   int check_subgroup) {
  uint8_t flags = in[0];
  if (!(flags & FLAG_C)) return -1;
  if (flags & FLAG_I) {
    if (flags != (FLAG_C | FLAG_I)) return -1;
    for (int i = 1; i < 96; i++)
      if (in[i]) return -1;
    return 1;
  }
  uint8_t buf[48];
  memcpy(buf, in, 48);
  buf[0] &= 0x1F;
  uint64_t x1w[6], x0w[6];
  if (!fp_from_be(x1w, buf)) return -1;       /* first 48B: c1 (ZCash order) */
  if (!fp_from_be(x0w, in + 48)) return -1;   /* second 48B: c0 */
  fp2 y2, t;
  fp_to_mont(x->c0, x0w);
  fp_to_mont(x->c1, x1w);
  fp2_sqr(&t, x);
  fp2_mul(&t, &t, x);
  fp2 b2;
  memcpy(&b2, BLS_B2_M, sizeof(fp2));
  fp2_add(&y2, &t, &b2);
  if (!fp2_sqrt(y, &y2)) return -2;
  if (fp2_lex_larger(y) != !!(flags & FLAG_S)) fp2_neg(y, y);
  if (check_subgroup) {
    g2p p;
    fp2_copy(&p.X, x);
    fp2_copy(&p.Y, y);
    fp2_one(&p.Z);
    if (!g2_in_subgroup(&p)) return -3;
  }
  return 0;
}

int lodestar_bls_g2_decompress(const uint8_t in[96], int32_t out_x[64],
                               int32_t out_y[64], int check_subgroup) {
  memset(out_x, 0, 64 * sizeof(int32_t));
  memset(out_y, 0, 64 * sizeof(int32_t));
  fp2 x, y;
  int rc = g2_parse_compressed_aff(in, &x, &y, check_subgroup);
  if (rc != 0) return rc;
  fp_to_limbs12(out_x, x.c0);
  fp_to_limbs12(out_x + 32, x.c1);
  fp_to_limbs12(out_y, y.c0);
  fp_to_limbs12(out_y + 32, y.c1);
  return 0;
}

/* ---------------- hash to curve (G2) ---------------- */

/* RFC 9380 5.3.1 expand_message_xmd, SHA-256, len fixed to 256 bytes
 * (count=2 draws x m=2 coords x L=64). msg arbitrary length. */
static void expand_message_xmd_256(const uint8_t *msg, size_t msg_len,
                                   const uint8_t *dst, size_t dst_len,
                                   uint8_t out[256]) {
  uint8_t dst_prime[256];
  size_t dpl = dst_len;
  memcpy(dst_prime, dst, dst_len);
  dst_prime[dpl++] = (uint8_t)dst_len;

  uint8_t b0[32], bi[32];
  /* b0 = H(Z_pad || msg || l_i_b_str || 0 || dst'); one-shot SHA over a
   * stack buffer — callers cap msg at 3KB (consensus messages are 32B). */
  {
    uint8_t big[4096];
    size_t off = 0;
    memset(big, 0, 64);
    off = 64;
    memcpy(big + off, msg, msg_len);
    off += msg_len;
    big[off++] = 1; /* l_i_b_str hi: 256 = 0x0100 */
    big[off++] = 0;
    big[off++] = 0;
    memcpy(big + off, dst_prime, dpl);
    off += dpl;
    lodestar_sha256(big, off, b0);
  }
  uint8_t cur[32 + 1 + 256];
  memcpy(cur, b0, 32);
  cur[32] = 1;
  memcpy(cur + 33, dst_prime, dpl);
  lodestar_sha256(cur, 33 + dpl, bi);
  memcpy(out, bi, 32);
  for (int i = 2; i <= 8; i++) {
    for (int j = 0; j < 32; j++) cur[j] = b0[j] ^ bi[j];
    cur[32] = (uint8_t)i;
    memcpy(cur + 33, dst_prime, dpl);
    lodestar_sha256(cur, 33 + dpl, bi);
    memcpy(out + (i - 1) * 32, bi, 32);
  }
}

/* 64 big-endian bytes -> field element (Montgomery), reduced mod p. */
static void fp_from_be64_mod(fp r, const uint8_t in[64]) {
  /* value = a1*2^384 + a0, a1 = top 16 bytes, a0 = bottom 48 bytes */
  uint64_t a1[6] = {0}, a0[6];
  for (int i = 0; i < 2; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[(1 - i) * 8 + j];
    a1[i] = w;
  }
  for (int i = 0; i < 6; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[16 + (5 - i) * 8 + j];
    a0[i] = w;
  }
  fp m1, m0;
  fp_mul(m1, a1, BLS_R2);       /* a1 * R  (valid: a1 < R) */
  fp_mul(m1, m1, BLS_R2);       /* a1 * R * R = (a1*2^384)*R mod p */
  fp_mul(m0, a0, BLS_R2);       /* a0 * R */
  fp_add(r, m1, m0);
}

/* simplified SWU onto E2' (RFC 9380 6.6.2), then 3-isogeny to E2. */
static void map_to_curve_g2(g2p *out, const fp2 *u) {
  fp2 A, B, Z, nba, bza;
  memcpy(&A, BLS_SSWU_A, sizeof(fp2));
  memcpy(&B, BLS_SSWU_B, sizeof(fp2));
  memcpy(&Z, BLS_SSWU_Z, sizeof(fp2));
  memcpy(&nba, BLS_SSWU_NBA, sizeof(fp2));
  memcpy(&bza, BLS_SSWU_BZA, sizeof(fp2));

  fp2 u2, zu2, tv, x1, gx1, y, x, one;
  fp2_sqr(&u2, u);
  fp2_mul(&zu2, &Z, &u2);
  fp2_sqr(&tv, &zu2);
  fp2_add(&tv, &tv, &zu2);            /* Z^2 u^4 + Z u^2 */
  if (fp2_is_zero(&tv)) {
    fp2_copy(&x1, &bza);              /* B/(Z*A) */
  } else {
    fp2 ti;
    fp2_inv(&ti, &tv);
    fp2_one(&one);
    fp2_add(&ti, &ti, &one);
    fp2_mul(&x1, &nba, &ti);          /* -B/A * (1 + 1/tv) */
  }
  fp2 t;
  fp2_sqr(&t, &x1);
  fp2_mul(&t, &t, &x1);
  fp2 ax;
  fp2_mul(&ax, &A, &x1);
  fp2_add(&t, &t, &ax);
  fp2_add(&gx1, &t, &B);
  if (fp2_sqrt(&y, &gx1)) {
    fp2_copy(&x, &x1);
  } else {
    fp2 x2, gx2;
    fp2_mul(&x2, &zu2, &x1);
    fp2_sqr(&t, &x2);
    fp2_mul(&t, &t, &x2);
    fp2_mul(&ax, &A, &x2);
    fp2_add(&t, &t, &ax);
    fp2_add(&gx2, &t, &B);
    fp2_sqrt(&y, &gx2);               /* must succeed */
    fp2_copy(&x, &x2);
  }
  if (fp2_sgn0(&y) != fp2_sgn0(u)) fp2_neg(&y, &y);

  /* 3-isogeny (Velu form): X(x) = x + t/(x-x0) + u/(x-x0)^2, then the
   * scaling isomorphism (x,y) -> (x/l^2, y/l^3). */
  fp2 x0c, tc, uc, d, di, di2, di3, xx, dx, two_u, yy;
  memcpy(&x0c, BLS_ISO_X0, sizeof(fp2));
  memcpy(&tc, BLS_ISO_T, sizeof(fp2));
  memcpy(&uc, BLS_ISO_U, sizeof(fp2));
  fp2_sub(&d, &x, &x0c);
  fp2_inv(&di, &d);
  fp2_sqr(&di2, &di);
  fp2_mul(&di3, &di2, &di);
  fp2 term;
  fp2_mul(&term, &tc, &di);
  fp2_add(&xx, &x, &term);
  fp2_mul(&term, &uc, &di2);
  fp2_add(&xx, &xx, &term);
  fp2_one(&one);
  fp2_mul(&term, &tc, &di2);
  fp2_sub(&dx, &one, &term);
  fp2_add(&two_u, &uc, &uc);
  fp2_mul(&term, &two_u, &di3);
  fp2_sub(&dx, &dx, &term);
  fp2_mul(&yy, &y, &dx);
  fp2_mul_fp(&xx, &xx, BLS_ISO_IL2);
  fp2_mul_fp(&yy, &yy, BLS_ISO_IL3);

  fp2_copy(&out->X, &xx);
  fp2_copy(&out->Y, &yy);
  fp2_one(&out->Z);
}

/* hash-to-curve returning affine Montgomery coordinates. */
static int hash_to_g2_aff(const uint8_t *msg, size_t msg_len,
                          const uint8_t *dst, size_t dst_len, fp2 *x, fp2 *y) {
  if (msg_len > 3000 || dst_len == 0 || dst_len > 255) return -1;
  uint8_t uniform[256];
  expand_message_xmd_256(msg, msg_len, dst, dst_len, uniform);
  fp2 u0, u1;
  fp_from_be64_mod(u0.c0, uniform);
  fp_from_be64_mod(u0.c1, uniform + 64);
  fp_from_be64_mod(u1.c0, uniform + 128);
  fp_from_be64_mod(u1.c1, uniform + 192);
  g2p q0, q1, q;
  map_to_curve_g2(&q0, &u0);
  map_to_curve_g2(&q1, &u1);
  g2_add(&q, &q0, &q1);
  g2_clear_cofactor(&q, &q);
  if (g2_is_infinity(&q)) return -2;  /* astronomically unlikely */
  g2_to_affine(x, y, &q);
  return 0;
}

int lodestar_bls_hash_to_g2(const uint8_t *msg, size_t msg_len,
                            const uint8_t *dst, size_t dst_len,
                            int32_t out_x[64], int32_t out_y[64]) {
  fp2 x, y;
  int rc = hash_to_g2_aff(msg, msg_len, dst, dst_len, &x, &y);
  if (rc != 0) return rc;
  fp_to_limbs12(out_x, x.c0);
  fp_to_limbs12(out_x + 32, x.c1);
  fp_to_limbs12(out_y, y.c0);
  fp_to_limbs12(out_y + 32, y.c1);
  return 0;
}

/* ---------------- pairing (optimal ate, host tier) ----------------
 *
 * The CPU verification fallback: without this the only non-device verify
 * path was the Python big-int oracle (~1 s/pairing) — any device outage
 * or the individual-retry path under attack traffic would collapse the
 * node.  Tower Fp2[v]/(v^3 - xi), xi = 1+u, then Fp6[w]/(w^2 - v) —
 * the same tower as the device tier (ops/fp6, ops/fp12) and the oracle
 * (bls/fields), so the Frobenius gamma tables are shared via
 * gen_bls12_consts.py.  Reference analog: blst's C pairing behind
 * verifyMultipleSignatures (chain/bls/maybeBatch.ts).
 */

typedef struct { fp2 c0, c1, c2; } fp6;
typedef struct { fp6 c0, c1; } fp12;

static void fp2_mul_xi(fp2 *r, const fp2 *a) {
  /* (1+u)(a0 + a1 u) = (a0 - a1) + (a0 + a1) u */
  fp t0, t1;
  fp_sub(t0, a->c0, a->c1);
  fp_add(t1, a->c0, a->c1);
  fp_copy(r->c0, t0);
  fp_copy(r->c1, t1);
}

static void fp6_add(fp6 *r, const fp6 *a, const fp6 *b) {
  fp2_add(&r->c0, &a->c0, &b->c0);
  fp2_add(&r->c1, &a->c1, &b->c1);
  fp2_add(&r->c2, &a->c2, &b->c2);
}
static void fp6_sub(fp6 *r, const fp6 *a, const fp6 *b) {
  fp2_sub(&r->c0, &a->c0, &b->c0);
  fp2_sub(&r->c1, &a->c1, &b->c1);
  fp2_sub(&r->c2, &a->c2, &b->c2);
}
static void fp6_neg(fp6 *r, const fp6 *a) {
  fp2_neg(&r->c0, &a->c0);
  fp2_neg(&r->c1, &a->c1);
  fp2_neg(&r->c2, &a->c2);
}
static void fp6_zero(fp6 *r) { fp2_zero(&r->c0); fp2_zero(&r->c1); fp2_zero(&r->c2); }
static void fp6_one(fp6 *r) { fp2_one(&r->c0); fp2_zero(&r->c1); fp2_zero(&r->c2); }
static int fp6_is_zero(const fp6 *a) {
  return fp2_is_zero(&a->c0) && fp2_is_zero(&a->c1) && fp2_is_zero(&a->c2);
}

static void fp6_mul(fp6 *r, const fp6 *a, const fp6 *b) {
  /* schoolbook with v^3 = xi */
  fp2 v0, v1, v2, t, s;
  fp2_mul(&v0, &a->c0, &b->c0);
  fp2_mul(&v1, &a->c1, &b->c1);
  fp2_mul(&v2, &a->c2, &b->c2);
  fp6 out;
  /* c0 = v0 + xi((a1+a2)(b1+b2) - v1 - v2) */
  fp2 a12, b12;
  fp2_add(&a12, &a->c1, &a->c2);
  fp2_add(&b12, &b->c1, &b->c2);
  fp2_mul(&t, &a12, &b12);
  fp2_sub(&t, &t, &v1);
  fp2_sub(&t, &t, &v2);
  fp2_mul_xi(&t, &t);
  fp2_add(&out.c0, &v0, &t);
  /* c1 = (a0+a1)(b0+b1) - v0 - v1 + xi v2 */
  fp2_add(&a12, &a->c0, &a->c1);
  fp2_add(&b12, &b->c0, &b->c1);
  fp2_mul(&t, &a12, &b12);
  fp2_sub(&t, &t, &v0);
  fp2_sub(&t, &t, &v1);
  fp2_mul_xi(&s, &v2);
  fp2_add(&out.c1, &t, &s);
  /* c2 = (a0+a2)(b0+b2) - v0 - v2 + v1 */
  fp2_add(&a12, &a->c0, &a->c2);
  fp2_add(&b12, &b->c0, &b->c2);
  fp2_mul(&t, &a12, &b12);
  fp2_sub(&t, &t, &v0);
  fp2_sub(&t, &t, &v2);
  fp2_add(&out.c2, &t, &v1);
  *r = out;
}
static void fp6_sqr(fp6 *r, const fp6 *a) { fp6_mul(r, a, a); }

static void fp6_mul_by_v(fp6 *r, const fp6 *a) {
  /* v(c0 + c1 v + c2 v^2) = xi c2 + c0 v + c1 v^2 */
  fp2 t;
  fp2_mul_xi(&t, &a->c2);
  fp2 c0 = a->c0, c1 = a->c1;
  fp2_copy(&r->c0, &t);
  fp2_copy(&r->c1, &c0);
  fp2_copy(&r->c2, &c1);
}

static void fp6_inv(fp6 *r, const fp6 *a) {
  /* standard: c0 = a0^2 - xi a1 a2, c1 = xi a2^2 - a0 a1,
   * c2 = a1^2 - a0 a2; t = a0 c0 + xi(a2 c1 + a1 c2); r = c_i / t */
  fp2 c0, c1, c2, t, s;
  fp2_sqr(&c0, &a->c0);
  fp2_mul(&t, &a->c1, &a->c2);
  fp2_mul_xi(&t, &t);
  fp2_sub(&c0, &c0, &t);
  fp2_sqr(&c1, &a->c2);
  fp2_mul_xi(&c1, &c1);
  fp2_mul(&t, &a->c0, &a->c1);
  fp2_sub(&c1, &c1, &t);
  fp2_sqr(&c2, &a->c1);
  fp2_mul(&t, &a->c0, &a->c2);
  fp2_sub(&c2, &c2, &t);
  fp2_mul(&t, &a->c0, &c0);
  fp2_mul(&s, &a->c2, &c1);
  fp2 s2;
  fp2_mul(&s2, &a->c1, &c2);
  fp2_add(&s, &s, &s2);
  fp2_mul_xi(&s, &s);
  fp2_add(&t, &t, &s);
  fp2 tinv;
  fp2_inv(&tinv, &t);
  fp2_mul(&r->c0, &c0, &tinv);
  fp2_mul(&r->c1, &c1, &tinv);
  fp2_mul(&r->c2, &c2, &tinv);
}

static void fp12_one(fp12 *r) { fp6_one(&r->c0); fp6_zero(&r->c1); }
static void fp12_conj(fp12 *r, const fp12 *a) {
  r->c0 = a->c0;
  fp6_neg(&r->c1, &a->c1);
}
static int fp12_is_one(const fp12 *a) {
  fp2 one;
  fp2_one(&one);
  return fp2_eq(&a->c0.c0, &one) && fp2_is_zero(&a->c0.c1) &&
         fp2_is_zero(&a->c0.c2) && fp6_is_zero(&a->c1);
}

static void fp12_mul(fp12 *r, const fp12 *a, const fp12 *b) {
  fp6 v0, v1, t, s;
  fp6_mul(&v0, &a->c0, &b->c0);
  fp6_mul(&v1, &a->c1, &b->c1);
  fp6_add(&t, &a->c0, &a->c1);
  fp6_add(&s, &b->c0, &b->c1);
  fp6_mul(&t, &t, &s);           /* (a0+a1)(b0+b1) */
  fp6_sub(&t, &t, &v0);
  fp6_sub(&t, &t, &v1);          /* c1 */
  fp6_mul_by_v(&s, &v1);
  fp6_add(&r->c0, &v0, &s);
  r->c1 = t;
}
static void fp12_sqr(fp12 *r, const fp12 *a) {
  /* complex squaring: c0 = (a0+a1)(a0+v a1) - v0 - v v0, c1 = 2 v0 */
  fp6 v0, t0, t1;
  fp6_mul(&v0, &a->c0, &a->c1);
  fp6_add(&t0, &a->c0, &a->c1);
  fp6_mul_by_v(&t1, &a->c1);
  fp6_add(&t1, &a->c0, &t1);
  fp6_mul(&t0, &t0, &t1);        /* (a0+a1)(a0 + v a1) */
  fp6_sub(&t0, &t0, &v0);
  fp6_mul_by_v(&t1, &v0);
  fp6_sub(&r->c0, &t0, &t1);
  fp6_add(&r->c1, &v0, &v0);
}

static void fp12_inv(fp12 *r, const fp12 *a) {
  /* (c0 - c1 w) / (c0^2 - v c1^2) */
  fp6 t0, t1;
  fp6_sqr(&t0, &a->c0);
  fp6_sqr(&t1, &a->c1);
  fp6_mul_by_v(&t1, &t1);
  fp6_sub(&t0, &t0, &t1);
  fp6_inv(&t0, &t0);
  fp6_mul(&r->c0, &a->c0, &t0);
  fp6_mul(&t1, &a->c1, &t0);
  fp6_neg(&r->c1, &t1);
}

/* sparse line multiply: f *= l0 + l1 w^2 + l2 w^3, i.e. in the fp6 pair
 * view A = (l0, l1, 0), B = (0, l2, 0) with f' = (f0 A + v f1 B,
 * (f0+f1)(A+B) - f0 A - f1 B)  [same layout as device ops/fp12.mul_by_line] */
static void fp6_mul_sparse01(fp6 *r, const fp6 *f, const fp2 *a0, const fp2 *a1) {
  /* f * (a0 + a1 v) */
  fp2 t0, t1, t2, s;
  fp6 out;
  fp2_mul(&t0, &f->c0, a0);
  fp2_mul(&t1, &f->c2, a1);
  fp2_mul_xi(&s, &t1);
  fp2_add(&out.c0, &t0, &s);
  fp2_mul(&t0, &f->c0, a1);
  fp2_mul(&t1, &f->c1, a0);
  fp2_add(&out.c1, &t0, &t1);
  fp2_mul(&t1, &f->c1, a1);
  fp2_mul(&t2, &f->c2, a0);
  fp2_add(&out.c2, &t1, &t2);
  *r = out;
}
static void fp6_mul_sparse1(fp6 *r, const fp6 *f, const fp2 *b1) {
  /* f * (b1 v) */
  fp2 t;
  fp6 out;
  fp2_mul(&t, &f->c2, b1);
  fp2_mul_xi(&out.c0, &t);
  fp2_mul(&out.c1, &f->c0, b1);
  fp2_mul(&out.c2, &f->c1, b1);
  *r = out;
}
static void fp12_mul_by_line(fp12 *f, const fp2 *l0, const fp2 *l1,
                             const fp2 *l2) {
  fp6 t0, t1, t2, g;
  fp2 s;
  fp6_mul_sparse01(&t0, &f->c0, l0, l1);     /* f0 * A */
  fp6_mul_sparse1(&t1, &f->c1, l2);          /* f1 * B */
  fp6_add(&g, &f->c0, &f->c1);
  fp2_add(&s, l1, l2);
  fp6_mul_sparse01(&t2, &g, l0, &s);         /* (f0+f1)(A+B) */
  fp6 vt1;
  fp6_mul_by_v(&vt1, &t1);
  fp6_add(&f->c0, &t0, &vt1);
  fp6_sub(&t2, &t2, &t0);
  fp6_sub(&f->c1, &t2, &t1);
}

/* Frobenius x^(p^k), k = 1..3, via the shared gamma tables: w-coefficient
 * view d = (c00, c10, c01, c11, c02, c12), conj each for odd k, then
 * d_i *= gamma_k[i] (same construction as device ops/fp12.frobenius). */
static void fp12_frobenius(fp12 *r, const fp12 *a, int k) {
  const uint64_t (*gam)[2][6] =
      k == 1 ? BLS_FROB_G1 : (k == 2 ? BLS_FROB_G2 : BLS_FROB_G3);
  const fp2 *d[6] = {&a->c0.c0, &a->c1.c0, &a->c0.c1,
                     &a->c1.c1, &a->c0.c2, &a->c1.c2};
  fp2 *o[6] = {&r->c0.c0, &r->c1.c0, &r->c0.c1,
               &r->c1.c1, &r->c0.c2, &r->c1.c2};
  for (int i = 0; i < 6; i++) {
    fp2 t;
    if (k & 1) fp2_conj(&t, d[i]);
    else fp2_copy(&t, d[i]);
    fp2 g;
    memcpy(&g, gam[i], sizeof(fp2));
    fp2_mul(o[i], &t, &g);
  }
}

/* Granger–Scott cyclotomic squaring (valid after the easy part) — the
 * same three-Fp4 formulas as device ops/fp12.cyclotomic_square. */
static void fp12_cyclotomic_sqr(fp12 *r, const fp12 *g) {
  const fp2 *a = &g->c0.c0, *b = &g->c0.c1, *c = &g->c0.c2;
  const fp2 *d = &g->c1.c0, *e = &g->c1.c1, *f = &g->c1.c2;
  fp2 a2, e2, c2, d2, b2, f2, t, t0, t2, t4, t6, t7, t8;
  fp2_sqr(&a2, a); fp2_sqr(&e2, e); fp2_sqr(&c2, c);
  fp2_sqr(&d2, d); fp2_sqr(&b2, b); fp2_sqr(&f2, f);
  /* t6 = 2ae, t7 = 2cd, t8 = 2bf*xi via (x+y)^2 - x^2 - y^2 */
  fp2_add(&t, a, e); fp2_sqr(&t, &t); fp2_sub(&t, &t, &a2); fp2_sub(&t6, &t, &e2);
  fp2_add(&t, c, d); fp2_sqr(&t, &t); fp2_sub(&t, &t, &c2); fp2_sub(&t7, &t, &d2);
  fp2_add(&t, b, f); fp2_sqr(&t, &t); fp2_sub(&t, &t, &b2); fp2_sub(&t, &t, &f2);
  fp2_mul_xi(&t8, &t);
  fp2_mul_xi(&t, &e2); fp2_add(&t0, &t, &a2);     /* t0 = a^2 + xi e^2 */
  fp2_mul_xi(&t, &c2); fp2_add(&t2, &t, &d2);     /* t2 = d^2 + xi c^2 */
  fp2_mul_xi(&t, &f2); fp2_add(&t4, &t, &b2);     /* t4 = b^2 + xi f^2 */
  fp12 out;
  /* c0' = (3t0 - 2a, 3t2 - 2b, 3t4 - 2c); c1' = (3t8+2d, 3t6+2e, 3t7+2f) */
  fp2 y;
#define GS_MINUS(dst, tv, xv)                                                  \
  do {                                                                         \
    fp2_sub(&y, &(tv), (xv));                                                  \
    fp2_add(&y, &y, &y);                                                       \
    fp2_add(&(dst), &y, &(tv));                                                \
  } while (0)
#define GS_PLUS(dst, tv, xv)                                                   \
  do {                                                                         \
    fp2_add(&y, &(tv), (xv));                                                  \
    fp2_add(&y, &y, &y);                                                       \
    fp2_add(&(dst), &y, &(tv));                                                \
  } while (0)
  GS_MINUS(out.c0.c0, t0, a);
  GS_MINUS(out.c0.c1, t2, b);
  GS_MINUS(out.c0.c2, t4, c);
  GS_PLUS(out.c1.c0, t8, d);
  GS_PLUS(out.c1.c1, t6, e);
  GS_PLUS(out.c1.c2, t7, f);
#undef GS_MINUS
#undef GS_PLUS
  *r = out;
}

/* line + double / line + add on homogeneous projective T (ported 1:1 from
 * device ops/pairing._line_and_double/_line_and_add, affine-P variant). */
static void pair_line_dbl(fp2 *l0, fp2 *l1, fp2 *l2, g2p *t,
                          const fp xp_neg, const fp yp) {
  fp2 xx, yy, zz, yz, xy, xxx, yyz, xxz, yzz, t2b, b2;
  memcpy(&b2, BLS_B2_M, sizeof(fp2));
  fp2 b3;
  fp2_add(&b3, &b2, &b2);
  fp2_add(&b3, &b3, &b2);
  fp2_sqr(&xx, &t->X);
  fp2_sqr(&yy, &t->Y);
  fp2_sqr(&zz, &t->Z);
  fp2_mul(&yz, &t->Y, &t->Z);
  fp2_mul(&xy, &t->X, &t->Y);
  fp2_mul(&xxx, &xx, &t->X);
  fp2_mul(&yyz, &yy, &t->Z);
  fp2_mul(&xxz, &xx, &t->Z);
  fp2_mul(&yzz, &yz, &t->Z);
  fp2_mul(&t2b, &b3, &zz);
  /* l0 = 3X^3 - 2Y^2 Z */
  fp2 s;
  fp2_add(l0, &xxx, &xxx);
  fp2_add(l0, l0, &xxx);
  fp2_add(&s, &yyz, &yyz);
  fp2_sub(l0, l0, &s);
  /* l1 = 3X^2 Z * (-xp),  l2 = 2YZ^2 * yp */
  fp2 three_xxz, two_yzz;
  fp2_add(&three_xxz, &xxz, &xxz);
  fp2_add(&three_xxz, &three_xxz, &xxz);
  fp2_mul_fp(l1, &three_xxz, xp_neg);
  fp2_add(&two_yzz, &yzz, &yzz);
  fp2_mul_fp(l2, &two_yzz, yp);
  /* double (RCB16 alg 9): */
  fp2 z8, y3s, t0c;
  fp2_add(&z8, &yy, &yy);
  fp2_add(&z8, &z8, &z8);
  fp2_add(&z8, &z8, &z8);                 /* 8Y^2 */
  fp2_add(&y3s, &yy, &t2b);
  fp2_add(&s, &t2b, &t2b);
  fp2_add(&s, &s, &t2b);
  fp2_sub(&t0c, &yy, &s);                 /* Y^2 - 3 b3 Z^2 */
  fp2 x3, z3, y3m, xt;
  fp2_mul(&x3, &t2b, &z8);
  fp2_mul(&z3, &yz, &z8);
  fp2_mul(&y3m, &t0c, &y3s);
  fp2_mul(&xt, &t0c, &xy);
  fp2_add(&t->X, &xt, &xt);
  fp2_add(&t->Y, &x3, &y3m);
  fp2_copy(&t->Z, &z3);
}

static void pair_line_add(fp2 *l0, fp2 *l1, fp2 *l2, g2p *t,
                          const fp2 *xq, const fp2 *yq, const fp xp_neg,
                          const fp yp) {
  fp2 b2, b3;
  memcpy(&b2, BLS_B2_M, sizeof(fp2));
  fp2_add(&b3, &b2, &b2);
  fp2_add(&b3, &b3, &b2);
  fp2 t0, t1, u, xqz, yqz, b3z, s;
  fp2_mul(&t0, &t->X, xq);
  fp2_mul(&t1, &t->Y, yq);
  fp2_add(&u, &t->X, &t->Y);
  fp2_add(&s, xq, yq);
  fp2_mul(&u, &u, &s);                       /* (X+Y)(xq+yq) */
  fp2_mul(&xqz, xq, &t->Z);
  fp2_mul(&yqz, yq, &t->Z);
  fp2_mul(&b3z, &b3, &t->Z);
  fp2 theta, h;
  fp2_sub(&theta, &t->Y, &yqz);              /* Y - yq Z */
  fp2_sub(&h, &t->X, &xqz);                  /* X - xq Z */
  /* lines: l0 = theta xq - yq h, l1 = theta(-xp), l2 = h yp */
  fp2 thxq, yqh;
  fp2_mul(&thxq, &theta, xq);
  fp2_mul(&yqh, yq, &h);
  fp2_sub(l0, &thxq, &yqh);
  fp2_mul_fp(l1, &theta, xp_neg);
  fp2_mul_fp(l2, &h, yp);
  /* mixed addition (RCB16 alg 8) */
  fp2 t3, y3p, t4, x3, z3, t1m, y3;
  fp2_sub(&t3, &u, &t0);
  fp2_sub(&t3, &t3, &t1);                    /* xy cross */
  fp2_add(&y3p, &xqz, &t->X);
  fp2_add(&t4, &yqz, &t->Y);
  fp2_add(&x3, &t0, &t0);
  fp2_add(&x3, &x3, &t0);                    /* 3 X xq */
  fp2_add(&z3, &t1, &b3z);
  fp2_sub(&t1m, &t1, &b3z);
  fp2_mul(&y3, &b3, &y3p);
  fp2 a_, b_, c_, d_, e_, f_;
  fp2_mul(&a_, &t3, &t1m);
  fp2_mul(&b_, &t4, &y3);
  fp2_mul(&c_, &y3, &x3);
  fp2_mul(&d_, &t1m, &z3);
  fp2_mul(&e_, &z3, &t4);
  fp2_mul(&f_, &x3, &t3);
  fp2_sub(&t->X, &a_, &b_);
  fp2_add(&t->Y, &c_, &d_);
  fp2_add(&t->Z, &e_, &f_);
}

/* f = conj(f_{|x|,Q}(P)) for P = (xp, yp) affine G1, Q affine G2 —
 * same convention as the oracle/device tiers. */
static void miller_loop_c(fp12 *f, const fp xp, const fp yp, const fp2 *xq,
                          const fp2 *yq) {
  fp xp_neg;
  fp_neg(xp_neg, xp);
  g2p t;
  fp2_copy(&t.X, xq);
  fp2_copy(&t.Y, yq);
  fp2_one(&t.Z);
  fp12_one(f);
  uint64_t x_abs = BLS_X_ABS[0];
  int top = 63;
  while (!((x_abs >> top) & 1)) top--;
  fp2 l0, l1, l2;
  for (int i = top - 1; i >= 0; i--) {
    fp12_sqr(f, f);
    pair_line_dbl(&l0, &l1, &l2, &t, xp_neg, yp);
    fp12_mul_by_line(f, &l0, &l1, &l2);
    if ((x_abs >> i) & 1) {
      pair_line_add(&l0, &l1, &l2, &t, xq, yq, xp_neg, yp);
      fp12_mul_by_line(f, &l0, &l1, &l2);
    }
  }
  fp12_conj(f, f);  /* x < 0 */
}

static void fp12_pow_x_abs(fp12 *r, const fp12 *g) {
  /* g^|x| with cyclotomic squarings (g is in the cyclotomic subgroup) */
  fp12 acc = *g;
  uint64_t x_abs = BLS_X_ABS[0];
  int top = 63;
  while (!((x_abs >> top) & 1)) top--;
  for (int i = top - 1; i >= 0; i--) {
    fp12_cyclotomic_sqr(&acc, &acc);
    if ((x_abs >> i) & 1) fp12_mul(&acc, &acc, g);
  }
  *r = acc;
}
static void fp12_pow_x(fp12 *r, const fp12 *g) {
  fp12_pow_x_abs(r, g);
  fp12_conj(r, r);  /* x negative */
}

/* final exponentiation — easy part then the HHT hard part; computes
 * pairing^3 exactly like the oracle/device (harmless for ==1 checks). */
static void final_exp_c(fp12 *r, const fp12 *f_in) {
  fp12 f, t;
  fp12_conj(&f, f_in);
  fp12_inv(&t, f_in);
  fp12_mul(&f, &f, &t);            /* f^(p^6 - 1) */
  fp12_frobenius(&t, &f, 2);
  fp12_mul(&f, &t, &f);            /* ^(p^2 + 1): cyclotomic now */
  /* a = pxm1(pxm1(f)), pxm1(g) = g^x * conj(g) */
  fp12 a, b, c, s;
  fp12_pow_x(&a, &f);
  fp12_conj(&t, &f);
  fp12_mul(&a, &a, &t);
  fp12_pow_x(&s, &a);
  fp12_conj(&t, &a);
  fp12_mul(&a, &s, &t);
  /* b = a^x * frob1(a) */
  fp12_pow_x(&b, &a);
  fp12_frobenius(&t, &a, 1);
  fp12_mul(&b, &b, &t);
  /* c = b^(x^2) * frob2(b) * conj(b) */
  fp12_pow_x(&c, &b);
  fp12_pow_x(&c, &c);
  fp12_frobenius(&t, &b, 2);
  fp12_mul(&c, &c, &t);
  fp12_conj(&t, &b);
  fp12_mul(&c, &c, &t);
  /* result = c * f^3 */
  fp12_sqr(&t, &f);
  fp12_mul(&t, &t, &f);
  fp12_mul(r, &c, &t);
}

/* dual Miller loop: f = conj(f_{|x|,Q1}(P1) * f_{|x|,Q2}(P2)) — ONE
 * shared fp12 squaring chain for both pairs (the squarings dominate;
 * a multi-pairing halves them vs two separate loops). */
static void miller_loop2_c(fp12 *f, const fp p1x, const fp p1y,
                           const fp2 *q1x, const fp2 *q1y, const fp p2x,
                           const fp p2y, const fp2 *q2x, const fp2 *q2y) {
  fp p1x_neg, p2x_neg;
  fp_neg(p1x_neg, p1x);
  fp_neg(p2x_neg, p2x);
  g2p t1, t2;
  fp2_copy(&t1.X, q1x); fp2_copy(&t1.Y, q1y); fp2_one(&t1.Z);
  fp2_copy(&t2.X, q2x); fp2_copy(&t2.Y, q2y); fp2_one(&t2.Z);
  fp12_one(f);
  uint64_t x_abs = BLS_X_ABS[0];
  int top = 63;
  while (!((x_abs >> top) & 1)) top--;
  fp2 l0, l1, l2;
  for (int i = top - 1; i >= 0; i--) {
    fp12_sqr(f, f);
    pair_line_dbl(&l0, &l1, &l2, &t1, p1x_neg, p1y);
    fp12_mul_by_line(f, &l0, &l1, &l2);
    pair_line_dbl(&l0, &l1, &l2, &t2, p2x_neg, p2y);
    fp12_mul_by_line(f, &l0, &l1, &l2);
    if ((x_abs >> i) & 1) {
      pair_line_add(&l0, &l1, &l2, &t1, q1x, q1y, p1x_neg, p1y);
      fp12_mul_by_line(f, &l0, &l1, &l2);
      pair_line_add(&l0, &l1, &l2, &t2, q2x, q2y, p2x_neg, p2y);
      fp12_mul_by_line(f, &l0, &l1, &l2);
    }
  }
  fp12_conj(f, f);  /* x < 0 */
}

/* one signature set: e(pk, H(m)) * e(-g1, sig) == 1 */
static int pairing_verify_one(const fp pk_x, const fp pk_y, const fp2 *h_x,
                              const fp2 *h_y, const fp2 *sig_x,
                              const fp2 *sig_y) {
  fp12 f;
  fp g1x, g1y_neg, gy;
  memcpy(g1x, BLS_G1_GX, sizeof(fp));
  memcpy(gy, BLS_G1_GY, sizeof(fp));
  fp_neg(g1y_neg, gy);
  miller_loop2_c(&f, pk_x, pk_y, h_x, h_y, g1x, g1y_neg, sig_x, sig_y);
  final_exp_c(&f, &f);
  return fp12_is_one(&f);
}

/* reassemble a field element from 32x12-bit device limbs (they carry the
 * Montgomery form directly — fp_to_limbs12 is the inverse). */
static void fp_from_limbs12(fp r, const int32_t in[32]) {
  uint64_t w[8];
  memset(w, 0, sizeof(w));
  for (int i = 0; i < 32; i++) {
    uint64_t v = (uint64_t)(uint32_t)in[i] & 0xFFF;
    int bit = 12 * i;
    w[bit / 64] |= v << (bit % 64);
    if ((bit % 64) > 52) w[bit / 64 + 1] |= v >> (64 - bit % 64);
  }
  memcpy(r, w, sizeof(fp));
}

/* Verify n signature sets on the CPU (pubkey 48B, 32B signing root,
 * signature 96B per set); out_ok[i] = 1 iff set i verifies.  The
 * production fallback/oracle tier (reference: blst verify in
 * chain/bls/maybeBatch.ts) — ~10 ms/set/core on this host vs the Python
 * oracle's ~2 s/set.  h_x/h_y non-NULL: per-set hash-to-curve device
 * limbs from the caller's signing-root cache (gossip shares roots, so
 * hashing dominates otherwise); msgs/msg_lens may then be NULL. */
int lodestar_bls_verify_sets(size_t n, const uint8_t *pks,
                             const uint8_t *msgs, const size_t *msg_lens,
                             const uint8_t *sigs, const uint8_t *dst,
                             size_t dst_len, const int32_t *h_x,
                             const int32_t *h_y, uint8_t *out_ok) {
  size_t msg_off = 0;
  for (size_t i = 0; i < n; i++) {
    out_ok[i] = 0;
    g1p pk;
    int rc = g1_parse_compressed(pks + 48 * i, &pk);
    if (rc != 0) continue;                 /* infinity pk invalid (KeyValidate) */
    if (!g1_in_subgroup(&pk)) continue;
    fp2 sx, sy;
    rc = g2_parse_compressed_aff(sigs + 96 * i, &sx, &sy, 1);
    if (rc != 0) continue;                 /* infinity sig never verifies */
    fp2 hx, hy;
    if (h_x != NULL && h_y != NULL) {
      fp_from_limbs12(hx.c0, h_x + 64 * i);
      fp_from_limbs12(hx.c1, h_x + 64 * i + 32);
      fp_from_limbs12(hy.c0, h_y + 64 * i);
      fp_from_limbs12(hy.c1, h_y + 64 * i + 32);
    } else {
      const uint8_t *msg = msgs + msg_off;
      size_t msg_len = msg_lens[i];
      msg_off += msg_len;
      if (hash_to_g2_aff(msg, msg_len, dst, dst_len, &hx, &hy) != 0) continue;
    }
    fp pkx, pky;
    g1_to_affine(pkx, pky, &pk);
    out_ok[i] = (uint8_t)pairing_verify_one(pkx, pky, &hx, &hy, &sx, &sy);
  }
  return 0;
}

/* ---------------- signing ----------------
 *
 * sign = [sk]·H(m): the host-tier signer (the Python oracle's G2 scalar
 * mul + hash costs ~50 ms/signature, which dominates every multi-epoch
 * simulation in the test suite; this is ~6x). */

static void fp_to_be48(uint8_t out[48], const uint64_t w[6]) {
  for (int i = 0; i < 6; i++)
    for (int b = 0; b < 8; b++)
      out[48 - 8 * i - 1 - b] = (uint8_t)(w[i] >> (8 * b));
}

int lodestar_bls_sign(const uint8_t sk_be[32], const uint8_t *msg,
                      size_t msg_len, const uint8_t *dst, size_t dst_len,
                      uint8_t out[96]) {
  uint64_t k[4];
  for (int i = 0; i < 4; i++) {
    uint64_t v = 0;
    for (int b = 0; b < 8; b++) v = (v << 8) | sk_be[8 * i + b];
    k[3 - i] = v;
  }
  /* 0 < sk < r */
  int all_zero = !(k[0] | k[1] | k[2] | k[3]);
  if (all_zero || fp_cmp_ge(k, BLS_ORDER_R, 4)) return -1;
  fp2 hx, hy;
  int rc = hash_to_g2_aff(msg, msg_len, dst, dst_len, &hx, &hy);
  if (rc != 0) return rc;
  g2p h, s;
  fp2_copy(&h.X, &hx);
  fp2_copy(&h.Y, &hy);
  fp2_one(&h.Z);
  g2_scalar_mul(&s, &h, k, 4);
  if (g2_is_infinity(&s)) return -2;  /* impossible for valid sk */
  fp2 x, y;
  g2_to_affine(&x, &y, &s);
  /* ZCash compressed: 48B c1 (flags in byte 0) then 48B c0, both BE */
  uint64_t w[6];
  fp_from_mont(w, x.c1);
  fp_to_be48(out, w);
  fp_from_mont(w, x.c0);
  fp_to_be48(out + 48, w);
  out[0] |= FLAG_C;
  if (fp2_lex_larger(&y)) out[0] |= FLAG_S;
  return 0;
}

/* ---------------- aggregation ---------------- */

/* Aggregate n compressed G1 pubkeys -> device limbs of the affine sum.
 * Returns 0 ok / 1 aggregate-is-infinity / -1 malformed / -2 off-curve /
 * -3 subgroup.  Infinity pubkeys contribute nothing (callers reject them
 * upstream at KeyValidate). */
int lodestar_bls_g1_aggregate(const uint8_t *pks, size_t n, int check_each,
                              int32_t out_x[32], int32_t out_y[32]) {
  memset(out_x, 0, 32 * sizeof(int32_t));
  memset(out_y, 0, 32 * sizeof(int32_t));
  g1p acc;
  g1_infinity(&acc);
  for (size_t i = 0; i < n; i++) {
    g1p p;
    int rc = g1_parse_compressed(pks + 48 * i, &p);
    if (rc == 1) continue;
    if (rc != 0) return rc;
    if (check_each && !g1_in_subgroup(&p)) return -3;
    g1_add(&acc, &acc, &p);
  }
  if (g1_is_infinity(&acc)) return 1;
  fp x, y;
  g1_to_affine(x, y, &acc);
  fp_to_limbs12(out_x, x);
  fp_to_limbs12(out_y, y);
  return 0;
}

/* ---------------- batched set marshalling ----------------
 *
 * For n signature sets (pubkey 48B, message 32B signing root, signature
 * 96B) fill the device arrays pk_x/pk_y (n,32), msg_x/msg_y/sig_x/sig_y
 * (n,64) and ok (n bytes).  A set that fails decompression/subgroup or has
 * an infinity pubkey/signature gets ok=0 and zeroed lanes (the reference
 * rejects those sets: maybeBatch.ts catching blst errors).
 */
int lodestar_bls_marshal_sets(size_t n, const uint8_t *pks, const uint8_t *msgs,
                              const uint8_t *sigs, const uint8_t *dst,
                              size_t dst_len, int check_pk_subgroup,
                              int check_sig_subgroup, int do_hash, int do_pk,
                              int32_t *pk_x, int32_t *pk_y, int32_t *msg_x,
                              int32_t *msg_y, int32_t *sig_x, int32_t *sig_y,
                              uint8_t *ok) {
  /* do_hash=0: caller fills msg_x/msg_y itself (e.g. from a
   * hash-to-curve cache — gossip shares signing roots across a whole
   * committee, so per-set hashing is mostly redundant work).
   * do_pk=0: caller fills pk_x/pk_y from its pubkey-limb cache (the
   * reference's pubkey cache deserializes each validator key once —
   * attesters repeat every epoch, so the per-set G1 sqrt is redundant
   * steady-state work). */
  if (!do_pk) {
    memset(pk_x, 0, n * 32 * sizeof(int32_t));
    memset(pk_y, 0, n * 32 * sizeof(int32_t));
  }
  for (size_t i = 0; i < n; i++) {
    ok[i] = 0;
    if (do_pk) {
      int rcp = lodestar_bls_g1_decompress(pks + 48 * i, pk_x + 32 * i,
                                           pk_y + 32 * i, check_pk_subgroup);
      if (rcp != 0) continue; /* infinity pubkey is invalid per Eth2 */
    }
    int rc = lodestar_bls_g2_decompress(sigs + 96 * i, sig_x + 64 * i,
                                        sig_y + 64 * i, check_sig_subgroup);
    if (rc != 0) continue;
    if (do_hash) {
      rc = lodestar_bls_hash_to_g2(msgs + 32 * i, 32, dst, dst_len,
                                   msg_x + 64 * i, msg_y + 64 * i);
      if (rc != 0) continue;
    }
    ok[i] = 1;
  }
  return 0;
}
