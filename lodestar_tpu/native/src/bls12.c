/* Native BLS12-381 host tier: the marshalling fast path.
 *
 * Replaces the pure-Python big-int hot path between wire bytes and the
 * device verifier (reference analog: blst's in-C preprocessing used by
 * chain/bls/multithread/worker.ts:33-55 and main-thread aggregation
 * bls/utils.ts:5-16).  Scope:
 *
 *   - G1/G2 point decompression (ZCash flags) + on-curve + subgroup checks
 *   - SSWU hash-to-curve for G2 (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_)
 *   - G1 pubkey aggregation
 *   - batched signature-set marshalling straight into the device's
 *     32x12-bit Montgomery limb layout (ops/limbs.py)
 *
 * Field arithmetic: 6x64-bit limbs, Montgomery form (R = 2^384), CIOS
 * multiplication with __uint128_t.  All constants are generated from the
 * Python oracle (gen_bls12_consts.py) so the two tiers cannot disagree.
 * Scalar multiplications here are variable-time: every input is public
 * (signatures, pubkeys, message hashes) — no secrets are processed.
 */

#include <stdint.h>
#include <string.h>
#include <stddef.h>

#include "bls12_consts.h"

void lodestar_sha256(const uint8_t *data, size_t len, uint8_t out[32]);

typedef uint64_t fp[6];
typedef struct { fp c0, c1; } fp2;
typedef struct { fp X, Y, Z; } g1p;   /* jacobian; Z==0 -> infinity */
typedef struct { fp2 X, Y, Z; } g2p;

/* ---------------- fp ---------------- */

static void fp_copy(fp r, const fp a) { memcpy(r, a, sizeof(fp)); }
static void fp_zero(fp r) { memset(r, 0, sizeof(fp)); }
static int fp_is_zero(const fp a) {
  return (a[0] | a[1] | a[2] | a[3] | a[4] | a[5]) == 0;
}
static int fp_eq(const fp a, const fp b) { return memcmp(a, b, sizeof(fp)) == 0; }

/* a >= b (both < 2^384) */
static int fp_cmp_ge(const uint64_t *a, const uint64_t *b, int n) {
  for (int i = n - 1; i >= 0; i--) {
    if (a[i] > b[i]) return 1;
    if (a[i] < b[i]) return 0;
  }
  return 1;
}

static void fp_sub_raw(uint64_t *r, const uint64_t *a, const uint64_t *b, int n) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < n; i++) {
    unsigned __int128 d = (unsigned __int128)a[i] - b[i] - (uint64_t)borrow;
    r[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

static void fp_add(fp r, const fp a, const fp b) {
  unsigned __int128 c = 0;
  for (int i = 0; i < 6; i++) {
    c += (unsigned __int128)a[i] + b[i];
    r[i] = (uint64_t)c;
    c >>= 64;
  }
  if (c || fp_cmp_ge(r, BLS_P, 6)) {
    /* subtract p (carry c can only be 0 here since 2p < 2^384+p... handle both) */
    uint64_t t[6];
    fp_sub_raw(t, r, BLS_P, 6);
    /* if there was a carry out, the subtraction is unconditionally right */
    fp_copy(r, t);
  }
}

static void fp_sub(fp r, const fp a, const fp b) {
  if (fp_cmp_ge(a, b, 6)) {
    fp_sub_raw(r, a, b, 6);
  } else {
    uint64_t t[6];
    unsigned __int128 c = 0;
    for (int i = 0; i < 6; i++) {
      c += (unsigned __int128)a[i] + BLS_P[i];
      t[i] = (uint64_t)c;
      c >>= 64;
    }
    fp_sub_raw(r, t, b, 6);
  }
}

static void fp_neg(fp r, const fp a) {
  if (fp_is_zero(a)) { fp_zero(r); return; }
  fp_sub_raw(r, BLS_P, a, 6);
}

/* CIOS Montgomery multiplication: r = a*b*R^-1 mod p, result < p. */
static void fp_mul(fp r, const fp a, const fp b) {
  uint64_t t[8];
  memset(t, 0, sizeof(t));
  for (int i = 0; i < 6; i++) {
    unsigned __int128 c = 0;
    for (int j = 0; j < 6; j++) {
      c += (unsigned __int128)a[j] * b[i] + t[j];
      t[j] = (uint64_t)c;
      c >>= 64;
    }
    c += t[6];
    t[6] = (uint64_t)c;
    t[7] = (uint64_t)(c >> 64);

    uint64_t m = t[0] * BLS_N0;
    c = (unsigned __int128)m * BLS_P[0] + t[0];
    c >>= 64;
    for (int j = 1; j < 6; j++) {
      c += (unsigned __int128)m * BLS_P[j] + t[j];
      t[j - 1] = (uint64_t)c;
      c >>= 64;
    }
    c += t[6];
    t[5] = (uint64_t)c;
    t[6] = t[7] + (uint64_t)(c >> 64);
  }
  if (t[6] || fp_cmp_ge(t, BLS_P, 6)) fp_sub_raw(t, t, BLS_P, 6);
  memcpy(r, t, sizeof(fp));
}

static void fp_sqr(fp r, const fp a) { fp_mul(r, a, a); }

/* a^e for little-endian word exponent (variable time; public data only). */
static void fp_exp(fp r, const fp a, const uint64_t *e, int words) {
  fp acc;
  fp_copy(acc, BLS_ONE_M);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) fp_sqr(acc, acc);
      if ((e[w] >> b) & 1) {
        if (started) fp_mul(acc, acc, a);
        else { fp_copy(acc, a); started = 1; }
      }
    }
  }
  fp_copy(r, acc);
}

static void fp_inv(fp r, const fp a) { fp_exp(r, a, BLS_EXP_INV, 6); }

/* sqrt (p = 3 mod 4): cand = a^((p+1)/4); returns 0 if a is not a QR. */
static int fp_sqrt(fp r, const fp a) {
  fp cand, chk;
  fp_exp(cand, a, BLS_EXP_SQRT, 6);
  fp_sqr(chk, cand);
  if (!fp_eq(chk, a)) return 0;
  fp_copy(r, cand);
  return 1;
}

/* Montgomery -> canonical integer (little-endian words). */
static void fp_from_mont(uint64_t out[6], const fp a) {
  fp one = {1, 0, 0, 0, 0, 0};
  fp_mul((uint64_t *)out, a, one);
}

static void fp_to_mont(fp r, const uint64_t in[6]) { fp_mul(r, in, BLS_R2); }

static int fp_sgn0(const fp a) {
  uint64_t c[6];
  fp_from_mont(c, a);
  return (int)(c[0] & 1);
}

static int fp_lex_larger(const fp a) {
  uint64_t c[6];
  fp_from_mont(c, a);
  /* canonical > (p-1)/2 */
  for (int i = 5; i >= 0; i--) {
    if (c[i] > BLS_HALF_P[i]) return 1;
    if (c[i] < BLS_HALF_P[i]) return 0;
  }
  return 0; /* equal -> not larger */
}

/* 48 big-endian bytes -> canonical words; returns 0 if >= p. */
static int fp_from_be(uint64_t out[6], const uint8_t in[48]) {
  for (int i = 0; i < 6; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[(5 - i) * 8 + j];
    out[i] = w;
  }
  return !fp_cmp_ge(out, BLS_P, 6);
}

/* Montgomery fp -> 32x12-bit int32 device limbs (value = a*R mod p). */
static void fp_to_limbs12(int32_t out[32], const fp a) {
  /* the Montgomery residue itself is what the device stores */
  const uint64_t *w = a;
  for (int i = 0; i < 32; i++) {
    int bit = i * 12;
    int word = bit >> 6, off = bit & 63;
    uint64_t v = w[word] >> off;
    if (off > 52 && word < 5) v |= w[word + 1] << (64 - off);
    out[i] = (int32_t)(v & 0xFFF);
  }
}

/* ---------------- fp2 ---------------- */

static void fp2_copy(fp2 *r, const fp2 *a) { *r = *a; }
static void fp2_zero(fp2 *r) { fp_zero(r->c0); fp_zero(r->c1); }
static int fp2_is_zero(const fp2 *a) { return fp_is_zero(a->c0) && fp_is_zero(a->c1); }
static int fp2_eq(const fp2 *a, const fp2 *b) {
  return fp_eq(a->c0, b->c0) && fp_eq(a->c1, b->c1);
}
static void fp2_one(fp2 *r) { fp_copy(r->c0, BLS_ONE_M); fp_zero(r->c1); }

static void fp2_add(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_add(r->c0, a->c0, b->c0);
  fp_add(r->c1, a->c1, b->c1);
}
static void fp2_sub(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_sub(r->c0, a->c0, b->c0);
  fp_sub(r->c1, a->c1, b->c1);
}
static void fp2_neg(fp2 *r, const fp2 *a) {
  fp_neg(r->c0, a->c0);
  fp_neg(r->c1, a->c1);
}
static void fp2_conj(fp2 *r, const fp2 *a) {
  fp_copy(r->c0, a->c0);
  fp_neg(r->c1, a->c1);
}

static void fp2_mul(fp2 *r, const fp2 *a, const fp2 *b) {
  fp t0, t1, t2, t3, s0, s1;
  fp_mul(t0, a->c0, b->c0);
  fp_mul(t1, a->c1, b->c1);
  fp_add(t2, a->c0, a->c1);
  fp_add(t3, b->c0, b->c1);
  fp_mul(t2, t2, t3);          /* (a0+a1)(b0+b1) */
  fp_sub(s0, t0, t1);          /* c0 = a0b0 - a1b1 */
  fp_sub(t2, t2, t0);
  fp_sub(s1, t2, t1);          /* c1 = cross */
  fp_copy(r->c0, s0);
  fp_copy(r->c1, s1);
}

static void fp2_sqr(fp2 *r, const fp2 *a) {
  fp t0, t1, s0;
  fp_add(t0, a->c0, a->c1);
  fp_sub(t1, a->c0, a->c1);
  fp_mul(s0, t0, t1);          /* (a0+a1)(a0-a1) */
  fp_mul(t0, a->c0, a->c1);
  fp_copy(r->c0, s0);
  fp_add(r->c1, t0, t0);       /* 2 a0 a1 */
}

static void fp2_mul_fp(fp2 *r, const fp2 *a, const fp k) {
  fp_mul(r->c0, a->c0, k);
  fp_mul(r->c1, a->c1, k);
}

static void fp2_inv(fp2 *r, const fp2 *a) {
  fp n, n0, n1;
  fp_sqr(n0, a->c0);
  fp_sqr(n1, a->c1);
  fp_add(n, n0, n1);
  fp_inv(n, n);
  fp_mul(r->c0, a->c0, n);
  fp_mul(n, a->c1, n);
  fp_neg(r->c1, n);
}

static void fp2_exp(fp2 *r, const fp2 *a, const uint64_t *e, int words) {
  fp2 acc;
  fp2_one(&acc);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) fp2_sqr(&acc, &acc);
      if ((e[w] >> b) & 1) {
        if (started) fp2_mul(&acc, &acc, a);
        else { fp2_copy(&acc, a); started = 1; }
      }
    }
  }
  fp2_copy(r, &acc);
}

/* Fq2 sqrt: cand = a^((p^2+7)/16) corrected by {1, i, w, iw}. 0 = not a QR. */
static int fp2_sqrt(fp2 *r, const fp2 *a) {
  if (fp2_is_zero(a)) { fp2_zero(r); return 1; }
  fp2 cand, s, chk;
  fp2_exp(&cand, a, BLS_EXP_SQRT_FQ2, 12);
  for (int i = 0; i < 4; i++) {
    fp2 corr;
    fp_copy(corr.c0, BLS_SQRT_CORR[i][0]);
    fp_copy(corr.c1, BLS_SQRT_CORR[i][1]);
    fp2_mul(&s, &cand, &corr);
    fp2_sqr(&chk, &s);
    if (fp2_eq(&chk, a)) { fp2_copy(r, &s); return 1; }
  }
  return 0;
}

static int fp2_sgn0(const fp2 *a) {
  /* RFC 9380 sgn0, m=2 */
  uint64_t c0[6];
  fp_from_mont(c0, a->c0);
  int sign_0 = (int)(c0[0] & 1);
  int zero_0 = 1;
  for (int i = 0; i < 6; i++) zero_0 &= (c0[i] == 0);
  int sign_1 = fp_sgn0(a->c1);
  return sign_0 | (zero_0 & sign_1);
}

static int fp2_lex_larger(const fp2 *y) {
  /* ZCash convention: compare (c1, c0) lexicographically with (p-1)/2 */
  if (!fp_is_zero(y->c1)) return fp_lex_larger(y->c1);
  return fp_lex_larger(y->c0);
}

/* ---------------- G1 (jacobian) ---------------- */

static void g1_infinity(g1p *r) {
  fp_copy(r->X, BLS_ONE_M);
  fp_copy(r->Y, BLS_ONE_M);
  fp_zero(r->Z);
}
static int g1_is_infinity(const g1p *p) { return fp_is_zero(p->Z); }

static void g1_dbl(g1p *r, const g1p *p) {
  if (g1_is_infinity(p)) { *r = *p; return; }
  fp A, B, C, D, E, F, t;
  fp_sqr(A, p->X);
  fp_sqr(B, p->Y);
  fp_sqr(C, B);
  fp_add(t, p->X, B);
  fp_sqr(t, t);
  fp_sub(t, t, A);
  fp_sub(t, t, C);
  fp_add(D, t, t);            /* 2((X+B)^2 - A - C) */
  fp_add(E, A, A);
  fp_add(E, E, A);            /* 3A */
  fp_sqr(F, E);
  fp t2;
  fp_add(t2, D, D);
  fp_sub(F, F, t2);           /* X3 = F - 2D */
  fp Y3;
  fp_sub(Y3, D, F);
  fp_mul(Y3, E, Y3);
  fp C8;
  fp_add(C8, C, C);
  fp_add(C8, C8, C8);
  fp_add(C8, C8, C8);         /* 8C */
  fp_sub(Y3, Y3, C8);
  fp Z3;
  fp_mul(Z3, p->Y, p->Z);
  fp_add(Z3, Z3, Z3);
  fp_copy(r->X, F);
  fp_copy(r->Y, Y3);
  fp_copy(r->Z, Z3);
}

static void g1_add(g1p *r, const g1p *p, const g1p *q) {
  if (g1_is_infinity(p)) { *r = *q; return; }
  if (g1_is_infinity(q)) { *r = *p; return; }
  fp Z1Z1, Z2Z2, U1, U2, S1, S2, H, rr, t;
  fp_sqr(Z1Z1, p->Z);
  fp_sqr(Z2Z2, q->Z);
  fp_mul(U1, p->X, Z2Z2);
  fp_mul(U2, q->X, Z1Z1);
  fp_mul(t, q->Z, Z2Z2);
  fp_mul(S1, p->Y, t);
  fp_mul(t, p->Z, Z1Z1);
  fp_mul(S2, q->Y, t);
  fp_sub(H, U2, U1);
  fp_sub(rr, S2, S1);
  if (fp_is_zero(H)) {
    if (fp_is_zero(rr)) { g1_dbl(r, p); return; }
    g1_infinity(r);
    return;
  }
  fp I, J, r2, V, X3, Y3, Z3;
  fp_add(t, H, H);
  fp_sqr(I, t);               /* (2H)^2 */
  fp_mul(J, H, I);
  fp_add(r2, rr, rr);
  fp_mul(V, U1, I);
  fp_sqr(X3, r2);
  fp_sub(X3, X3, J);
  fp_sub(X3, X3, V);
  fp_sub(X3, X3, V);
  fp_sub(Y3, V, X3);
  fp_mul(Y3, r2, Y3);
  fp_mul(t, S1, J);
  fp_add(t, t, t);
  fp_sub(Y3, Y3, t);
  fp_add(Z3, p->Z, q->Z);
  fp_sqr(Z3, Z3);
  fp_sub(Z3, Z3, Z1Z1);
  fp_sub(Z3, Z3, Z2Z2);
  fp_mul(Z3, Z3, H);
  fp_copy(r->X, X3);
  fp_copy(r->Y, Y3);
  fp_copy(r->Z, Z3);
}

static void g1_scalar_mul(g1p *r, const g1p *p, const uint64_t *k, int words) {
  g1p acc;
  g1_infinity(&acc);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) g1_dbl(&acc, &acc);
      if ((k[w] >> b) & 1) {
        if (started) g1_add(&acc, &acc, p);
        else { acc = *p; started = 1; }
      }
    }
  }
  if (!started) g1_infinity(&acc);
  *r = acc;
}

/* GLV endomorphism φ(x,y) = (β·x, y), β = 2^((p-1)/3) (Montgomery form).
 * On G1, φ acts as multiplication by −x² (verified against the Python
 * oracle, including completeness on random non-subgroup curve points:
 * tests/test_native_bls.py).  Fast membership: φ(P) + [x²]P == O —
 * a 128-bit ladder instead of the 255-bit order ladder (~2×). */
static const uint64_t BLS_BETA_M[6] = {
    0x30f1361b798a64e8ULL, 0xf3b8ddab7ece5a2aULL, 0x16a8ca3ac61577f7ULL,
    0xc26a2ff874fd029bULL, 0x3636b76660701c6eULL, 0x051ba4ab241b6160ULL};
static const uint64_t BLS_X_SQ[2] = {0x0000000100000000ULL,
                                     0xac45a4010001a402ULL};

static int g1_in_subgroup(const g1p *p) {
  if (g1_is_infinity(p)) return 1;
  g1p phi = *p, t;
  fp_mul(phi.X, phi.X, BLS_BETA_M);
  g1_scalar_mul(&t, p, BLS_X_SQ, 2);
  g1_add(&t, &t, &phi);
  return g1_is_infinity(&t);
}

static void g1_to_affine(fp x, fp y, const g1p *p) {
  fp zi, zi2;
  fp_inv(zi, p->Z);
  fp_sqr(zi2, zi);
  fp_mul(x, p->X, zi2);
  fp_mul(zi2, zi2, zi);
  fp_mul(y, p->Y, zi2);
}

/* ---------------- G2 (jacobian over fp2) ---------------- */

static void g2_infinity(g2p *r) {
  fp2_one(&r->X);
  fp2_one(&r->Y);
  fp2_zero(&r->Z);
}
static int g2_is_infinity(const g2p *p) { return fp2_is_zero(&p->Z); }

static void g2_dbl(g2p *r, const g2p *p) {
  if (g2_is_infinity(p)) { *r = *p; return; }
  fp2 A, B, C, D, E, F, t, t2, Y3, Z3, C8;
  fp2_sqr(&A, &p->X);
  fp2_sqr(&B, &p->Y);
  fp2_sqr(&C, &B);
  fp2_add(&t, &p->X, &B);
  fp2_sqr(&t, &t);
  fp2_sub(&t, &t, &A);
  fp2_sub(&t, &t, &C);
  fp2_add(&D, &t, &t);
  fp2_add(&E, &A, &A);
  fp2_add(&E, &E, &A);
  fp2_sqr(&F, &E);
  fp2_add(&t2, &D, &D);
  fp2_sub(&F, &F, &t2);
  fp2_sub(&Y3, &D, &F);
  fp2_mul(&Y3, &E, &Y3);
  fp2_add(&C8, &C, &C);
  fp2_add(&C8, &C8, &C8);
  fp2_add(&C8, &C8, &C8);
  fp2_sub(&Y3, &Y3, &C8);
  fp2_mul(&Z3, &p->Y, &p->Z);
  fp2_add(&Z3, &Z3, &Z3);
  fp2_copy(&r->X, &F);
  fp2_copy(&r->Y, &Y3);
  fp2_copy(&r->Z, &Z3);
}

static void g2_add(g2p *r, const g2p *p, const g2p *q) {
  if (g2_is_infinity(p)) { *r = *q; return; }
  if (g2_is_infinity(q)) { *r = *p; return; }
  fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, H, rr, t, I, J, r2, V, X3, Y3, Z3;
  fp2_sqr(&Z1Z1, &p->Z);
  fp2_sqr(&Z2Z2, &q->Z);
  fp2_mul(&U1, &p->X, &Z2Z2);
  fp2_mul(&U2, &q->X, &Z1Z1);
  fp2_mul(&t, &q->Z, &Z2Z2);
  fp2_mul(&S1, &p->Y, &t);
  fp2_mul(&t, &p->Z, &Z1Z1);
  fp2_mul(&S2, &q->Y, &t);
  fp2_sub(&H, &U2, &U1);
  fp2_sub(&rr, &S2, &S1);
  if (fp2_is_zero(&H)) {
    if (fp2_is_zero(&rr)) { g2_dbl(r, p); return; }
    g2_infinity(r);
    return;
  }
  fp2_add(&t, &H, &H);
  fp2_sqr(&I, &t);
  fp2_mul(&J, &H, &I);
  fp2_add(&r2, &rr, &rr);
  fp2_mul(&V, &U1, &I);
  fp2_sqr(&X3, &r2);
  fp2_sub(&X3, &X3, &J);
  fp2_sub(&X3, &X3, &V);
  fp2_sub(&X3, &X3, &V);
  fp2_sub(&Y3, &V, &X3);
  fp2_mul(&Y3, &r2, &Y3);
  fp2_mul(&t, &S1, &J);
  fp2_add(&t, &t, &t);
  fp2_sub(&Y3, &Y3, &t);
  fp2_add(&Z3, &p->Z, &q->Z);
  fp2_sqr(&Z3, &Z3);
  fp2_sub(&Z3, &Z3, &Z1Z1);
  fp2_sub(&Z3, &Z3, &Z2Z2);
  fp2_mul(&Z3, &Z3, &H);
  fp2_copy(&r->X, &X3);
  fp2_copy(&r->Y, &Y3);
  fp2_copy(&r->Z, &Z3);
}

static void g2_neg(g2p *r, const g2p *p) {
  *r = *p;
  fp2_neg(&r->Y, &p->Y);
}

static void g2_scalar_mul(g2p *r, const g2p *p, const uint64_t *k, int words) {
  g2p acc;
  g2_infinity(&acc);
  int started = 0;
  for (int w = words - 1; w >= 0; w--) {
    for (int b = 63; b >= 0; b--) {
      if (started) g2_dbl(&acc, &acc);
      if ((k[w] >> b) & 1) {
        if (started) g2_add(&acc, &acc, p);
        else { acc = *p; started = 1; }
      }
    }
  }
  if (!started) g2_infinity(&acc);
  *r = acc;
}

static void g2_psi(g2p *r, const g2p *p);

/* Fast membership (Scott): P ∈ G2 ⟺ ψ(P) == [x]P; x is negative, so
 * check ψ(P) + [|x|]P == O — a 64-bit ladder instead of the 255-bit
 * order ladder (~4×).  Verified against the Python oracle including
 * completeness on random non-subgroup E'(Fp2) points
 * (tests/test_native_bls.py). */
static int g2_in_subgroup(const g2p *p) {
  if (g2_is_infinity(p)) return 1;
  g2p psi_p, t;
  g2_psi(&psi_p, p);
  g2_scalar_mul(&t, p, BLS_X_ABS, 1);
  g2_add(&t, &t, &psi_p);
  return g2_is_infinity(&t);
}

static void g2_to_affine(fp2 *x, fp2 *y, const g2p *p) {
  fp2 zi, zi2;
  fp2_inv(&zi, &p->Z);
  fp2_sqr(&zi2, &zi);
  fp2_mul(x, &p->X, &zi2);
  fp2_mul(&zi2, &zi2, &zi);
  fp2_mul(y, &p->Y, &zi2);
}

/* psi endomorphism on jacobian coords: conjugate everything, then scale
 * X by CX and Y by CY (valid because conj(X/Z^2) = conj(X)/conj(Z)^2). */
static void g2_psi(g2p *r, const g2p *p) {
  fp2 cx, cy;
  memcpy(&cx, BLS_PSI_CX, sizeof(fp2));
  memcpy(&cy, BLS_PSI_CY, sizeof(fp2));
  fp2 X, Y, Z;
  fp2_conj(&X, &p->X);
  fp2_conj(&Y, &p->Y);
  fp2_conj(&Z, &p->Z);
  fp2_mul(&r->X, &X, &cx);
  fp2_mul(&r->Y, &Y, &cy);
  fp2_copy(&r->Z, &Z);
}

/* Budroni-Pintore: h_eff.P = [S1]P + [S2]psi(P) + psi^2(2P), S2 < 0. */
static void g2_clear_cofactor(g2p *r, const g2p *p) {
  g2p t1, t2, t3, psi_p;
  g2_scalar_mul(&t1, p, BLS_BP_S1, 3);
  g2_psi(&psi_p, p);
  g2_scalar_mul(&t2, &psi_p, BLS_BP_S2_ABS, 2);
  g2_neg(&t2, &t2);
  g2_dbl(&t3, p);
  g2_psi(&t3, &t3);
  g2_psi(&t3, &t3);
  g2_add(&t1, &t1, &t2);
  g2_add(r, &t1, &t3);
}

/* ---------------- serialization ---------------- */

#define FLAG_C 0x80
#define FLAG_I 0x40
#define FLAG_S 0x20

/* Parse a 48B compressed G1 point into affine-Z=1 montgomery coords.
 * Returns 0 ok / 1 infinity / -1 malformed / -2 not on curve.  The ZCash
 * flag rules: C must be set; I implies all other payload bits zero.  No
 * subgroup check here — callers decide (single shared implementation for
 * decompress + aggregate, so validation policy lives in one place). */
static int g1_parse_compressed(const uint8_t in[48], g1p *out) {
  uint8_t flags = in[0];
  if (!(flags & FLAG_C)) return -1;
  if (flags & FLAG_I) {
    if (flags != (FLAG_C | FLAG_I)) return -1;
    for (int i = 1; i < 48; i++)
      if (in[i]) return -1;
    g1_infinity(out);
    return 1;
  }
  uint8_t buf[48];
  memcpy(buf, in, 48);
  buf[0] &= 0x1F;
  uint64_t xw[6];
  if (!fp_from_be(xw, buf)) return -1;
  fp x, y, y2, t;
  fp_to_mont(x, xw);
  fp_sqr(t, x);
  fp_mul(t, t, x);
  fp_add(y2, t, BLS_B1_M);
  if (!fp_sqrt(y, y2)) return -2;
  if (fp_lex_larger(y) != !!(flags & FLAG_S)) fp_neg(y, y);
  fp_copy(out->X, x);
  fp_copy(out->Y, y);
  fp_copy(out->Z, BLS_ONE_M);
  return 0;
}

/* returns 0 ok / 1 infinity / -1 malformed / -2 not on curve /
 * -3 not in subgroup.  out_x/out_y are 32 int32 device limbs. */
int lodestar_bls_g1_decompress(const uint8_t in[48], int32_t out_x[32],
                               int32_t out_y[32], int check_subgroup) {
  memset(out_x, 0, 32 * sizeof(int32_t));
  memset(out_y, 0, 32 * sizeof(int32_t));
  g1p p;
  int rc = g1_parse_compressed(in, &p);
  if (rc != 0) return rc;
  if (check_subgroup && !g1_in_subgroup(&p)) return -3;
  fp_to_limbs12(out_x, p.X);
  fp_to_limbs12(out_y, p.Y);
  return 0;
}

int lodestar_bls_g2_decompress(const uint8_t in[96], int32_t out_x[64],
                               int32_t out_y[64], int check_subgroup) {
  memset(out_x, 0, 64 * sizeof(int32_t));
  memset(out_y, 0, 64 * sizeof(int32_t));
  uint8_t flags = in[0];
  if (!(flags & FLAG_C)) return -1;
  if (flags & FLAG_I) {
    if (flags != (FLAG_C | FLAG_I)) return -1;
    for (int i = 1; i < 96; i++)
      if (in[i]) return -1;
    return 1;
  }
  uint8_t buf[48];
  memcpy(buf, in, 48);
  buf[0] &= 0x1F;
  uint64_t x1w[6], x0w[6];
  if (!fp_from_be(x1w, buf)) return -1;       /* first 48B: c1 (ZCash order) */
  if (!fp_from_be(x0w, in + 48)) return -1;   /* second 48B: c0 */
  fp2 x, y, y2, t;
  fp_to_mont(x.c0, x0w);
  fp_to_mont(x.c1, x1w);
  fp2_sqr(&t, &x);
  fp2_mul(&t, &t, &x);
  fp2 b2;
  memcpy(&b2, BLS_B2_M, sizeof(fp2));
  fp2_add(&y2, &t, &b2);
  if (!fp2_sqrt(&y, &y2)) return -2;
  if (fp2_lex_larger(&y) != !!(flags & FLAG_S)) fp2_neg(&y, &y);
  if (check_subgroup) {
    g2p p;
    fp2_copy(&p.X, &x);
    fp2_copy(&p.Y, &y);
    fp2_one(&p.Z);
    if (!g2_in_subgroup(&p)) return -3;
  }
  fp_to_limbs12(out_x, x.c0);
  fp_to_limbs12(out_x + 32, x.c1);
  fp_to_limbs12(out_y, y.c0);
  fp_to_limbs12(out_y + 32, y.c1);
  return 0;
}

/* ---------------- hash to curve (G2) ---------------- */

/* RFC 9380 5.3.1 expand_message_xmd, SHA-256, len fixed to 256 bytes
 * (count=2 draws x m=2 coords x L=64). msg arbitrary length. */
static void expand_message_xmd_256(const uint8_t *msg, size_t msg_len,
                                   const uint8_t *dst, size_t dst_len,
                                   uint8_t out[256]) {
  uint8_t dst_prime[256];
  size_t dpl = dst_len;
  memcpy(dst_prime, dst, dst_len);
  dst_prime[dpl++] = (uint8_t)dst_len;

  uint8_t b0[32], bi[32];
  /* b0 = H(Z_pad || msg || l_i_b_str || 0 || dst'); one-shot SHA over a
   * stack buffer — callers cap msg at 3KB (consensus messages are 32B). */
  {
    uint8_t big[4096];
    size_t off = 0;
    memset(big, 0, 64);
    off = 64;
    memcpy(big + off, msg, msg_len);
    off += msg_len;
    big[off++] = 1; /* l_i_b_str hi: 256 = 0x0100 */
    big[off++] = 0;
    big[off++] = 0;
    memcpy(big + off, dst_prime, dpl);
    off += dpl;
    lodestar_sha256(big, off, b0);
  }
  uint8_t cur[32 + 1 + 256];
  memcpy(cur, b0, 32);
  cur[32] = 1;
  memcpy(cur + 33, dst_prime, dpl);
  lodestar_sha256(cur, 33 + dpl, bi);
  memcpy(out, bi, 32);
  for (int i = 2; i <= 8; i++) {
    for (int j = 0; j < 32; j++) cur[j] = b0[j] ^ bi[j];
    cur[32] = (uint8_t)i;
    memcpy(cur + 33, dst_prime, dpl);
    lodestar_sha256(cur, 33 + dpl, bi);
    memcpy(out + (i - 1) * 32, bi, 32);
  }
}

/* 64 big-endian bytes -> field element (Montgomery), reduced mod p. */
static void fp_from_be64_mod(fp r, const uint8_t in[64]) {
  /* value = a1*2^384 + a0, a1 = top 16 bytes, a0 = bottom 48 bytes */
  uint64_t a1[6] = {0}, a0[6];
  for (int i = 0; i < 2; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[(1 - i) * 8 + j];
    a1[i] = w;
  }
  for (int i = 0; i < 6; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[16 + (5 - i) * 8 + j];
    a0[i] = w;
  }
  fp m1, m0;
  fp_mul(m1, a1, BLS_R2);       /* a1 * R  (valid: a1 < R) */
  fp_mul(m1, m1, BLS_R2);       /* a1 * R * R = (a1*2^384)*R mod p */
  fp_mul(m0, a0, BLS_R2);       /* a0 * R */
  fp_add(r, m1, m0);
}

/* simplified SWU onto E2' (RFC 9380 6.6.2), then 3-isogeny to E2. */
static void map_to_curve_g2(g2p *out, const fp2 *u) {
  fp2 A, B, Z, nba, bza;
  memcpy(&A, BLS_SSWU_A, sizeof(fp2));
  memcpy(&B, BLS_SSWU_B, sizeof(fp2));
  memcpy(&Z, BLS_SSWU_Z, sizeof(fp2));
  memcpy(&nba, BLS_SSWU_NBA, sizeof(fp2));
  memcpy(&bza, BLS_SSWU_BZA, sizeof(fp2));

  fp2 u2, zu2, tv, x1, gx1, y, x, one;
  fp2_sqr(&u2, u);
  fp2_mul(&zu2, &Z, &u2);
  fp2_sqr(&tv, &zu2);
  fp2_add(&tv, &tv, &zu2);            /* Z^2 u^4 + Z u^2 */
  if (fp2_is_zero(&tv)) {
    fp2_copy(&x1, &bza);              /* B/(Z*A) */
  } else {
    fp2 ti;
    fp2_inv(&ti, &tv);
    fp2_one(&one);
    fp2_add(&ti, &ti, &one);
    fp2_mul(&x1, &nba, &ti);          /* -B/A * (1 + 1/tv) */
  }
  fp2 t;
  fp2_sqr(&t, &x1);
  fp2_mul(&t, &t, &x1);
  fp2 ax;
  fp2_mul(&ax, &A, &x1);
  fp2_add(&t, &t, &ax);
  fp2_add(&gx1, &t, &B);
  if (fp2_sqrt(&y, &gx1)) {
    fp2_copy(&x, &x1);
  } else {
    fp2 x2, gx2;
    fp2_mul(&x2, &zu2, &x1);
    fp2_sqr(&t, &x2);
    fp2_mul(&t, &t, &x2);
    fp2_mul(&ax, &A, &x2);
    fp2_add(&t, &t, &ax);
    fp2_add(&gx2, &t, &B);
    fp2_sqrt(&y, &gx2);               /* must succeed */
    fp2_copy(&x, &x2);
  }
  if (fp2_sgn0(&y) != fp2_sgn0(u)) fp2_neg(&y, &y);

  /* 3-isogeny (Velu form): X(x) = x + t/(x-x0) + u/(x-x0)^2, then the
   * scaling isomorphism (x,y) -> (x/l^2, y/l^3). */
  fp2 x0c, tc, uc, d, di, di2, di3, xx, dx, two_u, yy;
  memcpy(&x0c, BLS_ISO_X0, sizeof(fp2));
  memcpy(&tc, BLS_ISO_T, sizeof(fp2));
  memcpy(&uc, BLS_ISO_U, sizeof(fp2));
  fp2_sub(&d, &x, &x0c);
  fp2_inv(&di, &d);
  fp2_sqr(&di2, &di);
  fp2_mul(&di3, &di2, &di);
  fp2 term;
  fp2_mul(&term, &tc, &di);
  fp2_add(&xx, &x, &term);
  fp2_mul(&term, &uc, &di2);
  fp2_add(&xx, &xx, &term);
  fp2_one(&one);
  fp2_mul(&term, &tc, &di2);
  fp2_sub(&dx, &one, &term);
  fp2_add(&two_u, &uc, &uc);
  fp2_mul(&term, &two_u, &di3);
  fp2_sub(&dx, &dx, &term);
  fp2_mul(&yy, &y, &dx);
  fp2_mul_fp(&xx, &xx, BLS_ISO_IL2);
  fp2_mul_fp(&yy, &yy, BLS_ISO_IL3);

  fp2_copy(&out->X, &xx);
  fp2_copy(&out->Y, &yy);
  fp2_one(&out->Z);
}

int lodestar_bls_hash_to_g2(const uint8_t *msg, size_t msg_len,
                            const uint8_t *dst, size_t dst_len,
                            int32_t out_x[64], int32_t out_y[64]) {
  if (msg_len > 3000 || dst_len == 0 || dst_len > 255) return -1;
  uint8_t uniform[256];
  expand_message_xmd_256(msg, msg_len, dst, dst_len, uniform);
  fp2 u0, u1;
  fp_from_be64_mod(u0.c0, uniform);
  fp_from_be64_mod(u0.c1, uniform + 64);
  fp_from_be64_mod(u1.c0, uniform + 128);
  fp_from_be64_mod(u1.c1, uniform + 192);
  g2p q0, q1, q;
  map_to_curve_g2(&q0, &u0);
  map_to_curve_g2(&q1, &u1);
  g2_add(&q, &q0, &q1);
  g2_clear_cofactor(&q, &q);
  if (g2_is_infinity(&q)) return -2;  /* astronomically unlikely */
  fp2 x, y;
  g2_to_affine(&x, &y, &q);
  fp_to_limbs12(out_x, x.c0);
  fp_to_limbs12(out_x + 32, x.c1);
  fp_to_limbs12(out_y, y.c0);
  fp_to_limbs12(out_y + 32, y.c1);
  return 0;
}

/* ---------------- aggregation ---------------- */

/* Aggregate n compressed G1 pubkeys -> device limbs of the affine sum.
 * Returns 0 ok / 1 aggregate-is-infinity / -1 malformed / -2 off-curve /
 * -3 subgroup.  Infinity pubkeys contribute nothing (callers reject them
 * upstream at KeyValidate). */
int lodestar_bls_g1_aggregate(const uint8_t *pks, size_t n, int check_each,
                              int32_t out_x[32], int32_t out_y[32]) {
  memset(out_x, 0, 32 * sizeof(int32_t));
  memset(out_y, 0, 32 * sizeof(int32_t));
  g1p acc;
  g1_infinity(&acc);
  for (size_t i = 0; i < n; i++) {
    g1p p;
    int rc = g1_parse_compressed(pks + 48 * i, &p);
    if (rc == 1) continue;
    if (rc != 0) return rc;
    if (check_each && !g1_in_subgroup(&p)) return -3;
    g1_add(&acc, &acc, &p);
  }
  if (g1_is_infinity(&acc)) return 1;
  fp x, y;
  g1_to_affine(x, y, &acc);
  fp_to_limbs12(out_x, x);
  fp_to_limbs12(out_y, y);
  return 0;
}

/* ---------------- batched set marshalling ----------------
 *
 * For n signature sets (pubkey 48B, message 32B signing root, signature
 * 96B) fill the device arrays pk_x/pk_y (n,32), msg_x/msg_y/sig_x/sig_y
 * (n,64) and ok (n bytes).  A set that fails decompression/subgroup or has
 * an infinity pubkey/signature gets ok=0 and zeroed lanes (the reference
 * rejects those sets: maybeBatch.ts catching blst errors).
 */
int lodestar_bls_marshal_sets(size_t n, const uint8_t *pks, const uint8_t *msgs,
                              const uint8_t *sigs, const uint8_t *dst,
                              size_t dst_len, int check_pk_subgroup,
                              int check_sig_subgroup, int do_hash,
                              int32_t *pk_x, int32_t *pk_y, int32_t *msg_x,
                              int32_t *msg_y, int32_t *sig_x, int32_t *sig_y,
                              uint8_t *ok) {
  /* do_hash=0: caller fills msg_x/msg_y itself (e.g. from a
   * hash-to-curve cache — gossip shares signing roots across a whole
   * committee, so per-set hashing is mostly redundant work). */
  for (size_t i = 0; i < n; i++) {
    ok[i] = 0;
    int rc = lodestar_bls_g1_decompress(pks + 48 * i, pk_x + 32 * i,
                                        pk_y + 32 * i, check_pk_subgroup);
    if (rc != 0) continue; /* infinity pubkey is invalid per Eth2 */
    rc = lodestar_bls_g2_decompress(sigs + 96 * i, sig_x + 64 * i,
                                    sig_y + 64 * i, check_sig_subgroup);
    if (rc != 0) continue;
    if (do_hash) {
      rc = lodestar_bls_hash_to_g2(msgs + 32 * i, 32, dst, dst_len,
                                   msg_x + 64 * i, msg_y + 64 * i);
      if (rc != 0) continue;
    }
    ok[i] = 1;
  }
  return 0;
}
