/* Snappy block-format codec (compress + uncompress).
 *
 * Native replacement for the reference's `snappyjs` /
 * `@chainsafe/snappy-stream` payload codec (gossip messages, SSZ-snappy
 * req/resp framing — SURVEY.md §2.3). Implements the snappy block format
 * from the public format description: varint32 uncompressed length, then
 * literal (tag%4==0) and copy (1/2/4-byte offset) elements. The encoder
 * uses the standard greedy hash-table matcher; any valid snappy stream is
 * acceptable to peers, ratio is best-effort.
 */

#include <stddef.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* ---- varint ---- */

static size_t put_varint32(uint8_t *dst, uint32_t v) {
  size_t n = 0;
  while (v >= 0x80) {
    dst[n++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  dst[n++] = (uint8_t)v;
  return n;
}

static int get_varint32(const uint8_t *src, size_t len, uint32_t *out,
                        size_t *consumed) {
  uint32_t v = 0;
  int shift = 0;
  size_t i = 0;
  while (i < len && shift <= 28) {
    uint8_t b = src[i++];
    v |= (uint32_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      *consumed = i;
      return 0;
    }
    shift += 7;
  }
  return -1;
}

/* ---- emit helpers ---- */

static size_t emit_literal(uint8_t *dst, const uint8_t *src, uint32_t len) {
  size_t n = 0;
  uint32_t l = len - 1;
  if (l < 60) {
    dst[n++] = (uint8_t)(l << 2);
  } else if (l < 256) {
    dst[n++] = (uint8_t)(60 << 2);
    dst[n++] = (uint8_t)l;
  } else if (l < 65536) {
    dst[n++] = (uint8_t)(61 << 2);
    dst[n++] = (uint8_t)l;
    dst[n++] = (uint8_t)(l >> 8);
  } else if (l < (1u << 24)) {
    dst[n++] = (uint8_t)(62 << 2);
    dst[n++] = (uint8_t)l;
    dst[n++] = (uint8_t)(l >> 8);
    dst[n++] = (uint8_t)(l >> 16);
  } else {
    dst[n++] = (uint8_t)(63 << 2);
    dst[n++] = (uint8_t)l;
    dst[n++] = (uint8_t)(l >> 8);
    dst[n++] = (uint8_t)(l >> 16);
    dst[n++] = (uint8_t)(l >> 24);
  }
  memcpy(dst + n, src, len);
  return n + len;
}

/* copy of length [4..64] with offset < 65536 */
static size_t emit_copy_upto64(uint8_t *dst, uint32_t offset, uint32_t len) {
  if (len >= 4 && len <= 11 && offset < 2048) {
    dst[0] = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
    dst[1] = (uint8_t)offset;
    return 2;
  }
  dst[0] = (uint8_t)(2 | ((len - 1) << 2));
  dst[1] = (uint8_t)offset;
  dst[2] = (uint8_t)(offset >> 8);
  return 3;
}

static size_t emit_copy(uint8_t *dst, uint32_t offset, uint32_t len) {
  size_t n = 0;
  while (len >= 68) {
    n += emit_copy_upto64(dst + n, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    n += emit_copy_upto64(dst + n, offset, 60);
    len -= 60;
  }
  n += emit_copy_upto64(dst + n, offset, len);
  return n;
}

/* ---- compression ---- */

#define HASH_BITS 14
#define HASH_SIZE (1 << HASH_BITS)

static uint32_t hash4(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return (v * 0x1e35a7bdu) >> (32 - HASH_BITS);
}

size_t lodestar_snappy_max_compressed(size_t n) {
  return 32 + n + n / 6;
}

/* Returns compressed size, or 0 on error. dst must hold
 * lodestar_snappy_max_compressed(len). */
size_t lodestar_snappy_compress(const uint8_t *src, size_t len, uint8_t *dst) {
  size_t dn = 0;
  uint32_t *table;
  size_t ip = 0, anchor = 0;

  dn += put_varint32(dst, (uint32_t)len);
  if (len == 0) return dn;
  if (len < 16) {
    dn += emit_literal(dst + dn, src, (uint32_t)len);
    return dn;
  }

  /* absolute candidate positions, 0xffffffff = empty */
  table = (uint32_t *)malloc(HASH_SIZE * sizeof(uint32_t));
  if (!table) return 0;
  memset(table, 0xff, HASH_SIZE * sizeof(uint32_t));

  while (ip + 4 <= len) {
    uint32_t h = hash4(src + ip);
    size_t cand = table[h];
    table[h] = (uint32_t)ip;
    if (cand != 0xffffffffu && ip - cand <= 0xffff &&
        memcmp(src + cand, src + ip, 4) == 0) {
      size_t match_len = 4;
      while (ip + match_len < len &&
             src[cand + match_len] == src[ip + match_len])
        match_len++;
      if (ip > anchor)
        dn += emit_literal(dst + dn, src + anchor, (uint32_t)(ip - anchor));
      dn += emit_copy(dst + dn, (uint32_t)(ip - cand), (uint32_t)match_len);
      ip += match_len;
      anchor = ip;
    } else {
      ip++;
    }
  }
  if (anchor < len)
    dn += emit_literal(dst + dn, src + anchor, (uint32_t)(len - anchor));
  free(table);
  return dn;
}

/* ---- decompression ---- */

/* Returns 0 on success; out_len must equal the stream's declared size. */
int lodestar_snappy_uncompress(const uint8_t *src, size_t src_len,
                               uint8_t *dst, size_t dst_len) {
  uint32_t declared;
  size_t consumed, ip, op = 0;
  if (get_varint32(src, src_len, &declared, &consumed) != 0) return -1;
  if ((size_t)declared != dst_len) return -2;
  ip = consumed;
  while (ip < src_len) {
    uint8_t tag = src[ip++];
    uint32_t kind = tag & 3;
    if (kind == 0) { /* literal */
      uint32_t l = tag >> 2;
      if (l >= 60) {
        uint32_t nbytes = l - 59, v = 0, i;
        if (ip + nbytes > src_len) return -3;
        for (i = 0; i < nbytes; i++) v |= (uint32_t)src[ip + i] << (8 * i);
        ip += nbytes;
        l = v;
      }
      l += 1;
      if (ip + l > src_len || op + l > dst_len) return -4;
      memcpy(dst + op, src + ip, l);
      ip += l;
      op += l;
    } else {
      uint32_t l, offset;
      if (kind == 1) {
        if (ip >= src_len) return -5;
        l = 4 + ((tag >> 2) & 0x7);
        offset = ((uint32_t)(tag >> 5) << 8) | src[ip++];
      } else if (kind == 2) {
        if (ip + 2 > src_len) return -5;
        l = (tag >> 2) + 1;
        offset = (uint32_t)src[ip] | ((uint32_t)src[ip + 1] << 8);
        ip += 2;
      } else {
        if (ip + 4 > src_len) return -5;
        l = (tag >> 2) + 1;
        offset = (uint32_t)src[ip] | ((uint32_t)src[ip + 1] << 8) |
                 ((uint32_t)src[ip + 2] << 16) | ((uint32_t)src[ip + 3] << 24);
        ip += 4;
      }
      if (offset == 0 || offset > op || op + l > dst_len) return -6;
      /* overlapping copies are byte-serial by definition */
      while (l--) {
        dst[op] = dst[op - offset];
        op++;
      }
    }
  }
  return op == dst_len ? 0 : -7;
}
