/* _lodestar_native: CPython bindings for the native codec/hash tier.
 *
 * sha256(data) -> 32B digest
 * sha256_level(data: N*64 bytes) -> N*32 bytes   (one merkle level)
 * xxh64(data, seed=0) -> int
 * snappy_compress(data) -> bytes
 * snappy_uncompress(data) -> bytes
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

void lodestar_sha256(const uint8_t *data, size_t len, uint8_t out[32]);
void lodestar_sha256_level(const uint8_t *in, size_t n, uint8_t *out);
uint64_t lodestar_xxh64(const uint8_t *data, size_t len, uint64_t seed);
size_t lodestar_snappy_max_compressed(size_t n);
size_t lodestar_snappy_compress(const uint8_t *src, size_t len, uint8_t *dst);
int lodestar_snappy_uncompress(const uint8_t *src, size_t src_len,
                               uint8_t *dst, size_t dst_len);

static int get_varint_head(const uint8_t *src, Py_ssize_t len, uint32_t *out) {
  uint32_t v = 0;
  int shift = 0;
  Py_ssize_t i = 0;
  while (i < len && shift <= 28) {
    uint8_t b = src[i++];
    v |= (uint32_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return 0;
    }
    shift += 7;
  }
  return -1;
}

static PyObject *py_sha256(PyObject *self, PyObject *args) {
  Py_buffer buf;
  uint8_t out[32];
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  lodestar_sha256((const uint8_t *)buf.buf, (size_t)buf.len, out);
  PyBuffer_Release(&buf);
  return PyBytes_FromStringAndSize((const char *)out, 32);
}

static PyObject *py_sha256_level(PyObject *self, PyObject *args) {
  Py_buffer buf;
  PyObject *out;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  if (buf.len % 64 != 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "input must be a multiple of 64 bytes");
    return NULL;
  }
  out = PyBytes_FromStringAndSize(NULL, buf.len / 2);
  if (out == NULL) {
    PyBuffer_Release(&buf);
    return NULL;
  }
  lodestar_sha256_level((const uint8_t *)buf.buf, (size_t)(buf.len / 64),
                        (uint8_t *)PyBytes_AS_STRING(out));
  PyBuffer_Release(&buf);
  return out;
}

static PyObject *py_xxh64(PyObject *self, PyObject *args) {
  Py_buffer buf;
  unsigned long long seed = 0;
  uint64_t h;
  if (!PyArg_ParseTuple(args, "y*|K", &buf, &seed)) return NULL;
  h = lodestar_xxh64((const uint8_t *)buf.buf, (size_t)buf.len, (uint64_t)seed);
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLongLong((unsigned long long)h);
}

static PyObject *py_snappy_compress(PyObject *self, PyObject *args) {
  Py_buffer buf;
  PyObject *out;
  size_t max, n;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  if ((uint64_t)buf.len > 0xffffffffu) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "input too large for snappy block");
    return NULL;
  }
  max = lodestar_snappy_max_compressed((size_t)buf.len);
  out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)max);
  if (out == NULL) {
    PyBuffer_Release(&buf);
    return NULL;
  }
  n = lodestar_snappy_compress((const uint8_t *)buf.buf, (size_t)buf.len,
                               (uint8_t *)PyBytes_AS_STRING(out));
  PyBuffer_Release(&buf);
  if (n == 0 && buf.len != 0) {
    Py_DECREF(out);
    PyErr_SetString(PyExc_MemoryError, "snappy compression failed");
    return NULL;
  }
  if (_PyBytes_Resize(&out, (Py_ssize_t)n) < 0) return NULL;
  return out;
}

static PyObject *py_snappy_uncompress(PyObject *self, PyObject *args) {
  Py_buffer buf;
  PyObject *out;
  uint32_t declared;
  int rc;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  if (get_varint_head((const uint8_t *)buf.buf, buf.len, &declared) != 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "bad snappy header");
    return NULL;
  }
  out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)declared);
  if (out == NULL) {
    PyBuffer_Release(&buf);
    return NULL;
  }
  rc = lodestar_snappy_uncompress((const uint8_t *)buf.buf, (size_t)buf.len,
                                  (uint8_t *)PyBytes_AS_STRING(out),
                                  (size_t)declared);
  PyBuffer_Release(&buf);
  if (rc != 0) {
    Py_DECREF(out);
    PyErr_Format(PyExc_ValueError, "corrupt snappy stream (%d)", rc);
    return NULL;
  }
  return out;
}

/* ---- BLS12-381 host tier (bls12.c) ---- */

int lodestar_bls_g1_decompress(const uint8_t in[48], int32_t out_x[32],
                               int32_t out_y[32], int check_subgroup);
int lodestar_bls_g2_decompress(const uint8_t in[96], int32_t out_x[64],
                               int32_t out_y[64], int check_subgroup);
int lodestar_bls_hash_to_g2(const uint8_t *msg, size_t msg_len,
                            const uint8_t *dst, size_t dst_len,
                            int32_t out_x[64], int32_t out_y[64]);
int lodestar_bls_g1_aggregate(const uint8_t *pks, size_t n, int check_each,
                              int32_t out_x[32], int32_t out_y[32]);
int lodestar_bls_marshal_sets(size_t n, const uint8_t *pks, const uint8_t *msgs,
                              const uint8_t *sigs, const uint8_t *dst,
                              size_t dst_len, int check_pk_subgroup,
                              int check_sig_subgroup, int do_hash, int do_pk,
                              int32_t *pk_x, int32_t *pk_y, int32_t *msg_x,
                              int32_t *msg_y, int32_t *sig_x, int32_t *sig_y,
                              uint8_t *ok);

static PyObject *py_bls_g1_decompress(PyObject *self, PyObject *args) {
  Py_buffer buf;
  int check = 1, rc;
  if (!PyArg_ParseTuple(args, "y*|i", &buf, &check)) return NULL;
  if (buf.len != 48) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "G1 compressed point must be 48 bytes");
    return NULL;
  }
  PyObject *out = PyBytes_FromStringAndSize(NULL, 64 * 4);
  if (!out) { PyBuffer_Release(&buf); return NULL; }
  int32_t *limbs = (int32_t *)PyBytes_AS_STRING(out);
  /* subgroup check is a ~255-bit scalar mul: release the GIL like the
   * other heavy entry points */
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_bls_g1_decompress((const uint8_t *)buf.buf, limbs, limbs + 32,
                                  check);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&buf);
  return Py_BuildValue("(iN)", rc, out);
}

static PyObject *py_bls_g2_decompress(PyObject *self, PyObject *args) {
  Py_buffer buf;
  int check = 1, rc;
  if (!PyArg_ParseTuple(args, "y*|i", &buf, &check)) return NULL;
  if (buf.len != 96) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "G2 compressed point must be 96 bytes");
    return NULL;
  }
  PyObject *out = PyBytes_FromStringAndSize(NULL, 128 * 4);
  if (!out) { PyBuffer_Release(&buf); return NULL; }
  int32_t *limbs = (int32_t *)PyBytes_AS_STRING(out);
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_bls_g2_decompress((const uint8_t *)buf.buf, limbs, limbs + 64,
                                  check);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&buf);
  return Py_BuildValue("(iN)", rc, out);
}

static PyObject *py_bls_hash_to_g2(PyObject *self, PyObject *args) {
  Py_buffer msg, dst;
  int rc;
  if (!PyArg_ParseTuple(args, "y*y*", &msg, &dst)) return NULL;
  PyObject *out = PyBytes_FromStringAndSize(NULL, 128 * 4);
  if (!out) { PyBuffer_Release(&msg); PyBuffer_Release(&dst); return NULL; }
  int32_t *limbs = (int32_t *)PyBytes_AS_STRING(out);
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_bls_hash_to_g2((const uint8_t *)msg.buf, (size_t)msg.len,
                               (const uint8_t *)dst.buf, (size_t)dst.len,
                               limbs, limbs + 64);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&msg);
  PyBuffer_Release(&dst);
  return Py_BuildValue("(iN)", rc, out);
}

static PyObject *py_bls_g1_aggregate(PyObject *self, PyObject *args) {
  Py_buffer buf;
  int check = 1, rc;
  if (!PyArg_ParseTuple(args, "y*|i", &buf, &check)) return NULL;
  if (buf.len % 48 != 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "pubkeys must be N*48 bytes");
    return NULL;
  }
  PyObject *out = PyBytes_FromStringAndSize(NULL, 64 * 4);
  if (!out) { PyBuffer_Release(&buf); return NULL; }
  int32_t *limbs = (int32_t *)PyBytes_AS_STRING(out);
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_bls_g1_aggregate((const uint8_t *)buf.buf,
                                 (size_t)(buf.len / 48), check, limbs,
                                 limbs + 32);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&buf);
  return Py_BuildValue("(iN)", rc, out);
}

static PyObject *py_bls_marshal_sets(PyObject *self, PyObject *args) {
  Py_buffer pks, msgs, sigs, dst;
  int check_pk = 0, check_sig = 1, do_hash = 1, do_pk = 1;
  if (!PyArg_ParseTuple(args, "y*y*y*y*|iiii", &pks, &msgs, &sigs, &dst,
                        &check_pk, &check_sig, &do_hash, &do_pk))
    return NULL;
  Py_ssize_t n = pks.len / 48;
  PyObject *out = NULL, *ok = NULL;
  if (pks.len % 48 != 0 || msgs.len != n * 32 || sigs.len != n * 96) {
    PyErr_SetString(PyExc_ValueError,
                    "need n*48 pubkey, n*32 message, n*96 signature bytes");
    goto done;
  }
  /* layout: [pk_x n*32 | pk_y n*32 | msg_x n*64 | msg_y n*64 |
   *          sig_x n*64 | sig_y n*64] int32 */
  out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)(n * 320 * 4));
  ok = PyBytes_FromStringAndSize(NULL, n);
  if (!out || !ok) goto done;
  {
    int32_t *base = (int32_t *)PyBytes_AS_STRING(out);
    int32_t *pk_x = base, *pk_y = base + n * 32, *msg_x = base + n * 64,
            *msg_y = base + n * 128, *sig_x = base + n * 192,
            *sig_y = base + n * 256;
    uint8_t *okp = (uint8_t *)PyBytes_AS_STRING(ok);
    Py_BEGIN_ALLOW_THREADS
    lodestar_bls_marshal_sets((size_t)n, (const uint8_t *)pks.buf,
                              (const uint8_t *)msgs.buf,
                              (const uint8_t *)sigs.buf,
                              (const uint8_t *)dst.buf, (size_t)dst.len,
                              check_pk, check_sig, do_hash, do_pk, pk_x,
                              pk_y, msg_x, msg_y, sig_x, sig_y, okp);
    Py_END_ALLOW_THREADS
  }
done:
  PyBuffer_Release(&pks);
  PyBuffer_Release(&msgs);
  PyBuffer_Release(&sigs);
  PyBuffer_Release(&dst);
  if (!out || !ok) {
    Py_XDECREF(out);
    Py_XDECREF(ok);
    return NULL;
  }
  return Py_BuildValue("(NN)", out, ok);
}


int lodestar_bls_verify_sets(size_t n, const uint8_t *pks,
                             const uint8_t *msgs, const size_t *msg_lens,
                             const uint8_t *sigs, const uint8_t *dst,
                             size_t dst_len, const int32_t *h_x,
                             const int32_t *h_y, uint8_t *out_ok);

int lodestar_bls_sign(const uint8_t sk_be[32], const uint8_t *msg,
                      size_t msg_len, const uint8_t *dst, size_t dst_len,
                      uint8_t out[96]);

static PyObject *py_bls_sign(PyObject *self, PyObject *args) {
  Py_buffer sk, msg, dst;
  if (!PyArg_ParseTuple(args, "y*y*y*", &sk, &msg, &dst)) return NULL;
  if (sk.len != 32) {
    PyBuffer_Release(&sk); PyBuffer_Release(&msg); PyBuffer_Release(&dst);
    PyErr_SetString(PyExc_ValueError, "secret key must be 32 bytes");
    return NULL;
  }
  PyObject *out = PyBytes_FromStringAndSize(NULL, 96);
  if (!out) {
    PyBuffer_Release(&sk); PyBuffer_Release(&msg); PyBuffer_Release(&dst);
    return NULL;
  }
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_bls_sign((const uint8_t *)sk.buf, (const uint8_t *)msg.buf,
                         (size_t)msg.len, (const uint8_t *)dst.buf,
                         (size_t)dst.len, (uint8_t *)PyBytes_AS_STRING(out));
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&sk);
  PyBuffer_Release(&msg);
  PyBuffer_Release(&dst);
  return Py_BuildValue("(iN)", rc, out);
}

static PyObject *py_bls_verify_sets(PyObject *self, PyObject *args) {
  /* (pks n*48B, msgs concatenated, msg_lens n*8B LE, sigs n*96B, dst)
   * -> n verdict bytes.  Full CPU verification: decompress + subgroup +
   * hash-to-curve + two pairings per set (GIL released). */
  Py_buffer pks, msgs, lens, sigs, dst;
  Py_buffer hx = {0}, hy = {0};
  if (!PyArg_ParseTuple(args, "y*y*y*y*y*|y*y*", &pks, &msgs, &lens, &sigs,
                        &dst, &hx, &hy))
    return NULL;
  Py_ssize_t n = pks.len / 48;
  PyObject *ok = NULL;
  size_t *ml = NULL;
  if (pks.len % 48 != 0 || lens.len != n * 8 || sigs.len != n * 96) {
    PyErr_SetString(PyExc_ValueError,
                    "need n*48 pubkey, n*8 length, n*96 signature bytes");
    goto done;
  }
  ml = malloc(sizeof(size_t) * (n ? n : 1));
  if (!ml) {
    PyErr_NoMemory();
    goto done;
  }
  {
    const uint8_t *lp = (const uint8_t *)lens.buf;
    size_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      uint64_t v = 0;
      for (int b = 0; b < 8; b++) v |= (uint64_t)lp[8 * i + b] << (8 * b);
      ml[i] = (size_t)v;
      total += ml[i];
    }
    if ((Py_ssize_t)total != msgs.len) {
      PyErr_SetString(PyExc_ValueError, "message lengths disagree with buffer");
      goto done;
    }
  }
  ok = PyBytes_FromStringAndSize(NULL, n);
  if (!ok) goto done;
  {
    uint8_t *okp = (uint8_t *)PyBytes_AS_STRING(ok);
    const int32_t *hx_p =
        hx.buf != NULL && hx.len == n * 64 * 4 ? (const int32_t *)hx.buf : NULL;
    const int32_t *hy_p =
        hy.buf != NULL && hy.len == n * 64 * 4 ? (const int32_t *)hy.buf : NULL;
    Py_BEGIN_ALLOW_THREADS
    lodestar_bls_verify_sets((size_t)n, (const uint8_t *)pks.buf,
                             (const uint8_t *)msgs.buf, ml,
                             (const uint8_t *)sigs.buf,
                             (const uint8_t *)dst.buf, (size_t)dst.len,
                             hx_p, hy_p, okp);
    Py_END_ALLOW_THREADS
  }
done:
  free(ml);
  PyBuffer_Release(&pks);
  PyBuffer_Release(&msgs);
  PyBuffer_Release(&lens);
  PyBuffer_Release(&sigs);
  PyBuffer_Release(&dst);
  if (hx.buf) PyBuffer_Release(&hx);
  if (hy.buf) PyBuffer_Release(&hy);
  return ok;
}

/* ---- persistent KV engine (kvstore.c) ---- */

typedef struct kv_store kv_store;
kv_store *lodestar_kv_open(const char *dir);
int lodestar_kv_put(kv_store *s, const uint8_t *key, size_t klen,
                    const uint8_t *val, size_t vlen, int sync);
int lodestar_kv_delete(kv_store *s, const uint8_t *key, size_t klen, int sync);
int lodestar_kv_sync(kv_store *s);
int64_t lodestar_kv_get(kv_store *s, const uint8_t *key, size_t klen,
                        uint8_t *out, size_t out_cap);
typedef struct { const uint8_t *key; uint16_t len; } kv_keyref;
kv_keyref *lodestar_kv_range(kv_store *s, const uint8_t *gte, size_t gl,
                             const uint8_t *lt, size_t ll, uint64_t *n_out);
void lodestar_kv_stats(kv_store *s, uint64_t out[4]);
int lodestar_kv_compact(kv_store *s);
int lodestar_kv_should_compact(kv_store *s);
void lodestar_kv_close(kv_store *s);

static void kv_capsule_destruct(PyObject *cap) {
  kv_store *s = PyCapsule_GetPointer(cap, "lodestar.kv");
  if (s) lodestar_kv_close(s);
}

static kv_store *kv_from_capsule(PyObject *cap) {
  if (!PyCapsule_IsValid(cap, "lodestar.kv")) {
    PyErr_SetString(PyExc_ValueError, "invalid or closed KV handle");
    return NULL;
  }
  return (kv_store *)PyCapsule_GetPointer(cap, "lodestar.kv");
}

static PyObject *py_kv_open(PyObject *self, PyObject *args) {
  const char *dir;
  if (!PyArg_ParseTuple(args, "s", &dir)) return NULL;
  kv_store *s;
  Py_BEGIN_ALLOW_THREADS
  s = lodestar_kv_open(dir);
  Py_END_ALLOW_THREADS
  if (!s) {
    PyErr_Format(PyExc_OSError, "kv_open failed for %s", dir);
    return NULL;
  }
  return PyCapsule_New(s, "lodestar.kv", kv_capsule_destruct);
}

static PyObject *py_kv_put(PyObject *self, PyObject *args) {
  PyObject *cap;
  Py_buffer key, val;
  int sync = 1;
  if (!PyArg_ParseTuple(args, "Oy*y*|i", &cap, &key, &val, &sync)) return NULL;
  kv_store *s = kv_from_capsule(cap);
  if (!s) {
    PyBuffer_Release(&key);
    PyBuffer_Release(&val);
    return NULL;
  }
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_kv_put(s, (const uint8_t *)key.buf, (size_t)key.len,
                       (const uint8_t *)val.buf, (size_t)val.len, sync);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&key);
  PyBuffer_Release(&val);
  if (rc != 0) {
    PyErr_SetString(PyExc_OSError, "kv_put failed");
    return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject *py_kv_batch_put(PyObject *self, PyObject *args) {
  PyObject *cap, *items;
  if (!PyArg_ParseTuple(args, "OO", &cap, &items)) return NULL;
  kv_store *s = kv_from_capsule(cap);
  if (!s) return NULL;
  PyObject *seq = PySequence_Fast(items, "batch items must be a sequence");
  if (!seq) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);
    Py_buffer key, val;
    if (!PyArg_ParseTuple(pair, "y*y*", &key, &val)) {
      Py_DECREF(seq);
      return NULL;
    }
    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = lodestar_kv_put(s, (const uint8_t *)key.buf, (size_t)key.len,
                         (const uint8_t *)val.buf, (size_t)val.len, 0);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&key);
    PyBuffer_Release(&val);
    if (rc != 0) {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_OSError, "kv_put failed in batch");
      return NULL;
    }
  }
  Py_DECREF(seq);
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_kv_sync(s);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    PyErr_SetString(PyExc_OSError, "kv_sync failed");
    return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject *py_kv_get(PyObject *self, PyObject *args) {
  PyObject *cap;
  Py_buffer key;
  if (!PyArg_ParseTuple(args, "Oy*", &cap, &key)) return NULL;
  kv_store *s = kv_from_capsule(cap);
  if (!s) {
    PyBuffer_Release(&key);
    return NULL;
  }
  uint8_t small[4096];
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_kv_get(s, (const uint8_t *)key.buf, (size_t)key.len, small,
                       sizeof(small));
  Py_END_ALLOW_THREADS
  if (rc == -1) {
    PyBuffer_Release(&key);
    Py_RETURN_NONE;
  }
  if (rc == -2) {
    PyBuffer_Release(&key);
    PyErr_SetString(PyExc_OSError, "kv_get IO error");
    return NULL;
  }
  if ((size_t)rc <= sizeof(small)) {
    PyBuffer_Release(&key);
    return PyBytes_FromStringAndSize((const char *)small, (Py_ssize_t)rc);
  }
  PyObject *out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)rc);
  if (!out) {
    PyBuffer_Release(&key);
    return NULL;
  }
  int64_t rc2;
  Py_BEGIN_ALLOW_THREADS
  rc2 = lodestar_kv_get(s, (const uint8_t *)key.buf, (size_t)key.len,
                        (uint8_t *)PyBytes_AS_STRING(out), (size_t)rc);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&key);
  if (rc2 != rc) {
    Py_DECREF(out);
    PyErr_SetString(PyExc_OSError, "kv_get IO error");
    return NULL;
  }
  return out;
}

static PyObject *py_kv_delete(PyObject *self, PyObject *args) {
  PyObject *cap;
  Py_buffer key;
  int sync = 1;
  if (!PyArg_ParseTuple(args, "Oy*|i", &cap, &key, &sync)) return NULL;
  kv_store *s = kv_from_capsule(cap);
  if (!s) {
    PyBuffer_Release(&key);
    return NULL;
  }
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_kv_delete(s, (const uint8_t *)key.buf, (size_t)key.len, sync);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&key);
  if (rc != 0) {
    PyErr_SetString(PyExc_OSError, "kv_delete failed");
    return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject *py_kv_keys_range(PyObject *self, PyObject *args) {
  PyObject *cap;
  Py_buffer gte, lt;
  if (!PyArg_ParseTuple(args, "Oy*y*", &cap, &gte, &lt)) return NULL;
  kv_store *s = kv_from_capsule(cap);
  if (!s) {
    PyBuffer_Release(&gte);
    PyBuffer_Release(&lt);
    return NULL;
  }
  uint64_t n = 0;
  kv_keyref *arr = lodestar_kv_range(s, (const uint8_t *)gte.buf,
                                     (size_t)gte.len, (const uint8_t *)lt.buf,
                                     (size_t)lt.len, &n);
  PyBuffer_Release(&gte);
  PyBuffer_Release(&lt);
  if (!arr) {
    PyErr_NoMemory();
    return NULL;
  }
  PyObject *out = PyList_New((Py_ssize_t)n);
  if (!out) {
    free(arr);
    return NULL;
  }
  for (uint64_t i = 0; i < n; i++) {
    PyObject *k =
        PyBytes_FromStringAndSize((const char *)arr[i].key, arr[i].len);
    if (!k) {
      free(arr);
      Py_DECREF(out);
      return NULL;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, k);
  }
  free(arr);
  return out;
}

static PyObject *py_kv_stats(PyObject *self, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return NULL;
  kv_store *s = kv_from_capsule(cap);
  if (!s) return NULL;
  uint64_t st[4];
  lodestar_kv_stats(s, st);
  return Py_BuildValue("(KKKK)", (unsigned long long)st[0],
                       (unsigned long long)st[1], (unsigned long long)st[2],
                       (unsigned long long)st[3]);
}

static PyObject *py_kv_compact(PyObject *self, PyObject *args) {
  PyObject *cap;
  int force = 0;
  if (!PyArg_ParseTuple(args, "O|i", &cap, &force)) return NULL;
  kv_store *s = kv_from_capsule(cap);
  if (!s) return NULL;
  if (!force && !lodestar_kv_should_compact(s)) Py_RETURN_FALSE;
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = lodestar_kv_compact(s);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    PyErr_SetString(PyExc_OSError, "kv_compact failed");
    return NULL;
  }
  Py_RETURN_TRUE;
}

static PyMethodDef methods[] = {
    {"sha256", py_sha256, METH_VARARGS, "SHA-256 digest"},
    {"sha256_level", py_sha256_level, METH_VARARGS,
     "Hash N 64-byte chunks into N 32-byte digests"},
    {"xxh64", py_xxh64, METH_VARARGS, "XXH64 hash"},
    {"snappy_compress", py_snappy_compress, METH_VARARGS, "snappy block compress"},
    {"snappy_uncompress", py_snappy_uncompress, METH_VARARGS,
     "snappy block uncompress"},
    {"bls_g1_decompress", py_bls_g1_decompress, METH_VARARGS,
     "48B compressed G1 -> (rc, x||y device limbs int32[64])"},
    {"bls_g2_decompress", py_bls_g2_decompress, METH_VARARGS,
     "96B compressed G2 -> (rc, x||y device limbs int32[128])"},
    {"bls_hash_to_g2", py_bls_hash_to_g2, METH_VARARGS,
     "hash_to_curve G2 (RFC 9380) -> (rc, x||y device limbs int32[128])"},
    {"bls_g1_aggregate", py_bls_g1_aggregate, METH_VARARGS,
     "N*48B pubkeys -> (rc, x||y device limbs of the sum)"},
    {"bls_sign", py_bls_sign, METH_VARARGS,
     "sign a message: [sk]H(m) -> 96B compressed G2"},
    {"bls_verify_sets", py_bls_verify_sets, METH_VARARGS,
     "full CPU verification of n signature sets (two pairings per set)"},
    {"bls_marshal_sets", py_bls_marshal_sets, METH_VARARGS,
     "batch: pubkeys/messages/signatures -> (device limb buffer, ok flags)"},
    {"kv_open", py_kv_open, METH_VARARGS, "open/replay a KV datadir -> handle"},
    {"kv_put", py_kv_put, METH_VARARGS, "put(handle, key, value, sync=1)"},
    {"kv_batch_put", py_kv_batch_put, METH_VARARGS,
     "batch_put(handle, [(k, v), ...]) with one fsync"},
    {"kv_get", py_kv_get, METH_VARARGS, "get(handle, key) -> bytes | None"},
    {"kv_delete", py_kv_delete, METH_VARARGS, "delete(handle, key, sync=1)"},
    {"kv_keys_range", py_kv_keys_range, METH_VARARGS,
     "sorted keys in [gte, lt) (empty bound = open)"},
    {"kv_stats", py_kv_stats, METH_VARARGS,
     "(count, live_bytes, dead_bytes, active_segment)"},
    {"kv_compact", py_kv_compact, METH_VARARGS,
     "compact(handle, force=0) -> bool (ran)"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef module = {PyModuleDef_HEAD_INIT, "_lodestar_native",
                                    NULL, -1, methods};

PyMODINIT_FUNC PyInit__lodestar_native(void) {
  return PyModule_Create(&module);
}
