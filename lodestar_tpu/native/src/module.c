/* _lodestar_native: CPython bindings for the native codec/hash tier.
 *
 * sha256(data) -> 32B digest
 * sha256_level(data: N*64 bytes) -> N*32 bytes   (one merkle level)
 * xxh64(data, seed=0) -> int
 * snappy_compress(data) -> bytes
 * snappy_uncompress(data) -> bytes
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

void lodestar_sha256(const uint8_t *data, size_t len, uint8_t out[32]);
void lodestar_sha256_level(const uint8_t *in, size_t n, uint8_t *out);
uint64_t lodestar_xxh64(const uint8_t *data, size_t len, uint64_t seed);
size_t lodestar_snappy_max_compressed(size_t n);
size_t lodestar_snappy_compress(const uint8_t *src, size_t len, uint8_t *dst);
int lodestar_snappy_uncompress(const uint8_t *src, size_t src_len,
                               uint8_t *dst, size_t dst_len);

static int get_varint_head(const uint8_t *src, Py_ssize_t len, uint32_t *out) {
  uint32_t v = 0;
  int shift = 0;
  Py_ssize_t i = 0;
  while (i < len && shift <= 28) {
    uint8_t b = src[i++];
    v |= (uint32_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return 0;
    }
    shift += 7;
  }
  return -1;
}

static PyObject *py_sha256(PyObject *self, PyObject *args) {
  Py_buffer buf;
  uint8_t out[32];
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  lodestar_sha256((const uint8_t *)buf.buf, (size_t)buf.len, out);
  PyBuffer_Release(&buf);
  return PyBytes_FromStringAndSize((const char *)out, 32);
}

static PyObject *py_sha256_level(PyObject *self, PyObject *args) {
  Py_buffer buf;
  PyObject *out;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  if (buf.len % 64 != 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "input must be a multiple of 64 bytes");
    return NULL;
  }
  out = PyBytes_FromStringAndSize(NULL, buf.len / 2);
  if (out == NULL) {
    PyBuffer_Release(&buf);
    return NULL;
  }
  lodestar_sha256_level((const uint8_t *)buf.buf, (size_t)(buf.len / 64),
                        (uint8_t *)PyBytes_AS_STRING(out));
  PyBuffer_Release(&buf);
  return out;
}

static PyObject *py_xxh64(PyObject *self, PyObject *args) {
  Py_buffer buf;
  unsigned long long seed = 0;
  uint64_t h;
  if (!PyArg_ParseTuple(args, "y*|K", &buf, &seed)) return NULL;
  h = lodestar_xxh64((const uint8_t *)buf.buf, (size_t)buf.len, (uint64_t)seed);
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLongLong((unsigned long long)h);
}

static PyObject *py_snappy_compress(PyObject *self, PyObject *args) {
  Py_buffer buf;
  PyObject *out;
  size_t max, n;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  if ((uint64_t)buf.len > 0xffffffffu) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "input too large for snappy block");
    return NULL;
  }
  max = lodestar_snappy_max_compressed((size_t)buf.len);
  out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)max);
  if (out == NULL) {
    PyBuffer_Release(&buf);
    return NULL;
  }
  n = lodestar_snappy_compress((const uint8_t *)buf.buf, (size_t)buf.len,
                               (uint8_t *)PyBytes_AS_STRING(out));
  PyBuffer_Release(&buf);
  if (n == 0 && buf.len != 0) {
    Py_DECREF(out);
    PyErr_SetString(PyExc_MemoryError, "snappy compression failed");
    return NULL;
  }
  if (_PyBytes_Resize(&out, (Py_ssize_t)n) < 0) return NULL;
  return out;
}

static PyObject *py_snappy_uncompress(PyObject *self, PyObject *args) {
  Py_buffer buf;
  PyObject *out;
  uint32_t declared;
  int rc;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
  if (get_varint_head((const uint8_t *)buf.buf, buf.len, &declared) != 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "bad snappy header");
    return NULL;
  }
  out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)declared);
  if (out == NULL) {
    PyBuffer_Release(&buf);
    return NULL;
  }
  rc = lodestar_snappy_uncompress((const uint8_t *)buf.buf, (size_t)buf.len,
                                  (uint8_t *)PyBytes_AS_STRING(out),
                                  (size_t)declared);
  PyBuffer_Release(&buf);
  if (rc != 0) {
    Py_DECREF(out);
    PyErr_Format(PyExc_ValueError, "corrupt snappy stream (%d)", rc);
    return NULL;
  }
  return out;
}

static PyMethodDef methods[] = {
    {"sha256", py_sha256, METH_VARARGS, "SHA-256 digest"},
    {"sha256_level", py_sha256_level, METH_VARARGS,
     "Hash N 64-byte chunks into N 32-byte digests"},
    {"xxh64", py_xxh64, METH_VARARGS, "XXH64 hash"},
    {"snappy_compress", py_snappy_compress, METH_VARARGS, "snappy block compress"},
    {"snappy_uncompress", py_snappy_uncompress, METH_VARARGS,
     "snappy block uncompress"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef module = {PyModuleDef_HEAD_INIT, "_lodestar_native",
                                    NULL, -1, methods};

PyMODINIT_FUNC PyInit__lodestar_native(void) {
  return PyModule_Create(&module);
}
