"""Prepare-next-slot scheduler.

Reference: `chain/prepareNextSlot.ts:31` — at a fraction of the way through
each slot, pre-compute the next slot's state on the head (so epoch
transitions are paid off the critical path) and, when an execution payload
will be needed, issue an early forkchoiceUpdated with payload attributes so
the EL starts building.
"""

from __future__ import annotations

from ..state_transition import process_slots
from ..state_transition.stf import fork_types
from ..utils.logger import get_logger

log = get_logger("prepare-next-slot")


class PrepareNextSlotScheduler:
    """Call `on_slot(slot)` near the end of each slot (the dev loop and the
    clock service drive it; reference wires it to clock ticks)."""

    def __init__(self, chain):
        self.chain = chain
        self.prepared: dict[int, object] = {}

    def on_slot(self, clock_slot: int) -> None:
        chain = self.chain
        next_slot = clock_slot + 1
        head = chain.head_state
        if head.state.slot >= next_slot:
            return
        # far behind the clock (pre-sync): preparing the next slot would
        # replay the whole gap through process_slots — skip until caught up
        if next_slot - head.state.slot > 2 * chain.preset.SLOTS_PER_EPOCH:
            return
        try:
            pre = head.copy()
            process_slots(pre, chain.types, next_slot)
        except Exception:
            return
        # produce_block at next_slot consumes this instead of re-running
        # process_slots (the epoch transition is the expensive part)
        self.prepared = {next_slot: (chain.head_root, pre)}
        self._prepare_execution(pre)

    def get_prepared(self, slot: int, head_root: bytes | None = None):
        """The precomputed state for `slot`, if it was derived from
        `head_root` (a reorg between prepare and produce invalidates it)."""
        entry = self.prepared.get(slot)
        if entry is None:
            return None
        prepared_head, pre = entry
        if head_root is not None and prepared_head != head_root:
            return None
        return pre

    def _prepare_execution(self, pre) -> None:
        """Early payload-building kick (reference: prepareNextSlot's
        forkchoiceUpdated with attributes)."""
        chain = self.chain
        if chain.execution_engine is None or not pre.is_execution:
            return
        from .chain import build_payload_attributes

        prepared = build_payload_attributes(
            chain.config, pre, fork_types(pre)
        )
        if prepared is None:
            return
        parent_hash, attributes = prepared
        try:
            chain.execution_engine.notify_forkchoice_update(
                parent_hash, parent_hash, parent_hash, attributes
            )
        except Exception as e:
            # early payload-building is advisory; block production falls
            # back to a late forkchoiceUpdated
            log.debug("early forkchoiceUpdated failed: %s", e)
