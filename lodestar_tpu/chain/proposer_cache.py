"""Fee-recipient registrations per proposer.

Reference: `chain/beaconProposerCache.ts` — validators announce their
fee recipient via prepareBeaconProposer; block production looks the
proposer up here; entries expire after a retention window so stale
registrations don't linger.
"""

from __future__ import annotations

PROPOSER_PRESERVE_EPOCHS = 2


class BeaconProposerCache:
    def __init__(self, default_fee_recipient: bytes = b"\x00" * 20):
        self.default_fee_recipient = default_fee_recipient
        # validator index → (epoch registered, fee recipient)
        self._entries: dict[int, tuple[int, bytes]] = {}

    def add(self, epoch: int, validator_index: int, fee_recipient: bytes) -> None:
        self._entries[int(validator_index)] = (int(epoch), bytes(fee_recipient))

    def get(self, validator_index: int) -> bytes:
        entry = self._entries.get(int(validator_index))
        return entry[1] if entry is not None else self.default_fee_recipient

    def prune(self, current_epoch: int) -> None:
        cutoff = current_epoch - PROPOSER_PRESERVE_EPOCHS
        self._entries = {
            idx: (epoch, fr)
            for idx, (epoch, fr) in self._entries.items()
            if epoch >= cutoff
        }

    def __len__(self) -> int:
        return len(self._entries)
