"""Slot clock (reference: `chain/clock/LocalClock.ts` — wall-clock slot
ticking off genesisTime, gossip-disparity slot window)."""

from __future__ import annotations

import time
from typing import Callable

MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC = 0.5


class BeaconClock:
    """Time source → slot/epoch. `time_fn` is injectable (tests drive it
    manually; production uses time.time)."""

    def __init__(
        self,
        genesis_time: int,
        seconds_per_slot: int,
        slots_per_epoch: int,
        time_fn: Callable[[], float] = time.time,
    ):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self.slots_per_epoch = slots_per_epoch
        self.time_fn = time_fn

    @property
    def current_slot(self) -> int:
        dt = self.time_fn() - self.genesis_time
        return max(0, int(dt // self.seconds_per_slot))

    @property
    def current_epoch(self) -> int:
        return self.current_slot // self.slots_per_epoch

    def slot_with_gossip_disparity(self) -> tuple[int, int]:
        """(earliest, latest) slot acceptable under the 500 ms gossip clock
        disparity (reference currentSlotWithGossipDisparity)."""
        t = self.time_fn() - self.genesis_time
        early = int((t + MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC) // self.seconds_per_slot)
        late = int((t - MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC) // self.seconds_per_slot)
        return (max(0, late), max(0, early))

    def is_current_slot_given_disparity(self, slot: int) -> bool:
        lo, hi = self.slot_with_gossip_disparity()
        return lo <= slot <= hi

    def time_at_slot(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def seconds_into_slot(self) -> float:
        dt = self.time_fn() - self.genesis_time
        return dt % self.seconds_per_slot


class ManualClock(BeaconClock):
    """Deterministic clock for tests/sim: advance slots explicitly."""

    def __init__(self, genesis_time: int, seconds_per_slot: int, slots_per_epoch: int):
        self._now = float(genesis_time)
        super().__init__(
            genesis_time, seconds_per_slot, slots_per_epoch, time_fn=lambda: self._now
        )

    def set_slot(self, slot: int) -> None:
        self._now = self.genesis_time + slot * self.seconds_per_slot

    def advance_slot(self) -> None:
        self.set_slot(self.current_slot + 1)
