"""Chain services (SURVEY.md §2.2 `beacon-node/src/chain/`).

`BeaconChain` aggregates: the pluggable BLS verifier (`bls_verifier` — the
IBlsVerifier slot whose TPU implementation is this framework's north star),
clock, state/checkpoint caches, seen-caches, op pools, the block import
pipeline, and fork-choice wiring.
"""

from .bls_verifier import CpuBlsVerifier, IBlsVerifier  # noqa: F401
from .chain import BeaconChain  # noqa: F401
from .supervisor import SupervisedBlsVerifier  # noqa: F401
from .prepare_next_slot import PrepareNextSlotScheduler  # noqa: F401
