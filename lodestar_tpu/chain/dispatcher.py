"""Continuous-batching BLS dispatcher with priority lanes.

`ThreadBufferedVerifier` (chain/bls_verifier.py) is stop-and-wait: the
host sits idle while the device computes, then the device sits idle
while the host preps the next batch, and every gossip topic shares one
undifferentiated buffer — a flood of attestations can starve a block
proposal of its verification slot. This module applies the LLM-serving
continuous-batching idea (Orca-style iteration scheduling, vLLM-style
admission control) to BLS dispatch:

- **Coalescing** — requests arriving while the device is busy merge into
  the NEXT batch instead of waiting a full round-trip each.
- **Double-buffering** — two (configurable) worker threads call the
  wrapped verifier concurrently, so host marshal of batch N+1 overlaps
  device compute of batch N; the supervisor's dispatch lock serializes
  the actual device step, making the overlap pure host/device pipelining.
- **Priority lanes** — block > sync_committee > aggregate > attestation,
  mirroring the reference beacon node's gossip queue shapes. A batch is
  drained in strict lane order, so a block's signature sets always ride
  the first batch out.
- **Admission control / load-shedding** — per-lane queue caps (block is
  NEVER capped or shed) plus a global pending cap; under flood, queued
  attestations are evicted first, then aggregates, then sync-committee
  messages. Shed waiters get a PROMPT typed `BlsShedError` (mapped to
  gossip IGNORE by callers), never the waiter-timeout escalation ride.
  When the PR-4 supervisor breaker is open (device evicted, CPU tier
  serving), effective lane caps halve — the slow tier gets a shorter
  queue rather than a longer one.

Lint note (tools/lint/checks_locks.py): all `# guarded-by: _lock` state
is mutated only inside `*_locked` helpers; the Condition wraps the same
`self._lock` the annotations name.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .bls_verifier import (
    BlsShedError,
    ThreadBufferedVerifier,
    _verify_merged,
)
from ..observability import device_ledger

__all__ = ["BlsLaneDispatcher", "BlsShedError", "LANES", "DEFAULT_LANE"]

# Strict priority order, highest first (reference gossip queue shapes).
LANES = ("block", "sync_committee", "aggregate", "attestation")
LANE_PRIORITY = {lane: i for i, lane in enumerate(LANES)}
DEFAULT_LANE = "aggregate"


def _lane_caps_from_env() -> dict[str, int]:
    from ..utils.env import env_int

    # block is deliberately absent: the block lane is never capped.
    return {
        "sync_committee": env_int("LODESTAR_TPU_LANE_CAP_SYNC_COMMITTEE"),
        "aggregate": env_int("LODESTAR_TPU_LANE_CAP_AGGREGATE"),
        "attestation": env_int("LODESTAR_TPU_LANE_CAP_ATTESTATION"),
    }


class BlsLaneDispatcher(ThreadBufferedVerifier):
    """Drop-in `ThreadBufferedVerifier` replacement with continuous
    batching, four priority lanes, and flood load-shedding.

    `verify_signature_sets(sets, batchable=True, lane="aggregate")`
    blocks the calling (gossip-executor) thread until its verdict is
    ready, exactly like the base facade — but raises `BlsShedError`
    promptly when admission control sheds the request. Unknown lanes
    route to the default lane, so existing callers keep working
    unchanged."""

    def __init__(self, verifier, max_sigs: int | None = None,
                 max_wait_ms: float | None = None, prom=None, pipeline=None,
                 waiter_timeout_s: float | None = None,
                 workers: int | None = None, max_coalesce: int | None = None,
                 pending_cap: int | None = None,
                 lane_caps: dict[str, int] | None = None):
        from .bls_verifier import MAX_BUFFER_WAIT_MS, MAX_BUFFERED_SIGS
        from ..utils.env import env_bool, env_int

        super().__init__(
            verifier,
            max_sigs=MAX_BUFFERED_SIGS if max_sigs is None else max_sigs,
            max_wait_ms=MAX_BUFFER_WAIT_MS if max_wait_ms is None else max_wait_ms,
            prom=prom, pipeline=pipeline, waiter_timeout_s=waiter_timeout_s,
        )
        self.workers = env_int("LODESTAR_TPU_LANE_WORKERS") if workers is None else workers
        self.max_coalesce = (
            env_int("LODESTAR_TPU_LANE_MAX_COALESCE")
            if max_coalesce is None else max_coalesce
        )
        self.pending_cap = (
            env_int("LODESTAR_TPU_LANE_PENDING_CAP")
            if pending_cap is None else pending_cap
        )
        self.lane_caps = _lane_caps_from_env() if lane_caps is None else dict(lane_caps)
        # H(msg) dedup at the coalescing point (ISSUE 18): a flood of
        # aggregates for one attestation pays one hash_to_g2
        self._h2c_dedup = env_bool("LODESTAR_TPU_H2C_DEDUP")
        # the Condition shares self._lock (created by the base __init__),
        # so waiters/notifies and the guarded-by annotations agree
        self._cv = threading.Condition(self._lock)
        # entry: (sets, event, holder, lane, t_enqueued)
        self._lane_q: dict[str, deque] = {lane: deque() for lane in LANES}  # guarded-by: _lock
        self._lane_sets: dict[str, int] = {lane: 0 for lane in LANES}  # guarded-by: _lock
        self._pending_sets = 0  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self.pipeline.bind_lane_depths(self._lanes_state)
        self._threads = [
            threading.Thread(
                target=self._worker_loop, name=f"bls-lane-worker-{i}", daemon=True
            )
            for i in range(max(1, self.workers))
        ]
        for t in self._threads:
            t.start()

    # -- observability ------------------------------------------------------

    def _buffered_sigs(self) -> int:
        with self._lock:
            return self._pending_sets

    def _lanes_state(self) -> dict:
        """Live state for `/debug/lanes` and `pipeline.lanes_snapshot()`."""
        with self._lock:
            return {
                "lanes": {
                    lane: {
                        "queued_sets": self._lane_sets[lane],
                        "queued_requests": len(self._lane_q[lane]),
                        "cap": self.lane_caps.get(lane, 0),
                    }
                    for lane in LANES
                },
                "pending_sets": self._pending_sets,
                "pending_cap": self.pending_cap,
                "inflight_batches": self._inflight,
                "workers": len(self._threads),
                "max_coalesce": self.max_coalesce,
                "closed": self._closed,
            }

    # -- admission ----------------------------------------------------------

    def _breaker_open(self) -> bool:
        """True when the wrapped (supervised) verifier's breaker is open —
        device evicted, CPU tier serving — so effective lane caps halve:
        a ~300x slower tier needs a shorter queue, not a longer one."""
        try:
            return getattr(self.verifier, "breaker_state", None) == "open"
        except Exception:
            return False  # unsupervised verifier: no breaker, no halving

    def verify_signature_sets(self, sets, batchable: bool = True,
                              lane: str = DEFAULT_LANE) -> bool:
        sets = list(sets)
        if not sets:
            return False
        if lane not in LANE_PRIORITY:
            lane = DEFAULT_LANE
        # latency-critical callers and calls already at batch size skip
        # the queue entirely (base-facade contract, batchable=False)
        if not batchable or len(sets) >= self.max_sigs:
            if self.prom is not None:
                self.prom.bls_main_thread_sets_total.inc(len(sets))
            return self.verifier.verify_signature_sets(sets)
        ev = threading.Event()
        holder: list = [None]
        with self._cv:
            if self._closed:
                shed, direct = None, True
            else:
                shed = self._admit_locked(sets, ev, holder, lane)
                direct = False
        if direct:
            return self.verifier.verify_signature_sets(sets)
        if shed is not None:
            raise shed
        if not ev.wait(self.waiter_timeout):
            self.pipeline.waiter_timeout()
            from ..utils.logger import get_logger

            get_logger("bls-verifier").error(
                "verify waiter gave up after %.1fs: lane workers wedged "
                "(%d sets, lane=%s); counted in "
                "lodestar_bls_verifier_waiter_timeouts_total",
                self.waiter_timeout, len(sets), lane,
            )
            out = holder[0]
            if isinstance(out, BlsShedError):
                raise out
            return out if out is not None else False
        out = holder[0]
        if isinstance(out, BlsShedError):
            raise out
        return out

    def _admit_locked(self, sets, ev, holder, lane):
        """Admission control under the lock. Returns a `BlsShedError` to
        raise (request NOT queued) or None (queued, worker notified)."""
        n = len(sets)
        cap = self.lane_caps.get(lane, 0)
        if cap and self._breaker_open():
            cap = max(1, cap // 2)
        if cap and lane != "block" and self._lane_sets[lane] + n > cap:
            self.pipeline.lane_shed(lane, n)
            return BlsShedError(lane, n, "lane cap")
        if self.pending_cap and self._pending_sets + n > self.pending_cap:
            # flood: evict strictly-lower-priority queued work first …
            self._evict_locked(
                self._pending_sets + n - self.pending_cap, LANE_PRIORITY[lane]
            )
            # … and if that freed nothing (we ARE the lowest priority
            # with work), shed the incoming request — unless it's a block
            if self._pending_sets + n > self.pending_cap and lane != "block":
                self.pipeline.lane_shed(lane, n)
                return BlsShedError(lane, n, "pending cap")
        self._lane_q[lane].append((sets, ev, holder, lane, time.monotonic()))
        self._lane_sets[lane] += n
        self._pending_sets += n
        if self.prom is not None:
            self.prom.bls_buffer_depth.set(self._pending_sets)
        self.pipeline.lane_depth_set(lane, self._lane_sets[lane])
        self._cv.notify()
        return None

    def _evict_locked(self, need: int, incoming_priority: int) -> int:
        """Shed queued entries from the lowest-priority non-empty lane
        upward until `need` sets are freed, never touching the block lane
        or any lane at/above the incoming request's priority. Evicted
        waiters resolve IMMEDIATELY with the typed rejection."""
        freed = 0
        for lane in reversed(LANES):  # attestation first, block last
            if LANE_PRIORITY[lane] <= incoming_priority or lane == "block":
                break
            q = self._lane_q[lane]
            evicted = 0
            while q and freed < need:
                e_sets, e_ev, e_holder, e_lane, _ = q.popleft()
                k = len(e_sets)
                self._lane_sets[lane] -= k
                self._pending_sets -= k
                freed += k
                evicted += k
                e_holder[0] = BlsShedError(
                    e_lane, k, "evicted by higher-priority traffic"
                )
                e_ev.set()
            if evicted:
                self.pipeline.lane_shed(lane, evicted)
                self.pipeline.lane_depth_set(lane, self._lane_sets[lane])
            if freed >= need:
                break
        return freed

    # -- worker loop (continuous batching) ----------------------------------

    def _ready_reason_locked(self):
        """Why the head-of-queue work should dispatch NOW, or None."""
        if self._pending_sets == 0:
            return None
        if self._lane_q["block"]:
            return "priority"  # a block never waits out the timer window
        if self._pending_sets >= self.max_sigs:
            return "size"
        if self._inflight and self._pending_sets >= max(1, self.max_sigs // 2):
            # device busy and a half-batch is waiting: prep it now so the
            # host marshal overlaps the in-flight device step
            return "overlap"
        oldest = self._oldest_enqueue_locked()
        if oldest is not None and time.monotonic() - oldest >= self.max_wait:
            return "timer"
        return None

    def _oldest_enqueue_locked(self):
        oldest = None
        for q in self._lane_q.values():
            if q and (oldest is None or q[0][4] < oldest):
                oldest = q[0][4]
        return oldest

    def _wait_timeout_locked(self) -> float | None:
        oldest = self._oldest_enqueue_locked()
        if oldest is None:
            return None  # nothing queued: sleep until notified
        return max(0.001, self.max_wait - (time.monotonic() - oldest))

    def _pop_locked(self):
        """Drain queued entries in strict lane-priority order, coalescing
        up to `max_coalesce` sets into one device batch (always at least
        one entry, however large)."""
        entries: list = []
        n_sets = 0
        for lane in LANES:
            q = self._lane_q[lane]
            while q and (not entries or n_sets + len(q[0][0]) <= self.max_coalesce):
                e = q.popleft()
                k = len(e[0])
                self._lane_sets[lane] -= k
                self._pending_sets -= k
                n_sets += k
                entries.append(e)
            self.pipeline.lane_depth_set(lane, self._lane_sets[lane])
            if entries and n_sets >= self.max_coalesce:
                break
        if self.prom is not None:
            self.prom.bls_buffer_depth.set(self._pending_sets)
        return entries, n_sets

    def _begin_batch_locked(self) -> bool:
        overlapped = self._inflight > 0
        self._inflight += 1
        return overlapped

    def _end_batch_locked(self) -> None:
        self._inflight -= 1
        self._cv.notify_all()

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._closed:
                        return  # close() already shed every queued entry
                    reason = self._ready_reason_locked()
                    if reason is not None:
                        break
                    self._cv.wait(self._wait_timeout_locked())
                entries, n_sets = self._pop_locked()
                overlapped = self._begin_batch_locked()
            try:
                if entries:
                    self._dispatch_batch(entries, n_sets, reason, overlapped)
            finally:
                with self._cv:
                    self._end_batch_locked()

    def _dispatch_batch(self, entries, n_sets, reason, overlapped) -> None:
        now = time.monotonic()
        if self.prom is not None:
            for _, _, _, _, enq in entries:
                self.prom.bls_buffer_wait_seconds.observe(now - enq)
        self.pipeline.lane_coalesce(n_sets)
        self.pipeline.lane_overlap(overlapped)
        self._dedup_h2c(entries)
        t0 = time.monotonic()
        try:
            # device-time attribution: entries drain in strict priority
            # order, so the batch is charged to its highest-priority lane
            with device_ledger.ledger().lane_flush(
                entries[0][3], overlapped=overlapped
            ):
                per_request = _verify_merged(
                    self.verifier, [e[0] for e in entries], self.metrics,
                    self.prom,
                )
        except Exception:
            per_request = [False] * len(entries)
            from ..utils.logger import get_logger

            get_logger("bls-verifier").exception(
                "lane batch verification failed; resolving %d requests as "
                "invalid", len(entries),
            )
        self.pipeline.flush(reason, latency_s=time.monotonic() - t0)
        for (_, ev, holder, _, _), verdict in zip(entries, per_request):
            holder[0] = verdict
            ev.set()

    def _dedup_h2c(self, entries) -> None:
        """H(msg) dedup across the coalesced batch (ISSUE 18): committee
        traffic repeats attestation data across aggregates, so hash each
        UNIQUE 32-byte root once through the verifier's h2c cache before
        the marshal path walks the sets. Purely a pre-warm — the marshal
        path then hits `_h2c_cache` for every duplicate, so verdicts are
        bit-identical with dedup on or off. Verifiers without `warm_h2c`
        (mock/CPU tiers) skip silently."""
        if not self._h2c_dedup:
            return
        warm = getattr(self.verifier, "warm_h2c", None)
        if warm is None:
            return
        seen: set = set()
        dupes = 0
        for sets, _, _, _, _ in entries:
            for s in sets:
                try:
                    m = bytes(s.message)
                except (AttributeError, TypeError, ValueError):
                    continue  # mock/opaque sets have no message shape
                if len(m) != 32:
                    continue
                if m in seen:
                    dupes += 1
                else:
                    seen.add(m)
        if not seen:
            return
        try:
            warm(seen)
        except Exception:
            from ..utils.logger import get_logger

            get_logger("bls-verifier").exception("h2c dedup pre-warm failed")
            return
        self.pipeline.h2c_dedup(dupes)

    # -- lifecycle ----------------------------------------------------------

    def _close_locked(self) -> None:
        self._closed = True
        for lane in LANES:
            q = self._lane_q[lane]
            shed = 0
            while q:
                e_sets, e_ev, e_holder, e_lane, _ = q.popleft()
                k = len(e_sets)
                self._lane_sets[lane] -= k
                self._pending_sets -= k
                shed += k
                e_holder[0] = BlsShedError(e_lane, k, "dispatcher closed")
                e_ev.set()
            if shed:
                self.pipeline.lane_shed(lane, shed)
            self.pipeline.lane_depth_set(lane, 0)
        self._cv.notify_all()

    def close(self) -> None:
        """Stop the workers; queued waiters get the prompt typed shed
        rejection (the node is shutting down, not wedged). Idempotent;
        post-close verify calls go straight to the wrapped verifier."""
        with self._cv:
            if self._closed:
                return
            self._close_locked()
        for t in self._threads:
            t.join(timeout=10.0)
