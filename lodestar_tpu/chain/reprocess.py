"""Reprocess queue: hold attestations whose target block hasn't arrived.

Reference: `chain/reprocess.ts:51` (ReprocessController) — gossip
attestations referencing an unknown head block wait up to
WAIT_TIME_BEFORE_DROP for the block to be imported, then re-enter
validation; the block-import path notifies waiters by root.
"""

from __future__ import annotations

from dataclasses import dataclass, field

REPROCESS_MIN_WAIT_SEC = 2.0
MAX_QUEUED_TOTAL = 16_384  # global budget across all awaited roots


@dataclass
class _Waiting:
    items: list = field(default_factory=list)
    added_at: float = 0.0


class ReprocessController:
    def __init__(self, time_fn=None):
        import time as _time

        self._time = time_fn if time_fn is not None else _time.time
        self._by_root: dict[bytes, _Waiting] = {}
        self._total = 0  # running count — the budget check is on the hot path
        self.metrics = {"queued": 0, "resolved": 0, "dropped": 0}

    def wait_for_block(self, block_root: bytes, item) -> bool:
        """Queue `item` (an unvalidated attestation + its context) until
        `block_root` is imported. False when the global budget is spent —
        checked BEFORE creating any entry, so rejected floods of distinct
        unknown roots leave no residue."""
        if self._total >= MAX_QUEUED_TOTAL:
            self.metrics["dropped"] += 1
            return False
        waiting = self._by_root.setdefault(
            block_root, _Waiting(added_at=self._time())
        )
        waiting.items.append(item)
        self._total += 1
        self.metrics["queued"] += 1
        return True

    def on_block_imported(self, block_root: bytes) -> list:
        """Returns the queued items for this root — the caller re-runs
        gossip validation on each (reference: emits and re-validates)."""
        waiting = self._by_root.pop(block_root, None)
        if waiting is None:
            return []
        self._total -= len(waiting.items)
        self.metrics["resolved"] += len(waiting.items)
        return waiting.items

    def prune(self, max_age_sec: float = REPROCESS_MIN_WAIT_SEC) -> int:
        """Drop entries older than the wait budget; returns dropped count."""
        now = self._time()
        dropped = 0
        for root in [
            r for r, w in self._by_root.items() if now - w.added_at > max_age_sec
        ]:
            dropped += len(self._by_root.pop(root).items)
        self._total -= dropped
        self.metrics["dropped"] += dropped
        return dropped
