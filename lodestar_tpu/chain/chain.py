"""BeaconChain: the chain aggregate + block import pipeline.

Reference: `chain/chain.ts:66` (BeaconChain), `chain/blocks/` (BlockProcessor
→ verifyBlocksSanityChecks → verifyBlocksInEpoch [state transition ∥
signatures ∥ execution] → importBlock), `chain/produceBlock/`.

The import pipeline keeps the reference's separation: sanity checks →
state transition WITHOUT inline signature checks → ONE batched signature
verification over all sets of the segment (through the pluggable verifier
— TPU path) → fork-choice/cache/pool import.
"""

from __future__ import annotations

import numpy as np

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import DOMAIN_BEACON_ATTESTER
from ..state_transition import CachedBeaconState, process_slots
from ..state_transition.block import BlockProcessingError, get_attesting_indices
from ..state_transition.epoch import _get_block_root
from ..state_transition.signature_sets import get_block_signature_sets
from ..state_transition.stf import state_transition
from ..state_transition import util as st_util
from ..fork_choice import ForkChoice, ForkChoiceStore, ProtoArray
from .bls_verifier import CpuBlsVerifier, IBlsVerifier
from .clock import BeaconClock, ManualClock
from .op_pools import AggregatedAttestationPool, AttestationPool, OpPool
from .seen_cache import (
    SeenAggregatedAttestations,
    SeenAggregators,
    SeenAttesters,
    SeenBlockProposers,
)
from .state_cache import CheckpointStateCache, StateContextCache


class BlockImportError(ValueError):
    pass


class BeaconChain:
    """Single-process chain service (the composition the reference builds
    in `chain.ts` ctor: verifier, clock, caches, pools, fork choice)."""

    def __init__(
        self,
        config,
        types,
        anchor_state,
        verifier: IBlsVerifier | None = None,
        clock: BeaconClock | None = None,
        db=None,
    ):
        self.config = config
        self.types = types
        self.preset = config.preset
        self.bls = verifier if verifier is not None else CpuBlsVerifier()

        cached = CachedBeaconState(config, anchor_state, self.preset)
        self.head_state = cached
        anchor_root = _anchor_block_root(anchor_state)
        self.genesis_time = anchor_state.genesis_time

        self.clock = clock if clock is not None else ManualClock(
            self.genesis_time, config.SECONDS_PER_SLOT, self.preset.SLOTS_PER_EPOCH
        )

        proto = ProtoArray(
            justified_epoch=anchor_state.current_justified_checkpoint.epoch,
            finalized_epoch=anchor_state.finalized_checkpoint.epoch,
        )
        proto.on_block(
            anchor_state.slot,
            anchor_root,
            None,
            anchor_state.hash_tree_root(),
            anchor_state.current_justified_checkpoint.epoch,
            anchor_state.finalized_checkpoint.epoch,
        )
        store = ForkChoiceStore(
            current_slot=anchor_state.slot,
            justified_checkpoint=(
                anchor_state.current_justified_checkpoint.epoch,
                anchor_root,
            ),
            finalized_checkpoint=(
                anchor_state.finalized_checkpoint.epoch,
                anchor_root,
            ),
            justified_balances=cached.flat.effective_balance.astype(np.int64),
        )
        self.fork_choice = ForkChoice(store, proto, self.preset.SLOTS_PER_EPOCH)
        self.head_root = anchor_root

        self.state_cache = StateContextCache()
        self.checkpoint_state_cache = CheckpointStateCache()
        self.state_cache.add(
            anchor_state.hash_tree_root(), cached, block_root=anchor_root
        )

        self.attestation_pool = AttestationPool()
        self.aggregated_pool = AggregatedAttestationPool()
        self.op_pool = OpPool()
        self.seen_attesters = SeenAttesters()
        self.seen_aggregators = SeenAggregators()
        self.seen_block_proposers = SeenBlockProposers()
        self.seen_aggregated = SeenAggregatedAttestations()
        self.blocks: dict[bytes, object] = {anchor_root: None}
        self.finalized_blocks: dict[bytes, object] = {}

        from ..db import BeaconDb
        from .archiver import Archiver
        from .regen import StateRegenerator

        self.db = db if db is not None else BeaconDb(types)
        self.regen = StateRegenerator(self)
        self.archiver = Archiver(self, self.db)

        # light-client server (altair+ blocks carry sync aggregates)
        from ..light_client import LightClientServer

        self.light_client_server = LightClientServer(config, types, self.preset)

    # -- block import (reference chain/blocks pipeline) ----------------------

    def process_block(self, signed_block, verify_signatures: bool = True):
        block = signed_block.message
        block_root = block.hash_tree_root()
        # sanity checks (verifyBlocksSanityChecks)
        if block_root in self.blocks:
            return block_root  # already known
        parent_root = bytes(block.parent_root)
        if parent_root not in self.blocks:
            raise BlockImportError(f"unknown parent {parent_root.hex()}")
        finalized_slot = st_util.compute_start_slot_at_epoch(
            self.fork_choice.store.finalized_checkpoint[0],
            self.preset.SLOTS_PER_EPOCH,
        )
        if block.slot <= finalized_slot:
            raise BlockImportError("block slot not after finalized")

        # pre-state
        pre = self._get_pre_state(signed_block)
        # state transition without inline signature verification
        post = pre.copy()
        state_transition(
            post, self.types, signed_block,
            verify_state_root=True, verify_signatures=False,
        )
        # batched signature verification via the pluggable verifier (the
        # post state's epoch context covers the block's committees/proposer)
        if verify_signatures:
            sets = get_block_signature_sets(post, self.types, signed_block)
            if not self.bls.verify_signature_sets(sets):
                raise BlockImportError("block signature set verification failed")

        self._import_block(signed_block, block_root, post)
        return block_root

    def _get_pre_state(self, signed_block) -> CachedBeaconState:
        """Pre-state via regen: cache fast path, replay fallback
        (reference: regen.getPreState from the BlockProcessor)."""
        from .regen import RegenError

        try:
            return self.regen.get_pre_state(signed_block.message)
        except RegenError as e:
            raise BlockImportError(str(e)) from e

    def _import_block(self, signed_block, block_root: bytes, post) -> None:
        block = signed_block.message
        state = post.state
        prev_finalized = self.fork_choice.store.finalized_checkpoint[0]
        # fork choice
        self.fork_choice.update_time(max(self.clock.current_slot, block.slot))
        self.fork_choice.on_block(
            block.slot,
            block_root,
            bytes(block.parent_root),
            bytes(block.state_root),
            (
                state.current_justified_checkpoint.epoch,
                bytes(state.current_justified_checkpoint.root),
            ),
            (
                state.finalized_checkpoint.epoch,
                bytes(state.finalized_checkpoint.root),
            ),
            justified_balances=post.flat.effective_balance.astype(np.int64),
        )
        # per-attestation fork-choice votes (importBlock.ts:88-130)
        for att in block.body.attestations:
            try:
                indices = get_attesting_indices(
                    post, att.data, att.aggregation_bits
                )
                self.fork_choice.on_attestation(
                    indices, bytes(att.data.beacon_block_root), att.data.target.epoch
                )
            except Exception:
                continue
        # light-client data: the sync aggregate in this block signs its
        # parent (reference: lightClientServer.onImportBlockHead)
        if hasattr(block.body, "sync_aggregate"):
            parent_root = bytes(block.parent_root)
            parent_block = self.blocks.get(parent_root)
            parent_state = self.state_cache.get_by_block_root(parent_root)
            if parent_block is not None and parent_state is not None:
                try:
                    self.light_client_server.on_import_block(
                        signed_block, parent_block, parent_state
                    )
                except Exception:
                    pass  # light-client data is best-effort, never blocks import
        self.blocks[block_root] = signed_block
        self.db.block.put(block_root, signed_block)
        self.state_cache.add(state.hash_tree_root(), post, block_root=block_root)
        self.seen_block_proposers.add(block.slot, block.proposer_index)
        self.head_state = post
        self.update_head()
        # prune + archive on finalization advance
        fin_epoch = self.fork_choice.store.finalized_checkpoint[0]
        if fin_epoch > prev_finalized:
            self.seen_attesters.prune(fin_epoch)
            self.seen_aggregators.prune(fin_epoch)
            self.seen_aggregated.prune(fin_epoch)
            self.checkpoint_state_cache.prune_finalized(fin_epoch)
            self.archiver.process_finalized()
        self.aggregated_pool.prune(post.current_epoch)

    def update_head(self) -> bytes:
        self.head_root = self.fork_choice.update_head()
        head_state = self.state_cache.get_by_block_root(self.head_root)
        if head_state is not None:
            self.head_state = head_state
        return self.head_root

    # -- attestation intake (gossip path) ------------------------------------

    def on_gossip_attestation(self, attestation, data_root: bytes) -> None:
        self.attestation_pool.add(attestation, data_root)

    def on_aggregated_attestation(self, attestation, data_root: bytes) -> None:
        self.aggregated_pool.add(attestation, data_root)
        try:
            state = self.head_state
            indices = get_attesting_indices(
                state, attestation.data, attestation.aggregation_bits
            )
            self.fork_choice.update_time(self.clock.current_slot)
            self.fork_choice.on_attestation(
                indices,
                bytes(attestation.data.beacon_block_root),
                attestation.data.target.epoch,
            )
        except Exception:
            pass

    # -- block production (chain/produceBlock) -------------------------------

    def produce_block(self, slot: int, randao_reveal: bytes, graffiti: bytes = b""):
        """Assemble an unsigned block on the current head (reference
        produceBlock/produceBlockBody: pools → ops, eth1 vote, state root)."""
        pre = self.head_state.copy()
        if slot > pre.state.slot:
            process_slots(pre, self.types, slot)
        proposer = pre.epoch_ctx.get_beacon_proposer(slot)
        attestations = self.aggregated_pool.get_attestations_for_block(
            self.types, pre, self.preset.MAX_ATTESTATIONS
        )
        prop_slash, att_slash, exits = self.op_pool.get_slashings_and_exits(
            pre, self.preset
        )
        body = self.types.BeaconBlockBody(
            randao_reveal=randao_reveal,
            eth1_data=pre.state.eth1_data.copy(),
            graffiti=graffiti.ljust(32, b"\x00")[:32],
            proposer_slashings=[s.copy() for s in prop_slash],
            attester_slashings=[s.copy() for s in att_slash],
            attestations=attestations,
            voluntary_exits=[e.copy() for e in exits],
        )
        block = self.types.BeaconBlock(
            slot=slot,
            proposer_index=proposer,
            parent_root=pre.state.latest_block_header.hash_tree_root(),
            state_root=b"\x00" * 32,
            body=body,
        )
        trial = pre.copy()
        state_transition(
            trial,
            self.types,
            self.types.SignedBeaconBlock(message=block.copy(), signature=b"\x00" * 96),
            verify_state_root=False,
            verify_signatures=False,
        )
        block.state_root = trial.state.hash_tree_root()
        return block

    @property
    def finalized_checkpoint(self):
        return self.fork_choice.store.finalized_checkpoint

    @property
    def justified_checkpoint(self):
        return self.fork_choice.store.justified_checkpoint


def _anchor_block_root(state) -> bytes:
    hdr = state.latest_block_header.copy()
    if hdr.state_root == b"\x00" * 32:
        hdr.state_root = state.hash_tree_root()
    return hdr.hash_tree_root()


