"""BeaconChain: the chain aggregate + block import pipeline.

Reference: `chain/chain.ts:66` (BeaconChain), `chain/blocks/` (BlockProcessor
→ verifyBlocksSanityChecks → verifyBlocksInEpoch [state transition ∥
signatures ∥ execution] → importBlock), `chain/produceBlock/`.

The import pipeline keeps the reference's separation: sanity checks →
state transition WITHOUT inline signature checks → ONE batched signature
verification over all sets of the segment (through the pluggable verifier
— TPU path) → fork-choice/cache/pool import.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import TimeoutError as FuturesTimeout

import numpy as np

from ..state_transition import CachedBeaconState, process_slots
from ..state_transition.block import get_attesting_indices
from ..state_transition.signature_sets import get_block_signature_sets
from ..state_transition.stf import state_transition
from ..state_transition import util as st_util
from ..fork_choice import ForkChoice, ForkChoiceStore, ProtoArray
from ..observability import spans as _spans
from ..utils.env import env_float
from .bls_verifier import CpuBlsVerifier, IBlsVerifier
from .clock import BeaconClock, ManualClock
from .op_pools import (
    AggregatedAttestationPool,
    AttestationPool,
    BlsToExecutionChangePool,
    OpPool,
    SyncCommitteeMessagePool,
    SyncContributionAndProofPool,
)
from .seen_cache import (
    SeenAggregatedAttestations,
    SeenAggregators,
    SeenAttesters,
    SeenBlockProposers,
    SeenContributionAndProof,
    SeenSyncCommitteeMessages,
)
from .state_cache import CheckpointStateCache, StateContextCache

_log = logging.getLogger(__name__)


def _verify_now(verifier, sets) -> bool:
    """verify_signature_sets with batchable=False where the facade
    supports it (block/segment import must not wait out a gossip
    batching window).

    Support is detected from the signature (cached per underlying
    function, so instance-attribute overrides can't poison other
    instances of the class) — not by catching TypeError around the live
    call, which would swallow a genuine TypeError raised inside
    verification (malformed set contents) and silently re-run the whole
    batch. An explicit `batchable` parameter counts, and so does a
    `**kwargs` catch-all (ADVICE round 5): a thin wrapper/decorator that
    forwards keyword arguments to a batching facade must receive
    batchable=False, not silently fall into the wait-window path."""
    fn = verifier.verify_signature_sets
    key = getattr(fn, "__func__", fn)
    supports = _VERIFY_NOW_SUPPORT.get(key)
    if supports is None:
        import inspect

        try:
            params = inspect.signature(fn).parameters
            supports = "batchable" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (ValueError, TypeError):  # builtins without signatures
            supports = False
        _VERIFY_NOW_SUPPORT[key] = supports
    if supports:
        return fn(sets, batchable=False)
    return fn(sets)


import weakref

_VERIFY_NOW_SUPPORT: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class BlockImportError(ValueError):
    pass


def _bounded_result(fut, site: str, m=None):
    """``fut.result()`` bounded by LODESTAR_TPU_IMPORT_WAIT_TIMEOUT.

    Block/segment import must never pin the serving thread forever on a
    wedged future (a hung EL socket, a dead device worker): the wait is
    bounded (<= 0 disables the bound), and a timeout increments
    ``lodestar_chain_blocking_wait_timeouts_total{site=...}`` before
    failing the import with a clear error instead of hanging silently.
    """
    timeout = env_float("LODESTAR_TPU_IMPORT_WAIT_TIMEOUT")
    if timeout is not None and timeout <= 0:
        timeout = None
    try:
        return fut.result(timeout=timeout)
    except FuturesTimeout:
        if m is not None:
            m.blocking_wait_timeouts_total.inc(site=site)
        _log.error(
            "blocking wait at %s exceeded LODESTAR_TPU_IMPORT_WAIT_TIMEOUT "
            "(%.1fs) — escalating instead of hanging the import path",
            site, timeout,
        )
        raise BlockImportError(
            f"{site} wait exceeded LODESTAR_TPU_IMPORT_WAIT_TIMEOUT "
            f"({timeout:.1f}s); the verification backend may be wedged"
        ) from None


class BeaconChain:
    """Single-process chain service (the composition the reference builds
    in `chain.ts` ctor: verifier, clock, caches, pools, fork choice)."""

    def __init__(
        self,
        config,
        types,
        anchor_state,
        verifier: IBlsVerifier | None = None,
        clock: BeaconClock | None = None,
        db=None,
        execution_engine=None,
    ):
        self.config = config
        self.types = types
        self.preset = config.preset
        self.bls = verifier if verifier is not None else CpuBlsVerifier()
        self.execution_engine = execution_engine
        # serializes chain mutation between the event loop (gossip) and
        # worker threads (range sync, REST) — see process_block
        self.import_lock = threading.RLock()
        # two helpers for the 3-way parallel block verification
        # (signatures ∥ payload, overlapping the host state transition)
        from concurrent.futures import ThreadPoolExecutor

        self._verify_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="blockverify"
        )
        # irrecoverable-fault escalation (reference ProcessShutdownCallback
        # + faultInspectionWindow/allowedFaults, chain.ts:121-123)
        self.process_shutdown_callback = None
        self.fault_inspection_window_slots = 32
        self.allowed_faults = 5
        self._fault_slots: list[int] = []

        cached = CachedBeaconState(config, anchor_state, self.preset)
        self.head_state = cached
        anchor_root = _anchor_block_root(anchor_state)
        self.genesis_time = anchor_state.genesis_time

        self.clock = clock if clock is not None else ManualClock(
            self.genesis_time, config.SECONDS_PER_SLOT, self.preset.SLOTS_PER_EPOCH
        )

        proto = ProtoArray(
            justified_epoch=anchor_state.current_justified_checkpoint.epoch,
            finalized_epoch=anchor_state.finalized_checkpoint.epoch,
            slots_per_epoch=self.preset.SLOTS_PER_EPOCH,
        )
        proto.on_block(
            anchor_state.slot,
            anchor_root,
            None,
            anchor_state.hash_tree_root(),
            anchor_state.current_justified_checkpoint.epoch,
            anchor_state.finalized_checkpoint.epoch,
        )
        store = ForkChoiceStore(
            current_slot=anchor_state.slot,
            justified_checkpoint=(
                anchor_state.current_justified_checkpoint.epoch,
                anchor_root,
            ),
            finalized_checkpoint=(
                anchor_state.finalized_checkpoint.epoch,
                anchor_root,
            ),
            justified_balances=cached.flat.effective_balance.astype(np.int64),
        )
        self.fork_choice = ForkChoice(
            store,
            proto,
            self.preset.SLOTS_PER_EPOCH,
            seconds_per_slot=config.SECONDS_PER_SLOT,
            proposer_score_boost=config.PROPOSER_SCORE_BOOST,
            safe_slots_to_update_justified=self.preset.SAFE_SLOTS_TO_UPDATE_JUSTIFIED,
            justified_balances_getter=self._justified_balances_for,
        )
        self.head_root = anchor_root

        self.state_cache = StateContextCache()
        self.checkpoint_state_cache = CheckpointStateCache()
        self.state_cache.add(
            anchor_state.hash_tree_root(), cached, block_root=anchor_root
        )

        self.attestation_pool = AttestationPool()
        self.aggregated_pool = AggregatedAttestationPool()
        self.op_pool = OpPool()
        self.sync_committee_pool = SyncCommitteeMessagePool(self.preset)
        self.sync_contribution_pool = SyncContributionAndProofPool(self.preset)
        self.bls_changes_pool = BlsToExecutionChangePool()
        self.seen_attesters = SeenAttesters()
        self.seen_aggregators = SeenAggregators()
        self.seen_block_proposers = SeenBlockProposers()
        self.seen_aggregated = SeenAggregatedAttestations()
        self.seen_sync_committee = SeenSyncCommitteeMessages()
        self.seen_contribution_and_proof = SeenContributionAndProof()
        self.blocks: dict[bytes, object] = {anchor_root: None}
        self.finalized_blocks: dict[bytes, object] = {}

        from ..db import BeaconDb
        from .archiver import Archiver
        from .regen import StateRegenerator

        self.db = db if db is not None else BeaconDb(types)
        self.regen = StateRegenerator(self)
        self.archiver = Archiver(self, self.db)

        # light-client server (altair+ blocks carry sync aggregates)
        from ..light_client import LightClientServer

        self.light_client_server = LightClientServer(config, types, self.preset)

        from .prepare_next_slot import PrepareNextSlotScheduler

        self.prepare_next_slot = PrepareNextSlotScheduler(self)

        from .proposer_cache import BeaconProposerCache

        self.beacon_proposer_cache = BeaconProposerCache()

        from .emitter import ChainEventEmitter

        self.emitter = ChainEventEmitter()

    # -- block import (reference chain/blocks pipeline) ----------------------

    def process_block(self, signed_block, verify_signatures: bool = True):
        # one writer at a time: gossip handlers run on the event loop while
        # range sync imports from an executor thread — the import lock keeps
        # the state-transition + fork-choice update atomic per block
        with self.import_lock:
            return self._process_block_locked(signed_block, verify_signatures)

    def _process_block_locked(self, signed_block, verify_signatures: bool = True):
        block = signed_block.message
        block_root = block.hash_tree_root()
        # sanity checks (verifyBlocksSanityChecks)
        if block_root in self.blocks:
            return block_root  # already known
        parent_root = bytes(block.parent_root)
        if parent_root not in self.blocks:
            raise BlockImportError(f"unknown parent {parent_root.hex()}")
        finalized_slot = st_util.compute_start_slot_at_epoch(
            self.fork_choice.store.finalized_checkpoint[0],
            self.preset.SLOTS_PER_EPOCH,
        )
        if block.slot <= finalized_slot:
            raise BlockImportError("block slot not after finalized")

        # the lifecycle span: child of the gossip trace when one is
        # active, its own root trace on direct imports (REST publish,
        # unknown-block fetch) — either way one correlated trace per block
        with _spans.tracer.span(
            "chain/process_block",
            slot=int(block.slot),
            root=block_root.hex(),
        ):
            return self._process_block_spanned(
                signed_block, block_root, verify_signatures
            )

    def _process_block_spanned(
        self, signed_block, block_root: bytes, verify_signatures: bool
    ):
        block = signed_block.message
        # pre-state (advanced to the block's slot: its epoch context covers
        # the block's committees/proposer, so signature sets can be built
        # BEFORE the state transition — the key to the 3-way overlap)
        with _spans.tracer.span("chain/pre_state"):
            pre = self._get_pre_state(signed_block)

        # 3-way parallel verification (reference verifyBlock.ts:69-80:
        # state transition ∥ BLS signatures ∥ execution payload). The
        # signature batch releases the GIL in the native marshal + device
        # dispatch, and the payload check blocks on the EL's HTTP reply,
        # so both genuinely overlap the pure-Python state transition.
        import time as _time

        m = getattr(self, "metrics", None)
        fut_sig = fut_payload = None
        t_start = _time.monotonic()
        # worker threads don't inherit contextvars: hand them the live span
        trace_ctx = _spans.tracer.context()
        if verify_signatures:
            sets = get_block_signature_sets(pre, self.types, signed_block)
            # block import is latency-critical: verify immediately rather
            # than sitting in a batching facade's wait window
            fut_sig = self._verify_pool.submit(
                self._verify_now_traced, trace_ctx, sets
            )
        fut_payload = self._verify_pool.submit(
            self._verify_execution_payload_traced, trace_ctx, pre, signed_block
        )

        try:
            post = pre.copy()
            with _spans.tracer.span("chain/state_transition"):
                state_transition(
                    post, self.types, signed_block,
                    verify_state_root=True, verify_signatures=False,
                )
            t_stf = _time.monotonic()
            if m is not None:
                m.block_stf_seconds.observe(t_stf - t_start)
            if fut_sig is not None and not _bounded_result(
                fut_sig, "block_signature", m
            ):
                if m is not None:
                    m.block_import_errors_total.inc(reason="signature")
                raise BlockImportError("block signature set verification failed")
            if fut_sig is not None:
                self._record_milestone("sigs_verified", block.slot)
            t_sig = _time.monotonic()
            if m is not None and fut_sig is not None:
                # wait beyond the STF, i.e. the non-overlapped signature tail
                m.block_sig_seconds.observe(t_sig - t_stf)
            # raises on INVALID; bounded so a hung EL can't wedge imports
            payload_status = _bounded_result(fut_payload, "block_payload", m)
            if m is not None:
                m.block_payload_seconds.observe(_time.monotonic() - t_sig)
                m.block_import_seconds.observe(_time.monotonic() - t_start)
                m.processed_blocks_total.inc()
        except BaseException:
            # never abandon in-flight work: an orphaned payload check
            # would pin a pool worker on the EL's HTTP timeout and
            # serialize the NEXT import behind it (round-2 review)
            for fut in (fut_sig, fut_payload):
                if fut is not None:
                    try:
                        _bounded_result(fut, "block_drain", m)
                    except Exception as drained:
                        _log.debug("drained parallel import future: %s", drained)
            raise

        self._import_block(signed_block, block_root, post, payload_status)
        return block_root

    def _verify_now_traced(self, trace_ctx, sets) -> bool:
        """_verify_now on a pool worker, attached to the caller's trace so
        the signature batch appears as a `chain/bls_verify` span."""
        with _spans.tracer.attach(trace_ctx), _spans.tracer.span(
            "chain/bls_verify", sets=len(sets)
        ):
            return _verify_now(self.bls, sets)

    def _verify_execution_payload_traced(self, trace_ctx, pre, signed_block):
        with _spans.tracer.attach(trace_ctx), _spans.tracer.span(
            "chain/execution_payload"
        ):
            return self._verify_execution_payload(pre, signed_block)

    def _record_milestone(self, milestone: str, slot) -> None:
        """Slot-milestone delay, recorded only for blocks of the CURRENT
        clock slot: range-sync imports of historic blocks would flood the
        histogram's +Inf bucket with hours-old 'delays' and bury the
        live-following signal the metric exists for."""
        if int(slot) == self.clock.current_slot:
            _spans.record_slot_milestone(self, milestone, slot)

    def process_block_segment(self, signed_blocks, verify_signatures: bool = True):
        """Import a range-sync segment with ONE batched signature dispatch.

        Reference shape (verifyBlocksInEpoch + verifyBlocksSignatures:
        ~8,000 signatures per 64-block mainnet segment verified as one
        batch, multithread/index.ts:34): pass 1 rolls the state forward —
        with the same sanity guards as the per-block path — collecting
        every block's signature sets while the execution payloads verify
        on the pool; the whole segment's sets then go to the verifier as
        one call; pass 2 imports.

        Atomicity: a pass-1/verification failure imports NOTHING. A
        pass-2 failure (a block that passed STF but breaks fork-choice
        import) leaves the verified prefix imported; the caller's
        re-download then skips those via the known-root check.
        """
        with self.import_lock:
            return self._process_segment_locked(signed_blocks, verify_signatures)

    def _process_segment_locked(self, signed_blocks, verify_signatures: bool):
        signed_blocks = list(signed_blocks)
        with _spans.tracer.span(
            "chain/process_segment", blocks=len(signed_blocks)
        ):
            return self._process_segment_spanned(
                signed_blocks, verify_signatures
            )

    def _process_segment_spanned(self, signed_blocks, verify_signatures: bool):
        import time as _time

        m = getattr(self, "metrics", None)
        pending = []
        all_sets: list = []
        set_slots: list[int] = []  # signing block's slot, parallel to all_sets
        state = None
        finalized_slot = st_util.compute_start_slot_at_epoch(
            self.fork_choice.store.finalized_checkpoint[0],
            self.preset.SLOTS_PER_EPOCH,
        )
        for signed in signed_blocks:
            block = signed.message
            root = block.hash_tree_root()
            # the per-block path's sanity checks (verifyBlocksSanityChecks)
            if root in self.blocks:
                state = None  # next block re-resolves its pre-state
                continue
            if block.slot <= finalized_slot:
                raise BlockImportError("segment block slot not after finalized")
            if state is None and bytes(block.parent_root) not in self.blocks:
                raise BlockImportError(
                    f"unknown parent {bytes(block.parent_root).hex()}"
                )
            if state is None:
                pre = self._get_pre_state(signed)
            else:
                pre = state
                if block.slot > pre.state.slot:
                    process_slots(pre, self.types, block.slot)
            if verify_signatures:
                block_sets = get_block_signature_sets(pre, self.types, signed)
                all_sets.extend(block_sets)
                set_slots.extend([int(block.slot)] * len(block_sets))
            # payload verification overlaps the NEXT block's STF (the
            # per-block path's 3-way overlap, segment-shaped)
            fut_payload = self._verify_pool.submit(
                self._verify_execution_payload, pre, signed
            )
            t0 = _time.monotonic()
            post = pre.copy()
            with _spans.tracer.span(
                "chain/state_transition", slot=int(block.slot)
            ):
                state_transition(
                    post, self.types, signed,
                    verify_state_root=True, verify_signatures=False,
                )
            if m is not None:
                m.block_stf_seconds.observe(_time.monotonic() - t0)
            pending.append((signed, root, post, fut_payload))
            state = post.copy()

        try:
            if verify_signatures and all_sets:
                t0 = _time.monotonic()
                with _spans.tracer.span("chain/bls_verify", sets=len(all_sets)):
                    batch_ok = _verify_now(self.bls, all_sets)
                if not batch_ok:
                    if m is not None:
                        m.block_import_errors_total.inc(reason="signature")
                    # bisection verdicts make pinpointing cheap (O(k·log N)
                    # final exps on the device tier), so name the offending
                    # block instead of failing the whole segment opaquely —
                    # the caller's re-download/peer-scoring can act on it
                    detail = ""
                    pinpoint = getattr(
                        self.bls, "verify_signature_sets_individual", None
                    )
                    if callable(pinpoint):
                        try:
                            with _spans.tracer.span(
                                "chain/bls_pinpoint", sets=len(all_sets)
                            ):
                                verdicts = pinpoint(all_sets)
                            bad_slots = sorted(
                                {
                                    set_slots[i]
                                    for i, ok in enumerate(verdicts)
                                    if not ok
                                }
                            )
                            if bad_slots:
                                detail = (
                                    f" (invalid signature in block(s) at "
                                    f"slot(s) {bad_slots})"
                                )
                        except Exception as e:
                            # pinpointing is best-effort diagnostics
                            _log.debug("bisect pinpointing failed: %s", e)
                    raise BlockImportError(
                        "segment signature batch failed" + detail
                    )
                if pending:
                    self._record_milestone(
                        "sigs_verified", pending[-1][0].message.slot
                    )
                if m is not None:
                    m.block_sig_seconds.observe(_time.monotonic() - t0)
        except BaseException:
            for _, _, _, fut in pending:
                try:
                    _bounded_result(fut, "segment_drain", m)
                except Exception as drained:
                    _log.debug("drained segment payload future: %s", drained)
            raise

        roots = []
        for signed, root, post, fut_payload in pending:
            try:
                payload_status = _bounded_result(
                    fut_payload, "segment_payload", m
                )
            except BaseException:
                if m is not None:
                    m.block_import_errors_total.inc(reason="payload")
                for _, _, _, f in pending:
                    if not f.done():
                        try:
                            _bounded_result(f, "segment_drain", m)
                        except Exception as drained:
                            _log.debug(
                                "drained segment payload future: %s", drained
                            )
                raise
            t0 = _time.monotonic()
            self._import_block(signed, root, post, payload_status)
            if m is not None:
                m.block_import_seconds.observe(_time.monotonic() - t0)
                m.processed_blocks_total.inc()
            roots.append(root)
        return roots

    def _verify_execution_payload(self, post, signed_block):
        """Returns the engine status (None = nothing to verify) so the
        import records the right optimistic execution_status."""
        if self.execution_engine is None or not post.is_execution:
            return None
        from ..execution.engine import ExecutePayloadStatus
        from ..state_transition.bellatrix import has_execution_payload

        body = signed_block.message.body
        if not has_execution_payload(body):
            return None  # pre-merge empty payload: nothing for the EL
        status = self.execution_engine.notify_new_payload(body.execution_payload)
        if status in (ExecutePayloadStatus.INVALID, ExecutePayloadStatus.INVALID_BLOCK_HASH):
            # optimistic-sync invalidation: with a RESOLVABLE
            # latestValidHash, ancestors after the LVH block (and their
            # descendants) become non-viable (reference LVH walk —
            # round-1 VERDICT fork-choice gap). An unresolvable LVH
            # invalidates NOTHING extra: the offending block was never
            # imported, and guessing would brick a valid head.
            lvh = getattr(self.execution_engine, "last_latest_valid_hash", None)
            lvh_root = self._block_root_of_payload(lvh) if lvh else None
            if lvh_root is not None:
                parent_root = bytes(signed_block.message.parent_root)
                invalidated = self.fork_choice.proto.invalidate_payloads(
                    parent_root, lvh_root
                )
                if invalidated:
                    import logging

                    logging.getLogger(__name__).warning(
                        "engine INVALID invalidated %d optimistic ancestors",
                        len(invalidated),
                    )
            raise BlockImportError(f"execution payload invalid: {status}")
        return status

    def _block_root_of_payload(self, block_hash: bytes) -> bytes | None:
        """Beacon block root whose payload has `block_hash` (walks the hot
        blocks; None when unknown — then only the offending head is
        invalidated)."""
        for root, signed in self.blocks.items():
            if signed is None:
                continue
            body = signed.message.body
            payload = getattr(body, "execution_payload", None)
            if payload is not None and bytes(payload.block_hash) == block_hash:
                return root
        return None

    def _justified_balances_for(self, checkpoint):
        """Effective balances of the checkpoint's OWN state for fork-choice
        adoption (reference justifiedBalancesGetter, forkChoice.ts:129):
        resolved from the checkpoint-state cache; None lets fork choice
        keep its fallback (the importing block's balances)."""
        epoch, root = checkpoint
        cached = self.checkpoint_state_cache.get(epoch, root)
        if cached is None:
            return None
        return cached.flat.effective_balance.astype(np.int64)

    def _get_pre_state(self, signed_block) -> CachedBeaconState:
        """Pre-state via regen: cache fast path, replay fallback
        (reference: regen.getPreState from the BlockProcessor)."""
        from .regen import RegenError

        try:
            return self.regen.get_pre_state(signed_block.message)
        except RegenError as e:
            raise BlockImportError(str(e)) from e

    def _import_block(
        self, signed_block, block_root: bytes, post, payload_status=None
    ) -> None:
        with _spans.tracer.span(
            "chain/import",
            slot=int(signed_block.message.slot),
            root=block_root.hex(),
        ):
            self._import_block_spanned(
                signed_block, block_root, post, payload_status
            )

    def _import_block_spanned(
        self, signed_block, block_root: bytes, post, payload_status=None
    ) -> None:
        block = signed_block.message
        state = post.state
        prev_finalized = self.fork_choice.store.finalized_checkpoint[0]
        # timeliness for the proposer boost: seconds since the block's
        # slot started, at import time
        block_delay = self.clock.time_fn() - self.clock.time_at_slot(block.slot)
        with _spans.tracer.span("chain/fork_choice"):
            self.fork_choice.update_time(
                max(self.clock.current_slot, block.slot)
            )
            # unrealized checkpoints: what FFG would reach if the epoch
            # ended now — feeds tip pull-up + prior-epoch viability
            # (reference forkChoice.ts:406-453)
            try:
                from ..state_transition.unrealized import (
                    compute_unrealized_checkpoints,
                )

                unrealized_j, unrealized_f = compute_unrealized_checkpoints(
                    post, self.types
                )
            except Exception:
                # degrading to realized checkpoints keeps import alive, but
                # silently losing pull-up would be undiagnosable — log it
                import logging

                logging.getLogger(__name__).exception(
                    "compute_unrealized_checkpoints failed; using realized"
                )
                unrealized_j = unrealized_f = None
            self.fork_choice.on_block(
                block.slot,
                block_root,
                bytes(block.parent_root),
                bytes(block.state_root),
                (
                    state.current_justified_checkpoint.epoch,
                    bytes(state.current_justified_checkpoint.root),
                ),
                (
                    state.finalized_checkpoint.epoch,
                    bytes(state.finalized_checkpoint.root),
                ),
                justified_balances=post.flat.effective_balance.astype(np.int64),
                unrealized_justified_checkpoint=unrealized_j,
                unrealized_finalized_checkpoint=unrealized_f,
                block_delay_sec=block_delay,
                execution_status=_exec_status_for_fork_choice(
                    payload_status, post
                ),
            )
            if payload_status is not None and str(
                getattr(payload_status, "value", payload_status)
            ) == "VALID":
                # a VALID verdict confirms every optimistic ancestor too
                self.fork_choice.proto.set_execution_valid(block_root)
            # per-attestation fork-choice votes (importBlock.ts:88-130)
            monitor = getattr(self, "validator_monitor", None)
            monitored = monitor.monitored if monitor is not None else set()
            for att in block.body.attestations:
                try:
                    indices = get_attesting_indices(
                        post, att.data, att.aggregation_bits
                    )
                    self.fork_choice.on_attestation(
                        indices,
                        bytes(att.data.beacon_block_root),
                        att.data.target.epoch,
                    )
                    if monitored and monitored.intersection(
                        int(i) for i in indices
                    ):
                        spe = self.preset.SLOTS_PER_EPOCH
                        target_root = self.fork_choice.get_ancestor(
                            block_root, int(att.data.target.epoch) * spe
                        )
                        head_at_slot = self.fork_choice.get_ancestor(
                            block_root, int(att.data.slot)
                        )
                        monitor.on_attestation_included(
                            int(att.data.target.epoch),
                            indices,
                            int(block.slot) - int(att.data.slot),
                            target_correct=target_root
                            == bytes(att.data.target.root),
                            head_correct=head_at_slot
                            == bytes(att.data.beacon_block_root),
                        )
                except Exception as e:
                    _log.debug(
                        "validator-monitor inclusion accounting failed: %s", e
                    )
                    continue
        if monitored:
            epoch = int(block.slot) // self.preset.SLOTS_PER_EPOCH
            monitor.on_block_proposed(
                epoch, int(block.proposer_index), delay_sec=block_delay
            )
            agg = getattr(block.body, "sync_aggregate", None)
            if agg is not None:
                pk_to_idx = post.epoch_ctx.pubkey_to_index
                included = [
                    pk_to_idx.get(bytes(pk), -1)
                    for pk, bit in zip(
                        post.state.current_sync_committee.pubkeys,
                        list(agg.sync_committee_bits),
                    )
                    if bit
                ] if hasattr(post.state, "current_sync_committee") else []
                if included:
                    monitor.on_sync_signature_included(epoch, included)
        # light-client data: the sync aggregate in this block signs its
        # parent (reference: lightClientServer.onImportBlockHead)
        if hasattr(block.body, "sync_aggregate"):
            parent_root = bytes(block.parent_root)
            parent_block = self.blocks.get(parent_root)
            parent_state = self.state_cache.get_by_block_root(parent_root)
            if parent_block is not None and parent_state is not None:
                try:
                    self.light_client_server.on_import_block(
                        signed_block, parent_block, parent_state
                    )
                    self._emit_light_client_updates()
                except Exception as e:
                    # light-client data is best-effort, never blocks import
                    _log.debug("light-client server on_import_block failed: %s", e)
        self.blocks[block_root] = signed_block
        self.db.block.put(block_root, signed_block)
        self.state_cache.add(state.hash_tree_root(), post, block_root=block_root)
        self.seen_block_proposers.add(block.slot, block.proposer_index)
        self._record_milestone("imported", block.slot)
        prev_head = self.head_root
        self.head_state = post
        with _spans.tracer.span("chain/head_update"):
            self.update_head()
            self._notify_forkchoice_to_engine()
        from .emitter import ChainEvent

        self.emitter.emit(
            ChainEvent.block,
            {"slot": str(int(block.slot)), "block": "0x" + block_root.hex()},
        )
        if self.head_root != prev_head:
            self._record_milestone("head_updated", block.slot)
            # block.state_root is the imported state's verified root — no
            # re-merkleization on the import hot path
            state_root = (
                bytes(block.state_root)
                if self.head_root == block_root
                else self.head_state.state.latest_block_header.state_root
            )
            self.emitter.emit(
                ChainEvent.head,
                {
                    "slot": str(int(self.head_state.state.slot)),
                    "block": "0x" + self.head_root.hex(),
                    "state": "0x" + bytes(state_root).hex(),
                },
            )
        # prune + archive on finalization advance
        fin_epoch = self.fork_choice.store.finalized_checkpoint[0]
        if fin_epoch > prev_finalized:
            self.seen_attesters.prune(fin_epoch)
            self.seen_aggregators.prune(fin_epoch)
            self.seen_aggregated.prune(fin_epoch)
            self.checkpoint_state_cache.prune_finalized(fin_epoch)
            self.archiver.process_finalized()
            self.bls_changes_pool.prune(post)
            fin_root = self.fork_choice.store.finalized_checkpoint[1]
            self.emitter.emit(
                ChainEvent.finalized_checkpoint,
                {"epoch": str(fin_epoch), "block": "0x" + fin_root.hex()},
            )
        self.aggregated_pool.prune(post.current_epoch)
        self.sync_committee_pool.prune(block.slot)
        self.sync_contribution_pool.prune(block.slot)
        self.seen_sync_committee.prune(block.slot)
        self.seen_contribution_and_proof.prune(block.slot)
        self.beacon_proposer_cache.prune(post.current_epoch)

    def _emit_light_client_updates(self) -> None:
        """SSE light-client events after import (reference events.ts
        light_client_optimistic_update / finality_update topics)."""
        from .emitter import ChainEvent

        lc = self.light_client_server
        optimistic = getattr(lc, "latest_optimistic_update", None)
        if optimistic is not None:
            self.emitter.emit(
                ChainEvent.lightclient_optimistic_update, optimistic.to_obj()
            )
        finality = getattr(lc, "latest_finality_update", None)
        if finality is not None:
            self.emitter.emit(
                ChainEvent.lightclient_finality_update, finality.to_obj()
            )

    def update_head(self) -> bytes:
        try:
            self.head_root = self.fork_choice.update_head()
        except Exception:
            # fork-choice head selection failing is the reference's
            # irrecoverable class (chain.ts:121-123): count it against
            # the fault window and escalate to process shutdown when the
            # budget is spent — a node that cannot pick a head must not
            # keep attesting on a stale one
            self._register_irrecoverable_fault()
            raise
        head_state = self.state_cache.get_by_block_root(self.head_root)
        if head_state is not None:
            self.head_state = head_state
        return self.head_root

    def _register_irrecoverable_fault(self) -> None:
        """faultInspectionWindow/allowedFaults semantics (reference
        BeaconChain opts + ProcessShutdownCallback): more than
        ALLOWED_FAULTS head-selection failures within the sliding
        FAULT_INSPECTION_WINDOW_SLOTS triggers the shutdown callback
        (wired by the CLI to stop the process)."""
        now_slot = self.clock.current_slot
        window = self.fault_inspection_window_slots
        allowed = self.allowed_faults
        self._fault_slots.append(now_slot)
        self._fault_slots = [s for s in self._fault_slots if s >= now_slot - window]
        cb = self.process_shutdown_callback
        if cb is not None and len(self._fault_slots) > allowed:
            import logging

            logging.getLogger(__name__).critical(
                "%d fork-choice faults within %d slots: requesting shutdown",
                len(self._fault_slots), window,
            )
            cb("irrecoverable fork-choice errors")

    def _notify_forkchoice_to_engine(self) -> None:
        """Mirror the beacon head/finalized into the EL (reference:
        engine_forkchoiceUpdated on head change, importBlock.ts)."""
        if self.execution_engine is None or not self.head_state.is_execution:
            return
        from ..state_transition.bellatrix import is_merge_transition_complete

        state = self.head_state.state
        if not is_merge_transition_complete(state):
            return
        head_hash = bytes(state.latest_execution_payload_header.block_hash)
        fin_root = self.fork_choice.store.finalized_checkpoint[1]
        fin_state = self.state_cache.get_by_block_root(fin_root)
        fin_hash = (
            bytes(fin_state.state.latest_execution_payload_header.block_hash)
            if fin_state is not None and fin_state.is_execution
            else b"\x00" * 32
        )
        try:
            self.execution_engine.notify_forkchoice_update(head_hash, head_hash, fin_hash)
        except Exception as e:
            # EL sync is advisory for the beacon side
            _log.debug("forkchoiceUpdated notification failed: %s", e)

    # -- attestation intake (gossip path) ------------------------------------

    def on_gossip_attestation(self, attestation, data_root: bytes) -> None:
        with self.import_lock:
            outcome = self.attestation_pool.add(attestation, data_root)
        m = getattr(self, "metrics", None)
        if m is not None:
            m.attestation_pool_inserts_total.inc(outcome=str(outcome))
        monitor = getattr(self, "validator_monitor", None)
        if monitor is not None and monitor.monitored:
            try:
                indices = get_attesting_indices(
                    self.head_state, attestation.data, attestation.aggregation_bits
                )
                delay = self.clock.time_fn() - self.clock.time_at_slot(
                    int(attestation.data.slot)
                )
                for idx in indices:
                    monitor.on_gossip_attestation(
                        int(attestation.data.target.epoch), int(idx), delay
                    )
            except Exception as e:
                _log.debug("validator-monitor gossip accounting failed: %s", e)

    def on_aggregated_attestation(self, attestation, data_root: bytes) -> None:
        with self.import_lock:
            self._on_aggregated_attestation_locked(attestation, data_root)

    def _on_aggregated_attestation_locked(self, attestation, data_root: bytes) -> None:
        self.aggregated_pool.add(attestation, data_root)
        try:
            state = self.head_state
            indices = get_attesting_indices(
                state, attestation.data, attestation.aggregation_bits
            )
            self.fork_choice.update_time(self.clock.current_slot)
            self.fork_choice.on_attestation(
                indices,
                bytes(attestation.data.beacon_block_root),
                attestation.data.target.epoch,
            )
            monitor = getattr(self, "validator_monitor", None)
            if monitor is not None and monitor.monitored:
                monitor.on_attestation_in_aggregate(
                    int(attestation.data.target.epoch), indices
                )
        except Exception as e:
            # aggregate fork-choice accounting is advisory; the pool add
            # above already succeeded
            _log.debug("aggregated-attestation accounting failed: %s", e)

    # -- block production (chain/produceBlock) -------------------------------

    def produce_block(
        self,
        slot: int,
        randao_reveal: bytes,
        graffiti: bytes = b"",
        fee_recipient: bytes | None = None,
    ):
        """Assemble an unsigned block on the current head (reference
        produceBlock/produceBlockBody: pools → ops, eth1 vote, sync
        aggregate, execution payload via engine, state root)."""
        from ..state_transition.stf import fork_types

        prepared = self.prepare_next_slot.get_prepared(slot, self.head_root)
        if prepared is not None:
            pre = prepared.copy()
        else:
            pre = self.head_state.copy()
            if slot > pre.state.slot:
                process_slots(pre, self.types, slot)
        types = fork_types(pre)
        parent_root = pre.state.latest_block_header.hash_tree_root()
        proposer = pre.epoch_ctx.get_beacon_proposer(slot)
        if fee_recipient is None:
            # fall back to the proposer's prepareBeaconProposer registration
            fee_recipient = self.beacon_proposer_cache.get(proposer)
        attestations = self.aggregated_pool.get_attestations_for_block(
            types, pre, self.preset.MAX_ATTESTATIONS
        )
        prop_slash, att_slash, exits = self.op_pool.get_slashings_and_exits(
            pre, self.preset
        )
        # eth1 vote + pending deposits via the tracker when one is wired
        # (node opts.eth1_provider; reference produceBlockBody eth1 data
        # vote + deposits from the eth1 cache)
        tracker = getattr(self, "eth1_tracker", None)
        eth1_data = pre.state.eth1_data.copy()
        deposits = []
        if tracker is not None:
            # READ-only here: following (log catch-up over JSON-RPC) runs
            # on the node's slot cadence in the background — a historical
            # sync inline would blow the proposal deadline (round-2
            # review finding)
            try:
                eth1_data = tracker.get_eth1_vote(
                    pre.state, int(self.clock.time_fn())
                )
                deposits = tracker.get_deposits_for_block(pre.state)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "eth1 tracker failed; producing without deposits"
                )
                eth1_data = pre.state.eth1_data.copy()
                deposits = []
        body = types.BeaconBlockBody(
            randao_reveal=randao_reveal,
            eth1_data=eth1_data,
            graffiti=graffiti.ljust(32, b"\x00")[:32],
            proposer_slashings=[s.copy() for s in prop_slash],
            attester_slashings=[s.copy() for s in att_slash],
            attestations=attestations,
            deposits=deposits,
            voluntary_exits=[e.copy() for e in exits],
        )
        if hasattr(body, "sync_aggregate"):
            # the block's sync aggregate signs the parent (previous slot root)
            body.sync_aggregate = self.sync_contribution_pool.get_sync_aggregate(
                types, max(slot, 1) - 1, parent_root
            )
        if pre.is_execution:
            payload = self._produce_execution_payload(pre, types, fee_recipient)
            if payload is not None:
                body.execution_payload = payload
        if pre.is_capella:
            body.bls_to_execution_changes = [
                c.copy() for c in self.bls_changes_pool.get_for_block(pre, self.preset)
            ]
        block = types.BeaconBlock(
            slot=slot,
            proposer_index=proposer,
            parent_root=parent_root,
            state_root=b"\x00" * 32,
            body=body,
        )
        trial = pre.copy()
        state_transition(
            trial,
            types,
            types.SignedBeaconBlock(message=block.copy(), signature=b"\x00" * 96),
            verify_state_root=False,
            verify_signatures=False,
        )
        block.state_root = trial.state.hash_tree_root()
        return block

    def _produce_execution_payload(self, pre, types, fee_recipient: bytes):
        """Build the payload through the engine (reference
        prepareExecutionPayload → engine.getPayload). Pre-merge (default
        header, no engine building) → None, leaving the default payload."""
        if self.execution_engine is None:
            return None
        prepared = build_payload_attributes(self.config, pre, types, fee_recipient)
        if prepared is None:
            return None  # pre-merge: empty payload until the EL offers one
        parent_hash, attributes = prepared
        payload_id = self.execution_engine.notify_forkchoice_update(
            parent_hash, parent_hash, parent_hash, attributes
        )
        if payload_id is None:
            return None
        fork = "capella" if pre.is_capella else "bellatrix"
        built = self.execution_engine.get_payload(payload_id, fork=fork)

        # engines return either a _MockPayload-like object (snake_case
        # attributes) or engine-API JSON (camelCase, hex quantities)
        from ..execution.engine import engine_json_field

        def got(name, default=None):
            return engine_json_field(built, name, default)

        fields = dict(
            parent_hash=_as_bytes(got("parent_hash", b"\x00" * 32)),
            fee_recipient=_as_bytes(got("fee_recipient", fee_recipient)),
            state_root=_as_bytes(got("state_root", b"\x00" * 32)),
            receipts_root=_as_bytes(got("receipts_root", b"\x00" * 32)),
            logs_bloom=_as_bytes(got("logs_bloom", b"\x00" * 256)),
            prev_randao=_as_bytes(got("prev_randao", attributes.prev_randao)),
            block_number=_as_int(got("block_number", 0)),
            gas_limit=_as_int(got("gas_limit", 30_000_000)),
            gas_used=_as_int(got("gas_used", 0)),
            timestamp=_as_int(got("timestamp", attributes.timestamp)),
            extra_data=_as_bytes(got("extra_data", b"")),
            base_fee_per_gas=_as_int(got("base_fee_per_gas", 7)),
            block_hash=_as_bytes(got("block_hash", b"\x00" * 32)),
            transactions=[_as_bytes(tx) for tx in got("transactions", []) or []],
        )
        if pre.is_capella:
            fields["withdrawals"] = [
                _as_withdrawal(types, w) for w in got("withdrawals", []) or []
            ]
        return types.ExecutionPayload(**fields)

    @property
    def finalized_checkpoint(self):
        return self.fork_choice.store.finalized_checkpoint

    @property
    def justified_checkpoint(self):
        return self.fork_choice.store.justified_checkpoint


def build_payload_attributes(config, pre, types, fee_recipient: bytes = b"\x00" * 20):
    """(parent_hash, PayloadAttributes) for building the next payload on
    `pre`'s head, or None pre-merge. Shared by produce_block and the
    prepare-next-slot scheduler (reference: prepareExecutionPayload)."""
    from ..execution.engine import PayloadAttributes
    from ..state_transition.bellatrix import (
        compute_timestamp_at_slot,
        get_randao_mix,
        is_merge_transition_complete,
    )

    state = pre.state
    if not is_merge_transition_complete(state):
        return None
    withdrawals = []
    if pre.is_capella:
        from ..state_transition.capella import get_expected_withdrawals

        withdrawals = get_expected_withdrawals(pre, types)
    attributes = PayloadAttributes(
        timestamp=compute_timestamp_at_slot(config, state),
        prev_randao=get_randao_mix(state, pre.current_epoch, pre.preset),
        suggested_fee_recipient=fee_recipient,
        withdrawals=withdrawals,
    )
    return bytes(state.latest_execution_payload_header.block_hash), attributes


def _as_bytes(value) -> bytes:
    """Engine JSON uses 0x-hex strings; mocks use bytes."""
    if isinstance(value, str):
        return bytes.fromhex(value[2:] if value.startswith("0x") else value)
    return bytes(value)


def _as_int(value) -> int:
    """Engine JSON uses hex-quantity strings ("0x1"); mocks use ints."""
    if isinstance(value, str):
        return int(value, 16) if value.startswith("0x") else int(value)
    return int(value)


def _as_withdrawal(types, w):
    """Engine JSON withdrawal dict (camelCase hex) or an SSZ Withdrawal."""
    if isinstance(w, dict):
        return types.Withdrawal(
            index=_as_int(w.get("index", 0)),
            validator_index=_as_int(w.get("validatorIndex", w.get("validator_index", 0))),
            address=_as_bytes(w.get("address", b"\x00" * 20)),
            amount=_as_int(w.get("amount", 0)),
        )
    return w


def _exec_status_for_fork_choice(payload_status, post) -> str:
    """Engine verdict → proto-array execution_status (reference
    getPostMergeExecStatus: VALID→valid, SYNCING/ACCEPTED→syncing
    [optimistic import], no payload→pre_merge)."""
    if payload_status is None or not post.is_execution:
        return "pre_merge"
    v = str(getattr(payload_status, "value", payload_status))
    return "valid" if v == "VALID" else "syncing"


def _anchor_block_root(state) -> bytes:
    hdr = state.latest_block_header.copy()
    if hdr.state_root == b"\x00" * 32:
        hdr.state_root = state.hash_tree_root()
    return hdr.hash_tree_root()


