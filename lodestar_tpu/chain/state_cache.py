"""Hot state caches.

Reference: `chain/stateCache/` — `StateContextCache` (LRU of
CachedBeaconState by state root, max 96, `stateContextCache.ts:9`) and
`CheckpointStateCache` ((epoch, root)-keyed epoch-boundary states)."""

from __future__ import annotations

from collections import OrderedDict

MAX_STATES = 96


class StateContextCache:
    def __init__(self, max_states: int = MAX_STATES):
        self.max_states = max_states
        self._cache: "OrderedDict[bytes, object]" = OrderedDict()
        # block root → state root (for lookups by block)
        self._head_state_root_by_block: dict[bytes, bytes] = {}

    def get(self, state_root: bytes):
        cached = self._cache.get(state_root)
        if cached is not None:
            self._cache.move_to_end(state_root)
        return cached

    def add(self, state_root: bytes, cached_state, block_root: bytes | None = None):
        self._cache[state_root] = cached_state
        self._cache.move_to_end(state_root)
        if block_root is not None:
            self._head_state_root_by_block[block_root] = state_root
        while len(self._cache) > self.max_states:
            evicted, _ = self._cache.popitem(last=False)
            self._head_state_root_by_block = {
                b: s for b, s in self._head_state_root_by_block.items() if s != evicted
            }

    def get_by_block_root(self, block_root: bytes):
        state_root = self._head_state_root_by_block.get(block_root)
        return self.get(state_root) if state_root is not None else None

    def prune(self, keep_state_roots: set[bytes]) -> None:
        for root in [r for r in self._cache if r not in keep_state_roots]:
            del self._cache[root]

    def __len__(self) -> int:
        return len(self._cache)


class CheckpointStateCache:
    """(epoch, block root) → epoch-boundary state; serves attestation-target
    state lookups and epoch-cache warm starts."""

    def __init__(self, max_states: int = MAX_STATES):
        self.max_states = max_states
        self._cache: "OrderedDict[tuple[int, bytes], object]" = OrderedDict()

    def get(self, epoch: int, root: bytes):
        key = (epoch, root)
        cached = self._cache.get(key)
        if cached is not None:
            self._cache.move_to_end(key)
        return cached

    def add(self, epoch: int, root: bytes, cached_state) -> None:
        self._cache[(epoch, root)] = cached_state
        self._cache.move_to_end((epoch, root))
        while len(self._cache) > self.max_states:
            self._cache.popitem(last=False)

    def get_latest(self, root: bytes, max_epoch: int):
        best = None
        best_epoch = -1
        for (epoch, r), state in self._cache.items():
            if r == root and best_epoch < epoch <= max_epoch:
                best, best_epoch = state, epoch
        return best

    def prune_finalized(self, finalized_epoch: int) -> None:
        for key in [k for k in self._cache if k[0] < finalized_epoch]:
            del self._cache[key]
