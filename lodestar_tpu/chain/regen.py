"""State regeneration: produce any hot state by replaying blocks from the
nearest cached ancestor state.

Reference: `chain/regen/` — `QueuedStateRegenerator` (queued.ts:27) /
`StateRegenerator` (regen.ts:35-115): getPreState / getCheckpointState /
getState with checkpoint- and state-cache fast paths, block replay with
signature verification OFF (blocks were verified on first import).
"""

from __future__ import annotations

from ..state_transition import process_slots
from ..state_transition.stf import state_transition
from ..state_transition import util as st_util


class RegenError(ValueError):
    pass


class StateRegenerator:
    # reference QueuedStateRegenerator: JobItemQueue maxLength 256 — a
    # deep-replay storm must reject, not pile up unboundedly
    MAX_PENDING = 256

    def __init__(self, chain):
        self.chain = chain
        # (parent_root, slot) → advanced pre-state; see get_pre_state
        self._block_slot_cache: dict[tuple[bytes, int], object] = {}
        import threading

        # serialize expensive replays (the reference queues them for the
        # same reason: concurrent deep replays multiply the work) and
        # bound how many callers may wait
        self._replay_lock = threading.Lock()
        self._pending = 0
        self._pending_lock = threading.Lock()
        # guards _block_slot_cache: gossip validation and the import path
        # mutate it from different threads, and the pop(next(iter()))
        # eviction can KeyError under a race (round-2 advisor)
        self._slot_cache_lock = threading.Lock()

    def _admit(self):
        m = getattr(self.chain, "metrics", None)
        with self._pending_lock:
            if self._pending >= self.MAX_PENDING:
                if m is not None:
                    m.regen_rejections_total.inc()
                raise RegenError(
                    f"regen queue full ({self.MAX_PENDING} pending replays)"
                )
            self._pending += 1
            if m is not None:
                m.regen_queue_pending.set(self._pending)

    def _done(self):
        m = getattr(self.chain, "metrics", None)
        with self._pending_lock:
            self._pending -= 1
            if m is not None:
                m.regen_queue_pending.set(self._pending)

    def get_state_by_root(self, state_root: bytes):
        cached = self.chain.state_cache.get(state_root)
        if cached is not None:
            return cached
        raise RegenError("state root not in hot cache; replay requires block root")

    def get_state_for_block(self, block_root: bytes):
        """State after applying the block with `block_root` (replaying
        ancestors from the nearest cached state if needed). Replays are
        serialized and bounded (MAX_PENDING) like the reference's queued
        regenerator."""
        cached = self.chain.state_cache.get_by_block_root(block_root)
        if cached is not None:
            return cached
        self._admit()
        try:
            with self._replay_lock:
                return self._replay_for_block(block_root)
        finally:
            self._done()

    def _replay_for_block(self, block_root: bytes):
        # re-check under the lock: a concurrent replay may have cached it
        cached = self.chain.state_cache.get_by_block_root(block_root)
        if cached is not None:
            return cached
        m = getattr(self.chain, "metrics", None)
        if m is not None:
            m.regen_replays_total.inc()
        # walk back through fork choice ancestry to a cached state
        chain_path = []
        root = block_root
        base = None
        while True:
            node = self.chain.fork_choice.proto.get_node(root)
            if node is None:
                raise RegenError(f"unknown block {root.hex()}")
            cached = self.chain.state_cache.get_by_block_root(root)
            if cached is not None:
                base = cached
                break
            chain_path.append(root)
            if node.parent is None:
                raise RegenError("no cached ancestor state to replay from")
            root = self.chain.fork_choice.proto.nodes[node.parent].root
        # replay forward
        state = base.copy()
        for r in reversed(chain_path):
            signed = self.chain.blocks.get(r)
            if signed is None:
                raise RegenError(f"missing block body for {r.hex()}")
            state_transition(
                state, self.chain.types, signed,
                verify_state_root=False, verify_signatures=False,
            )
            self.chain.state_cache.add(
                state.state.hash_tree_root(), state.copy(), block_root=r
            )
        return state

    def get_pre_state(self, block) -> object:
        """Pre-state for a block: parent state advanced to the block's slot
        (reference getPreState — the BlockProcessor entry point).

        A tiny (parent_root, slot) cache dedupes the advance between
        gossip validation (proposer/signature checks) and the import that
        follows moments later — the reference's getBlockSlotState role.
        Callers must NOT mutate the returned state (import copies it)."""
        key = (bytes(block.parent_root), int(block.slot))
        with self._slot_cache_lock:
            cached = self._block_slot_cache.get(key)
        if cached is not None:
            return cached
        pre = self.get_state_for_block(bytes(block.parent_root))
        pre = pre.copy()
        if block.slot > pre.state.slot:
            process_slots(pre, self.chain.types, block.slot)
        # safe to share across reader threads: EpochContext builds its
        # shufflings/proposer tables eagerly in load_state (cache.py), so
        # the cached state is immutable for readers — the lock only has to
        # make the get/evict/insert sequence atomic
        with self._slot_cache_lock:
            while len(self._block_slot_cache) >= 4:
                k = next(iter(self._block_slot_cache), None)
                if k is None:
                    break
                self._block_slot_cache.pop(k, None)
            self._block_slot_cache[key] = pre
        return pre

    def get_checkpoint_state(self, epoch: int, root: bytes):
        """Epoch-boundary state for (epoch, root) — the attestation-target
        state (reference getCheckpointState)."""
        hit = self.chain.checkpoint_state_cache.get(epoch, root)
        if hit is not None:
            return hit
        state = self.get_state_for_block(root).copy()
        boundary = st_util.compute_start_slot_at_epoch(
            epoch, self.chain.preset.SLOTS_PER_EPOCH
        )
        if state.state.slot < boundary:
            process_slots(state, self.chain.types, boundary)
        self.chain.checkpoint_state_cache.add(epoch, root, state)
        return state
