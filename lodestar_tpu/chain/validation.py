"""Gossip object validation (consensus p2p spec REJECT/IGNORE ladders).

Reference: `chain/validation/attestation.ts:15` (the full ladder for
`beacon_attestation_{subnet}`), `aggregateAndProof.ts`, `block.ts`.
Outcomes mirror gossipsub validation results: ACCEPT / IGNORE (don't
propagate, no penalty) / REJECT (penalize peer).

The signature check goes through the chain's pluggable verifier with
`batchable=True` semantics — on the TPU tier that means the attestation
joins the next batched device dispatch (reference: `{batchable: true}` at
attestation.ts:139).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

from ..state_transition import util as st_util
from ..state_transition.signature_sets import indexed_attestation_signature_set


class GossipAction(str, Enum):
    ACCEPT = "ACCEPT"
    IGNORE = "IGNORE"
    REJECT = "REJECT"


@dataclass
class ValidationResult:
    action: GossipAction
    reason: str = ""
    attesting_index: int | None = None
    data_root: bytes | None = None


def validate_gossip_attestation(
    chain, types, attestation, subnet: int | None
) -> ValidationResult:
    """The beacon_attestation_{subnet} ladder (attestation.ts ordering)."""
    p = chain.preset
    data = attestation.data

    # [REJECT] exactly one aggregation bit
    bits = list(attestation.aggregation_bits)
    if sum(1 for b in bits if b) != 1:
        return ValidationResult(GossipAction.REJECT, "not exactly one bit set")

    # [IGNORE] slot within ATTESTATION_PROPAGATION_SLOT_RANGE of clock
    clock_slot = chain.clock.current_slot
    if not (
        data.slot <= clock_slot
        and clock_slot <= data.slot + p.SLOTS_PER_EPOCH
    ):
        return ValidationResult(GossipAction.IGNORE, "slot out of propagation range")

    # [REJECT] target epoch consistency
    if data.target.epoch != st_util.compute_epoch_at_slot(
        data.slot, p.SLOTS_PER_EPOCH
    ):
        return ValidationResult(GossipAction.REJECT, "target epoch mismatch")

    # [IGNORE] unknown head block (may arrive later → reprocess queue)
    head_block_root = bytes(data.beacon_block_root)
    if not chain.fork_choice.has_block(head_block_root):
        return ValidationResult(GossipAction.IGNORE, "unknown beacon_block_root")

    # [REJECT] target must be an ancestor of the head block
    target_slot = st_util.compute_start_slot_at_epoch(
        data.target.epoch, p.SLOTS_PER_EPOCH
    )
    target_ancestor = chain.fork_choice.get_ancestor(head_block_root, target_slot)
    if target_ancestor != bytes(data.target.root):
        return ValidationResult(GossipAction.REJECT, "target not ancestor of head")

    # committee lookup via the target checkpoint state (shuffling cache)
    try:
        target_state = chain.regen.get_checkpoint_state(
            data.target.epoch, bytes(data.target.root)
        )
    except Exception:
        return ValidationResult(GossipAction.IGNORE, "target state unavailable")
    ctx = target_state.epoch_ctx

    # [REJECT] committee index in range
    if data.index >= ctx.get_committee_count_per_slot(data.target.epoch):
        return ValidationResult(GossipAction.REJECT, "committee index out of range")
    committee = ctx.get_beacon_committee(data.slot, data.index)
    if len(bits) != len(committee):
        return ValidationResult(GossipAction.REJECT, "wrong bits length")

    # [REJECT] correct subnet
    if subnet is not None:
        expected = compute_subnet_for_attestation(
            ctx, data.slot, data.index, p
        )
        if subnet != expected:
            return ValidationResult(GossipAction.REJECT, "wrong subnet")

    attester_index = int(committee[bits.index(True)])

    # [IGNORE] already seen for this target epoch
    if chain.seen_attesters.is_known(data.target.epoch, attester_index):
        return ValidationResult(GossipAction.IGNORE, "already seen")

    # [REJECT] signature (batchable path on the device tier)
    sig_set = indexed_attestation_signature_set(
        target_state,
        types.IndexedAttestation(
            attesting_indices=[attester_index],
            data=data.copy(),
            signature=bytes(attestation.signature),
        ),
    )
    if not chain.bls.verify_signature_sets([sig_set]):
        return ValidationResult(GossipAction.REJECT, "invalid signature")

    # re-check seen after the async verify (reference double-checks at
    # attestation.ts:144-155 — logical race handling)
    if chain.seen_attesters.is_known(data.target.epoch, attester_index):
        return ValidationResult(GossipAction.IGNORE, "seen during verification")
    chain.seen_attesters.add(data.target.epoch, attester_index)

    return ValidationResult(
        GossipAction.ACCEPT,
        attesting_index=attester_index,
        data_root=data.hash_tree_root(),
    )


def compute_subnet_for_attestation(ctx, slot: int, committee_index: int, p) -> int:
    """Spec compute_subnet_for_attestation (reference:
    epochContext.computeSubnetForSlot :545)."""
    from ..params import ATTESTATION_SUBNET_COUNT

    slots_since_epoch_start = slot % p.SLOTS_PER_EPOCH
    cps = ctx.get_committee_count_per_slot(
        st_util.compute_epoch_at_slot(slot, p.SLOTS_PER_EPOCH)
    )
    committees_since_epoch_start = cps * slots_since_epoch_start
    return (committees_since_epoch_start + committee_index) % ATTESTATION_SUBNET_COUNT


def validate_gossip_block(chain, types, signed_block) -> ValidationResult:
    """The beacon_block ladder (block.ts): slot/proposer/parent checks;
    full verification happens in the import pipeline."""
    block = signed_block.message
    clock_slot = chain.clock.current_slot

    # [IGNORE] future slot (beyond gossip clock disparity)
    if block.slot > clock_slot:
        return ValidationResult(GossipAction.IGNORE, "future slot")

    # [IGNORE] not newer than finalized
    fin_epoch = chain.finalized_checkpoint[0]
    fin_slot = st_util.compute_start_slot_at_epoch(
        fin_epoch, chain.preset.SLOTS_PER_EPOCH
    )
    if block.slot <= fin_slot:
        return ValidationResult(GossipAction.IGNORE, "not after finalized slot")

    # [IGNORE] already seen proposal for (slot, proposer)
    if chain.seen_block_proposers.is_known(block.slot, block.proposer_index):
        return ValidationResult(GossipAction.IGNORE, "duplicate proposal")

    # [IGNORE] parent unknown (trigger unknown-block sync)
    parent_root = bytes(block.parent_root)
    if not chain.fork_choice.has_block(parent_root):
        return ValidationResult(GossipAction.IGNORE, "unknown parent")

    # [REJECT] parent slot must be lower
    parent = chain.fork_choice.proto.get_node(parent_root)
    if parent is not None and parent.slot >= block.slot:
        return ValidationResult(GossipAction.REJECT, "parent slot not lower")

    # [REJECT] proposer signature
    from ..state_transition.signature_sets import block_proposer_signature_set

    try:
        head_state = chain.head_state
        sig_set = block_proposer_signature_set(head_state, signed_block)
        if not chain.bls.verify_signature_sets([sig_set]):
            return ValidationResult(GossipAction.REJECT, "invalid proposer signature")
    except Exception:
        return ValidationResult(GossipAction.IGNORE, "cannot build signature set")

    return ValidationResult(GossipAction.ACCEPT)
