"""Gossip object validation (consensus p2p spec REJECT/IGNORE ladders).

Reference: `chain/validation/attestation.ts:15` (the full ladder for
`beacon_attestation_{subnet}`), `aggregateAndProof.ts`, `block.ts`.
Outcomes mirror gossipsub validation results: ACCEPT / IGNORE (don't
propagate, no penalty) / REJECT (penalize peer).

The signature check goes through the chain's pluggable verifier with
`batchable=True` semantics — on the TPU tier that means the attestation
joins the next batched device dispatch (reference: `{batchable: true}` at
attestation.ts:139).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

from ..observability import spans as _spans
from ..state_transition import util as st_util
from ..state_transition.signature_sets import indexed_attestation_signature_set


class GossipAction(str, Enum):
    ACCEPT = "ACCEPT"
    IGNORE = "IGNORE"
    REJECT = "REJECT"


_LANE_SUPPORT: dict = {}


def _verify_lane(verifier, sets, lane: str) -> bool:
    """verify_signature_sets with the priority-lane hint where the facade
    accepts one (`BlsLaneDispatcher`); plain verifiers get the classic
    call. Detection mirrors `chain._verify_now`: from the signature,
    cached per underlying function — never by catching TypeError around
    the live call (which would swallow a genuine TypeError raised inside
    verification and re-run the batch). A `**kwargs` catch-all counts so
    thin forwarding wrappers still deliver the hint.

    A `BlsShedError` raised here propagates to the ladder's caller: every
    gossip ladder maps it to IGNORE (our own overload must not penalize
    the peer) and the handler's catch-all (`gossip/handlers._process`)
    already treats any escaped exception as IGNORE."""
    fn = verifier.verify_signature_sets
    key = getattr(fn, "__func__", fn)
    supports = _LANE_SUPPORT.get(key)
    if supports is None:
        import inspect

        try:
            params = inspect.signature(fn).parameters
            supports = "lane" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):
            supports = False
        _LANE_SUPPORT[key] = supports
    if supports:
        return verifier.verify_signature_sets(sets, lane=lane)
    return verifier.verify_signature_sets(sets)


@dataclass
class ValidationResult:
    action: GossipAction
    reason: str = ""
    attesting_index: int | None = None
    data_root: bytes | None = None
    # sync-committee messages: ALL positions the validator holds in the
    # subcommittee (committees sample with replacement — one validator can
    # own several bits)
    positions: list[int] | None = None


def validate_gossip_attestation(
    chain, types, attestation, subnet: int | None
) -> ValidationResult:
    """The beacon_attestation_{subnet} ladder (attestation.ts ordering)."""
    p = chain.preset
    data = attestation.data

    # [REJECT] exactly one aggregation bit
    bits = list(attestation.aggregation_bits)
    if sum(1 for b in bits if b) != 1:
        return ValidationResult(GossipAction.REJECT, "not exactly one bit set")

    # [IGNORE] slot within ATTESTATION_PROPAGATION_SLOT_RANGE of clock
    clock_slot = chain.clock.current_slot
    if not (
        data.slot <= clock_slot
        and clock_slot <= data.slot + p.SLOTS_PER_EPOCH
    ):
        return ValidationResult(GossipAction.IGNORE, "slot out of propagation range")

    # [REJECT] target epoch consistency
    if data.target.epoch != st_util.compute_epoch_at_slot(
        data.slot, p.SLOTS_PER_EPOCH
    ):
        return ValidationResult(GossipAction.REJECT, "target epoch mismatch")

    # [IGNORE] unknown head block (may arrive later → reprocess queue)
    head_block_root = bytes(data.beacon_block_root)
    if not chain.fork_choice.has_block(head_block_root):
        return ValidationResult(GossipAction.IGNORE, "unknown beacon_block_root")

    # [REJECT] target must be an ancestor of the head block
    target_slot = st_util.compute_start_slot_at_epoch(
        data.target.epoch, p.SLOTS_PER_EPOCH
    )
    target_ancestor = chain.fork_choice.get_ancestor(head_block_root, target_slot)
    if target_ancestor != bytes(data.target.root):
        return ValidationResult(GossipAction.REJECT, "target not ancestor of head")

    # committee lookup via the target checkpoint state (shuffling cache)
    try:
        target_state = chain.regen.get_checkpoint_state(
            data.target.epoch, bytes(data.target.root)
        )
    except Exception:
        return ValidationResult(GossipAction.IGNORE, "target state unavailable")
    ctx = target_state.epoch_ctx

    # [REJECT] committee index in range
    if data.index >= ctx.get_committee_count_per_slot(data.target.epoch):
        return ValidationResult(GossipAction.REJECT, "committee index out of range")
    committee = ctx.get_beacon_committee(data.slot, data.index)
    if len(bits) != len(committee):
        return ValidationResult(GossipAction.REJECT, "wrong bits length")

    # [REJECT] correct subnet
    if subnet is not None:
        expected = compute_subnet_for_attestation(
            ctx, data.slot, data.index, p
        )
        if subnet != expected:
            return ValidationResult(GossipAction.REJECT, "wrong subnet")

    attester_index = int(committee[bits.index(True)])

    # [IGNORE] already seen for this target epoch
    if chain.seen_attesters.is_known(data.target.epoch, attester_index):
        return ValidationResult(GossipAction.IGNORE, "already seen")

    # [REJECT] signature (batchable path on the device tier)
    sig_set = indexed_attestation_signature_set(
        target_state,
        types.IndexedAttestation(
            attesting_indices=[attester_index],
            data=data.copy(),
            signature=bytes(attestation.signature),
        ),
    )
    from .bls_verifier import BlsShedError

    try:
        with _spans.tracer.span(
            "validation/bls_verify", sets=1, slot=int(data.slot)
        ):
            sig_ok = _verify_lane(chain.bls, [sig_set], "attestation")
    except BlsShedError:
        # dispatcher admission control shed us under flood: IGNORE (no
        # peer penalty) — attestations are the first lane to shed
        return ValidationResult(GossipAction.IGNORE, "verifier overloaded (shed)")
    if not sig_ok:
        return ValidationResult(GossipAction.REJECT, "invalid signature")

    # re-check seen after the async verify (reference double-checks at
    # attestation.ts:144-155 — logical race handling)
    if chain.seen_attesters.is_known(data.target.epoch, attester_index):
        return ValidationResult(GossipAction.IGNORE, "seen during verification")
    chain.seen_attesters.add(data.target.epoch, attester_index)

    return ValidationResult(
        GossipAction.ACCEPT,
        attesting_index=attester_index,
        data_root=data.hash_tree_root(),
    )


def compute_subnet_for_attestation(ctx, slot: int, committee_index: int, p) -> int:
    """Spec compute_subnet_for_attestation (reference:
    epochContext.computeSubnetForSlot :545)."""
    from ..params import ATTESTATION_SUBNET_COUNT

    slots_since_epoch_start = slot % p.SLOTS_PER_EPOCH
    cps = ctx.get_committee_count_per_slot(
        st_util.compute_epoch_at_slot(slot, p.SLOTS_PER_EPOCH)
    )
    committees_since_epoch_start = cps * slots_since_epoch_start
    return (committees_since_epoch_start + committee_index) % ATTESTATION_SUBNET_COUNT


def validate_gossip_block(chain, types, signed_block) -> ValidationResult:
    """The beacon_block ladder (block.ts): slot/proposer/parent checks;
    full verification happens in the import pipeline."""
    block = signed_block.message
    clock_slot = chain.clock.current_slot

    # [IGNORE] future slot (beyond gossip clock disparity)
    if block.slot > clock_slot:
        return ValidationResult(GossipAction.IGNORE, "future slot")

    # [IGNORE] not newer than finalized
    fin_epoch = chain.finalized_checkpoint[0]
    fin_slot = st_util.compute_start_slot_at_epoch(
        fin_epoch, chain.preset.SLOTS_PER_EPOCH
    )
    if block.slot <= fin_slot:
        return ValidationResult(GossipAction.IGNORE, "not after finalized slot")

    # [IGNORE] already seen proposal for (slot, proposer)
    if chain.seen_block_proposers.is_known(block.slot, block.proposer_index):
        return ValidationResult(GossipAction.IGNORE, "duplicate proposal")

    # [IGNORE] parent unknown (trigger unknown-block sync)
    parent_root = bytes(block.parent_root)
    if not chain.fork_choice.has_block(parent_root):
        return ValidationResult(GossipAction.IGNORE, "unknown parent")

    # [REJECT] parent slot must be lower
    parent = chain.fork_choice.proto.get_node(parent_root)
    if parent is not None and parent.slot >= block.slot:
        return ValidationResult(GossipAction.REJECT, "parent slot not lower")

    # [REJECT] block descends from the finalized checkpoint (block.ts: the
    # current finalized block must be an ancestor of the new block)
    fin_root = chain.finalized_checkpoint[1]
    if fin_epoch > 0 and chain.fork_choice.get_ancestor(parent_root, fin_slot) != fin_root:
        return ValidationResult(
            GossipAction.REJECT, "not a descendant of finalized checkpoint"
        )

    # [REJECT] expected proposer + proposer signature, both against the
    # state at (parent_root, block.slot) — the head state may sit on a
    # different fork or epoch with a different shuffling (round-1 advisor
    # finding; reference block.ts verifies against getBlockSlotState)
    from ..state_transition.signature_sets import block_proposer_signature_set

    try:
        with _spans.tracer.span("validation/regen", slot=int(block.slot)):
            state = chain.regen.get_pre_state(block)
    except Exception:
        return ValidationResult(GossipAction.IGNORE, "cannot regen parent state")
    if state.epoch_ctx.get_beacon_proposer(block.slot) != int(block.proposer_index):
        return ValidationResult(GossipAction.REJECT, "wrong proposer")
    try:
        sig_set = block_proposer_signature_set(state, signed_block)
        # blocks are latency-critical (each gossip hop re-validates):
        # never sit out a batching facade's wait window
        from .chain import _verify_now

        with _spans.tracer.span("validation/bls_verify", sets=1):
            sig_ok = _verify_now(chain.bls, [sig_set])
        if not sig_ok:
            return ValidationResult(GossipAction.REJECT, "invalid proposer signature")
    except Exception:
        return ValidationResult(GossipAction.IGNORE, "cannot build signature set")

    # re-check the proposal dedup after the (possibly awaited) signature
    # verification — a concurrent duplicate must not be double-forwarded
    if chain.seen_block_proposers.is_known(block.slot, block.proposer_index):
        return ValidationResult(GossipAction.IGNORE, "duplicate proposal (post-verify)")

    return ValidationResult(GossipAction.ACCEPT)


def validate_gossip_aggregate_and_proof(chain, types, signed_agg) -> ValidationResult:
    """The beacon_aggregate_and_proof ladder (reference
    `chain/validation/aggregateAndProof.ts`): aggregator membership +
    selection proof + aggregate signature, all via the batch verifier."""
    from ..config.beacon_config import compute_signing_root
    from ..params import DOMAIN_AGGREGATE_AND_PROOF, DOMAIN_SELECTION_PROOF
    from ..ssz.hashing import sha256
    from ..state_transition.signature_sets import _pk

    p = chain.preset
    agg = signed_agg.message
    attestation = agg.aggregate
    data = attestation.data

    # [IGNORE] propagation slot range
    clock_slot = chain.clock.current_slot
    if not (data.slot <= clock_slot <= data.slot + p.SLOTS_PER_EPOCH):
        return ValidationResult(GossipAction.IGNORE, "slot out of propagation range")

    # [REJECT] has participants
    bits = list(attestation.aggregation_bits)
    if not any(bits):
        return ValidationResult(GossipAction.REJECT, "empty aggregation bits")

    # [REJECT] target epoch consistency (spec: target.epoch must match the
    # epoch of data.slot)
    if int(data.target.epoch) != st_util.compute_epoch_at_slot(
        int(data.slot), p.SLOTS_PER_EPOCH
    ):
        return ValidationResult(GossipAction.REJECT, "target epoch mismatch")

    # [IGNORE] duplicate (aggregator, target) / non-strict superset check
    target_epoch = int(data.target.epoch)
    if chain.seen_aggregators.is_known(target_epoch, int(agg.aggregator_index)):
        return ValidationResult(GossipAction.IGNORE, "aggregator already seen")
    data_root = data.hash_tree_root()
    if chain.seen_aggregated.is_known_superset(data_root, bits):
        return ValidationResult(GossipAction.IGNORE, "aggregate already covered")

    # [IGNORE] unknown head block
    head_block_root = bytes(data.beacon_block_root)
    if not chain.fork_choice.has_block(head_block_root):
        return ValidationResult(GossipAction.IGNORE, "unknown beacon_block_root")

    try:
        target_state = chain.regen.get_checkpoint_state(
            target_epoch, bytes(data.target.root)
        )
    except Exception:
        return ValidationResult(GossipAction.IGNORE, "target state unavailable")
    ctx = target_state.epoch_ctx

    # [REJECT] committee index + bits length
    if data.index >= ctx.get_committee_count_per_slot(target_epoch):
        return ValidationResult(GossipAction.REJECT, "committee index out of range")
    committee = ctx.get_beacon_committee(data.slot, data.index)
    if len(bits) != len(committee):
        return ValidationResult(GossipAction.REJECT, "wrong bits length")

    # [REJECT] aggregator is a committee member
    aggregator_index = int(agg.aggregator_index)
    if aggregator_index not in [int(i) for i in committee]:
        return ValidationResult(GossipAction.REJECT, "aggregator not in committee")

    # [REJECT] selection proof selects this validator as aggregator
    # (spec is_aggregator: hash(proof) mod max(1, len//TARGET) == 0)
    modulo = max(1, len(committee) // 16)  # TARGET_AGGREGATORS_PER_COMMITTEE=16
    if int.from_bytes(sha256(bytes(agg.selection_proof))[:8], "little") % modulo != 0:
        return ValidationResult(GossipAction.REJECT, "not selected as aggregator")

    # [REJECT] three signatures, one batch: selection proof, aggregate-and-
    # proof envelope, and the aggregate attestation itself
    from ..state_transition.signature_sets import attestation_signature_set
    from ..bls import api as bls

    sel_domain = target_state.config.get_domain(DOMAIN_SELECTION_PROOF, data.slot)
    slot_bytes = int(data.slot).to_bytes(8, "little") + b"\x00" * 24
    from ..ssz.hashing import merkleize_chunks

    slot_root = merkleize_chunks([slot_bytes], 1)
    sel_set = bls.SignatureSet(
        pubkey=_pk(target_state, aggregator_index),
        message=compute_signing_root(slot_root, sel_domain),
        signature=bytes(agg.selection_proof),
    )
    env_domain = target_state.config.get_domain(DOMAIN_AGGREGATE_AND_PROOF, data.slot)
    env_set = bls.SignatureSet(
        pubkey=_pk(target_state, aggregator_index),
        message=compute_signing_root(agg.hash_tree_root(), env_domain),
        signature=bytes(signed_agg.signature),
    )
    att_set = attestation_signature_set(target_state, types, attestation)
    from .bls_verifier import BlsShedError

    try:
        with _spans.tracer.span(
            "validation/bls_verify", sets=3, slot=int(data.slot)
        ):
            sigs_ok = _verify_lane(
                chain.bls, [sel_set, env_set, att_set], "aggregate"
            )
    except BlsShedError:
        return ValidationResult(GossipAction.IGNORE, "verifier overloaded (shed)")
    if not sigs_ok:
        return ValidationResult(GossipAction.REJECT, "invalid signatures")

    # re-check after the (batched, possibly awaited) verification so a
    # concurrent duplicate is not double-forwarded (reference
    # aggregateAndProof.ts post-verify re-check; round-1 advisor finding)
    if chain.seen_aggregators.is_known(target_epoch, aggregator_index):
        return ValidationResult(GossipAction.IGNORE, "aggregator seen (post-verify)")
    chain.seen_aggregators.add(target_epoch, aggregator_index)
    chain.seen_aggregated.add(target_epoch, data_root, bits)
    return ValidationResult(GossipAction.ACCEPT, data_root=data_root)


def validate_gossip_voluntary_exit(chain, types, signed_exit) -> ValidationResult:
    """Reference `chain/validation/voluntaryExit.ts`: first-seen per
    validator, then full state validity incl. signature."""
    from ..state_transition.signature_sets import voluntary_exit_signature_set

    index = int(signed_exit.message.validator_index)
    if index in chain.op_pool.voluntary_exits:
        return ValidationResult(GossipAction.IGNORE, "exit already known")
    head = chain.head_state
    if index >= len(head.flat.pubkeys):
        return ValidationResult(GossipAction.REJECT, "unknown validator")
    v = head.state.validators[index]
    cur_epoch = head.epoch_ctx.current_epoch
    from ..params.presets import FAR_FUTURE_EPOCH

    if int(v.exit_epoch) != FAR_FUTURE_EPOCH:
        return ValidationResult(GossipAction.REJECT, "already exiting")
    if not (int(v.activation_epoch) <= cur_epoch):
        return ValidationResult(GossipAction.REJECT, "not active")
    if cur_epoch < int(signed_exit.message.epoch):
        return ValidationResult(GossipAction.REJECT, "exit epoch in future")
    if cur_epoch < int(v.activation_epoch) + chain.config.chain.SHARD_COMMITTEE_PERIOD:
        return ValidationResult(GossipAction.REJECT, "validator too young")
    from .bls_verifier import BlsShedError

    try:
        if not _verify_lane(
            chain.bls, [voluntary_exit_signature_set(head, signed_exit)],
            "aggregate",
        ):
            return ValidationResult(GossipAction.REJECT, "invalid signature")
    except BlsShedError:
        return ValidationResult(GossipAction.IGNORE, "verifier overloaded (shed)")
    return ValidationResult(GossipAction.ACCEPT)


def validate_gossip_proposer_slashing(chain, types, slashing) -> ValidationResult:
    """Reference `chain/validation/proposerSlashing.ts`."""
    from ..state_transition.signature_sets import proposer_slashing_signature_sets

    index = int(slashing.signed_header_1.message.proposer_index)
    if index in chain.op_pool.proposer_slashings:
        return ValidationResult(GossipAction.IGNORE, "slashing already known")
    h1, h2 = slashing.signed_header_1.message, slashing.signed_header_2.message
    if int(h1.slot) != int(h2.slot) or int(h1.proposer_index) != int(h2.proposer_index):
        return ValidationResult(GossipAction.REJECT, "headers not slashable")
    if h1.hash_tree_root() == h2.hash_tree_root():
        return ValidationResult(GossipAction.REJECT, "identical headers")
    head = chain.head_state
    if index >= len(head.flat.pubkeys):
        return ValidationResult(GossipAction.REJECT, "unknown proposer")
    v = head.state.validators[index]
    if bool(v.slashed):
        return ValidationResult(GossipAction.IGNORE, "already slashed")
    from .bls_verifier import BlsShedError

    try:
        if not _verify_lane(
            chain.bls, proposer_slashing_signature_sets(head, slashing),
            "aggregate",
        ):
            return ValidationResult(GossipAction.REJECT, "invalid signature")
    except BlsShedError:
        return ValidationResult(GossipAction.IGNORE, "verifier overloaded (shed)")
    return ValidationResult(GossipAction.ACCEPT)


def validate_gossip_attester_slashing(chain, types, slashing) -> ValidationResult:
    """Reference `chain/validation/attesterSlashing.ts`."""
    from ..state_transition.block import is_slashable_attestation_data
    from ..state_transition.signature_sets import attester_slashing_signature_sets

    a1, a2 = slashing.attestation_1, slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        return ValidationResult(GossipAction.REJECT, "not slashable")
    ind1 = {int(i) for i in a1.attesting_indices}
    ind2 = {int(i) for i in a2.attesting_indices}
    head = chain.head_state
    slashable = {
        i
        for i in ind1 & ind2
        if i < len(head.flat.pubkeys) and not bool(head.state.validators[i].slashed)
    }
    if not slashable:
        return ValidationResult(GossipAction.IGNORE, "no new slashable indices")
    from .bls_verifier import BlsShedError

    try:
        if not _verify_lane(
            chain.bls, attester_slashing_signature_sets(head, slashing),
            "aggregate",
        ):
            return ValidationResult(GossipAction.REJECT, "invalid signature")
    except BlsShedError:
        return ValidationResult(GossipAction.IGNORE, "verifier overloaded (shed)")
    return ValidationResult(GossipAction.ACCEPT)


# --- sync-committee topic ladders -------------------------------------------
#
# Reference: chain/validation/syncCommittee.ts (message ladder) and
# syncCommitteeContributionAndProof.ts (contribution ladder). Both route
# their signature sets through the chain's batchable verifier like
# attestations.

def _sync_subcommittee(chain, subcommittee_index: int) -> tuple[list[int], list[bytes]]:
    """(validator indices, pubkeys) of the given subcommittee slice of the
    CURRENT sync committee, cached per sync period (the committee only
    rotates every EPOCHS_PER_SYNC_COMMITTEE_PERIOD epochs — reference
    caches an indexed committee on the epoch context,
    epochCtx.getIndexedSyncCommittee)."""
    cached = chain.head_state
    p = chain.preset
    period = cached.epoch_ctx.current_epoch // p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    cache = getattr(chain, "_sync_subcommittee_cache", None)
    if cache is None:
        cache = chain._sync_subcommittee_cache = {}
    hit = cache.get((period, subcommittee_index))
    if hit is not None:
        return hit
    state = cached.state
    size = p.SYNC_COMMITTEE_SUBNET_SIZE
    start = subcommittee_index * size
    pk_to_idx = cached.epoch_ctx.pubkey_to_index
    pubkeys = [
        bytes(pk)
        for pk in list(state.current_sync_committee.pubkeys)[start : start + size]
    ]
    members = [pk_to_idx.get(pk, -1) for pk in pubkeys]
    if len(cache) > 16:
        # evict stale periods only — the current period's entries stay hot
        for k in [k for k in cache if k[0] != period]:
            del cache[k]
    cache[(period, subcommittee_index)] = (members, pubkeys)
    return members, pubkeys


def _sync_subcommittee_members(chain, subcommittee_index: int) -> list[int]:
    return _sync_subcommittee(chain, subcommittee_index)[0]


def is_sync_committee_aggregator(selection_proof: bytes, p) -> bool:
    """spec is_sync_committee_aggregator: hash(proof)[:8] little-endian mod
    max(1, subcommittee_size // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE)."""
    from ..params import TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE
    from ..ssz.hashing import sha256

    modulo = max(
        1, p.SYNC_COMMITTEE_SUBNET_SIZE // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE
    )
    return int.from_bytes(sha256(bytes(selection_proof))[:8], "little") % modulo == 0


def validate_gossip_sync_committee(
    chain, types, msg, subnet: int
) -> ValidationResult:
    """The sync_committee_{subnet} ladder (syncCommittee.ts ordering)."""
    from ..params import SYNC_COMMITTEE_SUBNET_COUNT
    from ..state_transition.signature_sets import sync_committee_message_signature_set

    # [IGNORE] message slot is the current slot (gossip clock disparity)
    if not chain.clock.is_current_slot_given_disparity(msg.slot):
        return ValidationResult(GossipAction.IGNORE, "not current slot")

    # [REJECT] subnet id in range
    if subnet >= SYNC_COMMITTEE_SUBNET_COUNT:
        return ValidationResult(GossipAction.REJECT, "invalid subcommittee index")

    # [REJECT] the validator belongs to the declared subcommittee
    members = _sync_subcommittee_members(chain, subnet)
    if int(msg.validator_index) not in members:
        return ValidationResult(
            GossipAction.REJECT, "validator not in sync subcommittee"
        )

    # [IGNORE] first message for (slot, subnet, validator)
    if chain.seen_sync_committee.is_known(
        int(msg.slot), subnet, int(msg.validator_index)
    ):
        return ValidationResult(GossipAction.IGNORE, "already seen")

    # [REJECT] signature over beacon_block_root
    from .bls_verifier import BlsShedError

    sig_set = sync_committee_message_signature_set(chain.head_state, msg)
    try:
        if not _verify_lane(chain.bls, [sig_set], "sync_committee"):
            return ValidationResult(GossipAction.REJECT, "invalid signature")
    except BlsShedError:
        return ValidationResult(GossipAction.IGNORE, "verifier overloaded (shed)")

    # re-check the seen cache after the (possibly batched/awaited)
    # signature verification, as attestation validation does
    if chain.seen_sync_committee.is_known(
        int(msg.slot), subnet, int(msg.validator_index)
    ):
        return ValidationResult(GossipAction.IGNORE, "already seen (post-verify)")
    chain.seen_sync_committee.add(int(msg.slot), subnet, int(msg.validator_index))
    # committees sample with replacement: report EVERY position this
    # validator holds in the subcommittee — the pool must set all its
    # bits from this one (first-seen-deduped) message
    positions = [i for i, v in enumerate(members) if v == int(msg.validator_index)]
    return ValidationResult(
        GossipAction.ACCEPT,
        attesting_index=positions[0],
        positions=positions,
    )


def validate_gossip_sync_contribution_and_proof(
    chain, types, signed
) -> ValidationResult:
    """The sync_committee_contribution_and_proof ladder
    (syncCommitteeContributionAndProof.ts ordering)."""
    from ..params import SYNC_COMMITTEE_SUBNET_COUNT
    from ..state_transition.signature_sets import (
        contribution_and_proof_signature_set,
        sync_contribution_signature_set,
        sync_selection_proof_signature_set,
    )

    cap = signed.message
    contribution = cap.contribution
    slot = int(contribution.slot)
    subcommittee = int(contribution.subcommittee_index)
    aggregator = int(cap.aggregator_index)

    # [IGNORE] contribution slot is the current slot
    if not chain.clock.is_current_slot_given_disparity(slot):
        return ValidationResult(GossipAction.IGNORE, "not current slot")

    # [REJECT] subcommittee index in range
    if subcommittee >= SYNC_COMMITTEE_SUBNET_COUNT:
        return ValidationResult(GossipAction.REJECT, "invalid subcommittee index")

    # [REJECT] aggregator is a member of the declared subcommittee
    members, subcommittee_pubkeys = _sync_subcommittee(chain, subcommittee)
    if aggregator not in members:
        return ValidationResult(
            GossipAction.REJECT, "aggregator not in sync subcommittee"
        )

    # [IGNORE] participants are a non-strict subset of an already-seen one
    if chain.seen_contribution_and_proof.participants_known(contribution):
        return ValidationResult(GossipAction.IGNORE, "participants already known")

    # [IGNORE] first contribution from this aggregator for (slot, subcommittee)
    if chain.seen_contribution_and_proof.is_aggregator_known(
        slot, subcommittee, aggregator
    ):
        return ValidationResult(GossipAction.IGNORE, "aggregator already seen")

    # [REJECT] the contribution has participants
    bits = list(contribution.aggregation_bits)
    participant_pubkeys = [pk for pk, b in zip(subcommittee_pubkeys, bits) if b]
    if not participant_pubkeys:
        return ValidationResult(GossipAction.REJECT, "no participants")

    # [REJECT] selection proof selects the aggregator
    if not is_sync_committee_aggregator(cap.selection_proof, chain.preset):
        return ValidationResult(GossipAction.REJECT, "not an aggregator")

    # [REJECT] all three signatures, batched through the verifier:
    # selection proof, contribution-and-proof envelope, and the aggregate
    cached = chain.head_state
    sets = [
        sync_selection_proof_signature_set(cached, types, cap),
        contribution_and_proof_signature_set(cached, signed),
        sync_contribution_signature_set(cached, contribution, participant_pubkeys),
    ]
    from .bls_verifier import BlsShedError

    try:
        if not _verify_lane(chain.bls, sets, "sync_committee"):
            return ValidationResult(GossipAction.REJECT, "invalid signature")
    except BlsShedError:
        return ValidationResult(GossipAction.IGNORE, "verifier overloaded (shed)")

    if chain.seen_contribution_and_proof.is_aggregator_known(
        slot, subcommittee, aggregator
    ):
        return ValidationResult(GossipAction.IGNORE, "aggregator seen (post-verify)")
    chain.seen_contribution_and_proof.add(cap)
    return ValidationResult(GossipAction.ACCEPT)
