"""Operation mempools.

Reference: `chain/opPools/` — `AttestationPool` (unaggregated, per-slot,
aggregates on insert), `AggregatedAttestationPool` (block packing via
greedy not-yet-seen coverage, `aggregatedAttestationPool.ts:108`),
`OpPool` (slashings + exits)."""

from __future__ import annotations

from ..bls import api as bls


class AttestationPool:
    """Unaggregated gossip attestations, aggregated on insert per
    (slot, data_root). Signature aggregation is G2 point addition (cheap,
    host); retained for SLOTS_RETAINED slots."""

    SLOTS_RETAINED = 3

    def __init__(self):
        # slot → data_root → (data, bits list[bool], agg signature point)
        self._by_slot: dict[int, dict[bytes, tuple[object, list[bool], object]]] = {}

    def add(self, attestation, data_root: bytes) -> str:
        slot = attestation.data.slot
        by_root = self._by_slot.setdefault(slot, {})
        bits = list(attestation.aggregation_bits)
        sig = bls.Signature.from_bytes(bytes(attestation.signature), validate=False)
        entry = by_root.get(data_root)
        if entry is None:
            by_root[data_root] = (attestation.data.copy(), bits, sig.point)
            return "added"
        data, agg_bits, agg_sig = entry
        new_bits = [b for b in bits]
        if all(ab or not nb for ab, nb in zip(agg_bits, new_bits)):
            return "already_known"
        merged = [a or b for a, b in zip(agg_bits, new_bits)]
        by_root[data_root] = (data, merged, agg_sig + sig.point)
        return "aggregated"

    def get_aggregate(self, slot: int, data_root: bytes):
        entry = self._by_slot.get(slot, {}).get(data_root)
        if entry is None:
            return None
        data, bits, sig_point = entry
        return data, bits, bls.Signature(sig_point)

    def prune(self, clock_slot: int) -> None:
        self._by_slot = {
            s: v
            for s, v in self._by_slot.items()
            if s >= clock_slot - self.SLOTS_RETAINED
        }


class AggregatedAttestationPool:
    """Aggregates (from gossip aggregate-and-proof or local aggregation)
    grouped by (target epoch, data root); `get_attestations_for_block`
    packs greedily by fresh-coverage count (reference
    getAttestationsForBlock)."""

    EPOCHS_RETAINED = 2

    def __init__(self):
        # data_root → (data, list[(bits, signature_bytes)])
        self._by_root: dict[bytes, tuple[object, list[tuple[list[bool], bytes]]]] = {}
        self._epoch_of_root: dict[bytes, int] = {}

    def add(self, attestation, data_root: bytes) -> None:
        """Insert, merging into an existing variant when bit-disjoint
        (reference aggregateInto: OR the bits, aggregate the signatures) —
        partial aggregates from different nodes combine into full ones."""
        from ..bls import api as bls

        bits = list(attestation.aggregation_bits)
        sig = bytes(attestation.signature)
        data, variants = self._by_root.setdefault(
            data_root, (attestation.data.copy(), [])
        )
        self._epoch_of_root[data_root] = attestation.data.target.epoch
        for i, (vbits, vsig) in enumerate(variants):
            if len(vbits) != len(bits):
                continue
            if all(v or not b for v, b in zip(vbits, bits)):
                return  # non-strict subset of an existing variant: redundant
            if not any(v and b for v, b in zip(vbits, bits)):
                merged_sig = bls.aggregate_signatures(
                    [
                        bls.Signature.from_bytes(vsig, validate=False),
                        bls.Signature.from_bytes(sig, validate=False),
                    ]
                ).to_bytes()
                variants[i] = (
                    [v or b for v, b in zip(vbits, bits)],
                    merged_sig,
                )
                return
        variants.append((bits, sig))

    def get_attestations_for_block(self, types, cached, max_attestations: int):
        """Pick the best variant per data root, preferring recent slots and
        maximal coverage; validity-filter against the block's state."""
        state = cached.state
        p = cached.preset
        candidates = []
        for data_root, (data, variants) in self._by_root.items():
            if not (
                data.slot + p.MIN_ATTESTATION_INCLUSION_DELAY
                <= state.slot
                <= data.slot + p.SLOTS_PER_EPOCH
            ):
                continue
            epoch = data.target.epoch
            if epoch == cached.current_epoch:
                if data.source != state.current_justified_checkpoint:
                    continue
            elif epoch == cached.previous_epoch:
                if data.source != state.previous_justified_checkpoint:
                    continue
            else:
                continue
            best = max(variants, key=lambda v: sum(v[0]))
            candidates.append((sum(best[0]), data.slot, data, best))
        candidates.sort(key=lambda c: (-c[1], -c[0]))  # recent slots, most bits
        out = []
        for _, _, data, (bits, sig) in candidates[:max_attestations]:
            out.append(
                types.Attestation(
                    aggregation_bits=bits, data=data.copy(), signature=sig
                )
            )
        return out

    def prune(self, current_epoch: int) -> None:
        stale = [
            r
            for r, e in self._epoch_of_root.items()
            if e + self.EPOCHS_RETAINED < current_epoch
        ]
        for r in stale:
            self._by_root.pop(r, None)
            self._epoch_of_root.pop(r, None)


class OpPool:
    """Slashings, exits — persisted ops awaiting block inclusion
    (reference opPool.ts; per-validator dedup)."""

    def __init__(self):
        self.proposer_slashings: dict[int, object] = {}
        self.attester_slashings: list[object] = []
        self.voluntary_exits: dict[int, object] = {}

    def add_proposer_slashing(self, slashing) -> None:
        self.proposer_slashings[slashing.signed_header_1.message.proposer_index] = (
            slashing
        )

    def add_attester_slashing(self, slashing) -> None:
        self.attester_slashings.append(slashing)

    def add_voluntary_exit(self, signed_exit) -> None:
        self.voluntary_exits[signed_exit.message.validator_index] = signed_exit

    def get_slashings_and_exits(self, cached, preset):
        from ..state_transition.block import is_slashable_validator

        proposer = [
            s
            for idx, s in self.proposer_slashings.items()
            if is_slashable_validator(cached.flat, idx, cached.current_epoch)
        ][: preset.MAX_PROPOSER_SLASHINGS]
        attester = self.attester_slashings[: preset.MAX_ATTESTER_SLASHINGS]
        exits = [
            e
            for idx, e in self.voluntary_exits.items()
            if int(cached.flat.exit_epoch[idx]) == 2**64 - 1
        ][: preset.MAX_VOLUNTARY_EXITS]
        return proposer, attester, exits

    def prune(self, cached) -> None:
        self.proposer_slashings = {
            i: s
            for i, s in self.proposer_slashings.items()
            if not bool(cached.flat.slashed[i])
        }
        self.voluntary_exits = {
            i: e
            for i, e in self.voluntary_exits.items()
            if int(cached.flat.exit_epoch[i]) == 2**64 - 1
        }


class SyncCommitteeMessagePool:
    """Per-subnet aggregation of individual sync-committee messages into
    contributions (reference syncCommitteeMessagePool.ts: bits + aggregated
    signature per (slot, block_root, subcommittee))."""

    SLOTS_RETAINED = 3

    def __init__(self, preset):
        self.preset = preset
        from ..params import SYNC_COMMITTEE_SUBNET_COUNT

        self.subnet_size = preset.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        # (slot, root, subcommittee) → (bits list, list[signature bytes])
        self._store: dict[tuple[int, bytes, int], tuple[list[bool], list[bytes]]] = {}

    def add(self, message, subcommittee_index: int, position_in_subcommittee: int):
        key = (message.slot, bytes(message.beacon_block_root), subcommittee_index)
        bits, sigs = self._store.setdefault(
            key, ([False] * self.subnet_size, [])
        )
        if bits[position_in_subcommittee]:
            return  # duplicate participant
        bits[position_in_subcommittee] = True
        sigs.append(bytes(message.signature))

    def get_contribution(self, types, slot: int, block_root: bytes, subcommittee: int):
        from ..bls import api as bls

        entry = self._store.get((slot, bytes(block_root), subcommittee))
        if entry is None:
            return None
        bits, sigs = entry
        agg = bls.aggregate_signatures(
            [bls.Signature.from_bytes(s, validate=False) for s in sigs]
        )
        return types.SyncCommitteeContribution(
            slot=slot,
            beacon_block_root=bytes(block_root),
            subcommittee_index=subcommittee,
            aggregation_bits=list(bits),
            signature=agg.to_bytes(),
        )

    def prune(self, clock_slot: int) -> None:
        self._store = {
            k: v for k, v in self._store.items() if k[0] + self.SLOTS_RETAINED >= clock_slot
        }


class SyncContributionAndProofPool:
    """Best contribution per (slot, root, subcommittee), merged into the
    block's SyncAggregate (reference syncContributionAndProofPool.ts
    `getAggregate`)."""

    SLOTS_RETAINED = 3

    def __init__(self, preset):
        self.preset = preset
        from ..params import SYNC_COMMITTEE_SUBNET_COUNT

        self.subnet_count = SYNC_COMMITTEE_SUBNET_COUNT
        self.subnet_size = preset.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        # (slot, root, subcommittee) → best contribution (most bits)
        self._best: dict[tuple[int, bytes, int], object] = {}

    def add(self, contribution) -> None:
        key = (
            contribution.slot,
            bytes(contribution.beacon_block_root),
            contribution.subcommittee_index,
        )
        existing = self._best.get(key)
        if existing is None or sum(contribution.aggregation_bits) > sum(
            existing.aggregation_bits
        ):
            self._best[key] = contribution.copy()

    def get_sync_aggregate(self, types, slot: int, block_root: bytes):
        """SyncAggregate for a block at `slot` signing `block_root` (the
        parent). Empty participation → infinity signature, per spec."""
        from ..bls import api as bls

        bits = [False] * self.preset.SYNC_COMMITTEE_SIZE
        sigs = []
        for sub in range(self.subnet_count):
            contrib = self._best.get((slot, bytes(block_root), sub))
            if contrib is None:
                continue
            for i, b in enumerate(contrib.aggregation_bits):
                if b:
                    bits[sub * self.subnet_size + i] = True
            sigs.append(
                bls.Signature.from_bytes(bytes(contrib.signature), validate=False)
            )
        if not sigs:
            return types.SyncAggregate(
                sync_committee_bits=bits,
                sync_committee_signature=b"\xc0" + b"\x00" * 95,
            )
        return types.SyncAggregate(
            sync_committee_bits=bits,
            sync_committee_signature=bls.aggregate_signatures(sigs).to_bytes(),
        )

    def prune(self, clock_slot: int) -> None:
        self._best = {
            k: v for k, v in self._best.items() if k[0] + self.SLOTS_RETAINED >= clock_slot
        }


class BlsToExecutionChangePool:
    """Pending capella credential changes, deduped per validator
    (reference opPool bls_to_execution_changes handling)."""

    def __init__(self):
        self._by_validator: dict[int, object] = {}

    def add(self, signed_change) -> None:
        self._by_validator.setdefault(
            signed_change.message.validator_index, signed_change
        )

    def get_for_block(self, cached, preset) -> list:
        from ..params import BLS_WITHDRAWAL_PREFIX

        out = []
        for idx, change in self._by_validator.items():
            if idx >= len(cached.state.validators):
                continue
            wc = bytes(cached.state.validators[idx].withdrawal_credentials)
            if wc[:1] == BLS_WITHDRAWAL_PREFIX:
                out.append(change)
            if len(out) == preset.MAX_BLS_TO_EXECUTION_CHANGES:
                break
        return out

    def prune(self, cached) -> None:
        from ..params import BLS_WITHDRAWAL_PREFIX

        self._by_validator = {
            i: c
            for i, c in self._by_validator.items()
            if i < len(cached.state.validators)
            and bytes(cached.state.validators[i].withdrawal_credentials)[:1]
            == BLS_WITHDRAWAL_PREFIX
        }
