"""Chain event bus.

Reference: `chain/emitter.ts` (`ChainEventEmitter`) — typed events fired
at block import/head update/finalization, consumed by the REST event
stream (`api/.../events.ts`), the notifier, and sim liveness trackers.

Thread-safe: emissions come from whichever thread imports blocks (event
loop, range-sync executor, REST), subscribers may be SSE streamer queues
on other threads.
"""

from __future__ import annotations

import threading
from enum import Enum

from ..utils.logger import get_logger

log = get_logger("chain-emitter")


class ChainEvent(str, Enum):
    # reference eventstream topic names (routes/events.ts)
    head = "head"
    block = "block"
    attestation = "attestation"
    finalized_checkpoint = "finalized_checkpoint"
    chain_reorg = "chain_reorg"
    lightclient_optimistic_update = "light_client_optimistic_update"
    lightclient_finality_update = "light_client_finality_update"


class ChainEventEmitter:
    def __init__(self):
        self._subs: dict[ChainEvent, list] = {}
        self._lock = threading.Lock()

    def on(self, event: ChainEvent, callback) -> None:
        with self._lock:
            self._subs.setdefault(event, []).append(callback)

    def off(self, event: ChainEvent, callback) -> None:
        with self._lock:
            subs = self._subs.get(event, [])
            if callback in subs:
                subs.remove(callback)

    def emit(self, event: ChainEvent, payload: dict) -> None:
        with self._lock:
            subs = list(self._subs.get(event, ()))
        for cb in subs:
            try:
                cb(event, payload)
            except Exception:
                # a bad subscriber must not break block import
                log.warning("subscriber failed for %s", event, exc_info=True)
