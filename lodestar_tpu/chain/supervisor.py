"""Fault-tolerant supervision of the device BLS tier.

The north star keeps the CPU (native blst-equivalent) path as "fallback
and oracle" — but until round 7 it was only an oracle in tests: any
device-side exception made the batching facades resolve every waiter as
False, so a TPU OOM / preemption / wedged cold compile silently rejected
valid blocks and attestations (the missed-slots failure mode ADVICE r5
warned about for cold kernels). `SupervisedBlsVerifier` owns the failure
policy between the facades and `DeviceBlsVerifier`:

- **Per-dispatch deadline** — every device call runs on a disposable
  watchdog-bounded worker thread (`LODESTAR_TPU_DEVICE_DEADLINE`
  seconds, default 120, `0` disables). A blown deadline abandons the
  wedged worker (it parks as a daemon until the call ever returns) and
  falls back; the next dispatch gets a fresh worker, so one stuck XLA
  compile cannot serialize the pipeline forever.
- **One jittered-backoff retry** for raised device errors (transient
  XLA shapes: RESOURCE_EXHAUSTED, preemption, backend resets) via
  `utils/retry.RetryPolicy`. Deadline blowouts are NOT retried — a
  wedged kernel just burns a second deadline.
- **CPU-oracle fallback** — when the device tier fails, waiters receive
  *correct oracle verdicts* from `CpuBlsVerifier` instead of blanket
  False. Only when BOTH tiers fail does the caller see False, counted
  and logged as `both_tiers_failed`.
- **Negative-verdict audit** — a device-reported False rejects a block
  (the costly direction), and BLS soundness is asymmetric: random
  hardware corruption yields a pairing product that is NOT the identity
  (a spurious False) but cannot forge the unique identity element (a
  spurious True). So device-False verdicts are re-checked on the CPU
  oracle; an overturned verdict counts as a device failure and feeds
  the breaker. All-valid steady state pays zero CPU work.
- **Mesh chip eviction** (round 7) — when the device tier serves from a
  chip mesh (`parallel/mesh.BlsMeshDispatcher`), a sick chip is treated
  like a sick device in miniature: the failed dispatch evicts the
  suspect chip (attributed via the exception's `chip` field when
  available), the call retries immediately on the surviving mesh, and
  serving continues — no breaker trip, no CPU fallback, a 4-chip node
  degrades to a 3-chip one visibly (`lodestar_bls_mesh_*` gauges). The
  canary thread keeps probing while chips are evicted and re-admits the
  full census once a probe passes.
- **Circuit breaker** — N consecutive device failures
  (`LODESTAR_TPU_BREAKER_THRESHOLD`, default 3) open the breaker:
  traffic routes straight to the CPU tier with no per-call deadline
  churn. A background canary thread probes a small known-valid batch
  every `LODESTAR_TPU_BREAKER_COOLDOWN` seconds (default 30): the probe
  moves the breaker half-open, a passing probe re-closes it, a failing
  one re-opens. Production traffic never rides the half-open state —
  only the canary risks the device.

Observability: breaker-state gauge + transition counter,
retry/fallback/deadline/canary/mismatch counters (all on
`observability.stages.PipelineMetrics`, i.e. `/metrics`), spans inside
an active lifecycle trace, rate-limited logs, and the metrics server's
`/debug/breaker` endpoint (wired by `node/node.py` to
`breaker_snapshot`). The whole state machine is drivable by
`lodestar_tpu.testing.faults` — see docs/robustness.md for the chaos
drill runbook.
"""

from __future__ import annotations

import queue
import threading
import time

from ..utils.env import env_bool, env_float
from ..utils.logger import RateLimitedLogger, get_logger
from ..utils.retry import RetryPolicy
from .bls_verifier import CpuBlsVerifier

BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
BREAKER_STATE_VALUES = {
    BREAKER_CLOSED: 0,
    BREAKER_HALF_OPEN: 1,
    BREAKER_OPEN: 2,
}

class DeviceDeadlineExceeded(RuntimeError):
    """A device dispatch outlived its watchdog deadline."""




class _DeadlineDispatcher:
    """Run callables on a disposable daemon worker, bounded by a deadline.

    One worker thread serves dispatches in order (device calls serialize
    anyway). When a call blows its deadline the worker is ABANDONED —
    the wedged thread keeps running as a daemon until the call returns
    (a thread stuck inside an XLA compile cannot be interrupted from
    Python), notices its generation is stale, and exits; the next
    dispatch lazily spawns a fresh worker. `concurrent.futures` is
    deliberately avoided: its workers are joined at interpreter exit,
    so a truly wedged thread would hang process shutdown."""

    # hard cap on abandoned-but-still-wedged workers: during an infinite
    # device wedge every probe/dispatch would otherwise leak one thread
    # per deadline; past the cap, dispatches fail fast (same
    # DeviceDeadlineExceeded path — the CPU tier serves) until at least
    # one wedged call finally returns and its thread exits
    MAX_ABANDONED = 8

    def __init__(self, name: str = "bls-device-dispatch"):
        self._name = name
        self._lock = threading.Lock()
        self._queue: queue.Queue | None = None
        self._worker: threading.Thread | None = None
        self._abandoned: list[threading.Thread] = []
        self._generation = 0

    def _ensure_worker(self) -> queue.Queue:
        with self._lock:
            if self._queue is not None and self._worker is not None \
                    and self._worker.is_alive():
                return self._queue
            self._generation += 1
            gen = self._generation
            q: queue.Queue = queue.Queue()

            def _loop():
                while True:
                    item = q.get()
                    if item is None:
                        return
                    fn, box, done = item
                    try:
                        box["result"] = fn()
                    except BaseException as e:  # delivered to the waiter
                        box["error"] = e
                    finally:
                        done.set()
                    with self._lock:
                        if self._generation != gen:
                            return  # abandoned mid-call: don't linger

            worker = threading.Thread(target=_loop, name=self._name, daemon=True)
            worker.start()
            self._queue, self._worker = q, worker
            return q

    def run(self, fn, deadline_s: float | None):
        """Execute `fn()`; raise DeviceDeadlineExceeded after
        `deadline_s` (None/<=0 = unbounded, executed inline)."""
        if deadline_s is None or deadline_s <= 0:
            return fn()
        with self._lock:
            self._abandoned = [t for t in self._abandoned if t.is_alive()]
            wedged = len(self._abandoned)
        if wedged >= self.MAX_ABANDONED:
            raise DeviceDeadlineExceeded(
                f"{wedged} wedged dispatch workers still draining; "
                "refusing to spawn more"
            )
        q = self._ensure_worker()
        done = threading.Event()
        box: dict = {}
        q.put((fn, box, done))
        if not done.wait(deadline_s):
            with self._lock:
                if self._queue is q:  # abandon the wedged worker
                    self._queue = None
                    if self._worker is not None:
                        self._abandoned.append(self._worker)
                    self._worker = None
                    self._generation += 1
            raise DeviceDeadlineExceeded(
                f"device dispatch exceeded {deadline_s:.3f}s deadline"
            )
        if "error" in box:
            raise box["error"]
        return box["result"]

    def close(self) -> None:
        with self._lock:
            q = self._queue
            self._queue = None
            self._worker = None
            self._generation += 1
        if q is not None:
            q.put(None)


class SupervisedBlsVerifier:
    """IBlsVerifier facade owning the device-tier failure policy.

    Sits between the batching facades and `DeviceBlsVerifier`; every
    unknown attribute (h2c_cache_size, stop_profiling, max_sets_per_job,
    …) delegates to the device tier so the facade adds policy, not
    surface."""

    def __init__(
        self,
        device,
        cpu=None,
        *,
        observer=None,
        deadline_s: float | None = None,
        failure_threshold: int | None = None,
        cooldown_s: float | None = None,
        retries: int | None = None,
        retry_base_delay_s: float = 0.05,
        audit_negative: bool | None = None,
        canary_thread: bool = True,
        canary_sets=None,
        time_fn=time.monotonic,
    ):
        from ..observability.stages import default_pipeline

        self.device = device
        self.cpu = cpu if cpu is not None else CpuBlsVerifier()
        self.observer = (
            observer
            or getattr(device, "observer", None)
            or default_pipeline()
        )
        self.deadline_s = (
            deadline_s
            if deadline_s is not None
            else env_float("LODESTAR_TPU_DEVICE_DEADLINE")
        )
        self.failure_threshold = int(
            failure_threshold
            if failure_threshold is not None
            else env_float("LODESTAR_TPU_BREAKER_THRESHOLD")
        )
        self.cooldown_s = (
            cooldown_s
            if cooldown_s is not None
            else env_float("LODESTAR_TPU_BREAKER_COOLDOWN")
        )
        retries = (
            retries
            if retries is not None
            else int(env_float("LODESTAR_TPU_DEVICE_RETRIES"))
        )
        if audit_negative is None:
            audit_negative = env_bool("LODESTAR_TPU_AUDIT_NEGATIVE")
        self.audit_negative = bool(audit_negative)
        # deadline blowouts are never retried (a wedged kernel just burns
        # a second deadline); raised errors get `retries` extra attempts
        self._retry_policy = RetryPolicy(
            max_attempts=1 + max(0, retries),
            base_delay_s=retry_base_delay_s,
            max_delay_s=2.0,
            jitter=0.5,
            retryable=lambda e: not isinstance(e, DeviceDeadlineExceeded),
        )
        self._dispatcher = _DeadlineDispatcher()
        self._time = time_fn
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED  # guarded-by: _lock
        self._consecutive_failures = 0  # guarded-by: _lock
        self._opened_at: float | None = None  # guarded-by: _lock
        self._canary_thread_enabled = bool(canary_thread)
        self._canary_thread: threading.Thread | None = None  # guarded-by: _lock
        self._canary_sets = canary_sets
        self._closed = False  # guarded-by: _lock
        self._log = get_logger("bls-supervisor")
        self._rl = RateLimitedLogger(self._log, interval_s=30.0)
        self.observer.breaker_state(BREAKER_STATE_VALUES[self._state])

    # -- attribute surface ----------------------------------------------------

    def __getattr__(self, name):
        if name == "device":  # not yet set (unpickling/copy): no recursion
            raise AttributeError(name)
        return getattr(self.device, name)

    # -- breaker state machine -------------------------------------------------

    def _transition_locked(self, to: str) -> None:
        if self._state == to:
            return
        frm, self._state = self._state, to
        if to == BREAKER_OPEN:
            self._opened_at = self._time()
        self.observer.breaker_state(BREAKER_STATE_VALUES[to], to=to)
        self._log.warning("circuit breaker %s -> %s", frm, to)
        self._maybe_span_event("bls/breaker_transition", frm=frm, to=to)

    def _record_device_failure(self, reason: str) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if (
                self._state == BREAKER_CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._transition_locked(BREAKER_OPEN)
                start_canary = self._canary_thread_enabled
            else:
                start_canary = False
        if start_canary:
            self._start_canary_thread()
        # a device failure is exactly the event SLO burn state exists
        # for: re-evaluate now (rate-limited, never raises) instead of
        # waiting for the next scrape
        from ..observability import slo

        slo.poke()

    def _record_device_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0

    def _device_allowed(self) -> bool:
        with self._lock:
            return self._state == BREAKER_CLOSED

    @property
    def breaker_state(self) -> str:
        with self._lock:
            return self._state

    def breaker_snapshot(self) -> dict:
        """State + policy + counters for `/debug/breaker`."""
        with self._lock:
            state = self._state
            failures = self._consecutive_failures
            opened_at = self._opened_at
        doc = {
            "state": state,
            "state_value": BREAKER_STATE_VALUES[state],
            "consecutive_failures": failures,
            "failure_threshold": self.failure_threshold,
            "cooldown_s": self.cooldown_s,
            "deadline_s": self.deadline_s,
            "retries": self._retry_policy.max_attempts - 1,
            "audit_negative": self.audit_negative,
        }
        if opened_at is not None and state != BREAKER_CLOSED:
            doc["open_for_s"] = round(self._time() - opened_at, 3)
        doc["counters"] = self.observer.supervisor_snapshot()
        mesh_snap = getattr(self.device, "mesh_snapshot", None)
        if mesh_snap is not None:
            try:
                m = mesh_snap()
            except Exception:  # pragma: no cover
                m = None
            if m is not None:
                doc["mesh"] = m
        return doc

    # -- canary ----------------------------------------------------------------

    def _build_canary_sets(self):
        if self._canary_sets is None:
            from ..bls import api as bls

            sets = []
            for i in range(2):
                sk = bls.interop_secret_key(i)
                msg = bytes([0xCA, i]) + b"\x7e" * 30
                sets.append(
                    bls.SignatureSet(
                        pubkey=sk.to_public_key(),
                        message=msg,
                        signature=sk.sign(msg).to_bytes(),
                    )
                )
            self._canary_sets = sets
        return self._canary_sets

    def _mesh_has_evicted(self) -> bool:
        try:
            fn = getattr(self.device, "mesh_has_evicted", None)
            return bool(fn()) if fn is not None else False
        except Exception:  # pragma: no cover — introspection must not raise
            return False

    def probe(self) -> bool:
        """One canary probe: open -> half_open -> device dispatch of a
        known-valid batch; success re-closes the breaker, failure
        re-opens it. Production traffic never rides half_open — only
        this probe risks the device.

        Mesh re-admission rides the same probe: evicted chips are
        restored FIRST, so the canary batch validates the full mesh — a
        still-sick chip fails the probe and is re-evicted (by the
        dispatch eviction policy if it raised, or explicitly below if the
        breaker was otherwise closed), while a recovered chip rejoins
        serving with only the canary batch at risk."""
        readmitted = 0
        if self._mesh_has_evicted():
            readmit = getattr(self.device, "mesh_readmit", None)
            if readmit is not None:
                try:
                    readmitted = int(readmit() or 0)
                except Exception:  # pragma: no cover
                    readmitted = 0
        with self._lock:
            was_closed = self._state == BREAKER_CLOSED
            if was_closed and not readmitted:
                return True
            if not was_closed:
                self._transition_locked(BREAKER_HALF_OPEN)
        ok = False
        err: Exception | None = None
        try:
            sets = self._build_canary_sets()
            with self._maybe_span("bls/canary_probe"):
                ok = bool(
                    self._device_call(
                        lambda: self.device.verify_signature_sets(sets),
                        len(sets),
                    )
                )
        except Exception as e:  # noqa: BLE001 — any failure keeps it open
            err = e
        self.observer.supervisor_canary_probe(ok)
        with self._lock:
            if ok:
                self._consecutive_failures = 0
                self._transition_locked(BREAKER_CLOSED)
            elif not was_closed:
                self._transition_locked(BREAKER_OPEN)
        if not ok:
            if readmitted and was_closed and not self._mesh_has_evicted():
                # restored full mesh failed the probe without attributing
                # a chip: shrink again rather than leave production
                # traffic on a sick full mesh
                evict = getattr(self.device, "mesh_evict", None)
                if evict is not None:
                    try:
                        evict(chip=None, reason="canary_failed")
                    except Exception:  # pragma: no cover
                        self._log.debug(
                            "mesh_evict after failed canary errored",
                            exc_info=True,
                        )
            self._rl.warning(
                "canary", "canary probe failed (%s); device stays degraded",
                err if err is not None else "device returned False",
            )
        elif readmitted:
            self._log.info(
                "canary probe passed; %d mesh chip(s) re-admitted",
                readmitted,
            )
        else:
            self._log.info("canary probe passed; breaker closed")
        return ok

    def _start_canary_thread(self) -> None:
        with self._lock:
            if (
                self._closed
                or (self._canary_thread is not None
                    and self._canary_thread.is_alive())
            ):
                return
            t = threading.Thread(
                target=self._canary_loop, name="bls-canary", daemon=True
            )
            self._canary_thread = t
        t.start()

    def _canary_loop(self) -> None:
        while True:
            time.sleep(max(0.001, self.cooldown_s))
            with self._lock:
                if self._closed:
                    return
                state = self._state
            # the loop also outlives a closed breaker while mesh chips
            # remain evicted: re-admission needs a canary too
            if state == BREAKER_CLOSED and not self._mesh_has_evicted():
                return
            try:
                self.probe()
            except Exception:  # pragma: no cover — probe() already guards
                self._log.exception("canary probe crashed")

    # -- spans -----------------------------------------------------------------

    def _maybe_span(self, name: str, **attrs):
        """Span only inside an active lifecycle trace — the supervisor
        runs on flush threads where opening root traces per dispatch
        would flood the /debug/traces ring."""
        import contextlib

        from ..observability import spans

        if spans.tracer.context() is None:
            return contextlib.nullcontext()
        return spans.tracer.span(name, **attrs)

    def _maybe_span_event(self, name: str, **attrs) -> None:
        from ..observability import spans

        spans.tracer.event(name, **attrs)

    # -- dispatch --------------------------------------------------------------

    def _evict_sick_host(self, exc, n_sets: int, reason: str) -> bool:
        """Fleet half of the failure policy (ISSUE 20): when a dispatch
        failure attributes a whole HOST (`exc.host`, e.g.
        testing.faults.InjectedHostFault), evict that host from the
        two-level serving mesh and retry on the survivors — the
        chip-eviction ladder one level up. Like chip eviction, a host
        eviction consumes NO transient-retry budget and does NOT feed
        the breaker: a fleet serving correctly on fewer hosts is
        healthy, just smaller (and the FleetRouter has already
        rebalanced the evicted host's subnets). Returns True when a
        host was evicted (caller should retry)."""
        host = getattr(exc, "host", None)
        if host is None:
            return False
        evict = getattr(self.device, "mesh_evict_host", None)
        if evict is None:
            return False
        try:
            new_size = evict(host=host, reason=reason)
        except Exception:  # pragma: no cover — eviction must never mask
            return False
        if new_size is None:
            return False
        self._maybe_span_event(
            "bls/fleet_host_eviction", reason=reason, new_size=new_size
        )
        self._rl.warning(
            "fleet_evict",
            "fleet host evicted (%s); retrying %d sets on the surviving "
            "%d-chip mesh", reason, n_sets, max(new_size, 1),
        )
        if self._canary_thread_enabled:
            self._start_canary_thread()
        return True

    def _evict_sick_chip(self, exc, n_sets: int, reason: str) -> bool:
        """Mesh half of the failure policy (round-7 tentpole): when the
        device tier serves from a chip mesh, a failed dispatch evicts the
        suspect chip — the one the exception attributes (`exc.chip`, e.g.
        testing.faults.InjectedChipFault), else the dispatcher's default
        — and the call retries immediately on the surviving mesh.
        Eviction does NOT consume the transient-retry budget and does NOT
        feed the breaker: a 3-chip node serving correctly is healthy, just
        smaller. The canary thread re-admits once probes pass. Returns
        True when a chip was evicted (caller should retry)."""
        evict = getattr(self.device, "mesh_evict", None)
        if evict is None:
            return False
        try:
            new_size = evict(chip=getattr(exc, "chip", None), reason=reason)
        except Exception:  # pragma: no cover — eviction must never mask
            return False
        if new_size is None:
            return False
        self._maybe_span_event(
            "bls/mesh_eviction", reason=reason, new_size=new_size
        )
        self._rl.warning(
            "mesh_evict",
            "mesh chip evicted (%s); retrying %d sets on the surviving "
            "%d-chip mesh", reason, n_sets, max(new_size, 1),
        )
        if self._canary_thread_enabled:
            self._start_canary_thread()
        return True

    def _device_call(self, fn, n_sets: int):
        """One supervised device call: deadline-bounded, one jittered
        retry for raised errors, chip-eviction retries when the device
        serves from a mesh (bounded by the mesh size — `mesh_evict`
        returns None once nothing is left to evict). Raises on final
        failure."""
        attempts = self._retry_policy.max_attempts
        attempt = 0
        while True:
            try:
                return self._dispatcher.run(fn, self.deadline_s)
            except DeviceDeadlineExceeded:
                self.observer.supervisor_deadline()
                self._rl.error(
                    "deadline",
                    "device dispatch (%d sets) blew the %.1fs deadline; "
                    "worker abandoned",
                    n_sets, self.deadline_s,
                )
                # a wedged chip is a sick chip: shrink the mesh and retry
                # on the survivors; without a mesh, deadline blowouts are
                # never retried (a wedged kernel just burns a second one)
                if self._evict_sick_chip(None, n_sets, "deadline"):
                    continue
                raise
            except Exception as e:
                if self._evict_sick_host(e, n_sets, type(e).__name__):
                    continue
                if self._evict_sick_chip(e, n_sets, type(e).__name__):
                    continue
                attempt += 1
                if attempt >= attempts:
                    raise
                self.observer.supervisor_retry()
                self._rl.warning(
                    "retry",
                    "device dispatch failed (%s: %s); retrying once with "
                    "backoff", type(e).__name__, e,
                )
                self._retry_policy.sleep(self._retry_policy.delay_s(attempt - 1))

    def _cpu_fallback(self, fn, reason: str, n_sets: int, default):
        """Serve from the CPU oracle; only a CPU failure on top of a
        device failure yields the blanket-`default` (False) verdicts."""
        self.observer.supervisor_fallback(reason, n_sets)
        if reason != "negative_audit":  # audits are healthy-path, not outages
            self._rl.warning(
                "fallback:" + reason,
                "device tier unavailable (%s); serving %d sets from the CPU "
                "oracle", reason, n_sets,
            )
        try:
            with self._maybe_span("bls/cpu_fallback", reason=reason):
                return fn()
        except Exception:
            self.observer.both_tiers_failed()
            self._log.exception(
                "both_tiers_failed: CPU oracle failed after device failure "
                "(%s); resolving %d sets as invalid", reason, n_sets,
            )
            return default

    # -- IBlsVerifier ----------------------------------------------------------

    def verify_signature_sets(self, sets) -> bool:
        sets = list(sets)
        if not sets:
            return False
        if not self._device_allowed():
            return self._cpu_fallback(
                lambda: self.cpu.verify_signature_sets(sets),
                "breaker_open", len(sets), False,
            )
        try:
            with self._maybe_span("bls/supervised_batch", sets=len(sets)):
                verdict = bool(
                    self._device_call(
                        lambda: self.device.verify_signature_sets(sets),
                        len(sets),
                    )
                )
        except DeviceDeadlineExceeded:
            self._record_device_failure("deadline")
            return self._cpu_fallback(
                lambda: self.cpu.verify_signature_sets(sets),
                "deadline", len(sets), False,
            )
        except Exception:
            self._record_device_failure("exception")
            self._log.exception(
                "device batch dispatch failed after retry; falling back "
                "to the CPU oracle"
            )
            return self._cpu_fallback(
                lambda: self.cpu.verify_signature_sets(sets),
                "exception", len(sets), False,
            )
        if verdict:
            self._record_device_success()
            return True
        if not self.audit_negative:
            self._record_device_success()
            return False
        # negative-verdict audit: a device False rejects blocks — confirm
        # on the oracle (free in the all-valid steady state; an overturned
        # verdict is flaky-device evidence and feeds the breaker)
        cpu_verdict = self._cpu_fallback(
            lambda: bool(self.cpu.verify_signature_sets(sets)),
            "negative_audit", len(sets), False,
        )
        if cpu_verdict:
            self.observer.verdict_mismatch()
            self._record_device_failure("verdict_mismatch")
            self._rl.error(
                "mismatch",
                "device reported a batch of %d sets invalid but the CPU "
                "oracle verified it — flaky device verdicts", len(sets),
            )
        else:
            self._record_device_success()
        return cpu_verdict

    def verify_signature_sets_individual(self, sets) -> list[bool]:
        sets = list(sets)
        if not sets:
            return []
        if not self._device_allowed():
            return self._cpu_fallback(
                lambda: self.cpu.verify_signature_sets_individual(sets),
                "breaker_open", len(sets), [False] * len(sets),
            )
        try:
            with self._maybe_span("bls/supervised_individual", sets=len(sets)):
                verdicts = list(
                    self._device_call(
                        lambda: self.device.verify_signature_sets_individual(
                            sets
                        ),
                        len(sets),
                    )
                )
        except DeviceDeadlineExceeded:
            self._record_device_failure("deadline")
            return self._cpu_fallback(
                lambda: self.cpu.verify_signature_sets_individual(sets),
                "deadline", len(sets), [False] * len(sets),
            )
        except Exception:
            self._record_device_failure("exception")
            self._log.exception(
                "device individual dispatch failed after retry; falling "
                "back to the CPU oracle"
            )
            return self._cpu_fallback(
                lambda: self.cpu.verify_signature_sets_individual(sets),
                "exception", len(sets), [False] * len(sets),
            )
        self._record_device_success()
        if not self.audit_negative:
            return [bool(v) for v in verdicts]
        rejected = [i for i, v in enumerate(verdicts) if not v]
        if not rejected:
            return [bool(v) for v in verdicts]
        # audit ONLY the rejected sets on the oracle
        audited = self._cpu_fallback(
            lambda: self.cpu.verify_signature_sets_individual(
                [sets[i] for i in rejected]
            ),
            "negative_audit", len(rejected), [False] * len(rejected),
        )
        overturned = 0
        out = [bool(v) for v in verdicts]
        for i, cpu_v in zip(rejected, audited):
            if cpu_v:
                overturned += 1
                out[i] = True
        if overturned:
            self.observer.verdict_mismatch(overturned)
            self._record_device_failure("verdict_mismatch")
            self._rl.error(
                "mismatch",
                "device rejected %d/%d sets the CPU oracle verified — "
                "flaky device verdicts", overturned, len(sets),
            )
        return out

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        """Stop the canary thread and release the dispatch worker."""
        with self._lock:
            self._closed = True
        self._dispatcher.close()
