"""The pluggable batch BLS verifier boundary.

Reference: `IBlsVerifier` (`chain/bls/interface.ts:20-46`) with two
implementations — main-thread single verifier and the worker-pool batcher
(`multithread/index.ts:98`). Here the implementations are:

- `CpuBlsVerifier` — the oracle tier, verifying via the big-int pipeline
  (role of `BlsSingleThreadVerifier`).
- `DeviceBlsVerifier` — wraps `lodestar_tpu.parallel.TpuBlsVerifier`
  (single-dispatch XLA batch kernels; role of the whole worker pool).
- `BufferedVerifier` — async batching front-end reproducing the pool's
  dynamic batching semantics: buffer `batchable` requests up to
  MAX_BUFFERED_SIGS or MAX_BUFFER_WAIT_MS, then verify as one batch and
  fall back to per-set verdicts when a batch fails
  (`multithread/index.ts:39-57,260-275`, `worker.ts:55-95`).
"""

from __future__ import annotations

import asyncio
import time
from typing import Protocol, Sequence

from ..bls import api as bls

MAX_SIGNATURE_SETS_PER_JOB = 128
MAX_BUFFERED_SIGS = 32
MAX_BUFFER_WAIT_MS = 100


class BlsShedError(RuntimeError):
    """Typed rejection for a verify request shed by dispatcher admission
    control (per-lane queue caps / flood load-shedding in
    `chain/dispatcher.BlsLaneDispatcher`).

    Waiters of a shed request get this PROMPTLY — the shed decision
    resolves their event immediately — never the 300 s
    LODESTAR_TPU_WAITER_TIMEOUT escalation ride (that path is for a
    WEDGED flush thread, not a deliberate policy decision). Callers map
    it to the gossip IGNORE action: shedding our own overload must not
    penalize peers."""

    def __init__(self, lane: str, n_sets: int, why: str = "shed"):
        super().__init__(
            f"bls verify request shed ({why}): lane={lane} sets={n_sets}"
        )
        self.lane = lane
        self.n_sets = n_sets


class IBlsVerifier(Protocol):
    def verify_signature_sets(self, sets: Sequence[bls.SignatureSet]) -> bool: ...

    def verify_signature_sets_individual(
        self, sets: Sequence[bls.SignatureSet]
    ) -> list[bool]: ...


class CpuBlsVerifier:
    """CPU-tier verifier (reference BlsSingleThreadVerifier / blst C).

    Round-3: backed by the native C pairing (`native/src/bls12.c`
    lodestar_bls_verify_sets — dual Miller loop + cyclotomic final exp,
    GIL released), ~300x the big-int oracle, so a device outage or the
    individual-retry path under attack traffic no longer collapses the
    node (VERDICT r2 Missing #4). Falls back to the Python oracle when
    the extension is unavailable or for non-standard set shapes."""

    def _native_verify(self, sets) -> list[bool] | None:
        from .. import native as _native

        if not _native.HAVE_NATIVE_BLS or not sets:
            return None
        if not all(len(s.signature) == 96 for s in sets):
            return None
        try:
            pk_b = b"".join(s.pubkey.to_bytes() for s in sets)
        except (bls.BlsError, ValueError):
            return None
        sig_b = b"".join(s.signature for s in sets)
        return _native.bls_verify_sets(
            pk_b, [s.message for s in sets], sig_b, bls.DST_G2
        )

    def verify_signature_sets(self, sets) -> bool:
        sets = list(sets)
        if not sets:
            return False
        out = self._native_verify(sets)
        if out is not None:
            return all(out)
        return bls.verify_signature_sets(sets)

    def verify_signature_sets_individual(self, sets) -> list[bool]:
        sets = list(sets)
        out = self._native_verify(sets)
        if out is not None:
            return out
        return [bls.verify_signature_sets([s]) for s in sets]


class DeviceBlsVerifier:
    """Device-tier verifier over the XLA batch kernels.

    Device-side signature decompression is the DEFAULT wire→verdict path
    (LODESTAR_TPU_DEVICE_DECOMPRESS=0 is the off-switch); batches the
    native tier can't marshal (odd signature/message lengths, missing C
    extension) silently fall back to the host-marshal path — that
    downgrade is logged (rate-limited) and counted
    (`lodestar_bls_verifier_decompress_fallback_total`) so a default-path
    e2e regression is visible instead of silent.

    Every dispatch runs inside a named `TraceAnnotation` scope (the
    SURVEY §5 tracing hook at the verifier boundary; stages inside the
    fused kernel carry `jax.named_scope` tags — view with
    TensorBoard/XProf). Profiling starts three ways:
    LODESTAR_TPU_PROFILE=<dir> auto-starts on first dispatch,
    `start_profiling()` here, or the metrics server's `/profiler/start`
    endpoint — all share one process-wide switch
    (`observability.trace`)."""

    _FALLBACK_LOG_INTERVAL_S = 60.0

    def __init__(
        self,
        buckets: tuple[int, ...] = (4, 16, 64, MAX_SIGNATURE_SETS_PER_JOB),
        grouped_configs: tuple[tuple[int, int], ...] = ((16, 8), (64, 64)),
        observer=None,
    ):
        from ..parallel.verifier import TpuBlsVerifier
        from ..utils.env import env_str

        self._inner = TpuBlsVerifier(
            buckets=buckets, grouped_configs=grouped_configs, observer=observer
        )
        self.observer = self._inner.observer
        self.max_sets_per_job = buckets[-1]
        self._profile_dir = env_str("LODESTAR_TPU_PROFILE")
        self._last_fallback_log = float("-inf")

    def _annotate(self, label: str):
        from ..observability import trace

        if self._profile_dir and not trace.profiling_active():
            trace.start_profiling(self._profile_dir)
        return trace.annotation(label)

    def start_profiling(self, trace_dir: str | None = None):
        from ..observability import trace

        return trace.start_profiling(trace_dir or self._profile_dir)

    def stop_profiling(self) -> None:
        from ..observability import trace

        trace.stop_profiling()

    def h2c_cache_size(self) -> int:
        return len(self._inner._h2c_cache)

    # -- mesh passthroughs (supervisor failure policy; parallel/mesh) -------

    def mesh_evict(self, chip: int | None = None, reason: str = "failure"):
        return self._inner.mesh_evict(chip=chip, reason=reason)

    def mesh_readmit(self) -> int:
        return self._inner.mesh_readmit()

    def mesh_has_evicted(self) -> bool:
        return self._inner.mesh_has_evicted()

    def mesh_snapshot(self):
        return self._inner.mesh_snapshot()

    def mesh_evict_host(self, host: int | None = None,
                        reason: str = "failure"):
        return self._inner.mesh_evict_host(host=host, reason=reason)

    def fleet_snapshot(self):
        return self._inner.fleet_snapshot()

    def fleet_attach_router(self, router) -> None:
        self._inner.fleet_attach_router(router)

    # -- epoch-resident crypto passthroughs (ISSUE 18) ----------------------

    def warm_h2c(self, messages) -> int:
        return self._inner.warm_h2c(messages)

    def epoch_table_populate(self, epoch: int, pubkeys) -> int:
        return self._inner.epoch_table_populate(epoch, pubkeys)

    def epoch_table_snapshot(self):
        return self._inner.epoch_table_snapshot()

    def _note_decompress_fallback(self, sets) -> None:
        """Count + rate-limited-log a device-decompress batch downgraded
        to host marshal because `_native_eligible` rejected its shape —
        the default e2e path quietly losing its ~6x win must be visible
        (round-6 satellite; VERDICT r5 #4)."""
        if not sets or not self._inner._device_decompress:
            return
        if self._inner._native_eligible(sets):
            return
        self.observer.decompress_fallback()
        now = time.monotonic()
        if now - self._last_fallback_log >= self._FALLBACK_LOG_INTERVAL_S:
            self._last_fallback_log = now
            from ..utils.logger import get_logger

            get_logger("bls-verifier").warning(
                "device-decompress batch (%d sets) fell back to host "
                "marshal: native tier ineligible (non-standard "
                "message/signature lengths or missing C extension); "
                "further downgrades counted in "
                "lodestar_bls_verifier_decompress_fallback_total",
                len(sets),
            )

    def verify_signature_sets(self, sets) -> bool:
        sets = list(sets)
        if not sets:
            return False
        self._note_decompress_fallback(sets)
        # chunk oversized batches (reference chunkifyMaximizeChunkSize)
        with self._annotate(f"bls_verify_batch/{len(sets)}"):
            for i in range(0, len(sets), self.max_sets_per_job):
                if not self._inner.verify_signature_sets(
                    sets[i : i + self.max_sets_per_job]
                ):
                    return False
            return True

    def verify_signature_sets_individual(self, sets) -> list[bool]:
        sets = list(sets)
        self._note_decompress_fallback(sets)
        out: list[bool] = []
        with self._annotate(f"bls_verify_individual/{len(sets)}"):
            for i in range(0, len(sets), self.max_sets_per_job):
                out.extend(
                    self._inner.verify_signature_sets_individual(
                        sets[i : i + self.max_sets_per_job]
                    )
                )
        return out


class BufferedVerifier:
    """Async batching front-end over any IBlsVerifier.

    verify(sets, batchable=True) awaits the batched verdict for ITS sets
    only: a failed merged batch falls back to per-set verification so one
    bad gossip object cannot poison its neighbors (reference retry
    semantics, worker.ts:55-95 — realized as a second batched dispatch,
    not N round-trips)."""

    def __init__(self, verifier: IBlsVerifier, prom=None, pipeline=None):
        from ..observability.stages import default_pipeline

        self.verifier = verifier
        self._buffer: list[tuple[list[bls.SignatureSet], asyncio.Future, float]] = []
        self._flush_task: asyncio.Task | None = None
        self.metrics = {"batches": 0, "sigs_verified": 0, "batch_fallbacks": 0}
        # optional prometheus family bundle (create_beacon_metrics result):
        # feeds the bls-verifier dashboard rows (queue depth, buffer wait,
        # sets/job, fallback rate — reference blsThreadPool.*)
        self.prom = prom
        # pipeline telemetry (flush reasons/latency, live queue gauge);
        # inherits the node bundle's instance when wired with prom=
        self.pipeline = (
            pipeline
            or getattr(prom, "pipeline", None)
            or default_pipeline()
        )
        self.pipeline.bind_buffer_depth(
            lambda: sum(len(s) for s, _, _ in self._buffer)
        )

    async def verify(self, sets: Sequence[bls.SignatureSet], batchable: bool = False) -> bool:
        sets = list(sets)
        if not sets:
            return False
        if not batchable:
            return self.verifier.verify_signature_sets(sets)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._buffer.append((sets, fut, time.monotonic()))
        buffered = sum(len(s) for s, _, _ in self._buffer)
        if self.prom is not None:
            self.prom.bls_buffer_depth.set(buffered)
        if buffered >= MAX_BUFFERED_SIGS:
            self._flush(reason="size")
        elif self._flush_task is None:
            self._flush_task = loop.create_task(self._delayed_flush())
        return await fut

    async def _delayed_flush(self) -> None:
        await asyncio.sleep(MAX_BUFFER_WAIT_MS / 1000)
        self._flush(reason="timer")

    def _flush(self, reason: str = "manual") -> None:
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        buffer, self._buffer = self._buffer, []
        if not buffer:
            return
        now = time.monotonic()
        if self.prom is not None:
            for _, _, enq in buffer:
                self.prom.bls_buffer_wait_seconds.observe(now - enq)
        t0 = time.monotonic()
        try:
            per_request = _verify_merged(
                self.verifier, [b[0] for b in buffer], self.metrics, self.prom
            )
        except Exception as e:  # resolve waiters rather than hang them
            per_request = [False] * len(buffer)
            from ..utils.logger import get_logger

            get_logger("bls-verifier").error(
                "buffered batch verification failed (%s); resolving %d "
                "requests as invalid", e, len(buffer),
            )
        self.pipeline.flush(reason, latency_s=time.monotonic() - t0)
        for (_, fut, _), verdict in zip(buffer, per_request):
            if not fut.done():
                fut.set_result(verdict)


def _verify_merged(verifier, set_groups, metrics, prom) -> list[bool]:
    """Merge request groups into one batch verification with the per-set
    fallback, updating the shared metrics families; returns one verdict
    per GROUP. The single copy of the batching semantics behind both the
    asyncio and the thread facade (reference: multithread/index.ts
    job merge + worker.ts retry-individually)."""
    merged: list = []
    for sets in set_groups:
        merged.extend(sets)
    metrics["batches"] += 1
    metrics["sigs_verified"] += len(merged)
    if prom is not None:
        prom.bls_buffer_depth.set(0)
        prom.bls_job_sets.observe(len(merged))
        prom.bls_batches_total.inc()
        prom.bls_sets_total.inc(len(merged))
    if verifier.verify_signature_sets(merged):
        return [True] * len(set_groups)
    metrics["batch_fallbacks"] += 1
    if prom is not None:
        prom.bls_batch_fallbacks_total.inc()
    verdicts = verifier.verify_signature_sets_individual(merged)
    out = []
    pos = 0
    for sets in set_groups:
        share = verdicts[pos : pos + len(sets)]
        pos += len(sets)
        out.append(all(share))
    return out


class ThreadBufferedVerifier:
    """Sync IBlsVerifier facade merging CONCURRENT verify calls into
    device batches.

    The gossip validation queues run their ladders on executor threads
    (`gossip/handlers._process`), each verifying one object's signature
    set synchronously — without merging, every attestation would be its
    own device dispatch. This facade buffers calls across threads up to
    MAX_BUFFERED_SIGS or MAX_BUFFER_WAIT_MS and verifies them as ONE
    batch, falling back to per-set verdicts when the batch fails — the
    thread-world twin of `BufferedVerifier` (reference semantics:
    `multithread/index.ts:39-57`, worker threads enqueue into pool jobs).
    Single-caller workloads degrade gracefully: the wait-window timer
    flushes them at the deadline."""

    def __init__(self, verifier: IBlsVerifier, max_sigs: int = MAX_BUFFERED_SIGS,
                 max_wait_ms: float = MAX_BUFFER_WAIT_MS, prom=None,
                 pipeline=None, waiter_timeout_s: float | None = None):
        import threading

        from ..observability.stages import default_pipeline
        from ..utils.env import env_float

        self.verifier = verifier
        self.max_sigs = max_sigs
        self.max_wait = max_wait_ms / 1000.0
        # defense-in-depth: waiters NEVER block forever on the flush
        # thread (a wedged device call used to deadlock every gossip /
        # import thread at ev.wait()). Generous by design — the
        # supervisor's per-dispatch deadline fires far earlier; this is
        # the last-resort escalation path.
        if waiter_timeout_s is None:
            waiter_timeout_s = env_float("LODESTAR_TPU_WAITER_TIMEOUT")
        self.waiter_timeout = waiter_timeout_s
        self.prom = prom
        self._lock = threading.Lock()
        self._entries: list[tuple[list, object, list]] = []  # guarded-by: _lock
        self._timer: object | None = None  # guarded-by: _lock
        self.metrics = {"batches": 0, "sigs_verified": 0, "batch_fallbacks": 0}
        # pipeline telemetry: flush-reason counter, flush latency, and the
        # LIVE buffer-depth gauge (collection-time callback — no polling)
        self.pipeline = (
            pipeline
            or getattr(prom, "pipeline", None)
            or getattr(verifier, "observer", None)
            or default_pipeline()
        )
        self.pipeline.bind_buffer_depth(self._buffered_sigs)

    def _buffered_sigs(self) -> int:
        with self._lock:
            return sum(len(e[0]) for e in self._entries)

    def __getattr__(self, name):
        # delegate everything else (stop_profiling, max_sets_per_job, …)
        # to the wrapped verifier — the facade adds batching, not surface
        if name == "verifier":  # not yet set (unpickling/copy): no recursion
            raise AttributeError(name)
        return getattr(self.verifier, name)

    # non-batchable path parity: chain code that must not wait calls this
    def verify_signature_sets_individual(self, sets):
        return self.verifier.verify_signature_sets_individual(sets)

    def verify_signature_sets(self, sets, batchable: bool = True) -> bool:
        import threading

        sets = list(sets)
        if not sets:
            return False
        # latency-critical callers (block import) and calls already at
        # batch size skip the wait window entirely — the async facade's
        # batchable=False contract (reference: verifySignatureSets opts)
        if not batchable or len(sets) >= self.max_sigs:
            if self.prom is not None:
                self.prom.bls_main_thread_sets_total.inc(len(sets))
            return self.verifier.verify_signature_sets(sets)
        ev = threading.Event()
        holder: list = [None]
        flush_now = None
        with self._lock:
            self._entries.append((sets, ev, holder))
            buffered = sum(len(e[0]) for e in self._entries)
            if self.prom is not None:
                self.prom.bls_buffer_depth.set(buffered)
            if buffered >= self.max_sigs:
                flush_now = self._take_locked()
            elif self._timer is None:
                self._timer = threading.Timer(self.max_wait, self._flush_timed)
                self._timer.daemon = True
                self._timer.start()
        if flush_now is not None:
            self._run_batch(flush_now, reason="size")
        if not ev.wait(self.waiter_timeout):
            # the flush thread is wedged past every deadline the
            # supervisor enforces — escalate loudly and fail THIS call
            # rather than deadlock the gossip/import thread forever
            self.pipeline.waiter_timeout()
            from ..utils.logger import get_logger

            get_logger("bls-verifier").error(
                "verify waiter gave up after %.1fs: flush thread wedged "
                "(%d sets in this request); counted in "
                "lodestar_bls_verifier_waiter_timeouts_total",
                self.waiter_timeout, len(sets),
            )
            out = holder[0]
            if isinstance(out, BlsShedError):
                raise out
            return out if out is not None else False
        out = holder[0]
        if isinstance(out, BlsShedError):
            # a shed entry resolves its waiter IMMEDIATELY with the typed
            # rejection — re-raise it here so callers can map overload to
            # the gossip IGNORE action instead of reading a False verdict
            raise out
        return out

    def _take_locked(self):
        entries, self._entries = self._entries, []
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return entries

    def _flush_timed(self):
        with self._lock:
            self._timer = None
            entries = self._take_locked()
        if entries:
            self._run_batch(entries, reason="timer")

    def _run_batch(self, entries, reason: str = "manual") -> None:
        """Verify a merged batch and resolve every entry — ALWAYS: an
        exception here (device OOM, preemption) must resolve waiters
        rather than hang them (their Event wait has a generous timeout as
        the last-resort escape, but a resolved verdict beats a timeout).
        When the wrapped verifier is `SupervisedBlsVerifier`, device
        failures never reach this except-path — waiters get CPU-oracle
        verdicts; blanket False remains only for both-tiers-failed."""
        t0 = time.monotonic()
        try:
            per_request = _verify_merged(
                self.verifier, [e[0] for e in entries], self.metrics, self.prom
            )
        except Exception:
            per_request = [False] * len(entries)
            from ..utils.logger import get_logger

            get_logger("bls-verifier").exception(
                "buffered batch verification failed; resolving %d requests "
                "as invalid", len(entries),
            )
        self.pipeline.flush(reason, latency_s=time.monotonic() - t0)
        for (_, ev, holder), verdict in zip(entries, per_request):
            holder[0] = verdict
            ev.set()


class MockBlsVerifier:
    """Constant-result verifier for tests/sims (reference
    `test/utils/mocks/bls.ts:3` BlsVerifierMock) — exercises every code
    path around signature verification without paying for pairings."""

    def __init__(self, result: bool = True):
        self.result = result
        self.sets_seen = 0

    def verify_signature_sets(self, sets) -> bool:
        self.sets_seen += len(sets)
        return self.result

    def verify_signature_sets_individual(self, sets) -> list[bool]:
        self.sets_seen += len(sets)
        return [self.result] * len(sets)
