"""Archiver: migrate finalized data hot→cold on finalization.

Reference: `chain/archiver/` — `archiveBlocks.ts:27` (move finalized-chain
blocks into the slot-indexed archive, drop non-canonical hot blocks),
`archiveStates.ts:24,43` (full state snapshot every
`archive_state_epoch_frequency` epochs), checkpoint-state pruning.
"""

from __future__ import annotations

from ..state_transition import util as st_util


class Archiver:
    def __init__(self, chain, db, archive_state_epoch_frequency: int = 1024):
        self.chain = chain
        self.db = db
        self.frequency = archive_state_epoch_frequency
        self.last_archived_epoch = -1

    def process_finalized(self) -> None:
        """Called after finalization advances (reference: Archiver's
        checkpoint listener)."""
        fin_epoch, fin_root = self.chain.finalized_checkpoint
        if fin_epoch <= self.last_archived_epoch:
            return
        fin_slot = st_util.compute_start_slot_at_epoch(
            fin_epoch, self.chain.preset.SLOTS_PER_EPOCH
        )
        proto = self.chain.fork_choice.proto

        # canonical finalized chain = ancestors of the finalized block
        canonical: list[bytes] = []
        if fin_root in proto.indices:
            canonical = [n.root for n in proto.iter_ancestors(fin_root)]
        canonical_set = set(canonical)

        # blocks below the finalized slot leave the hot set: canonical →
        # archive; non-canonical siblings are dropped (reference
        # archiveBlocks "migrate hot→cold")
        for root, signed in list(self.chain.blocks.items()):
            if signed is None or signed.message.slot >= fin_slot:
                continue
            if root in canonical_set:
                self.db.archive_block(signed)
                self.chain.finalized_blocks[root] = signed
                m = getattr(self.chain, "metrics", None)
                if m is not None:
                    m.archiver_blocks_total.inc()
            del self.chain.blocks[root]
            if self.db.block.has(root):
                self.db.block.delete(root)

        # periodic full state snapshot
        if fin_epoch % self.frequency == 0 or self.last_archived_epoch < 0:
            state = self.chain.state_cache.get_by_block_root(fin_root)
            if state is not None:
                self.db.state_archive.put(
                    self.db.state_archive.slot_key(state.state.slot), state.state
                )
                m = getattr(self.chain, "metrics", None)
                if m is not None:
                    m.archiver_states_total.inc()
        self.last_archived_epoch = fin_epoch
        self.chain.fork_choice.prune()
