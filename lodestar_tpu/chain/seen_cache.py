"""Anti-duplication gossip caches (reference: `chain/seenCache/*.ts` —
SeenAttesters, SeenAggregators, SeenBlockProposers, SeenAggregatedAttestations).

Epoch-keyed maps pruned on finalization; the aggregated-attestation cache
keeps seen aggregation-bit sets per attestation-data root and answers
non-strict-superset queries ("is this aggregate already covered?")."""

from __future__ import annotations


class SeenByEpoch:
    """epoch → {validator index} (SeenAttesters / SeenAggregators)."""

    def __init__(self):
        self._by_epoch: dict[int, set[int]] = {}
        self.lowest_permissible_epoch = 0

    def is_known(self, epoch: int, index: int) -> bool:
        return index in self._by_epoch.get(epoch, ())

    def add(self, epoch: int, index: int) -> None:
        if epoch < self.lowest_permissible_epoch:
            raise ValueError("epoch below pruned horizon")
        self._by_epoch.setdefault(epoch, set()).add(index)

    def prune(self, finalized_epoch: int) -> None:
        self.lowest_permissible_epoch = finalized_epoch
        self._by_epoch = {
            e: s for e, s in self._by_epoch.items() if e >= finalized_epoch
        }


SeenAttesters = SeenByEpoch
SeenAggregators = SeenByEpoch


class SeenBlockProposers:
    """slot → {proposer index} (duplicate block proposal detection)."""

    def __init__(self):
        self._by_slot: dict[int, set[int]] = {}

    def is_known(self, slot: int, proposer: int) -> bool:
        return proposer in self._by_slot.get(slot, ())

    def add(self, slot: int, proposer: int) -> None:
        self._by_slot.setdefault(slot, set()).add(proposer)

    def prune(self, finalized_slot: int) -> None:
        self._by_slot = {s: v for s, v in self._by_slot.items() if s >= finalized_slot}


class SeenAggregatedAttestations:
    """data_root → list of seen aggregation-bit tuples; an incoming
    aggregate is redundant iff some seen bitset is a non-strict superset
    (reference seenAggregatedAttestations non-strict superset check)."""

    def __init__(self):
        self._by_root: dict[bytes, list[tuple[bool, ...]]] = {}
        self._epoch_of_root: dict[bytes, int] = {}

    def is_known_superset(self, data_root: bytes, bits: list[bool]) -> bool:
        for seen in self._by_root.get(data_root, ()):
            if len(seen) == len(bits) and all(
                s or not b for s, b in zip(seen, bits)
            ):
                return True
        return False

    def add(self, epoch: int, data_root: bytes, bits: list[bool]) -> None:
        entry = tuple(bits)
        existing = self._by_root.setdefault(data_root, [])
        # drop strictly-dominated entries to bound growth
        existing[:] = [
            s for s in existing
            if not (len(s) == len(entry) and all(e or not b for e, b in zip(entry, s)))
        ]
        existing.append(entry)
        self._epoch_of_root[data_root] = epoch

    def prune(self, finalized_epoch: int) -> None:
        stale = [r for r, e in self._epoch_of_root.items() if e < finalized_epoch]
        for r in stale:
            self._by_root.pop(r, None)
            self._epoch_of_root.pop(r, None)
