"""Anti-duplication gossip caches (reference: `chain/seenCache/*.ts` —
SeenAttesters, SeenAggregators, SeenBlockProposers, SeenAggregatedAttestations).

Epoch-keyed maps pruned on finalization; the aggregated-attestation cache
keeps seen aggregation-bit sets per attestation-data root and answers
non-strict-superset queries ("is this aggregate already covered?")."""

from __future__ import annotations


class SeenByEpoch:
    """epoch → {validator index} (SeenAttesters / SeenAggregators)."""

    def __init__(self):
        self._by_epoch: dict[int, set[int]] = {}
        self.lowest_permissible_epoch = 0

    def is_known(self, epoch: int, index: int) -> bool:
        return index in self._by_epoch.get(epoch, ())

    def add(self, epoch: int, index: int) -> None:
        if epoch < self.lowest_permissible_epoch:
            raise ValueError("epoch below pruned horizon")
        self._by_epoch.setdefault(epoch, set()).add(index)

    def prune(self, finalized_epoch: int) -> None:
        self.lowest_permissible_epoch = finalized_epoch
        self._by_epoch = {
            e: s for e, s in self._by_epoch.items() if e >= finalized_epoch
        }


SeenAttesters = SeenByEpoch
SeenAggregators = SeenByEpoch


class SeenBlockProposers:
    """slot → {proposer index} (duplicate block proposal detection)."""

    def __init__(self):
        self._by_slot: dict[int, set[int]] = {}

    def is_known(self, slot: int, proposer: int) -> bool:
        return proposer in self._by_slot.get(slot, ())

    def add(self, slot: int, proposer: int) -> None:
        self._by_slot.setdefault(slot, set()).add(proposer)

    def prune(self, finalized_slot: int) -> None:
        self._by_slot = {s: v for s, v in self._by_slot.items() if s >= finalized_slot}


class SeenAggregatedAttestations:
    """data_root → list of seen aggregation-bit tuples; an incoming
    aggregate is redundant iff some seen bitset is a non-strict superset
    (reference seenAggregatedAttestations non-strict superset check)."""

    def __init__(self):
        self._by_root: dict[bytes, list[tuple[bool, ...]]] = {}
        self._epoch_of_root: dict[bytes, int] = {}

    def is_known_superset(self, data_root: bytes, bits: list[bool]) -> bool:
        for seen in self._by_root.get(data_root, ()):
            if len(seen) == len(bits) and all(
                s or not b for s, b in zip(seen, bits)
            ):
                return True
        return False

    def add(self, epoch: int, data_root: bytes, bits: list[bool]) -> None:
        entry = tuple(bits)
        existing = self._by_root.setdefault(data_root, [])
        # drop strictly-dominated entries to bound growth
        existing[:] = [
            s for s in existing
            if not (len(s) == len(entry) and all(e or not b for e, b in zip(entry, s)))
        ]
        existing.append(entry)
        self._epoch_of_root[data_root] = epoch

    def prune(self, finalized_epoch: int) -> None:
        stale = [r for r, e in self._epoch_of_root.items() if e < finalized_epoch]
        for r in stale:
            self._by_root.pop(r, None)
            self._epoch_of_root.pop(r, None)


class SeenSyncCommitteeMessages:
    """First-seen per (slot, subnet, validator) — the [IGNORE] dedup of the
    sync_committee_{subnet} topic (reference seenCache/seenCommittee.ts)."""

    SLOTS_RETAINED = 3

    def __init__(self):
        self._seen: set[tuple[int, int, int]] = set()

    def is_known(self, slot: int, subnet: int, validator_index: int) -> bool:
        return (slot, subnet, validator_index) in self._seen

    def add(self, slot: int, subnet: int, validator_index: int) -> None:
        self._seen.add((slot, subnet, validator_index))

    def prune(self, clock_slot: int) -> None:
        self._seen = {
            k for k in self._seen if k[0] + self.SLOTS_RETAINED >= clock_slot
        }


class SeenContributionAndProof:
    """Dedup for sync_committee_contribution_and_proof: first-seen per
    aggregator (slot, subcommittee, aggregator_index) plus the non-strict
    participant-superset check per (slot, root, subcommittee) (reference
    seenCache/seenGossipBlockInput... seenContributionAndProof.ts
    participantsKnown/isAggregatorKnown)."""

    SLOTS_RETAINED = 3

    def __init__(self):
        self._aggregators: set[tuple[int, int, int]] = set()
        self._participants: dict[tuple[int, bytes, int], list[list[bool]]] = {}

    def is_aggregator_known(
        self, slot: int, subcommittee: int, aggregator_index: int
    ) -> bool:
        return (slot, subcommittee, aggregator_index) in self._aggregators

    def participants_known(self, contribution) -> bool:
        """True when some already-seen contribution's bits are a non-strict
        superset of this contribution's bits."""
        key = (
            int(contribution.slot),
            bytes(contribution.beacon_block_root),
            int(contribution.subcommittee_index),
        )
        bits = list(contribution.aggregation_bits)
        for seen in self._participants.get(key, []):
            if all(s or not b for s, b in zip(seen, bits)):
                return True
        return False

    def add(self, contribution_and_proof) -> None:
        c = contribution_and_proof.contribution
        self._aggregators.add(
            (
                int(c.slot),
                int(c.subcommittee_index),
                int(contribution_and_proof.aggregator_index),
            )
        )
        key = (int(c.slot), bytes(c.beacon_block_root), int(c.subcommittee_index))
        self._participants.setdefault(key, []).append(list(c.aggregation_bits))

    def prune(self, clock_slot: int) -> None:
        self._aggregators = {
            k for k in self._aggregators if k[0] + self.SLOTS_RETAINED >= clock_slot
        }
        self._participants = {
            k: v
            for k, v in self._participants.items()
            if k[0] + self.SLOTS_RETAINED >= clock_slot
        }
