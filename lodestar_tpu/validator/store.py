"""ValidatorStore: keys + slashing-gated signing.

Reference: `validator/src/services/validatorStore.ts` — signBlock (:307),
signAttestation (:358) with checkAndInsert* protection gates (:379),
randao reveals, selection proofs, aggregate-and-proof signing.
"""

from __future__ import annotations

from ..bls import api as bls
from ..config.beacon_config import compute_signing_root
from ..params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
)
from ..ssz import uint64
from ..state_transition import util as st_util
from .slashing_protection import SlashingProtection


class ValidatorStore:
    def __init__(self, config, slashing_protection: SlashingProtection):
        self.config = config
        self.protection = slashing_protection
        self._keys: dict[bytes, bls.SecretKey] = {}
        # pubkey → ExternalSignerClient (reference: remote signer support in
        # validatorStore via externalSignerClient)
        self._remote: dict[bytes, object] = {}

    # -- key management ------------------------------------------------------

    def add_secret_key(self, sk: bls.SecretKey) -> bytes:
        pk = sk.to_public_key().to_bytes()
        self._keys[pk] = sk
        return pk

    def add_remote_key(self, pubkey: bytes, signer) -> bytes:
        """Register a pubkey whose signatures come from an external signer
        (reference: `externalSignerClient`)."""
        self._remote[pubkey] = signer
        return pubkey

    def remove_key(self, pubkey: bytes) -> bool:
        # pop BOTH maps: a pubkey registered as local and remote must lose
        # every signing path, or a keymanager delete would report success
        # while the remote path keeps signing
        local = self._keys.pop(pubkey, None) is not None
        remote = self._remote.pop(pubkey, None) is not None
        return local or remote

    def has_pubkey(self, pubkey: bytes) -> bool:
        return pubkey in self._keys or pubkey in self._remote

    @property
    def pubkeys(self) -> list[bytes]:
        return list(dict.fromkeys(list(self._keys) + list(self._remote)))

    def _sign_root(self, pubkey: bytes, root: bytes) -> bytes:
        sk = self._keys.get(pubkey)
        if sk is not None:
            return sk.sign(root).to_bytes()
        signer = self._remote.get(pubkey)
        if signer is not None:
            return signer.sign(pubkey, root)
        raise KeyError(f"no signer for {pubkey.hex()}")

    # -- signing (each gate mirrors validatorStore) --------------------------

    def sign_block(self, pubkey: bytes, types, block):
        domain = self.config.get_domain(DOMAIN_BEACON_PROPOSER, block.slot)
        root = compute_signing_root(block.hash_tree_root(), domain)
        self.protection.check_and_insert_block_proposal(pubkey, block.slot, root)
        sig = self._sign_root(pubkey, root)
        return types.SignedBeaconBlock(message=block, signature=sig)

    def sign_attestation(self, pubkey: bytes, data) -> bytes:
        spe = self.config.preset.SLOTS_PER_EPOCH
        domain = self.config.get_domain(
            DOMAIN_BEACON_ATTESTER,
            st_util.compute_start_slot_at_epoch(data.target.epoch, spe),
            data.target.epoch,
        )
        root = compute_signing_root(data.hash_tree_root(), domain)
        self.protection.check_and_insert_attestation(
            pubkey, data.source.epoch, data.target.epoch, root
        )
        return self._sign_root(pubkey, root)

    def sign_randao(self, pubkey: bytes, slot: int) -> bytes:
        epoch = slot // self.config.preset.SLOTS_PER_EPOCH
        domain = self.config.get_domain(DOMAIN_RANDAO, slot)
        root = compute_signing_root(uint64.hash_tree_root(epoch), domain)
        return self._sign_root(pubkey, root)

    def sign_selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        domain = self.config.get_domain(DOMAIN_SELECTION_PROOF, slot)
        root = compute_signing_root(uint64.hash_tree_root(slot), domain)
        return self._sign_root(pubkey, root)

    def sign_aggregate_and_proof(self, pubkey: bytes, types, agg_and_proof):
        domain = self.config.get_domain(
            DOMAIN_AGGREGATE_AND_PROOF, agg_and_proof.aggregate.data.slot
        )
        root = compute_signing_root(agg_and_proof.hash_tree_root(), domain)
        sig = self._sign_root(pubkey, root)
        return types.SignedAggregateAndProof(
            message=agg_and_proof, signature=sig
        )

    def is_aggregator(
        self, slot: int, committee_size: int, pubkey: bytes, proof: bytes | None = None
    ) -> bool:
        """TARGET_AGGREGATORS_PER_COMMITTEE-based selection (spec
        is_aggregator): hash(selection_proof) mod max(1, size/16) == 0."""
        from ..params import TARGET_AGGREGATORS_PER_COMMITTEE
        from ..ssz.hashing import sha256

        if proof is None:
            proof = self.sign_selection_proof(pubkey, slot)
        modulo = max(1, committee_size // TARGET_AGGREGATORS_PER_COMMITTEE)
        return int.from_bytes(sha256(proof)[:8], "little") % modulo == 0
