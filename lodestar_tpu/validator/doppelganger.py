"""Doppelganger protection.

Reference: `validator/src/services/doppelgangerService.ts` — before a
validator starts signing, watch the network for DOPPELGANGER_EPOCHS_TO_CHECK
full epochs; any liveness sighting of our indices (attestation or proposal
by someone else holding the same key) permanently blocks signing.
"""

from __future__ import annotations

from enum import Enum

from ..utils.logger import get_logger

DOPPELGANGER_EPOCHS_TO_CHECK = 2


class DoppelgangerStatus(str, Enum):
    VERIFYING = "VerifyingSafety"
    SAFE = "SigningEnabled"
    DETECTED = "DoppelgangerDetected"


class DoppelgangerService:
    """`register(index, epoch)` when a key is added; call
    `on_epoch(epoch, liveness)` once per epoch with a liveness map
    (validator_index → seen-this-epoch) from the beacon node's liveness
    endpoint; gate every signing path on `is_signing_safe`."""

    def __init__(self, epochs_to_check: int = DOPPELGANGER_EPOCHS_TO_CHECK):
        self.epochs_to_check = epochs_to_check
        self.log = get_logger("doppelganger")
        # index → (registered_epoch, status)
        self._state: dict[int, tuple[int, DoppelgangerStatus]] = {}

    def register(self, validator_index: int, current_epoch: int) -> None:
        self._state.setdefault(
            validator_index, (current_epoch, DoppelgangerStatus.VERIFYING)
        )

    def status(self, validator_index: int) -> DoppelgangerStatus:
        entry = self._state.get(validator_index)
        # unregistered indices are assumed managed elsewhere: signing allowed
        return entry[1] if entry else DoppelgangerStatus.SAFE

    def is_signing_safe(self, validator_index: int) -> bool:
        return self.status(validator_index) == DoppelgangerStatus.SAFE

    def any_detected(self) -> bool:
        return any(
            st == DoppelgangerStatus.DETECTED for _, st in self._state.values()
        )

    def on_epoch(self, epoch: int, liveness: dict[int, bool]) -> None:
        """`liveness[idx]` True = the network saw idx attest/propose this
        epoch. Sightings during VERIFYING mean another instance holds the
        key → DETECTED (never signs). After `epochs_to_check` clean epochs
        → SAFE."""
        for idx, (registered, status) in list(self._state.items()):
            if status != DoppelgangerStatus.VERIFYING:
                continue
            if liveness.get(idx, False):
                self.log.error(
                    "DOPPELGANGER DETECTED for validator %d — signing disabled",
                    idx,
                )
                self._state[idx] = (registered, DoppelgangerStatus.DETECTED)
            elif epoch >= registered + self.epochs_to_check:
                self.log.info("validator %d cleared doppelganger check", idx)
                self._state[idx] = (registered, DoppelgangerStatus.SAFE)
