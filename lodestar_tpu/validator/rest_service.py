"""Validator client over the REST Beacon API.

Reference: `validator/src/validator.ts:53` + `services/` — the production
validator never touches chain internals; it discovers duties, produces and
publishes everything through the Beacon API, gated by slashing protection
and doppelganger checks. This mirrors that wiring over `BeaconApiClient`.
"""

from __future__ import annotations

from ..utils.logger import get_logger
from .doppelganger import DoppelgangerService
from .store import ValidatorStore


class RestValidatorService:
    def __init__(
        self,
        config,
        types,
        client,
        store: ValidatorStore,
        doppelganger: DoppelgangerService | None = None,
        fee_recipient: bytes | None = None,
    ):
        self.config = config
        self.types = types
        self.client = client
        self.store = store
        self.doppelganger = doppelganger
        self.fee_recipient = fee_recipient
        self.log = get_logger("validator")
        self._indices: dict[bytes, int] = {}  # pubkey → validator index
        self._attester_duties: dict[int, list[dict]] = {}  # slot → duties
        self._proposer_duties: dict[int, int] = {}  # slot → validator index
        self._duties_epoch = -1

    # -- index + duty discovery ----------------------------------------------

    def resolve_indices(self) -> dict[bytes, int]:
        unresolved = [pk for pk in self.store.pubkeys if pk not in self._indices]
        for pk in unresolved:
            try:
                entry = self.client.getStateValidator("head", "0x" + pk.hex())
            except Exception as e:
                # unresolved keys retry on the next duty poll
                self.log.debug(
                    "getStateValidator(%s…) failed: %s", pk.hex()[:8], e
                )
                continue
            if entry is not None:
                self._indices[pk] = int(entry["index"])
        return self._indices

    def update_duties(self, epoch: int) -> None:
        """Refresh attester + proposer duty maps for `epoch` (reference
        attestationDutiesService/blockDutiesService polling)."""
        indices = self.resolve_indices()
        if not indices:
            return
        if self.doppelganger is not None:
            # late-resolving indices still get the full observation window
            # (register() is idempotent — no effect on known indices)
            for idx in indices.values():
                self.doppelganger.register(idx, epoch)
        self._attester_duties.clear()
        self._proposer_duties.clear()
        atts = self.client.getAttesterDuties(
            epoch, body=[str(i) for i in indices.values()]
        ) or []
        for duty in atts:
            self._attester_duties.setdefault(int(duty["slot"]), []).append(duty)
        props = self.client.getProposerDuties(epoch) or []
        ours = set(indices.values())
        for duty in props:
            if int(duty["validator_index"]) in ours:
                self._proposer_duties[int(duty["slot"])] = int(duty["validator_index"])
        self._duties_epoch = epoch
        if self.fee_recipient is not None:
            # re-register every epoch: the node-side proposer cache expires
            # stale registrations (reference prepareBeaconProposerService)
            try:
                self.client.prepareBeaconProposer(
                    body=[
                        {
                            "validator_index": str(i),
                            "fee_recipient": "0x" + self.fee_recipient.hex(),
                        }
                        for i in indices.values()
                    ]
                )
            except Exception as e:
                self.log.warning("prepareBeaconProposer failed: %s", e)
        self.log.info(
            "duties epoch %d: %d attester slots, %d proposals",
            epoch,
            len(self._attester_duties),
            len(self._proposer_duties),
        )

    def _pubkey_of(self, index: int) -> bytes | None:
        for pk, idx in self._indices.items():
            if idx == index:
                return pk
        return None

    def _may_sign(self, index: int) -> bool:
        return self.doppelganger is None or self.doppelganger.is_signing_safe(index)

    # -- per-slot work --------------------------------------------------------

    def on_slot(self, slot: int) -> None:
        spe = self.config.preset.SLOTS_PER_EPOCH
        epoch = slot // spe
        if epoch != self._duties_epoch:
            self.update_duties(epoch)
            if self.doppelganger is not None and epoch > 0:
                liveness = self.client.getLiveness(
                    epoch - 1, body=[str(i) for i in self._indices.values()]
                ) or []
                self.doppelganger.on_epoch(
                    epoch, {int(e["index"]): e["is_live"] for e in liveness}
                )
        self.propose_if_due(slot)
        self.attest_if_due(slot)

    def propose_if_due(self, slot: int):
        index = self._proposer_duties.get(slot)
        if index is None:
            return None
        pk = self._pubkey_of(index)
        if pk is None or not self._may_sign(index):
            return None
        reveal = self.store.sign_randao(pk, slot)
        obj = self.client.produceBlockV2(
            slot, query={"randao_reveal": "0x" + reveal.hex()}
        )
        from ..types import get_types

        types = get_types(self.config.preset).by_fork.get(
            obj.get("version"), self.types
        )
        block = types.BeaconBlock.from_obj(obj["data"])
        signed = self.store.sign_block(pk, types, block)
        self.client.publishBlock(body=signed.to_obj())
        self.log.info("proposed block at slot %d (validator %d)", slot, index)
        return signed

    def attest_if_due(self, slot: int) -> list:
        duties = self._attester_duties.get(slot, [])
        produced = []
        for duty in duties:
            index = int(duty["validator_index"])
            pk = self._pubkey_of(index)
            if pk is None or not self._may_sign(index):
                continue
            cidx = int(duty["committee_index"])
            data_obj = self.client.produceAttestationData(
                query={"slot": slot, "committee_index": cidx}
            )
            data = self.types.AttestationData.from_obj(data_obj)
            sig = self.store.sign_attestation(pk, data)
            bits = [False] * int(duty["committee_length"])
            bits[int(duty["validator_committee_index"])] = True
            att = self.types.Attestation(
                aggregation_bits=bits, data=data, signature=sig
            )
            self.client.submitPoolAttestations(body=[att.to_obj()])
            produced.append(att)
            # aggregation duty (reference: aggregator per committee)
            if self.store.is_aggregator(slot, len(bits), pk):
                self._aggregate(slot, cidx, pk, index, data)
        return produced

    def _aggregate(self, slot: int, cidx: int, pk: bytes, index: int, data) -> None:
        try:
            agg_obj = self.client.getAggregatedAttestation(
                query={
                    "slot": slot,
                    "attestation_data_root": "0x" + data.hash_tree_root().hex(),
                }
            )
        except Exception:
            return
        aggregate = self.types.Attestation.from_obj(agg_obj)
        proof = self.store.sign_selection_proof(pk, slot)
        agg_and_proof = self.types.AggregateAndProof(
            aggregator_index=index, aggregate=aggregate, selection_proof=proof
        )
        signed = self.store.sign_aggregate_and_proof(pk, self.types, agg_and_proof)
        self.client.publishAggregateAndProofs(body=[signed.to_obj()])
