"""Slashing protection: the only stateful safety gate a validator has.

Reference: `validator/src/slashingProtection/` — block-by-slot repository,
attestation-by-target repository, and the min/max-surround algorithm
(`minMaxSurround/minMaxSurround.ts`) detecting surround votes in O(1) per
check via distance spans; interchange = EIP-3076 JSON.

This implementation keeps the same safety conditions:
  blocks: a second block at slot <= max(signed slots) is refused unless it
          is the identical signing root at the same slot.
  attestations: refuse double votes (same target, different root),
          surrounding votes (s < s', t > t') and surrounded votes
          (s > s', t < t'), via min/max span arrays per validator.
"""

from __future__ import annotations

import json

from ..db.repository import Bucket, Repository


class SlashingError(ValueError):
    pass


class _U64:
    @staticmethod
    def serialize(v: int) -> bytes:
        return int(v).to_bytes(8, "big")

    @staticmethod
    def deserialize(b: bytes) -> int:
        return int.from_bytes(b, "big")


class _Json:
    @staticmethod
    def serialize(v) -> bytes:
        return json.dumps(v, sort_keys=True).encode()

    @staticmethod
    def deserialize(b: bytes):
        return json.loads(b.decode())


class SlashingProtection:
    """Per-pubkey protection DB over the shared KV store (buckets 20-24 in
    the reference schema)."""

    def __init__(self, db):
        self.blocks = Repository(
            db, Bucket.validator_slashingProtectionBlockBySlot, _Json
        )
        self.atts = Repository(
            db, Bucket.validator_slashingProtectionAttestationByTarget, _Json
        )
        self.spans_min = Repository(
            db, Bucket.validator_slashingProtectionMinSpanDistance, _Json
        )
        self.spans_max = Repository(
            db, Bucket.validator_slashingProtectionMaxSpanDistance, _Json
        )

    # -- blocks --------------------------------------------------------------

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        rec = self.blocks.get(pubkey) or {}
        max_slot = rec.get("max_slot", -1)
        roots = rec.get("roots", {})
        if slot <= max_slot:
            prev = roots.get(str(slot))
            if prev != signing_root.hex():
                raise SlashingError(
                    f"block proposal at slot {slot} <= previously signed {max_slot}"
                )
            return  # identical re-sign is safe
        roots[str(slot)] = signing_root.hex()
        # keep a bounded window of recent roots
        if len(roots) > 64:
            for k in sorted(roots, key=int)[: len(roots) - 64]:
                del roots[k]
        self.blocks.put(pubkey, {"max_slot": slot, "roots": roots})

    # -- attestations --------------------------------------------------------

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingError("source after target")
        rec = self.atts.get(pubkey) or {}
        targets = rec.get("targets", {})

        # double vote
        prev = targets.get(str(target_epoch))
        if prev is not None:
            if prev["root"] != signing_root.hex():
                raise SlashingError(f"double vote at target {target_epoch}")
            return

        # surround checks against recorded votes
        for t_str, v in targets.items():
            t, s = int(t_str), v["source"]
            if source_epoch < s and target_epoch > t:
                raise SlashingError(f"surrounding vote of ({s},{t})")
            if source_epoch > s and target_epoch < t:
                raise SlashingError(f"surrounded by ({s},{t})")

        targets[str(target_epoch)] = {
            "source": source_epoch,
            "root": signing_root.hex(),
        }
        # bound history: keep most recent 512 targets (distance-span
        # compression — reference minMaxSurround — is an optimization on
        # the same invariant)
        if len(targets) > 512:
            for k in sorted(targets, key=int)[: len(targets) - 512]:
                del targets[k]
        self.atts.put(
            pubkey,
            {
                "targets": targets,
                "max_target": max(target_epoch, rec.get("max_target", -1)),
                "min_source": min(source_epoch, rec.get("min_source", source_epoch)),
            },
        )

    # -- EIP-3076 interchange ------------------------------------------------

    def export_interchange(self, genesis_validators_root: bytes, pubkeys) -> dict:
        data = []
        for pk in pubkeys:
            blocks_rec = self.blocks.get(pk) or {}
            atts_rec = self.atts.get(pk) or {}
            data.append(
                {
                    "pubkey": "0x" + pk.hex(),
                    "signed_blocks": [
                        {"slot": str(s), "signing_root": "0x" + r}
                        for s, r in sorted(
                            blocks_rec.get("roots", {}).items(), key=lambda kv: int(kv[0])
                        )
                    ],
                    "signed_attestations": [
                        {
                            "source_epoch": str(v["source"]),
                            "target_epoch": t,
                            "signing_root": "0x" + v["root"],
                        }
                        for t, v in sorted(
                            atts_rec.get("targets", {}).items(), key=lambda kv: int(kv[0])
                        )
                    ],
                }
            )
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, obj: dict) -> None:
        for entry in obj.get("data", []):
            pk = bytes.fromhex(entry["pubkey"][2:])
            for b in entry.get("signed_blocks", []):
                try:
                    self.check_and_insert_block_proposal(
                        pk,
                        int(b["slot"]),
                        bytes.fromhex(b.get("signing_root", "0x")[2:] or "00"),
                    )
                except SlashingError:
                    continue
            for a in entry.get("signed_attestations", []):
                try:
                    self.check_and_insert_attestation(
                        pk,
                        int(a["source_epoch"]),
                        int(a["target_epoch"]),
                        bytes.fromhex(a.get("signing_root", "0x")[2:] or "00"),
                    )
                except SlashingError:
                    continue
