"""Slashing protection: the only stateful safety gate a validator has.

Reference: `validator/src/slashingProtection/` — block-by-slot repository,
attestation-by-target repository, and the min/max-surround algorithm
(`minMaxSurround/minMaxSurround.ts`) detecting surround votes in O(1) per
check via distance spans; interchange = EIP-3076 JSON.

This implementation keeps the same safety conditions:
  blocks: a second block at slot <= max(signed slots) is refused unless it
          is the identical signing root at the same slot.
  attestations: refuse double votes (same target, different root),
          surrounding votes (s < s', t > t') and surrounded votes
          (s > s', t < t'), via min/max distance spans per validator.

Surround detection is the reference's min-max-surround algorithm
(`minMaxSurround/minMaxSurround.ts`): per validator,
  max_span[e] = max{t' − e : recorded votes (s', t') with s' < e}
  min_span[e] = min{t' − e : recorded votes (s', t') with s' > e}
so a new vote (s, t) is surrounded iff s + max_span[s] > t and surrounds
a recorded vote iff s + min_span[s] < t — O(1) per check regardless of
how many targets were pruned from the exact-root history. Span updates
walk outward from the new vote and stop at the first epoch whose stored
span already dominates (the monotonicity early-break of the reference's
update loops), bounded by `max_epoch_lookback`. Votes whose source falls
below the maintained span floor are refused conservatively (the safety
direction of EIP-3076: never sign when history is unknown).
"""

from __future__ import annotations

import json

from ..db.repository import Bucket, Repository


class SlashingError(ValueError):
    pass


class _U64:
    @staticmethod
    def serialize(v: int) -> bytes:
        return int(v).to_bytes(8, "big")

    @staticmethod
    def deserialize(b: bytes) -> int:
        return int.from_bytes(b, "big")


class _Json:
    @staticmethod
    def serialize(v) -> bytes:
        return json.dumps(v, sort_keys=True).encode()

    @staticmethod
    def deserialize(b: bytes):
        return json.loads(b.decode())


class _SpanStore:
    """Chunked distance-span storage for one (repo, pubkey).

    Spans live in per-1024-epoch chunk records (key = pubkey ‖ u32 chunk
    index) so a signature only rewrites the chunks its walk touched —
    the reference stores per-epoch span records for the same reason
    (`minMaxSurround/`: O(changed epochs), not O(lookback), per update).
    The owning attestation record tracks which chunk ids exist."""

    CHUNK = 1024

    def __init__(self, repo, pubkey: bytes, chunk_ids: list[int]):
        self.repo = repo
        self.pk = pubkey
        self.chunk_ids = set(chunk_ids)
        self._loaded: dict[int, dict] = {}
        self._dirty: set[int] = set()

    def _key(self, cid: int) -> bytes:
        return self.pk + cid.to_bytes(4, "big")

    def _chunk(self, cid: int) -> dict:
        c = self._loaded.get(cid)
        if c is None:
            c = (self.repo.get(self._key(cid)) or {}) if cid in self.chunk_ids else {}
            self._loaded[cid] = c
        return c

    def get(self, epoch: int):
        return self._chunk(epoch // self.CHUNK).get(str(epoch % self.CHUNK))

    def set(self, epoch: int, dist: int) -> None:
        cid = epoch // self.CHUNK
        self._chunk(cid)[str(epoch % self.CHUNK)] = dist
        self._dirty.add(cid)
        self.chunk_ids.add(cid)

    def prune_below(self, floor: int) -> None:
        """Drop whole chunks strictly below the floor (boundary-chunk
        entries below the floor are unreachable — floor-rejected — and
        bounded by one chunk, so they are left in place)."""
        for cid in [c for c in self.chunk_ids if (c + 1) * self.CHUNK <= floor]:
            self.repo.delete(self._key(cid))
            self.chunk_ids.discard(cid)
            self._loaded.pop(cid, None)
            self._dirty.discard(cid)

    def flush(self) -> None:
        for cid in self._dirty:
            self.repo.put(self._key(cid), self._loaded[cid])
        self._dirty.clear()


class SlashingProtection:
    """Per-pubkey protection DB over the shared KV store (buckets 20-24 in
    the reference schema).

    `max_epoch_lookback` bounds how far span updates walk (reference:
    `minMaxSurround.ts` `maxEpochLookback`); spans older than
    `max_target − lookback` are pruned and the floor advances — votes
    reaching below the floor are refused rather than guessed at."""

    def __init__(self, db, max_epoch_lookback: int = 8192):
        self.blocks = Repository(
            db, Bucket.validator_slashingProtectionBlockBySlot, _Json
        )
        self.atts = Repository(
            db, Bucket.validator_slashingProtectionAttestationByTarget, _Json
        )
        self.spans_min = Repository(
            db, Bucket.validator_slashingProtectionMinSpanDistance, _Json
        )
        self.spans_max = Repository(
            db, Bucket.validator_slashingProtectionMaxSpanDistance, _Json
        )
        self.max_epoch_lookback = max_epoch_lookback

    # -- blocks --------------------------------------------------------------

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        rec = self.blocks.get(pubkey) or {}
        max_slot = rec.get("max_slot", -1)
        roots = rec.get("roots", {})
        if slot <= max_slot:
            prev = roots.get(str(slot))
            if prev != signing_root.hex():
                raise SlashingError(
                    f"block proposal at slot {slot} <= previously signed {max_slot}"
                )
            return  # identical re-sign is safe
        roots[str(slot)] = signing_root.hex()
        # keep a bounded window of recent roots
        if len(roots) > 64:
            for k in sorted(roots, key=int)[: len(roots) - 64]:
                del roots[k]
        self.blocks.put(pubkey, {"max_slot": slot, "roots": roots})

    # -- attestations --------------------------------------------------------

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingError("source after target")
        rec = self.atts.get(pubkey) or {}
        targets = rec.get("targets", {})

        # double vote against the exact-root window
        prev = targets.get(str(target_epoch))
        if prev is not None:
            if prev["root"] != signing_root.hex():
                raise SlashingError(f"double vote at target {target_epoch}")
            return
        # a target at or below the pruned exact-root window cannot be
        # double-vote-checked — refuse rather than guess (EIP-3076 safety)
        if target_epoch <= rec.get("pruned_below", -1):
            raise SlashingError(
                f"target {target_epoch} below retained history"
            )

        # one-time migration: records from before the span rewrite have
        # targets but no span data — rebuild spans by replaying the
        # retained votes (surround info for already-pruned votes is gone,
        # so the floor starts at the lowest retained source: older votes
        # are refused, never guessed at)
        if targets and "span_floor" not in rec:
            replay = sorted(
                ((v["source"], int(t), v["root"]) for t, v in targets.items()),
                key=lambda x: x[1],
            )
            self.atts.put(
                pubkey,
                {
                    "targets": {},
                    "span_floor": max(0, min(s for s, _, _ in replay)),
                    "min_chunks": [],
                    "max_chunks": [],
                    "max_target": rec.get("max_target", -1),
                    "min_source": rec.get("min_source", 0),
                    "pruned_below": rec.get("pruned_below", -1),
                },
            )
            # A retained vote wider than max_epoch_lookback (long
            # non-finality) can make a later replayed vote's source fall
            # below the advancing span floor and fail mid-replay. Such
            # votes must not vanish silently: raise pruned_below to the
            # highest lost target so future votes at (or below) those
            # targets are refused instead of passing the double-vote
            # check against an emptied window.
            lost_targets = []
            for s, t, root in replay:
                try:
                    self.check_and_insert_attestation(
                        pubkey, s, t, bytes.fromhex(root)
                    )
                except SlashingError:
                    lost_targets.append(t)
            if lost_targets:
                poisoned = self.atts.get(pubkey) or {}
                poisoned["pruned_below"] = max(
                    poisoned.get("pruned_below", -1), max(lost_targets)
                )
                self.atts.put(pubkey, poisoned)
            rec = self.atts.get(pubkey) or {}
            targets = rec.get("targets", {})
            # the in-flight vote must re-pass the prune gate against the
            # migrated record (pruned_below may have advanced just now)
            if target_epoch <= rec.get("pruned_below", -1):
                raise SlashingError(
                    f"target {target_epoch} below retained history"
                )

        # min-max-surround in O(1): spans answer both directions without
        # consulting (possibly pruned) individual votes
        mins = _SpanStore(self.spans_min, pubkey, rec.get("min_chunks", []))
        maxs = _SpanStore(self.spans_max, pubkey, rec.get("max_chunks", []))
        floor = rec.get("span_floor")
        if floor is not None and source_epoch < floor:
            raise SlashingError(
                f"source {source_epoch} below span floor {floor}: "
                "history unknown, refusing to sign"
            )
        # wide votes (span > lookback) are kept verbatim: the bounded span
        # walks cannot encode them, and they only arise in extreme
        # non-finality, so a direct scan over the handful of them is exact
        wide = [tuple(w) for w in rec.get("wide", [])]
        for ws, wt in wide:
            if ws < source_epoch and target_epoch < wt:
                raise SlashingError(f"surrounded by wide vote ({ws},{wt})")
            if source_epoch < ws and wt < target_epoch:
                raise SlashingError(f"surrounding wide vote ({ws},{wt})")
        d_max = maxs.get(source_epoch)
        if d_max is not None and source_epoch + d_max > target_epoch:
            raise SlashingError(
                f"surrounded by a recorded vote reaching target "
                f"{source_epoch + d_max}"
            )
        d_min = mins.get(source_epoch)
        if d_min is not None and source_epoch + d_min < target_epoch:
            raise SlashingError(
                f"surrounding a recorded vote with target {source_epoch + d_min}"
            )

        # record: exact-root window (bounded, tracks its prune floor) …
        targets[str(target_epoch)] = {
            "source": source_epoch,
            "root": signing_root.hex(),
        }
        pruned_below = rec.get("pruned_below", -1)
        if len(targets) > 512:
            drop = sorted(targets, key=int)[: len(targets) - 512]
            pruned_below = max(pruned_below, int(drop[-1]))
            for k in drop:
                del targets[k]
        # … and the spans (reference update loops with the monotonicity
        # early break: stop at the first epoch whose stored span already
        # dominates — see minMaxSurround.ts updateMinSpan/updateMaxSpan).
        # BOTH walks are bounded by the lookback; a vote too wide for the
        # max walk goes on the wide list instead, so nothing is silently
        # dropped.
        lo_bound = max(0, source_epoch - self.max_epoch_lookback)
        for e in range(source_epoch - 1, lo_bound - 1, -1):
            d = mins.get(e)
            new = target_epoch - e
            if d is not None and d <= new:
                break
            mins.set(e, new)
        hi_bound = min(target_epoch, source_epoch + 1 + self.max_epoch_lookback)
        for e in range(source_epoch + 1, hi_bound):
            d = maxs.get(e)
            new = target_epoch - e
            if d is not None and d >= new:
                break
            maxs.set(e, new)
        if target_epoch - source_epoch > self.max_epoch_lookback:
            wide.append((source_epoch, target_epoch))
            # drop wide votes made redundant by the new one (surrounded
            # wide votes can never trigger again once a wider one exists)
            wide = [
                (ws, wt)
                for ws, wt in wide
                if not (source_epoch < ws and wt < target_epoch)
            ]

        max_target = max(target_epoch, rec.get("max_target", -1))
        new_floor = max(0, max_target - self.max_epoch_lookback)
        if floor is None:
            floor = lo_bound
        if new_floor > floor:
            mins.prune_below(new_floor)
            maxs.prune_below(new_floor)
            floor = new_floor

        mins.flush()
        maxs.flush()
        self.atts.put(
            pubkey,
            {
                "targets": targets,
                "pruned_below": pruned_below,
                "max_target": max_target,
                "min_source": min(source_epoch, rec.get("min_source", source_epoch)),
                "span_floor": floor,
                "min_chunks": sorted(mins.chunk_ids),
                "max_chunks": sorted(maxs.chunk_ids),
                "wide": [list(w) for w in wide],
            },
        )

    # -- EIP-3076 interchange ------------------------------------------------

    def export_interchange(self, genesis_validators_root: bytes, pubkeys) -> dict:
        data = []
        for pk in pubkeys:
            blocks_rec = self.blocks.get(pk) or {}
            atts_rec = self.atts.get(pk) or {}
            data.append(
                {
                    "pubkey": "0x" + pk.hex(),
                    "signed_blocks": [
                        {"slot": str(s), "signing_root": "0x" + r}
                        for s, r in sorted(
                            blocks_rec.get("roots", {}).items(), key=lambda kv: int(kv[0])
                        )
                    ],
                    "signed_attestations": [
                        {
                            "source_epoch": str(v["source"]),
                            "target_epoch": t,
                            "signing_root": "0x" + v["root"],
                        }
                        for t, v in sorted(
                            atts_rec.get("targets", {}).items(), key=lambda kv: int(kv[0])
                        )
                    ],
                }
            )
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, obj: dict) -> None:
        for entry in obj.get("data", []):
            pk = bytes.fromhex(entry["pubkey"][2:])
            for b in entry.get("signed_blocks", []):
                try:
                    self.check_and_insert_block_proposal(
                        pk,
                        int(b["slot"]),
                        bytes.fromhex(b.get("signing_root", "0x")[2:] or "00"),
                    )
                except SlashingError:
                    continue
            for a in entry.get("signed_attestations", []):
                try:
                    self.check_and_insert_attestation(
                        pk,
                        int(a["source_epoch"]),
                        int(a["target_epoch"]),
                        bytes.fromhex(a.get("signing_root", "0x")[2:] or "00"),
                    )
                except SlashingError:
                    continue
