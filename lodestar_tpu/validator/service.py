"""Validator duty services: per-slot block proposal + attestation duties.

Reference: `validator/src/services/` — `AttestationDutiesService` (epoch
duty discovery), `AttestationService` (produce/sign/publish at slot/3,
aggregate at 2·slot/3), `BlockProposingService`. The `api` parameter is
anything exposing the in-process beacon-api surface (`BeaconChain` today,
a REST client later — same methods)."""

from __future__ import annotations

from dataclasses import dataclass

from ..bls import api as bls
from .store import ValidatorStore


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    committee_index: int
    committee_length: int
    slot: int


@dataclass
class ProposerDuty:
    pubkey: bytes
    validator_index: int
    slot: int


class ValidatorService:
    def __init__(self, config, types, chain, store: ValidatorStore, metrics=None):
        self.metrics = metrics
        self.config = config
        self.types = types
        self.chain = chain
        self.store = store
        self._indices: dict[bytes, int] | None = None

    # -- duty discovery (reference attestationDuties/blockDuties) ------------

    def _validator_indices(self) -> dict[bytes, int]:
        if self._indices is None:
            self._indices = {}
        ctx = self.chain.head_state.epoch_ctx
        for pk in self.store.pubkeys:
            if pk not in self._indices:
                idx = ctx.pubkey_to_index.get(pk)
                if idx is not None:
                    self._indices[pk] = idx
        return self._indices

    def get_attester_duties(self, epoch: int) -> list[AttesterDuty]:
        state = self.chain.head_state
        ctx = state.epoch_ctx
        indices = self._validator_indices()
        by_index = {v: k for k, v in indices.items()}
        duties = []
        spe = self.config.preset.SLOTS_PER_EPOCH
        start = epoch * spe
        for slot in range(start, start + spe):
            for cidx in range(ctx.get_committee_count_per_slot(epoch)):
                committee = ctx.get_beacon_committee(slot, cidx)
                for pos, vidx in enumerate(committee):
                    pk = by_index.get(int(vidx))
                    if pk is not None:
                        duties.append(
                            AttesterDuty(
                                pubkey=pk,
                                validator_index=int(vidx),
                                committee_index=cidx,
                                committee_length=len(committee),
                                slot=slot,
                            )
                        )
        return duties

    def get_proposer_duties(self, epoch: int) -> list[ProposerDuty]:
        ctx = self.chain.head_state.epoch_ctx
        if epoch != ctx.current_epoch:
            raise ValueError("proposer duties only for the current epoch")
        indices = self._validator_indices()
        by_index = {v: k for k, v in indices.items()}
        spe = self.config.preset.SLOTS_PER_EPOCH
        out = []
        for i, proposer in enumerate(ctx.proposers):
            pk = by_index.get(proposer)
            if pk is not None:
                out.append(
                    ProposerDuty(
                        pubkey=pk, validator_index=proposer, slot=epoch * spe + i
                    )
                )
        return out

    # -- per-slot work (reference attestation.ts / block.ts services) --------

    def propose_block_if_due(self, slot: int):
        """If one of our validators proposes at `slot`, produce + sign +
        import the block. Returns the signed block or None."""
        from ..state_transition import process_slots

        trial = self.chain.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, self.types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        by_index = {v: k for k, v in self._validator_indices().items()}
        pk = by_index.get(proposer)
        if pk is None:
            return None
        import time as _t

        _t0 = _t.monotonic()
        reveal = self.store.sign_randao(pk, slot)
        block = self.chain.produce_block(slot, randao_reveal=reveal)
        signed = self.store.sign_block(pk, self.types, block)
        if self.metrics is not None:
            self.metrics.vc_signer_seconds.observe(
                _t.monotonic() - _t0, kind="block"
            )
        try:
            self.chain.process_block(signed)
        except Exception:
            if self.metrics is not None:
                self.metrics.vc_duties_total.inc(kind="block", outcome="error")
            raise
        if self.metrics is not None:
            self.metrics.vc_duties_total.inc(kind="block", outcome="published")
        return signed

    def attest_if_due(self, slot: int) -> list:
        """Produce + sign + publish attestations for all our duties at
        `slot` (head vote at slot/3 semantics; here: after head update)."""
        state = self.chain.head_state
        ctx = state.epoch_ctx
        epoch = slot // self.config.preset.SLOTS_PER_EPOCH
        spe = self.config.preset.SLOTS_PER_EPOCH
        start = epoch * spe
        head_root = self.chain.head_root
        if start == slot:
            target_root = head_root
        else:
            target_root = bytes(
                state.state.block_roots[
                    start % self.config.preset.SLOTS_PER_HISTORICAL_ROOT
                ]
            )
        indices = self._validator_indices()
        produced = []
        for cidx in range(ctx.get_committee_count_per_slot(epoch)):
            committee = ctx.get_beacon_committee(slot, cidx)
            members = {int(v): pos for pos, v in enumerate(committee)}
            ours = [
                (pk, idx) for pk, idx in indices.items() if idx in members
            ]
            if not ours:
                continue
            data = self.types.AttestationData(
                slot=slot,
                index=cidx,
                beacon_block_root=head_root,
                source=state.state.current_justified_checkpoint.copy(),
                target=self.types.Checkpoint(epoch=epoch, root=target_root),
            )
            sigs = []
            bits = [False] * len(committee)
            for pk, idx in ours:
                import time as _t

                _t0 = _t.monotonic()
                sig = self.store.sign_attestation(pk, data)
                if self.metrics is not None:
                    self.metrics.vc_signer_seconds.observe(
                        _t.monotonic() - _t0, kind="attestation"
                    )
                    self.metrics.vc_duties_total.inc(
                        kind="attestation", outcome="signed"
                    )
                sigs.append(bls.Signature.from_bytes(sig, validate=False))
                bits[members[idx]] = True
            att = self.types.Attestation(
                aggregation_bits=bits,
                data=data,
                signature=bls.aggregate_signatures(sigs).to_bytes(),
            )
            self.chain.on_aggregated_attestation(att, data.hash_tree_root())
            produced.append(att)
        return produced

    def aggregate_if_due(self, slot: int, attestations: list) -> list:
        """Build SignedAggregateAndProof for every duty where one of our
        validators is the selected aggregator of its committee (reference
        AttestationService aggregation phase at 2·slot/3)."""
        state = self.chain.head_state
        ctx = state.epoch_ctx
        epoch = slot // self.config.preset.SLOTS_PER_EPOCH
        indices = self._validator_indices()
        by_committee = {
            (int(a.data.slot), int(a.data.index)): a for a in attestations
        }
        out = []
        for cidx in range(ctx.get_committee_count_per_slot(epoch)):
            committee = [int(v) for v in ctx.get_beacon_committee(slot, cidx)]
            ours = [(pk, idx) for pk, idx in indices.items() if idx in committee]
            for pk, idx in ours:
                # the selection proof doubles as the aggregator lottery
                # ticket — sign once, reuse for the check and the envelope
                proof = self.store.sign_selection_proof(pk, slot)
                if not self.store.is_aggregator(slot, len(committee), pk, proof=proof):
                    continue
                agg = by_committee.get((slot, cidx))
                if agg is None:
                    continue
                agg_and_proof = self.types.AggregateAndProof(
                    aggregator_index=idx,
                    aggregate=agg.copy(),
                    selection_proof=proof,
                )
                out.append(
                    self.store.sign_aggregate_and_proof(pk, self.types, agg_and_proof)
                )
        return out
