"""Validator client (SURVEY.md §2.1 `validator` package).

Reference surface: `Validator` (validator.ts:53), `ValidatorStore` with
slashing-protection-gated signing (`services/validatorStore.ts:307+`),
duty services (attestationDuties.ts / attestation.ts / block.ts),
EIP-3076 slashing protection (`slashingProtection/`).

The transport here is in-process against a `BeaconChain` (the REST client
indirection arrives with the api package); signing and protection logic is
transport-independent.
"""

from .store import ValidatorStore  # noqa: F401
from .slashing_protection import SlashingProtection, SlashingError  # noqa: F401
from .service import ValidatorService  # noqa: F401
from .rest_service import RestValidatorService  # noqa: F401
from .doppelganger import DoppelgangerService, DoppelgangerStatus  # noqa: F401
from .external_signer import ExternalSignerClient, ExternalSignerServer  # noqa: F401
