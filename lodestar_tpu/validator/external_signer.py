"""External (remote) signer client + a minimal in-process signer server.

Reference: `validator/src/util/externalSignerClient.ts` — the web3signer
HTTP API: `GET /api/v1/eth2/publicKeys`, `POST /api/v1/eth2/sign/{pubkey}`
with a signing-root payload, returning `{"signature": "0x..."}`.
The bundled `ExternalSignerServer` plays the web3signer role for e2e tests
(reference e2e runs a real web3signer container).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..bls import api as bls


class ExternalSignerError(Exception):
    pass


class ExternalSignerClient:
    """Blocking HTTP client to a web3signer-compatible endpoint."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 retries: int = 2):
        self.host = host
        self.port = port
        self.timeout = timeout
        # transport blips to the signer retry through utils/retry (signing
        # is idempotent: same root -> same signature); HTTP error replies
        # (unknown pubkey, slashing-protection refusal) never do
        self.retries = retries

    def _request(self, method: str, path: str, body=None):
        from ..utils.http import json_http_request

        return json_http_request(
            self.host, self.port, method, path, body,
            timeout=self.timeout, error_cls=ExternalSignerError,
            retries=self.retries,
        )

    def list_pubkeys(self) -> list[bytes]:
        keys = self._request("GET", "/api/v1/eth2/publicKeys") or []
        return [bytes.fromhex(k.removeprefix("0x")) for k in keys]

    def sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        result = self._request(
            "POST",
            f"/api/v1/eth2/sign/0x{pubkey.hex()}",
            {"signingRoot": "0x" + signing_root.hex()},
        )
        return bytes.fromhex(result["signature"].removeprefix("0x"))

    def upcheck(self) -> bool:
        try:
            return self._request("GET", "/upcheck") is not None
        except Exception:
            return False


class ExternalSignerServer:
    """In-process web3signer-compatible server over a set of secret keys."""

    def __init__(self, secret_keys: list[bls.SecretKey], host: str = "127.0.0.1", port: int = 0):
        self._keys = {sk.to_public_key().to_bytes(): sk for sk in secret_keys}
        keys = self._keys

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, status: int, obj) -> None:
                raw = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                if self.path == "/upcheck":
                    self._send(200, {"status": "OK"})
                elif self.path == "/api/v1/eth2/publicKeys":
                    self._send(200, ["0x" + pk.hex() for pk in keys])
                else:
                    self._send(404, {"message": "not found"})

            def do_POST(self):
                if not self.path.startswith("/api/v1/eth2/sign/"):
                    return self._send(404, {"message": "not found"})
                pk_hex = self.path.rsplit("/", 1)[-1].removeprefix("0x")
                sk = keys.get(bytes.fromhex(pk_hex))
                if sk is None:
                    return self._send(404, {"message": "unknown pubkey"})
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length))
                root = bytes.fromhex(body["signingRoot"].removeprefix("0x"))
                self._send(200, {"signature": "0x" + sk.sign(root).to_bytes().hex()})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
