"""EIP-2335 BLS keystores (encrypt/decrypt) + keystore directory loading.

Reference: the CLI's keystore management (`cli/src/cmds/validator` import
flows via @chainsafe/bls-keystore) — scrypt or pbkdf2 KDF, AES-128-CTR
cipher, sha256 checksum. Round-trips with web3signer/eth2.0-deposit-cli
keystores.
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import unicodedata
import uuid

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

from ..bls import api as bls


class KeystoreError(Exception):
    pass


def _normalize_password(password: str) -> bytes:
    """EIP-2335: NFKD normalize, strip C0/C1/Delete control codes."""
    norm = unicodedata.normalize("NFKD", password)
    return "".join(c for c in norm if unicodedata.category(c) != "Cc").encode()


def _derive_key(kdf: dict, password: bytes) -> bytes:
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(
            password,
            salt=salt,
            n=params["n"],
            r=params["r"],
            p=params["p"],
            dklen=params["dklen"],
            maxmem=2**31 - 1,
        )
    if kdf["function"] == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            "sha256", password, salt, params["c"], dklen=params["dklen"]
        )
    raise KeystoreError(f"unsupported kdf {kdf['function']}")


def _aes128ctr(key16: bytes, iv: bytes, data: bytes) -> bytes:
    cipher = Cipher(algorithms.AES(key16), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


def decrypt_keystore(keystore: dict, password: str) -> bytes:
    """→ the 32-byte BLS secret scalar."""
    crypto = keystore["crypto"]
    dk = _derive_key(crypto["kdf"], _normalize_password(password))
    ciphertext = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise KeystoreError("wrong password (checksum mismatch)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return _aes128ctr(dk[:16], iv, ciphertext)


def encrypt_keystore(
    secret: bytes, password: str, path: str = "", kdf: str = "pbkdf2"
) -> dict:
    """EIP-2335 JSON for a 32-byte secret (pbkdf2 default: fast enough for
    tests; scrypt for production-grade)."""
    salt = secrets.token_bytes(32)
    if kdf == "scrypt":
        kdf_module = {
            "function": "scrypt",
            "params": {"dklen": 32, "n": 262144, "r": 8, "p": 1, "salt": salt.hex()},
            "message": "",
        }
    else:
        kdf_module = {
            "function": "pbkdf2",
            "params": {"dklen": 32, "c": 262144, "prf": "hmac-sha256", "salt": salt.hex()},
            "message": "",
        }
    dk = _derive_key(kdf_module, _normalize_password(password))
    iv = secrets.token_bytes(16)
    ciphertext = _aes128ctr(dk[:16], iv, secret)
    sk = bls.SecretKey.from_bytes(secret)
    return {
        "crypto": {
            "kdf": kdf_module,
            "checksum": {
                "function": "sha256",
                "params": {},
                "message": hashlib.sha256(dk[16:32] + ciphertext).digest().hex(),
            },
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": ciphertext.hex(),
            },
        },
        "path": path,
        "pubkey": sk.to_public_key().to_bytes().hex(),
        "uuid": str(uuid.uuid4()),
        "version": 4,
    }


def load_keystores_dir(directory: str, password: str) -> list[bls.SecretKey]:
    """Import every keystore-*.json under `directory` (reference: keystore
    import flow, one shared password file)."""
    out = []
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(directory, name)) as f:
            ks = json.load(f)
        if "crypto" not in ks:
            continue
        secret = decrypt_keystore(ks, password)
        sk = bls.SecretKey.from_bytes(secret)
        expected = ks.get("pubkey")
        if expected and sk.to_public_key().to_bytes().hex() != expected:
            raise KeystoreError(f"{name}: pubkey mismatch after decrypt")
        out.append(sk)
    return out
