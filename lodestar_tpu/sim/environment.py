"""In-process multi-node simulation over real TCP/UDP networking.

Each simulated node is a full vertical: BeaconChain + Network (secure
transport, gossipsub mesh, req/resp, discovery) + ValidatorService with
its share of the interop keys. Blocks travel ONLY via gossip (the
proposer's node publishes; every other node imports through the gossip
validation pipeline), aggregates travel on the aggregate topic, so a
finalizing run proves the whole stack end-to-end.

Signature verification defaults to MockBlsVerifier (reference sims use
real blst through native code; the pure-Python oracle at ~1s/pairing
would make a 4-node × 4-epoch sim take hours). `verifier="device"`
swaps in the REAL device batch verifier (VERDICT round-1 weak #5: the
flagship component exercised in the end-to-end loop) — used by the
slow-marked sim test on the virtual CPU mesh with small buckets.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from ..bls import api as bls
from ..chain import BeaconChain
from ..chain.bls_verifier import MockBlsVerifier
from ..config.beacon_config import BeaconConfig, ChainForkConfig
from ..config.chain_config import MINIMAL_CHAIN_CONFIG
from ..db.controller import MemoryDb
from ..params.presets import MINIMAL
from ..state_transition import interop_genesis_state
from ..types import get_types
from ..utils.logger import get_logger
from ..validator.service import ValidatorService
from ..validator.slashing_protection import SlashingProtection
from ..validator.store import ValidatorStore
from ..network.network import Network
from ..network.transport import NodeIdentity

log = get_logger("sim")


@dataclass
class EpochReport:
    epoch: int
    missed_blocks: int = 0
    head_roots: set = field(default_factory=set)
    finalized_epochs: list[int] = field(default_factory=list)
    participation: float = 0.0


@dataclass
class SimNode:
    index: int
    chain: BeaconChain
    network: Network
    validators: ValidatorService
    key_range: range


class SimulationEnvironment:
    """N beacon nodes × M total validators, keys striped across nodes."""

    def __init__(self, n_nodes: int = 4, n_validators: int = 32,
                 verifier: str = "mock"):
        self.n_nodes = n_nodes
        self.n_validators = n_validators
        self.verifier_kind = verifier
        types = get_types(MINIMAL).phase0
        fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
        state = interop_genesis_state(
            fork_config, types, n_validators, genesis_time=1_600_000_000
        )
        self.config = BeaconConfig(
            MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
        )
        self.types = types
        self.genesis_state = state
        self.nodes: list[SimNode] = []
        self.reports: list[EpochReport] = []
        self.blocks_produced = 0

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        per_node = self.n_validators // self.n_nodes
        for i in range(self.n_nodes):
            if self.verifier_kind == "device":
                from ..chain.bls_verifier import DeviceBlsVerifier

                verifier = DeviceBlsVerifier(buckets=(4, 8))
            elif self.verifier_kind == "cpu":
                # real verification on the native C pairing — fast enough
                # (~7 ms/set) for multi-node finalizing sims, unlike the
                # big-int oracle it replaced (round-3)
                from ..chain.bls_verifier import CpuBlsVerifier

                verifier = CpuBlsVerifier()
            else:
                verifier = MockBlsVerifier()
            chain = BeaconChain(
                self.config,
                self.types,
                self.genesis_state.copy(),
                verifier=verifier,
            )
            network = Network(
                self.config,
                self.types,
                chain,
                identity=NodeIdentity.from_seed(b"sim" + bytes([i])),
                verify_signatures=self.verifier_kind != "mock",
            )
            store = ValidatorStore(self.config, SlashingProtection(MemoryDb()))
            key_range = range(i * per_node, (i + 1) * per_node)
            for k in key_range:
                store.add_secret_key(bls.interop_secret_key(k))
            service = ValidatorService(self.config, self.types, chain, store)
            self.nodes.append(SimNode(i, chain, network, service, key_range))

        # boot networking: node 0 is the bootnode
        await self.nodes[0].network.start(discovery=True)
        boot = [self.nodes[0].network.discovery.local_enr]
        for node in self.nodes[1:]:
            await node.network.start(discovery=True, bootnodes=boot)
        for node in self.nodes:
            await node.network.discovery.lookup(node.network.peer_id)
        # let meshes converge
        for _ in range(4):
            await asyncio.sleep(0.05)
            for node in self.nodes:
                await node.network.gossip.heartbeat()

    async def stop(self) -> None:
        for node in self.nodes:
            await node.network.stop()

    # -- slot loop -----------------------------------------------------------

    async def run_slot(self, slot: int) -> None:
        spe = self.config.preset.SLOTS_PER_EPOCH
        for node in self.nodes:
            node.chain.clock.set_slot(slot)
            node.chain.fork_choice.update_time(slot)

        # 1. proposal: exactly one node's validator has the duty; the
        # service imports into its own chain, the network gossips the block
        for node in self.nodes:
            signed = node.validators.propose_block_if_due(slot)
            if signed is not None:
                self.blocks_produced += 1
                await node.network.publish_block(signed)
                break

        # 2. give gossip a beat to deliver the block everywhere
        await self._settle()

        # 3. attestations: every node's validators attest to their head;
        # aggregates travel on the aggregate topic
        for node in self.nodes:
            atts = node.validators.attest_if_due(slot)
            for signed_agg in node.validators.aggregate_if_due(slot, atts):
                await node.network.publish_aggregate(signed_agg)
        await self._settle()

        # report at the first slot of the next epoch: the boundary
        # transition (justification/finality updates) has been processed by
        # this slot's block import
        if slot % spe == 0:
            self._report_epoch(slot // spe - 1)

    async def run_epochs(self, n_epochs: int) -> None:
        spe = self.config.preset.SLOTS_PER_EPOCH
        start = self.nodes[0].chain.head_state.state.slot
        for slot in range(start + 1, start + n_epochs * spe + 1):
            await self.run_slot(slot)

    async def _settle(self, rounds: int = 20) -> None:
        """Drain gossip queues/inboxes (no wall-clock slot pacing in sim)."""
        for _ in range(rounds):
            await asyncio.sleep(0)
        await asyncio.sleep(0.05)

    # -- assertions ----------------------------------------------------------

    def _report_epoch(self, epoch: int) -> None:
        spe = self.config.preset.SLOTS_PER_EPOCH
        report = EpochReport(epoch=epoch)
        # reported at slot (epoch+1)*spe: proposals expected for every slot
        # 1..here (genesis slot 0 has none)
        report.missed_blocks = (epoch + 1) * spe - self.blocks_produced
        for node in self.nodes:
            report.head_roots.add(node.chain.head_root)
            report.finalized_epochs.append(node.chain.finalized_checkpoint[0])
        # participation: unique attesters of the just-rotated epoch over the
        # validator set (phase0 pending-attestation coverage on node 0)
        head = self.nodes[0].chain.head_state
        attesters: set[int] = set()
        for pa in head.state.previous_epoch_attestations:
            committee = head.epoch_ctx.get_beacon_committee(
                int(pa.data.slot), int(pa.data.index)
            )
            for pos, bit in enumerate(pa.aggregation_bits):
                if bit:
                    attesters.add(int(committee[pos]))
        report.participation = len(attesters) / max(1, len(head.state.validators))
        self.reports.append(report)
        log.info(
            "epoch %d: missed=%d heads=%d finalized=%s",
            epoch,
            report.missed_blocks,
            len(report.head_roots),
            report.finalized_epochs,
        )


class SimulationAssertions:
    """The per-epoch invariants the reference sim asserts
    (`simulation.test.ts`: missed blocks, participation, finality, heads)."""

    @staticmethod
    def assert_no_missed_blocks(env: SimulationEnvironment) -> None:
        for report in env.reports:
            assert report.missed_blocks == 0, (
                f"epoch {report.epoch}: {report.missed_blocks} missed blocks"
            )

    @staticmethod
    def assert_heads_consistent(env: SimulationEnvironment) -> None:
        for report in env.reports:
            assert len(report.head_roots) == 1, (
                f"epoch {report.epoch}: {len(report.head_roots)} distinct heads"
            )

    @staticmethod
    def assert_finalization(env: SimulationEnvironment, min_final: int) -> None:
        last = env.reports[-1]
        for i, fin in enumerate(last.finalized_epochs):
            assert fin >= min_final, (
                f"node {i} finalized epoch {fin} < {min_final}"
            )

    @staticmethod
    def assert_participation(env: SimulationEnvironment, minimum: float) -> None:
        for report in env.reports[1:]:
            assert report.participation >= minimum, (
                f"epoch {report.epoch}: participation {report.participation}"
            )
