"""Multi-node simulation harness.

Reference: `cli/test/utils/simulation/` — `SimulationEnvironment` spawns
{N beacon nodes × M validators} in one process over real networking,
runs epochs, and asserts per-epoch liveness invariants (missed blocks,
participation, finality, head consistency) — `simulation.test.ts:18-90`
and `simTestInfoTracker` (`test/utils/node/simTest.ts:20-60`).
"""

from .environment import SimulationEnvironment, SimulationAssertions  # noqa: F401
