"""Key-value DB abstraction with bucket-prefixed keys + typed repositories.

Reference: `packages/db` — `IDatabaseController` over LevelDB
(`controller/level.ts`), `Repository<Id, T>` with SSZ encode/decode
(`abstractRepository.ts`), `Bucket` enum (`schema.ts:5-70`). Backends:
`MemoryDb` (dict-backed; the reference uses one for tests too) and
`FileDb` — an append-only-log + in-memory-index store in the same spirit
as LevelDB's design, pure stdlib.
"""

from .controller import FileDb, IDatabaseController, MemoryDb  # noqa: F401
from .repository import Bucket, Repository  # noqa: F401
from .beacon import BeaconDb  # noqa: F401
