"""Database controllers: ordered KV stores.

`MemoryDb` — sorted-dict semantics over a plain dict (tests, sim).
`FileDb` — crash-tolerant append-only log with periodic compaction and an
in-memory index: the LSM idea of LevelDB reduced to its minimum viable
form in stdlib Python (reference's native leveldown → SURVEY.md §2.3;
a full C++ LSM engine is a later tier — the interface won't change).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Protocol


class IDatabaseController(Protocol):
    def get(self, key: bytes) -> bytes | None: ...
    def put(self, key: bytes, value: bytes) -> None: ...
    def delete(self, key: bytes) -> None: ...
    def batch_put(self, items: list[tuple[bytes, bytes]]) -> None: ...
    def keys_stream(self, gte: bytes, lt: bytes) -> Iterator[bytes]: ...
    def values_stream(self, gte: bytes, lt: bytes) -> Iterator[bytes]: ...
    def close(self) -> None: ...


class MemoryDb:
    def __init__(self):
        self._data: dict[bytes, bytes] = {}

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        self._data[key] = value

    def delete(self, key: bytes) -> None:
        self._data.pop(key, None)

    def batch_put(self, items) -> None:
        for k, v in items:
            self._data[k] = v

    def keys_stream(self, gte: bytes, lt: bytes):
        for k in sorted(self._data):
            if gte <= k < lt:
                yield k

    def values_stream(self, gte: bytes, lt: bytes):
        for k in self.keys_stream(gte, lt):
            yield self._data[k]

    def entries_stream(self, gte: bytes, lt: bytes):
        for k in self.keys_stream(gte, lt):
            yield k, self._data[k]

    def close(self) -> None:
        pass


_REC = struct.Struct("<BII")  # op, key_len, value_len


class FileDb(MemoryDb):
    """Append-only log + in-memory index. Every put/delete appends a
    record; open() replays the log; compact() rewrites it. Durable across
    restarts (fsync on batch boundaries)."""

    COMPACT_WASTE_RATIO = 4

    def __init__(self, path: str):
        self.metrics = None  # set by the node for compaction counters
        super().__init__()
        self.path = path
        self._ops = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            self._replay()
        self._fh = open(path, "ab")

    def _replay(self) -> None:
        with open(self.path, "rb") as fh:
            while True:
                head = fh.read(_REC.size)
                if len(head) < _REC.size:
                    break
                op, klen, vlen = _REC.unpack(head)
                key = fh.read(klen)
                value = fh.read(vlen)
                if len(key) < klen or len(value) < vlen:
                    break  # torn tail record: ignore (crash tolerance)
                if op == 0:
                    self._data[key] = value
                else:
                    self._data.pop(key, None)
                self._ops += 1

    def _append(self, op: int, key: bytes, value: bytes) -> None:
        self._fh.write(_REC.pack(op, len(key), len(value)))
        self._fh.write(key)
        self._fh.write(value)
        self._ops += 1

    def put(self, key: bytes, value: bytes) -> None:
        super().put(key, value)
        self._append(0, key, value)
        self._fh.flush()

    def delete(self, key: bytes) -> None:
        super().delete(key)
        self._append(1, key, b"")
        self._fh.flush()

    def batch_put(self, items) -> None:
        for k, v in items:
            super().put(k, v)
            self._append(0, k, v)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        if self._ops > self.COMPACT_WASTE_RATIO * max(64, len(self._data)):
            self.compact()
            m = getattr(self, "metrics", None)
            if m is not None:
                m.db_compactions_total.inc()

    def compact(self) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as fh:
            for k, v in self._data.items():
                fh.write(_REC.pack(0, len(k), len(v)))
                fh.write(k)
                fh.write(v)
            fh.flush()
            os.fsync(fh.fileno())
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "ab")
        self._ops = len(self._data)

    def close(self) -> None:
        self._fh.close()


class NativeKvDb:
    """IDatabaseController over the native C storage engine
    (`native/src/kvstore.c` — the leveldown/LevelDB-class tier,
    SURVEY.md §2.3). Values live on disk; only the key index is in
    memory, so datadirs can exceed process memory. Crash-tolerant
    (CRC-framed records, torn tails dropped on replay), batched writes
    fsync once, dead space reclaimed by compaction.

    Thread-safe: one lock serializes writers (the engine itself is
    single-writer by design).
    """

    def __init__(self, path: str):
        import threading

        from .. import native

        if not native.HAVE_NATIVE or not hasattr(native._mod, "kv_open"):
            raise RuntimeError(
                "native KV engine unavailable (no C toolchain?) — "
                "use FileDb for pure-Python persistence"
            )
        self._mod = native._mod
        self._h = self._mod.kv_open(path)
        self._lock = threading.Lock()
        self.path = path

    # NOTE: the C engine mutates its index with the GIL released, so
    # READERS take the same lock as writers (round-2 review: a reader
    # racing kv_grow/compact would use-after-free).

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._mod.kv_get(self._h, key)

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._mod.kv_put(self._h, key, value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._mod.kv_delete(self._h, key)

    def batch_put(self, items) -> None:
        with self._lock:
            self._mod.kv_batch_put(self._h, [(bytes(k), bytes(v)) for k, v in items])
            self._mod.kv_compact(self._h)  # no-op below the dead-ratio gate

    def keys_stream(self, gte: bytes, lt: bytes):
        with self._lock:
            keys = self._mod.kv_keys_range(self._h, gte, lt)
        yield from keys

    def values_stream(self, gte: bytes, lt: bytes):
        for _, v in self.entries_stream(gte, lt):
            yield v

    def entries_stream(self, gte: bytes, lt: bytes):
        with self._lock:
            keys = self._mod.kv_keys_range(self._h, gte, lt)
        for k in keys:
            with self._lock:
                v = self._mod.kv_get(self._h, k)
            if v is not None:
                yield k, v

    def stats(self) -> dict:
        with self._lock:
            count, live, dead, seg = self._mod.kv_stats(self._h)
        return {
            "entries": count,
            "live_bytes": live,
            "dead_bytes": dead,
            "active_segment": seg,
        }

    def compact(self) -> None:
        with self._lock:
            self._mod.kv_compact(self._h, 1)

    def close(self) -> None:
        with self._lock:
            self._h = None  # capsule destructor closes + fsyncs
