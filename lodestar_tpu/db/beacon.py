"""BeaconDb: the typed repositories a beacon node persists.

Reference: `beacon-node/src/db/beacon.ts` + `db/repositories/` — block,
blockArchive (slot-indexed with root indices), stateArchive, eth1 data,
light-client buckets."""

from __future__ import annotations

from .controller import IDatabaseController, MemoryDb
from .repository import Bucket, Repository


class BeaconDb:
    def __init__(self, types, db: IDatabaseController | None = None):
        self.db = db if db is not None else MemoryDb()
        t = types
        # hot blocks by root
        self.block = Repository(self.db, Bucket.allForks_block, t.SignedBeaconBlock.ssz_type)
        # finalized blocks by slot (ordered) + root→slot index
        self.block_archive = Repository(
            self.db, Bucket.allForks_blockArchive, t.SignedBeaconBlock.ssz_type
        )
        self._block_archive_root_index = Repository(
            self.db, Bucket.index_blockArchiveRootIndex, _BytesType()
        )
        # finalized states by slot
        self.state_archive = Repository(
            self.db, Bucket.allForks_stateArchive, t.BeaconState.ssz_type
        )
        self.eth1_data = Repository(self.db, Bucket.phase0_eth1Data, t.Eth1Data.ssz_type)

    # -- block archive helpers (reference blockArchive repo dual-index) ------

    def archive_block(self, signed_block) -> None:
        slot_key = Repository.slot_key(signed_block.message.slot)
        self.block_archive.put(slot_key, signed_block)
        self._block_archive_root_index.put(
            signed_block.message.hash_tree_root(), slot_key
        )

    def get_archived_block_by_root(self, root: bytes):
        slot_key = self._block_archive_root_index.get(root)
        if slot_key is None:
            return None
        return self.block_archive.get(slot_key)

    def close(self) -> None:
        self.db.close()


class _BytesType:
    @staticmethod
    def serialize(v: bytes) -> bytes:
        return v

    @staticmethod
    def deserialize(v: bytes) -> bytes:
        return v
