"""Bucket-prefixed typed repositories.

Reference: `db/src/schema.ts:5-70` (Bucket enum — numeric prefixes
namespacing each repository inside one KV store) + `abstractRepository.ts`
(`Repository<Id, T>`: SSZ encode/decode at the boundary, batch ops, key
streaming)."""

from __future__ import annotations

from enum import IntEnum
from typing import Generic, Iterator, TypeVar

T = TypeVar("T")


class Bucket(IntEnum):
    # mirrors the reference's bucket ids where meaningful (schema.ts)
    allForks_stateArchive = 0
    allForks_block = 1
    allForks_blockArchive = 2
    index_blockArchiveParentRootIndex = 3
    index_blockArchiveRootIndex = 4
    phase0_eth1Data = 6
    index_depositDataRoot = 7
    phase0_depositEvent = 8
    phase0_preGenesisState = 30
    phase0_preGenesisStateLastProcessedBlock = 31
    # validator / slashing protection (20-24 reference range)
    validator_metaData = 41
    validator_slashingProtectionBlockBySlot = 20
    validator_slashingProtectionAttestationByTarget = 21
    validator_slashingProtectionAttestationLowerBound = 22
    validator_slashingProtectionMinSpanDistance = 23
    validator_slashingProtectionMaxSpanDistance = 24
    # light client server
    lightClient_syncCommitteeWitness = 51
    lightClient_syncCommittee = 52
    lightClient_checkpointHeader = 54
    lightClient_bestLightClientUpdate = 55
    backfilled_ranges = 42


def _encode_key(bucket: int, key: bytes) -> bytes:
    return bucket.to_bytes(1, "big") + key


class Repository(Generic[T]):
    """SSZ-typed repository over one bucket. `ssz_type` must expose
    serialize/deserialize (any SSZType); ids are raw bytes (roots) or
    uint64-BE slots for ordered range scans."""

    # class-level op counters by (bucket, op) — the reference records
    # per-repository db operation metrics (db pkg "per-op metrics") that
    # feed lodestar_db_* families; exposed via snapshot_op_metrics()
    _op_counts: dict[tuple[int, str], int] = {}

    def __init__(self, db, bucket: Bucket, ssz_type):
        self.db = db
        self.bucket = int(bucket)
        self.type = ssz_type

    def _count(self, op: str) -> None:
        key = (self.bucket, op)
        Repository._op_counts[key] = Repository._op_counts.get(key, 0) + 1

    @classmethod
    def snapshot_op_metrics(cls) -> dict[tuple[int, str], int]:
        return dict(cls._op_counts)

    # -- keys ----------------------------------------------------------------

    def _key(self, id_: bytes) -> bytes:
        return _encode_key(self.bucket, id_)

    @staticmethod
    def slot_key(slot: int) -> bytes:
        return slot.to_bytes(8, "big")

    # -- ops -----------------------------------------------------------------

    def get(self, id_: bytes) -> T | None:
        self._count("get")
        raw = self.db.get(self._key(id_))
        return self.type.deserialize(raw) if raw is not None else None

    def get_binary(self, id_: bytes) -> bytes | None:
        return self.db.get(self._key(id_))

    def has(self, id_: bytes) -> bool:
        return self.db.get(self._key(id_)) is not None

    def put(self, id_: bytes, value: T) -> None:
        self._count("put")
        self.db.put(self._key(id_), self.type.serialize(value))

    def put_binary(self, id_: bytes, raw: bytes) -> None:
        self.db.put(self._key(id_), raw)

    def delete(self, id_: bytes) -> None:
        self._count("delete")
        self.db.delete(self._key(id_))

    def batch_put(self, items: list[tuple[bytes, T]]) -> None:
        self._count("batch_put")
        self.db.batch_put(
            [(self._key(i), self.type.serialize(v)) for i, v in items]
        )

    def batch_delete(self, ids: list[bytes]) -> None:
        for i in ids:
            self.delete(i)

    # -- streams -------------------------------------------------------------

    def _range(self) -> tuple[bytes, bytes]:
        return _encode_key(self.bucket, b""), _encode_key(self.bucket + 1, b"")

    def keys_stream(self) -> Iterator[bytes]:
        gte, lt = self._range()
        for k in self.db.keys_stream(gte, lt):
            yield k[1:]

    def values_stream(self) -> Iterator[T]:
        gte, lt = self._range()
        for v in self.db.values_stream(gte, lt):
            yield self.type.deserialize(v)

    def first_key(self) -> bytes | None:
        for k in self.keys_stream():
            return k
        return None

    def last_key(self) -> bytes | None:
        last = None
        for k in self.keys_stream():
            last = k
        return last
