"""Fork schedule derived from a ChainConfig.

Equivalent of /root/reference/packages/config/src/forkConfig/index.ts
(`IForkConfig`): orders forks by activation epoch, answers "which fork is
active at slot/epoch N", and exposes per-fork version/prev-version info.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..params import FAR_FUTURE_EPOCH, ForkName, ForkSeq
from .chain_config import ChainConfig


@dataclass(frozen=True)
class ForkInfo:
    name: str
    seq: ForkSeq
    epoch: int
    version: bytes
    prev_version: bytes
    prev_fork_name: str


class ForkConfig:
    def __init__(self, chain_config: ChainConfig, slots_per_epoch: int):
        cc = chain_config
        self.slots_per_epoch = slots_per_epoch
        entries = [
            (ForkName.phase0, 0, cc.GENESIS_FORK_VERSION),
            (ForkName.altair, cc.ALTAIR_FORK_EPOCH, cc.ALTAIR_FORK_VERSION),
            (ForkName.bellatrix, cc.BELLATRIX_FORK_EPOCH, cc.BELLATRIX_FORK_VERSION),
            (ForkName.capella, cc.CAPELLA_FORK_EPOCH, cc.CAPELLA_FORK_VERSION),
        ]
        forks: dict[str, ForkInfo] = {}
        prev_name, prev_version = ForkName.phase0, cc.GENESIS_FORK_VERSION
        for name, epoch, version in entries:
            forks[name] = ForkInfo(
                name=name,
                seq=ForkSeq[name],
                epoch=epoch,
                version=version,
                prev_version=prev_version,
                prev_fork_name=prev_name,
            )
            if epoch != FAR_FUTURE_EPOCH:
                prev_name, prev_version = name, version
        self.forks = forks
        # Forks ascending by (activation epoch, seq); only scheduled ones.
        self.forks_ascending = sorted(forks.values(), key=lambda f: (f.epoch, f.seq))
        self.forks_descending = list(reversed(self.forks_ascending))

    def get_fork_info(self, name: str) -> ForkInfo:
        return self.forks[name]

    def get_fork_name_at_epoch(self, epoch: int) -> str:
        for fork in self.forks_descending:
            if epoch >= fork.epoch and fork.epoch != FAR_FUTURE_EPOCH:
                return fork.name
        return ForkName.phase0

    def get_fork_name_at_slot(self, slot: int) -> str:
        return self.get_fork_name_at_epoch(slot // self.slots_per_epoch)

    def get_fork_version_at_epoch(self, epoch: int) -> bytes:
        return self.forks[self.get_fork_name_at_epoch(epoch)].version

    def get_scheduled_forks(self) -> list[ForkInfo]:
        return [f for f in self.forks_ascending if f.epoch != FAR_FUTURE_EPOCH]

    def get_active_forks_around_epoch(self, epoch: int, tolerance_epochs: int = 2) -> list[str]:
        """Forks active within ±tolerance of `epoch` — used by the network
        layer to subscribe to both forks' gossip topics around a transition
        (reference: network.ts fork subscription logic)."""
        active: list[str] = []
        for fork in self.get_scheduled_forks():
            if fork.epoch == 0 or fork.epoch <= epoch + tolerance_epochs:
                active.append(fork.name)
        # Keep only the latest fork plus any fork whose transition is nearby.
        result = []
        for i, name in enumerate(active):
            fork = self.forks[name]
            is_last = i == len(active) - 1
            next_fork = self.forks[active[i + 1]] if not is_last else None
            if is_last or (next_fork is not None and epoch < next_fork.epoch + tolerance_epochs):
                result.append(name)
        return result
