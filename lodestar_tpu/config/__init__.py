"""Runtime config (layer L1) — equivalent of @lodestar/config."""

from .beacon_config import (  # noqa: F401
    BeaconConfig,
    ChainForkConfig,
    compute_domain,
    compute_fork_data_root,
    compute_fork_digest,
    compute_signing_root,
    create_beacon_config,
    create_chain_fork_config,
    get_network_config,
)
from .chain_config import (  # noqa: F401
    MAINNET_CHAIN_CONFIG,
    MINIMAL_CHAIN_CONFIG,
    NETWORK_CONFIGS,
    ChainConfig,
)
from .fork_config import ForkConfig, ForkInfo  # noqa: F401
