"""BeaconConfig = chain config + fork schedule + cached signing domains.

Equivalent of /root/reference/packages/config/src/beaconConfig.ts
(`createIBeaconConfig`): binds a ChainConfig + preset to a
``genesis_validators_root`` and precomputes the signing domain for every
(fork, domain_type) pair, since domain computation involves hashing
(`getDomain`, config/src/forkConfig + domain cache).
"""

from __future__ import annotations

from hashlib import sha256

from ..params import ACTIVE_PRESET, PRESETS, Preset
from .chain_config import ChainConfig, NETWORK_CONFIGS
from .fork_config import ForkConfig


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    """hash_tree_root(ForkData(current_version, genesis_validators_root)).

    ForkData is a 2-field container of (Bytes4, Bytes32): its root is the hash
    of the two 32-byte chunks (version right-padded).
    """
    chunk0 = current_version + b"\x00" * 28
    return sha256(chunk0 + genesis_validators_root).digest()


def compute_fork_digest(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(
    domain_type: bytes,
    fork_version: bytes,
    genesis_validators_root: bytes,
) -> bytes:
    fork_data_root = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + fork_data_root[:28]


def compute_signing_root(object_root: bytes, domain: bytes) -> bytes:
    """hash_tree_root(SigningData(object_root, domain)) — the message actually
    signed by every BLS signature in the protocol."""
    return sha256(object_root + domain).digest()


class ChainForkConfig(ForkConfig):
    """ChainConfig + preset + fork schedule (reference `IChainForkConfig`)."""

    def __init__(self, chain_config: ChainConfig, preset: Preset | None = None):
        self.chain = chain_config
        self.preset = preset or PRESETS.get(chain_config.PRESET_BASE, ACTIVE_PRESET)
        super().__init__(chain_config, self.preset.SLOTS_PER_EPOCH)

    def __getattr__(self, name: str):
        # Fall through to chain config then preset, so spec code can write
        # `config.SLOTS_PER_EPOCH` or `config.SECONDS_PER_SLOT` uniformly.
        chain = object.__getattribute__(self, "chain")
        if hasattr(chain, name):
            return getattr(chain, name)
        preset = object.__getattribute__(self, "preset")
        if hasattr(preset, name):
            return getattr(preset, name)
        raise AttributeError(name)


class BeaconConfig(ChainForkConfig):
    """ChainForkConfig bound to genesis_validators_root with a domain cache."""

    def __init__(
        self,
        chain_config: ChainConfig,
        genesis_validators_root: bytes,
        preset: Preset | None = None,
    ):
        super().__init__(chain_config, preset)
        self.genesis_validators_root = genesis_validators_root
        # (fork_version, domain_type) -> domain
        self._domain_cache: dict[tuple[bytes, bytes], bytes] = {}
        self._fork_digests: dict[str, bytes] = {
            f.name: compute_fork_digest(f.version, genesis_validators_root)
            for f in self.forks.values()
        }
        self._digest_to_fork = {d: n for n, d in self._fork_digests.items()}

    def get_domain(self, domain_type: bytes, slot: int, message_epoch: int | None = None) -> bytes:
        """Domain for signing at `slot` (spec `get_domain`): the fork version
        is taken from the epoch of the message (attestation epochs may differ
        from the state slot's epoch)."""
        epoch = message_epoch if message_epoch is not None else slot // self.slots_per_epoch
        fork_version = self.get_fork_version_at_epoch(epoch)
        return self.get_domain_at_fork(domain_type, fork_version)

    def get_domain_at_fork(self, domain_type: bytes, fork_version: bytes) -> bytes:
        key = (fork_version, domain_type)
        domain = self._domain_cache.get(key)
        if domain is None:
            domain = compute_domain(domain_type, fork_version, self.genesis_validators_root)
            self._domain_cache[key] = domain
        return domain

    def fork_digest(self, fork_name: str) -> bytes:
        return self._fork_digests[fork_name]

    def fork_name_from_digest(self, digest: bytes) -> str:
        return self._digest_to_fork[digest]


def create_chain_fork_config(chain_config: ChainConfig) -> ChainForkConfig:
    return ChainForkConfig(chain_config)


def create_beacon_config(
    chain_config: ChainConfig, genesis_validators_root: bytes
) -> BeaconConfig:
    return BeaconConfig(chain_config, genesis_validators_root)


def get_network_config(name: str) -> ChainForkConfig:
    return ChainForkConfig(NETWORK_CONFIGS[name])
