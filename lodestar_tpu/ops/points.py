"""Elliptic-curve point ops on homogeneous projective coordinates (device).

Generic over the coordinate field: the same code drives G1 (field = ops.fp,
shapes (..., 32)) and G2 (field = ops.fp2, shapes (..., 2, 32)). Points are
(X, Y, Z) tuples with the curve's affine point (X/Z, Y/Z); infinity is
(0, 1, 0), representable and handled by the COMPLETE addition formulas of
Renes–Costello–Batina 2016 (a = 0 case) — no branches, no special cases, so
everything vmaps and shards cleanly. This replaces the reference's jacobian
add/dbl branching inside blst (SURVEY.md §2.3: `@chainsafe/blst` point ops).

Scalar multiplication is a fixed-trip MSB-first double-and-add `lax.scan`
over a bit vector — data-independent control flow, batchable over both
points and scalars (the random-coefficient batch-verify path,
reference: blst verifyMultipleSignatures' rand-scaling).
"""

from __future__ import annotations


import jax.numpy as jnp
from jax import lax

from ..bls import curve as _curve
from ..bls import fields as _fields
from . import fp, fp2
from .io_host import fq2_to_limbs, fq_to_limbs


class CurveOps:
    """Point arithmetic for one curve over field module `F`.

    `b3` is 3·b (curve constant) as a field limb array; `coord_ndim` is the
    number of trailing axes of one coordinate (1 for Fp, 2 for Fp2).
    """

    def __init__(self, F, b3, coord_ndim: int):
        self.F = F
        self.b3 = b3
        self.coord_ndim = coord_ndim

    # -- constructors -------------------------------------------------------

    def infinity(self, batch: tuple = ()):
        return (self.F.zero(batch), self.F.one(batch), self.F.zero(batch))

    def from_affine(self, x, y):
        batch = x.shape[: x.ndim - self.coord_ndim]
        return (x, y, self.F.one(batch))

    # -- predicates ---------------------------------------------------------

    def is_infinity(self, p):
        return self.F.is_zero(p[2])

    def eq(self, p, q):
        """Projective equality: X1·Z2 == X2·Z1 and Y1·Z2 == Y2·Z1 (plus
        matching infinity flags)."""
        x1, y1, z1 = p
        x2, y2, z2 = q
        cross_x = self.F.eq(self.F.mul(x1, z2), self.F.mul(x2, z1))
        cross_y = self.F.eq(self.F.mul(y1, z2), self.F.mul(y2, z1))
        inf1, inf2 = self.is_infinity(p), self.is_infinity(q)
        both_inf = inf1 & inf2
        return both_inf | (cross_x & cross_y & ~inf1 & ~inf2)

    def select(self, cond, p, q):
        s = self.F.select
        return (s(cond, p[0], q[0]), s(cond, p[1], q[1]), s(cond, p[2], q[2]))

    # -- group law (complete, branchless) -----------------------------------
    #
    # LATENCY DISCIPLINE (round-2 profile, tools/kernel_profile.py): the
    # scalar ladders are latency-bound — at 4096 lanes each Montgomery
    # multiply's sequential cost dominates, and wider stacked multiplies
    # are ~4× cheaper per lane. So every formula below evaluates its
    # INDEPENDENT products as ONE stacked F.mul call: RCB16 addition runs
    # as 3 stacked calls (6+2+6 products) instead of 14 sequential ones,
    # doubling as 3 (4+1+4) instead of ~10.

    def _mulstack(self, lhs, rhs):
        """One stacked field multiply over a new leading axis (operands
        broadcast to a common shape first — constants like b3 ride along)."""
        F = self.F
        shape = jnp.broadcast_shapes(*(a.shape for a in lhs), *(b.shape for b in rhs))
        lhs = [jnp.broadcast_to(a, shape) for a in lhs]
        rhs = [jnp.broadcast_to(b, shape) for b in rhs]
        out = F.mul(jnp.stack(lhs, axis=0), jnp.stack(rhs, axis=0))
        return [out[i] for i in range(len(lhs))]

    def _addstack(self, exprs):
        """One stacked `F.reduce_sums` over a stage's independent add/sub
        COLUMN expressions (each with value < 4p) — the add-side analog
        of `_mulstack` (one carry scan instead of one per value)."""
        shape = jnp.broadcast_shapes(*(e.shape for e in exprs))
        out = self.F.reduce_sums(
            jnp.stack([jnp.broadcast_to(e, shape) for e in exprs], axis=0)
        )
        return [out[i] for i in range(len(exprs))]

    def add(self, p, q):
        """RCB16 Algorithm 7 (a=0): complete projective addition.

        Stacked-scan discipline on BOTH op kinds: 3 stacked multiplies
        (6+2+6 products) and 4 stacked add-scans — round 4 paid ~15
        individual add scans on top of the multiplies."""
        F, b3 = self.F, self.b3
        TP = F.TWO_P
        x1, y1, z1 = p
        x2, y2, z2 = q
        xy1, yz1, xz1, xy2, yz2, xz2 = self._addstack(
            [x1 + y1, y1 + z1, x1 + z1, x2 + y2, y2 + z2, x2 + z2]
        )
        # stage A: all 6 cross products at once
        t0, t1, t2, u, v, w = self._mulstack(
            [x1, y1, z1, xy1, yz1, xz1], [x2, y2, z2, xy2, yz2, xz2]
        )
        s01, s12, s02, t00 = self._addstack(
            [t0 + t1, t1 + t2, t0 + t2, t0 + t0]
        )
        t3, t4, y3p, x3 = self._addstack(
            [u - s01 + TP, v - s12 + TP, w - s02 + TP, t00 + t0]
        )
        # stage B: the two b3 scalings
        t2b, y3 = self._mulstack([b3, b3], [t2, y3p])
        z3, t1 = self._addstack([t1 + t2b, t1 - t2b + TP])
        # stage C: the 6 output products
        a, b, c, d, e, f = self._mulstack(
            [t3, t4, y3, t1, z3, x3], [t1, y3, x3, z3, t4, t3]
        )
        ox, oy, oz = self._addstack([a - b + TP, c + d, e + f])
        return (ox, oy, oz)

    def add_mixed(self, p, q_affine):
        """RCB16 Algorithm 8 (a=0): complete mixed addition, Z2 = 1.

        NOTE: the affine operand cannot encode infinity; callers mask
        degenerate inputs at the API layer.
        """
        F, b3 = self.F, self.b3
        TP = F.TWO_P
        x1, y1, z1 = p
        x2, y2 = q_affine
        xy1, xy2 = self._addstack([x1 + y1, x2 + y2])
        # stage A: cross products + the b3·z1 scaling are all independent
        t0, t1, u, xz, yz, t2b = self._mulstack(
            [x1, y1, xy1, x2, y2, b3], [x2, y2, xy2, z1, z1, z1]
        )
        s01, t00, y3p, t4, z3, t1m = self._addstack(
            [t0 + t1, t0 + t0, xz + x1, yz + y1, t1 + t2b, t1 - t2b + TP]
        )
        t3, x3 = self._addstack([u - s01 + TP, t00 + t0])
        t1 = t1m
        # stage B: b3 scaling of y3p
        y3 = F.mul(b3, y3p)
        # stage C: outputs
        a, b, c, d, e, f = self._mulstack(
            [t3, t4, y3, t1, z3, x3], [t1, y3, x3, z3, t4, t3]
        )
        ox, oy, oz = self._addstack([a - b + TP, c + d, e + f])
        return (ox, oy, oz)

    def double(self, p):
        """RCB16 Algorithm 9 (a=0): complete projective doubling."""
        F, b3 = self.F, self.b3
        TP = F.TWO_P
        x, y, z = p
        # stage A: the 4 independent squares/products
        t0, t1, t2, txy = self._mulstack([y, y, z, x], [y, z, z, y])
        # stage B: b3·z²
        t2b = F.mul(b3, t2)
        z2d, y3s, t1c = self._addstack([t0 + t0, t0 + t2b, t2b + t2b])
        z4d, t2c = self._addstack([z2d + z2d, t1c + t2b])
        z8, t0c = self._addstack([z4d + z4d, t0 - t2c + TP])  # z8 = 8y²
        # stage C: the 4 output products
        x3, z3, y3, xt = self._mulstack(
            [t2b, t1, t0c, t0c], [z8, z8, y3s, txy]
        )
        oy, ox = self._addstack([x3 + y3, xt + xt])
        return (ox, oy, z3)

    def neg(self, p):
        return (p[0], self.F.neg(p[1]), p[2])

    # -- scalar multiplication ---------------------------------------------

    def scalar_mul_bits(self, bits, q_affine):
        """[k]Q for Q affine, k given as (..., nbits) int32 bits (MSB first).

        MSB-first double-and-add over a fixed-trip scan; the conditional add
        is a select, so batched scalars (vmap over sets) cost the same as
        uniform ones — the batch is where the parallelism lives.
        """
        nbits = bits.shape[-1]
        batch = jnp.broadcast_shapes(
            bits.shape[:-1], q_affine[0].shape[: q_affine[0].ndim - self.coord_ndim]
        )
        xq = jnp.broadcast_to(
            q_affine[0], batch + q_affine[0].shape[q_affine[0].ndim - self.coord_ndim:]
        )
        yq = jnp.broadcast_to(
            q_affine[1], batch + q_affine[1].shape[q_affine[1].ndim - self.coord_ndim:]
        )
        bits_t = jnp.moveaxis(jnp.broadcast_to(bits, batch + (nbits,)), -1, 0)

        def step(acc, bit):
            acc = self.double(acc)
            added = self.add_mixed(acc, (xq, yq))
            acc = self.select(bit != 0, added, acc)
            return acc, None

        acc, _ = lax.scan(step, self.infinity(batch), bits_t)
        return acc

    def scalar_mul_windowed(self, bits, q_affine, window: int = 4):
        """[k]Q via fixed 2^w windows: same contract as `scalar_mul_bits`
        with ~half the group additions for 64-bit scalars.

        MEASURED NEGATIVE RESULT on v5e (round 2, tools/win_check.py):
        despite the op-count win, this runs SLOWER than the bit ladder
        (G2 @512 lanes: 307 vs 262 ms) — the 2^w per-lane table selects
        (16 vectorized where()s per window) outweigh the saved mixed
        adds, and XLA compile time grows ~30x (the unrolled table build
        + select trees). Kept as a pinned, differential-tested option;
        the verifier kernels use `scalar_mul_bits`.

        Per window step: w doublings + ONE complete addition of the
        table entry T[digit] (T = [0·Q .. (2^w−1)·Q], 2^w−2 mixed adds
        to build, amortized over the whole batch's scan). The per-lane
        table lookup is 2^w field selects — noise next to a group add.
        Complete formulas make the digit-0 case uniform (adds the
        identity), so the scan body is branch-free like the bit ladder.
        """
        nbits = bits.shape[-1]
        if nbits % window != 0:
            return self.scalar_mul_bits(bits, q_affine)
        batch = jnp.broadcast_shapes(
            bits.shape[:-1], q_affine[0].shape[: q_affine[0].ndim - self.coord_ndim]
        )
        coord = q_affine[0].shape[q_affine[0].ndim - self.coord_ndim :]
        xq = jnp.broadcast_to(q_affine[0], batch + coord)
        yq = jnp.broadcast_to(q_affine[1], batch + coord)
        bits = jnp.broadcast_to(bits, batch + (nbits,))

        # digits, MSB-first: (n_windows, ...batch)
        weights = jnp.asarray([1 << (window - 1 - i) for i in range(window)])
        digits = jnp.moveaxis(
            jnp.sum(bits.reshape(batch + (nbits // window, window)) * weights, -1),
            -1,
            0,
        )

        # table T[d] = d·Q as stacked projective coords, axis 0 = digit
        entries = [self.infinity(batch), self.from_affine(xq, yq)]
        for _ in range(2, 1 << window):
            entries.append(self.add_mixed(entries[-1], (xq, yq)))
        table = tuple(
            jnp.stack([e[i] for e in entries], axis=0) for i in range(3)
        )

        def lookup(digit):
            cond = lambda d: digit == d  # noqa: E731
            out = tuple(t[0] for t in table)
            for d in range(1, 1 << window):
                out = self.select(cond(d), tuple(t[d] for t in table), out)
            return out

        def step(acc, digit):
            for _ in range(window):
                acc = self.double(acc)
            return self.add(acc, lookup(digit)), None

        acc, _ = lax.scan(step, self.infinity(batch), digits)
        return acc

    # -- normalization ------------------------------------------------------

    def to_affine(self, p):
        """(X/Z, Y/Z); infinity maps to (0, 0) — mask via is_infinity."""
        zinv = self.F.inv(p[2])
        return (self.F.mul(p[0], zinv), self.F.mul(p[1], zinv))


# --- curve instances -------------------------------------------------------

def _b3_g1():
    return jnp.asarray(fq_to_limbs(_fields.Fq(12)))  # 3·4


def _b3_g2():
    # 3·4(1+u) = 12 + 12u
    return jnp.asarray(fq2_to_limbs(_fields.Fq2.from_ints(12, 12)))


g1 = CurveOps(fp, _b3_g1(), coord_ndim=1)
g2 = CurveOps(fp2, _b3_g2(), coord_ndim=2)

# Generators as affine limb constants (host-computed from the oracle)
_g1_gen = _curve.PointG1.generator().to_affine()
_g2_gen = _curve.PointG2.generator().to_affine()
G1_GEN_X = jnp.asarray(fq_to_limbs(_g1_gen[0]))
G1_GEN_Y = jnp.asarray(fq_to_limbs(_g1_gen[1]))
G2_GEN_X = jnp.asarray(fq2_to_limbs(_g2_gen[0]))
G2_GEN_Y = jnp.asarray(fq2_to_limbs(_g2_gen[1]))


# --- ψ endomorphism on G2 (device tier) ------------------------------------
#
# ψ = untwist∘Frobenius∘twist acts on G2 as multiplication by the BLS
# parameter z = X_PARAM (since p ≡ t−1 = z mod r): ψ(x, y) =
# (c_x·conj(x), c_y·conj(y)) with the oracle's Budroni–Pintore constants
# (bls/curve.py psi()). The grouped batch verifier splits its 64-bit
# random coefficients as r = a + z·b (a, b 32-bit — still 2^-64 sound:
# (a, b) ↦ a + z·b is injective, so r is uniform over 2^64 residues) and
# trades half of every scalar-combination for one ψ application: 2 fp2
# multiplies instead of 32 doubling steps.

_PSI_CX_L = jnp.asarray(fq2_to_limbs(_curve._PSI_CX))
_PSI_CY_L = jnp.asarray(fq2_to_limbs(_curve._PSI_CY))


def g2_psi(p):
    """ψ of a projective G2 point: (c_x·conj(X), c_y·conj(Y), conj(Z)).

    Conjugation commutes with the projective quotient (it is Fp-linear),
    so infinity maps to infinity and no normalization is needed."""
    x, y, z = p
    out = fp2.mul(
        jnp.stack([fp2.conj(x), fp2.conj(y)], axis=0),
        jnp.stack(
            [
                jnp.broadcast_to(_PSI_CX_L, x.shape),
                jnp.broadcast_to(_PSI_CY_L, y.shape),
            ],
            axis=0,
        ),
    )
    return (out[0], out[1], fp2.conj(z))


def _neg_g1_pow2_table(nbits: int):
    """Affine limb table of −[2^b]·g1, b = 0..nbits−1 (host-computed).

    The grouped verifier's signature aggregate rides constant-G1 Miller
    lanes: e(−g1, Σ 2^b·U_b) = Π_b e(−[2^b]g1, U_b), so the per-bit plane
    sums never need a sequential Horner combine on device."""
    import numpy as np

    xs, ys = [], []
    cur = _curve.PointG1.generator()
    for _ in range(nbits):
        aff = cur.to_affine()
        xs.append(fq_to_limbs(aff[0]))
        ys.append(fq_to_limbs(-aff[1]))
        cur = cur.double()
    return jnp.asarray(np.stack(xs)), jnp.asarray(np.stack(ys))


# 64 entries: the per-set kernel's signature aggregate uses full 64-bit
# random coefficients (no GLS split) and needs −[2^b]g1 for b = 0..63;
# the grouped kernel's 32-bit halves use the prefix
NEG_G1_POW2_64_X, NEG_G1_POW2_64_Y = _neg_g1_pow2_table(64)
NEG_G1_POW2_X, NEG_G1_POW2_Y = NEG_G1_POW2_64_X[:32], NEG_G1_POW2_64_Y[:32]
