"""On-device G2 signature decompression + batched subgroup checking.

Removes the host's e2e floor (VERDICT r4 #5): the per-set work that kept
the chip underfed on few-core hosts was the C-tier signature decompression
(~0.6 ms/set: one Fp2 square root + a per-point ψ subgroup check). Both
move on-device here:

- **Decompression** (`decompress`): ZCash-format 96-byte compressed G2
  points are unpacked to 12-bit limbs by static byte gathers, validated
  (flags, coordinate range, curve membership), and the y coordinate is
  recovered by a branchless Fp2 square root (`fp2_sqrt`) using the complex
  method — two Fp exponentiations per lane, wide-batched, with the
  inverse obtained FREE from the same power chain (see below). The sign
  is selected by the compression flag.

- **Subgroup checking** (`planes_in_subgroup`): instead of a per-lane
  [x]-ladder, the verifier's EXISTING random bit-plane sums U_b are
  checked: ψ(U_b) == [x]·U_b for all 64 planes. ψ(P) = [x]P holds
  exactly on G2 (M. Scott, "A note on group membership tests for G1, G2
  and GT on BLS pairing-friendly curves", 2021 — the same endomorphism
  test the native C tier uses per point). Soundness of the batched form:
  write each accepted point S_i = g_i + h_i with g_i ∈ G2 and h_i in the
  complementary (cofactor) subgroup H — the decomposition exists and is
  endomorphism-stable because gcd(h2, r) = 1. ψ − [x] vanishes on G2 and
  is injective on H, so plane b passes iff Σ_{i: bit_b(r_i)} h_i = 0.
  For any fixed nonzero (h_i) vector a uniform mask zeroes the sum with
  probability ≤ 1/2 (condition on all bits but one at an index with
  h_i ≠ 0), and the 64 planes use independent bits ⇒ an out-of-subgroup
  signature survives with probability ≤ 2^-64 — the same bound as the
  verification equation itself, over the same randomness (union bound:
  total false-accept ≤ 2·2^-64).

Fp2 sqrt (p ≡ 3 mod 4), branchless complex method for c = c0 + c1·u:
    n  = c0² + c1²                     (norm; a QR in Fp whenever c is
                                        a square in Fp2)
    λ  = n^((p+1)/4)                   [Fp pow #1]  λ² == n else reject
    t  = (c0 + λ)/2
    u* = t^((p-3)/4)                   [Fp pow #2]
    e₀ = u*·t        (= t^((p+1)/4))
    χ  = u*·e₀       (= t^((p-1)/2) = ±1: the QR test, no third pow)
  χ = 1 (t is a QR):   y = e₀ + (c1/2)·u* · u        (1/e₀ = u*)
  χ = −1 (t non-QR):   y = −(c1/2)·u* + e₀·u
  The second branch works because e₀ = √(−t), u* = −1/e₀, and of the two
  candidate real parts (c0±λ)/2 exactly one is a QR (their product is
  −c1²/4, a non-residue) — all derived identities cost only multiplies,
  so the whole sqrt is TWO Fp pow chains + O(1) muls per lane. (Corner:
  c1 = 0 with c0 a non-residue would need √c0·u; the candidate then
  fails the final y² == c check and the lane reports invalid — honest
  signatures never land there, and the facade's per-set fallback keeps
  verdicts correct if an adversary crafts such an x.)

Reference analog: blst's POINTonE2_Uncompress + subgroup check as used by
the worker (`chain/bls/multithread/worker.ts:33-101` per SURVEY §2.2).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..bls.fields import P as _P_INT
from ..bls.fields import Fq
from . import fp, fp2
from .io_host import fq_to_limbs
from .limbs import N_LIMBS, P_LIMBS, int_to_limbs
from .pairing import X_ABS
from .points import g2, g2_psi

# --- constants --------------------------------------------------------------

_INV2 = jnp.asarray(fq_to_limbs(Fq(pow(2, -1, _P_INT))))  # Montgomery 1/2
_P_ARR = jnp.asarray(P_LIMBS)
# canonical c > (p-1)/2  ⟺  c >= (p+1)/2 (lex compare on limbs)
_HALF_P1 = jnp.asarray(int_to_limbs((_P_INT + 1) // 2))

_POW_SQRT = (_P_INT + 1) // 4
_POW_U = (_P_INT - 3) // 4

# byte→limb static gather: little-endian byte j holds bits 8j..8j+7 of the
# 384-bit coordinate; limb i holds bits 12i..12i+11
_IDX0 = np.array([(12 * i) // 8 for i in range(N_LIMBS)])
_SHIFT = np.array([(12 * i) % 8 for i in range(N_LIMBS)])
_IDX1 = _IDX0 + 1


def _bytes48_to_limbs(be_bytes):
    """(..., 48) uint8 big-endian → (..., 32) int32 canonical 12-bit limbs
    (normal domain, NOT Montgomery)."""
    le = jnp.flip(be_bytes.astype(jnp.int32), axis=-1)
    lo = jnp.take(le, jnp.asarray(_IDX0), axis=-1)
    # top limb's high byte would index past the end; bits there are zero
    hi = jnp.take(le, jnp.asarray(np.minimum(_IDX1, 47)), axis=-1)
    hi = jnp.where(jnp.asarray(_IDX1 < 48), hi, 0)
    sh = jnp.asarray(_SHIFT)
    return ((lo >> sh) + (hi << (8 - sh))) & 0xFFF


def _lex_lt_p(a):
    """a < p on canonical limb vectors."""
    return ~fp._lex_ge(a, _P_ARR)


def fp2_sqrt(c):
    """Branchless Fp2 square root (see module docstring).

    c: (..., 2, 32) Montgomery limbs. Returns (y, ok): y with y² == c when
    ok; ok False where c has no square root (or hits the c1=0 non-QR
    corner — callers treat either as an invalid encoding)."""
    c0 = c[..., 0, :]
    c1 = c[..., 1, :]
    sq = fp.mul(jnp.stack([c0, c1], 0), jnp.stack([c0, c1], 0))
    n = fp.add(sq[0], sq[1])
    lam = fp.pow_const(n, _POW_SQRT)
    lam_ok = fp.eq(fp.mul(lam, lam), n)
    t = fp.mul(fp.add(c0, lam), _INV2)
    u_ = fp.pow_const(t, _POW_U)
    pr = fp.mul(
        jnp.stack([u_, c1], 0),
        jnp.stack([t, jnp.broadcast_to(_INV2, t.shape)], 0),
    )
    e0, c1h = pr[0], pr[1]  # e₀ = u*·t, c1h = c1/2
    chi_one = fp.eq(fp.mul(u_, e0), fp.one_mont(e0.shape[:-1]))
    f0 = fp.mul(c1h, u_)
    e = fp.select(chi_one, e0, fp.neg(f0))
    f = fp.select(chi_one, f0, e0)
    y = jnp.stack([e, f], axis=-2)
    ok = lam_ok & fp2.eq(fp2.square(y), c)
    return y, ok


def _y_is_lex_larger(y):
    """ZCash sort flag: y > −y comparing (c1, then c0) canonically."""
    yc = jnp.stack([fp.from_mont(y[..., 0, :]), fp.from_mont(y[..., 1, :])], -2)
    c0_big = fp._lex_ge(yc[..., 0, :], _HALF_P1)
    c1_big = fp._lex_ge(yc[..., 1, :], _HALF_P1)
    c1_zero = jnp.all(yc[..., 1, :] == 0, axis=-1)
    return jnp.where(c1_zero, c0_big, c1_big)


def decompress(raw):
    """Decompress ZCash-format G2 signatures on device.

    raw: (..., 96) uint8. Returns (x, y, ok):
    x, y (..., 2, 32) Montgomery limbs of an affine curve point; ok bool —
    False for malformed flags, out-of-range coordinates, off-curve x, the
    infinity encoding (an infinity signature never verifies per eth2), or
    the sqrt corner documented above. Coordinates of !ok lanes are
    garbage; callers must mask. Subgroup membership is NOT checked here —
    the verifier checks its random plane sums instead
    (`planes_in_subgroup`)."""
    raw = jnp.asarray(raw)
    flags = raw[..., 0].astype(jnp.int32)
    compressed = (flags & 0x80) != 0
    infinity = (flags & 0x40) != 0
    sign = (flags & 0x20) != 0

    top = raw.astype(jnp.int32).at[..., 0].set(flags & 0x1F)
    xc1 = _bytes48_to_limbs(top[..., :48])
    xc0 = _bytes48_to_limbs(top[..., 48:96])
    in_range = _lex_lt_p(xc1) & _lex_lt_p(xc0)
    x = jnp.stack([fp.to_mont(xc0), fp.to_mont(xc1)], axis=-2)

    # y² = x³ + 4(1+u)
    xsq = fp2.square(x)
    b2 = jnp.asarray(
        np.stack([fq_to_limbs(Fq(4)), fq_to_limbs(Fq(4))])
    )
    rhs = fp2.add(fp2.mul(xsq, x), b2)
    y, sqrt_ok = fp2_sqrt(rhs)
    flip = _y_is_lex_larger(y) != sign
    y = fp2.select(~flip, y, fp2.neg(y))

    ok = compressed & ~infinity & in_range & sqrt_ok
    return x, y, ok


def g2_mul_x_abs(p):
    """[|x|]·P for the BLS parameter |x| — STATIC double-and-add (63
    doublings + 5 additions unrolled at trace time; the bit pattern is a
    compile-time constant, so no scan and no selects)."""
    bits = bin(X_ABS)[2:]
    acc = p
    for b in bits[1:]:
        acc = g2.double(acc)
        if b == "1":
            acc = g2.add(acc, p)
    return acc


def planes_in_subgroup(u_planes):
    """ψ(U_b) == [x]·U_b over the leading plane axis → scalar bool.

    x = X_PARAM < 0, so the right side is −[|x|]·U_b. Infinity planes
    pass (ψ(O) = O = [x]O) via the projective eq's infinity case —
    correct: an all-zero mask says nothing and contributes nothing."""
    lhs = g2_psi(u_planes)
    rhs = g2.neg(g2_mul_x_abs(u_planes))
    return jnp.all(g2.eq(lhs, rhs))
