"""Limb representation of Fp (BLS12-381 base field) for int32 TPU lanes.

Representation choice (SURVEY.md §7 hard part #1): TPUs have no 64-bit
integer multiply worth using, so a field element is a little-endian vector
of 32 limbs x 12 bits held in int32. Schoolbook products of 12-bit limbs
are < 2^24 and a full 32-term convolution column stays < 2^29, so every
intermediate of the Montgomery pipeline fits signed int32 with headroom.

Values are kept in Montgomery form (a*R mod p, R = 2^384) and allowed to
range over [0, 2p) between operations (lazy reduction — same trick blst
uses); `canonical()` produces the unique representative < p.

All device functions in ops/ treat the trailing axis (size 32) as the limb
axis and broadcast over any leading batch axes.
"""

from __future__ import annotations

import numpy as np

from ..bls.fields import P

LIMB_BITS = 12
N_LIMBS = 32
LIMB_MASK = (1 << LIMB_BITS) - 1
DTYPE = np.int32

# Montgomery radix
R_MONT = 1 << (LIMB_BITS * N_LIMBS)  # 2^384
R2 = (R_MONT * R_MONT) % P  # for to_mont: a*R = REDC(a * R2)
# -p^-1 mod 2^12 (p is odd)
N0 = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


def int_to_limbs(x: int) -> np.ndarray:
    """Python int -> (32,) int32 little-endian 12-bit limbs. x must fit 384 bits."""
    if not 0 <= x < R_MONT:
        raise ValueError("value out of 384-bit range")
    out = np.zeros(N_LIMBS, dtype=DTYPE)
    for i in range(N_LIMBS):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    return out


def limbs_to_int(limbs) -> int:
    """(…, 32) limbs -> Python int (single element only)."""
    arr = np.asarray(limbs)
    if arr.ndim != 1:
        raise ValueError("limbs_to_int takes a single element")
    acc = 0
    for i in reversed(range(N_LIMBS)):
        acc = (acc << LIMB_BITS) | int(arr[i])
    return acc


# Device-side constants (plain numpy; jnp will const-fold them under jit)
P_LIMBS = int_to_limbs(P)
TWO_P_LIMBS = int_to_limbs(2 * P)
R2_LIMBS = int_to_limbs(R2)
ONE_MONT_LIMBS = int_to_limbs(R_MONT % P)  # 1 in Montgomery form
ZERO_LIMBS = np.zeros(N_LIMBS, dtype=DTYPE)


def fp_to_mont_host(x: int) -> np.ndarray:
    """Host-side: normal-domain int -> Montgomery-form limbs."""
    return int_to_limbs((x * R_MONT) % P)


def fp_from_mont_host(limbs) -> int:
    """Host-side: Montgomery-form limbs -> normal-domain int."""
    return (limbs_to_int(limbs) * pow(R_MONT, -1, P)) % P
