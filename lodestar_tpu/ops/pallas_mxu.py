"""Pallas TPU kernel: VMEM-resident Montgomery multiply on the MXU.

The round-4 cut of the lever named since round 2 (BASELINE.md): the
conv-as-matmul design (`ops/fp.conv`) wins microbenchmarks but loses
end-to-end in plain XLA because the matmul cannot fuse its producer —
every convolution materializes the 32x-blowup outer product through HBM.
This kernel runs the SAME proven pipeline per batch tile with every
intermediate in VMEM:

    outer   (T, 1024) int32   a_i * b_j            VPU
    parts   (3T, 1024) bf16   8-bit splits          VPU  (bf16-exact <=255)
    t_cols  = parts @ S       (1024, 64) 0/1        MXU  (f32 accumulate)
    m_cols  = parts(t mod R) @ Toep(N') parts       MXU  (constant matrix)
    u_cols  = parts(m) @ Toep(p) parts              MXU  (constant matrix)
    out     = carry(t_cols + u_cols)[:, 32:]        VPU  (log-depth carry)

versus the word-serial scan path (`fp._mul_scan`): the 32-step REDC scan
and its 32 dynamic-slice updates disappear entirely — reduction becomes
two constant-matrix matmuls — and the only sequential structure left is
three log-depth carry propagations.

Layout: batch on sublanes, limbs on lanes ((T, 32) blocks; the matmul
contraction axis 1024 rides the lane dimension). Carries shift along
lanes via static pad/slice concatenation, which Mosaic lowers to lane
shifts.

Bounds (same argument as `fp._mul_fused`): inputs < 2p with canonical
12-bit limbs, conv columns < 2^29, t+u columns < 2^30 (signed int32 ok),
output < 2p. Matmul exactness: every MXU input is an 8-bit part (<=255,
exact in bf16's 8-bit mantissa); f32 accumulation of <=32 terms of
<=255*255 stays < 2^21 << 2^24.

Oracle: differential tests vs `fp._mul_scan` (tests/test_pallas_mxu.py)
run the kernel in interpret mode on CPU and compiled on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..bls.fields import P as _P_INT
from .limbs import LIMB_BITS, LIMB_MASK, N_LIMBS, P_LIMBS, R_MONT, int_to_limbs

_NPRIME_LIMBS = int_to_limbs((-pow(_P_INT, -1, R_MONT)) % R_MONT)

# default batch-tile height; 8-bit-part working set stays ~3 MB of VMEM
TILE = 256


def _conv_select() -> np.ndarray:
    """(N^2, 2N) 0/1 f32: flattened outer index (i, j) -> column i+j."""
    s = np.zeros((N_LIMBS * N_LIMBS, 2 * N_LIMBS), np.float32)
    for i in range(N_LIMBS):
        for j in range(N_LIMBS):
            s[i * N_LIMBS + j, i + j] = 1.0
    return s


def _toeplitz(vec: np.ndarray, out_cols: int) -> np.ndarray:
    """(N, out_cols) f32 with T[i, k] = vec[k-i]: conv-by-constant as a
    matmul (x @ T)[k] = sum_i x_i vec_{k-i}."""
    t = np.zeros((N_LIMBS, out_cols), np.float32)
    for i in range(N_LIMBS):
        for k in range(out_cols):
            if 0 <= k - i < N_LIMBS:
                t[i, k] = float(vec[k - i])
    return t


def _split8(v: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    return v & 0xFF, v >> 8


_S_MAT = _conv_select()
_NP_LO, _NP_HI = _split8(_NPRIME_LIMBS)
_P_LO, _P_HI = _split8(P_LIMBS)
# packed constant matrices: [lo | hi] side by side so one matmul yields
# both part-convolutions (see kernel)
_TN = np.concatenate(
    [_toeplitz(_NP_LO, N_LIMBS), _toeplitz(_NP_HI, N_LIMBS)], axis=1
)  # (32, 64)
_TP = np.concatenate(
    [_toeplitz(_P_LO, 2 * N_LIMBS), _toeplitz(_P_HI, 2 * N_LIMBS)], axis=1
)  # (32, 128)


def _shift_lanes(x: jnp.ndarray, right: int) -> jnp.ndarray:
    """Shift along the last (lane) axis toward higher indices, zero fill."""
    return jnp.pad(x, ((0, 0), (right, 0)))[:, : x.shape[1]]


def _carry_lanes(cols: jnp.ndarray) -> jnp.ndarray:
    """Non-negative-value carry propagation along lanes -> 12-bit digits.

    cols (T, K) int32, columns < 2^30, value non-negative and assumed to
    fit K limbs (out-carry dropped — callers guarantee, same contract as
    `fp.carry_scan`). Three shift-folds bring digits to [0, 2^12]; the
    residual +1 chain resolves with a generate/propagate Kogge–Stone
    prefix (log-depth, lane shifts only)."""
    k = cols.shape[1]

    def fold(x):
        return (x & LIMB_MASK) + _shift_lanes(x >> LIMB_BITS, 1)

    v = fold(fold(fold(cols)))  # digits in [0, 2^12]
    g = (v > LIMB_MASK).astype(jnp.int32)
    p = (v == LIMB_MASK).astype(jnp.int32)
    shift = 1
    while shift < k:
        g_prev = _shift_lanes(g, shift)
        p_prev = _shift_lanes(p, shift)
        g = g | (p & g_prev)
        p = p & p_prev
        shift *= 2
    carry_in = _shift_lanes(g, 1)
    return (v + carry_in) & LIMB_MASK


def _mxu_kernel(a_ref, b_ref, s_ref, tn_ref, tp_ref, out_ref):
    """One (TILE, 32) batch tile of REDC(a*b); see module docstring."""
    a = a_ref[...]
    b = b_ref[...]
    t_rows = a.shape[0]
    n = N_LIMBS

    # outer product (T, 1024): column i*32+j = a_i * b_j
    a_rep = jnp.concatenate(
        [jax.lax.broadcast_in_dim(a[:, i : i + 1], (t_rows, n), (0, 1)) for i in range(n)],
        axis=1,
    )
    b_tile = jnp.concatenate([b] * n, axis=1)
    outer = a_rep * b_tile  # < 2^24

    # 8-bit parts -> one packed (3T, 1024) @ (1024, 64) MXU matmul
    parts = jnp.concatenate(
        [outer & 0xFF, (outer >> 8) & 0xFF, outer >> 16], axis=0
    ).astype(jnp.bfloat16)
    c = jax.lax.dot_general(
        parts,
        s_ref[...],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    # MOSAIC MISCOMPILE GUARD: `x << k` on a sliced matmul output silently
    # lowers to 0 at tile heights >= 64 (v5e, 2026-07; minimal repro in
    # tests/test_pallas_mxu.py) — recombinations use integer multiplies.
    t_cols = c[:t_rows] + c[t_rows : 2 * t_rows] * 256 + c[2 * t_rows :] * 65536

    t = _carry_lanes(t_cols)  # 64 canonical limbs of a*b

    # m = (t mod R) * N' mod R  — constant-Toeplitz matmul on 8-bit parts
    t_lo = t[:, :n]
    tl = jnp.concatenate([t_lo & 0xFF, t_lo >> 8], axis=0).astype(jnp.bfloat16)
    mm = jax.lax.dot_general(
        tl, tn_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    # mm rows: [t0 | t1] x cols [N'0 | N'1] -> four part convolutions
    m_cols = (
        mm[:t_rows, :n]
        + (mm[:t_rows, n:] + mm[t_rows:, :n]) * 256
        + mm[t_rows:, n:] * 65536
    )
    m = _carry_lanes(m_cols)  # mod R: out-carry dropped

    # u = m * p over 64 columns
    ml = jnp.concatenate([m & 0xFF, m >> 8], axis=0).astype(jnp.bfloat16)
    uu = jax.lax.dot_general(
        ml, tp_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)
    u_cols = (
        uu[:t_rows, : 2 * n]
        + (uu[:t_rows, 2 * n :] + uu[t_rows:, : 2 * n]) * 256
        + uu[t_rows:, 2 * n :] * 65536
    )

    # (t + m*p) / R: low 32 limbs are ≡ 0 by construction of m
    summed = _carry_lanes(t_cols + u_cols)
    out_ref[...] = summed[:, n:]


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@functools.partial(jax.jit, static_argnames=("interpret", "tile"))
def _mxu_tiles(a: jnp.ndarray, b: jnp.ndarray, interpret: bool, tile: int):
    """a, b: (batch_padded, 32) int32, batch_padded % tile == 0."""
    n_tiles = a.shape[0] // tile
    return pl.pallas_call(
        _mxu_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile, N_LIMBS), lambda i: (i, 0)),
            pl.BlockSpec((tile, N_LIMBS), lambda i: (i, 0)),
            pl.BlockSpec(_S_MAT.shape, lambda i: (0, 0)),
            pl.BlockSpec(_TN.shape, lambda i: (0, 0)),
            pl.BlockSpec(_TP.shape, lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, N_LIMBS), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(a.shape, jnp.int32),
        interpret=interpret,
    )(
        a,
        b,
        jnp.asarray(_S_MAT, jnp.bfloat16),
        jnp.asarray(_TN, jnp.bfloat16),
        jnp.asarray(_TP, jnp.bfloat16),
    )


MIN_LANES = 4096  # below this, the ~200 us per-call launch latency loses
# to the scan path (measured v5e round 4: full verifier kernel through the
# Pallas path unconditionally = 867 sets/s vs 1001 scan — the small-batch
# tail sites, e.g. the final-exponentiation chains at unit batch, pay the
# fixed cost thousands of times). Override: LODESTAR_TPU_PALLAS_MIN_LANES.


def _min_lanes() -> int:
    from ..utils.env import env_int

    v = env_int("LODESTAR_TPU_PALLAS_MIN_LANES")
    return v if v else MIN_LANES


def mont_mul(
    a: jnp.ndarray,
    b: jnp.ndarray,
    interpret: bool | None = None,
    tile: int = TILE,
) -> jnp.ndarray:
    """Drop-in for `ops.fp.mul`: framework layout (..., 32), broadcastable
    batch axes, [0, 2p) lazy-reduction contract. Batches smaller than the
    launch-latency break-even fall back to the word-serial scan."""
    if interpret is None:
        interpret = not _on_tpu()
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    n_flat = 1
    for d in batch:
        n_flat *= d
    if n_flat < _min_lanes():
        from . import fp as _fp

        return _fp._mul_scan(a, b)
    a = jnp.broadcast_to(a, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    b = jnp.broadcast_to(b, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    n = a.shape[0]
    t = tile if n >= tile else max(8, 1 << (n - 1).bit_length())
    pad = (-n) % t
    if pad:
        a = jnp.concatenate([a, jnp.zeros((pad, N_LIMBS), a.dtype)], axis=0)
        b = jnp.concatenate([b, jnp.zeros((pad, N_LIMBS), b.dtype)], axis=0)
    out = _mxu_tiles(a, b, interpret, t)[:n]
    return out.reshape(batch + (N_LIMBS,))
