"""TPU-native BLS12-381 kernels (the north star per BASELINE.json).

This package is the device tier: fixed-width limb arithmetic over the
381-bit base field mapped onto int32 lanes, field towers, curve groups,
the optimal ate pairing, and the batched signature-set verification kernel
— all pure JAX (jnp/lax), jit-compatible, vmap-batchable, and shardable
over a `jax.sharding.Mesh`.

Role in the architecture: the reference offloads BLS work to a pool of
CPU worker threads (`beacon-node/src/chain/bls/multithread/index.ts`);
here the same `IBlsVerifier` boundary dispatches to these kernels instead,
with `lodestar_tpu/bls` (pure-Python big ints) as the correctness oracle.
"""
