"""VMEM-resident Pallas Miller-loop and fused full-pairing kernels.

The XLA path (`ops/pairing.miller_loop`) lowers each Fp2/Fp6/Fp12 tower
op of the 63 doubling/addition steps as separate HLO fusions — the
running Fp12 accumulator, the line evaluations and the G2 ladder point
bounce through HBM between every field op, which is the latency wall of
the ungrouped worst case (ROADMAP item 2, VERDICT r5 #3). This kernel
moves the ENTIRE Miller loop of a batch tile inside one `pl.pallas_call`:
the accumulator, the running point T and every intermediate of the fused
line/double/add formulas stay VMEM-resident for all 63 iterations, and
each tile pays exactly one HBM round-trip (inputs in, Fp12 out).

Bit-identicality by construction: the kernel body traces the SAME
`pairing._miller_loop_impl` graph the XLA path runs — same stacked fp2
multiplies, same bounds-tracked combine scans, same `lax.scan`/`lax.cond`
step structure (Pallas supports JAX control flow inside kernels) — so
compiled and interpreted outputs match the default path limb-for-limb.
The differential suite (tests/test_pallas_tower.py) pins interpreter mode
against `miller_loop` on CPU; the existing oracle/KAT tests cover the
dispatch because `pairing.miller_loop` routes here when enabled.

Gating (`LODESTAR_TPU_PALLAS_MILLER`, registered in utils/env.py):
  auto (default) — on when the backend lowers Pallas (TPU); off elsewhere
  1/on          — forced; off-TPU runs the Pallas interpreter
  0/off         — always the XLA path

Tile geometry: MILLER_TILE batch lanes per program. The per-tile working
set is dominated by the stacked fp2 multiply stages (≤ 9 products × 2
Fp × 64 columns × 4 B ≈ 4.6 kB/lane live at once) plus the (2,3,2,32)
accumulator — 8 lanes stay well under the ~16 MB VMEM budget including
Mosaic's double buffers. Limbs ride the trailing axis as in the
framework-wide layout; correctness-first (the win targeted here is HBM
avoidance, not vreg occupancy — see ops/pallas_fp.py for the
lane-transposed treatment of a single field op).

FULL-PAIRING fusion (ISSUE 18): `pairing_fused_pallas` extends the same
design from the Miller loop to the WHOLE per-set pairing tail — each tile
runs 2·PAIRING_TILE Miller lanes (pk·H(m) lanes plus the −g1·sig lanes),
the per-set Fp12 product, and the shared-inversion
`final_exponentiation_batch`, all inside ONE `pl.pallas_call`: the Fp12
accumulator never spills to HBM between the Miller loop and the final
exp (the 820 ms floor-profile gap this targets). Bit-identicality to the
XLA `miller_loop` + `final_exponentiation_batch` route is again by
construction AND by grouping-invariance: `final_exponentiation_batch` is
bit-identical to per-lane `final_exponentiation` on EVERY input (the
tests/test_final_exp_batch.py contract), so a per-tile batched FE equals
the full-batch one lane-for-lane — tiling cannot change verdict limbs.
PAIRING_TILE is half of MILLER_TILE: a tile still runs 2·PAIRING_TILE
Miller lanes (same live set as one Miller tile) and the FE hard part
holds a handful of extra live Fp12s. Gated by LODESTAR_TPU_PALLAS_PAIRING
(auto-on-TPU, interpreter parity on CPU), independent of the Miller knob.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..observability.trace import named_scope
from ..utils.env import env_str
from .limbs import N_LIMBS

MILLER_TILE = 8  # batch lanes per Pallas program (VMEM headroom: see above)
PAIRING_TILE = 4  # per-set lanes per fused-pairing program (2x Miller lanes)

_FALSE_VALUES = ("0", "off", "false", "no", "")


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _tri_state(name: str) -> bool:
    mode = (env_str(name) or "auto").strip().lower()
    if mode == "auto":
        return _on_tpu()
    return mode not in _FALSE_VALUES


def enabled() -> bool:
    """Resolve the LODESTAR_TPU_PALLAS_MILLER tri-state for this process."""
    return _tri_state("LODESTAR_TPU_PALLAS_MILLER")


def pairing_enabled() -> bool:
    """Resolve the LODESTAR_TPU_PALLAS_PAIRING tri-state for this process
    (the fused full-pairing kernel; independent of the Miller knob)."""
    return _tri_state("LODESTAR_TPU_PALLAS_PAIRING")


@functools.lru_cache(maxsize=1)
def _tile_jaxpr():
    """Trace one Miller tile of `pairing._miller_loop_impl` to a jaxpr.

    Pallas kernels may not close over array constants (the field modulus,
    the x-bit schedule, the reduction masks, the twist coefficients …),
    so the tile graph is traced ONCE here and its constants are shipped
    to the kernel as extra pallas inputs; the kernel replays the exact
    same jaxpr on VMEM values via `eval_jaxpr` — bit-identicality to the
    XLA path is by construction, not by reimplementation."""
    from . import pairing  # deferred: pairing dispatches back into this module

    struct = jax.ShapeDtypeStruct
    return jax.make_jaxpr(
        lambda a, b, c, d: pairing._miller_loop_impl(a, b, None, c, d, None)
    )(
        struct((MILLER_TILE, N_LIMBS), jnp.int32),
        struct((MILLER_TILE, N_LIMBS), jnp.int32),
        struct((MILLER_TILE, 2, N_LIMBS), jnp.int32),
        struct((MILLER_TILE, 2, N_LIMBS), jnp.int32),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _miller_tiles(xp, yp, xq, yq, interpret: bool):
    """xp/yp (n, 32), xq/yq (n, 2, 32) with n % MILLER_TILE == 0.

    Each program reads one tile, replays the full 63-iteration Miller
    loop on VMEM-resident values (accumulator, ladder point, line
    evaluations all stay on-core across iterations), and writes the
    Fp12 result once."""
    from jax import core as jax_core
    from jax.experimental import pallas as pl

    closed = _tile_jaxpr()
    consts = [jnp.asarray(c) for c in closed.consts]
    # Mosaic wants >=2-D refs: ship low-rank constants as (1, …) blocks
    # and restore the traced rank inside the kernel.
    shipped = [c.reshape((1,) * max(0, 2 - c.ndim) + c.shape) for c in consts]

    def kernel(*refs):
        (*c_refs, xp_ref, yp_ref, xq_ref, yq_ref, out_ref) = refs
        cvals = [r[...].reshape(c.shape) for r, c in zip(c_refs, consts)]
        (res,) = jax_core.eval_jaxpr(
            closed.jaxpr, cvals,
            xp_ref[...], yp_ref[...], xq_ref[...], yq_ref[...],
        )
        out_ref[...] = res

    n = xp.shape[0]

    def _const_spec(c):
        return pl.BlockSpec(c.shape, lambda i, _nd=c.ndim: (0,) * _nd)

    spec_p = pl.BlockSpec((MILLER_TILE, N_LIMBS), lambda i: (i, 0))
    spec_q = pl.BlockSpec((MILLER_TILE, 2, N_LIMBS), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(n // MILLER_TILE,),
        in_specs=[_const_spec(c) for c in shipped]
        + [spec_p, spec_p, spec_q, spec_q],
        out_specs=pl.BlockSpec(
            (MILLER_TILE, 2, 3, 2, N_LIMBS), lambda i: (i, 0, 0, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((n, 2, 3, 2, N_LIMBS), jnp.int32),
        interpret=interpret,
    )(*shipped, xp, yp, xq, yq)


def miller_loop_pallas(p_aff, q_aff, interpret: bool | None = None):
    """Drop-in for `pairing.miller_loop` (affine P, affine Q) backed by
    the VMEM-resident tile kernel.

    Accepts the framework layout — P (xp, yp) limbs (..., 32), Q (xq, yq)
    limbs (..., 2, 32), broadcastable leading batch axes — and returns
    conj(f_{|x|,Q}(P)) limbs (..., 2, 3, 2, 32), bit-identical to the XLA
    path. Padding lanes added to fill the last tile are garbage-in/
    sliced-off (all-int arithmetic: no traps, bounds hold for zero
    inputs). `interpret` defaults to automatic: compiled on TPU, the
    Pallas interpreter elsewhere (the CPU differential suite)."""
    if interpret is None:
        interpret = not _on_tpu()
    xp, yp = p_aff
    xq, yq = q_aff
    batch = jnp.broadcast_shapes(xp.shape[:-1], xq.shape[:-2])
    if batch == ():
        # unit batch axis: the axon workaround of pairing._miller_loop_impl
        out = miller_loop_pallas(
            (xp[None], yp[None]), (xq[None], yq[None]), interpret=interpret
        )
        return out[0]
    xp = jnp.broadcast_to(xp, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    yp = jnp.broadcast_to(yp, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    xq = jnp.broadcast_to(xq, batch + (2, N_LIMBS)).reshape(-1, 2, N_LIMBS)
    yq = jnp.broadcast_to(yq, batch + (2, N_LIMBS)).reshape(-1, 2, N_LIMBS)
    n = xp.shape[0]
    pad = (-n) % MILLER_TILE
    if pad:
        xp = jnp.concatenate([xp, jnp.zeros((pad, N_LIMBS), xp.dtype)], 0)
        yp = jnp.concatenate([yp, jnp.zeros((pad, N_LIMBS), yp.dtype)], 0)
        xq = jnp.concatenate([xq, jnp.zeros((pad, 2, N_LIMBS), xq.dtype)], 0)
        yq = jnp.concatenate([yq, jnp.zeros((pad, 2, N_LIMBS), yq.dtype)], 0)
    with named_scope("bls/miller_pallas"):
        out = _miller_tiles(xp, yp, xq, yq, interpret)
    return out[:n].reshape(batch + (2, 3, 2, N_LIMBS))


# --- fused full pairing (ISSUE 18) ------------------------------------------


@functools.lru_cache(maxsize=1)
def _pairing_tile_jaxpr():
    """Trace one fused-pairing tile — 2·PAIRING_TILE Miller lanes, the
    per-set Fp12 products, and the shared-inversion batched final exp —
    to a jaxpr, once.

    Same const-shipping contract as `_tile_jaxpr`: the generator point,
    the modulus, the x-bit schedules and the FE hard-part constants all
    become jaxpr consts shipped to the kernel as extra pallas inputs.
    The LODESTAR_TPU_FINAL_EXP_KS_CARRY knob latches at this first trace
    exactly like the XLA `final_exponentiation_batch` compile does."""
    from . import fp, fp12, pairing  # deferred: pairing dispatches back here
    from .points import G1_GEN_X, G1_GEN_Y

    def tile(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y):
        n = PAIRING_TILE
        neg_gy = fp.neg(G1_GEN_Y)
        xs = jnp.concatenate(
            [pk_x, jnp.broadcast_to(G1_GEN_X, (n, N_LIMBS))], 0
        )
        ys = jnp.concatenate([pk_y, jnp.broadcast_to(neg_gy, (n, N_LIMBS))], 0)
        qx = jnp.concatenate([msg_x, sig_x], 0)
        qy = jnp.concatenate([msg_y, sig_y], 0)
        fs = pairing._miller_loop_impl(xs, ys, None, qx, qy, None)
        prod = fp12.mul(fs[:n], fs[n:])
        return pairing.final_exponentiation_batch(prod)

    struct = jax.ShapeDtypeStruct
    p = struct((PAIRING_TILE, N_LIMBS), jnp.int32)
    q = struct((PAIRING_TILE, 2, N_LIMBS), jnp.int32)
    return jax.make_jaxpr(tile)(p, p, q, q, q, q)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pairing_tiles(pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, interpret: bool):
    """pk (n, 32), msg/sig (n, 2, 32) with n % PAIRING_TILE == 0 →
    final-exponentiated per-set Fp12 limbs (n, 2, 3, 2, 32).

    Each program replays the whole pairing of one tile on VMEM-resident
    values: the Miller accumulators, the per-set products and every FE
    intermediate stay on-core; one HBM round-trip per tile total."""
    from jax import core as jax_core
    from jax.experimental import pallas as pl

    closed = _pairing_tile_jaxpr()
    consts = [jnp.asarray(c) for c in closed.consts]
    shipped = [c.reshape((1,) * max(0, 2 - c.ndim) + c.shape) for c in consts]

    def kernel(*refs):
        (*c_refs, px_ref, py_ref, mx_ref, my_ref, sx_ref, sy_ref,
         out_ref) = refs
        cvals = [r[...].reshape(c.shape) for r, c in zip(c_refs, consts)]
        (res,) = jax_core.eval_jaxpr(
            closed.jaxpr, cvals,
            px_ref[...], py_ref[...], mx_ref[...], my_ref[...],
            sx_ref[...], sy_ref[...],
        )
        out_ref[...] = res

    n = pk_x.shape[0]

    def _const_spec(c):
        return pl.BlockSpec(c.shape, lambda i, _nd=c.ndim: (0,) * _nd)

    spec_p = pl.BlockSpec((PAIRING_TILE, N_LIMBS), lambda i: (i, 0))
    spec_q = pl.BlockSpec((PAIRING_TILE, 2, N_LIMBS), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(n // PAIRING_TILE,),
        in_specs=[_const_spec(c) for c in shipped]
        + [spec_p, spec_p, spec_q, spec_q, spec_q, spec_q],
        out_specs=pl.BlockSpec(
            (PAIRING_TILE, 2, 3, 2, N_LIMBS), lambda i: (i, 0, 0, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((n, 2, 3, 2, N_LIMBS), jnp.int32),
        interpret=interpret,
    )(*shipped, pk_x, pk_y, msg_x, msg_y, sig_x, sig_y)


def pairing_fused_pallas(pk_aff, msg_aff, sig_aff, interpret: bool | None = None):
    """Fused per-set pairing: final_exp(e-terms of e(pk_i, H(m_i)) ·
    e(−g1, sig_i)) limbs for every lane, VMEM-resident end to end.

    pk (xp, yp) limbs (n, 32); msg/sig (x, y) limbs (n, 2, 32). Returns
    the final-exponentiated Fp12 limbs (n, 2, 3, 2, 32) — callers finish
    with `fp12.is_one(...) & valid` exactly like the XLA route finishes
    `final_exponentiation_batch`. Bit-identical to
    `_individual_pairing_terms` + `final_exponentiation_batch` on every
    lane: the tile jaxpr composes those very functions, and the batched
    FE's per-lane-identical contract makes the tiling invisible. Padding
    lanes added to fill the last tile are garbage-in/sliced-off (the
    zero-lane guard inside the FE keeps the Montgomery prefix product
    finite for any input). `interpret` defaults to automatic: compiled
    on TPU, the Pallas interpreter elsewhere (the CPU differential
    suite)."""
    if interpret is None:
        interpret = not _on_tpu()
    pk_x, pk_y = pk_aff
    msg_x, msg_y = msg_aff
    sig_x, sig_y = sig_aff
    n = pk_x.shape[0]
    pad = (-n) % PAIRING_TILE
    if pad:
        zp = jnp.zeros((pad, N_LIMBS), pk_x.dtype)
        zq = jnp.zeros((pad, 2, N_LIMBS), msg_x.dtype)
        pk_x = jnp.concatenate([pk_x, zp], 0)
        pk_y = jnp.concatenate([pk_y, zp], 0)
        msg_x = jnp.concatenate([msg_x, zq], 0)
        msg_y = jnp.concatenate([msg_y, zq], 0)
        sig_x = jnp.concatenate([sig_x, zq], 0)
        sig_y = jnp.concatenate([sig_y, zq], 0)
    with named_scope("bls/pairing_pallas"):
        out = _pairing_tiles(
            pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, interpret
        )
    return out[:n]
