"""VMEM-resident Pallas Miller-loop tower kernel (ISSUE 14).

The XLA path (`ops/pairing.miller_loop`) lowers each Fp2/Fp6/Fp12 tower
op of the 63 doubling/addition steps as separate HLO fusions — the
running Fp12 accumulator, the line evaluations and the G2 ladder point
bounce through HBM between every field op, which is the latency wall of
the ungrouped worst case (ROADMAP item 2, VERDICT r5 #3). This kernel
moves the ENTIRE Miller loop of a batch tile inside one `pl.pallas_call`:
the accumulator, the running point T and every intermediate of the fused
line/double/add formulas stay VMEM-resident for all 63 iterations, and
each tile pays exactly one HBM round-trip (inputs in, Fp12 out).

Bit-identicality by construction: the kernel body traces the SAME
`pairing._miller_loop_impl` graph the XLA path runs — same stacked fp2
multiplies, same bounds-tracked combine scans, same `lax.scan`/`lax.cond`
step structure (Pallas supports JAX control flow inside kernels) — so
compiled and interpreted outputs match the default path limb-for-limb.
The differential suite (tests/test_pallas_tower.py) pins interpreter mode
against `miller_loop` on CPU; the existing oracle/KAT tests cover the
dispatch because `pairing.miller_loop` routes here when enabled.

Gating (`LODESTAR_TPU_PALLAS_MILLER`, registered in utils/env.py):
  auto (default) — on when the backend lowers Pallas (TPU); off elsewhere
  1/on          — forced; off-TPU runs the Pallas interpreter
  0/off         — always the XLA path

Tile geometry: MILLER_TILE batch lanes per program. The per-tile working
set is dominated by the stacked fp2 multiply stages (≤ 9 products × 2
Fp × 64 columns × 4 B ≈ 4.6 kB/lane live at once) plus the (2,3,2,32)
accumulator — 8 lanes stay well under the ~16 MB VMEM budget including
Mosaic's double buffers. Limbs ride the trailing axis as in the
framework-wide layout; correctness-first (the win targeted here is HBM
avoidance, not vreg occupancy — see ops/pallas_fp.py for the
lane-transposed treatment of a single field op).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..observability.trace import named_scope
from ..utils.env import env_str
from .limbs import N_LIMBS

MILLER_TILE = 8  # batch lanes per Pallas program (VMEM headroom: see above)

_FALSE_VALUES = ("0", "off", "false", "no", "")


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def enabled() -> bool:
    """Resolve the LODESTAR_TPU_PALLAS_MILLER tri-state for this process."""
    mode = (env_str("LODESTAR_TPU_PALLAS_MILLER") or "auto").strip().lower()
    if mode == "auto":
        return _on_tpu()
    return mode not in _FALSE_VALUES


@functools.lru_cache(maxsize=1)
def _tile_jaxpr():
    """Trace one Miller tile of `pairing._miller_loop_impl` to a jaxpr.

    Pallas kernels may not close over array constants (the field modulus,
    the x-bit schedule, the reduction masks, the twist coefficients …),
    so the tile graph is traced ONCE here and its constants are shipped
    to the kernel as extra pallas inputs; the kernel replays the exact
    same jaxpr on VMEM values via `eval_jaxpr` — bit-identicality to the
    XLA path is by construction, not by reimplementation."""
    from . import pairing  # deferred: pairing dispatches back into this module

    struct = jax.ShapeDtypeStruct
    return jax.make_jaxpr(
        lambda a, b, c, d: pairing._miller_loop_impl(a, b, None, c, d, None)
    )(
        struct((MILLER_TILE, N_LIMBS), jnp.int32),
        struct((MILLER_TILE, N_LIMBS), jnp.int32),
        struct((MILLER_TILE, 2, N_LIMBS), jnp.int32),
        struct((MILLER_TILE, 2, N_LIMBS), jnp.int32),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _miller_tiles(xp, yp, xq, yq, interpret: bool):
    """xp/yp (n, 32), xq/yq (n, 2, 32) with n % MILLER_TILE == 0.

    Each program reads one tile, replays the full 63-iteration Miller
    loop on VMEM-resident values (accumulator, ladder point, line
    evaluations all stay on-core across iterations), and writes the
    Fp12 result once."""
    from jax import core as jax_core
    from jax.experimental import pallas as pl

    closed = _tile_jaxpr()
    consts = [jnp.asarray(c) for c in closed.consts]
    # Mosaic wants >=2-D refs: ship low-rank constants as (1, …) blocks
    # and restore the traced rank inside the kernel.
    shipped = [c.reshape((1,) * max(0, 2 - c.ndim) + c.shape) for c in consts]

    def kernel(*refs):
        (*c_refs, xp_ref, yp_ref, xq_ref, yq_ref, out_ref) = refs
        cvals = [r[...].reshape(c.shape) for r, c in zip(c_refs, consts)]
        (res,) = jax_core.eval_jaxpr(
            closed.jaxpr, cvals,
            xp_ref[...], yp_ref[...], xq_ref[...], yq_ref[...],
        )
        out_ref[...] = res

    n = xp.shape[0]

    def _const_spec(c):
        return pl.BlockSpec(c.shape, lambda i, _nd=c.ndim: (0,) * _nd)

    spec_p = pl.BlockSpec((MILLER_TILE, N_LIMBS), lambda i: (i, 0))
    spec_q = pl.BlockSpec((MILLER_TILE, 2, N_LIMBS), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(n // MILLER_TILE,),
        in_specs=[_const_spec(c) for c in shipped]
        + [spec_p, spec_p, spec_q, spec_q],
        out_specs=pl.BlockSpec(
            (MILLER_TILE, 2, 3, 2, N_LIMBS), lambda i: (i, 0, 0, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((n, 2, 3, 2, N_LIMBS), jnp.int32),
        interpret=interpret,
    )(*shipped, xp, yp, xq, yq)


def miller_loop_pallas(p_aff, q_aff, interpret: bool | None = None):
    """Drop-in for `pairing.miller_loop` (affine P, affine Q) backed by
    the VMEM-resident tile kernel.

    Accepts the framework layout — P (xp, yp) limbs (..., 32), Q (xq, yq)
    limbs (..., 2, 32), broadcastable leading batch axes — and returns
    conj(f_{|x|,Q}(P)) limbs (..., 2, 3, 2, 32), bit-identical to the XLA
    path. Padding lanes added to fill the last tile are garbage-in/
    sliced-off (all-int arithmetic: no traps, bounds hold for zero
    inputs). `interpret` defaults to automatic: compiled on TPU, the
    Pallas interpreter elsewhere (the CPU differential suite)."""
    if interpret is None:
        interpret = not _on_tpu()
    xp, yp = p_aff
    xq, yq = q_aff
    batch = jnp.broadcast_shapes(xp.shape[:-1], xq.shape[:-2])
    if batch == ():
        # unit batch axis: the axon workaround of pairing._miller_loop_impl
        out = miller_loop_pallas(
            (xp[None], yp[None]), (xq[None], yq[None]), interpret=interpret
        )
        return out[0]
    xp = jnp.broadcast_to(xp, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    yp = jnp.broadcast_to(yp, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    xq = jnp.broadcast_to(xq, batch + (2, N_LIMBS)).reshape(-1, 2, N_LIMBS)
    yq = jnp.broadcast_to(yq, batch + (2, N_LIMBS)).reshape(-1, 2, N_LIMBS)
    n = xp.shape[0]
    pad = (-n) % MILLER_TILE
    if pad:
        xp = jnp.concatenate([xp, jnp.zeros((pad, N_LIMBS), xp.dtype)], 0)
        yp = jnp.concatenate([yp, jnp.zeros((pad, N_LIMBS), yp.dtype)], 0)
        xq = jnp.concatenate([xq, jnp.zeros((pad, 2, N_LIMBS), xq.dtype)], 0)
        yq = jnp.concatenate([yq, jnp.zeros((pad, 2, N_LIMBS), yq.dtype)], 0)
    with named_scope("bls/miller_pallas"):
        out = _miller_tiles(xp, yp, xq, yq, interpret)
    return out[:n].reshape(batch + (2, 3, 2, N_LIMBS))
