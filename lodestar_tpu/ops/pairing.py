"""Optimal ate pairing on BLS12-381 — device tier (jit/vmap-able JAX).

Re-implements the oracle (`bls/pairing.py`) the TPU way:
- Q stays on the twist E'(Fp2) in homogeneous projective coordinates; lines
  are evaluated through the untwist (x/w², y/w³) and scaled by w³ and by
  Fp2 denominators — both annihilated by the final exponentiation (w^N = 1
  since 6(p²−1) | N = (p¹²−1)/r), so no inversions inside the loop.
- The Miller loop is ONE `lax.scan` over the 63 parameter bits; the rare
  addition step (6 set bits in |x|) sits behind `lax.cond` with a scalar
  (unbatched) predicate, so XLA keeps it a real branch and zero-bit
  iterations skip the addition entirely — batched pairings share the branch
  because the bit pattern is the same for every lane.
- Final exponentiation: easy part (p⁶−1)(p²+1) then the HHT hard part,
  matching the oracle's convention (computes pairing³ — harmless for
  verification equations; see bls/pairing.py:104 docstring).

Conventions (MUST match the oracle bit-for-bit — differential tests):
miller_loop returns conj(f_{|x|,Q}(P)); e(O, Q) = e(P, O) = 1 handled by
the caller via masks (`pairing_check` below).

Reference analog: the blst pairing core behind verifyMultipleSignatures
(`chain/bls/maybeBatch.ts:18-27` per SURVEY.md §2.3) — here it is a
vmap'd kernel over signature sets instead of a worker-thread C call.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..bls.fields import X_PARAM
from . import fp, fp2, fp12
from .points import g2

X_ABS = abs(X_PARAM)
# MSB-first bits of |x| after the leading 1 (63 scan steps, 5 ones)
_X_BITS_TAIL = np.array([int(b) for b in bin(X_ABS)[3:]], dtype=np.int32)


# one stacked fp2 multiply over a new leading axis — the latency
# discipline of ops/points.py applied to the Miller step (same helper:
# g2 is the CurveOps instance whose field is fp2)
def _stack_mul(lhs, rhs):
    return g2._mulstack(lhs, rhs)


def _lift_fp(a):
    """Fp element (..., 32) → Fp2 with zero imaginary part, so Fp scalings
    can ride the stacked fp2 multiplies."""
    import jax.numpy as jnp

    return jnp.stack([a, jnp.zeros_like(a)], axis=-2)


def _line_and_double(t, xp_neg2, yp2, zp2, b3):
    """Fused tangent line + point doubling for the Miller step.

    Line (scaled by 2YZ²·w³, ×Zp for projective P):
        l0 = 3X³ − 2Y²Z,  l1 = 3X²Z·(−xp),  l2 = 2YZ²·yp
    Double: RCB16 Algorithm 9 (a=0) on the twist.

    The two share X², Y², Z², YZ, XY — everything runs as THREE stacked
    fp2 multiplies (5+5+7 products) instead of ~9 sequential ones
    (profile: the Miller scan body is latency-bound like the ladders).
    xp_neg2/yp2/zp2 are the G1 evaluation point lifted to Fp2 (zero
    imaginary part); zp2 is None for affine P."""
    x, y, z = t
    W = fp.wrap
    # stage A: shared quadratic monomials
    xx, yy, zz, yz, xy = _stack_mul([x, y, z, y, x], [x, y, z, z, y])
    # stage B: cubics + the b3 scaling
    xxx, yyz, xxz, yzz, t2b = _stack_mul(
        [xx, yy, xx, yz, b3], [x, z, z, z, zz]
    )
    # combines grouped into TWO bounds-tracked scans by candidate count
    # (round 4: ~12 sequential add scans)
    y3s, two_yzz, three_xxz = fp.reduce_stack(
        [W(yy) + W(t2b), W(yzz).double(),
         W(xxz).double() + W(xxz)]
    )
    z8, l0, t0c = fp.reduce_stack(
        [W(yy).double().double().double(),                      # 8Y²
         W(xxx).double() + W(xxx) - W(yyz).double(),            # 3X³ − 2Y²Z
         W(yy) - (W(t2b).double() + W(t2b))]
    )
    # stage C: line evaluations + double outputs
    lhs = [three_xxz, two_yzz, t2b, yz, t0c, t0c]
    rhs = [xp_neg2, yp2, z8, z8, y3s, xy]
    if zp2 is not None:
        lhs.append(l0)
        rhs.append(zp2)
    out = _stack_mul(lhs, rhs)
    l1, l2, x3, z3, y3m, xt = out[:6]
    if zp2 is not None:
        l0 = out[6]
    ox, oy = fp.reduce_stack([W(xt).double(), W(x3) + W(y3m)])
    t_next = (ox, oy, z3)
    return l0, l1, l2, t_next


def _line_and_add_projq(t, q_proj, xp_neg2, yp2, zp2, b3):
    """Fused chord line + FULL projective addition T+Q (Q projective).

    Same line as `_line_and_add` scaled uniformly by Zq² (a subfield
    factor, annihilated by the final exponentiation): with
    θ' = Y·Zq − Yq·Z = Zq·θ and H' = X·Zq − Xq·Z = Zq·H,
        l0 = θ'·Xq − Yq·H',  l1 = (Zq·θ')·(−xp),  l2 = (Zq·H')·yp.
    Addition: RCB16 Algorithm 7 (a=0), both operands projective — the
    grouped batch equation feeds Q lanes that come out of point sums
    (projective), and one inversion per lane would dwarf the Miller loop.
    Three stacked fp2 multiplies (8+6+9), mirroring the mixed variant."""
    x, y, z = t
    xq, yq, zq = q_proj
    W = fp.wrap
    sxy, sq = fp.reduce_sums(jnp.stack([x + y, xq + yq]))
    # stage A: RCB16 cross products + the four line cross terms
    t0, t1, t2, u, yzq, yqz, xzq, xqz = _stack_mul(
        [x, y, z, sxy, y, yq, x, xq],
        [xq, yq, zq, sq, zq, z, zq, z],
    )
    theta, h, t3, t4, y3p, x3 = fp.reduce_stack(
        [W(yzq) - W(yqz),              # Zq·(Y − yq·Z)
         W(xzq) - W(xqz),              # Zq·(X − xq·Z)
         W(u) - W(t0) - W(t1),
         W(yzq) + W(yqz),
         W(xzq) + W(xqz),
         W(t0).double() + W(t0)]
    )
    # stage B: b3 scalings + line products
    t2b, th_xq, yq_h, thz, hz, y3 = _stack_mul(
        [b3, theta, yq, zq, zq, b3], [t2, xq, h, theta, h, y3p]
    )
    l0, z3, t1m = fp.reduce_stack(
        [W(th_xq) - W(yq_h), W(t1) + W(t2b), W(t1) - W(t2b)]
    )
    # stage C: addition outputs + the two line evaluations (+ optional l0·zp)
    lhs = [t3, t4, y3, t1m, z3, x3, thz, hz]
    rhs = [t1m, y3, x3, z3, t4, t3, xp_neg2, yp2]
    if zp2 is not None:
        lhs.append(l0)
        rhs.append(zp2)
    out = _stack_mul(lhs, rhs)
    a, b, c, d, e, f, l1, l2 = out[:8]
    if zp2 is not None:
        l0 = out[8]
    ox, oy, oz = fp.reduce_stack(
        [W(a) - W(b), W(c) + W(d), W(e) + W(f)]
    )
    return l0, l1, l2, (ox, oy, oz)


def _line_and_add(t, q_aff, xp_neg2, yp2, zp2, b3):
    """Fused chord line + mixed addition T+Q for the Miller step.

    Line (scaled by H·w³, ×Zp for projective P) with θ = Y − yq·Z,
    H = X − xq·Z:  l0 = θ·xq − yq·H,  l1 = θ·(−xp),  l2 = H·yp.
    Addition: RCB16 Algorithm 8 (a=0), Q affine. Three stacked fp2
    multiplies (6+6+7) instead of ~9 sequential."""
    x, y, z = t
    xq, yq = q_aff
    W = fp.wrap
    sxy, sq = fp.reduce_sums(jnp.stack([x + y, xq + yq]))
    # stage A: line + addition cross products (xq·z / yq·z shared)
    t0, t1, u, xqz, yqz, b3z = _stack_mul(
        [x, y, sxy, xq, yq, b3], [xq, yq, sq, z, z, z]
    )
    theta, h, t3, y3p, t4, x3, z3, t1m = fp.reduce_stack(
        [W(y) - W(yqz),
         W(x) - W(xqz),
         W(u) - W(t0) - W(t1),
         W(xqz) + W(x),
         W(yqz) + W(y),
         W(t0).double() + W(t0),
         W(t1) + W(b3z),
         W(t1) - W(b3z)]
    )
    # stage B: line products + the b3·y3p scaling
    th_xq, yq_h, l1, l2, y3 = _stack_mul(
        [theta, yq, theta, h, b3], [xq, h, xp_neg2, yp2, y3p]
    )
    l0 = fp2.sub(th_xq, yq_h)
    # stage C: addition outputs (+ optional l0·zp)
    lhs = [t3, t4, y3, t1m, z3, x3]
    rhs = [t1m, y3, x3, z3, t4, t3]
    if zp2 is not None:
        lhs.append(l0)
        rhs.append(zp2)
    out = _stack_mul(lhs, rhs)
    a, b, c, d, e, f = out[:6]
    if zp2 is not None:
        l0 = out[6]
    ox, oy, oz = fp.reduce_stack(
        [W(a) - W(b), W(c) + W(d), W(e) + W(f)]
    )
    return l0, l1, l2, (ox, oy, oz)


def miller_loop(p_aff, q_aff):
    """f = conj(f_{|x|,Q}(P)) for P ∈ G1 affine (xp, yp limbs), Q ∈ G2
    affine ((2,32)-limb coords). Batched over leading axes; does NOT handle
    infinity — callers mask (see `pairing_check`).

    When LODESTAR_TPU_PALLAS_MILLER resolves on (auto: TPU backends) the
    affine loop runs the VMEM-resident Pallas tower kernel
    (`ops/pallas_tower.py`) — bit-identical, one HBM round-trip per batch
    tile instead of one per field op. The projective variants below keep
    the XLA path (their lanes come out of fused point sums already)."""
    from . import pallas_tower

    if pallas_tower.enabled():
        return pallas_tower.miller_loop_pallas(p_aff, q_aff)
    return _miller_loop_impl(p_aff[0], p_aff[1], None, q_aff[0], q_aff[1], None)


def miller_loop_projective(p_proj, q_aff):
    """Same as `miller_loop` but P = (Xp, Yp, Zp) homogeneous projective —
    equal post-final-exp, up to the Zp^k subfield scale (see `_line_dbl`).
    Zp = 0 lanes produce garbage; callers mask them."""
    return _miller_loop_impl(
        p_proj[0], p_proj[1], p_proj[2], q_aff[0], q_aff[1], None
    )


def miller_loop_proj_pq(p_proj, q_proj):
    """P AND Q homogeneous projective — equal post-final-exp up to Zp/Zq
    subfield scales. The grouped batch equation's form: its Q lanes come
    out of on-device point sums (projective), and a per-lane Fp2 inversion
    (~570 sequential multiplies via Fermat) would dwarf the whole Miller
    loop. Zp = 0 or Zq = 0 lanes produce garbage; callers mask them."""
    return _miller_loop_impl(
        p_proj[0], p_proj[1], p_proj[2], q_proj[0], q_proj[1], q_proj[2]
    )


def _miller_loop_impl(xp, yp, zp, xq, yq, zq):
    batch = jnp.broadcast_shapes(xp.shape[:-1], xq.shape[:-2])
    # Axon-backend workaround: rank-4 (unbatched) fp12 chains miscompile on
    # the experimental TPU platform (observed: final_exponentiation gives
    # different results scalar vs batched on identical inputs, 2026-07).
    # A unit batch axis costs nothing and keeps every deep chain batched.
    if batch == ():
        out = _miller_loop_impl(
            xp[None],
            yp[None],
            None if zp is None else zp[None],
            xq[None],
            yq[None],
            None if zq is None else zq[None],
        )
        return out[0]
    xp = jnp.broadcast_to(xp, batch + xp.shape[-1:])
    yp = jnp.broadcast_to(yp, batch + yp.shape[-1:])
    if zp is not None:
        zp = jnp.broadcast_to(zp, batch + zp.shape[-1:])
    xq = jnp.broadcast_to(xq, batch + xq.shape[-2:])
    yq = jnp.broadcast_to(yq, batch + yq.shape[-2:])
    if zq is not None:
        zq = jnp.broadcast_to(zq, batch + zq.shape[-2:])
    # lift the G1 evaluation point into Fp2 once so its scalings join the
    # fused stacked multiplies of _line_and_double/_line_and_add
    xp_neg2 = _lift_fp(fp.neg(xp))
    yp2 = _lift_fp(yp)
    zp2 = None if zp is None else _lift_fp(zp)
    b3 = g2.b3

    t0 = g2.from_affine(xq, yq) if zq is None else (xq, yq, zq)
    f0 = fp12.one(batch)

    def step(carry, bit):
        t, f = carry
        l0, l1, l2, t = _line_and_double(t, xp_neg2, yp2, zp2, b3)
        f = fp12.mul_by_line(fp12.square(f), l0, l1, l2)

        def with_add(operand):
            t_in, f_in = operand
            if zq is None:
                a0, a1, a2, t_out = _line_and_add(
                    t_in, (xq, yq), xp_neg2, yp2, zp2, b3
                )
            else:
                a0, a1, a2, t_out = _line_and_add_projq(
                    t_in, (xq, yq, zq), xp_neg2, yp2, zp2, b3
                )
            f_out = fp12.mul_by_line(f_in, a0, a1, a2)
            return t_out, f_out

        t, f = lax.cond(bit != 0, with_add, lambda o: o, (t, f))
        return (t, f), None

    (t_final, f), _ = lax.scan(step, (t0, f0), jnp.asarray(_X_BITS_TAIL))
    del t_final
    return fp12.conj(f)


def _pow_x_abs(g):
    """g^|x| via square-and-multiply scan (63 squarings, 5 multiplies behind
    a scalar-predicate cond). Callers are all inside the final
    exponentiation's hard part, so g is cyclotomic and the squarings use
    the Granger–Scott form (9 Fp2 squares vs 12 Fp2 products)."""

    def step(acc, bit):
        acc = fp12.cyclotomic_square(acc)
        acc = lax.cond(bit != 0, lambda a: fp12.mul(a, g), lambda a: a, acc)
        return acc, None

    acc, _ = lax.scan(step, g, jnp.asarray(_X_BITS_TAIL))
    return acc


def _pow_x(g):
    """g^x, x negative: g^|x| then conjugate (cyclotomic inverse)."""
    return fp12.conj(_pow_x_abs(g))


def _hard_part(f):
    """HHT hard part on a cyclotomic element (computes pairing³ —
    preserves == 1 checks since 3 ∤ r)."""

    def pow_x_minus_1(g):
        return fp12.mul(_pow_x(g), fp12.conj(g))

    a = pow_x_minus_1(pow_x_minus_1(f))
    b = fp12.mul(_pow_x(a), fp12.frobenius(a, 1))
    c = fp12.mul(
        fp12.mul(_pow_x(_pow_x(b)), fp12.frobenius(b, 2)), fp12.conj(b)
    )
    f3 = fp12.mul(fp12.mul(f, f), f)
    return fp12.mul(c, f3)


def final_exponentiation(f):
    """Easy part then HHT hard part — mirrors oracle final_exponentiation
    (computes pairing³; preserves == 1 checks since 3 ∤ r)."""
    if f.ndim == 4:
        # unit-batch wrapper: see the axon-backend note in _miller_loop_impl
        return final_exponentiation(f[None])[0]
    f = fp12.mul(fp12.conj(f), fp12.inv(f))  # f^(p⁶−1)
    f = fp12.mul(fp12.frobenius(f, 2), f)  # ^(p²+1): cyclotomic now
    return _hard_part(f)


def final_exponentiation_batch(fs):
    """`final_exponentiation` over axis 0 with the easy part's Fp12
    inversion AMORTIZED: fp12.batch_inv runs ONE Fermat inversion chain
    for the whole batch (Montgomery product trick) instead of one ~570-
    sequential-multiply chain per lane. The hard part is already pure
    vmapped scan work and shares its latency across lanes for free.

    The shared final-exp entry for EVERY verdict path (ISSUE 14): the
    per-set/grouped/pk-grouped/bisect kernels and their sharded twins all
    route here (`final_exponentiation_one` for single products). Two
    contracts beyond the per-lane form:

    - zero lanes are SAFE: a zero lane would poison the whole batch
      through the Montgomery product, so zero lanes are substituted with
      the identity before `batch_inv` and their inverse forced back to
      zero afterwards — exactly what the per-lane Fermat chain computes
      for zero (0^(p−2) = 0), keeping the entry bit-identical to
      per-lane `final_exponentiation` on EVERY input (differential tests
      in tests/test_ops_pairing.py and tests/test_final_exp_batch.py).
    - the hard part's ~1,000 sequential small muls can run the scan-free
      Kogge–Stone carry (`fp.ks_carry`) via
      LODESTAR_TPU_FINAL_EXP_KS_CARRY=1; measured on the CPU backend the
      carry_scan default stays (compile/runtime numbers in
      docs/architecture.md §"Final-exp batching & Pallas Miller loop"),
      and the knob is confined to THIS kernel — the site count elsewhere
      blows the compile budget (fp.py round-2 lesson).
    """
    from ..utils.env import env_bool

    carry_ctx = (
        fp.carry_form(fp._ks_carry_impl)
        if env_bool("LODESTAR_TPU_FINAL_EXP_KS_CARRY")
        else fp.carry_form(None)
    )
    with carry_ctx:
        nz = ~jnp.all(fp.canonical(fs) == 0, axis=(-1, -2, -3, -4))
        safe = fp12.select(nz, fs, fp12.one(fs.shape[:-4]))
        inv = fp12.select(nz, fp12.batch_inv(safe), fp12.zero(fs.shape[:-4]))
        f = fp12.mul(fp12.conj(fs), inv)  # f^(p⁶−1)
        f = fp12.mul(fp12.frobenius(f, 2), f)  # ^(p²+1): cyclotomic now
        return _hard_part(f)


def final_exponentiation_one(f):
    """Final exponentiation of ONE product, routed through the shared
    batched kernel: a unit batch axis keeps deep fp12 chains batched (the
    axon workaround in `_miller_loop_impl`) and keeps every verdict path
    on a single consensus-critical final-exp implementation. For n = 1
    `fp12.batch_inv` degenerates to `fp12.inv`, so this is bit-identical
    to per-lane `final_exponentiation`."""
    return final_exponentiation_batch(f[None])[0]


def pairing(p_aff, q_aff):
    return final_exponentiation(miller_loop(p_aff, q_aff))


def pairing_check(p_affs, q_affs, valid_mask):
    """Π_i e(P_i, Q_i) == 1 over the batch axis 0 (the multi-pairing
    verification primitive, oracle: bls/pairing.multi_pairing).

    p_affs = (xp, yp) with leading batch axis; q_affs = (xq, yq) likewise;
    valid_mask (batch,) bool — False lanes contribute 1 (the e(O, ·) = 1
    convention for infinity inputs).
    """
    if p_affs[0].shape[0] == 0:
        return jnp.asarray(True)  # empty product == 1 (vacuous truth)
    fs = miller_loop(p_affs, q_affs)
    fs = fp12.select(valid_mask, fs, fp12.one(fs.shape[:-4]))
    return fp12.is_one(final_exponentiation_one(fp12.product_tree(fs)))
