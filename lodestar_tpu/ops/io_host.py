"""Host-side conversions between oracle objects and device limb arrays.

The oracle tier (`lodestar_tpu/bls`) speaks Python big ints; the device tier
(`lodestar_tpu/ops`) speaks (..., 32) int32 Montgomery limb vectors. These
helpers cross that boundary — they run on the host only and are NOT
jit-compatible.
"""

from __future__ import annotations

import numpy as np

from ..bls.curve import PointG1, PointG2
from ..bls.fields import Fq, Fq2, Fq6, Fq12
from .limbs import N_LIMBS, fp_from_mont_host, fp_to_mont_host


def fq_to_limbs(x: Fq) -> np.ndarray:
    return fp_to_mont_host(x.n)


def limbs_to_fq(a) -> Fq:
    return Fq(fp_from_mont_host(np.asarray(a)))


def fq2_to_limbs(x: Fq2) -> np.ndarray:
    return np.stack([fp_to_mont_host(x.c0.n), fp_to_mont_host(x.c1.n)])


def limbs_to_fq2(a) -> Fq2:
    a = np.asarray(a)
    return Fq2(limbs_to_fq(a[0]), limbs_to_fq(a[1]))


def fq6_to_limbs(x: Fq6) -> np.ndarray:
    return np.stack([fq2_to_limbs(x.c0), fq2_to_limbs(x.c1), fq2_to_limbs(x.c2)])


def limbs_to_fq6(a) -> Fq6:
    a = np.asarray(a)
    return Fq6(limbs_to_fq2(a[0]), limbs_to_fq2(a[1]), limbs_to_fq2(a[2]))


def fq12_to_limbs(x: Fq12) -> np.ndarray:
    return np.stack([fq6_to_limbs(x.c0), fq6_to_limbs(x.c1)])


def limbs_to_fq12(a) -> Fq12:
    a = np.asarray(a)
    return Fq12(limbs_to_fq6(a[0]), limbs_to_fq6(a[1]))


def g1_affine_to_limbs(p: PointG1) -> tuple[np.ndarray, np.ndarray, bool]:
    """→ (x, y) Montgomery limbs + infinity flag (coords zeroed at infinity)."""
    aff = p.to_affine()
    if aff is None:
        z = np.zeros(N_LIMBS, np.int32)
        return z, z.copy(), True
    return fq_to_limbs(aff[0]), fq_to_limbs(aff[1]), False


def g2_affine_to_limbs(p: PointG2) -> tuple[np.ndarray, np.ndarray, bool]:
    """→ (x, y) each (2, 32) Montgomery limbs + infinity flag."""
    aff = p.to_affine()
    if aff is None:
        z = np.zeros((2, N_LIMBS), np.int32)
        return z, z.copy(), True
    return fq2_to_limbs(aff[0]), fq2_to_limbs(aff[1]), False


def scalar_to_bits(r: int, nbits: int) -> np.ndarray:
    """Scalar → (nbits,) int32 bit vector, MSB first (device scan order)."""
    if not 0 <= r < (1 << nbits):
        raise ValueError("scalar out of range")
    return np.array([(r >> (nbits - 1 - i)) & 1 for i in range(nbits)], np.int32)
