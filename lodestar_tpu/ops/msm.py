"""Multi-scalar multiplication by bit-planes (device tier).

The grouped batch-verification equation needs Σ_i k_i·P_i for 32-bit
scalars k_i — per root-group on the pubkey side, globally on the signature
side (SURVEY §2.3 "aggregate-pubkey G1 MSM as vmap'd XLA kernels";
reference analog: blst's per-set jacobian pubkey aggregation,
`chain/bls/utils.ts:5-16`, lifted to whole-batch scale).

Per-lane double-and-add ladders cost 2·nbits point ops per POINT. Here the
sum is decomposed by bit-plane instead:

    Σ_i k_i·P_i = Σ_b 2^b · U_b,   U_b = Σ_{i: bit b of k_i} P_i

and each U_b is a masked sum — nbits point ops per point, with two more
structural wins on top:

- subset-4 sharing: lanes are grouped in fours and all 16 subset sums of
  each group are precomputed ONCE (11 adds per group, shared by every
  bit-plane); a plane then gathers its subset by the 4-bit mask and
  tree-reduces over groups. Per-plane work drops from L−1 to L/4 adds.
- the power-of-two recombination (Σ 2^b·U_b) is the CALLER's problem —
  the batch verifier never materializes it, pairing each U_b against a
  precomputed −[2^b]g1 constant instead (`points.NEG_G1_POW2_*`), or
  Horner-combining across lanes where it must (per-root pubkey sums).

Everything is static-shape, branch-free, and generic over the coordinate
field via `CurveOps` (G1 and G2 alike).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _bsl(curve, a, sl):
    """Slice the last batch axis (the one just before the coord axes)."""
    return a[(Ellipsis, sl) + (slice(None),) * curve.coord_ndim]


def tree_sum(curve, p):
    """log-depth complete-add reduction over the last batch axis.

    p: (X, Y, Z) projective with shape (..., n, *coord) → (..., *coord).
    """
    n = p[0].shape[-1 - curve.coord_ndim]
    while n > 1:
        half = n // 2
        a = tuple(_bsl(curve, c, slice(0, half)) for c in p)
        b = tuple(_bsl(curve, c, slice(half, 2 * half)) for c in p)
        s = curve.add(a, b)
        if n % 2:
            tail = tuple(_bsl(curve, c, slice(2 * half, n)) for c in p)
            s = tuple(
                jnp.concatenate([sc, tc], axis=-1 - curve.coord_ndim)
                for sc, tc in zip(s, tail)
            )
        p = s
        n = p[0].shape[-1 - curve.coord_ndim]
    return tuple(_bsl(curve, c, 0) for c in p)


def subset_table4(curve, p4):
    """All 16 subset sums of 4 projective points.

    p4: (..., 4, *coord) → (..., 16, *coord); entry m sums the lanes whose
    bit is set in m (entry 0 = infinity). 11 complete adds in 3 stacked
    calls (6 pairs, 4 triples, 1 quad) — shared by every bit-plane that
    gathers from the table.
    """
    cn = curve.coord_ndim
    pt = [tuple(_bsl(curve, c, k) for c in p4) for k in range(4)]

    def stk(pts):
        return tuple(jnp.stack([q[i] for q in pts], axis=0) for i in range(3))

    def unstk(s, k):
        return tuple(c[k] for c in s)

    # pairs: 0+1, 0+2, 1+2, 0+3, 1+3, 2+3
    pr = curve.add(
        stk([pt[0], pt[0], pt[1], pt[0], pt[1], pt[2]]),
        stk([pt[1], pt[2], pt[2], pt[3], pt[3], pt[3]]),
    )
    p01, p02, p12, p03, p13, p23 = (unstk(pr, k) for k in range(6))
    # triples: 0+1+2, 0+1+3, 0+2+3, 1+2+3
    tr = curve.add(
        stk([p01, p01, p02, p12]), stk([pt[2], pt[3], pt[3], pt[3]])
    )
    t012, t013, t023, t123 = (unstk(tr, k) for k in range(4))
    # quad
    quad = curve.add(t012, pt[3])

    inf = curve.infinity(pt[0][0].shape[: pt[0][0].ndim - cn])
    entries = [
        inf, pt[0], pt[1], p01, pt[2], p02, p12, t012,
        pt[3], p03, p13, t013, p23, t023, t123, quad,
    ]
    return tuple(
        jnp.stack([e[i] for e in entries], axis=-1 - cn) for i in range(3)
    )


def masked_plane_sums(curve, p, bits):
    """Per-bit-plane masked sums: U_t = Σ_l bits[..., l, t]·P_l.

    p: projective (..., L, *coord), L % 4 == 0; bits: (..., L, T) in {0,1}.
    Returns (T, ..., *coord) projective — plane axis LEADING so callers
    can scan/slice it.
    """
    cn = curve.coord_ndim
    L = p[0].shape[-1 - cn]
    T = bits.shape[-1]
    batch = p[0].shape[: -1 - cn]
    G = L // 4
    p4 = tuple(c.reshape(batch + (G, 4) + c.shape[-cn:]) for c in p)
    table = subset_table4(curve, p4)  # (..., G, 16, *coord)
    # 4-bit subset index per (group, plane)
    b4 = bits.reshape(batch + (G, 4, T))
    idx = (
        b4[..., 0, :] + (b4[..., 1, :] << 1) + (b4[..., 2, :] << 2)
        + (b4[..., 3, :] << 3)
    )  # (..., G, T)
    planes = tuple(
        jnp.take_along_axis(
            c, idx.reshape(idx.shape + (1,) * cn), axis=-1 - cn
        )
        for c in table
    )  # (..., G, T, *coord)
    # plane axis to the front, keep G last for the tree
    planes = tuple(jnp.moveaxis(c, -1 - cn, 0) for c in planes)  # (T,...,G,)
    return tree_sum(curve, planes)  # (T, ..., *coord)


def horner_pow2(curve, planes):
    """Σ_t 2^t · planes[t] over the LEADING plane axis (LSB first).

    31 doublings + 32 complete adds as one lax.scan — used where the
    power-of-two recombination cannot ride constant Miller lanes (the
    per-root pubkey sums, which pair against variable H(m) points).
    Vectorize the trailing batch axes to amortize the sequential depth.
    """
    cn = curve.coord_ndim
    batch = planes[0].shape[1 : planes[0].ndim - cn]
    xs = tuple(jnp.flip(c, axis=0) for c in planes)  # MSB first

    def step(acc, plane):
        return curve.add(curve.double(acc), plane), None

    acc, _ = lax.scan(step, curve.infinity(batch), xs)
    return acc
