"""Pallas TPU kernel for the Montgomery field multiply — the innermost
hot op of the pairing pipeline (SURVEY.md §7 hard part #1).

The XLA path (`ops/fp.mul`) materializes the 64-column convolution and
the 32-step REDC scan as separate HLOs with HBM traffic between fusions;
this kernel keeps the entire schoolbook product + Montgomery reduction +
carry propagation in VMEM for a batch tile — one HBM round-trip per tile.

Layout: Pallas tiling wants the last axis = 128 lanes, so the kernel
works on (limbs, batch) blocks — limbs (32/64) on the sublane axis,
batch elements on the lane axis (full 128-lane vregs; the batch-major
layout would use 32/128 lanes). The wrapper transposes from the
framework-wide batch-leading `(..., 32)` layout, pads the batch to a
lane multiple, and restores the layout afterwards.

ROUND-2 REWRITE: the round-1 kernel used `.at[i:i+32].add(...)`
(scatter-add), which Mosaic does not lower (`NotImplementedError:
scatter-add` on real TPU — it only ever ran interpreted). All shifted
accumulations are now static `jnp.pad`s (concatenate lowers fine), so
the kernel compiles for the TC core.

`interpret=True` (automatic off-TPU) runs the same kernel through the
Pallas interpreter, so the differential suite covers it on the CPU
backend; on TPU hardware the compiled kernel is used.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .limbs import LIMB_BITS, LIMB_MASK, N_LIMBS, N0, P_LIMBS

LANES = 128


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _shift_rows(x, down: int, total: int):
    """Pad x (r, L) with `down` zero rows above, to `total` rows."""
    return jnp.pad(x, ((down, total - down - x.shape[0]), (0, 0)))


def _mont_mul_kernel(a_ref, b_ref, p_ref, n0_ref, out_ref):
    """One batch tile: a,b (N, LANES) int32 → REDC(a*b) (N, LANES).

    All intermediates are VMEM values; loops are Python-static, so the
    kernel unrolls into straight-line VPU code with no scatter/gather."""
    a = a_ref[:]
    b = b_ref[:]
    p = p_ref[:]          # (N, LANES) broadcast column of P limbs
    n0 = n0_ref[0, 0]

    n = N_LIMBS
    # schoolbook convolution into 2N uncarried int32 columns: row k of t
    # is Σ_{i+j=k} a_i·b_j — each a-row contributes a shifted copy of
    # a_i * b.
    t = jnp.zeros((2 * n, a.shape[1]), jnp.int32)
    for i in range(n):
        t = t + _shift_rows(a[i, :][None, :] * b, i, 2 * n)

    # word-serial Montgomery reduction: kill one low limb per step.
    # Row updates are built as whole-tensor adds of padded deltas
    # (no scatter): t += shift(m·p, i); then fold row i's residue into
    # row i+1 and zero row i.
    for i in range(n):
        row = t[i, :][None, :]
        m = (row * n0) & LIMB_MASK
        t = t + _shift_rows(m * p, i, 2 * n)
        row = t[i, :][None, :]
        carry = row >> LIMB_BITS
        t = t + _shift_rows(carry, i + 1, 2 * n) - _shift_rows(row, i, 2 * n)

    # carry propagation over the high half → canonical 12-bit limbs.
    # Three shift-folds bring digits to [0, 2^12], then a generate/
    # propagate Kogge-Stone prefix resolves the ±1 chain (log depth —
    # all row shifts are pads, VPU-only).
    hi = t[n:, :]

    def fold(x):
        c = x >> LIMB_BITS
        return (x & LIMB_MASK) + _shift_rows(c[:-1, :], 1, n)

    v = fold(fold(fold(hi)))
    g = (v > LIMB_MASK).astype(jnp.int32)
    pr = (v == LIMB_MASK).astype(jnp.int32)
    shift = 1
    while shift < n:
        g_prev = _shift_rows(g[:-shift, :], shift, n)
        p_prev = _shift_rows(pr[:-shift, :], shift, n)
        g = g | (pr & g_prev)
        pr = pr & p_prev
        shift *= 2
    carry_in = _shift_rows(g[:-1, :], 1, n)
    out_ref[:] = (v + carry_in) & LIMB_MASK


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mont_mul_tiles(a_t: jnp.ndarray, b_t: jnp.ndarray, interpret: bool):
    """a_t, b_t: (N_LIMBS, batch_padded) — batch_padded % LANES == 0."""
    p = jnp.broadcast_to(
        jnp.asarray(P_LIMBS, jnp.int32)[:, None], (N_LIMBS, LANES)
    )
    n0 = jnp.full((1, 1), N0, jnp.int32)
    n_tiles = a_t.shape[1] // LANES
    return pl.pallas_call(
        _mont_mul_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, i)),
            pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, i)),
            pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct(a_t.shape, jnp.int32),
        interpret=interpret,
    )(a_t, b_t, p, n0)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, interpret: bool | None = None) -> jnp.ndarray:
    """Drop-in for `ops.fp.mul` backed by the Pallas kernel.

    Accepts the framework layout `(..., N_LIMBS)` with broadcastable batch
    axes; same [0,2p) lazy-reduction contract as fp.mul."""
    if interpret is None:
        interpret = not _on_tpu()
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    b = jnp.broadcast_to(b, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    n = a.shape[0]
    pad = (-n) % LANES
    if pad:
        a = jnp.concatenate([a, jnp.zeros((pad, N_LIMBS), a.dtype)], axis=0)
        b = jnp.concatenate([b, jnp.zeros((pad, N_LIMBS), b.dtype)], axis=0)
    out_t = _mont_mul_tiles(a.T, b.T, interpret)
    out = out_t.T[:n]
    return out.reshape(batch + (N_LIMBS,))
