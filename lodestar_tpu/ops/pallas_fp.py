"""Pallas TPU kernel for the Montgomery field multiply — the innermost
hot op of the pairing pipeline (SURVEY.md §7 hard part #1).

The XLA path (`ops/fp.mul`) materializes the 64-column convolution
between HLO ops; the Pallas kernel keeps the entire schoolbook product +
Montgomery reduction + carry propagation in VMEM for a batch tile, one
HBM round-trip per tile.

Layout: Pallas tiling wants the last axis = 128 lanes, so the kernel
works on (limbs, batch) blocks — limbs (32/64) on the sublane axis,
batch elements on the lane axis. The wrapper transposes from the
framework-wide batch-leading `(..., 32)` layout, pads the batch to a
lane multiple, and restores the layout afterwards.

`interpret=True` (automatic off-TPU) runs the same kernel through the
Pallas interpreter, so the differential suite covers it on the CPU
backend; on TPU hardware the compiled kernel is used.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .limbs import LIMB_BITS, LIMB_MASK, N_LIMBS, N0, P_LIMBS

LANES = 128


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _mont_mul_kernel(a_ref, b_ref, p_ref, out_ref):
    """One batch tile: a,b (N_LIMBS, LANES) int32 → REDC(a*b) (N_LIMBS, LANES).

    All intermediates are VMEM values; loops are Python-static (32 limbs),
    so the kernel unrolls into straight-line VPU code."""
    a = a_ref[:]
    b = b_ref[:]
    p = p_ref[:]

    # schoolbook convolution into 2*N_LIMBS uncarried int32 columns
    t = jnp.zeros((2 * N_LIMBS, a.shape[1]), jnp.int32)
    for i in range(N_LIMBS):
        t = t.at[i : i + N_LIMBS, :].add(a[i : i + 1, :] * b)

    # word-serial Montgomery reduction: kill one low limb per step
    for i in range(N_LIMBS):
        m = (t[i : i + 1, :] * N0) & LIMB_MASK
        t = t.at[i : i + N_LIMBS, :].add(m * p)
        carry = t[i : i + 1, :] >> LIMB_BITS
        t = t.at[i + 1 : i + 2, :].add(carry)
        t = t.at[i : i + 1, :].set(0)

    # carry propagation over the high half → canonical 12-bit limbs
    hi = t[N_LIMBS:, :]
    carry = jnp.zeros((1, a.shape[1]), jnp.int32)
    rows = []
    for i in range(N_LIMBS):
        v = hi[i : i + 1, :] + carry
        rows.append(v & LIMB_MASK)
        carry = v >> LIMB_BITS
    out_ref[:] = jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mont_mul_tiles(a_t: jnp.ndarray, b_t: jnp.ndarray, interpret: bool):
    """a_t, b_t: (N_LIMBS, batch_padded) — batch_padded % LANES == 0."""
    p = jnp.asarray(P_LIMBS, jnp.int32)[:, None] * jnp.ones((1, LANES), jnp.int32)
    n_tiles = a_t.shape[1] // LANES
    return pl.pallas_call(
        _mont_mul_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, i)),
            pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, i)),
            pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((N_LIMBS, LANES), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct(a_t.shape, jnp.int32),
        interpret=interpret,
    )(a_t, b_t, p)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, interpret: bool | None = None) -> jnp.ndarray:
    """Drop-in for `ops.fp.mul` backed by the Pallas kernel.

    Accepts the framework layout `(..., N_LIMBS)` with broadcastable batch
    axes; same [0,2p) lazy-reduction contract as fp.mul."""
    if interpret is None:
        interpret = not _on_tpu()
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    b = jnp.broadcast_to(b, batch + (N_LIMBS,)).reshape(-1, N_LIMBS)
    n = a.shape[0]
    pad = (-n) % LANES
    if pad:
        a = jnp.concatenate([a, jnp.zeros((pad, N_LIMBS), a.dtype)], axis=0)
        b = jnp.concatenate([b, jnp.zeros((pad, N_LIMBS), b.dtype)], axis=0)
    out_t = _mont_mul_tiles(a.T, b.T, interpret)
    out = out_t.T[:n]
    return out.reshape(batch + (N_LIMBS,))
