"""Fp2 = Fp[u]/(u²+1) on int32 limb vectors (device tier).

An Fp2 element is a (..., 2, 32) int32 array: axis -2 indexes (c0, c1) of
c0 + c1·u, axis -1 is the 12-bit limb axis from `limbs.py`. All leading axes
are batch axes.

Kernel-shape note: the Karatsuba product runs as ONE stacked `fp.mul` call
(3 base-field products stacked on a new leading axis), so a tower
multiplication costs a single Montgomery-reduction scan over a 3x-wider
batch — sequential depth stays constant while the VPU lanes fill up. The
same trick compounds up the tower (fp6, fp12).

Oracle: `lodestar_tpu/bls/fields.Fq2`.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import fp
from .limbs import N_LIMBS


def _split(a):
    return a[..., 0, :], a[..., 1, :]


def _join(c0, c1):
    return jnp.stack([c0, c1], axis=-2)


def add(a, b):
    return fp.add(a, b)  # fp ops are elementwise over all leading axes


# stacked-add discipline: elementwise over the (2, 32) coord block too
reduce_sums = fp.reduce_sums
TWO_P = fp.TWO_P


def sub(a, b):
    return fp.sub(a, b)


def neg(a):
    return fp.neg(a)


def double(a):
    return fp.add(a, a)


def _bcast(a, b):
    batch = jnp.broadcast_shapes(a.shape[:-2], b.shape[:-2])
    return (
        jnp.broadcast_to(a, batch + a.shape[-2:]),
        jnp.broadcast_to(b, batch + b.shape[-2:]),
    )


def _lazy_enabled() -> bool:
    from ..utils.env import env_bool

    return env_bool("LODESTAR_TPU_LAZY_FP2")


def _lazy_max_elems() -> int:
    from ..utils.env import env_int

    return env_int("LODESTAR_TPU_LAZY_FP2_MAX_ELEMS")


def _use_lazy(big_a) -> bool:
    """Lazy reduction doubles the live intermediate width (64 columns);
    at the grouped kernel's subset-table shapes (75M-element stacks) that
    tipped the 64×256 gossip batch over HBM — huge stacked products fall
    back to the classic 3-multiply form (whose REDC interleaves in-scan
    and keeps the working set at 32 limbs)."""
    if not _lazy_enabled():
        return False
    n = 1
    for d in big_a.shape:
        n *= d
    return n <= _lazy_max_elems()


def mul(a, b):
    """Karatsuba product.

    Default: LAZY REDUCTION — 3 convolutions + 2 Montgomery reductions
    (blst applies the same trick to this tower): the Karatsuba combines
    happen on unreduced 64-column products, so one whole REDC is saved
    per Fp2 product. c0's subtraction is offset by the constant 4p²
    (keeping the value non-negative; soundness bound in `fp.redc_cols`).
    LODESTAR_TPU_LAZY_FP2=0 restores the 3-full-multiply form."""
    a, b = _bcast(a, b)
    a0, a1 = _split(a)
    b0, b1 = _split(b)
    big_a = jnp.stack([a0, a1, fp.add(a0, a1)], axis=0)
    big_b = jnp.stack([b0, b1, fp.add(b0, b1)], axis=0)
    if _use_lazy(big_a):
        cols = fp.conv_cols(big_a, big_b)
        p0, p1, p2 = cols[0], cols[1], cols[2]
        c0_cols = p0 - p1 + fp.FOUR_P2_COLS
        # 8p² offset: fp.add may have REDUCED (a0+a1) by 2p, so the
        # integer p2 − p0 − p1 can reach −8p² (see fp.EIGHT_P2_COLS note)
        c1_cols = p2 - p0 - p1 + fp.EIGHT_P2_COLS
        out = fp.redc_cols(jnp.stack([c0_cols, c1_cols], axis=0))
        return _join(out[0], out[1])
    p = fp.mul(big_a, big_b)
    p0, p1, p2 = p[0], p[1], p[2]
    c0 = fp.sub(p0, p1)  # a0b0 - a1b1
    c1 = fp.sub(p2, fp.add(p0, p1))  # (a0+a1)(b0+b1) - a0b0 - a1b1
    return _join(c0, c1)


def square(a):
    """(a0+a1u)² : c0 = (a0+a1)(a0−a1), c1 = 2·a0·a1 — one stacked
    convolution + one stacked reduction on the lazy path (2 full Fp muls
    otherwise)."""
    a0, a1 = _split(a)
    big_a = jnp.stack([fp.add(a0, a1), a0], axis=0)
    big_b = jnp.stack([fp.sub(a0, a1), fp.add(a1, a1)], axis=0)
    if _use_lazy(big_a):
        cols = fp.conv_cols(big_a, big_b)
        out = fp.redc_cols(cols)
        return _join(out[0], out[1])
    p = fp.mul(big_a, big_b)
    return _join(p[0], p[1])


def mul_fp(a, k):
    """Fp2 × Fp scalar: k has shape (..., 32)."""
    return fp.mul(a, k[..., None, :])


def mul_by_xi(a):
    """Multiply by the Fp6 non-residue ξ = 1 + u: (c0 − c1) + (c0 + c1)u."""
    a0, a1 = _split(a)
    return _join(fp.sub(a0, a1), fp.add(a0, a1))


def xi_s(s: "fp.Sum") -> "fp.Sum":
    """ξ·(expression) on a bounds-tracked Sum over an (…, 2, 32) block
    (see fp.Sum / fp.reduce_stack — the deep-combine add discipline)."""
    c0 = s.cols[..., 0, :]
    c1 = s.cols[..., 1, :]
    cols = jnp.stack([c0 - c1, c0 + c1], axis=-2)
    return fp.Sum(cols, min(s.lo - s.hi, 2 * s.lo), max(s.hi - s.lo, 2 * s.hi))


def conj(a):
    a0, a1 = _split(a)
    return _join(a0, fp.neg(a1))


def inv(a):
    """(a0 − a1u)/(a0² + a1²). Zero maps to zero (callers mask infinity)."""
    a0, a1 = _split(a)
    p = fp.mul(jnp.stack([a0, a1], axis=0), jnp.stack([a0, a1], axis=0))
    norm_inv = fp.inv(fp.add(p[0], p[1]))
    q = fp.mul(jnp.stack([a0, a1], axis=0), norm_inv[None])
    return _join(q[0], fp.neg(q[1]))


def is_zero(a):
    return jnp.all(fp.canonical(a) == 0, axis=(-1, -2))


def eq(a, b):
    return jnp.all(fp.canonical(a) == fp.canonical(b), axis=(-1, -2))


def select(cond, a, b):
    return jnp.where(cond[..., None, None], a, b)


def zero(batch: tuple = ()):
    return jnp.zeros(batch + (2, N_LIMBS), jnp.int32)


def one(batch: tuple = ()):
    return _join(fp.one_mont(batch), fp.zero(batch))
