"""Fp6 = Fp2[v]/(v³ − ξ) on int32 limb vectors (device tier).

Element shape: (..., 3, 2, 32) — axis -3 indexes (c0, c1, c2) of
c0 + c1·v + c2·v². The 6-product Karatsuba multiplication stacks into ONE
fp2.mul call (which itself is one fp.mul call → 18 Fp products in a single
Montgomery scan).

Oracle: `lodestar_tpu/bls/fields.Fq6`.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import fp, fp2
from .limbs import N_LIMBS


def _split(a):
    return a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]


def _join(c0, c1, c2):
    return jnp.stack([c0, c1, c2], axis=-3)


def add(a, b):
    return fp.add(a, b)


def sub(a, b):
    return fp.sub(a, b)


def neg(a):
    return fp.neg(a)


def _bcast(a, b):
    batch = jnp.broadcast_shapes(a.shape[:-3], b.shape[:-3])
    return (
        jnp.broadcast_to(a, batch + a.shape[-3:]),
        jnp.broadcast_to(b, batch + b.shape[-3:]),
    )


def mul(a, b):
    """Toom/Karatsuba interpolation: 6 Fp2 products, one stacked call.

    c0 = v0 + ξ((a1+a2)(b1+b2) − v1 − v2)
    c1 = (a0+a1)(b0+b1) − v0 − v1 + ξ·v2
    c2 = (a0+a2)(b0+b2) − v0 − v2 + v1
    """
    a, b = _bcast(a, b)
    a0, a1, a2 = _split(a)
    b0, b1, b2 = _split(b)
    sa12, sa01, sa02, sb12, sb01, sb02 = fp.reduce_sums(
        jnp.stack([a1 + a2, a0 + a1, a0 + a2, b1 + b2, b0 + b1, b0 + b2])
    )
    big_a = jnp.stack([a0, a1, a2, sa12, sa01, sa02], axis=0)
    big_b = jnp.stack([b0, b1, b2, sb12, sb01, sb02], axis=0)
    v = fp2.mul(big_a, big_b)
    v0, v1, v2, v12, v01, v02 = v[0], v[1], v[2], v[3], v[4], v[5]
    # interpolation as ONE bounds-tracked combine scan (fp.reduce_stack)
    # instead of ~11 sequential add/sub scans
    W = fp.wrap
    c0 = W(v0) + fp2.xi_s(W(v12) - W(v1) - W(v2))
    c1 = W(v01) - W(v0) - W(v1) + fp2.xi_s(W(v2))
    c2 = W(v02) - W(v0) - W(v2) + W(v1)
    c0, c1, c2 = fp.reduce_stack([c0, c1, c2])
    return _join(c0, c1, c2)


def square(a):
    return mul(a, a)


def mul_by_v(a):
    """v·(c0 + c1v + c2v²) = ξc2 + c0·v + c1·v²."""
    a0, a1, a2 = _split(a)
    return _join(fp2.mul_by_xi(a2), a0, a1)


def mul_by_v_s(s: "fp.Sum") -> "fp.Sum":
    """`mul_by_v` on a bounds-tracked Sum over an (…, 3, 2, 32) block."""
    x2 = fp.Sum(s.cols[..., 2, :, :], s.lo, s.hi)
    xi2 = fp2.xi_s(x2)
    cols = jnp.stack(
        [xi2.cols, s.cols[..., 0, :, :], s.cols[..., 1, :, :]], axis=-3
    )
    return fp.Sum(cols, min(xi2.lo, s.lo), max(xi2.hi, s.hi))


def join_s(s0: "fp.Sum", s1: "fp.Sum", s2: "fp.Sum") -> "fp.Sum":
    """Stack three fp2-block Sums into one fp6-block Sum."""
    cols = jnp.stack([s0.cols, s1.cols, s2.cols], axis=-3)
    return fp.Sum(
        cols, min(s0.lo, s1.lo, s2.lo), max(s0.hi, s1.hi, s2.hi)
    )


def mul_fp2(a, k):
    """Fp6 × Fp2 scalar: k has shape (..., 2, 32)."""
    return fp2.mul(a, k[..., None, :, :])


def inv(a):
    """Standard tower inversion (mirrors the oracle's Fq6.inverse)."""
    a0, a1, a2 = _split(a)
    p = fp2.mul(
        jnp.stack([a0, a1, a2, a0, a1, a0], axis=0),
        jnp.stack([a0, a2, a2, a1, a1, a2], axis=0),
    )
    sq0, p12, sq2, p01, sq1, p02 = p[0], p[1], p[2], p[3], p[4], p[5]
    t0 = fp2.sub(sq0, fp2.mul_by_xi(p12))  # a0² − ξ a1a2
    t1 = fp2.sub(fp2.mul_by_xi(sq2), p01)  # ξ a2² − a0a1
    t2 = fp2.sub(sq1, p02)  # a1² − a0a2
    q = fp2.mul(jnp.stack([a0, a2, a1], axis=0), jnp.stack([t0, t1, t2], axis=0))
    denom = fp2.add(q[0], fp2.mul_by_xi(fp2.add(q[1], q[2])))
    dinv = fp2.inv(denom)
    out = fp2.mul(jnp.stack([t0, t1, t2], axis=0), dinv[None])
    return _join(out[0], out[1], out[2])


def is_zero(a):
    return jnp.all(fp.canonical(a) == 0, axis=(-1, -2, -3))


def eq(a, b):
    return jnp.all(fp.canonical(a) == fp.canonical(b), axis=(-1, -2, -3))


def select(cond, a, b):
    return jnp.where(cond[..., None, None, None], a, b)


def zero(batch: tuple = ()):
    return jnp.zeros(batch + (3, 2, N_LIMBS), jnp.int32)


def one(batch: tuple = ()):
    return _join(fp2.one(batch), fp2.zero(batch), fp2.zero(batch))
