"""Base-field Fp arithmetic on int32 limb vectors (device tier).

Montgomery-form arithmetic over p (BLS12-381) with the 32x12-bit limb
layout from `limbs.py`. Everything here is pure JAX: jit-compatible,
shape-polymorphic over leading batch axes (limb axis is always last), and
safe to `vmap`/`shard_map`.

Design notes (why this maps well to TPU):
- All hot paths are fixed-trip `lax.scan`s or statically unrolled loops:
  no data-dependent control flow, so XLA compiles one fused kernel.
- The default TPU multiply maps the limb convolution onto the MXU
  (`conv` + `_mul_fused`): one packed (3B,1024)@(1024,64) bf16 matmul
  per convolution, full-width Montgomery reduction, carries as short
  scans. CPU keeps the word-serial scan multiply.
- Values range over [0, 2p) between ops (lazy reduction); every op's
  output respects that invariant, and `canonical` gives the < p form.
- COMPILE-SIZE DISCIPLINE (round-2 lesson): a full verifier kernel
  traces ~1500 carry sites. Carries must stay graph-light — the
  `carry_scan` form costs ~5 jaxpr eqns/site vs ~300 for the unrolled
  Kogge-Stone (`ks_carry`), which inflated the kernel to 650k eqns and
  >50 min XLA compiles. Runtime at production widths is carry-neutral
  (BASELINE.md: 96.6 vs 95.1 ms per 100 chained muls), so the scans
  stay; `ks_carry` remains available for experiments.

Oracle: `lodestar_tpu/bls/fields.Fq` (differential tests in
tests/test_ops_fp.py).
"""

from __future__ import annotations

import contextlib
import math

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..bls.fields import P as _P_INT
from .limbs import (
    LIMB_BITS,
    LIMB_MASK,
    N_LIMBS,
    N0,
    ONE_MONT_LIMBS,
    P_LIMBS,
    R2_LIMBS,
    R_MONT,
    TWO_P_LIMBS,
    int_to_limbs,
)

_P = jnp.asarray(P_LIMBS)
_TWO_P = jnp.asarray(TWO_P_LIMBS)
_R2 = jnp.asarray(R2_LIMBS)
_ONE_MONT = jnp.asarray(ONE_MONT_LIMBS)


# Trace-time carry-strategy override (ISSUE 14). Every carry/borrow
# propagation in this module — carry_scan, _cond_sub_cols, reduce_stack —
# funnels through _carry_scan_out, so swapping its implementation inside a
# `carry_form(...)` region reroutes a whole traced subgraph (e.g. the final
# exponentiation's ~1,000 small muls) without threading a parameter through
# the tower. The override is consulted at TRACE time only; the default
# (None) keeps the graph-light lax.scan form everywhere else.
_CARRY_OUT_OVERRIDE = None


@contextlib.contextmanager
def carry_form(impl):
    """Route every carry propagation traced inside the region through
    `impl` (signature of `_carry_scan_out`: signed columns → (canonical
    limbs, final carry)). Pass `_ks_carry_impl` for the scan-free
    Kogge–Stone form; None restores the default."""
    global _CARRY_OUT_OVERRIDE
    prev = _CARRY_OUT_OVERRIDE
    _CARRY_OUT_OVERRIDE = impl
    try:
        yield
    finally:
        _CARRY_OUT_OVERRIDE = prev


def carry_scan(t: jnp.ndarray) -> jnp.ndarray:
    """Exact carry/borrow propagation -> canonical 12-bit limbs.

    Works for signed inputs: `>>` is arithmetic shift and `& MASK` is the
    positive remainder, so borrows ripple as negative carries. The final
    carry out of the top limb is dropped (callers guarantee the value fits
    384 bits and is non-negative). One `lax.scan` eqn in the graph — the
    graph-light workhorse behind every add/sub/mul (see module docstring).

    UNROLLED ×8 (round 5): the dependency chain is unchanged, but at
    kernel shapes the cost is per-ITERATION fixed overhead, not math —
    measured on v5e, fp.mul at 4096 lanes ran 9.9 M muls/s vs 48 M at
    131k lanes, i.e. ~64 while-loop iterations of overhead dominated.
    8 columns per scan step cuts iterations 8× for ~8 more eqns in the
    body (still graph-light, unlike the ~300-eqn Kogge–Stone); measured
    9.9 → 15.1 M muls/s at 4096 lanes. Column counts not divisible by 8
    fall back to one column per step.
    """
    return _carry_scan_out(t)[0]


def _carry_scan_out(t: jnp.ndarray):
    """`carry_scan` + the FINAL carry (−1 for negative values, 0
    otherwise — callers use it as a sign probe). The single unrolled-scan
    implementation; an unused final carry is dead-code-eliminated, so
    `carry_scan` delegating here costs nothing."""
    if _CARRY_OUT_OVERRIDE is not None:
        return _CARRY_OUT_OVERRIDE(t)
    tt = jnp.moveaxis(t, -1, 0)
    k = tt.shape[0]
    u = 8 if k % 8 == 0 else 1
    tk = tt.reshape((k // u, u) + tt.shape[1:])

    def step(carry, cols):
        outs = []
        for j in range(u):
            v = cols[j] + carry
            outs.append(v & LIMB_MASK)
            carry = v >> LIMB_BITS
        return carry, jnp.stack(outs)

    out_carry, out = lax.scan(step, jnp.zeros(tt.shape[1:], jnp.int32), tk)
    return jnp.moveaxis(out.reshape((k,) + tt.shape[1:]), 0, -1), out_carry


def _ks_carry_impl(t: jnp.ndarray):
    """Log-depth signed carry/borrow propagation -> (canonical limbs, out).

    Accepts signed columns with |t| < 2^30 whose VALUE (Σ t_i·2^(12i)) is
    non-negative; returns limbs in [0, 2^12) plus the unmasked top residue
    `out` (what carries past the last column).

    Structure (everything fuses — no lax.scan, no sequential chain):
      1. three shift-folds with arithmetic shifts: digits land in [-1, 2^12]
         (fold1 carries ≤ 2^18, fold2 ≤ 2^6+1, fold3 ≤ 1 — signed).
      2. the residual ±1 carry chain is a Kogge–Stone prefix over monotone
         carry maps {-1,0,1}→{-1,0,1}, each map encoded by its three
         outputs; composition is 3 selects, ⌈log2(K)⌉ rounds.

    NOT used on the default paths: it emits ~300 jaxpr eqns per site and
    measured runtime-neutral vs `carry_scan` at production widths — see
    the module docstring's compile-size note. Kept as an experiment and
    differentially pinned against `carry_scan`.
    """
    k = t.shape[-1]

    def fold(x):
        c = x >> LIMB_BITS
        return (x & LIMB_MASK) + jnp.concatenate(
            [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
        )

    t = fold(fold(fold(t)))  # digits ∈ [-1, 2^12]
    # per-position carry map: f(c) = (d + c) >> 12 for carry-in c ∈ {-1,0,1}
    lo = (t - 1) >> LIMB_BITS
    mid = t >> LIMB_BITS
    hi = (t + 1) >> LIMB_BITS

    def ev(gl, gm, gh, v):
        """Evaluate map g (its three outputs) at v ∈ {-1,0,1}."""
        return jnp.where(v < 0, gl, jnp.where(v > 0, gh, gm))

    L, M, H = lo, mid, hi
    shift = 1
    while shift < k:
        def pad(x, fill):
            return jnp.concatenate(
                [jnp.full_like(x[..., :shift], fill), x[..., :-shift]], axis=-1
            )

        # inclusive prefix: map_i ← map_i ∘ map_{i-shift} (identity fill)
        fl, fm, fh = pad(L, -1), pad(M, 0), pad(H, 1)
        L, M, H = ev(L, M, H, fl), ev(L, M, H, fm), ev(L, M, H, fh)
        shift *= 2

    # carry into position i = (prefix map through i-1)(0) = that map's mid
    cin = jnp.concatenate([jnp.zeros_like(M[..., :1]), M[..., :-1]], axis=-1)
    digits = (t + cin) & LIMB_MASK
    out = M[..., -1]  # carry past the top column
    return digits, out


def ks_carry(t: jnp.ndarray) -> jnp.ndarray:
    """Log-depth carry propagation; drops the out-carry (callers guarantee
    the non-negative value fits the column count). Contract of
    `carry_scan`, fused implementation. Experimental — see module
    docstring."""
    digits, _ = _ks_carry_impl(t)
    return digits


def _lex_ge(a: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """a >= m comparing canonical limb vectors (trailing limb axis)."""
    diff = a - m
    nz = diff != 0
    pos = diff > 0
    rev_nz = jnp.flip(nz, axis=-1)
    first = jnp.argmax(rev_nz, axis=-1)  # index (from top) of highest nonzero
    idx = (N_LIMBS - 1 - first)[..., None]
    top_sign = jnp.take_along_axis(pos, idx, axis=-1)[..., 0]
    return jnp.where(nz.any(axis=-1), top_sign, True)


def _cond_sub_cols(cols: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Canonical limbs of (v mod-reduce m): v − m if v ≥ m else v, for
    SIGNED column input v with 0 ≤ value < 2^384 and m canonical.

    ONE stacked carry scan over both candidates (v and v − m), selected
    by the final borrow of the v − m lane — replaces the round-4 pattern
    carry_scan + _lex_ge + carry_scan (3 sequential passes; the scans'
    per-iteration overhead dominates at kernel shapes, see carry_scan)."""
    both = jnp.stack([cols, cols - m])
    limbs, out = _carry_scan_out(both)
    return jnp.where((out[1] < 0)[..., None], limbs[0], limbs[1])


def _cond_sub(a: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """a - m if a >= m else a; a canonical, result canonical."""
    return _cond_sub_cols(a, m)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub_cols(a + b, _TWO_P)


# exported bias for `reduce_sums` subtraction expressions (a − b + TWO_P)
TWO_P = _TWO_P


def reduce_sums(cols: jnp.ndarray) -> jnp.ndarray:
    """Canonical [0, 2p) limbs from SIGNED column expressions with VALUE
    in [0, 4p) — the add-side analog of the stacked-multiply discipline.

    Formula code stacks a whole stage's independent adds/subs as raw
    column arithmetic (`a + b`, `a - b + fp.TWO_P`, limbs stay within
    int32 trivially) and pays ONE shared carry scan for all of them,
    instead of one `fp.add` scan per value. Expressions must keep their
    value under 4p (one conditional 2p-subtract restores the invariant):
    chain a second reduce_sums level for deeper sums like 3t0 or 8y²."""
    return _cond_sub_cols(cols, _TWO_P)


class Sum:
    """Trace-time bounds-tracked column expression (the deep-combine form
    of the stacked-add discipline).

    Wraps signed limb columns whose VALUE lies in [lo, hi) — bounds in
    units of 2p, tracked through +/− at trace time. `reduce_stack` turns
    a whole list of such expressions (a tower combine, a line-function
    stage) into canonical [0, 2p) limbs with ONE carry scan over all
    candidates — replacing one scan per fp.add/sub. Column magnitudes
    stay tiny (a handful of 12-bit limbs plus bias), so int32 is never
    at risk; only the VALUE bounds need the bookkeeping this class
    automates."""

    __slots__ = ("cols", "lo", "hi")

    def __init__(self, cols, lo, hi):
        self.cols = cols
        self.lo = lo
        self.hi = hi

    def __add__(self, o):
        return Sum(self.cols + o.cols, self.lo + o.lo, self.hi + o.hi)

    def __sub__(self, o):
        return Sum(self.cols - o.cols, self.lo - o.hi, self.hi - o.lo)

    def double(self):
        return Sum(self.cols + self.cols, 2 * self.lo, 2 * self.hi)


def wrap(cols) -> Sum:
    """Canonical [0, 2p) limbs as a Sum (lo=0, hi=1 in 2p units)."""
    return Sum(cols, 0, 1)


def reduce_stack(sums: "list[Sum]") -> "list[jnp.ndarray]":
    """Canonical [0, 2p) limbs for every Sum, ONE shared carry scan.

    Each expression is biased by its own multiple of 2p (≡ 0 mod p, so
    values are unchanged mod p) to make it non-negative, then reduced by
    selecting among k_j candidates v − i·2p in a single stacked scan —
    the i-th candidate's final borrow says whether i·2p still fits.

    Candidate counts are PER SUM (ADVICE r5): sizing every expression to
    the loosest bounds in the stack padded the scan with dead lanes —
    e.g. cyclotomic_square's c0 spans 14 candidates but rode its
    neighbor's 23. The scan now carries Σ k_j rows instead of
    len(sums)·max k_j; selection logic per Sum is unchanged."""
    shape = jnp.broadcast_shapes(*(s.cols.shape for s in sums))
    cands = []
    spans: list[tuple[int, int]] = []  # (first candidate row, k_j) per Sum
    for s in sums:
        bias = max(0, -math.floor(s.lo))
        k = max(1, math.ceil(s.hi + bias))  # value < k·2p after biasing
        base = jnp.broadcast_to(s.cols + bias * _TWO_P, shape)
        spans.append((len(cands), k))
        for i in range(k):
            cands.append(base - i * _TWO_P)
    limbs, out = _carry_scan_out(jnp.stack(cands))
    results = []
    for start, k in spans:
        # largest non-negative candidate via a fused where-chain (a gather
        # here measurably slowed the latency-bound kernels)
        res = limbs[start]
        for i in range(1, k):
            res = jnp.where(
                (out[start + i] >= 0)[..., None], limbs[start + i], res
            )
        results.append(res)
    return results


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub_cols(a - b + _TWO_P, _TWO_P)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def double(a: jnp.ndarray) -> jnp.ndarray:
    return add(a, a)


# full-width -p^-1 mod R as 32 12-bit limbs (for the fused REDC)
_NPRIME = jnp.asarray(int_to_limbs((-pow(_P_INT, -1, R_MONT)) % R_MONT))


def _conv_matrix() -> np.ndarray:
    """(N²,2N) 0/1 f32: flattened outer-product index (i,j) → column i+j."""
    s = np.zeros((N_LIMBS * N_LIMBS, 2 * N_LIMBS), np.float32)
    for i in range(N_LIMBS):
        for j in range(N_LIMBS):
            s[i * N_LIMBS + j, i + j] = 1.0
    return s


_S = jnp.asarray(_conv_matrix())


def conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Column convolution of 12-bit limb vectors via ONE fixed MXU matmul.

    a, b: (..., N) canonical 12-bit limbs → (..., 2N) int32 columns.
    The ≤2^24 products are split into three 8-bit parts: each part is
    ≤ 255, EXACT in bf16 (8-bit mantissa), so the TPU's DEFAULT-precision
    single-pass matmul is bit-exact — parts × 0/1 entries accumulate in
    f32 with partial sums ≤ 32·2^8 ≪ 2^24. The parts ride a new leading
    axis through a single packed matmul (one dispatch, one HLO) and are
    recombined with shifts. Measured (BASELINE.md): the 8-bit-split
    DEFAULT-precision form beats both the 6-pass HIGHEST form and the
    VPU scan path.
    """
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,))
    b = jnp.broadcast_to(b, batch + (N_LIMBS,))
    outer = (a[..., :, None] * b[..., None, :]).reshape(batch + (N_LIMBS * N_LIMBS,))
    parts = jnp.stack(
        [outer & 0xFF, (outer >> 8) & 0xFF, outer >> 16], axis=0
    ).astype(jnp.float32)
    c = jnp.matmul(parts, _S, preferred_element_type=jnp.float32).astype(jnp.int32)
    return c[0] + (c[1] << 8) + (c[2] << 16)


# --- column-space (lazy-reduction) pipeline ---------------------------------
#
# Products as 64 uncarried int32 columns let tower code ADD/SUBTRACT whole
# products before reducing: Fp2 Karatsuba becomes 3 convolutions + 2 REDCs
# instead of 3 full Montgomery multiplies (the classic lazy-reduction
# optimization blst applies to the same tower). Column bounds: one product
# of canonical-limb inputs stays < 2^29; up to 3 products (plus a constant
# offset) fit signed int32.


def conv_cols(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook convolution → (..., 2N) int32 columns, as 32 STATIC
    shifted multiply-adds (no dynamic slicing, no matmul blowup — fuses
    into wide VPU code)."""
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,))
    b = jnp.broadcast_to(b, batch + (N_LIMBS,))
    pad = [(0, 0)] * len(batch)
    t = jnp.zeros(batch + (2 * N_LIMBS,), jnp.int32)
    for i in range(N_LIMBS):
        t = t + jnp.pad(
            a[..., i : i + 1] * b, pad + [(i, N_LIMBS - i)]
        )
    return t


def _conv_cols_mod_r(a: jnp.ndarray, const: jnp.ndarray) -> jnp.ndarray:
    """Truncated convolution (columns 0..N-1 only) with a constant
    operand — the `m = t·N' mod R` step of full-width REDC."""
    t = jnp.zeros(a.shape, jnp.int32)
    pad = [(0, 0)] * (a.ndim - 1)
    for i in range(N_LIMBS):
        seg = a[..., i : i + 1] * const[: N_LIMBS - i]
        t = t + jnp.pad(seg, pad + [(i, 0)])
    return t


def redc_cols(t_cols: jnp.ndarray) -> jnp.ndarray:
    """Montgomery-reduce signed columns → canonical limbs in [0, 2p).

    `t_cols` (..., 2N) int32 columns of a NON-NEGATIVE value < 12p²
    (columns may be negative). GRAPH-LIGHT: the reduction is the proven
    word-serial `lax.scan` (`_redc_scan` — the ONE copy of the
    consensus-critical pipeline, shared with `_mul_scan`) applied
    DIRECTLY to the signed columns — only the low 12 bits of a column
    feed the m-digit, and arithmetic shifts ripple negative carries, so
    no prior normalization is needed (≈12 jaxpr eqns total). The
    full-width m/u-convolution form costs ~200 eqns per site and blew
    kernel compiles past 50 min (the round-2 compile-size lesson,
    relearned on the lazy tower; `redc_cols_conv` keeps that form for
    experiments)."""
    # (t + m·p)/R < 12p²/R + p ≈ 2.51p: one conditional subtract restores
    # the [0, 2p) contract (x ≥ 2p ⇒ x − 2p < 0.51p). The propagate and
    # the subtract share ONE stacked scan (_cond_sub_cols on signed
    # columns) — round-5 scan-count discipline.
    return _cond_sub_cols(_redc_scan(t_cols)[..., N_LIMBS:], _TWO_P)


def _redc_scan(t: jnp.ndarray) -> jnp.ndarray:
    """The word-serial Montgomery reduction scan over (..., 2N) columns —
    kills one low limb per step; accepts signed, uncarried columns. The
    single shared implementation behind `_mul_scan` and `redc_cols`.

    UNROLLED ×8 like `carry_scan`: each scan iteration kills EIGHT low
    limbs inside one (N+8)-wide window — same dependency chain, 4 loop
    iterations instead of 32 (per-iteration overhead dominates at kernel
    shapes; see carry_scan)."""
    u = 8
    win = N_LIMBS + u

    def redc_step(acc, i):
        chunk = lax.dynamic_slice_in_dim(acc, i * u, win, axis=-1)
        for j in range(u):
            m = (chunk[..., j : j + 1] * N0) & LIMB_MASK
            chunk = chunk.at[..., j : j + N_LIMBS].add(m * _P)
            carry = chunk[..., j : j + 1] >> LIMB_BITS
            chunk = chunk.at[..., j + 1 : j + 2].add(carry)
            chunk = chunk.at[..., j : j + 1].set(0)
        return (
            lax.dynamic_update_slice_in_dim(acc, chunk, i * u, axis=-1),
            None,
        )

    out, _ = lax.scan(redc_step, t, jnp.arange(N_LIMBS // u))
    return out


def redc_cols_conv(t_cols: jnp.ndarray) -> jnp.ndarray:
    """Full-width REDC via pad-convolutions (m = t·N' mod R, u = m·p) —
    the graph-HEAVY variant; see `redc_cols` for why it is not the
    default. Same contract."""
    t = carry_scan(t_cols)
    m_cols = _conv_cols_mod_r(t[..., :N_LIMBS], _NPRIME)
    m = carry_scan(m_cols)  # mod R = drop the out-carry
    u_cols = conv_cols(m, _P)
    summed = carry_scan(t_cols + u_cols)
    return _cond_sub(summed[..., N_LIMBS:], _TWO_P)


# column offsets (canonical 64-limb forms of 4p² and 8p²) keeping lazy
# combinations non-negative as INTEGERS (they are ≡ 0 mod p, so the
# reduced value is unchanged):
# - c0 = a0b0 − a1b1 + 4p²: a1b1 < (2p)² = 4p².
# - c1 = s_a·s_b − a0b0 − a1b1 + 8p²: s_a = fp.add(a0, a1) may be the
#   REDUCED representative (−2p), making the integer difference as low
#   as −8p² — the mod-p value is right but `redc_cols` needs the
#   non-negative integer (bug caught by the [p, 2p)-input differential
#   tests; canonical-input tests cannot see it).
FOUR_P2_COLS = jnp.asarray(
    np.asarray(
        [(4 * _P_INT * _P_INT >> (12 * i)) & 0xFFF for i in range(64)],
        np.int32,
    )
)
EIGHT_P2_COLS = jnp.asarray(
    np.asarray(
        [(8 * _P_INT * _P_INT >> (12 * i)) & 0xFFF for i in range(64)],
        np.int32,
    )
)


def _mul_padconv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery multiply via static pad-convolutions + full-width
    conv-REDC (no lax.scan REDC, no dynamic slices) — the graph-heavy
    experimental form; see `_default_impl`."""
    return redc_cols_conv(conv_cols(a, b))


def _mul_scan(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Word-serial Montgomery multiply (32-step REDC scan).

    The CPU-backend default and LODESTAR_TPU_LEGACY_FP=1 fallback; the
    TPU default is `_mul_fused`.
    """
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,))
    b = jnp.broadcast_to(b, batch + (N_LIMBS,))
    t = jnp.zeros(batch + (2 * N_LIMBS,), dtype=jnp.int32)
    for i in range(N_LIMBS):  # static unroll: 32 vector multiply-adds
        t = t.at[..., i : i + N_LIMBS].add(a[..., i : i + 1] * b)
    return carry_scan(_redc_scan(t)[..., N_LIMBS:])


def _mul_fused(a: jnp.ndarray, b: jnp.ndarray, carry=None) -> jnp.ndarray:
    """Fused Montgomery multiply: MXU convolutions + full-width REDC.

        t = a·b            (conv as one packed bf16 matmul)
        m = (t mod R)·N' mod R
        out = (t + m·p) / R

    `carry` parameterizes the carry-propagation strategy (default
    `carry_scan` — graph-light; `mxu_fp.mul` passes its Kogge–Stone
    variant) so the consensus-critical REDC pipeline exists exactly once.

    Bounds: conv columns < 2^29, t+u columns < 2^30; output < 2p for
    inputs < 2p: t < (2p)² so t/R < 4p²/R < p (R = 2^384 > 4p);
    m·p/R < p; result < 2p.
    """
    if carry is None:
        carry = carry_scan
    t_cols = conv(a, b)
    t = carry(t_cols)  # (2p)² < 2^768 fits 64 limbs: no out-carry
    m_cols = conv(t[..., :N_LIMBS], _NPRIME)[..., :N_LIMBS]
    m = carry(m_cols)  # mod R = drop the out-carry
    u_cols = conv(m, _P)
    summed = carry(t_cols + u_cols)  # t+u < 2^766: no out-carry
    # low 32 limbs are ≡ 0 by construction of m; result = (t+u) >> 384
    return summed[..., N_LIMBS:]


_DEFAULT_IMPL = None


def _default_impl():
    """Pick the default multiply once per process: the word-serial scan.

    Round-4 record of the alternatives (tools/fp_probe.py, v5e):
    - `_mul_padconv` (static pad-convs + m/u-conv REDC): 27.2 vs 32.2 ms
      per 100-mul chain @4096 — WINS standalone but costs ~270 jaxpr
      eqns/site vs the scan's ~75, inflating full-kernel compiles past
      50 min (round-2 compile-size lesson). Opt-in:
      LODESTAR_TPU_PADCONV_FP=1.
    - Pallas MXU kernel (`ops/pallas_mxu.py`): VMEM-resident tiles fix
      round 2's HBM blowup and win isolated chains ~1.25×, but ~200 µs
      per-call in-graph launch latency loses the full kernel (867 vs
      1001 sets/s). Opt-in: LODESTAR_TPU_PALLAS_MXU=1.
    The lazy-reduction Fp2 tower keeps the real win compile-light: it
    REMOVES a third of the REDCs and runs the rest through the same
    word-serial scan (`redc_cols`).
    """
    global _DEFAULT_IMPL
    if _DEFAULT_IMPL is None:
        _DEFAULT_IMPL = _mul_scan
    return _DEFAULT_IMPL


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product REDC(a*b): inputs < 2p, output < 2p.

    Default path is the word-serial scan on every backend — see
    `_default_impl` for the measurement that demoted the MXU path.
    Env overrides: LODESTAR_TPU_PALLAS_MUL=1 routes through the Pallas
    VMEM-resident kernel (`ops/pallas_fp.py`); LODESTAR_TPU_LEGACY_FP=1
    forces the word-serial scan explicitly; LODESTAR_TPU_MXU_MUL=1
    (round 1's opt-in flag) forces the `mxu_fp.mul` MXU/Kogge–Stone
    variant.
    """
    from ..utils.env import env_bool

    if env_bool("LODESTAR_TPU_PADCONV_FP"):
        return _mul_padconv(a, b)
    if env_bool("LODESTAR_TPU_PALLAS_MXU"):
        from .pallas_mxu import mont_mul

        return mont_mul(a, b)
    if env_bool("LODESTAR_TPU_PALLAS_MUL"):
        from .pallas_fp import mont_mul

        return mont_mul(a, b)
    if env_bool("LODESTAR_TPU_LEGACY_FP"):
        return _mul_scan(a, b)
    if env_bool("LODESTAR_TPU_MXU_MUL"):
        from . import mxu_fp

        return mxu_fp.mul(a, b)
    return _default_impl()(a, b)


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Normal-domain canonical limbs -> Montgomery form."""
    return mul(a, _R2)


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery form -> canonical normal-domain limbs (< p)."""
    one = jnp.zeros(N_LIMBS, jnp.int32).at[0].set(1)
    return _cond_sub(mul(a, one), _P)


def canonical(a: jnp.ndarray) -> jnp.ndarray:
    """Reduce the [0, 2p) representative to the unique [0, p) form."""
    return _cond_sub(a, _P)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, broadcasting cond over the limb axis."""
    return jnp.where(cond[..., None], a, b)


def zero(batch: tuple = ()) -> jnp.ndarray:
    return jnp.zeros(batch + (N_LIMBS,), jnp.int32)


def one_mont(batch: tuple = ()) -> jnp.ndarray:
    return jnp.broadcast_to(_ONE_MONT, batch + (N_LIMBS,))


# Uniform field-module interface (CurveOps is generic over fp/fp2): "one" is
# the multiplicative identity in the working (Montgomery) representation.
one = one_mont


def _exp_bits(e: int) -> np.ndarray:
    """MSB-first bit array of a positive exponent (static)."""
    bits = bin(e)[2:]
    return np.frombuffer(bits.encode(), np.uint8).astype(np.int32) - ord("0")


def pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a static exponent, square-and-multiply over a bit scan."""
    if e == 0:
        return one_mont(a.shape[:-1])
    bits = jnp.asarray(_exp_bits(e))

    def step(acc, bit):
        acc = square(acc)
        acc = jnp.where(bit != 0, mul(acc, a), acc)
        return acc, None

    # first bit is always 1: start from a
    acc, _ = lax.scan(step, a, bits[1:])
    return acc


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse a^(p-2); a must be nonzero (0 maps to 0)."""
    return pow_const(a, _P_INT - 2)


def sqrt_candidate(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p+1)/4) — a square root iff a is a QR (p ≡ 3 mod 4)."""
    return pow_const(a, (_P_INT + 1) // 4)
