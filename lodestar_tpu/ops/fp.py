"""Base-field Fp arithmetic on int32 limb vectors (device tier).

Montgomery-form arithmetic over p (BLS12-381) with the 32x12-bit limb
layout from `limbs.py`. Everything here is pure JAX: jit-compatible,
shape-polymorphic over leading batch axes (limb axis is always last), and
safe to `vmap`/`shard_map`.

Design notes (why this maps well to TPU):
- All hot paths are fixed-trip `lax.scan`s or statically unrolled loops:
  no data-dependent control flow, so XLA compiles one fused kernel.
- The schoolbook product is 32 vector multiply-adds on the VPU; the
  Montgomery reduction is a 32-step scan whose body is one vector
  multiply-add — sequential over limbs, parallel over the batch, which is
  where the throughput comes from (BASELINE.json wants batched signature
  sets, not single-signature latency).
- Values range over [0, 2p) between ops (lazy reduction); every op's
  output respects that invariant, and `canonical` gives the < p form.

Oracle: `lodestar_tpu/bls/fields.Fq` (differential tests in
tests/test_ops_fp.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..bls.fields import P as _P_INT
from .limbs import (
    LIMB_BITS,
    LIMB_MASK,
    N_LIMBS,
    N0,
    ONE_MONT_LIMBS,
    P_LIMBS,
    R2_LIMBS,
    TWO_P_LIMBS,
)

_P = jnp.asarray(P_LIMBS)
_TWO_P = jnp.asarray(TWO_P_LIMBS)
_R2 = jnp.asarray(R2_LIMBS)
_ONE_MONT = jnp.asarray(ONE_MONT_LIMBS)


def carry_scan(t: jnp.ndarray) -> jnp.ndarray:
    """Exact carry/borrow propagation -> canonical 12-bit limbs.

    Works for signed inputs: `>>` is arithmetic shift and `& MASK` is the
    positive remainder, so borrows ripple as negative carries. The final
    carry out of the top limb is dropped (callers guarantee the value fits
    384 bits and is non-negative).
    """
    tt = jnp.moveaxis(t, -1, 0)

    def step(carry, col):
        v = col + carry
        return v >> LIMB_BITS, v & LIMB_MASK

    _, out = lax.scan(step, jnp.zeros(tt.shape[1:], jnp.int32), tt)
    return jnp.moveaxis(out, 0, -1)


def _lex_ge(a: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """a >= m comparing canonical limb vectors (trailing limb axis)."""
    diff = a - m
    nz = diff != 0
    pos = diff > 0
    rev_nz = jnp.flip(nz, axis=-1)
    first = jnp.argmax(rev_nz, axis=-1)  # index (from top) of highest nonzero
    idx = (N_LIMBS - 1 - first)[..., None]
    top_sign = jnp.take_along_axis(pos, idx, axis=-1)[..., 0]
    return jnp.where(nz.any(axis=-1), top_sign, True)


def _cond_sub(a: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """a - m if a >= m else a; a canonical, result canonical."""
    ge = _lex_ge(a, m)
    return carry_scan(a - jnp.where(ge[..., None], m, 0))


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub(carry_scan(a + b), _TWO_P)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub(carry_scan(a - b + _TWO_P), _TWO_P)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def double(a: jnp.ndarray) -> jnp.ndarray:
    return add(a, a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product REDC(a*b): inputs < 2p, output < 2p.

    Schoolbook convolution into 64 uncarried int32 columns (each < 2^29),
    then word-by-word Montgomery reduction as a 32-step scan. Peak column
    value stays < 2^31 (see limbs.py for the bound).

    LODESTAR_TPU_PALLAS_MUL=1 routes through the Pallas VMEM-resident
    kernel (`ops/pallas_fp.py`) instead — same contract, one HBM
    round-trip per batch tile on TPU hardware.
    """
    import os

    if os.environ.get("LODESTAR_TPU_PALLAS_MUL") == "1":
        from .pallas_fp import mont_mul

        return mont_mul(a, b)
    if os.environ.get("LODESTAR_TPU_MXU_MUL") == "1":
        from . import mxu_fp

        return mxu_fp.mul(a, b)
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,))
    b = jnp.broadcast_to(b, batch + (N_LIMBS,))
    t = jnp.zeros(batch + (2 * N_LIMBS,), dtype=jnp.int32)
    for i in range(N_LIMBS):  # static unroll: 32 vector multiply-adds
        t = t.at[..., i : i + N_LIMBS].add(a[..., i : i + 1] * b)

    # Montgomery reduction as a 32-step lax.scan. A statically-unrolled
    # variant was measured on v5e: ~3% faster at run time but it multiplies
    # the HLO of every consumer (the full batch kernel's first compile went
    # from ~3 min to >20 min) — the scan keeps the graph compact, which is
    # the right trade for a kernel compiled per batch-bucket.
    def redc_step(t, i):
        chunk = lax.dynamic_slice_in_dim(t, i, N_LIMBS, axis=-1)
        m = (chunk[..., 0:1] * N0) & LIMB_MASK
        chunk = chunk + m * _P
        carry = chunk[..., 0:1] >> LIMB_BITS  # low limb is ≡ 0 mod 2^12 now
        chunk = chunk.at[..., 1:2].add(carry)
        chunk = chunk.at[..., 0:1].set(0)
        return lax.dynamic_update_slice_in_dim(t, chunk, i, axis=-1), None

    t, _ = lax.scan(redc_step, t, jnp.arange(N_LIMBS))
    return carry_scan(t[..., N_LIMBS:])


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Normal-domain canonical limbs -> Montgomery form."""
    return mul(a, _R2)


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery form -> canonical normal-domain limbs (< p)."""
    one = jnp.zeros(N_LIMBS, jnp.int32).at[0].set(1)
    return _cond_sub(mul(a, one), _P)


def canonical(a: jnp.ndarray) -> jnp.ndarray:
    """Reduce the [0, 2p) representative to the unique [0, p) form."""
    return _cond_sub(a, _P)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, broadcasting cond over the limb axis."""
    return jnp.where(cond[..., None], a, b)


def zero(batch: tuple = ()) -> jnp.ndarray:
    return jnp.zeros(batch + (N_LIMBS,), jnp.int32)


def one_mont(batch: tuple = ()) -> jnp.ndarray:
    return jnp.broadcast_to(_ONE_MONT, batch + (N_LIMBS,))


# Uniform field-module interface (CurveOps is generic over fp/fp2): "one" is
# the multiplicative identity in the working (Montgomery) representation.
one = one_mont


def _exp_bits(e: int) -> np.ndarray:
    """MSB-first bit array of a positive exponent (static)."""
    bits = bin(e)[2:]
    return np.frombuffer(bits.encode(), np.uint8).astype(np.int32) - ord("0")


def pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a static exponent, square-and-multiply over a bit scan."""
    if e == 0:
        return one_mont(a.shape[:-1])
    bits = jnp.asarray(_exp_bits(e))

    def step(acc, bit):
        acc = square(acc)
        acc = jnp.where(bit != 0, mul(acc, a), acc)
        return acc, None

    # first bit is always 1: start from a
    acc, _ = lax.scan(step, a, bits[1:])
    return acc


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse a^(p-2); a must be nonzero (0 maps to 0)."""
    return pow_const(a, _P_INT - 2)


def sqrt_candidate(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p+1)/4) — a square root iff a is a QR (p ≡ 3 mod 4)."""
    return pow_const(a, (_P_INT + 1) // 4)

