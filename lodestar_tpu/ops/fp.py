"""Base-field Fp arithmetic on int32 limb vectors (device tier).

Montgomery-form arithmetic over p (BLS12-381) with the 32x12-bit limb
layout from `limbs.py`. Everything here is pure JAX: jit-compatible,
shape-polymorphic over leading batch axes (limb axis is always last), and
safe to `vmap`/`shard_map`.

Design notes (why this maps well to TPU):
- All hot paths are fixed-trip `lax.scan`s or statically unrolled loops:
  no data-dependent control flow, so XLA compiles one fused kernel.
- The schoolbook product is 32 vector multiply-adds on the VPU; the
  Montgomery reduction is a 32-step scan whose body is one vector
  multiply-add — sequential over limbs, parallel over the batch, which is
  where the throughput comes from (BASELINE.json wants batched signature
  sets, not single-signature latency).
- Values range over [0, 2p) between ops (lazy reduction); every op's
  output respects that invariant, and `canonical` gives the < p form.

Oracle: `lodestar_tpu/bls/fields.Fq` (differential tests in
tests/test_ops_fp.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..bls.fields import P as _P_INT
from .limbs import (
    LIMB_BITS,
    LIMB_MASK,
    N_LIMBS,
    N0,
    ONE_MONT_LIMBS,
    P_LIMBS,
    R2_LIMBS,
    R_MONT,
    TWO_P_LIMBS,
    int_to_limbs,
)

_P = jnp.asarray(P_LIMBS)
_TWO_P = jnp.asarray(TWO_P_LIMBS)
_R2 = jnp.asarray(R2_LIMBS)
_ONE_MONT = jnp.asarray(ONE_MONT_LIMBS)


def carry_scan(t: jnp.ndarray) -> jnp.ndarray:
    """Sequential carry propagation (reference implementation).

    Kept as the differential oracle for `ks_carry` and for ad-hoc use; hot
    paths use the log-depth `ks_carry` instead — a 32/64-step `lax.scan`
    of tiny steps is pure dispatch latency on TPU.
    """
    tt = jnp.moveaxis(t, -1, 0)

    def step(carry, col):
        v = col + carry
        return v >> LIMB_BITS, v & LIMB_MASK

    _, out = lax.scan(step, jnp.zeros(tt.shape[1:], jnp.int32), tt)
    return jnp.moveaxis(out, 0, -1)


def _ks_carry_impl(t: jnp.ndarray):
    """Log-depth signed carry/borrow propagation -> (canonical limbs, out).

    Accepts signed columns with |t| < 2^30 whose VALUE (Σ t_i·2^(12i)) is
    non-negative; returns limbs in [0, 2^12) plus the unmasked top residue
    `out` (what carries past the last column — callers append a zero column
    when they need it, or rely on the value fitting to drop it).

    Structure (everything fuses — no lax.scan, no sequential chain):
      1. three shift-folds with arithmetic shifts: digits land in [-1, 2^12]
         (fold1 carries ≤ 2^18, fold2 ≤ 2^6+1, fold3 ≤ 1 — signed).
      2. the residual ±1 carry chain is a Kogge–Stone prefix over monotone
         carry maps {-1,0,1}→{-1,0,1}, each map encoded by its three
         outputs; composition is 3 selects, ⌈log2(K)⌉ rounds.
    """
    k = t.shape[-1]

    def fold(x):
        c = x >> LIMB_BITS
        return (x & LIMB_MASK) + jnp.concatenate(
            [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
        )

    t = fold(fold(fold(t)))  # digits ∈ [-1, 2^12]
    # per-position carry map: f(c) = (d + c) >> 12 for carry-in c ∈ {-1,0,1}
    lo = (t - 1) >> LIMB_BITS
    mid = t >> LIMB_BITS
    hi = (t + 1) >> LIMB_BITS

    def ev(gl, gm, gh, v):
        """Evaluate map g (its three outputs) at v ∈ {-1,0,1}."""
        return jnp.where(v < 0, gl, jnp.where(v > 0, gh, gm))

    L, M, H = lo, mid, hi
    shift = 1
    while shift < k:
        def pad(x, fill):
            return jnp.concatenate(
                [jnp.full_like(x[..., :shift], fill), x[..., :-shift]], axis=-1
            )

        # inclusive prefix: map_i ← map_i ∘ map_{i-shift} (identity fill)
        fl, fm, fh = pad(L, -1), pad(M, 0), pad(H, 1)
        L, M, H = ev(L, M, H, fl), ev(L, M, H, fm), ev(L, M, H, fh)
        shift *= 2

    # carry into position i = (prefix map through i-1)(0) = that map's mid
    cin = jnp.concatenate([jnp.zeros_like(M[..., :1]), M[..., :-1]], axis=-1)
    digits = (t + cin) & LIMB_MASK
    out = M[..., -1]  # carry past the top column
    return digits, out


def ks_carry(t: jnp.ndarray) -> jnp.ndarray:
    """Log-depth carry propagation; drops the out-carry (callers guarantee
    the non-negative value fits the column count). Contract of
    `carry_scan`, fused implementation."""
    digits, _ = _ks_carry_impl(t)
    return digits


def _carry_out(t: jnp.ndarray):
    """ks_carry + the value carried past the top column (appends a zero
    column so fold carries are captured, not dropped). The extension
    column is masked like every limb, so the out value is only exact for
    carries < 2^12 — ample for the complement-add use (carry ∈ {0,1})."""
    ext = jnp.concatenate([t, jnp.zeros_like(t[..., :1])], axis=-1)
    digits, _ = _ks_carry_impl(ext)
    return digits[..., :-1], digits[..., -1]


def _cond_sub(a: jnp.ndarray, comp_m: jnp.ndarray) -> jnp.ndarray:
    """a - m if a >= m else a, with comp_m = 2^384 - m precomputed.

    Complement-add: y = a + (2^384 - m) overflows bit 384 exactly when
    a >= m, and then the truncated y IS a - m. One fused carry + select —
    no lexicographic compare, no borrow chain.
    """
    y, out = _carry_out(a + comp_m)
    return jnp.where(out[..., None] > 0, y, a)


_COMP_TWO_P = jnp.asarray(int_to_limbs((1 << 384) - 2 * _P_INT))
_COMP_P = jnp.asarray(int_to_limbs((1 << 384) - _P_INT))


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub(ks_carry(a + b), _COMP_TWO_P)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub(ks_carry(a - b + _TWO_P), _COMP_TWO_P)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def double(a: jnp.ndarray) -> jnp.ndarray:
    return add(a, a)


# full-width -p^-1 mod R as 32 12-bit limbs (for the fused REDC)
_NPRIME = jnp.asarray(int_to_limbs((-pow(_P_INT, -1, R_MONT)) % R_MONT))


def _conv_matrix() -> np.ndarray:
    """(N²,2N) 0/1 f32: flattened outer-product index (i,j) → column i+j."""
    s = np.zeros((N_LIMBS * N_LIMBS, 2 * N_LIMBS), np.float32)
    for i in range(N_LIMBS):
        for j in range(N_LIMBS):
            s[i * N_LIMBS + j, i + j] = 1.0
    return s


_S = jnp.asarray(_conv_matrix())


def conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Column convolution of 12-bit limb vectors via a fixed MXU matmul.

    a, b: (..., N) canonical 12-bit limbs → (..., 2N) int32 columns.
    The ≤2^24 products are split into three 8-bit parts: each part is
    ≤ 255, EXACT in bf16 (8-bit mantissa), so the TPU's DEFAULT-precision
    single-pass matmul is bit-exact — parts × 0/1 entries accumulate in
    f32 with partial sums ≤ 32·2^8 ≪ 2^24. Measured (BASELINE.md): three
    one-pass matmuls beat two six-pass HIGHEST ones and the VPU scan.
    """
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,))
    b = jnp.broadcast_to(b, batch + (N_LIMBS,))
    outer = (a[..., :, None] * b[..., None, :]).reshape(batch + (N_LIMBS * N_LIMBS,))
    p0 = (outer & 0xFF).astype(jnp.float32)
    p1 = ((outer >> 8) & 0xFF).astype(jnp.float32)
    p2 = (outer >> 16).astype(jnp.float32)
    c0 = jnp.matmul(p0, _S, preferred_element_type=jnp.float32)
    c1 = jnp.matmul(p1, _S, preferred_element_type=jnp.float32)
    c2 = jnp.matmul(p2, _S, preferred_element_type=jnp.float32)
    return (
        c0.astype(jnp.int32)
        + (c1.astype(jnp.int32) << 8)
        + (c2.astype(jnp.int32) << 16)
    )


def _mul_scan(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Round-1 word-serial Montgomery multiply (32-step REDC scan).

    Kept as a differential reference and LODESTAR_TPU_LEGACY_FP=1 fallback;
    superseded by `_mul_fused` — the scan's 32 sequential steps are
    dispatch latency the fused path eliminates.
    """
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,))
    b = jnp.broadcast_to(b, batch + (N_LIMBS,))
    t = jnp.zeros(batch + (2 * N_LIMBS,), dtype=jnp.int32)
    for i in range(N_LIMBS):  # static unroll: 32 vector multiply-adds
        t = t.at[..., i : i + N_LIMBS].add(a[..., i : i + 1] * b)

    def redc_step(t, i):
        chunk = lax.dynamic_slice_in_dim(t, i, N_LIMBS, axis=-1)
        m = (chunk[..., 0:1] * N0) & LIMB_MASK
        chunk = chunk + m * _P
        carry = chunk[..., 0:1] >> LIMB_BITS  # low limb is ≡ 0 mod 2^12 now
        chunk = chunk.at[..., 1:2].add(carry)
        chunk = chunk.at[..., 0:1].set(0)
        return lax.dynamic_update_slice_in_dim(t, chunk, i, axis=-1), None

    t, _ = lax.scan(redc_step, t, jnp.arange(N_LIMBS))
    return carry_scan(t[..., N_LIMBS:])


def _mul_fused(a: jnp.ndarray, b: jnp.ndarray, carry=None) -> jnp.ndarray:
    """Fused Montgomery multiply: MXU convolutions + full-width REDC +
    log-depth carries — zero `lax.scan`s, so whole tower operations
    compile into a handful of fused kernels instead of hundreds of
    sequential scan steps.

        t = a·b            (conv as three exact bf16 matmuls)
        m = (t mod R)·N' mod R
        out = (t + m·p) / R

    `carry` parameterizes the carry-propagation strategy (default
    `ks_carry`; `mxu_fp.mul` passes its generate/propagate variant) so
    the consensus-critical REDC pipeline exists exactly once.

    Bounds: conv columns < 2^29, t+u columns < 2^30 (ks_carry's limit);
    output < 2p for inputs < 2p: t < (2p)² so t/R < 4p²/R < p
    (R = 2^384 > 4p); m·p/R < p; result < 2p.
    """
    if carry is None:
        carry = ks_carry
    t_cols = conv(a, b)
    t = carry(t_cols)  # (2p)² < 2^768 fits 64 limbs: no out-carry
    m_cols = conv(t[..., :N_LIMBS], _NPRIME)[..., :N_LIMBS]
    m = carry(m_cols)  # mod R = drop the out-carry
    u_cols = conv(m, _P)
    summed = carry(t_cols + u_cols)  # t+u < 2^766: no out-carry
    # low 32 limbs are ≡ 0 by construction of m; result = (t+u) >> 384
    return summed[..., N_LIMBS:]


_DEFAULT_IMPL = None


def _default_impl():
    """Pick the default multiply once per process.

    TPU: `_mul_fused` — the MXU convolution + full-width REDC design
    (BASELINE.md measured it ahead of the scan path on v5e). Other
    backends (CPU tests / virtual mesh): the word-serial scan — the
    (B,1024)@(1024,64) constant matmuls that feed the MXU are a large
    compile-time and runtime pessimization on the CPU backend. Both
    paths are differentially pinned against the big-int oracle either
    way (tests/test_ops_fp.py).
    """
    global _DEFAULT_IMPL
    if _DEFAULT_IMPL is None:
        import jax

        _DEFAULT_IMPL = _mul_fused if jax.default_backend() == "tpu" else _mul_scan
    return _DEFAULT_IMPL


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product REDC(a*b): inputs < 2p, output < 2p.

    Default path on TPU is `_mul_fused` (MXU convolution + full-width
    REDC); on other backends the word-serial scan (see `_default_impl`).
    Env overrides: LODESTAR_TPU_PALLAS_MUL=1 routes through the Pallas
    VMEM-resident kernel (`ops/pallas_fp.py`); LODESTAR_TPU_LEGACY_FP=1
    forces the round-1 word-serial scan; LODESTAR_TPU_MXU_MUL=1 (round
    1's opt-in flag for the then-experimental MXU path) forces the
    `mxu_fp.mul` carry variant on any backend.
    """
    import os

    if os.environ.get("LODESTAR_TPU_PALLAS_MUL") == "1":
        from .pallas_fp import mont_mul

        return mont_mul(a, b)
    if os.environ.get("LODESTAR_TPU_LEGACY_FP") == "1":
        return _mul_scan(a, b)
    if os.environ.get("LODESTAR_TPU_MXU_MUL") == "1":
        from . import mxu_fp

        return mxu_fp.mul(a, b)
    return _default_impl()(a, b)


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Normal-domain canonical limbs -> Montgomery form."""
    return mul(a, _R2)


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery form -> canonical normal-domain limbs (< p)."""
    one = jnp.zeros(N_LIMBS, jnp.int32).at[0].set(1)
    return _cond_sub(mul(a, one), _COMP_P)


def canonical(a: jnp.ndarray) -> jnp.ndarray:
    """Reduce the [0, 2p) representative to the unique [0, p) form."""
    return _cond_sub(a, _COMP_P)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, broadcasting cond over the limb axis."""
    return jnp.where(cond[..., None], a, b)


def zero(batch: tuple = ()) -> jnp.ndarray:
    return jnp.zeros(batch + (N_LIMBS,), jnp.int32)


def one_mont(batch: tuple = ()) -> jnp.ndarray:
    return jnp.broadcast_to(_ONE_MONT, batch + (N_LIMBS,))


# Uniform field-module interface (CurveOps is generic over fp/fp2): "one" is
# the multiplicative identity in the working (Montgomery) representation.
one = one_mont


def _exp_bits(e: int) -> np.ndarray:
    """MSB-first bit array of a positive exponent (static)."""
    bits = bin(e)[2:]
    return np.frombuffer(bits.encode(), np.uint8).astype(np.int32) - ord("0")


def pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a static exponent, square-and-multiply over a bit scan."""
    if e == 0:
        return one_mont(a.shape[:-1])
    bits = jnp.asarray(_exp_bits(e))

    def step(acc, bit):
        acc = square(acc)
        acc = jnp.where(bit != 0, mul(acc, a), acc)
        return acc, None

    # first bit is always 1: start from a
    acc, _ = lax.scan(step, a, bits[1:])
    return acc


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse a^(p-2); a must be nonzero (0 maps to 0)."""
    return pow_const(a, _P_INT - 2)


def sqrt_candidate(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p+1)/4) — a square root iff a is a QR (p ≡ 3 mod 4)."""
    return pow_const(a, (_P_INT + 1) // 4)

