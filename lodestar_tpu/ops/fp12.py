"""Fp12 = Fp6[w]/(w² − v) on int32 limb vectors (device tier).

Element shape: (..., 2, 3, 2, 32) — axis -4 indexes (c0, c1) of c0 + c1·w.
A full multiplication is 3 Fp6 products stacked into ONE fp6.mul call
(= 54 Fp products in a single Montgomery scan). The pairing's line update
uses the sparse `mul_by_line` (15 Fp2 products) instead of a full mul.

Frobenius maps use the flattened Fq2[w]/(w⁶ − ξ) view with γ constants
computed once on the host by the oracle (`bls.fields._FROB_GAMMA`).

Oracle: `lodestar_tpu/bls/fields.Fq12`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..bls import fields as _f
from . import fp, fp2, fp6
from .limbs import N_LIMBS, fp_to_mont_host


def _split(a):
    return a[..., 0, :, :, :], a[..., 1, :, :, :]


def _join(c0, c1):
    return jnp.stack([c0, c1], axis=-4)


def _bcast(a, b):
    batch = jnp.broadcast_shapes(a.shape[:-4], b.shape[:-4])
    return (
        jnp.broadcast_to(a, batch + a.shape[-4:]),
        jnp.broadcast_to(b, batch + b.shape[-4:]),
    )


def add(a, b):
    return fp.add(a, b)


def mul(a, b):
    """Karatsuba over w: c0 = v0 + v·v1, c1 = (a0+a1)(b0+b1) − v0 − v1.

    Both the operand sums and the interpolation run as single
    bounds-tracked combine scans (fp.reduce_stack) — the add-side analog
    of stacking the three Fp6 products into one multiply."""
    a, b = _bcast(a, b)
    a0, a1 = _split(a)
    b0, b1 = _split(b)
    sa, sb = fp.reduce_sums(jnp.stack([a0 + a1, b0 + b1]))
    v = fp6.mul(jnp.stack([a0, a1, sa], axis=0), jnp.stack([b0, b1, sb], axis=0))
    v0, v1, v01 = v[0], v[1], v[2]
    W = fp.wrap
    c0 = W(v0) + fp6.mul_by_v_s(W(v1))
    c1 = W(v01) - W(v0) - W(v1)
    c0, c1 = fp.reduce_stack([c0, c1])
    return _join(c0, c1)


def square(a):
    """Complex squaring: c0 = (a0+a1)(a0+v·a1) − v0 − v·v0, c1 = 2v0."""
    a0, a1 = _split(a)
    W = fp.wrap
    s0, s1 = fp.reduce_stack(
        [W(a0) + W(a1), W(a0) + fp6.mul_by_v_s(W(a1))]
    )
    v = fp6.mul(jnp.stack([a0, s0], axis=0), jnp.stack([a1, s1], axis=0))
    v0, mixed = v[0], v[1]
    c0 = W(mixed) - W(v0) - fp6.mul_by_v_s(W(v0))
    c1 = W(v0).double()
    c0, c1 = fp.reduce_stack([c0, c1])
    return _join(c0, c1)


def conj(a):
    """x^(p⁶): negate the w component."""
    a0, a1 = _split(a)
    return _join(a0, fp6.neg(a1))


def cyclotomic_square(g):
    """Granger–Scott squaring — valid ONLY for elements of the cyclotomic
    subgroup G_{Φ6}(Fp2) (anything after the final exponentiation's easy
    part). 9 Fp2 squarings in one stacked call vs the generic square's 12
    Fp2 products, and a flatter add tree.

    With c0 = (a, b, c), c1 = (d, e, f) over Fp2 (the three Fp4
    subalgebras (a,e), (c,d), (b,f) with y² = ξ):
        t0 = a² + ξe²   t6 = 2ae
        t2 = d² + ξc²   t7 = 2cd
        t4 = b² + ξf²   t8 = 2bf·ξ
        c0' = (3t0−2a, 3t2−2b, 3t4−2c)
        c1' = (3t8+2d, 3t6+2e, 3t7+2f)
    Differentially pinned against the oracle's generic square on
    cyclotomic inputs (tests/test_ops_pairing.py)."""
    g0, g1 = _split(g)
    a, b, c = fp6._split(g0)
    d, e, f = fp6._split(g1)
    W = fp.wrap
    sae, scd, sbf = fp.reduce_sums(jnp.stack([a + e, c + d, b + f]))
    lhs = jnp.stack([a, e, sae, c, d, scd, f, b, sbf], axis=0)
    s = fp2.mul(lhs, lhs)
    a2, e2, ae2, c2, d2, cd2, f2, b2, bf2 = (W(s[i]) for i in range(9))
    t6 = ae2 - a2 - e2  # 2ae
    t7 = cd2 - c2 - d2  # 2cd
    t8 = fp2.xi_s(bf2 - b2 - f2)  # 2bf·ξ
    t0 = fp2.xi_s(e2) + a2
    t2 = fp2.xi_s(c2) + d2
    t4 = fp2.xi_s(f2) + b2

    def three_t_minus_2x(t, x):
        return t.double() + t - W(x).double()

    def three_t_plus_2x(t, x):
        return t.double() + t + W(x).double()

    # the whole output assembly is ONE bounds-tracked combine scan
    c0 = fp6.join_s(
        three_t_minus_2x(t0, a),
        three_t_minus_2x(t2, b),
        three_t_minus_2x(t4, c),
    )
    c1 = fp6.join_s(
        three_t_plus_2x(t8, d),
        three_t_plus_2x(t6, e),
        three_t_plus_2x(t7, f),
    )
    c0, c1 = fp.reduce_stack([c0, c1])
    return _join(c0, c1)


def inv(a):
    """(c0 + c1w)⁻¹ = (c0 − c1w)/(c0² − v·c1²)."""
    a0, a1 = _split(a)
    sq = fp6.mul(jnp.stack([a0, a1], axis=0), jnp.stack([a0, a1], axis=0))
    denom = fp6.sub(sq[0], fp6.mul_by_v(sq[1]))
    dinv = fp6.inv(denom)
    out = fp6.mul(jnp.stack([a0, a1], axis=0), dinv[None])
    return _join(out[0], fp6.neg(out[1]))


def batch_inv(a):
    """Element-wise inverse over axis 0 via Montgomery's product trick:
    ONE tower inversion (a ~570-sequential-multiply Fermat chain) plus
    log-depth prefix/suffix product scans replaces n independent
    inversion chains —

        a_i⁻¹ = (Π_{j<i} a_j) · (Π_{j>i} a_j) · (Π_j a_j)⁻¹.

    The amortized entry behind `pairing.final_exponentiation_batch`
    (bisection probes share the easy part's inversion). All inputs must
    be nonzero — a single zero lane poisons the whole batch (the callers
    feed Miller-loop outputs and identity padding, never zero)."""
    n = a.shape[0]
    if n == 1:
        return inv(a)
    from jax import lax

    inc = lax.associative_scan(mul, a, axis=0)  # inclusive prefix products
    inc_rev = lax.associative_scan(mul, jnp.flip(a, axis=0), axis=0)
    # exclusive prefix (identity-shifted) and exclusive suffix
    ident = one((1,) + a.shape[1:-4])
    pre = jnp.concatenate([ident, inc[:-1]], axis=0)
    suf = jnp.concatenate([jnp.flip(inc_rev, axis=0)[1:], ident], axis=0)
    total_inv = inv(inc[-1:])  # unit batch axis: see pairing's axon note
    return mul(mul(pre, suf), total_inv)


def mul_by_line(f, l0, l1, l2):
    """f · (l0 + l1·w² + l2·w³), l_i ∈ Fp2 — the sparse pairing-line update.

    In tower coordinates the line is (A, B) with A = (l0, l1, 0),
    B = (0, l2, 0); Karatsuba needs f0·A, f1·B, (f0+f1)(A+B) where
    A+B = (l0, l1+l2, 0) — 15 Fp2 products in one stacked call.
    """
    f0, f1 = _split(f)
    f00, f01, f02 = fp6._split(f0)
    f10, f11, f12 = fp6._split(f1)
    W = fp.wrap
    g0, g1, g2, s = fp.reduce_sums(
        jnp.stack([f00 + f10, f01 + f11, f02 + f12, l1 + l2])
    )
    lhs = jnp.stack(
        [f00, f02, f00, f01, f01, f02, f12, f10, f11, g0, g2, g0, g1, g1, g2],
        axis=0,
    )
    rhs = jnp.stack(
        [l0, l1, l1, l0, l1, l0, l2, l2, l2, l0, s, s, l0, s, l0],
        axis=0,
    )
    rhs = jnp.broadcast_to(rhs, lhs.shape)
    p = fp2.mul(lhs, rhs)
    # t0 = f0·A, t1 = f1·B (B = l2·v), t2 = (f0+f1)(A+B) — then the
    # Karatsuba combine c0 = t0 + v·t1, c1 = t2 − t0 − t1, ALL as one
    # bounds-tracked scan (round 4 paid ~12 separate add scans here)
    t0 = fp6.join_s(
        W(p[0]) + fp2.xi_s(W(p[1])),
        W(p[2]) + W(p[3]),
        W(p[4]) + W(p[5]),
    )
    t1 = fp6.join_s(fp2.xi_s(W(p[6])), W(p[7]), W(p[8]))
    t2 = fp6.join_s(
        W(p[9]) + fp2.xi_s(W(p[10])),
        W(p[11]) + W(p[12]),
        W(p[13]) + W(p[14]),
    )
    c0 = t0 + fp6.mul_by_v_s(t1)
    c1 = t2 - t0 - t1
    c0, c1 = fp.reduce_stack([c0, c1])
    return _join(c0, c1)


# --- Frobenius -------------------------------------------------------------

def _gamma_const() -> np.ndarray:
    """(3, 6, 2, 32) Montgomery limbs: γ_i^(k) = ξ^(i(p^k−1)/6), k=1..3."""
    out = np.zeros((3, 6, 2, N_LIMBS), np.int32)
    for k in (1, 2, 3):
        for i, g in enumerate(_f._FROB_GAMMA[k]):
            out[k - 1, i, 0] = fp_to_mont_host(g.c0.n)
            out[k - 1, i, 1] = fp_to_mont_host(g.c1.n)
    return out


_GAMMA = _gamma_const()


def _to_w(a):
    """(..., 2, 3, 2, 32) tower layout → (..., 6, 2, 32) w-coefficients."""
    a0, a1 = _split(a)
    d = [
        a0[..., 0, :, :], a1[..., 0, :, :],
        a0[..., 1, :, :], a1[..., 1, :, :],
        a0[..., 2, :, :], a1[..., 2, :, :],
    ]
    return jnp.stack(d, axis=-3)


def _from_w(d):
    c0 = jnp.stack([d[..., 0, :, :], d[..., 2, :, :], d[..., 4, :, :]], axis=-3)
    c1 = jnp.stack([d[..., 1, :, :], d[..., 3, :, :], d[..., 5, :, :]], axis=-3)
    return _join(c0, c1)


def frobenius(a, power: int):
    """x^(p^power), power ∈ {1,2,3}: conj^power per w-coeff, then ·γ_i."""
    if power not in (1, 2, 3):
        raise ValueError("frobenius power must be 1, 2 or 3")
    d = _to_w(a)
    if power % 2 == 1:
        d = jnp.concatenate([d[..., 0:1, :], fp.neg(d[..., 1:2, :])], axis=-2)
    gammas = jnp.asarray(_GAMMA[power - 1])  # (6, 2, 32)
    d = fp2.mul(d, gammas)
    return _from_w(d)


def product_tree(fs):
    """log2-depth product over axis 0 (length static; empty → 1).

    Shared by pairing.pairing_check and the batch verifier — the reduction
    shape matters for device parallelism (sequential fold would serialize
    the whole batch)."""
    n = fs.shape[0]
    if n == 0:
        return one(fs.shape[1:-4])
    while n > 1:
        half = n // 2
        head = mul(fs[:half], fs[half : 2 * half])
        fs = head if n % 2 == 0 else jnp.concatenate([head, fs[2 * half :]], 0)
        n = fs.shape[0]
    return fs[0]


def is_one(a):
    return eq(a, one(a.shape[:-4]))


def eq(a, b):
    return jnp.all(
        fp.canonical(a) == fp.canonical(b), axis=(-1, -2, -3, -4)
    )


def select(cond, a, b):
    return jnp.where(cond[..., None, None, None, None], a, b)


def zero(batch: tuple = ()):
    return jnp.zeros(batch + (2, 3, 2, N_LIMBS), jnp.int32)


def one(batch: tuple = ()):
    return _join(fp6.one(batch), fp6.zero(batch))
