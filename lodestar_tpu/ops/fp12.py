"""Fp12 = Fp6[w]/(w² − v) on int32 limb vectors (device tier).

Element shape: (..., 2, 3, 2, 32) — axis -4 indexes (c0, c1) of c0 + c1·w.
A full multiplication is 3 Fp6 products stacked into ONE fp6.mul call
(= 54 Fp products in a single Montgomery scan). The pairing's line update
uses the sparse `mul_by_line` (15 Fp2 products) instead of a full mul.

Frobenius maps use the flattened Fq2[w]/(w⁶ − ξ) view with γ constants
computed once on the host by the oracle (`bls.fields._FROB_GAMMA`).

Oracle: `lodestar_tpu/bls/fields.Fq12`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..bls import fields as _f
from . import fp, fp2, fp6
from .limbs import N_LIMBS, fp_to_mont_host


def _split(a):
    return a[..., 0, :, :, :], a[..., 1, :, :, :]


def _join(c0, c1):
    return jnp.stack([c0, c1], axis=-4)


def _bcast(a, b):
    batch = jnp.broadcast_shapes(a.shape[:-4], b.shape[:-4])
    return (
        jnp.broadcast_to(a, batch + a.shape[-4:]),
        jnp.broadcast_to(b, batch + b.shape[-4:]),
    )


def add(a, b):
    return fp.add(a, b)


def mul(a, b):
    """Karatsuba over w: c0 = v0 + v·v1, c1 = (a0+a1)(b0+b1) − v0 − v1."""
    a, b = _bcast(a, b)
    a0, a1 = _split(a)
    b0, b1 = _split(b)
    big_a = jnp.stack([a0, a1, fp6.add(a0, a1)], axis=0)
    big_b = jnp.stack([b0, b1, fp6.add(b0, b1)], axis=0)
    v = fp6.mul(big_a, big_b)
    v0, v1, v01 = v[0], v[1], v[2]
    c0 = fp6.add(v0, fp6.mul_by_v(v1))
    c1 = fp6.sub(fp6.sub(v01, v0), v1)
    return _join(c0, c1)


def square(a):
    """Complex squaring: c0 = (a0+a1)(a0+v·a1) − v0 − v·v0, c1 = 2v0."""
    a0, a1 = _split(a)
    big_a = jnp.stack([a0, fp6.add(a0, a1)], axis=0)
    big_b = jnp.stack([a1, fp6.add(a0, fp6.mul_by_v(a1))], axis=0)
    v = fp6.mul(big_a, big_b)
    v0, mixed = v[0], v[1]
    c0 = fp6.sub(fp6.sub(mixed, v0), fp6.mul_by_v(v0))
    c1 = fp6.add(v0, v0)
    return _join(c0, c1)


def conj(a):
    """x^(p⁶): negate the w component."""
    a0, a1 = _split(a)
    return _join(a0, fp6.neg(a1))


def cyclotomic_square(g):
    """Granger–Scott squaring — valid ONLY for elements of the cyclotomic
    subgroup G_{Φ6}(Fp2) (anything after the final exponentiation's easy
    part). 9 Fp2 squarings in one stacked call vs the generic square's 12
    Fp2 products, and a flatter add tree.

    With c0 = (a, b, c), c1 = (d, e, f) over Fp2 (the three Fp4
    subalgebras (a,e), (c,d), (b,f) with y² = ξ):
        t0 = a² + ξe²   t6 = 2ae
        t2 = d² + ξc²   t7 = 2cd
        t4 = b² + ξf²   t8 = 2bf·ξ
        c0' = (3t0−2a, 3t2−2b, 3t4−2c)
        c1' = (3t8+2d, 3t6+2e, 3t7+2f)
    Differentially pinned against the oracle's generic square on
    cyclotomic inputs (tests/test_ops_pairing.py)."""
    g0, g1 = _split(g)
    a, b, c = fp6._split(g0)
    d, e, f = fp6._split(g1)
    lhs = jnp.stack(
        [a, e, fp2.add(a, e), c, d, fp2.add(c, d), f, b, fp2.add(b, f)], axis=0
    )
    s = fp2.mul(lhs, lhs)
    a2, e2, ae2, c2, d2, cd2, f2, b2, bf2 = (s[i] for i in range(9))
    t6 = fp2.sub(fp2.sub(ae2, a2), e2)  # 2ae
    t7 = fp2.sub(fp2.sub(cd2, c2), d2)  # 2cd
    t8 = fp2.mul_by_xi(fp2.sub(fp2.sub(bf2, b2), f2))  # 2bf·ξ
    t0 = fp2.add(fp2.mul_by_xi(e2), a2)
    t2 = fp2.add(fp2.mul_by_xi(c2), d2)
    t4 = fp2.add(fp2.mul_by_xi(f2), b2)

    def three_t_minus_2x(t, x):
        y = fp2.sub(t, x)
        return fp2.add(fp2.add(y, y), t)

    def three_t_plus_2x(t, x):
        y = fp2.add(t, x)
        return fp2.add(fp2.add(y, y), t)

    c0 = fp6._join(
        three_t_minus_2x(t0, a),
        three_t_minus_2x(t2, b),
        three_t_minus_2x(t4, c),
    )
    c1 = fp6._join(
        three_t_plus_2x(t8, d),
        three_t_plus_2x(t6, e),
        three_t_plus_2x(t7, f),
    )
    return _join(c0, c1)


def inv(a):
    """(c0 + c1w)⁻¹ = (c0 − c1w)/(c0² − v·c1²)."""
    a0, a1 = _split(a)
    sq = fp6.mul(jnp.stack([a0, a1], axis=0), jnp.stack([a0, a1], axis=0))
    denom = fp6.sub(sq[0], fp6.mul_by_v(sq[1]))
    dinv = fp6.inv(denom)
    out = fp6.mul(jnp.stack([a0, a1], axis=0), dinv[None])
    return _join(out[0], fp6.neg(out[1]))


def mul_by_line(f, l0, l1, l2):
    """f · (l0 + l1·w² + l2·w³), l_i ∈ Fp2 — the sparse pairing-line update.

    In tower coordinates the line is (A, B) with A = (l0, l1, 0),
    B = (0, l2, 0); Karatsuba needs f0·A, f1·B, (f0+f1)(A+B) where
    A+B = (l0, l1+l2, 0) — 15 Fp2 products in one stacked call.
    """
    f0, f1 = _split(f)
    f00, f01, f02 = fp6._split(f0)
    f10, f11, f12 = fp6._split(f1)
    g = fp6.add(f0, f1)
    g0, g1, g2 = fp6._split(g)
    s = fp2.add(l1, l2)
    lhs = jnp.stack(
        [f00, f02, f00, f01, f01, f02, f12, f10, f11, g0, g2, g0, g1, g1, g2],
        axis=0,
    )
    rhs = jnp.stack(
        [l0, l1, l1, l0, l1, l0, l2, l2, l2, l0, s, s, l0, s, l0],
        axis=0,
    )
    rhs = jnp.broadcast_to(rhs, lhs.shape)
    p = fp2.mul(lhs, rhs)
    # t0 = f0·A over v-coords
    t0 = fp6._join(
        fp2.add(p[0], fp2.mul_by_xi(p[1])),  # f00·l0 + ξ f02·l1
        fp2.add(p[2], p[3]),  # f00·l1 + f01·l0
        fp2.add(p[4], p[5]),  # f01·l1 + f02·l0
    )
    # t1 = f1·B = f1·(l2 v) = ξ f12 l2 + f10 l2 v + f11 l2 v²
    t1 = fp6._join(fp2.mul_by_xi(p[6]), p[7], p[8])
    # t2 = (f0+f1)(A+B), A+B = (l0, s, 0)
    t2 = fp6._join(
        fp2.add(p[9], fp2.mul_by_xi(p[10])),
        fp2.add(p[11], p[12]),
        fp2.add(p[13], p[14]),
    )
    c0 = fp6.add(t0, fp6.mul_by_v(t1))
    c1 = fp6.sub(fp6.sub(t2, t0), t1)
    return _join(c0, c1)


# --- Frobenius -------------------------------------------------------------

def _gamma_const() -> np.ndarray:
    """(3, 6, 2, 32) Montgomery limbs: γ_i^(k) = ξ^(i(p^k−1)/6), k=1..3."""
    out = np.zeros((3, 6, 2, N_LIMBS), np.int32)
    for k in (1, 2, 3):
        for i, g in enumerate(_f._FROB_GAMMA[k]):
            out[k - 1, i, 0] = fp_to_mont_host(g.c0.n)
            out[k - 1, i, 1] = fp_to_mont_host(g.c1.n)
    return out


_GAMMA = _gamma_const()


def _to_w(a):
    """(..., 2, 3, 2, 32) tower layout → (..., 6, 2, 32) w-coefficients."""
    a0, a1 = _split(a)
    d = [
        a0[..., 0, :, :], a1[..., 0, :, :],
        a0[..., 1, :, :], a1[..., 1, :, :],
        a0[..., 2, :, :], a1[..., 2, :, :],
    ]
    return jnp.stack(d, axis=-3)


def _from_w(d):
    c0 = jnp.stack([d[..., 0, :, :], d[..., 2, :, :], d[..., 4, :, :]], axis=-3)
    c1 = jnp.stack([d[..., 1, :, :], d[..., 3, :, :], d[..., 5, :, :]], axis=-3)
    return _join(c0, c1)


def frobenius(a, power: int):
    """x^(p^power), power ∈ {1,2,3}: conj^power per w-coeff, then ·γ_i."""
    if power not in (1, 2, 3):
        raise ValueError("frobenius power must be 1, 2 or 3")
    d = _to_w(a)
    if power % 2 == 1:
        d = jnp.concatenate([d[..., 0:1, :], fp.neg(d[..., 1:2, :])], axis=-2)
    gammas = jnp.asarray(_GAMMA[power - 1])  # (6, 2, 32)
    d = fp2.mul(d, gammas)
    return _from_w(d)


def product_tree(fs):
    """log2-depth product over axis 0 (length static; empty → 1).

    Shared by pairing.pairing_check and the batch verifier — the reduction
    shape matters for device parallelism (sequential fold would serialize
    the whole batch)."""
    n = fs.shape[0]
    if n == 0:
        return one(fs.shape[1:-4])
    while n > 1:
        half = n // 2
        head = mul(fs[:half], fs[half : 2 * half])
        fs = head if n % 2 == 0 else jnp.concatenate([head, fs[2 * half :]], 0)
        n = fs.shape[0]
    return fs[0]


def is_one(a):
    return eq(a, one(a.shape[:-4]))


def eq(a, b):
    return jnp.all(
        fp.canonical(a) == fp.canonical(b), axis=(-1, -2, -3, -4)
    )


def select(cond, a, b):
    return jnp.where(cond[..., None, None, None, None], a, b)


def zero(batch: tuple = ()):
    return jnp.zeros(batch + (2, 3, 2, N_LIMBS), jnp.int32)


def one(batch: tuple = ()):
    return _join(fp6.one(batch), fp6.zero(batch))
