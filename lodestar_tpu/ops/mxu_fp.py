"""Experimental MXU-mapped field multiply (the BASELINE.md plan).

Two structural changes vs `fp.mul`:

1. **Convolutions as fixed matmuls.** The 32-limb schoolbook product is
   `t[k] = Σ_{i+j=k} a_i·b_j` — an outer product (VPU) followed by a
   contraction with a FIXED 0/1 tensor, i.e. one `(B,1024) @ (1024,64)`
   matmul with a constant matrix — MXU work. Products are ≤ 2^24, so
   each is split into three 8-bit parts (see `_conv`): bf16 holds ≤255
   exactly and the MXU accumulates in f32, so single-pass
   DEFAULT-precision matmuls produce bit-exact integer results.

2. **Full-width Montgomery reduction.** Instead of the word-serial
   32-step REDC scan, the textbook full-radix form:
       m = (t mod R)·N' mod R,   result = (t + m·p) / R
   with N' = -p^{-1} mod R precomputed at full width. Both extra
   products are the same fixed-matmul convolution — the only sequential
   work left is carry propagation (three `lax.scan` passes of cheap
   add/shift steps).

Contract matches `fp.mul`: inputs < 2p (lazy domain), output < 2p.
Proof of the output bound: t < (2p)² so t/R < 4p²/R < p (R = 2^384 >
4p); m·p/R < p; result < 2p. ✓

Measured (v5e, 100 chained muls @4096 lanes): the first cut used
two six-pass HIGHEST-precision matmuls and lost (119 ms vs 112 ms);
splitting products into three 8-bit parts makes single-pass
DEFAULT-precision (bf16-input, f32-accumulate) matmuls bit-exact and
WINS: 95 ms vs 104 ms (~9% faster than the VPU scan path). Replacing
the three sequential carry scans with shift-folds + a Kogge-Stone
prefix (log-depth, ~9 parallel steps) measured perf-neutral at this
shape (96.6 vs 95.1 ms) but removes the 160-step sequential chain —
kept for its asymptotics. Opt-in via LODESTAR_TPU_MXU_MUL=1; the
differential suite pins every piece (lookahead vs scan, mul vs the
big-int oracle) either way.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..bls.fields import P as _P_INT
from .limbs import LIMB_BITS, LIMB_MASK, N_LIMBS, P_LIMBS, R_MONT, int_to_limbs

# full-width -p^-1 mod R as 32 12-bit limbs
_NPRIME_INT = (-pow(_P_INT, -1, R_MONT)) % R_MONT
_NPRIME = jnp.asarray(int_to_limbs(_NPRIME_INT))
_P = jnp.asarray(P_LIMBS)


def _conv_matrix() -> np.ndarray:
    """(N²,2N) 0/1 f32: flattened outer-product index (i,j) → column i+j."""
    s = np.zeros((N_LIMBS * N_LIMBS, 2 * N_LIMBS), np.float32)
    for i in range(N_LIMBS):
        for j in range(N_LIMBS):
            s[i * N_LIMBS + j, i + j] = 1.0
    return s


_S = jnp.asarray(_conv_matrix())


def _conv(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Column convolution of 12-bit limb vectors via the fixed matmul.

    a, b: (..., N) canonical 12-bit limbs → (..., 2N) int32 columns
    (≤ 32·2^24 — the caller's bound analysis keeps totals in int32)."""
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, batch + (N_LIMBS,))
    b = jnp.broadcast_to(b, batch + (N_LIMBS,))
    outer = (a[..., :, None] * b[..., None, :]).reshape(batch + (N_LIMBS * N_LIMBS,))
    # Split the ≤2^24 products into three 8-bit parts: each part is ≤ 255,
    # EXACT in bf16 (8-bit mantissa), so the TPU's DEFAULT-precision
    # (single-pass bf16) matmul is bit-exact — parts × 0/1 entries
    # accumulate in f32 with sums ≤ 32·2^8 ≪ 2^24. Three one-pass matmuls
    # beat two six-pass HIGHEST ones.
    p0 = (outer & 0xFF).astype(jnp.float32)
    p1 = ((outer >> 8) & 0xFF).astype(jnp.float32)
    p2 = (outer >> 16).astype(jnp.float32)
    c0 = jnp.matmul(p0, _S, preferred_element_type=jnp.float32)
    c1 = jnp.matmul(p1, _S, preferred_element_type=jnp.float32)
    c2 = jnp.matmul(p2, _S, preferred_element_type=jnp.float32)
    return (
        c0.astype(jnp.int32)
        + (c1.astype(jnp.int32) << 8)
        + (c2.astype(jnp.int32) << 16)
    )


def _carry(t: jnp.ndarray) -> jnp.ndarray:
    """Log-depth carry propagation (carry-lookahead), dropping the final
    out-carry (callers' bound analysis guarantees it is irrelevant).

    Columns are < 2^30. Three shift-folds bring every limb into
    [0, 2^12]: the first fold's carries are ≤ 2^18, the second's ≤ 2^7,
    the third's ≤ 1. What remains is a bit-carry adder solved by a
    Kogge-Stone generate/propagate prefix in ⌈log2(n)⌉ steps — ~9
    parallel steps total instead of an n-step sequential scan."""
    mask = LIMB_MASK

    def fold(x):
        carries = x >> LIMB_BITS
        shifted = jnp.concatenate(
            [jnp.zeros_like(carries[..., :1]), carries[..., :-1]], axis=-1
        )
        return (x & mask) + shifted

    v = fold(fold(fold(t)))  # limbs ∈ [0, 2^12]
    # generate: limb overflows on its own; propagate: a carry-in ripples
    g = v > mask
    p = v == mask
    # Kogge-Stone prefix over c_{i+1} = g_i | (p_i & c_i)
    n = t.shape[-1]
    shift = 1
    while shift < n:
        g_prev = jnp.concatenate(
            [jnp.zeros_like(g[..., :shift]), g[..., :-shift]], axis=-1
        )
        p_prev = jnp.concatenate(
            [jnp.zeros_like(p[..., :shift]), p[..., :-shift]], axis=-1
        )
        g = g | (p & g_prev)
        p = p & p_prev
        shift *= 2
    carry_in = jnp.concatenate(
        [jnp.zeros_like(g[..., :1]), g[..., :-1]], axis=-1
    ).astype(jnp.int32)
    out = (v + carry_in) & mask
    return out, None


def _carry_scan(t: jnp.ndarray):
    """Reference sequential carry (kept for differential testing)."""
    tt = jnp.moveaxis(t, -1, 0)

    def step(carry, col):
        v = col + carry
        return v >> LIMB_BITS, v & LIMB_MASK

    final_carry, out = lax.scan(step, jnp.zeros(tt.shape[1:], jnp.int32), tt)
    return jnp.moveaxis(out, 0, -1), final_carry


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product REDC(a·b) via MXU convolutions; contract as
    fp.mul (inputs < 2p, output < 2p)."""
    # t = a·b, fully carried to canonical limbs (values < (2p)² < R²)
    t_cols = _conv(a, b)
    t, t_carry = _carry(t_cols)  # t_carry == 0: (2p)² < 2^768 exactly fits 64 limbs

    # m = (t mod R)·N' mod R — low half convolution, carried, truncated
    m_cols = _conv(t[..., :N_LIMBS], _NPRIME)[..., :N_LIMBS]
    m, _ = _carry(m_cols)  # mod R = drop the out-carry

    # u = m·p; t + u ≡ 0 mod R ⇒ (t + u)/R is exact after carrying
    u_cols = _conv(m, _P)
    total = t_cols + u_cols  # columns ≤ 2·32·2^24 < 2^30: still int32-safe
    summed, _out = _carry(total)  # t+u < 2^766 fits 64 limbs: no out-carry
    # low 32 limbs are ≡ 0 by construction of m; result = (t+u) >> 384
    return summed[..., N_LIMBS:]
