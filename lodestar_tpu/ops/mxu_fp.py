"""MXU-mapped field multiply — the experiment that became `fp.mul`.

Round 1 developed this module as the opt-in experiment mapping the limb
convolution onto the MXU (see BASELINE.md for measured results); round 2
promoted the design into the default `fp.mul` path. The convolution and
the full-width REDC pipeline now live in `fp` (`fp.conv`,
`fp._mul_fused`) so the consensus-critical reduction exists exactly once;
this module keeps:

- `_carry`: the original generate/propagate Kogge–Stone carry (unsigned,
  bit-carry adder form) — a differential counterpart to `fp.ks_carry`'s
  signed carry-map form;
- `_carry_scan`: the sequential reference carry;
- `mul`: the fused pipeline instantiated with `_carry`, selectable at
  runtime via LODESTAR_TPU_MXU_MUL=1 (round 1's opt-in flag).

Design notes and measured numbers (v5e, 100 chained muls @4096 lanes):

1. **Convolutions as fixed matmuls.** The 32-limb schoolbook product is
   `t[k] = Σ_{i+j=k} a_i·b_j` — an outer product (VPU) followed by a
   contraction with a FIXED 0/1 tensor, i.e. one `(B,1024) @ (1024,64)`
   matmul with a constant matrix — MXU work. Products are ≤ 2^24, so
   each is split into three 8-bit parts: bf16 holds ≤255 exactly and the
   MXU accumulates in f32, so single-pass DEFAULT-precision matmuls are
   bit-exact. First cut (12-bit splits, HIGHEST precision = 6-pass) lost
   (119 ms vs 112 ms); the 8-bit split WINS: 95 ms vs 104 ms (~9% over
   the VPU scan path).

2. **Full-width Montgomery reduction.** Instead of the word-serial
   32-step REDC scan, the textbook full-radix form:
       m = (t mod R)·N' mod R,   result = (t + m·p) / R
   with N' = -p^{-1} mod R precomputed at full width. The only
   sequential work left is carry propagation, done in log depth.

Contract matches `fp.mul`: inputs < 2p (lazy domain), output < 2p
(bound proof in `fp._mul_fused`).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from . import fp
from .limbs import LIMB_BITS, LIMB_MASK

# re-exported for back-compat: round-1 callers/tests reached these here
_NPRIME = fp._NPRIME
_P = fp._P
_conv = fp.conv


def _carry(t: jnp.ndarray):
    """Log-depth carry propagation (carry-lookahead), dropping the final
    out-carry (callers' bound analysis guarantees it is irrelevant).

    Columns are < 2^30. Three shift-folds bring every limb into
    [0, 2^12]: the first fold's carries are ≤ 2^18, the second's ≤ 2^7,
    the third's ≤ 1. What remains is a bit-carry adder solved by a
    Kogge-Stone generate/propagate prefix in ⌈log2(n)⌉ steps — ~9
    parallel steps total instead of an n-step sequential scan.

    Unsigned-columns-only counterpart to `fp.ks_carry` (which also
    handles borrows); kept as a differential reference for it."""
    mask = LIMB_MASK

    def fold(x):
        carries = x >> LIMB_BITS
        shifted = jnp.concatenate(
            [jnp.zeros_like(carries[..., :1]), carries[..., :-1]], axis=-1
        )
        return (x & mask) + shifted

    v = fold(fold(fold(t)))  # limbs ∈ [0, 2^12]
    # generate: limb overflows on its own; propagate: a carry-in ripples
    g = v > mask
    p = v == mask
    # Kogge-Stone prefix over c_{i+1} = g_i | (p_i & c_i)
    n = t.shape[-1]
    shift = 1
    while shift < n:
        g_prev = jnp.concatenate(
            [jnp.zeros_like(g[..., :shift]), g[..., :-shift]], axis=-1
        )
        p_prev = jnp.concatenate(
            [jnp.zeros_like(p[..., :shift]), p[..., :-shift]], axis=-1
        )
        g = g | (p & g_prev)
        p = p & p_prev
        shift *= 2
    carry_in = jnp.concatenate(
        [jnp.zeros_like(g[..., :1]), g[..., :-1]], axis=-1
    ).astype(jnp.int32)
    out = (v + carry_in) & mask
    return out, None


def _carry_scan(t: jnp.ndarray):
    """Reference sequential carry (kept for differential testing)."""
    tt = jnp.moveaxis(t, -1, 0)

    def step(carry, col):
        v = col + carry
        return v >> LIMB_BITS, v & LIMB_MASK

    final_carry, out = lax.scan(step, jnp.zeros(tt.shape[1:], jnp.int32), tt)
    return jnp.moveaxis(out, 0, -1), final_carry


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product REDC(a·b) — the shared fused pipeline with this
    module's generate/propagate carry; contract as fp.mul."""
    return fp._mul_fused(a, b, carry=lambda t: _carry(t)[0])
