"""On-disk store of serialized AOT-compiled XLA executables.

PR 11 measured the restart problem this module exists to fix: 168.1 s
cold to serving-ready (163.4 s of it XLA compile) vs 33.7 s even with a
warm `.jax_cache` — the trace cache removes the *compile* but a restart
still pays tracing + lowering for every kernel, and a post-eviction mesh
shrink recompiles ON the serving path. This store removes XLA from the
restart loop entirely: each kernel's `Lowered.compile()` product is
serialized with `jax.experimental.serialize_executable` and persisted,
keyed by the compile ledger's existing (kernel, shape-or-static key)
signature plus a build fingerprint (jax/jaxlib/backend/device kind and
count — the PR 11 `build_info` labels), so a restarted node
`deserialize_and_load`s machine code instead of tracing anything.

Robustness is the point, not just speed:

- artifact writes are write-to-tmp + `os.replace` (atomic on POSIX), so
  a crash mid-export can never leave a half-written file under the
  final name;
- every artifact carries a JSON header with a SHA-256 of the payload;
  truncated, bit-flipped or version-mismatched artifacts raise a typed
  error (`AotMiss` / `AotCorrupt` / `AotVersionMismatch`) that the
  compile ledger turns into a counted, flight-recorded fallback to a
  normal JIT compile — never a crash, never a silently wrong
  executable;
- the payload pickle is only opened AFTER the checksum verifies: the
  checksum is an integrity (not authenticity) check — the store
  directory has the same trust level as `.jax_cache` and the code
  itself.

File layout (one file per (kernel, key, fingerprint)):

    8 bytes   magic  b"LTPUAOT1" (the trailing digit is the format
              version; a future format bump reads as version_mismatch,
              not corruption)
    4 bytes   big-endian header length
    N bytes   JSON header {kernel, key, fingerprint{...}, payload_sha256,
              payload_len, created_unix}
    M bytes   pickle of (serialized_executable_bytes, in_tree, out_tree)
              — the `serialize_executable.serialize` triple

The store location honors LODESTAR_TPU_AOT_STORE (0/off/none disables;
unset = the repo-local `.aot_store` next to `.jax_cache`); load and
export are independently gated by LODESTAR_TPU_AOT_LOAD (default on)
and LODESTAR_TPU_AOT_EXPORT (the producer mode `tools/warmup.py
--aot-export` sets). The compile ledger (observability/compile_ledger)
owns ALL accounting — this module never touches metrics or the flight
recorder, so it stays importable from tools without a registry.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import tempfile
import time

__all__ = [
    "AotError",
    "AotMiss",
    "AotCorrupt",
    "AotVersionMismatch",
    "AotStore",
    "fingerprint",
    "store",
    "store_dir",
    "load_enabled",
    "export_enabled",
    "reset_for_tests",
]

MAGIC = b"LTPUAOT1"
_MAGIC_STEM = MAGIC[:-1]  # any version of the format
_HEADER_LEN_MAX = 1 << 20  # a header is ~300 bytes; 1 MiB = corrupt
SUFFIX = ".aot"

# the build identity an executable is only valid under: machine code
# compiled by one jaxlib for one backend/device-set must never be loaded
# into another (runtime_info is the PR 11 build_info source)
FINGERPRINT_KEYS = ("jax", "jaxlib", "backend", "device_kind",
                    "device_count")


class AotError(Exception):
    """Base for every store failure mode the ledger degrades on."""


class AotMiss(AotError):
    """No artifact for this (kernel, key) — the normal cold case."""


class AotCorrupt(AotError):
    """Artifact exists but is truncated/bit-flipped/unreadable."""


class AotVersionMismatch(AotError):
    """Artifact is intact but for a different build (jax/jaxlib/backend/
    device set) or an older store format."""


def fingerprint() -> dict:
    """The build identity stamped into (and checked against) every
    artifact. Device enumeration is required — an executable is machine
    code for a specific device set."""
    from ..utils.jax_env import runtime_info

    info = runtime_info(enumerate_devices=True)
    return {k: str(info.get(k, "unknown")) for k in FINGERPRINT_KEYS}


def _digest(kernel: str, key: str, fp: dict) -> str:
    blob = json.dumps([kernel, key, fp], sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


def _safe(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in name)


class AotStore:
    """One directory of `.aot` artifacts, addressed by (kernel, key)
    under the CURRENT build fingerprint. Thread-safe by construction:
    loads are read-only and saves are atomic renames."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._fingerprint: dict | None = None  # resolved lazily: jax init

    def current_fingerprint(self) -> dict:
        if self._fingerprint is None:
            self._fingerprint = fingerprint()
        return self._fingerprint

    def path_for(self, kernel: str, key: str) -> str:
        digest = _digest(kernel, key, self.current_fingerprint())
        return os.path.join(self.root, f"{_safe(kernel)}-{digest}{SUFFIX}")

    # -- producer -----------------------------------------------------------

    def save(self, kernel: str, key: str, compiled) -> dict:
        """Serialize a `jax.stages.Compiled` and atomically persist it.
        Returns the written header. Raises AotError on serialization
        failure (the caller counts it and keeps serving the in-memory
        executable — export failure must never fail a dispatch)."""
        try:
            from jax.experimental import serialize_executable

            payload_bytes, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            payload = pickle.dumps(
                (payload_bytes, in_tree, out_tree),
                protocol=pickle.HIGHEST_PROTOCOL,
            )
        except Exception as e:
            raise AotCorrupt(f"serialize failed: {e!r}") from e
        header = {
            "kernel": kernel,
            "key": key,
            "fingerprint": self.current_fingerprint(),
            "payload_sha256": hashlib.sha256(payload).hexdigest(),
            "payload_len": len(payload),
            "created_unix": round(time.time(), 1),
        }
        header_bytes = json.dumps(header, sort_keys=True).encode()
        path = self.path_for(kernel, key)
        os.makedirs(self.root, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=self.root, prefix=os.path.basename(path), suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(MAGIC)
                f.write(struct.pack(">I", len(header_bytes)))
                f.write(header_bytes)
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: readers see old-or-new, never half
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass  # tmp already renamed or never created
            raise AotCorrupt(f"artifact write failed: {e!r}") from e
        header["path"] = path
        return header

    # -- consumer -----------------------------------------------------------

    def read_header(self, path: str) -> dict:
        """Parse and validate an artifact's header WITHOUT loading the
        payload (directory listings, prune tooling). Raises the same
        typed errors as `load`."""
        try:
            with open(path, "rb") as f:
                return self._read_header_open(f)
        except FileNotFoundError:
            raise AotMiss(path) from None
        except OSError as e:
            raise AotCorrupt(f"unreadable artifact: {e!r}") from e

    def _read_header_open(self, f) -> dict:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            if magic.startswith(_MAGIC_STEM):
                raise AotVersionMismatch(f"store format {magic!r}")
            raise AotCorrupt("bad magic")
        raw_len = f.read(4)
        if len(raw_len) != 4:
            raise AotCorrupt("truncated header length")
        (header_len,) = struct.unpack(">I", raw_len)
        if not 0 < header_len <= _HEADER_LEN_MAX:
            raise AotCorrupt(f"implausible header length {header_len}")
        header_bytes = f.read(header_len)
        if len(header_bytes) != header_len:
            raise AotCorrupt("truncated header")
        try:
            header = json.loads(header_bytes)
        except ValueError as e:
            raise AotCorrupt(f"header not JSON: {e!r}") from e
        if not isinstance(header, dict):
            raise AotCorrupt("header not an object")
        return header

    def load(self, kernel: str, key: str):
        """Load the executable for (kernel, key) under the current
        fingerprint. Returns a callable `jax.stages.Compiled`. Raises
        AotMiss / AotCorrupt / AotVersionMismatch — the ledger maps each
        to its outcome counter and falls back to JIT."""
        path = self.path_for(kernel, key)
        try:
            with open(path, "rb") as f:
                header = self._read_header_open(f)
                if header.get("fingerprint") != self.current_fingerprint():
                    raise AotVersionMismatch(
                        f"built for {header.get('fingerprint')}"
                    )
                if header.get("kernel") != kernel or header.get("key") != key:
                    # digest collision or a hand-renamed file: the header
                    # is the authority, the filename just an index
                    raise AotCorrupt("header kernel/key mismatch")
                payload = f.read()
        except FileNotFoundError:
            raise AotMiss(f"{kernel}:{key}") from None
        except OSError as e:
            raise AotCorrupt(f"unreadable artifact: {e!r}") from e
        expected_len = header.get("payload_len")
        if expected_len != len(payload):
            raise AotCorrupt(
                f"payload {len(payload)}B, header says {expected_len}B"
            )
        sha = hashlib.sha256(payload).hexdigest()
        if sha != header.get("payload_sha256"):
            raise AotCorrupt("payload checksum mismatch")
        # checksum verified: the pickle below is the bytes the exporter
        # wrote, bit-for-bit
        try:
            payload_bytes, in_tree, out_tree = pickle.loads(payload)
            from jax.experimental import serialize_executable

            loaded = serialize_executable.deserialize_and_load(
                payload_bytes, in_tree, out_tree
            )
        except Exception as e:
            raise AotCorrupt(f"deserialize failed: {e!r}") from e
        try:
            os.utime(path)  # recency for the shared LRU prune budget
        except OSError:
            pass  # read-only store: LRU falls back to mtime
        return loaded

    # -- introspection ------------------------------------------------------

    def entries(self) -> list[dict]:
        """Header (+ path/bytes) of every parseable artifact; unreadable
        files are listed with an `error` field instead of raising —
        `/debug/compiles` and the pruner must see a corrupt store, not
        fail on it."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if not name.endswith(SUFFIX):
                continue
            path = os.path.join(self.root, name)
            try:
                header = self.read_header(path)
                header = {
                    k: header.get(k)
                    for k in ("kernel", "key", "fingerprint", "payload_len",
                              "created_unix")
                }
            except AotError as e:
                header = {"error": f"{type(e).__name__}: {e}"}
            try:
                header["bytes"] = os.path.getsize(path)
            except OSError:
                header["bytes"] = 0
            header["path"] = path
            out.append(header)
        return out

    def total_bytes(self) -> int:
        total = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        for name in names:
            try:
                total += os.path.getsize(os.path.join(self.root, name))
            except OSError:
                continue
        return total


def default_store_dir() -> str:
    """The repo-local `.aot_store`, sibling of `.jax_cache`."""
    return os.path.abspath(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "..", "..", ".aot_store")
    )


def store_dir() -> str | None:
    """The configured store directory, or None when disabled
    (LODESTAR_TPU_AOT_STORE=0/off/none)."""
    from ..utils.env import raw

    env = raw("LODESTAR_TPU_AOT_STORE")
    if env is not None and env.strip().lower() in ("0", "off", "none", ""):
        return None
    return env or default_store_dir()


def load_enabled() -> bool:
    from ..utils.env import env_bool

    return env_bool("LODESTAR_TPU_AOT_LOAD")


def export_enabled() -> bool:
    from ..utils.env import env_bool

    return env_bool("LODESTAR_TPU_AOT_EXPORT")


_store: AotStore | None = None
_store_root: str | None = None


def store() -> AotStore | None:
    """The process-wide store for the configured directory, or None when
    disabled. Re-resolved when the env-configured root changes (tests
    point LODESTAR_TPU_AOT_STORE at tmp dirs)."""
    global _store, _store_root
    root = store_dir()
    if root is None:
        _store, _store_root = None, None
        return None
    if _store is None or _store_root != root:
        _store = AotStore(root)
        _store_root = root
    return _store


def reset_for_tests() -> None:
    """Drop the cached store instance (and its memoized fingerprint)."""
    global _store, _store_root
    _store, _store_root = None, None
