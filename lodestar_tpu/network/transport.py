"""Secure, multiplexed TCP transport — the libp2p-bundle equivalent.

Reference: `beacon-node/src/network/nodejs/bundle.ts` composes libp2p from
TCP transport + noise channel encryption + mplex stream muxing + an
ed25519 peer-id. This module provides the same three layers natively on
asyncio:

- **Identity**: ed25519 keypair; peer id = hex of SHA-256(pubkey)[:20]
  (the role of libp2p's multihash PeerId).
- **Encryption**: a Noise-XX-shaped handshake (X25519 ephemerals, HKDF-
  SHA256, ChaCha20Poly1305) in which each side authenticates by signing
  the handshake transcript with its ed25519 identity key — the same
  authentication structure as libp2p-noise, where the static key is
  bound to the PeerId by signature.
- **Muxing**: mplex-style frames (varint<<3|flag header) carrying
  independent bidirectional streams; NewStream data carries the
  protocol id (collapsing multistream-select's negotiation round-trip
  into stream open, which Req/Resp can do because every protocol is
  known up front).

All wire I/O is on the host (TPU plays no role here); frames are
length-prefixed ciphertexts so the reader never blocks mid-record.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Awaitable, Callable

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF
from cryptography.hazmat.primitives import hashes

from ..ssz.hashing import sha256
from ..utils.logger import get_logger

MAX_FRAME = 1 << 20  # 1 MiB plaintext per mux frame
NOISE_PROLOGUE = b"lodestar-tpu-noise-xx"
SIG_CONTEXT = b"lodestar-tpu-transport-identity:"

log = get_logger("transport")


class TransportError(Exception):
    pass


class HandshakeError(TransportError):
    pass


class StreamReset(TransportError):
    pass


# ---------------------------------------------------------------------------
# identity


class NodeIdentity:
    """ed25519 identity; signs handshake transcripts (libp2p PeerId role)."""

    def __init__(self, private_key: Ed25519PrivateKey | None = None):
        self.private_key = private_key or Ed25519PrivateKey.generate()
        self.public_bytes = self.private_key.public_key().public_bytes_raw()
        self.peer_id = peer_id_from_pubkey(self.public_bytes)

    @classmethod
    def from_seed(cls, seed: bytes) -> "NodeIdentity":
        return cls(Ed25519PrivateKey.from_private_bytes(sha256(seed)))

    def sign(self, data: bytes) -> bytes:
        return self.private_key.sign(SIG_CONTEXT + data)


def peer_id_from_pubkey(pubkey: bytes) -> str:
    return sha256(pubkey)[:20].hex()


def verify_identity(pubkey: bytes, sig: bytes, data: bytes) -> bool:
    try:
        Ed25519PublicKey.from_public_bytes(pubkey).verify(sig, SIG_CONTEXT + data)
        return True
    except (InvalidSignature, ValueError):
        return False


# ---------------------------------------------------------------------------
# noise-style secure channel


def _hkdf(secret: bytes, salt: bytes, info: bytes, n: int = 32) -> bytes:
    return HKDF(algorithm=hashes.SHA256(), length=n, salt=salt, info=info).derive(secret)


class _SecureChannel:
    """Per-direction ChaCha20Poly1305 with 64-bit counter nonces."""

    def __init__(self, send_key: bytes, recv_key: bytes):
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_n = 0
        self._recv_n = 0

    @staticmethod
    def _nonce(counter: int) -> bytes:
        return b"\x00\x00\x00\x00" + struct.pack("<Q", counter)

    def encrypt(self, plaintext: bytes) -> bytes:
        ct = self._send.encrypt(self._nonce(self._send_n), plaintext, b"")
        self._send_n += 1
        return ct

    def decrypt(self, ciphertext: bytes) -> bytes:
        pt = self._recv.decrypt(self._nonce(self._recv_n), ciphertext, b"")
        self._recv_n += 1
        return pt


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME + 16:
        raise TransportError(f"oversized frame: {length}")
    return await reader.readexactly(length)


def _write_frame(writer: asyncio.StreamWriter, data: bytes) -> None:
    writer.write(struct.pack(">I", len(data)) + data)


async def perform_handshake(
    identity: NodeIdentity,
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    initiator: bool,
) -> tuple[_SecureChannel, str, bytes]:
    """XX-pattern handshake; returns (channel, remote peer id, remote pubkey).

    msg1  i→r : e_i
    msg2  r→i : e_r || Enc(k_hs, n=0, s_pub_r || Sig_r(transcript || "resp"))
    msg3  i→r : Enc(k_hs, n=1, s_pub_i || Sig_i(transcript || "init"))
    keys: HKDF(dh(e_i, e_r)) — handshake key then directional transport keys
    salted by the transcript hash, so the channel is bound to both
    authenticated identities.
    """
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes_raw()

    if initiator:
        _write_frame(writer, eph_pub)
        await writer.drain()
        msg2 = await _read_frame(reader)
        if len(msg2) < 32:
            raise HandshakeError("short handshake msg2")
        remote_eph, enc = msg2[:32], msg2[32:]
    else:
        remote_eph = await _read_frame(reader)
        if len(remote_eph) != 32:
            raise HandshakeError("bad ephemeral size")

    shared = eph.exchange(X25519PublicKey.from_public_bytes(remote_eph))
    transcript = NOISE_PROLOGUE + (
        eph_pub + remote_eph if initiator else remote_eph + eph_pub
    )
    hs_key = _hkdf(shared, salt=b"", info=b"handshake")
    hs = ChaCha20Poly1305(hs_key)

    def _auth_payload(role: bytes) -> bytes:
        return identity.public_bytes + identity.sign(transcript + role)

    def _verify_auth(plain: bytes, role: bytes) -> bytes:
        pub, sig = plain[:32], plain[32:]
        if not verify_identity(pub, sig, transcript + role):
            raise HandshakeError("identity signature invalid")
        return pub

    try:
        if initiator:
            remote_pub = _verify_auth(
                hs.decrypt(_SecureChannel._nonce(0), enc, b""), b"resp"
            )
            _write_frame(
                writer,
                hs.encrypt(_SecureChannel._nonce(1), _auth_payload(b"init"), b""),
            )
            await writer.drain()
        else:
            _write_frame(
                writer,
                eph_pub
                + hs.encrypt(_SecureChannel._nonce(0), _auth_payload(b"resp"), b""),
            )
            await writer.drain()
            msg3 = await _read_frame(reader)
            remote_pub = _verify_auth(
                hs.decrypt(_SecureChannel._nonce(1), msg3, b""), b"init"
            )
    except HandshakeError:
        raise
    except Exception as e:  # AEAD failures, truncation
        raise HandshakeError(f"handshake failed: {e}") from e

    salt = sha256(transcript)
    k_i2r = _hkdf(shared, salt=salt, info=b"i2r")
    k_r2i = _hkdf(shared, salt=salt, info=b"r2i")
    channel = (
        _SecureChannel(k_i2r, k_r2i) if initiator else _SecureChannel(k_r2i, k_i2r)
    )
    return channel, peer_id_from_pubkey(remote_pub), remote_pub


# ---------------------------------------------------------------------------
# mplex-style muxer

_NEW_STREAM = 0
_MSG_RECEIVER = 1
_MSG_INITIATOR = 2
_CLOSE_RECEIVER = 3
_CLOSE_INITIATOR = 4
_RESET_RECEIVER = 5
_RESET_INITIATOR = 6


def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _decode_varint(data: bytes, i: int) -> tuple[int, int]:
    value = 0
    shift = 0
    while i < len(data):
        b = data[i]
        i += 1
        value |= (b & 0x7F) << shift
        if not (b & 0x80):
            return value, i
        shift += 7
        if shift > 63:
            break
    raise TransportError("bad varint in mux frame")


class Stream:
    """One bidirectional substream over a Connection."""

    def __init__(self, conn: "Connection", stream_id: int, initiator: bool, protocol: str):
        self.conn = conn
        self.stream_id = stream_id
        self.initiator = initiator
        self.protocol = protocol
        self._inbox: asyncio.Queue[bytes | None] = asyncio.Queue()
        self._reset = False
        self._remote_closed = False
        self._local_closed = False

    async def write(self, data: bytes) -> None:
        if self._reset:
            raise StreamReset(f"stream {self.stream_id} reset")
        if self._local_closed:
            raise TransportError("write after close")
        flag = _MSG_INITIATOR if self.initiator else _MSG_RECEIVER
        for off in range(0, len(data), MAX_FRAME - 64):
            await self.conn._send_mux(self.stream_id, flag, data[off : off + MAX_FRAME - 64])
        if not data:
            await self.conn._send_mux(self.stream_id, flag, b"")

    async def read(self, timeout: float | None = None) -> bytes | None:
        """Next data chunk, or None on remote close/EOF."""
        if self._reset:
            raise StreamReset(f"stream {self.stream_id} reset")
        if self._remote_closed and self._inbox.empty():
            return None
        try:
            if timeout is None:
                item = await self._inbox.get()
            else:
                item = await asyncio.wait_for(self._inbox.get(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(f"stream {self.stream_id} read timeout") from None
        if item is None and self._reset:
            raise StreamReset(f"stream {self.stream_id} reset")
        return item

    async def read_all(self, timeout: float | None = None) -> bytes:
        """Drain until remote close; returns concatenated bytes."""
        chunks = []
        while True:
            chunk = await self.read(timeout)
            if chunk is None:
                return b"".join(chunks)
            chunks.append(chunk)

    async def close(self) -> None:
        """Half-close our write side."""
        if self._local_closed or self._reset:
            return
        self._local_closed = True
        flag = _CLOSE_INITIATOR if self.initiator else _CLOSE_RECEIVER
        try:
            await self.conn._send_mux(self.stream_id, flag, b"")
        except TransportError:
            pass
        if self._remote_closed:
            self._forget()

    async def reset(self) -> None:
        if self._reset:
            return
        self._mark_reset()
        self._forget()
        flag = _RESET_INITIATOR if self.initiator else _RESET_RECEIVER
        try:
            await self.conn._send_mux(self.stream_id, flag, b"")
        except TransportError:
            pass

    def _forget(self) -> None:
        """Drop the connection's registry entry (both fully-closed and
        reset streams) so long-lived connections don't accumulate streams."""
        self.conn.streams.pop((self.stream_id, self.initiator), None)

    def _mark_reset(self) -> None:
        self._reset = True
        self._inbox.put_nowait(None)

    def _on_data(self, data: bytes) -> None:
        self._inbox.put_nowait(data)

    def _on_remote_close(self) -> None:
        self._remote_closed = True
        self._inbox.put_nowait(None)
        if self._local_closed:
            self._forget()


StreamHandler = Callable[[Stream], Awaitable[None]]


class Connection:
    """An authenticated, multiplexed session with one remote peer."""

    def __init__(
        self,
        transport: "Transport",
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        channel: _SecureChannel,
        peer_id: str,
        remote_pubkey: bytes,
        initiator: bool,
    ):
        self.transport = transport
        self._reader = reader
        self._writer = writer
        self._channel = channel
        self.peer_id = peer_id
        self.remote_pubkey = remote_pubkey
        self.initiator = initiator
        self.streams: dict[tuple[int, bool], Stream] = {}
        self._next_stream_id = 0 if initiator else 1  # odd/even split avoids collision
        self._closed = False
        self._write_lock = asyncio.Lock()
        self._reader_task: asyncio.Task | None = None
        self.on_close: list[Callable[[], None]] = []

    # -- outgoing ------------------------------------------------------------

    async def open_stream(self, protocol: str) -> Stream:
        if self._closed:
            raise TransportError("connection closed")
        stream_id = self._next_stream_id
        self._next_stream_id += 2
        stream = Stream(self, stream_id, initiator=True, protocol=protocol)
        self.streams[(stream_id, True)] = stream
        await self._send_mux(stream_id, _NEW_STREAM, protocol.encode())
        return stream

    async def _send_mux(self, stream_id: int, flag: int, data: bytes) -> None:
        if self._closed:
            raise TransportError("connection closed")
        header = _encode_varint((stream_id << 3) | flag) + _encode_varint(len(data))
        async with self._write_lock:
            _write_frame(self._writer, self._channel.encrypt(header + data))
            await self._writer.drain()

    # -- incoming ------------------------------------------------------------

    def _start(self) -> None:
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while not self._closed:
                frame = await _read_frame(self._reader)
                plain = self._channel.decrypt(frame)
                await self._dispatch(plain)
        except (asyncio.IncompleteReadError, ConnectionError, TransportError):
            pass
        except Exception as e:  # AEAD failure = peer misbehaving
            log.debug(f"connection {self.peer_id[:8]} read error: {e}")
        finally:
            await self.close()

    async def _dispatch(self, plain: bytes) -> None:
        header, i = _decode_varint(plain, 0)
        length, i = _decode_varint(plain, i)
        data = plain[i : i + length]
        stream_id, flag = header >> 3, header & 0x7
        # A frame from the remote INITIATOR targets our receiver-side entry.
        if flag == _NEW_STREAM:
            protocol = data.decode(errors="replace")
            stream = Stream(self, stream_id, initiator=False, protocol=protocol)
            self.streams[(stream_id, False)] = stream
            handler = self.transport._resolve_handler(protocol)
            if handler is None:
                await stream.reset()
                return
            asyncio.get_running_loop().create_task(self._run_handler(handler, stream))
            return

        from_initiator = flag in (_MSG_INITIATOR, _CLOSE_INITIATOR, _RESET_INITIATOR)
        key = (stream_id, not from_initiator)
        stream = self.streams.get(key)
        if stream is None:
            return
        if flag in (_MSG_INITIATOR, _MSG_RECEIVER):
            stream._on_data(data)
        elif flag in (_CLOSE_INITIATOR, _CLOSE_RECEIVER):
            stream._on_remote_close()
        elif flag in (_RESET_INITIATOR, _RESET_RECEIVER):
            stream._mark_reset()
            self.streams.pop(key, None)

    async def _run_handler(self, handler: StreamHandler, stream: Stream) -> None:
        try:
            await handler(stream)
        except StreamReset:
            pass
        except Exception as e:  # noqa: BLE001 — a handler bug must not kill the conn
            log.debug(f"stream handler error ({stream.protocol}): {e}")
            await stream.reset()

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for stream in list(self.streams.values()):
            stream._mark_reset()
        self.streams.clear()
        try:
            self._writer.close()
        except Exception as e:
            log.debug("writer close raced the transport teardown: %s", e)
        self.transport._forget(self)
        for cb in self.on_close:
            cb()


class Transport:
    """Listens, dials, and owns live connections (one per peer)."""

    def __init__(self, identity: NodeIdentity | None = None):
        self.identity = identity or NodeIdentity()
        self.peer_id = self.identity.peer_id
        self.connections: dict[str, Connection] = {}
        self._handlers: dict[str, StreamHandler] = {}
        self._prefix_handlers: list[tuple[str, StreamHandler]] = []
        self._server: asyncio.AbstractServer | None = None
        self.listen_addr: tuple[str, int] | None = None
        self.on_connection: list[Callable[[Connection], None]] = []

    # -- protocol registry ---------------------------------------------------

    def set_stream_handler(self, protocol: str, handler: StreamHandler) -> None:
        self._handlers[protocol] = handler

    def set_prefix_handler(self, prefix: str, handler: StreamHandler) -> None:
        """Match any protocol id starting with `prefix` (req/resp family)."""
        self._prefix_handlers.append((prefix, handler))

    def _resolve_handler(self, protocol: str) -> StreamHandler | None:
        handler = self._handlers.get(protocol)
        if handler is not None:
            return handler
        for prefix, h in self._prefix_handlers:
            if protocol.startswith(prefix):
                return h
        return None

    # -- lifecycle -----------------------------------------------------------

    async def listen(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._accept, host, port)
        sock = self._server.sockets[0]
        self.listen_addr = sock.getsockname()[:2]
        return self.listen_addr

    async def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            channel, peer_id, pub = await asyncio.wait_for(
                perform_handshake(self.identity, reader, writer, initiator=False),
                timeout=10.0,
            )
        except (HandshakeError, asyncio.TimeoutError, ConnectionError, asyncio.IncompleteReadError):
            writer.close()
            return
        self._adopt(Connection(self, reader, writer, channel, peer_id, pub, False))

    async def dial(self, host: str, port: int) -> Connection:
        reader, writer = await asyncio.open_connection(host, port)
        try:
            channel, peer_id, pub = await asyncio.wait_for(
                perform_handshake(self.identity, reader, writer, initiator=True),
                timeout=10.0,
            )
        except (
            HandshakeError,
            asyncio.TimeoutError,
            ConnectionError,
            asyncio.IncompleteReadError,
        ) as e:
            writer.close()
            raise HandshakeError(str(e)) from e
        return self._adopt(Connection(self, reader, writer, channel, peer_id, pub, True))

    def _adopt(self, conn: Connection) -> Connection:
        old = self.connections.get(conn.peer_id)
        if old is not None and not old._closed:
            # simultaneous open: both sides dialed each other, and each
            # would otherwise keep the TCP stream the other discarded.
            # Deterministic tiebreak — BOTH ends keep the connection whose
            # initiator has the smaller peer id — picks one shared stream.
            if old.initiator != conn.initiator:
                keep_old = old.initiator == (self.peer_id < conn.peer_id)
                if keep_old:
                    asyncio.get_running_loop().create_task(conn.close())
                    return old
            asyncio.get_running_loop().create_task(old.close())
        self.connections[conn.peer_id] = conn
        conn._start()
        for cb in self.on_connection:
            cb(conn)
        return conn

    def _forget(self, conn: Connection) -> None:
        if self.connections.get(conn.peer_id) is conn:
            self.connections.pop(conn.peer_id, None)

    async def close(self) -> None:
        # stop accepting, THEN close connections, THEN wait: since 3.12
        # Server.wait_closed() also waits for accepted client connections,
        # so any other order can hang on a connection that slips in (or on
        # connections waiting for the server)
        if self._server is not None:
            self._server.close()
        for conn in list(self.connections.values()):
            await conn.close()
        if self._server is not None:
            await self._server.wait_closed()
